"""The consensus core: per-peer Multi-Paxos state machine + K/V FSMs.

Host-runtime re-implementation of ``src/riak_ensemble_peer.erl`` (the
reference's 2242-line gen_fsm).  States: setup, probe, pending,
election, prefollow, prepare, prelead, leading, following, repair,
exchange (peer.erl:34-39).  The batched TPU engine
(:mod:`riak_ensemble_tpu.parallel.engine`) lifts the ballot/commit
bookkeeping of thousands of these FSMs onto ``[E, M]`` device arrays;
this scalar version is the semantics oracle and the host/slow path.

Key mechanics mirrored from the reference:

- Leader election: probe (fact discovery, :360-377) → election
  (randomized timeout, :493-505) → prepare (phase-1 ballot, epoch+1,
  :579-596) → prelead (phase-2 new_epoch, :609-620) → leading.
  Followers: prefollow (:540-568) → following (:794-836).
- Commits replicate the #fact{} to a quorum (try_commit, :776-788;
  local_commit, :891-909 resets the per-epoch obj_seq counter).
- The leader tick chains mod_tick → maybe_ping → maybe_change_views →
  maybe_clear_pending → maybe_update_ensembles → maybe_transition then
  renews the lease (:1074-1096).
- K/V ops run on hash-partitioned workers as blocking FSMs
  (:1267-1297, :1369-1500); per-key sequencing via obj_sequence
  (:1776-1791); reads take the lease fast path or a quorum epoch
  check (:1493-1516); stale reads rewrite the key at the current epoch
  (update_key, :1564-1596); all-notfound reads skip tombstones
  (:1568-1584).
- gen_fsm blocking semantics: the reference FSM blocks in callbacks
  during quorum waits while messages queue in the process mailbox.
  Here those sections run as "FSM tasks" — while one is active,
  incoming events are deferred to a backlog and replayed afterwards,
  giving the same serialization.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from riak_ensemble_tpu import funref
from riak_ensemble_tpu import msg as msglib
from riak_ensemble_tpu.backend import BACKENDS, Backend
from riak_ensemble_tpu.config import Config
from riak_ensemble_tpu.directory import Directory
from riak_ensemble_tpu.lease import Lease
from riak_ensemble_tpu.runtime import Actor, Future, Runtime, Timer
from riak_ensemble_tpu.storage import Storage
from riak_ensemble_tpu.synctree import PeerTree, SyncTree
from riak_ensemble_tpu.synctree import exchange as exchangelib
from riak_ensemble_tpu.synctree.backends import DictBackend
from riak_ensemble_tpu.types import (
    NOTFOUND, Fact, Obj, PeerId, initial_fact, latest_fact, members_of,
    peer_order,
)
from riak_ensemble_tpu.worker import WorkerPool

H_OBJ_NONE = b"\x00"


def peer_name(ensemble: Any, peer_id: PeerId) -> Tuple:
    return ("peer", ensemble, peer_id)


def tree_name(ensemble: Any, peer_id: PeerId) -> Tuple:
    return ("tree", ensemble, peer_id)


def get_obj_hash(obj: Obj) -> bytes:
    """``<<0, Epoch:64, Seq:64>>`` — epoch/seq as the object hash;
    byte-order compare == version compare (peer.erl:1717-1724)."""
    return (H_OBJ_NONE + obj.epoch.to_bytes(8, "big")
            + obj.seq.to_bytes(8, "big"))


def valid_obj_hash(actual: bytes, known: bytes) -> bool:
    """peer.erl:1726-1729."""
    return actual[:1] == H_OBJ_NONE and known[:1] == H_OBJ_NONE and \
        actual >= known


class Peer(Actor):
    # ------------------------------------------------------------------
    # setup (peer.erl init:1810-1860)

    def __init__(self, runtime: Runtime, ensemble: Any, peer_id: PeerId,
                 config: Config, directory: Directory, storage: Storage,
                 backend: str = "basic", backend_args: Tuple = (),
                 tree_backend: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None,
                 initial_views=None) -> None:
        super().__init__(runtime, peer_name(ensemble, peer_id), peer_id.node)
        self.ensemble = ensemble
        self.id = peer_id
        self.config = config
        self.directory = directory
        self.storage = storage
        self.clock = clock if clock is not None else (lambda: runtime.now)

        self.fsm_state = "setup"
        self.ets: Dict[Any, int] = {}
        self.awaiting = msglib.MsgState(id=peer_id)
        self.preliminary: Optional[Tuple[PeerId, int]] = None
        self.abandoned: Optional[Tuple[int, int]] = None
        self.timer: Optional[Timer] = None
        self.ready = False
        self.tree_trust = not config.tree_validation
        self.tree_ready = False
        # NB: named alive_credits, not `alive` — Actor.alive is the
        # liveness flag and shadowing it would make _deliver drop
        # messages once the ping credits hit zero.
        self.alive_credits = config.alive_ticks
        self._backend_monitors: List[Tuple[Any, Callable]] = []
        self.last_views: Optional[Sequence] = None
        self.watchers: List[Any] = []
        self.busy = False
        self._fsm_backlog: List[Any] = []

        self.mod: Backend = BACKENDS[backend](ensemble, peer_id,
                                              backend_args)
        for helper in self.mod.monitored():
            self.monitor_backend(helper)
        # synctree (shared-tree override via synctree_path,
        # peer.erl:2155-2167).
        tree_path = self.mod.synctree_path(ensemble, peer_id)
        factory = tree_backend if tree_backend is not None else DictBackend
        if tree_path is None:
            tid, be = (ensemble, peer_id), factory()
        else:
            tid, p = tree_path
            be = factory(path=p)
        self.tree = tree_name(ensemble, peer_id)
        PeerTree(runtime, self.tree, self.node,
                 SyncTree(tree_id=tid, backend=be))

        self.workers = WorkerPool(runtime, config.peer_workers)
        self.lease_obj = Lease(self.clock)

        saved = self._reload_fact(initial_views)
        self.fact = saved
        self.members = members_of(saved.views)
        self._check_views()
        self._local_commit(self.fact)
        self.runtime.post(self.name, ("init",))

    # ------------------------------------------------------------------
    # fact accessors

    @property
    def epoch(self) -> int:
        return self.fact.epoch

    @property
    def seq(self) -> int:
        return self.fact.seq

    @property
    def leader(self) -> Optional[PeerId]:
        return self.fact.leader

    @property
    def views(self):
        return self.fact.views

    # ------------------------------------------------------------------
    # event plumbing

    def handle(self, msg: Tuple) -> None:
        if self.busy:
            self._fsm_backlog.append(msg)
            return
        kind = msg[0]
        # all-state events (handle_event, peer.erl:1886-1905)
        if kind == "reply":
            _, reqid, peer, value = msg
            self.awaiting = msglib.handle_reply(self, reqid, peer, value,
                                                self.awaiting)
            return
        if kind == "quorum_timeout_tick":
            if self.awaiting.awaiting == msg[1]:
                self.awaiting = msglib.quorum_timeout(self, self.awaiting)
            return
        if kind == "watch_leader_status":
            watcher = msg[1]
            if watcher not in self.watchers:
                self._notify_leader_status([watcher])
                self.watchers.append(watcher)
                # Watcher-death cleanup (erlang:monitor, peer.erl:1874,
                # 1920-1925).
                self.runtime.monitor(
                    watcher,
                    lambda w: self.watchers.remove(w)
                    if w in self.watchers else None)
            return
        if kind == "stop_watching":
            if msg[1] in self.watchers:
                self.watchers.remove(msg[1])
            return
        if kind == "backend_pong":
            self.alive_credits = self.config.alive_ticks
            return
        if kind == "backend_down":
            # DOWN for a backend-monitored process -> the behaviour
            # decides (module_handle_down, peer.erl:1937-1948).
            self._module_handle_down(msg[1])
            return
        if kind == "peer_sync":
            _, fut, inner = msg
            self._handle_sync(inner, fut)
            return
        if kind == "xcall":
            # Wire-safe remote sync call (exchange tree_pid etc.).
            _, from_, inner = msg
            fut = Future()
            msglib.handle_xcall(self, from_, fut)
            self._handle_sync(inner, fut)
            return
        handler = getattr(self, "st_" + self.fsm_state)
        handler(msg)

    def st_setup(self, msg: Tuple) -> None:
        if msg[0] == "init":
            self._probe_init()
        else:
            self._common(msg)

    def _run_fsm_section(self, gen) -> None:
        """Run a blocking FSM section as a task; defer events meanwhile
        (models gen_fsm blocking in a callback)."""
        assert not self.busy
        self.busy = True

        def wrapper():
            try:
                yield from gen
            finally:
                self.busy = False
                backlog, self._fsm_backlog = self._fsm_backlog, []
                for m in backlog:
                    self.runtime.post(self.name, m)

        self.runtime.spawn_task(wrapper(), name=f"fsm:{self.id}")

    # ------------------------------------------------------------------
    # timers (single slot, peer.erl set_timer/cancel_timer:2229-2242)

    def _set_timer(self, delay: float, event: Tuple) -> None:
        self._cancel_timer()
        self.timer = self.send_after(delay, event)

    def _cancel_timer(self) -> None:
        if self.timer is not None:
            self.timer.cancel()
            self.timer = None

    # ------------------------------------------------------------------
    # peer addressing / fan-out helpers

    def peer_addr(self, peer_id: PeerId):
        if peer_id == self.id:
            return self.name
        return self.directory.get_peer_addr(self.ensemble, peer_id)

    def get_peers(self, members) -> List[Tuple[PeerId, Any]]:
        return [(p, self.peer_addr(p)) for p in members]

    def _send_all(self, message: Tuple, required: str = "quorum",
                  members=None) -> None:
        members = members if members is not None else self.members
        self.awaiting = msglib.send_all(self, message, self.id,
                                        self.get_peers(members),
                                        self.views, required)

    def _blocking_send_all(self, message: Tuple, peers=None,
                           required: str = "quorum", extra=None) -> Future:
        peers = peers if peers is not None else self.get_peers(self.members)
        return msglib.blocking_send_all(self, message, self.id, peers,
                                        self.views, required, extra)

    def _cast_all(self, message: Tuple) -> None:
        msglib.cast_all(self, message, self.id,
                        self.get_peers(self.members))

    def _reply(self, from_, value) -> None:
        msglib.reply(self, from_, self.id, value)

    # ==================================================================
    # Core protocol states
    # ==================================================================

    def _probe_init(self) -> None:
        """probe(init), peer.erl:360-369."""
        self.fsm_state = "probe"
        self._set_fact(leader=None)
        if self._is_pending():
            self._pending_init()
        else:
            self._send_all(("probe",))

    def st_probe(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "quorum_met":
            replies = msg[1]
            latest = latest_fact_of(replies, self.fact)
            existing = existing_leader(replies, self.abandoned, latest)
            self.fact = latest
            self.members = members_of(latest.views)
            self._maybe_follow(existing)
        elif kind == "timeout":
            latest = latest_fact_of(msg[1], self.fact)
            self.fact = latest
            self._check_views()
            self._probe_delay()
        elif kind == "probe_continue":
            self._probe_init()
        else:
            self._common(msg)

    def _probe_delay(self) -> None:
        self.fsm_state = "probe"
        self._set_timer(self.config.probe_delay, ("probe_continue",))

    def _maybe_follow(self, leader) -> None:
        """peer.erl:435-444."""
        if not self.tree_trust:
            self._exchange_init()
        elif leader is None or leader == self.id:
            self._set_fact(leader=None)
            self._election_init()
        else:
            self._set_fact(leader=leader)
            self._following_init(ready=False)

    # -- pending (peer.erl:394-432) ------------------------------------

    def _pending_init(self) -> None:
        self.fsm_state = "pending"
        self.tree_trust = False
        self._set_timer(self.config.pending(), ("pending_timeout",))

    def st_pending(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "pending_timeout":
            self.st_probe(("timeout", []))
        elif kind == "prepare":
            _, cand, next_epoch, from_ = msg
            if next_epoch > self.epoch:
                self._reply(from_, self.fact)
                self._cancel_timer()
                self._prefollow_init(cand, next_epoch)
            # else: silently stay pending (reference keeps state)
        elif kind == "commit":
            _, fact, from_ = msg
            if fact.epoch >= self.epoch:
                self._reply(from_, "ok")
                self._local_commit(fact)
                self._cancel_timer()
                self._following_init()
        else:
            self._common(msg)

    def _is_pending(self) -> bool:
        """peer.erl:937-945."""
        pending = self.directory.get_pending(self.ensemble)
        if pending:
            _, pending_views = pending
            pend_members = members_of(pending_views)
            return self.id not in self.members and self.id in pend_members
        return False

    # -- repair / exchange (peer.erl:446-489) ---------------------------

    def _repair_init(self) -> None:
        self.fsm_state = "repair"
        self.tree_trust = False
        self.send_local(self.tree, ("tree_async_repair", self.name))

    def st_repair(self, msg: Tuple) -> None:
        if msg[0] == "repair_complete":
            self._exchange_init()
        else:
            self._common(msg)

    def _exchange_init(self) -> None:
        self.fsm_state = "exchange"
        self._start_exchange()

    def st_exchange(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "exchange_complete":
            self.tree_trust = True
            self._election_init()
        elif kind == "exchange_failed":
            self._probe_delay()
        else:
            self._common(msg)

    def _start_exchange(self) -> None:
        exchangelib.start_exchange(self, self.tree,
                                   self.get_peers(self.members),
                                   self.views, self.tree_trust)

    # -- election (peer.erl:493-538) ------------------------------------

    def _election_init(self) -> None:
        self.fsm_state = "election"
        self._set_timer(self.config.election_timeout(self.runtime.rng),
                        ("election_timeout",))

    def st_election(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "election_timeout":
            if self._mod_ping():
                self.timer = None
                self._prepare_init()
            else:
                self._election_init()
        elif kind == "prepare":
            _, cand, next_epoch, from_ = msg
            if next_epoch > self.epoch:
                self._reply(from_, self.fact)
                self._cancel_timer()
                self._prefollow_init(cand, next_epoch)
        elif kind == "commit":
            _, fact, from_ = msg
            if fact.epoch >= self.epoch:
                self._reply(from_, "ok")
                self._local_commit(fact)
                self._cancel_timer()
                self._following_init()
        else:
            self._common(msg)

    # -- prefollow (peer.erl:540-577) -----------------------------------

    def _prefollow_init(self, cand: PeerId, next_epoch: int) -> None:
        self.fsm_state = "prefollow"
        self.preliminary = (cand, next_epoch)
        self._set_timer(self.config.prefollow(), ("prefollow_timeout",))

    def st_prefollow(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "new_epoch":
            _, cand, next_epoch, from_ = msg
            if (cand, next_epoch) == self.preliminary:
                self._set_fact(leader=cand, epoch=next_epoch)
                self._cancel_timer()
                self._reply(from_, "ok")
                self._following_init(ready=False)
            else:
                self._cancel_timer()
                self._probe_init()
        elif kind == "prefollow_timeout":
            self._probe_init()
        else:
            self._common(msg)

    # -- prepare / prelead (peer.erl:579-626) ---------------------------

    def _prepare_init(self) -> None:
        self.fsm_state = "prepare"
        next_epoch = self.epoch + 1
        self._send_all(("prepare", self.id, next_epoch))

    def st_prepare(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "quorum_met":
            latest = latest_fact_of(msg[1], self.fact)
            next_epoch = self.epoch + 1
            self.fact = latest
            self.preliminary = (self.id, next_epoch)
            self.members = members_of(latest.views)
            self._prelead_init()
        elif kind == "timeout":
            self._probe_init()
        else:
            self._common(msg)

    def _prelead_init(self) -> None:
        self.fsm_state = "prelead"
        cand, next_epoch = self.preliminary
        assert cand == self.id
        self._send_all(("new_epoch", self.id, next_epoch))

    def st_prelead(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "quorum_met":
            _, next_epoch = self.preliminary
            self.fact = _fact_replace(self.fact, leader=self.id,
                                      epoch=next_epoch, seq=0,
                                      view_vsn=(next_epoch, -1))
            self._leading_init()
        elif kind == "timeout":
            self._probe_init()
        else:
            self._common(msg)

    # -- leading (peer.erl:629-721) -------------------------------------

    def _leading_init(self) -> None:
        self.fsm_state = "leading"
        self.alive_credits = self.config.alive_ticks
        self.tree_ready = False
        self._start_exchange()
        self._notify_leader_status(self.watchers)
        self._leader_tick()

    def st_leading(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "tick":
            self._leader_tick()
        elif kind == "exchange_complete":
            self.tree_trust = True
            self.tree_ready = True
        elif kind == "exchange_failed":
            self._step_down("probe")
        elif kind == "forward":
            _, fut, inner = msg
            self._leading_sync(inner, fut)
        else:
            self._common(msg)

    # -- following (peer.erl:791-867) -----------------------------------

    def _following_init(self, ready: Optional[bool] = None) -> None:
        if ready is False:
            self.ready = False
        self.fsm_state = "following"
        self._start_exchange()
        self._reset_follower_timer()

    def _reset_follower_timer(self) -> None:
        self._set_timer(self.config.follower(), ("follower_timeout",))

    def st_following(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "commit":
            _, fact, from_ = msg
            if fact.epoch >= self.epoch:
                self._local_commit(fact)
                self._reply(from_, "ok")
                self._reset_follower_timer()
        elif kind == "exchange_complete":
            self.tree_trust = True
        elif kind == "exchange_failed":
            self._probe_init()
        elif kind == "follower_timeout":
            self.timer = None
            self._abandon()
        elif kind == "check_epoch":
            _, leader, epoch, from_ = msg
            if self._check_epoch(leader, epoch):
                self._reply(from_, "ok")
            else:
                self._reply(from_, "nack")
        elif kind == "get" and len(msg) == 5:
            _, key, peer, epoch, from_ = msg
            if self._valid_request(peer, epoch):
                self._do_local_get(from_, key)
            else:
                self._reply(from_, "nack")
        elif kind == "put" and len(msg) == 6:
            _, key, obj, peer, epoch, from_ = msg
            if self._valid_request(peer, epoch):
                self._do_local_put(from_, key, obj)
            else:
                self._reply(from_, "nack")
        elif kind == "update_hash":
            _, key, objhash, maybe_from = msg
            result = self.tree_insert_sync(key, objhash)
            if result == "corrupted":
                if maybe_from is not None:
                    self._reply(maybe_from, "nack")
                self._repair_init()
            else:
                if maybe_from is not None:
                    self._reply(maybe_from, "ok")
        else:
            self._common(msg)

    def _abandon(self) -> None:
        """peer.erl:932-935."""
        self.abandoned = (self.epoch, self.seq)
        self._set_fact(leader=None)
        self._probe_init()

    def _valid_request(self, peer, req_epoch) -> bool:
        return self.ready and req_epoch == self.epoch and peer == self.leader

    def _check_epoch(self, leader, epoch) -> bool:
        return epoch == self.epoch and leader == self.leader

    # -- common handlers (peer.erl:998-1045) ----------------------------

    def _common(self, msg: Tuple) -> None:
        kind = msg[0]
        if kind == "probe":
            self._reply(msg[1], self.fact)
        elif kind == "exchange":
            self._reply(msg[1], "ok" if self.tree_trust else "nack")
        elif kind == "all_exchange":
            self._reply(msg[1], "ok")
        elif kind == "tick":
            pass  # errant tick
        elif kind == "forward":
            pass  # not leading: drop, client times out
        elif kind == "update_hash":
            maybe_from = msg[3]
            if maybe_from is not None:
                self._reply(maybe_from, "nack")
        elif kind in ("quorum_met", "timeout", "exchange_complete",
                      "exchange_failed", "repair_complete",
                      "probe_continue", "election_timeout",
                      "prefollow_timeout", "follower_timeout",
                      "pending_timeout", "init"):
            if kind == "init" and self.fsm_state == "setup":
                self._probe_init()
            # else: stale event from a previous state; drop.
        else:
            self._nack(msg)

    def _nack(self, msg: Tuple) -> None:
        """peer.erl:1047-1069: nack only known request shapes."""
        kind = msg[0]
        if kind in ("prepare", "new_epoch"):
            self._reply(msg[3], "nack")
        elif kind == "commit":
            self._reply(msg[2], "nack")
        elif kind == "get" and len(msg) == 5:
            self._reply(msg[4], "nack")
        elif kind == "put" and len(msg) == 6:
            self._reply(msg[5], "nack")
        # anything else: silently ignored

    # ------------------------------------------------------------------
    # sync events (gen_fsm sync_send_event surface)

    def _handle_sync(self, inner: Tuple, fut: Future) -> None:
        kind = inner[0]
        # all-state sync events (peer.erl:1907-1933)
        if kind == "get_leader":
            fut.resolve(self.leader)
            return
        if kind == "get_info":
            fut.resolve((self.fsm_state, self.tree_trust, self.epoch))
            return
        if kind == "tree_info":
            top = self.tree_top_hash_sync()
            fut.resolve((self.tree_trust, self.tree_ready, top))
            return
        if kind == "debug_local_get":
            self._mod_get(inner[1], (fut, self.id))
            return
        if kind == "force_state":
            epoch, seq = inner[1]
            self._set_fact(epoch=epoch, seq=seq)
            fut.resolve("ok")
            return
        if kind == "tree_pid":
            fut.resolve(self.tree)
            return
        if kind == "fwd":
            # A request already forwarded once by a follower: handle
            # only if leading, else nack — never re-forward, or two
            # followers with mutually stale fact.leader would bounce
            # one request forever (the reference's forward is likewise
            # a single hop, peer.erl:864-867).
            if self.fsm_state == "leading":
                self._leading_sync(inner[1], fut)
            else:
                fut.resolve("nack")
            return
        if kind == "tree_corrupted":
            # common sync (peer.erl:1036-1040); leading overrides below.
            if self.fsm_state == "leading":
                fut.resolve("ok")
                self.tree_trust = False
                self._step_down("repair")
            else:
                fut.resolve("ok")
                self._repair_init()
            return
        if self.fsm_state == "leading":
            self._leading_sync(inner, fut)
        elif self.fsm_state == "following":
            self._following_sync(inner, fut)
        else:
            fut.resolve("nack")

    def _following_sync(self, inner: Tuple, fut: Future) -> None:
        """following/3: forward client K/V to the leader
        (peer.erl:838-858, 1348-1356)."""
        if inner[0] in ("get", "put", "overwrite", "join", "update_members"):
            leader_addr = self.peer_addr(self.leader) if self.leader else None
            if leader_addr is None:
                return  # drop; client times out
            if self.leader.node == self.node:
                # Same-host: hand over the caller's future directly.
                self.send(leader_addr, ("forward", fut, inner))
            else:
                # Cross-node: a live future can't ride the wire; use
                # the request-id'd xcall proxy (the From-pid analog).
                # "fwd"-wrapped so the remote never forwards again.
                out = msglib.xcall(self, leader_addr, ("fwd", inner),
                                   self.config.local_put_timeout)
                out.add_waiter(fut.resolve)
        else:
            fut.resolve("nack")

    def _leading_sync(self, inner: Tuple, fut: Future) -> None:
        """leading/3 (peer.erl:655-721) + leading_kv (1267-1297)."""
        kind = inner[0]
        if kind == "update_members":
            self._run_fsm_section(self._do_update_members(inner[1], fut))
        elif kind == "check_quorum":
            self._run_fsm_section(self._do_check_quorum(fut))
        elif kind == "ping_quorum":
            self._do_ping_quorum(fut)
        elif kind == "stable_views":
            pending, views = self.fact.pending, self.fact.views
            stable = len(views) == 1 and (pending is None or
                                          not pending[1])
            fut.resolve(("ok", stable))
        elif kind == "get" and len(inner) == 3:
            _, key, opts = inner
            if not self.tree_ready:
                fut.resolve("failed")
            else:
                self.workers.async_(
                    key, lambda: self._do_get_fsm(key, fut, opts))
        elif kind == "put":
            _, key, fun, args = inner
            try:
                # Wire events carry ("fn", name, bound) specs, not
                # closures (the reference's MFA, root.erl:82,104).
                fun = funref.resolve(fun)
            except ValueError:
                fun = None
            if fun is None or not self.tree_ready:
                fut.resolve("failed")
            else:
                self.workers.async_(
                    key, lambda: self._do_put_fsm(key, fun, args, fut))
        elif kind == "overwrite":
            _, key, value = inner
            if not self.tree_ready:
                fut.resolve("failed")
            else:
                self.workers.async_(
                    key, lambda: self._do_overwrite_fsm(key, value, fut))
        elif kind == "local_get":
            self._do_local_get((fut, None), inner[1])
        elif kind == "local_put":
            self._do_local_put((fut, None), inner[1], inner[2])
        elif kind == "request_failed":
            # No reply: the worker blocks; step_down kills it
            # (peer.erl:1274-1275 + reset_workers).
            self._step_down("prepare")
        elif kind == "join":
            self._run_fsm_section(
                self._do_update_members([("add", inner[1])], fut))
        else:
            fut.resolve("nack")

    # ------------------------------------------------------------------
    # leader periodic work

    def _leader_tick(self) -> None:
        self._run_fsm_section(self._leader_tick_gen())

    def _leader_tick_gen(self):
        """peer.erl:1074-1096."""
        self._mod_tick()
        result = "ok" if self._mod_ping() else "failed"
        if result == "ok":
            result = yield from self._maybe_change_views()
        if result == "ok":
            result = yield from self._maybe_clear_pending()
        if result == "ok":
            result = self._maybe_update_ensembles()
        if result == "ok":
            result = yield from self._maybe_transition()
        if result == "failed":
            self._step_down("probe")
        elif result == "shutdown":
            self.directory.stop_peer(self.ensemble, self.id)
            self._step_down("stop")
        else:
            self.lease_obj.lease(self.config.lease())
            self._set_timer(self.config.ensemble_tick, ("tick",))

    def _maybe_change_views(self):
        """peer.erl:1115-1135."""
        pending = self.directory.get_pending(self.ensemble)
        if not pending or not pending[1]:
            return "ok"
        vsn, views = pending
        pend_vsn = self.fact.pend_vsn
        if pend_vsn is None or vsn > pend_vsn:
            view_vsn = (self.epoch, self.seq)
            new_fact = _fact_replace(self.fact, views=tuple(views),
                                     pend_vsn=vsn, view_vsn=view_vsn)
            self.workers.pause()
            ok = yield from self._try_commit(new_fact)
            if ok:
                self.workers.unpause()
                return "changed"
            return "failed"
        return "ok"

    def _maybe_clear_pending(self):
        """peer.erl:1137-1159."""
        fact = self.fact
        if fact.pending is None or not fact.pending[1]:
            return "ok"
        vsn = fact.pending[0]
        if vsn == fact.pend_vsn and vsn == fact.commit_vsn:
            cur = self.directory.get_views(self.ensemble)
            if cur and tuple(cur[1]) == tuple(fact.views):
                new_fact = _fact_replace(
                    fact, pending=((fact.epoch, fact.seq), ()))
                ok = yield from self._try_commit(new_fact)
                return "changed" if ok else "failed"
        return "ok"

    def _maybe_update_ensembles(self) -> str:
        """peer.erl:1161-1178."""
        vsn = self.fact.view_vsn
        views = self.fact.views
        if self.ensemble == "root":
            self.directory.root_gossip(self, vsn, self.id, views)
        else:
            self.directory.update_ensemble(self.ensemble, self.id, views,
                                           vsn)
        if self.fact.pending is not None:
            pvsn, pviews = self.fact.pending
            self.directory.gossip_pending(self.ensemble, pvsn, pviews)
        return "ok"

    def _maybe_transition(self):
        """peer.erl:1199-1214."""
        if self._should_transition():
            return (yield from self._transition())
        ok = yield from self._try_commit(self.fact)
        return "ok" if ok else "failed"

    def _should_transition(self) -> bool:
        """peer.erl:751-755: views stable since last tick AND more than
        one view active."""
        return (self.views == self.last_views) and len(self.views) > 1

    def _transition(self):
        """peer.erl:756-774: collapse joint views to the newest."""
        fact = self.fact
        latest = fact.views[0]
        new_fact = _fact_replace(fact, views=(latest,),
                                 view_vsn=(fact.epoch, fact.seq),
                                 commit_vsn=fact.pend_vsn)
        ok = yield from self._try_commit(new_fact)
        if not ok:
            return "failed"
        if self.id not in latest:
            return "shutdown"
        return "ok"

    def _try_commit(self, new_fact: Fact):
        """peer.erl:776-788; generator returning bool."""
        views = self.views
        new_fact = _fact_replace(new_fact, seq=new_fact.seq + 1)
        self._local_commit(new_fact)
        fut = self._blocking_send_all(("commit", new_fact))
        outcome = yield fut
        if outcome[0] == "quorum_met":
            self.last_views = views
            return True
        self._set_fact(leader=None)
        return False

    def _do_update_members(self, changes, fut: Future):
        """leading({update_members,..}), peer.erl:655-672."""
        cluster = self.directory.cluster()
        view = list(self.views[0])
        members = list(self.members)
        errors = []
        for op, pid in changes:
            if op == "add":
                if pid.node not in cluster:
                    errors.append(("not_in_cluster", pid))
                elif pid in members:
                    errors.append(("already_member", pid))
                else:
                    members.append(pid)
                    view.append(pid)
            elif op == "del":
                if pid not in members:
                    errors.append(("not_member", pid))
                else:
                    members.remove(pid)
                    view.remove(pid)
        if errors:
            fut.resolve(("error", errors))
            return
        new_view = tuple(sorted(set(view), key=peer_order))
        views2 = (new_view,) + tuple(self.views)
        new_fact = _fact_replace(
            self.fact, pending=((self.epoch, self.seq), views2))
        ok = yield from self._try_commit(new_fact)
        if ok:
            fut.resolve("ok")
        else:
            fut.resolve("timeout")
            self._step_down("probe")

    def _do_check_quorum(self, fut: Future):
        """leading(check_quorum,..), peer.erl:673-680."""
        ok = yield from self._try_commit(self.fact)
        if ok:
            fut.resolve("ok")
        else:
            fut.resolve("timeout")
            self._step_down("probe")

    def _do_ping_quorum(self, fut: Future) -> None:
        """leading(ping_quorum,..), peer.erl:681-703: replicate a fact
        bump, let replies accumulate for 1s (lazy collector — everyone
        reachable gets counted, not just the first majority), then
        report who answered."""
        new_fact = _fact_replace(self.fact, seq=self.fact.seq + 1)
        self._local_commit(new_fact)
        qfut, cname = msglib.lazy_send_all(
            self, ("commit", new_fact), self.id,
            self.get_peers(self.members), self.views)
        extra = [(self.id, "ok")] if self.id in self.members else []
        tree_ready = self.tree_ready
        leader_id = self.id

        def waiter():
            yield self.runtime.sleep(1.0)
            if cname is not None:
                self.runtime.post(cname, ("ask",))
            outcome = yield self.runtime.with_timeout(qfut, 0.5,
                                                      ("timeout", []))
            if outcome[0] == "quorum_met":
                fut.resolve((leader_id, tree_ready, extra + outcome[1]))
            else:
                fut.resolve((leader_id, tree_ready, extra))

        self.runtime.spawn_task(waiter(), name="ping_quorum")

    # ------------------------------------------------------------------
    # step down / commit plumbing

    def _step_down(self, next_state: str = "probe") -> None:
        """peer.erl:911-930.  Watchers are told the NEXT state
        (notify_leader_status(Watchers, Next, ..), peer.erl:916)."""
        self._notify_leader_status(self.watchers, leading=False)
        self.lease_obj.unlease()
        self._cancel_timer()
        self.workers.reset()
        self._set_fact(leader=None)
        if next_state == "probe":
            self._probe_init()
        elif next_state == "prepare":
            self._prepare_init()
        elif next_state == "repair":
            self._repair_init()
        elif next_state == "stop":
            self.stop()

    def _local_commit(self, fact: Fact) -> None:
        """peer.erl:891-909: persist fact, reset per-epoch obj_seq."""
        self.fact = fact
        self._maybe_save_fact()
        epoch, seq = fact.epoch, fact.seq
        if ("obj_seq", epoch) in self.ets:
            self.ets["epoch"] = epoch
            self.ets["seq"] = seq
        else:
            self.ets.clear()
            self.ets.update({"epoch": epoch, "seq": seq,
                             ("obj_seq", epoch): 0})
        self.ready = True
        self.members = members_of(fact.views)

    def _set_fact(self, **kw) -> None:
        self.fact = _fact_replace(self.fact, **kw)

    def _check_views(self) -> None:
        """peer.erl:952-964."""
        cur = self.directory.get_views(self.ensemble)
        vsn = (self.fact.epoch, self.fact.seq)
        # Empty views = the reference's `undefined` (a manager-started
        # peer with no saved fact): always adopt the manager's views.
        if cur and (cur[0] > vsn or not self.fact.views):
            self.fact = _fact_replace(self.fact, views=tuple(cur[1]))
            self.members = members_of(self.fact.views)
        else:
            self.members = members_of(self.fact.views)

    # -- fact persistence (peer.erl:2185-2228) --------------------------

    def _fact_key(self):
        return (repr(self.ensemble), self.id)

    def _reload_fact(self, initial_views=None) -> Fact:
        saved = self.storage.get(self._fact_key())
        if saved is not None:
            return saved
        return initial_fact(initial_views if initial_views else ())

    def _maybe_save_fact(self) -> None:
        old = self.storage.get(self._fact_key())
        if old is None or _fact_replace(old, seq=0) != \
                _fact_replace(self.fact, seq=0):
            self.storage.put(self._fact_key(), self.fact)
            self.storage.sync()  # async flush; see storage.py coalescing

    # ------------------------------------------------------------------
    # backend indirection (peer.erl:2115-2153)

    def monitor_backend(self, actor_name: Any) -> None:
        """Monitor a backend helper process on the backend's behalf
        (erlang:monitor; DOWN flows to Mod:handle_down via the FSM
        mailbox so suspension semantics hold, peer.erl:1919-1929)."""
        def callback(name: Any) -> None:
            # The monitor fired (helper died): the entry is spent —
            # prune it so a backend that re-monitors a replacement
            # helper after every restart doesn't grow the list.  (The
            # deferred callback may land after on_stop cleared it.)
            try:
                self._backend_monitors.remove((name, callback))
            except ValueError:
                pass
            self.runtime.post(self.name, ("backend_down", name))

        self._backend_monitors.append((actor_name, callback))
        self.runtime.monitor(actor_name, callback)

    def _module_handle_down(self, name: Any) -> None:
        """module_handle_down (peer.erl:1937-1948): the behaviour
        returns False (not mine), ('ok',) (recovered), or ('reset',)
        — its storage is gone; step down and re-probe so the ensemble
        re-establishes state from the quorum."""
        result = self.mod.handle_down(name, name, "down")
        if result is False or result is None:
            return
        if result[0] == "reset":
            self._step_down("probe")

    def _mod_ping(self) -> bool:
        """Alive-ticks credit counter (peer.erl:2115-2128): 'async'
        spends a credit; backend_pong refills them."""
        result = self.mod.ping(self)
        if result == "ok":
            return True
        if result == "async" and self.alive_credits > 0:
            self.alive_credits -= 1
            return True
        return False

    def backend_pong(self) -> None:
        self.runtime.post(self.name, ("backend_pong",))

    def _mod_tick(self) -> None:
        f = self.fact
        self.mod.tick(f.epoch, f.seq, f.leader, f.views)

    def _mod_get(self, key, from_) -> None:
        self.mod.get(key, from_)

    def _mod_put(self, key, obj, from_) -> None:
        self.mod.put(key, obj, from_)

    def _do_local_get(self, from_, key) -> None:
        """Backend replies directly to from_ (reply-chain opt)."""
        self._mod_get(key, self._backend_from(from_))

    def _do_local_put(self, from_, key, obj) -> None:
        self._mod_put(key, obj, self._backend_from(from_))

    def _backend_from(self, from_):
        """Normalize a wire-from or (future, _) into a backend From."""
        if from_ is None:
            # Fire-and-forget put (read-repair cast, peer.erl:1518-1536:
            # From=undefined — the backend's reply is discarded).
            return (lambda value: None, self.id)
        if isinstance(from_, tuple) and len(from_) == 2 and \
                isinstance(from_[0], Future):
            return (from_[0], self.id)
        # wire from: (owner_name, reqid)
        return (lambda value: msglib.reply(self, from_, self.id, value),
                self.id)

    # ------------------------------------------------------------------
    # tree access (sync, same-node gen_server call semantics)

    def _tree_actor(self) -> PeerTree:
        return self.runtime.whereis(self.tree)

    def tree_get_sync(self, key):
        tree = self._tree_actor()
        fut = Future()
        tree.handle(("tree_get", key, fut))
        return fut.value

    def tree_insert_sync(self, key, objhash):
        tree = self._tree_actor()
        fut = Future()
        tree.handle(("tree_insert", key, objhash, fut))
        return fut.value

    def tree_top_hash_sync(self):
        tree = self._tree_actor()
        fut = Future()
        tree.handle(("tree_top_hash", fut))
        return fut.value

    # ==================================================================
    # K/V FSMs (run on workers; generators)
    # ==================================================================

    def _obj_sequence(self) -> int:
        """peer.erl:1776-1791."""
        epoch = self.ets["epoch"]
        seq = self.ets["seq"]
        self.ets[("obj_seq", epoch)] += 1
        return seq + self.ets[("obj_seq", epoch)]

    def _sync_to_self(self, inner: Tuple):
        """Worker-side sync_send_event back to own FSM; generator
        yielding the reply future (never resolves if the FSM kills the
        workers first — matching reference semantics)."""
        fut = Future()
        self.runtime.post(self.name, ("peer_sync", fut, inner))
        return fut

    def _local_get_from_worker(self, key):
        fut = Future()
        self.runtime.post(self.name, ("peer_sync", fut, ("local_get", key)))
        return self.runtime.with_timeout(fut, self.config.local_get_timeout)

    def _local_put_from_worker(self, key, obj):
        fut = Future()
        self.runtime.post(self.name,
                          ("peer_sync", fut, ("local_put", key, obj)))
        return self.runtime.with_timeout(fut, self.config.local_put_timeout)

    def _is_current(self, local, key, known_hash) -> str:
        """'timeout' | 'true' | 'false' (peer.erl:1550-1562)."""
        if local in ("timeout", "nack", "failed"):
            return "timeout"
        if local is NOTFOUND:
            return "false"
        if not self._verify_obj(key, local, known_hash):
            return "false"
        return "true" if local.epoch == self.epoch else "false"

    def _verify_obj(self, key, obj, known_hash) -> bool:
        """verify_hash (peer.erl:1740-1763)."""
        if obj is NOTFOUND:
            return known_hash is None
        if known_hash is None:
            return True
        return valid_obj_hash(get_obj_hash(obj), known_hash)

    # -- get FSM (peer.erl:1434-1491) -----------------------------------

    def _do_get_fsm(self, key, fut: Future, opts):
        known = self.tree_get_sync(key)
        if known == "corrupted":
            fut.resolve("failed")
            yield self._sync_to_self(("tree_corrupted",))
            return
        local = yield self._local_get_from_worker(key)
        local_only = "read_repair" not in opts
        cur = self._is_current(local, key, known)
        if cur == "timeout":
            fut.resolve("timeout")
        elif cur == "true":
            if local_only:
                ok = yield from self._check_lease()
                if ok:
                    fut.resolve(("ok", local))
                else:
                    fut.resolve("timeout")
                    yield self._sync_to_self(("request_failed",))
            else:
                result = yield from self._get_latest_obj(key, local, known)
                if result[0] == "ok":
                    _, latest, replies = result
                    self._maybe_repair(key, latest, replies)
                    fut.resolve(("ok", latest))
                else:
                    fut.resolve("timeout")
        else:
            result = yield from self._update_key(key, local, known)
            if result[0] == "ok":
                fut.resolve(("ok", result[1]))
            elif result[0] == "corrupted":
                fut.resolve("failed")
                yield self._sync_to_self(("tree_corrupted",))
            else:
                fut.resolve("failed")
                yield self._sync_to_self(("request_failed",))

    def _check_lease(self):
        """peer.erl:1493-1516.  The lease is trusted only up to the
        clock-skew margin (Config.read_margin) — the same guard the
        batched plane's read fast path applies; past it the read
        falls back to the check_epoch quorum round."""
        if self.config.trust_lease and \
                self.lease_obj.check_lease(self.config.read_margin()):
            return True
        fut = self._blocking_send_all(("check_epoch", self.id, self.epoch))
        outcome = yield fut
        return outcome[0] == "quorum_met"

    def _maybe_repair(self, key, latest, replies) -> None:
        """peer.erl:1518-1536: async read-repair puts."""
        should = any(obj != latest for _, obj in replies if obj != "nack")
        if should:
            self._cast_all(("put", key, latest, self.id, self.epoch, None))

    # -- put FSMs (peer.erl:1369-1432) ----------------------------------

    def _do_put_fsm(self, key, fun, args, fut: Future):
        known = self.tree_get_sync(key)
        if known == "corrupted":
            fut.resolve("failed")
            yield self._sync_to_self(("tree_corrupted",))
            return
        local = yield self._local_get_from_worker(key)
        cur = self._is_current(local, key, known)
        if cur == "timeout":
            fut.resolve("unavailable")
            return
        if cur == "true":
            yield from self._do_modify_fsm(key, local, fun, args, fut)
        else:
            result = yield from self._update_key(key, local, known)
            if result[0] == "ok":
                yield from self._do_modify_fsm(key, result[1], fun, args,
                                               fut)
            elif result[0] == "corrupted":
                fut.resolve("failed")
                yield self._sync_to_self(("tree_corrupted",))
            else:
                yield self._sync_to_self(("request_failed",))
                fut.resolve("unavailable")

    def _do_modify_fsm(self, key, current, fun, args, fut: Future):
        """peer.erl:1404-1416."""
        seq = self._obj_sequence()
        new = fun(current, seq, self, args)
        if new == "failed":
            fut.resolve("failed")
            return
        _, new_obj = new
        result = yield from self._put_obj(key, new_obj, seq)
        if result[0] == "ok":
            fut.resolve(("ok", result[1]))
        elif result[0] == "corrupted":
            fut.resolve("failed")
            yield self._sync_to_self(("tree_corrupted",))
        else:
            yield self._sync_to_self(("request_failed",))
            fut.resolve("timeout")

    def _do_overwrite_fsm(self, key, value, fut: Future):
        """peer.erl:1418-1432."""
        epoch = self.epoch
        seq = self._obj_sequence()
        obj = self.mod.new_obj(epoch, seq, key, value)
        result = yield from self._put_obj(key, obj, seq)
        if result[0] == "ok":
            fut.resolve(("ok", result[1]))
        elif result[0] == "corrupted":
            fut.resolve("timeout")
            yield self._sync_to_self(("tree_corrupted",))
        else:
            yield self._sync_to_self(("request_failed",))
            fut.resolve("timeout")

    # -- shared K/V helpers ---------------------------------------------

    def _update_key(self, key, local, known):
        """Quorum read + rewrite at current epoch (peer.erl:1564-1596).
        Returns ('ok', obj) | ('failed',) | ('corrupted',)."""
        num_peers = len(self.get_peers(self.members))
        result = yield from self._get_latest_obj(key, local, known)
        if result[0] != "ok":
            return ("failed",)
        _, latest, replies = result
        if latest is NOTFOUND and len(replies) + 1 == num_peers:
            # Everyone said notfound: skip the tombstone write
            # (peer.erl:1568-1584).
            seq = self._obj_sequence()
            new = self.mod.new_obj(self.epoch, seq, key, NOTFOUND)
            return ("ok", new)
        put = yield from self._put_obj(key, latest)
        return put

    def _get_latest_obj(self, key, local, known):
        """Quorum read with hash extra-check (peer.erl:1623-1662).
        Returns ('ok', latest, replies) | ('failed',)."""
        epoch = self.epoch
        peers = self.get_peers(self.members)

        def check(replies):
            for _, robj in replies:
                if robj == "nack":
                    continue
                if robj is NOTFOUND:
                    if known is None:
                        return True
                elif known is None or \
                        valid_obj_hash(get_obj_hash(robj), known):
                    # existing object is by definition newer than a
                    # notfound known-hash
                    return True
            return False

        extra = None if self._verify_obj(key, local, known) else check
        required = "all_or_quorum" if known is None else "quorum"
        fut = self._blocking_send_all(("get", key, self.id, epoch),
                                      peers=peers, required=required,
                                      extra=extra)
        outcome = yield fut
        if outcome[0] != "quorum_met":
            return ("failed",)
        replies = outcome[1]
        latest = local
        for _, robj in replies:
            if robj is NOTFOUND:
                continue
            if latest is NOTFOUND or latest in ("timeout", "nack", "failed"):
                latest = robj
            else:
                latest = self.mod.latest_obj(latest, robj)
        if latest in ("timeout", "nack", "failed"):
            latest = NOTFOUND
        if not self._verify_obj(key, latest, known):
            return ("failed",)
        return ("ok", latest, replies)

    def _put_obj(self, key, obj, seq: Optional[int] = None):
        """Quorum write + hash update (peer.erl:1664-1698).
        Returns ('ok', obj) | ('failed',) | ('corrupted',)."""
        if seq is None:
            seq = self._obj_sequence()
        epoch = self.epoch
        if obj is NOTFOUND:
            obj2 = self.mod.new_obj(epoch, seq, key, NOTFOUND)
        else:
            obj2 = self.mod.set_obj_epoch(
                epoch, self.mod.set_obj_seq(seq, obj))
        peers = self.get_peers(self.members)
        fut = self._blocking_send_all(("put", key, obj2, self.id, epoch),
                                      peers=peers)
        local = yield self._local_put_from_worker(key, obj2)
        if local in ("timeout", "nack", "failed"):
            yield self._sync_to_self(("request_failed",))
            return ("failed",)
        outcome = yield fut
        if outcome[0] != "quorum_met":
            return ("failed",)
        objhash = get_obj_hash(local)
        if self.tree_insert_sync(key, objhash) == "corrupted":
            return ("corrupted",)
        ok = yield from self._send_update_hash(key, objhash)
        if not ok:
            return ("failed",)
        return ("ok", local)

    def _send_update_hash(self, key, objhash):
        """peer.erl:1700-1715."""
        if not self.config.synchronous_tree_updates:
            self._cast_all(("update_hash", key, objhash, None))
            return True
        fut = self._blocking_send_all(("update_hash", key, objhash))
        outcome = yield fut
        return outcome[0] == "quorum_met"

    # ------------------------------------------------------------------
    # leadership watchers (peer.erl:212-218, 2070-2075)

    def _notify_leader_status(self, watchers, leading=None) -> None:
        if leading is None:
            leading = self.fsm_state == "leading"
        status = "is_leading" if leading else "is_not_leading"
        for w in list(watchers):
            if self.runtime.whereis(w) is None:
                if w in self.watchers:
                    self.watchers.remove(w)
                continue
            self.send_local(w, (status, self.name, self.id, self.ensemble,
                                self.epoch))

    def on_stop(self) -> None:
        self._cancel_timer()
        self.workers.reset()
        # A backend helper may outlive this peer: release its monitors
        # or each peer restart leaks a closure pinning the dead Peer.
        for target, callback in self._backend_monitors:
            self.runtime.demonitor(target, callback)
        self._backend_monitors.clear()
        if self.runtime.whereis(self.tree) is not None:
            self.runtime.stop_actor(self.tree)


# ---------------------------------------------------------------------------
# module helpers


def _fact_replace(fact: Fact, **kw) -> Fact:
    import dataclasses
    return dataclasses.replace(fact, **kw)


def latest_fact_of(replies, own: Fact) -> Fact:
    """peer.erl:2031-2040."""
    best = own
    for _, fact in replies:
        if isinstance(fact, Fact):
            best = latest_fact(best, fact)
    return best


def existing_leader(replies, abandoned, latest: Fact):
    """Vote among probe replies for a live leader (peer.erl:2042-2068)."""
    if latest.leader is None:
        members = members_of(latest.views)
        counts: Dict[Tuple[int, PeerId], int] = {}
        for _, fact in replies:
            if not isinstance(fact, Fact) or fact.leader is None:
                continue
            vsn = (fact.epoch, fact.seq)
            valid = abandoned is None or vsn > abandoned
            if valid and fact.leader in members:
                counts[(fact.epoch, fact.leader)] = \
                    counts.get((fact.epoch, fact.leader), 0) + 1
        if not counts:
            return None
        # max count; deterministic tie-break on (epoch, leader)
        (_, leader), _ = max(counts.items(),
                             key=lambda kv: (kv[1], kv[0][0]))
        return leader
    if abandoned is None or (latest.epoch, latest.seq) > abandoned:
        return latest.leader
    return None


# ---------------------------------------------------------------------------
# K/V modify functions (peer.erl do_kupdate/do_kput_once/do_kmodify)


@funref.register("peer:kupdate")
def do_kupdate(obj, _next_seq, peer: Peer, args):
    """CAS on (epoch, seq) (peer.erl:259-270)."""
    current, new = args
    expected = (peer.mod.obj_epoch(current), peer.mod.obj_seq(current))
    if (peer.mod.obj_epoch(obj), peer.mod.obj_seq(obj)) == expected:
        return ("ok", peer.mod.set_obj_value(new, obj))
    return "failed"


@funref.register("peer:kput_once")
def do_kput_once(obj, _next_seq, peer: Peer, args):
    """peer.erl:278-284."""
    (new,) = args
    if peer.mod.obj_value(obj) is NOTFOUND:
        return ("ok", peer.mod.set_obj_value(new, obj))
    return "failed"


@funref.register("peer:kmodify")
def do_kmodify(obj, next_seq, peer: Peer, args):
    """peer.erl:303-317: user function applied inside the put FSM."""
    mod_fun, default = args
    try:
        mod_fun = funref.resolve(mod_fun)
    except ValueError:
        return "failed"
    value = peer.mod.obj_value(obj)
    if value is NOTFOUND:
        value = default
    vsn = (peer.epoch, next_seq)
    new = mod_fun(vsn, value)
    if new == "failed":
        return "failed"
    return ("ok", peer.mod.set_obj_value(new, obj))


# ---------------------------------------------------------------------------
# Direct (router-less) sync API used by tests and the router


def sync_send_event(runtime: Runtime, target_name, message: Tuple,
                    timeout: float = 30.0):
    """gen_fsm:sync_send_event analog: drives the loop until replied."""
    fut = Future()
    runtime.post(target_name, ("peer_sync", fut, message))
    try:
        return runtime.await_future(
            runtime.with_timeout(fut, timeout), timeout=timeout + 1.0)
    except TimeoutError:
        return "timeout"
