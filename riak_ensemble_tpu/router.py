"""Leader routing with late-message isolation.

Re-implementation of ``src/riak_ensemble_router.erl``: a pool of 7
router actors per node (``routers/0``, router.erl:163-170) that route a
request addressed by *ensemble id* to that ensemble's leader — local
leader gets the event directly, a remote leader gets the request
forwarded to a random router on the leader's node
(``ensemble_cast``, router.erl:216-232).

Late-message isolation (router.erl:40-43,75-122): every sync request
runs through a spawned per-request proxy actor; on timeout the caller
gets ``timeout`` and any stray late reply is absorbed by the
(now-stopped) proxy rather than corrupting a later request.  Request
identity is a fresh reqid per call (the ``make_ref()`` pattern).

Unknown leader → immediate ``timeout`` result (router.erl fail_cast /
``ensemble_cast`` error branch).
"""

from __future__ import annotations

import itertools
from typing import Any, Tuple

from riak_ensemble_tpu.runtime import Actor, Future, Runtime

#: router.erl:163-170 — seven routers per node.
N_ROUTERS = 7

_proxy_ids = itertools.count(1)
_refs = itertools.count(1)


def router_name(node: str, i: int) -> Tuple:
    return ("router", node, i)


def manager_name(node: str) -> Tuple:
    return ("manager", node)


class Router(Actor):
    """One of the per-node router pool (router.erl gen_server)."""

    def __init__(self, runtime: Runtime, node: str, index: int) -> None:
        super().__init__(runtime, router_name(node, index), node)
        self.index = index

    def _directory(self):
        return self.runtime.whereis(manager_name(self.node))

    def handle(self, msg: Tuple) -> None:
        if msg[0] == "ensemble_cast":
            _, ensemble, inner = msg
            self.ensemble_cast(ensemble, inner)

    def ensemble_cast(self, ensemble: Any, inner: Tuple) -> None:
        """router.erl:216-232."""
        directory = self._directory()
        leader = directory.get_leader(ensemble) if directory else None
        if leader is None:
            _fail_cast(self, inner)
            return
        if leader.node == self.node:
            addr = directory.get_peer_addr(ensemble, leader)
            if addr is None:
                _fail_cast(self, inner)
                return
            self._handle_ensemble_cast(inner, addr)
        else:
            cast(self.runtime, self, leader.node, ensemble, inner)

    def _handle_ensemble_cast(self, inner: Tuple, addr: Any) -> None:
        """Deliver to the local leader; for sync requests bridge the
        peer's local Future reply back over the network
        (router.erl:235-249 spawned per-request caller)."""
        if inner[0] == "sync_send_event":
            _, from_, event, timeout = inner
            owner, ref = from_
            fut = Future()
            self.runtime.post(addr, ("peer_sync", fut, event))
            router = self

            def relay(result: Any) -> None:
                router.send(owner, ("rtr_reply", ref, result))

            self.runtime.with_timeout(fut, timeout).add_waiter(relay)


def _fail_cast(router: Router, inner: Tuple) -> None:
    """router.erl fail_cast: sync callers get an immediate timeout."""
    if inner[0] == "sync_send_event":
        _, (owner, ref), _, _ = inner
        router.send(owner, ("rtr_reply", ref, "timeout"))


def cast(runtime: Runtime, src: Actor, node: str, ensemble: Any,
         inner: Tuple) -> None:
    """Forward to a random router on `node` (router.erl:128-142); a
    dead/unreachable router means the message is simply lost and the
    caller times out (noconnect semantics, router.erl:144-160)."""
    pick = runtime.rng.randrange(N_ROUTERS)
    src.send(router_name(node, pick), ("ensemble_cast", ensemble, inner))


class _Proxy(Actor):
    """Per-request proxy (router.erl sync_proxy:89-122)."""

    def __init__(self, runtime: Runtime, node: str, fut: Future,
                 ref: int) -> None:
        super().__init__(runtime, ("rtr_proxy", node, next(_proxy_ids)),
                         node)
        self.fut = fut
        self.ref = ref

    def handle(self, msg: Tuple) -> None:
        if msg[0] == "rtr_reply" and msg[1] == self.ref:
            self.fut.resolve(msg[2])
            self.stop()


def sync_send_event_fut(runtime: Runtime, node: str, ensemble: Any,
                        event: Tuple, timeout: float) -> Future:
    """Route `event` to the ensemble's leader starting from `node`'s
    router pool; returns a Future resolving to the reply or
    ``"timeout"`` (router.erl sync_send_event:71-87).

    The per-request proxy lives on the CALLING process's node (a
    networked runtime hosts one node and exposes it as ``.node``; the
    simulator hosts all nodes, so the proxy co-locates with the target
    pool there) and the request reaches a possibly-remote router over
    the transport.
    """
    fut = Future()
    ref = next(_refs)
    local_node = getattr(runtime, "node", node)
    proxy = _Proxy(runtime, local_node, fut, ref)
    inner = ("sync_send_event", (proxy.name, ref), event, timeout)
    pick = runtime.rng.randrange(N_ROUTERS)
    runtime.net_send(local_node, router_name(node, pick),
                     ("ensemble_cast", ensemble, inner))

    out = runtime.with_timeout(fut, timeout)

    def cleanup(_v: Any) -> None:
        if runtime.whereis(proxy.name) is not None:
            runtime.stop_actor(proxy.name)

    out.add_waiter(cleanup)
    return out


def sync_send_event(runtime: Runtime, node: str, ensemble: Any,
                    event: Tuple, timeout: float = 10.0):
    """Blocking (loop-driving) form for tests/clients."""
    fut = sync_send_event_fut(runtime, node, ensemble, event, timeout)
    try:
        return runtime.await_future(fut, timeout=timeout + 1.0)
    except TimeoutError:
        return "timeout"


def start_routers(runtime: Runtime, node: str) -> None:
    """riak_ensemble_router_sup:init (router_sup.erl:40-45)."""
    for i in range(N_ROUTERS):
        if runtime.whereis(router_name(node, i)) is None:
            Router(runtime, node, i)
