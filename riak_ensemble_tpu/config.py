"""Configuration with derived-timeout hierarchy.

Mirrors ``src/riak_ensemble_config.erl:27-130``.  The derivation chain
``tick < lease < follower_timeout < election_timeout`` is a correctness
constraint (a leader must refresh its lease well before followers give
up on it); overriding one knob re-derives the ones below it unless they
are explicitly pinned.

All durations are in **seconds** (the host runtime uses a monotonic
float-second clock, virtual in tests, ``CLOCK_BOOTTIME`` in production
via the C++ clock module).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional


@dataclass
class Config:
    # Primary ensemble tick: leader lease-refresh rate
    # (config.erl:27-28, default 500ms).
    ensemble_tick: float = 0.5

    # Leader lease duration; > tick, < follower_timeout
    # (config.erl:34-35, default 1.5x tick).
    lease_duration: Optional[float] = None

    # Whether leaders may serve reads locally inside an unexpired lease
    # (config.erl:41-42).  The batched service's lease-protected read
    # fast path honors this: False forces every read through a device
    # round.
    trust_lease: bool = True

    # Safety margin subtracted from the lease before a leader serves a
    # local read (the clock-skew guard of the lease argument): a fast
    # read is allowed only while now + margin < lease expiry, and the
    # inequality lease + margin < follower_timeout must hold — a
    # follower must outwait any read the leader could still be
    # serving.  Default tick/2 (well inside the 3x-lease headroom the
    # default derivation chain leaves).
    read_lease_margin: Optional[float] = None

    # How long a follower waits for leader commits before abandoning it
    # (config.erl:47-48, default 4x lease).
    follower_timeout: Optional[float] = None

    # Randomized election timeout base (config.erl:52-54: ft + U(0, ft)).
    # election_timeout() below applies the randomization.
    election_timeout_base: Optional[float] = None

    # Prefollow timeout: wait on a preliminary leader (config.erl:58-60).
    prefollow_timeout: Optional[float] = None

    # Pending timeout: peers not-yet-members wait in `pending` state
    # (config.erl:64-66, default 10x tick).
    pending_timeout: Optional[float] = None

    # Alive ticks: failed leader ticks tolerated before step-down
    # (config.erl:70-72 alive_tokens, default 2).
    alive_ticks: int = 2

    # Worker pool size per peer (config.erl:88-89, default 1).
    peer_workers: int = 1

    # Probe retry delay (config.erl:77-84, default 1s).
    probe_delay: float = 1.0

    # Coalesced fact storage: flush delay after first dirty write and
    # periodic tick (config.erl:94-101, 50ms / 5s).
    storage_delay: float = 0.05
    storage_tick: float = 5.0

    # Distrust synctrees on restart until an exchange completes
    # (config.erl:104-108).
    tree_validation: bool = True

    # Send follower synctree updates synchronously (config.erl:112-117).
    synchronous_tree_updates: bool = False

    # Extra wait for *all* responses before treating notfound as
    # authoritative — tombstone avoidance (config.erl:126-127, 1ms).
    notfound_read_delay: float = 0.001

    # Local backend op timeouts (peer.erl LOCAL_GET/PUT_TIMEOUT, 60s).
    local_get_timeout: float = 60.0
    local_put_timeout: float = 60.0

    # Quorum vote-collection timeout (msg.erl:95,235 = tick).
    quorum_timeout: Optional[float] = None

    # K/V client-facing request timeout (peer.erl ?REQUEST_TIMEOUT 30s).
    request_timeout: float = 30.0

    # Gossip tick for the cluster manager (manager.erl:569-573, 2s).
    gossip_tick: float = 2.0

    # Routers per node (router.erl:163-170). The host runtime has no
    # process-mailbox bottleneck, kept for parity/introspection.
    routers: int = 7

    # -- derived accessors ------------------------------------------------

    def lease(self) -> float:
        return self.lease_duration if self.lease_duration is not None \
            else self.ensemble_tick * 1.5

    def follower(self) -> float:
        return self.follower_timeout if self.follower_timeout is not None \
            else self.lease() * 4

    def read_margin(self) -> float:
        return self.read_lease_margin \
            if self.read_lease_margin is not None \
            else self.ensemble_tick * 0.5

    def election_timeout(self, rng: random.Random) -> float:
        base = self.election_timeout_base if self.election_timeout_base is not None \
            else self.follower()
        return base + rng.uniform(0, base)

    def prefollow(self) -> float:
        return self.prefollow_timeout if self.prefollow_timeout is not None \
            else self.ensemble_tick * 2

    def pending(self) -> float:
        return self.pending_timeout if self.pending_timeout is not None \
            else self.ensemble_tick * 10

    def quorum(self) -> float:
        return self.quorum_timeout if self.quorum_timeout is not None \
            else self.ensemble_tick

    def validate(self) -> None:
        """Assert the timeout hierarchy invariant."""
        assert self.ensemble_tick < self.lease() < self.follower(), (
            "config invariant violated: need tick < lease < follower_timeout "
            f"got {self.ensemble_tick} / {self.lease()} / {self.follower()}"
        )
        # The lease-read safety inequality: a leader may serve a local
        # read up to (lease - margin) after its last quorum contact,
        # and a follower elects only after follower_timeout of leader
        # silence — lease + margin < follower_timeout keeps every
        # possible leased read strictly inside the followers' patience
        # even under clock skew up to the margin.  Only binding when
        # leased reads are possible at all (trust_lease): an opted-out
        # config never serves around the quorum round and keeps the
        # pre-existing construction contract.
        assert not self.trust_lease or (
            0.0 <= self.read_margin() and
            self.lease() + self.read_margin() < self.follower()), (
            "config invariant violated: need lease + read_margin < "
            f"follower_timeout, got {self.lease()} + {self.read_margin()}"
            f" vs {self.follower()}"
        )


#: Test-friendly config: 10x faster than production defaults so virtual-
#: time integration tests converge in a few simulated seconds.
def fast_test_config() -> Config:
    return Config(ensemble_tick=0.05, probe_delay=0.1, storage_delay=0.005,
                  storage_tick=0.5, gossip_tick=0.2)
