"""One-node process entry for real (multi-process) deployments.

Each OS process runs this module for one node: it brings up the
networked runtime (:mod:`riak_ensemble_tpu.netruntime`), the node's
stack (storage → manager → routers, the sup-tree order), and either
idles as a cluster member or executes a user script — an async
function ``main(node)`` — for orchestration (tests, operational
one-shots).

    python -m riak_ensemble_tpu.netnode --node node0 \
        --peer node0=127.0.0.1:7501 --peer node1=127.0.0.1:7502 \
        --fast --script bring_up.py

The :class:`AsyncNode` handle exposes awaitable versions of the public
surface: enable/join/remove/create_ensemble and the client K/V API.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from typing import Any, Dict, Optional, Sequence, Tuple

from riak_ensemble_tpu import funref
from riak_ensemble_tpu import router as routerlib
from riak_ensemble_tpu.client import translate
from riak_ensemble_tpu.config import Config, fast_test_config
from riak_ensemble_tpu.manager import Manager
from riak_ensemble_tpu.netruntime import NetRuntime
from riak_ensemble_tpu.storage import Storage
from riak_ensemble_tpu.types import NOTFOUND, Obj, PeerId


class AsyncNode:
    def __init__(self, runtime: NetRuntime, manager: Manager,
                 storage: Storage) -> None:
        self.runtime = runtime
        self.manager = manager
        self.storage = storage
        self.node = runtime.node

    # -- cluster ops -------------------------------------------------------

    async def enable(self, wait: float = 60.0) -> str:
        result = self.manager.enable()
        if result != "ok":
            return result
        deadline = self.runtime.now + wait
        while self.runtime.now < deadline:
            peer = self.manager.local_peers.get(
                ("root", PeerId("root", self.node)))
            if peer is not None and peer.fsm_state == "leading":
                return "ok"
            await asyncio.sleep(0.05)
        return "timeout"

    async def join(self, other_node: str, timeout: float = 60.0):
        return await self.runtime.await_future(
            self.manager.join_async(other_node, timeout), timeout + 5.0)

    async def remove(self, target: str, timeout: float = 60.0):
        return await self.runtime.await_future(
            self.manager.remove_async(target, timeout), timeout + 5.0)

    async def create_ensemble(self, ensemble: Any,
                              peers: Sequence[PeerId], mod: str = "basic",
                              args=(), timeout: float = 30.0):
        leader = peers[0] if peers else None
        return await self.runtime.await_future(
            self.manager.create_ensemble(ensemble, leader, list(peers),
                                         mod, tuple(args), timeout),
            timeout + 5.0)

    def members(self) -> Sequence[str]:
        return self.manager.cluster()

    # -- async client (client.erl surface) ----------------------------------

    async def _sync(self, ensemble, event, timeout: float):
        if not self.manager.enabled():
            return ("error", "unavailable")
        fut = routerlib.sync_send_event_fut(self.runtime, self.node,
                                            ensemble, event, timeout)
        try:
            result = await self.runtime.await_future(fut, timeout + 2.0)
        except asyncio.TimeoutError:
            result = "timeout"
        return translate(result)

    async def kget(self, ensemble, key, timeout: float = 10.0, opts=()):
        return await self._sync(ensemble, ("get", key, tuple(opts)),
                                timeout)

    async def kover(self, ensemble, key, value, timeout: float = 10.0):
        return await self._sync(ensemble, ("overwrite", key, value),
                                timeout)

    async def kput_once(self, ensemble, key, value, timeout: float = 10.0):
        return await self._sync(
            ensemble, ("put", key, funref.ref("peer:kput_once"), [value]),
            timeout)

    async def kupdate(self, ensemble, key, current: Obj, new,
                      timeout: float = 10.0):
        return await self._sync(
            ensemble, ("put", key, funref.ref("peer:kupdate"),
                       [current, new]), timeout)

    async def kdelete(self, ensemble, key, timeout: float = 10.0):
        return await self.kover(ensemble, key, NOTFOUND, timeout)

    async def ksafe_delete(self, ensemble, key, current: Obj,
                           timeout: float = 10.0):
        return await self.kupdate(ensemble, key, current, NOTFOUND,
                                  timeout)


async def run_node(node: str, peers: Dict[str, Tuple[str, int]],
                   config: Optional[Config] = None,
                   data_root: Optional[str] = None, seed: int = 0,
                   script: Optional[Any] = None) -> None:
    config = config if config is not None else Config()
    runtime = NetRuntime(node, peers, seed=seed)
    await runtime.start()
    storage = Storage(runtime, node, config, data_root)
    manager = Manager(runtime, node, config, storage)
    handle = AsyncNode(runtime, manager, storage)
    try:
        if script is not None:
            await script(handle)
        else:
            await asyncio.Event().wait()  # serve forever
    finally:
        await runtime.stop()


def _parse_peer(spec: str) -> Tuple[str, Tuple[str, int]]:
    name, addr = spec.split("=", 1)
    host, port = addr.rsplit(":", 1)
    return name, (host, int(port))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True)
    ap.add_argument("--peer", action="append", required=True,
                    help="node=host:port (repeat; must include --node)")
    ap.add_argument("--data-root", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fast", action="store_true",
                    help="test-speed timeouts (fast_test_config)")
    ap.add_argument("--script", default=None,
                    help="python file defining `async def main(node)`")
    args = ap.parse_args(argv)

    peers = dict(_parse_peer(s) for s in args.peer)
    config = fast_test_config() if args.fast else Config()
    script = None
    if args.script:
        import importlib.util
        spec = importlib.util.spec_from_file_location("node_script",
                                                      args.script)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        script = mod.main
    asyncio.run(run_node(args.node, peers, config, args.data_root,
                         args.seed, script))
    return 0


if __name__ == "__main__":
    sys.exit(main())
