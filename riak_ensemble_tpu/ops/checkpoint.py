"""Checkpoint/restore for the batched engine state.

The reference checkpoints per-peer facts through the coalescing storage
manager (maybe_save_fact, peer.erl:2201-2228; SURVEY §5) and recovers
by reloading + probing.  The device engine's equivalent: snapshot the
whole ``EngineState`` — E ensembles' ballots and replicated stores in
one pytree — via orbax (the TPU-native checkpointer), and restore it
into a fresh process.  A restored state is immediately serveable: the
ballot arrays ARE the facts, so there is no probe phase (the batched
analog of reload_fact + local_commit).

Orbax handles sharded arrays transparently, so the same two calls
checkpoint a mesh-sharded state from a multi-host job.
"""

from __future__ import annotations

import os
from typing import Optional

from riak_ensemble_tpu.ops.engine import EngineState


def save(path: str, state: EngineState) -> None:
    """Write a checkpoint (atomic directory swap, orbax semantics)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, state._asdict(), force=True)


def load(path: str, template: Optional[EngineState] = None) -> EngineState:
    """Restore a checkpoint.  ``template`` (an ``init_state`` of the
    same shapes) restores each array DIRECTLY onto the template
    leaf's sharding — so a checkpoint taken under one device
    placement restores onto another (mesh-sharded save → single-shard
    serve and back) without inheriting the save-time placement from
    the file.  Without a template, arrays come back with saved
    metadata."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    if template is not None:
        tpl = template._asdict()
        restore_args = jax.tree.map(
            lambda x: ocp.ArrayRestoreArgs(sharding=x.sharding)
            if isinstance(x, jax.Array) else ocp.RestoreArgs(), tpl)
        restored = ckptr.restore(path, item=tpl,
                                 restore_args=restore_args)
    else:
        restored = ckptr.restore(path)
    return EngineState(**restored)
