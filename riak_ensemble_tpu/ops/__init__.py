"""Jit-compiled protocol kernels: quorum reduction, ballot matrix
transitions, Merkle hashing.  These are the TPU data path; the host
runtime (:mod:`riak_ensemble_tpu.runtime`) drives them."""

from riak_ensemble_tpu.ops.quorum import (  # noqa: F401
    MET,
    UNDECIDED,
    NACK,
    quorum_met,
    quorum_met_batch,
)
