"""Quorum vote reduction — the kernel of the communication layer.

Reference semantics: ``riak_ensemble_msg:quorum_met/5``
(``src/riak_ensemble_msg.erl:377-418``):

- ``views`` is a list of member lists (joint consensus); quorum must be
  met in EVERY view, checked in order.
- Per view: ``thresh = len(members)//2 + 1`` (or ``len(members)`` for
  ``required='all'``); the caller counts as one implicit valid reply
  when it is a member, except in ``'other'`` mode (used by the
  untrusted-tree exchange, which must hear a majority *excluding*
  itself).
- A view with ``nacks >= thresh``, or where everyone was heard from yet
  quorum wasn't reached, fails the whole call with ``NACK``.  A view
  that might still succeed returns ``UNDECIDED`` (keep collecting) —
  and, exactly like the reference's recursion, later views are NOT
  examined for nacks in that case.

Two implementations with identical semantics:

- :func:`quorum_met` — host scalar version on Python sets, used by the
  per-peer FSM in the host runtime (and as the differential-test
  oracle).
- :func:`quorum_met_batch` — jit/vmap-able array version over an
  ``[E]`` ensemble batch with an ``[M]`` peer axis and ``[V, M]`` view
  membership masks.  This is the majority-reduce that rides ICI
  (``psum`` over the peer mesh axis) in the sharded engine.

The two agree exactly for ``extra=None`` (differentially tested).  The
``extra`` predicate (read-path hash-validity check) exists only on the
scalar path by design: the batched engine's read kernel expresses the
same check directly as array ops over its reply buffers
(an arbitrary Python callable can't cross into jit).
"""

from __future__ import annotations

import functools
from typing import Callable, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Result codes (shared by scalar and batched versions).
MET = 1
UNDECIDED = 0
NACK = -1

#: required() modes (msg.erl:43).
REQUIRED_MODES = ("quorum", "all", "all_or_quorum", "other")


def quorum_met(replies: Iterable[Tuple[object, object]],
               self_id: object,
               views: Sequence[Sequence[object]],
               required: str = "quorum",
               extra: "Optional[Callable[[list], bool]]" = None) -> int:
    """Scalar quorum predicate.

    ``replies`` is an iterable of ``(peer_id, reply)`` where a reply of
    the string ``'nack'`` is a negative vote.  Returns MET / UNDECIDED /
    NACK.  ``extra`` is an optional extra predicate on the replies,
    evaluated only once every view has met (the recursion base case,
    msg.erl:382-388) — used by the read path's hash-validity check.
    """
    assert required in REQUIRED_MODES, required
    replies = list(replies)
    for members in views:
        members = list(members)
        filtered = [(p, r) for (p, r) in replies if p in members]
        valid = [p for (p, r) in filtered if r != "nack"]
        nacks = [p for (p, r) in filtered if r == "nack"]
        if required == "all":
            thresh = len(members)
        else:
            thresh = len(members) // 2 + 1
        heard = len(valid)
        if required != "other" and self_id in members:
            heard += 1
        if heard >= thresh:
            continue
        if len(nacks) >= thresh:
            return NACK
        if heard + len(nacks) == len(members):
            return NACK
        return UNDECIDED
    if extra is not None and not extra(replies):
        return UNDECIDED
    return MET


def find_valid(replies):
    """Partition replies into (valid, nacks) (msg.erl:420-426)."""
    valid = [(p, r) for (p, r) in replies if r != "nack"]
    nacks = [(p, r) for (p, r) in replies if r == "nack"]
    return valid, nacks


# ---------------------------------------------------------------------------
# Batched array version


def reduce_peers(x: jax.Array, axis_name) -> jax.Array:
    """Sum over the trailing (local) peer axis, then over the mesh
    'peer' axis when sharded — the vote-count all-reduce.  Shared by
    every peer-axis reduction in the batched engine."""
    s = x.sum(-1)
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
    return s


@functools.partial(jax.jit, static_argnames=("required", "axis_name"))
def quorum_met_batch(valid: jax.Array,
                     nack: jax.Array,
                     view_mask: jax.Array,
                     self_idx: jax.Array,
                     required: str = "quorum",
                     axis_name: Optional[str] = None) -> jax.Array:
    """Batched quorum predicate.

    Args:
      valid:      bool ``[..., M]`` — peer m replied positively.
      nack:       bool ``[..., M]`` — peer m replied nack.  (A peer is
                  at most one of valid/nack; unheard peers are neither.)
      view_mask:  bool ``[..., V, M]`` — membership of peer m in view v.
                  All-zero rows are ignored (views list shorter than V).
      self_idx:   int  ``[...]`` — caller's index on the peer axis, or
                  -1 when the caller is not on this peer axis.
      required:   one of REQUIRED_MODES (static).
      axis_name:  mesh axis name when the peer axis M is sharded under
                  ``shard_map`` — vote counts become ``psum`` ICI
                  all-reduces (this is how the sharded engine calls
                  it).  Sharded callers must pass ``self_idx=-1`` and
                  fold their own vote into ``valid`` (a global index
                  cannot be matched against a local peer slice).

    Returns int8 ``[...]`` of MET / UNDECIDED / NACK.
    """
    assert required in REQUIRED_MODES, required
    vm = view_mask.astype(jnp.int32)                      # [..., V, M]
    members = reduce_peers(vm, axis_name)                 # [..., V]
    active = members > 0                                  # [..., V]
    n_valid = reduce_peers(vm * valid[..., None, :].astype(jnp.int32),
                           axis_name)
    n_nack = reduce_peers(vm * nack[..., None, :].astype(jnp.int32),
                          axis_name)

    if required == "all":
        thresh = members
    else:
        thresh = members // 2 + 1

    if axis_name is not None:
        # Sharded contract enforced at the source: a global self_idx
        # cannot be matched against a local peer slice, so the self
        # term is hard-zeroed (callers fold self into `valid`); this
        # also saves an all-reduce on the hot ICI path.
        self_in_view = jnp.zeros_like(members)
    else:
        m = view_mask.shape[-1]
        self_oh = jax.nn.one_hot(self_idx, m, dtype=jnp.int32)  # [..., M]
        self_in_view = (vm * self_oh[..., None, :]).sum(-1)     # [..., V]
    if required != "other":
        heard = n_valid + self_in_view
    else:
        heard = n_valid

    met_v = heard >= thresh                               # [..., V]
    nack_v = (n_nack >= thresh) | ((heard + n_nack) == members)
    # Inactive (padding) views count as met and never nack.
    met_v = met_v | ~active
    nack_v = nack_v & active

    all_met = met_v.all(-1)
    # First unmet view, in order — matches the reference's recursion,
    # which only reports NACK if every earlier view already met.
    first_unmet = jnp.argmin(met_v.astype(jnp.int32), axis=-1)  # [...]
    unmet_nacked = jnp.take_along_axis(
        nack_v.astype(jnp.int8), first_unmet[..., None], axis=-1
    )[..., 0]
    out = jnp.where(all_met, MET,
                    jnp.where(unmet_nacked > 0, NACK, UNDECIDED))
    return out.astype(jnp.int8)


def views_to_mask(views: Sequence[Sequence[int]], n_views: int,
                  n_peers: int) -> np.ndarray:
    """Encode a list of views (of peer indices) as a [V, M] bool mask."""
    mask = np.zeros((n_views, n_peers), dtype=bool)
    for i, view in enumerate(views):
        for p in view:
            mask[i, p] = True
    return mask
