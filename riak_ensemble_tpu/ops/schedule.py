"""Wide-round scheduling: pack a [K, E] op stream into [G, E, W]
conflict-free planes for :func:`engine.kv_step_scan_wide`.

The reference serializes same-key ops through its key-hashed worker
(``riak_ensemble_peer:async/3``, peer.erl:1220-1225) while distinct
keys proceed concurrently.  The batched engine's scan got the
serialization by running EVERY op as its own round; this scheduler
recovers the concurrency: ops on distinct slots within an ensemble are
conflict-free (no lane reads or writes another lane's slot; a GET can
write too — rewrite/tombstone/repair — so GETs chain like writes), so
they share one wide round, and the g-th op on the SAME slot goes to
round g (occurrence-index chaining preserves per-slot order).

The wide execution applies groups sequentially and lanes logically in
lane order (seqs by in-round rank), so it equals running the ops
through scalar rounds in (group, lane) order — a valid serialization
that reorders only ops on DIFFERENT slots, exactly the freedom the
reference's per-key workers have.

Shapes are bucketed (pow2 G and W) so the jit cache sees a handful of
plane shapes, not one per flush.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np

from riak_ensemble_tpu.ops.engine import OP_NOOP


class WidePlan(NamedTuple):
    """Scheduled planes + the result-routing map.

    kind/slot/val/lease_ok/exp_epoch/exp_seq: ``[G, E, W]`` (padding
    lanes are OP_NOOP at slot -1; ``lease_ok`` is None when the
    caller's lease is per-ensemble and rides an [E]-broadcast
    instead).  ``map_g``/``map_w``: ``[K, E]`` int32 — original op
    (k, e)'s group and lane, for routing ``KvResult[G, E, W]`` back
    to per-op order (NOOP padding maps to (0, 0); its routed result
    is meaningless and callers mask it).
    """

    kind: np.ndarray
    slot: np.ndarray
    val: np.ndarray
    lease_ok: Optional[np.ndarray]
    exp_epoch: np.ndarray
    exp_seq: np.ndarray
    map_g: np.ndarray
    map_w: np.ndarray


def _pow2_at_least(n: int) -> int:
    return 1 << (max(n, 1) - 1).bit_length()


def schedule_wide(kind: np.ndarray, slot: np.ndarray, val: np.ndarray,
                  lease_ok: Optional[np.ndarray],
                  exp_epoch: np.ndarray, exp_seq: np.ndarray,
                  max_width: int = 0,
                  max_groups: int = 0) -> Optional[WidePlan]:
    """Pack ``[K, E]`` planes into a :class:`WidePlan`.

    Vectorized (no per-op Python loop): occurrence indices come from a
    lexsort over (ensemble, slot, k) — an op's group is its rank among
    same-slot predecessors — and lane indices from a second lexsort
    over (ensemble, group, k).  O(K·E log(K·E)).

    ``max_width`` > 0 caps W (splitting overfull groups by spilling
    lanes to later groups would complicate ordering, so instead the
    cap simply falls back to W=1 scheduling when a flush is wider —
    callers use it to bound plane memory; 0 = no cap).

    ``max_groups`` > 0 returns None as soon as the duplicate chains
    run deeper than that many groups — the caller will take its
    scalar path, so the lane sort and plane packing (about two thirds
    of the scheduling cost) are skipped for those flushes.
    """
    k_depth, n_ens = kind.shape
    kind = np.ascontiguousarray(kind, np.int32)
    slot = np.ascontiguousarray(slot, np.int32)

    kk, ee = np.meshgrid(np.arange(k_depth, dtype=np.int32),
                         np.arange(n_ens, dtype=np.int32), indexing="ij")
    active = kind != OP_NOOP

    def _rank_in_runs(key_a: np.ndarray, key_b: np.ndarray) -> np.ndarray:
        """Rank of each element among same-(key_a, key_b) elements,
        in k order (lexsort + index-minus-run-start)."""
        order = np.lexsort((kk.ravel(), key_b.ravel(), key_a.ravel()))
        a_s = key_a.ravel()[order]
        b_s = key_b.ravel()[order]
        run_start = np.ones(order.size, bool)
        run_start[1:] = (a_s[1:] != a_s[:-1]) | (b_s[1:] != b_s[:-1])
        idx = np.arange(order.size)
        start_idx = np.maximum.accumulate(np.where(run_start, idx, 0))
        rank = np.empty(order.size, np.int32)
        rank[order] = (idx - start_idx).astype(np.int32)
        return rank.reshape(k_depth, n_ens)

    # Group = occurrence index among same-(e, slot) ACTIVE ops.  NOOP
    # padding and invalid-slot ops (slot < 0 — they can never write,
    # so they cannot conflict) get forced-unique negative keys: -1-k
    # is unique per row, and a real slot is never negative, so neither
    # can chain into anything.
    chain_slot = np.where(active & (slot >= 0), slot, -1 - kk)
    group = _rank_in_runs(ee, chain_slot)
    group[~active] = 0
    if max_groups and active.any() \
            and int(group[active].max()) + 1 > max_groups:
        return None  # deep duplicate chains: caller's scalar path

    # Lane = rank of k among ACTIVE ops in the same (e, group);
    # inactives share a sentinel group key, so they never dilute a
    # real group's lane numbering.
    lane = _rank_in_runs(ee, np.where(active, group, -1))
    lane[~active] = 0

    any_active = bool(active.any())
    n_groups = int(group[active].max()) + 1 if any_active else 1
    width = int(lane[active].max()) + 1 if any_active else 1
    if max_width and width > max_width:
        # Wider than the caller's memory budget: degenerate to the
        # sequential layout ([K, E, 1]), which is always legal — but
        # it has K groups, so a max_groups bound still applies (the
        # caller's warmed-program set must hold for EVERY returned
        # plan, not just the un-capped ones).
        if max_groups and k_depth > max_groups:
            return None
        group, lane = kk.copy(), np.zeros_like(kk)
        n_groups, width = k_depth, 1
    n_groups = _pow2_at_least(n_groups)
    width = _pow2_at_least(width)

    m = active
    def pack(plane: np.ndarray, fill: int) -> np.ndarray:
        out = np.full((n_groups, n_ens, width), fill, np.int32)
        out[group[m], ee[m], lane[m]] = np.asarray(plane, np.int32)[m]
        return out

    return WidePlan(
        kind=pack(kind, OP_NOOP), slot=pack(slot, -1), val=pack(val, 0),
        lease_ok=(None if lease_ok is None else
                  pack(np.asarray(lease_ok, np.int32), 0).astype(bool)),
        exp_epoch=pack(exp_epoch, 0), exp_seq=pack(exp_seq, 0),
        map_g=group, map_w=lane)


def route_results(plan: WidePlan, field: np.ndarray) -> np.ndarray:
    """Gather a ``[G, E, W, ...]`` result field back to the original
    ``[K, E, ...]`` op order."""
    ee = np.arange(plan.map_g.shape[1], dtype=np.int32)[None, :]
    return field[plan.map_g, ee, plan.map_w]


def flat_order(plan: WidePlan) -> Tuple[np.ndarray, np.ndarray]:
    """(k, e) indices of real ops in (group, lane) execution order per
    ensemble — the serialization the wide rounds realize (used by the
    differential tests to build the equivalent scalar op stream)."""
    k_depth, n_ens = plan.map_g.shape
    kk = np.arange(k_depth, dtype=np.int32)
    out_k = np.empty_like(plan.map_g)
    for e in range(n_ens):
        order = np.lexsort((plan.map_w[:, e], plan.map_g[:, e]))
        out_k[:, e] = kk[order]
    return out_k, np.broadcast_to(
        np.arange(n_ens, dtype=np.int32)[None, :], (k_depth, n_ens))


def shard_active_columns(active: np.ndarray, n_ens: int,
                         n_shards: int, a_min: int
                         ) -> Tuple[list, int]:
    """Split a GLOBAL active-column index set into per-ens-shard LOCAL
    index lists with one common pow2 bucket width.

    The mesh keeps E in ``n_shards`` contiguous blocks of
    ``E/n_shards`` rows (NamedSharding over the 'ens' axis), so a
    global column index ``c`` lives on shard ``c // e_loc`` at local
    index ``c % e_loc``.  Compaction-aware sharding computes the |A|
    bucket PER SHARD — every shard packs the same ``a_width`` columns
    (pow2 ≥ the busiest shard's count, floored at ``a_min``, capped at
    ``e_loc``) so the shard_map'd packer sees one static shape while
    each shard's d2h payload stays local.

    Returns ``(per_shard, a_width)``: ``per_shard[s]`` is an int32
    array of ≤ ``a_width`` LOCAL indices (the caller pads to
    ``a_width``); ``a_width == e_loc`` means no compaction wins on
    this flush (every shard at full width).
    """
    e_loc = n_ens // n_shards
    active = np.asarray(active, np.int32)
    shard_of = active // e_loc
    per_shard = [active[shard_of == s] - s * e_loc
                 for s in range(n_shards)]
    busiest = max((p.size for p in per_shard), default=0)
    a_width = _pow2_at_least(max(busiest, a_min))
    return per_shard, min(a_width, e_loc)
