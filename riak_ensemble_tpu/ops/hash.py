"""synctree_jax: the Merkle hash trie as a batched TPU kernel.

The host :class:`~riak_ensemble_tpu.synctree.tree.SyncTree` mirrors the
reference's per-peer trie (md5 buckets, width 16, 1M segments —
synctree.erl:88-89,251-259) for protocol-faithful per-op updates.  This
module is the scale path (BASELINE.md ladder #4, "1M-key Merkle
exchange"): the whole trie as a structure-of-arrays program —

- ``levels[k]``: ``[width**k, LANES]`` uint32 hash lanes, level 0 the
  root (1 bucket), the last level the segment/leaf hashes,
- :func:`build` — one fused bottom-up rebuild (``rehash``'s role,
  synctree.erl:489-535) as per-level fold-reductions that XLA
  vectorizes across every bucket at once,
- :func:`update` — incremental batched insert: scatter new leaf hashes
  and recompute only the touched root-ward paths (the always-up-to-date
  write-path property, synctree.erl:44-73 — NOT a lazy full rebuild),
- :func:`diff_levels` / :func:`exchange_cost` — the level-by-level
  exchange descent (synctree.erl:372-417): per-level differing-bucket
  masks, giving the O(width · height · diffs) traffic bound that the
  streaming exchange ships over the network,
- :func:`verify` — full integrity check: recompute every parent from
  its children and flag mismatched buckets ({corrupted, Level, Bucket}
  detection, synctree.erl:322-340, as a bitmap).

Hash lanes are a murmur3-style mix — not md5: inside jit the hash only
needs uniformity + avalanche (corruption/diff detection), and a 4-lane
128-bit mix keeps the MXU-adjacent VPU busy instead of forcing a
byte-serial digest.  The host tree keeps cryptographic md5 where the
reference does.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: 4 x uint32 lanes = 128-bit hashes per bucket.
LANES = 4

#: Device-tree hash-format version.  Bump whenever :func:`fold` (or the
#: leaf-hash family) changes output values: checkpoints persist
#: ``tree_leaf``/``tree_node`` verbatim, and a restore across a format
#: change must rebuild every tree or `_verify_path` fails on every slot
#: (see docs/MIGRATION.md).  History: 1 = chained per-child accumulator
#: (rounds 1-3), 2 = linear-pre-mix parallel fold (round 4),
#: 3 = salted non-linear parallel fold (round 5).
HASH_FORMAT = 3

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_F1 = np.uint32(0x85EBCA6B)
_F2 = np.uint32(0xC2B2AE35)


def _rotl(x, r: int):
    return (x << r) | (x >> (32 - r))


def _fmix(h):
    """murmur3 finalizer: full avalanche per lane."""
    h = h ^ (h >> 16)
    h = h * _F1
    h = h ^ (h >> 13)
    h = h * _F2
    return h ^ (h >> 16)


def fold(children: jnp.ndarray) -> jnp.ndarray:
    """Combine ``[..., width, LANES]`` child hashes into ``[..., LANES]``
    parent hashes (the md5-over-concatenated-children role,
    synctree.erl hash/1:255-259).

    Parallel-mix form: each child is avalanched independently with a
    position salt (order sensitivity without order DEPENDENCE), the
    mixes sum mod 2^32, and one cross-lane stir + final avalanche seal
    the parent.  The original chained form (murmur-style sequential
    accumulator with a per-child lane roll) serialized the width axis
    and shuffled lanes 16x per fold — XLA could not vectorize it, and
    the fold dominated the whole K/V round (~3 ms per level at the
    512-ens CPU rung vs ~0.3 ms for this form).  Corruption/diff
    detection needs uniformity + avalanche, not a sequential
    construction — per-child ``_fmix`` provides both.

    The per-child pre-mix is deliberately NON-linear in (child, pos):
    the child is xor'd with an avalanched position salt and then
    multiplied by a per-position odd constant before the ``_fmix``.  A
    linear pre-mix (``child*C1 + pos*C2``) admits a deterministic
    compensated-swap collision — replacing children ``(a, b)`` at
    positions (0, 1) with ``(b+d, a-d)``, ``d = C2·C1⁻¹ mod 2³²``,
    preserves the pre-mix multiset and thus the sum (hash format 2's
    structured blind spot; regression: test_hash_kernel.py
    compensated-swap tests).  With distinct odd multipliers per
    position, neither additive nor xor shifts compensate a swap.
    Threat model matches the reference's: this is a public integrity
    hash for corruption/divergence *detection* (the reference's obj
    "hash" is the plaintext ``<<0,Epoch:64,Seq:64>>``,
    peer.erl:1717-1724) — adversarial forgery resistance is out of
    scope on the device path; the host tree keeps cryptographic md5.
    """
    width = children.shape[-2]
    # trace-time numpy constants: [width, 1] salts + odd multipliers
    pos = np.arange(width, dtype=np.uint32)
    salt = _fmix(pos * _C2 + np.uint32(0x9E3779B9))[:, None]
    mul = (_fmix(pos * _F1 + _C1) | np.uint32(1))[:, None]
    lane = jnp.arange(LANES, dtype=jnp.uint32)
    h = _fmix((children ^ salt) * mul + lane)
    acc = h.sum(axis=-2, dtype=jnp.uint32)
    # two cross-lane stirs: after roll(1)+fmix then roll(2), lane j
    # reads lanes {j, j-1, j-2, j-3} — a change in ANY input lane
    # avalanches every output lane (test_fold_avalanche pins ~50%)
    acc = _fmix(acc ^ jnp.roll(acc, 1, axis=-1))
    acc = acc ^ jnp.roll(acc, 2, axis=-1)
    return _fmix(acc ^ np.uint32(width))


def leaf_hash(epoch: jnp.ndarray, seq: jnp.ndarray) -> jnp.ndarray:
    """Object-version leaf hashes: the reference's obj 'hash' IS the
    (epoch, seq) version (``get_obj_hash`` = ``<<0, Epoch:64, Seq:64>>``,
    peer.erl:1717-1724); mix them into the lane format.  Shapes
    broadcast; returns ``[..., LANES]``."""
    e = jnp.asarray(epoch, jnp.uint32)
    s = jnp.asarray(seq, jnp.uint32)
    base = jnp.stack([e, s, e ^ _rotl(s, 7), s ^ _rotl(e, 11)], axis=-1)
    return _fmix(base * _C1 + jnp.arange(LANES, dtype=jnp.uint32))


def obj_leaf_hash(epoch: jnp.ndarray, seq: jnp.ndarray,
                  val: jnp.ndarray) -> jnp.ndarray:
    """Object leaf hash covering version AND payload handle.

    The reference's obj hash is version-only (``<<0, Epoch:64, Seq:64>>``,
    peer.erl:1717-1724; payload corruption is the backend CRC's job).
    The device store holds the payload handle right next to the version,
    so covering it is free and strictly stronger: a replica whose
    ``obj_val`` lane was damaged out-of-band fails the tree check too.
    Shapes broadcast; returns ``[..., LANES]`` uint32.
    """
    e = jnp.asarray(epoch, jnp.uint32)
    s = jnp.asarray(seq, jnp.uint32)
    v = jnp.asarray(val, jnp.uint32)
    base = jnp.stack([e ^ _rotl(v, 5), s ^ _rotl(v, 9),
                      e ^ _rotl(s, 7), s ^ _rotl(e, 11)], axis=-1)
    return _fmix(base * _C1 + jnp.arange(LANES, dtype=jnp.uint32))


Levels = Tuple[jnp.ndarray, ...]


@functools.partial(jax.jit, static_argnames=("width",))
def build(leaves: jnp.ndarray, width: int = 16) -> Levels:
    """Bottom-up rebuild: ``leaves [S, LANES]`` → levels root-first
    (root ``[1, LANES]`` ... leaves ``[S, LANES]``)."""
    levels = [leaves]
    cur = leaves
    while cur.shape[0] > 1:
        cur = fold(cur.reshape(-1, width, LANES))
        levels.append(cur)
    return tuple(reversed(levels))


@functools.partial(jax.jit, static_argnames=("width",))
def update(levels: Levels, seg_ids: jnp.ndarray,
           new_leaves: jnp.ndarray, width: int = 16) -> Levels:
    """Incremental batched insert (the write-path hash update,
    peer.erl:1731-1738, batched across K keys).

    ``seg_ids [K]`` / ``new_leaves [K, LANES]``: scatter the leaf
    hashes, then per level recompute only the K touched parents by
    gathering their ``width`` children — O(K · width · height) work
    regardless of tree size.  Duplicate parents recompute identically,
    so the parent scatter is idempotent.

    Duplicate ``seg_ids`` in one batch are LAST-WRITE-WINS (the batch
    is a sequence of inserts): JAX leaves duplicate-index scatter order
    unspecified, so every duplicate is redirected to the value of its
    final occurrence before scattering.  A scatter-max over the segment
    axis finds that occurrence in O(K + S) (max is order-independent,
    so it is deterministic under duplicate indices, unlike set).
    """
    out = list(levels)
    depth = len(levels) - 1  # leaf level index
    k = seg_ids.shape[0]
    last_occ_by_seg = jnp.zeros(out[depth].shape[0], jnp.int32) \
        .at[seg_ids].max(jnp.arange(k, dtype=jnp.int32))
    out[depth] = out[depth].at[seg_ids].set(
        new_leaves[last_occ_by_seg[seg_ids]])
    ids = seg_ids
    for level in range(depth - 1, -1, -1):
        parent_ids = ids // width
        child_base = parent_ids * width
        # [K, width] child indices → gather [K, width, LANES]
        gather_ids = child_base[:, None] + jnp.arange(width)[None, :]
        children = out[level + 1][gather_ids]
        out[level] = out[level].at[parent_ids].set(fold(children))
        ids = parent_ids
    return tuple(out)


@jax.jit
def diff_levels(a: Levels, b: Levels) -> Tuple[jnp.ndarray, ...]:
    """Per-level differing-bucket masks between two trees — the
    device-side form of the exchange descent (synctree.erl:386-417).
    Mask k is True where bucket hashes differ at level k; the leaf
    mask marks exactly the segments whose keys need repair."""
    return tuple(jnp.any(x != y, axis=-1) for x, y in zip(a, b))


@functools.partial(jax.jit, static_argnames=("width",))
def exchange_cost(a: Levels, b: Levels, width: int = 16) -> jnp.ndarray:
    """Buckets that a streaming exchange would actually fetch: at each
    level only children of differing parents are visited
    (O(width·height·diffs), the remote-exchange traffic bound
    exercised by synctree_remote.erl).  Returns ``[height+1]`` visit
    counts root-ward → leaf-ward."""
    masks = diff_levels(a, b)
    counts = [jnp.asarray(1, jnp.int32)]  # root always compared
    visit = masks[0]  # [1]
    for level in range(1, len(masks)):
        # children of differing parents are visited...
        visited_children = jnp.repeat(visit, width)
        counts.append(jnp.sum(visited_children.astype(jnp.int32)))
        # ...and among those, the differing ones descend further
        visit = visited_children & masks[level]
    return jnp.stack(counts)


@functools.partial(jax.jit, static_argnames=("width",))
def verify(levels: Levels, width: int = 16) -> Tuple[jnp.ndarray, ...]:
    """Integrity sweep: recompute each parent level from its children
    and flag mismatches — per-level corruption bitmaps (the BFS verify,
    synctree.erl:549-571, as one fused pass)."""
    out = []
    for level in range(len(levels) - 1):
        expect = fold(levels[level + 1].reshape(-1, width, LANES))
        out.append(jnp.any(expect != levels[level], axis=-1))
    return tuple(out)


def segment_of(key_hash: jnp.ndarray, segments: int) -> jnp.ndarray:
    """Key → segment (the md5-mod mapping, synctree.erl:251-253) for
    uint32 key hashes computed host-side."""
    return jnp.asarray(key_hash, jnp.uint32) % np.uint32(segments)
