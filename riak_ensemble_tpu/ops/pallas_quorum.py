"""Pallas TPU kernel for the quorum vote reduction.

The batched predicate (:func:`riak_ensemble_tpu.ops.quorum.
quorum_met_batch`) is a chain of small reductions over the peer axis.
This kernel re-casts it MXU-first: per-view vote counting IS a matmul —

    heard[E, V] = votes[E, M] @ view_membership[M, V]

so the systolic array counts votes for every (ensemble, view) pair in
one pass, with the threshold/nack logic fused behind it on the VPU.
Axes are padded to the 128-lane tile (M and V are small — 3..8 — in
practice, so one [E_blk, 128] @ [128, 128] tile per grid step), the
ensemble axis is the grid.

Semantics match ``quorum_met_batch`` exactly (differentially tested in
``tests/test_pallas_quorum.py``): joint-view AND, in-order first-unmet
nack detection, inactive-view padding, 'all'/'quorum'/'other' modes,
and the implicit self vote (folded in as a +1 on the votes matrix
before the matmul, which is literally what ``heard = n_valid +
self_in_view`` computes).

On non-TPU platforms the kernel runs in interpreter mode (tests); the
jnp reference implementation remains the portable path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from riak_ensemble_tpu.ops.quorum import MET, NACK, REQUIRED_MODES, UNDECIDED

LANE = 128


def _resolve(heard, n_nack, members, thresh, is_active, out_ref):
    """Shared kernel tail: threshold + joint-view AND + in-order
    first-unmet nack — the subtle half of the quorum semantics
    (msg.erl:377-418's recursion), written once for both the shared-
    and per-ensemble-mask front ends."""
    met_v = (heard >= thresh) | ~is_active                  # [BE, Vp]
    nack_v = ((n_nack >= thresh) | (heard + n_nack == members)) \
        & is_active

    all_met = jnp.min(met_v.astype(jnp.int32), axis=1)      # [BE]
    # First unmet view in order (the reference recursion examines
    # views left to right): min index where met_v is False.
    vp = met_v.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, met_v.shape, 1)
    first_unmet = jnp.min(jnp.where(met_v, vp, iota), axis=1)
    unmet_nacked = jnp.max(
        jnp.where((iota == first_unmet[:, None]) & nack_v, 1, 0),
        axis=1)

    res = jnp.where(all_met > 0, MET,
                    jnp.where(unmet_nacked > 0, NACK, UNDECIDED))
    out_ref[:] = jnp.broadcast_to(res[:, None].astype(jnp.int32),
                                  out_ref.shape)


def _kernel(votes_ref, nacks_ref, vmt_ref, members_ref, thresh_ref,
            active_ref, out_ref):
    votes = votes_ref[:]          # [BE, Mp] f32 (valid + self term)
    nacks = nacks_ref[:]          # [BE, Mp] f32
    vmt = vmt_ref[:]              # [Mp, Vp] f32 view membership
    members = members_ref[:]      # [1, Vp]
    thresh = thresh_ref[:]        # [1, Vp]
    active = active_ref[:]        # [1, Vp] (1.0 = real view)

    # MXU: per-view vote counts for the whole ensemble block at once.
    heard = jnp.dot(votes, vmt, preferred_element_type=jnp.float32)
    n_nack = jnp.dot(nacks, vmt, preferred_element_type=jnp.float32)
    _resolve(heard, n_nack, members, thresh, active > 0.0, out_ref)


@functools.partial(jax.jit,
                   static_argnames=("required", "block_e", "interpret"))
def quorum_met_pallas(valid: jax.Array, nack: jax.Array,
                      view_mask: jax.Array, self_idx: jax.Array,
                      required: str = "quorum", block_e: int = 256,
                      interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in for ``quorum_met_batch(..., axis_name=None)`` on a 2-D
    ``[E, M]`` batch with shared or per-ensemble ``view_mask``
    (``[V, M]`` or ``[E, V, M]`` — the latter reduces to the shared
    case only if identical, so per-ensemble masks take the jnp path;
    the engine's steady state is one shared mask).

    Returns int8 ``[E]`` of MET / UNDECIDED / NACK.
    """
    assert required in REQUIRED_MODES, required
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    e, m = valid.shape
    assert view_mask.ndim == 2, "pallas path takes a shared [V, M] mask"
    v = view_mask.shape[0]
    assert m <= LANE and v <= LANE, "peer/view axes exceed one tile"

    vm = view_mask.astype(jnp.float32)                    # [V, M]
    members = vm.sum(-1)                                  # [V]
    active = (members > 0).astype(jnp.float32)
    if required == "all":
        thresh = members
    else:
        thresh = jnp.floor(members / 2) + 1

    votes = valid.astype(jnp.float32)
    if required != "other":
        self_oh = jax.nn.one_hot(self_idx, m, dtype=jnp.float32)
        votes = votes + jnp.broadcast_to(self_oh, votes.shape)

    # Pad to tiles.
    ep = -(-e // block_e) * block_e
    votes = jnp.pad(votes, ((0, ep - e), (0, LANE - m)))
    nacks = jnp.pad(nack.astype(jnp.float32),
                    ((0, ep - e), (0, LANE - m)))
    vmt = jnp.pad(vm.T, ((0, LANE - m), (0, LANE - v)))   # [Mp, Vp]
    # Padded (inactive) views: members=0 → active=0 → always met.
    members_p = jnp.pad(members, (0, LANE - v))[None, :]
    thresh_p = jnp.pad(thresh, (0, LANE - v))[None, :]
    active_p = jnp.pad(active, (0, LANE - v))[None, :]

    grid = (ep // block_e,)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_e, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_e, LANE), lambda i: (i, 0)),
            pl.BlockSpec((LANE, LANE), lambda i: (0, 0)),
            pl.BlockSpec((1, LANE), lambda i: (0, 0)),
            pl.BlockSpec((1, LANE), lambda i: (0, 0)),
            pl.BlockSpec((1, LANE), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_e, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((ep, LANE), jnp.int32),
        interpret=interpret,
    )(votes, nacks, vmt, members_p, thresh_p, active_p)
    return out[:e, 0].astype(jnp.int8)


# ---------------------------------------------------------------------------
# Per-ensemble view masks (the engine's state layout)


_SUB = 8  # f32 sublane tile: pad the view axis to it


def _ekernel(votes_ref, nacks_ref, mask_ref, out_ref):
    """Per-ensemble variant: each ensemble carries its own ``[V, M]``
    membership (reconfigs diverge them), so vote counting is a fused
    broadcast-multiply-reduce over the peer lanes instead of one shared
    MXU matmul, with the threshold derived in-kernel; the resolve tail
    is shared with :func:`_kernel`."""
    votes = votes_ref[:]          # [BE, Mp] f32
    nacks = nacks_ref[:]          # [BE, Mp] f32
    mask = mask_ref[:]            # [BE, Vp, Mp] f32

    heard = jnp.sum(mask * votes[:, None, :], axis=2)       # [BE, Vp]
    n_nack = jnp.sum(mask * nacks[:, None, :], axis=2)
    members = jnp.sum(mask, axis=2)
    thresh = jnp.floor(members * 0.5) + 1.0
    _resolve(heard, n_nack, members, thresh, members > 0.0, out_ref)


@functools.partial(jax.jit,
                   static_argnames=("block_e", "interpret"))
def quorum_met_epallas(valid: jax.Array, nack: jax.Array,
                       view_mask: jax.Array, block_e: int = 512,
                       interpret: Optional[bool] = None) -> jax.Array:
    """Pallas form of the ENGINE's quorum predicate: ``required=
    "quorum"``, no self term (the leader's vote is already folded into
    ``valid``), per-ensemble ``view_mask [E, V, M]``.  Drop-in for
    ``quorum_met_batch(valid, nack, view_mask, self_idx=-1,
    required="quorum", axis_name=None)``; returns int8 ``[E]``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    e, m = valid.shape
    assert view_mask.ndim == 3 and view_mask.shape[0] == e \
        and view_mask.shape[2] == m, view_mask.shape
    v = view_mask.shape[1]
    assert m <= LANE and v <= _SUB, "peer/view axes exceed one tile"

    ep = -(-e // block_e) * block_e
    votes = jnp.pad(valid.astype(jnp.float32),
                    ((0, ep - e), (0, LANE - m)))
    nacks = jnp.pad(nack.astype(jnp.float32),
                    ((0, ep - e), (0, LANE - m)))
    # Padded views have zero members → inactive → always met.
    mask = jnp.pad(view_mask.astype(jnp.float32),
                   ((0, ep - e), (0, _SUB - v), (0, LANE - m)))

    grid = (ep // block_e,)
    out = pl.pallas_call(
        _ekernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_e, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_e, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_e, _SUB, LANE), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_e, LANE), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((ep, LANE), jnp.int32),
        interpret=interpret,
    )(votes, nacks, mask)
    return out[:e, 0].astype(jnp.int8)
