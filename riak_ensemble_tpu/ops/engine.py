"""Batched consensus engine — the vmapped ballot matrix.

The reference runs one Erlang gen_fsm process per peer per ensemble
(``src/riak_ensemble_peer.erl``); independent consensus groups are the
parallelism axis (SURVEY §2.7).  Here that axis is literal: the ballot
state of E ensembles x M peers lives in device arrays, and the protocol
transitions are jitted array kernels:

- :func:`elect_step` — batched leader election: phase-1 prepare
  (``prepare/2``, peer.erl:579-596; NextEpoch = epoch+1, :877-885) and
  phase-2 new_epoch (``prelead/2``, :609-620) fused into one kernel,
  with the quorum predicate of ``riak_ensemble_msg:quorum_met/5``
  (msg.erl:377-418) as a masked majority-reduce.
- :func:`kv_step` — batched steady-state K/V data path: the leased
  local read (``do_get_fsm`` fast path, peer.erl:1460-1462,1493-1516),
  the quorum epoch-check read (``check_epoch`` round, :1493-1516), the
  quorum replicated write (``put_obj``: local put + blocking_send_all
  {put,...} + wait_for_quorum, peer.erl:1669-1698), the quorum
  latest-object read (``get_latest_obj``, :1623-1662), the
  stale-epoch rewrite (``update_key``, :1564-1596), async read repair
  of lagging replicas (``maybe_repair``, :1518-1536) and the
  notfound tombstone-avoidance dance (``all_or_quorum`` +
  notfound_read_delay, msg.erl:282-317, peer.erl:1568-1584) — the
  "thundering herd" of first-touch rewrites after an election is
  batched across all ensembles in one kernel step (SURVEY §7).
- :func:`kv_step_scan` — K sequential ops per ensemble per launch via
  ``lax.scan`` (amortizes dispatch; per-key serialization analog of the
  key-hashed worker pool, peer.erl:1220-1225).

**Integrity is on the data path** (the synctree tree-is-truth design,
``src/synctree.erl:44-73``): every replica carries a Merkle trie over
its slot store — ``tree_leaf`` (per-slot object hashes) plus
``tree_node`` (the upper levels, root last).  Every committed write
updates the leaf AND recomputes its root-ward path in the same kernel
(the always-up-to-date write-path property — ``put_obj`` →
``update_hash``/``send_update_hash``, peer.erl:1669-1715); every read
verifies the accessed slot's path root-ward (``get_path``/
``verify_hash``, synctree.erl:302-340) and checks the object against
its leaf (``valid_obj_hash``, peer.erl:1726), excluding failed
replicas from the read quorum (the hash extra-check of
``get_latest_obj``, :1646-1649) and surfacing them in
``KvResult.tree_corrupt`` for the host.  Read repair then heals
divergent or corrupted replicas in the same round.  Bulk kernels —
:func:`verify_trees`, :func:`rebuild_trees`, :func:`exchange_step` —
give the host the full repair/exchange surface
(``riak_ensemble_exchange``, ``riak_ensemble_peer_tree:do_repair``).

Peer-axis reductions go through :func:`quorum.reduce_peers` / :func:`_pmax`, which
lower to ``jax.lax.psum``/``pmax`` over a mesh axis when ``axis_name``
is given — under ``shard_map`` over a ``('ens', 'peer')`` mesh the vote
count literally rides the ICI all-reduce (see
:mod:`riak_ensemble_tpu.parallel.mesh`).  Host-side concerns — timers,
leases (monotonic clock), failure detection, membership gossip — stay
in the host runtime; the ``up`` and ``lease_ok`` masks are how the host
injects them into the kernels.

All integers are int32 (TPU-native; x64 stays disabled).  Object
payloads are int32 handles — real values live in the host/backend
object store keyed by (slot, epoch, seq); the device arrays carry the
version discipline, which is what consensus is about.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from riak_ensemble_tpu import funref
from riak_ensemble_tpu.ops import hash as hashk
from riak_ensemble_tpu.ops import quorum as quorum_lib
from riak_ensemble_tpu.ops.quorum import (
    quorum_met_batch, reduce_peers, views_to_mask,
)

#: opt-in: run the engine's quorum reduce as the Pallas kernel
#: (ops/pallas_quorum.quorum_met_epallas) instead of the jnp chain.
#: Single-shard launches only — the sharded (axis_name) path keeps the
#: psum collectives.
PALLAS_QUORUM = os.environ.get("RETPU_PALLAS_QUORUM", "") == "1"

# Op kinds for kv_step.
OP_NOOP = 0
OP_GET = 1
OP_PUT = 2
#: compare-and-swap: commit ``val`` iff the slot's current version
#: equals (exp_epoch, exp_seq); expecting (0, 0) on an absent slot is
#: create-if-missing — so OP_CAS carries both do_kupdate
#: (peer.erl:259-270) and do_kput_once (:278-284) semantics.
OP_CAS = 3
#: device read-modify-write — the batched analog of running kmodify's
#: mod-fun INSIDE the leader's FSM (do_kmodify, peer.erl:303-317): the
#: round reads the slot's latest hash-valid value, applies a
#: registered table fun (fun code in the ``exp_epoch`` plane —
#: funref.RMW_*; int32 operand in ``val``) and commits the result
#: under the SAME round's seq discipline.  The read and the write are
#: atomic within the round (no other lane touches the slot), so a
#: device RMW can never CAS-conflict — one round replaces the host's
#: read → fn → CAS retry cycle.  An absent key (or a tombstone) reads
#: as value 0 for the arithmetic funs; a fun result of 0 commits the
#: tombstone (the engine-wide 0-is-notfound payload encoding).
OP_RMW = 4

# The mod-fun table codes (canonical home: funref.py — the registry
# the service resolves kmodify funrefs against; re-exported here so
# kernel callers need only the engine module).
RMW_ADD = funref.RMW_ADD
RMW_SUB = funref.RMW_SUB
RMW_MAX = funref.RMW_MAX
RMW_MIN = funref.RMW_MIN
RMW_SET = funref.RMW_SET
RMW_BAND = funref.RMW_BAND
RMW_BOR = funref.RMW_BOR
RMW_BXOR = funref.RMW_BXOR
RMW_PIA = funref.RMW_PIA

# Merge-section cell codes (the commutative replication lane,
# docs/ARCHITECTURE.md §18): a merged cell names the FOLD to apply
# against the replica lane's own current value, not an op.
MERGE_ADD = funref.MERGE_ADD
MERGE_MAX = funref.MERGE_MAX
MERGE_MIN = funref.MERGE_MIN
MERGE_AND = funref.MERGE_AND
MERGE_OR = funref.MERGE_OR


def merge_vals(cur: jax.Array, mcls: jax.Array,
               operand: jax.Array) -> jax.Array:
    """The compiled half of the replica's merge-scatter: fold each
    merged cell's coalesced ``operand`` into the lane's own current
    value ``cur`` by merge class — the same int32 select ladder the
    kv round's RMW arm runs, restricted to the order-free funs (add
    covers sub via leader-side negation; semilattice max/min/and/or
    fold by themselves).  Elementwise over [n] cell vectors; callers
    gather ``cur`` from their own object plane and scatter the result
    back, so N leader-side ops on one hot slot land as ONE lattice
    merge with no per-entry sequencing."""
    return jnp.select(
        [mcls == MERGE_ADD, mcls == MERGE_MAX, mcls == MERGE_MIN,
         mcls == MERGE_AND],
        [cur + operand, jnp.maximum(cur, operand),
         jnp.minimum(cur, operand), cur & operand],
        default=cur | operand)


#: Merkle trie fan-out (the reference's width-16 trie, synctree.erl:88).
TREE_WIDTH = 16


class EngineState(NamedTuple):
    """Ballot + replicated-store + integrity state for E ensembles x M
    peers.

    Leading axes: E (ensemble) shardable over mesh axis 'ens', M (peer)
    shardable over mesh axis 'peer'.  With sharded M, each shard holds
    its local peer slice; ``leader``/``obj_seq_ctr`` are replicated
    along 'peer'.

    ``tree_leaf``/``tree_node`` are each replica's synctree: leaf k is
    the hash of the replica's object at slot k; ``tree_node`` holds the
    upper levels flattened leafward→root (sizes from
    :func:`tree_sizes`).  Maintained synchronously by the K/V kernels.
    """

    epoch: jax.Array        # [E, M] int32  per-peer current epoch
    fact_seq: jax.Array     # [E, M] int32  per-peer fact seq
    leader: jax.Array       # [E]    int32  global leader peer idx, -1 none
    view_mask: jax.Array    # [E, V, M] bool  joint-consensus views,
    #                         newest first (slot 0 = head), all-zero
    #                         rows = unused capacity in the views list
    view_vsn: jax.Array     # [E] int32  bumps on every views change
    pend_vsn: jax.Array     # [E] int32  vsn of the adopted pending change
    commit_vsn: jax.Array   # [E] int32  pend_vsn as of the last collapse
    obj_seq_ctr: jax.Array  # [E]    int32  leader per-epoch obj counter
    obj_epoch: jax.Array    # [E, M, S] int32  replica store: obj epochs
    obj_seq: jax.Array      # [E, M, S] int32  replica store: obj seqs
    obj_val: jax.Array      # [E, M, S] int32  replica store: payloads
    tree_leaf: jax.Array    # [E, M, S, LANES] uint32  Merkle leaf hashes
    tree_node: jax.Array    # [E, M, U, LANES] uint32  upper levels, flat


class KvResult(NamedTuple):
    committed: jax.Array    # [E] bool  put/rewrite/tombstone reached quorum
    get_ok: jax.Array       # [E] bool  read served (lease or epoch quorum)
    found: jax.Array        # [E] bool  read found an object
    value: jax.Array        # [E] int32 read payload (0 if not found)
    obj_vsn: jax.Array      # [E, 2] int32 (epoch, seq) of the read/put obj
    quorum_ok: jax.Array    # [E] bool  leader up + epoch quorum this round
    tree_corrupt: jax.Array  # [E, M] bool replica failed the integrity gate


# ---------------------------------------------------------------------------
# The canonical sharded-pytree layout (ONE state layout, two placements)
#
# Every path — the single-shard service, the mesh service, checkpoints,
# warmup — shares the axis layout declared right here next to the
# NamedTuples it describes.  ``state_specs()`` gives the mesh placement
# (E over 'ens', M over 'peer'); ``state_specs(ens=None, peer=None)``
# gives the single-shard placement (everything replicated) — the SAME
# pytree of PartitionSpecs, so the two worlds can never drift apart.


def state_specs(ens: Optional[str] = "ens",
                peer: Optional[str] = "peer") -> "EngineState":
    """:class:`EngineState`-shaped pytree of ``PartitionSpec``\\ s.

    ``ens``/``peer`` name the mesh axes the E and M dims shard over
    (None = replicated along that axis).  Field ↔ spec table lives in
    docs/ARCHITECTURE.md §17.
    """
    from jax.sharding import PartitionSpec as P
    return EngineState(
        epoch=P(ens, peer),
        fact_seq=P(ens, peer),
        leader=P(ens),
        view_mask=P(ens, None, peer),
        view_vsn=P(ens),
        pend_vsn=P(ens),
        commit_vsn=P(ens),
        obj_seq_ctr=P(ens),
        obj_epoch=P(ens, peer, None),
        obj_seq=P(ens, peer, None),
        obj_val=P(ens, peer, None),
        tree_leaf=P(ens, peer, None, None),
        tree_node=P(ens, peer, None, None),
    )


def scan_result_specs(ens: Optional[str] = "ens",
                      peer: Optional[str] = "peer") -> "KvResult":
    """:class:`KvResult` specs for :func:`kv_step_scan`'s stacked
    ``[K, E]`` planes (``obj_vsn`` ``[K, E, 2]``, ``tree_corrupt``
    ``[K, E, M]``)."""
    from jax.sharding import PartitionSpec as P
    return KvResult(
        committed=P(None, ens), get_ok=P(None, ens),
        found=P(None, ens), value=P(None, ens),
        obj_vsn=P(None, ens, None), quorum_ok=P(None, ens),
        tree_corrupt=P(None, ens, peer),
    )


def wide_result_specs(ens: Optional[str] = "ens",
                      peer: Optional[str] = "peer") -> "KvResult":
    """:class:`KvResult` specs for :func:`kv_step_scan_wide`'s
    ``[G, E, W]`` planes (``obj_vsn`` ``[G, E, W, 2]``,
    ``tree_corrupt`` ``[G, E, M]``)."""
    from jax.sharding import PartitionSpec as P
    return KvResult(
        committed=P(None, ens, None), get_ok=P(None, ens, None),
        found=P(None, ens, None), value=P(None, ens, None),
        obj_vsn=P(None, ens, None, None), quorum_ok=P(None, ens, None),
        tree_corrupt=P(None, ens, peer),
    )


def state_sharding(mesh) -> "EngineState":
    """:func:`state_specs` bound to a concrete mesh: an
    :class:`EngineState` of ``NamedSharding`` ready for
    ``jax.device_put`` / checkpoint-restore templates."""
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), state_specs(),
        is_leaf=lambda x: isinstance(x, PartitionSpec))


# ---------------------------------------------------------------------------
# Merkle trie layout + path kernels (the synctree on the data path)


@functools.lru_cache(maxsize=None)
def tree_sizes(n_slots: int) -> Tuple[int, ...]:
    """Upper-level sizes leafward→root for an ``n_slots``-leaf trie
    (width 16; short levels padded with zero hashes)."""
    sizes = []
    n = n_slots
    while n > 1:
        n = -(-n // TREE_WIDTH)
        sizes.append(n)
    if not sizes:
        sizes = [1]
    return tuple(sizes)


@functools.lru_cache(maxsize=None)
def _tree_offsets(n_slots: int) -> Tuple[Tuple[int, ...], int]:
    sizes = tree_sizes(n_slots)
    offs, total = [], 0
    for n in sizes:
        offs.append(total)
        total += n
    return tuple(offs), total


def _fold_blocks(x: jax.Array) -> jax.Array:
    """Fold ``[..., n, LANES]`` into ``[..., ceil(n/16), LANES]`` parent
    hashes, zero-padding the last (short) block."""
    n = x.shape[-2]
    nb = -(-n // TREE_WIDTH)
    pad = nb * TREE_WIDTH - n
    if pad:
        zeros = jnp.zeros(x.shape[:-2] + (pad, hashk.LANES), jnp.uint32)
        x = jnp.concatenate([x, zeros], axis=-2)
    return hashk.fold(x.reshape(x.shape[:-2] + (nb, TREE_WIDTH,
                                                hashk.LANES)))


def build_uppers(leaves: jax.Array) -> jax.Array:
    """Bottom-up rebuild of the upper levels from ``[..., S, LANES]``
    leaves → flat ``[..., U, LANES]`` (the ``rehash`` role,
    synctree.erl:489-535, one fused pass)."""
    outs = []
    cur = leaves
    for _ in tree_sizes(leaves.shape[-2]):
        cur = _fold_blocks(cur)
        outs.append(cur)
    return jnp.concatenate(outs, axis=-2) if len(outs) > 1 else outs[0]


def _gather_children(arr: jax.Array, parent_idx: jax.Array,
                     n: int) -> jax.Array:
    """Gather the 16 children of ``parent_idx [E, W]`` from a
    per-replica level array ``arr [E, Ml, n, LANES]`` →
    ``[E, Ml, W, 16, LANES]`` (zero-padded beyond ``n``, matching
    :func:`_fold_blocks`)."""
    e, w = parent_idx.shape
    ml = arr.shape[1]
    idx = (parent_idx[..., None] * TREE_WIDTH
           + jnp.arange(TREE_WIDTH, dtype=jnp.int32))        # [E, W, 16]
    valid = idx < n
    idxc = jnp.clip(idx, 0, n - 1).reshape(e, 1, w * TREE_WIDTH, 1)
    g = jnp.take_along_axis(arr, idxc, axis=2)
    g = g.reshape(e, ml, w, TREE_WIDTH, hashk.LANES)
    return jnp.where(valid[:, None, :, :, None], g, jnp.uint32(0))


def _verify_path(tree_leaf: jax.Array, tree_node: jax.Array,
                 slot: jax.Array) -> jax.Array:
    """Root-ward path verification for W slots per ensemble: recompute
    each stored parent on the paths from its stored children and
    compare (``get_path``/``verify_hash``, synctree.erl:302-340).
    ``slot [E, W]`` → ``[E, Ml, W]`` bool — replica's tree corrupted
    on lane w's path."""
    s = tree_leaf.shape[-2]
    offs, _ = _tree_offsets(s)
    sizes = tree_sizes(s)
    e, ml = tree_leaf.shape[:2]
    bad = jnp.zeros((e, ml, slot.shape[1]), bool)
    child_arr, child_n, idx = tree_leaf, s, slot
    for off, n in zip(offs, sizes):
        pidx = idx // TREE_WIDTH                             # [E, W]
        expect = hashk.fold(_gather_children(child_arr, pidx, child_n))
        level = jax.lax.slice_in_dim(tree_node, off, off + n, axis=2)
        stored = jnp.take_along_axis(
            level, pidx[:, None, :, None], axis=2)           # [E,Ml,W,L]
        bad = bad | (expect != stored).any(-1)
        child_arr, child_n, idx = level, n, pidx
    return bad


def _write_path(tree_leaf: jax.Array, tree_node: jax.Array,
                slot: jax.Array, new_leaf: jax.Array,
                mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Set lane w's leaf to ``new_leaf [E, W, LANES]`` on replicas in
    ``mask [E, Ml, W]`` and recompute their root-ward paths — the
    synchronous write-path hash update (``update_hash`` +
    ``update_path``, peer.erl:1731-1738, synctree.erl:201-209).
    Non-writing replicas' nodes are untouched (a recompute would
    silently alter a corrupted-but-unwritten tree).

    HBM discipline: updates are SCATTERS at the touched (slot, path)
    positions, not full-plane ``where`` rewrites — per round only
    O(E·M·W·height·LANES) elements move, not the whole
    ``[E, M, S(+U), LANES]`` tree (inside the kv scan the carried
    buffers alias, so the scatter lowers to an in-place update).
    Masked-off lanes aim out of bounds and are DROPPED, which keeps
    duplicate in-bounds targets conflict-free: lanes sharing a parent
    all scatter the identical post-update fold of its 16 children.
    """
    e, ml, w = mask.shape
    s = tree_leaf.shape[-2]
    offs, total = _tree_offsets(s)
    sizes = tree_sizes(s)
    eidx = jnp.arange(e, dtype=jnp.int32)[:, None, None]     # [E, 1, 1]
    midx = jnp.arange(ml, dtype=jnp.int32)[None, :, None]    # [1, Ml, 1]
    sl = jnp.where(mask, slot[:, None, :], s)                # [E, Ml, W]
    tree_leaf = tree_leaf.at[eidx, midx, sl].set(
        jnp.broadcast_to(new_leaf[:, None], (e, ml, w, hashk.LANES)),
        mode="drop")
    child_arr, child_n, idx = tree_leaf, s, slot
    node = tree_node
    for off, n in zip(offs, sizes):
        pidx = idx // TREE_WIDTH                             # [E, W]
        parent = hashk.fold(_gather_children(child_arr, pidx, child_n))
        tgt = jnp.where(mask, off + pidx[:, None, :], total)
        node = node.at[eidx, midx, tgt].set(parent, mode="drop")
        child_arr, child_n = (
            jax.lax.slice_in_dim(node, off, off + n, axis=2), n)
        idx = pidx
    return tree_leaf, node


def init_state(n_ensembles: int, n_peers: int, n_slots: int,
               n_views: int = 2,
               views: Optional[Sequence[Sequence[int]]] = None) -> EngineState:
    """Fresh state: no leader, epoch 0, empty stores, trees built over
    the empty stores (every leaf = hash of the absent object).

    ``views`` is a list of views (each a list of global peer indices)
    applied to every ensemble; default one view of all peers.
    """
    e, m, s, v = n_ensembles, n_peers, n_slots, n_views
    if views is None:
        vm = np.zeros((v, m), dtype=bool)
        vm[0, :] = True
    else:
        assert len(views) <= v
        vm = views_to_mask(views, v, m)
    zero = jnp.zeros((), jnp.int32)
    empty_leaf = hashk.obj_leaf_hash(zero, zero, zero)           # [LANES]
    leaves = jnp.broadcast_to(empty_leaf, (s, hashk.LANES))
    uppers = build_uppers(leaves)                                # [U, LANES]
    return EngineState(
        epoch=jnp.zeros((e, m), jnp.int32),
        fact_seq=jnp.zeros((e, m), jnp.int32),
        leader=jnp.full((e,), -1, jnp.int32),
        view_mask=jnp.broadcast_to(jnp.asarray(vm), (e, v, m)),
        view_vsn=jnp.zeros((e,), jnp.int32),
        pend_vsn=jnp.zeros((e,), jnp.int32),
        commit_vsn=jnp.zeros((e,), jnp.int32),
        obj_seq_ctr=jnp.zeros((e,), jnp.int32),
        obj_epoch=jnp.zeros((e, m, s), jnp.int32),
        obj_seq=jnp.zeros((e, m, s), jnp.int32),
        obj_val=jnp.zeros((e, m, s), jnp.int32),
        tree_leaf=jnp.broadcast_to(leaves, (e, m, s, hashk.LANES)),
        tree_node=jnp.broadcast_to(uppers,
                                   (e, m) + uppers.shape),
    )


# ---------------------------------------------------------------------------
# Peer-axis reductions (ICI collectives under shard_map)


def _pmax(x: jax.Array, axis_name: Optional[str]) -> jax.Array:
    m = x.max(-1)
    if axis_name is not None:
        m = jax.lax.pmax(m, axis_name)
    return m


def _global_peer_idx(m_local: int, axis_name: Optional[str]) -> jax.Array:
    """Global peer indices of the local peer slice ([M_local] int32)."""
    idx = jnp.arange(m_local, dtype=jnp.int32)
    if axis_name is not None:
        idx = idx + jax.lax.axis_index(axis_name).astype(jnp.int32) * m_local
    return idx


def _quorum_met(ack: jax.Array, heard: jax.Array, view_mask: jax.Array,
                axis_name: Optional[str]) -> jax.Array:
    """Majority in EVERY active view (msg.erl:377-418), via the shared
    batched predicate :func:`quorum.quorum_met_batch`.

    ack [E, Ml] bool (epoch-matching up members — the caller's own vote
    is already included, so self_idx=-1); heard [E, Ml] bool (up
    members — heard-but-not-acking peers are nacks); view_mask
    [E, V, Ml] bool -> [E] bool.

    With ``RETPU_PALLAS_QUORUM=1`` (and no peer-axis sharding) the
    reduce runs as the Pallas kernel — differentially tested against
    this path.
    """
    if PALLAS_QUORUM and axis_name is None and ack.ndim == 2:
        from riak_ensemble_tpu.ops.pallas_quorum import quorum_met_epallas
        res = quorum_met_epallas(ack, heard & ~ack, view_mask)
        return res == quorum_lib.MET
    if PALLAS_QUORUM and axis_name is None and ack.ndim == 3:
        # Wide-round shape [E, W, Ml] (every K/V round since the lane
        # refactor — W=1 included): flatten the lane axis into the
        # ensemble axis for the kernel, whose contract is [E', Ml].
        from riak_ensemble_tpu.ops.pallas_quorum import quorum_met_epallas
        e, w, ml = ack.shape
        # Broadcast BOTH a 3-dim [E, V, Ml] and an already-widened
        # 4-dim [E, W, V, Ml] view_mask to the full lane shape: a
        # 3-dim mask with W > 1 would otherwise reshape to the wrong
        # element count and crash any caller that didn't pre-widen.
        vm = jnp.broadcast_to(
            view_mask if view_mask.ndim == 4 else view_mask[:, None],
            (e, w) + view_mask.shape[-2:])
        res = quorum_met_epallas(
            ack.reshape(e * w, ml), (heard & ~ack).reshape(e * w, ml),
            vm.reshape(e * w, *vm.shape[-2:]))
        return (res == quorum_lib.MET).reshape(e, w)
    res = quorum_met_batch(
        ack, heard & ~ack, view_mask,
        jnp.full(ack.shape[:-1], -1, jnp.int32),
        required="quorum", axis_name=axis_name)
    return res == quorum_lib.MET


def _latest_among(pe: jax.Array, ps: jax.Array, pv: jax.Array,
                  ok: jax.Array, axis_name: Optional[str]
                  ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Batched ``get_latest_obj`` (peer.erl:1623-1662): the newest
    (epoch, seq) object among the replicas in ``ok`` (already filtered
    for reachability AND hash validity — the extra-check of
    :1646-1649), via a three-stage masked max-reduce over the trailing
    peer axis.  pe/ps/pv/ok are ``[..., Ml]``.

    Returns (epoch [...], seq [...], val [...], found [...]).
    """
    exists = ps > 0                                          # seq>=1 once written
    h = ok & exists
    neg = jnp.int32(-1)
    emax = _pmax(jnp.where(h, pe, neg), axis_name)           # [...]
    smax = _pmax(jnp.where(h & (pe == emax[..., None]), ps, neg), axis_name)
    on_max = h & (pe == emax[..., None]) & (ps == smax[..., None])
    vmax = _pmax(jnp.where(on_max, pv, jnp.iinfo(jnp.int32).min), axis_name)
    found = smax > 0
    return (jnp.maximum(emax, 0), jnp.maximum(smax, 0),
            jnp.where(found, vmax, 0), found)


# ---------------------------------------------------------------------------
# Election kernel


@functools.partial(jax.jit, static_argnames=("axis_name",))
def elect_step(state: EngineState, elect: jax.Array, cand: jax.Array,
               up: jax.Array, axis_name: Optional[str] = None
               ) -> Tuple[EngineState, jax.Array]:
    """Batched two-phase leader election for the ensembles in ``elect``.

    elect [E] bool — run an election in this ensemble this step.
    cand  [E] int32 — global peer index of the candidate (the reference
        picks whichever peer's randomized election timer fires first,
        peer.erl:493-505; the host supplies that choice).
    up    [E, Ml] bool — host availability mask (down/suspended peers
        never ack; the analog of synthesized nacks, msg.erl:134-138).

    Phase 1 (prepare, peer.erl:579-588): NextEpoch = max(epochs)+1;
    member peers with epoch < NextEpoch ack with their fact.  Phase 2
    (prelead new_epoch, :609-620): on quorum, members adopt NextEpoch,
    fact seq resets to 0, per-epoch obj counter resets (local_commit
    resets obj_seq, peer.erl:891-909).  Returns (state', elected [E]).
    """
    e, ml = state.epoch.shape
    gidx = _global_peer_idx(ml, axis_name)
    member = state.view_mask.any(1)                          # [E, Ml]
    heard = up & member
    next_epoch = _pmax(jnp.where(heard, state.epoch, -1), axis_name) + 1
    # Prepare acceptance is epoch < NextEpoch (peer.erl:506-519); with
    # NextEpoch = max(heard epochs)+1 computed from the same heard set,
    # every heard peer accepts by construction — refusal would need a
    # concurrent higher ballot, which sequential kernel launches over
    # consistent state rule out.
    ack = heard
    # The candidate must itself be an up member (it leads the round);
    # a host race handing in a dead/non-member candidate must not
    # produce a leader whose replica never adopted the new epoch.
    cand_heard = reduce_peers(
        ((gidx[None, :] == cand[:, None]) & heard).astype(jnp.int32),
        axis_name) > 0
    won = (_quorum_met(ack, heard, state.view_mask, axis_name)
           & elect & (cand >= 0) & cand_heard)

    adopt = won[:, None] & heard                             # [E, Ml]
    epoch = jnp.where(adopt, next_epoch[:, None], state.epoch)
    fact_seq = jnp.where(adopt, 0, state.fact_seq)
    leader = jnp.where(won, cand, state.leader)
    obj_seq_ctr = jnp.where(won, 0, state.obj_seq_ctr)
    return state._replace(epoch=epoch, fact_seq=fact_seq, leader=leader,
                          obj_seq_ctr=obj_seq_ctr), won


# ---------------------------------------------------------------------------
# K/V kernel


class _KvCtx(NamedTuple):
    """Loop-invariant K/V round context.

    Everything here depends only on ballot state (epoch/leader/views)
    and the ``up`` mask — none of which a K/V round mutates — so a
    scan of K rounds computes it (and its ~5 peer-axis collectives)
    exactly once (kv_step_scan).
    """

    heard: jax.Array        # [E, Ml] up members
    leader_up: jax.Array    # [E] the leader itself is up (it serves ops)
    lead_epoch: jax.Array   # [E] proposal epoch (leader's epoch)
    epoch_ok: jax.Array     # [E] epoch-check round reached quorum
    n_member: jax.Array     # [E] global member count (for all_or_quorum)


def _kv_context(state: EngineState, up: jax.Array,
                axis_name: Optional[str]) -> _KvCtx:
    e, ml = state.epoch.shape
    gidx = _global_peer_idx(ml, axis_name)                   # [Ml]
    is_leader = gidx[None, :] == state.leader[:, None]       # [E, Ml]
    has_leader = state.leader >= 0                           # [E]
    member = state.view_mask.any(1)
    heard = up & member
    # Leader's epoch, replicated to every shard (the proposal epoch).
    lead_epoch = reduce_peers(jnp.where(is_leader, state.epoch, 0),
                              axis_name)
    # Every op is served BY the leader (leased reads are the leader's
    # local read, puts include the leader's local put — peer.erl:1669-
    # 1698); a down leader serves nothing, whatever the quorum says.
    # This is also what makes commits durable under leased reads: a
    # committed write always includes the leader's own replica.
    leader_up = reduce_peers((is_leader & heard).astype(jnp.int32),
                             axis_name) > 0
    # Epoch-check acks: shared by put replication and non-leased reads.
    ack = heard & (state.epoch == lead_epoch[:, None])
    epoch_ok = (_quorum_met(ack, heard, state.view_mask, axis_name)
                & has_leader & leader_up)
    n_member = reduce_peers(member.astype(jnp.int32), axis_name)
    return _KvCtx(heard=heard, leader_up=leader_up & has_leader,
                  lead_epoch=lead_epoch, epoch_ok=epoch_ok,
                  n_member=n_member)


def _kv_round(state: EngineState, ctx: _KvCtx, kind: jax.Array,
              slot: jax.Array, val: jax.Array, lease_ok: jax.Array,
              axis_name: Optional[str],
              exp_epoch: Optional[jax.Array] = None,
              exp_seq: Optional[jax.Array] = None
              ) -> Tuple[EngineState, KvResult]:
    """One WIDE K/V protocol round given a precomputed context.

    kind/slot/val/lease_ok/exp_epoch/exp_seq are ``[E, W]``: W
    conflict-free op lanes per ensemble — the host schedules ops so
    that the valid slots within a row are DISTINCT (duplicate-slot
    ops go to later rounds), which is SURVEY §2.7's "conflict-free
    slots advance in one batched kernel step".  Lanes see the
    pre-round state (atomic for CAS because no other lane touches the
    same slot) and commit seqs in lane order, so on a corruption-free
    tree the result is bit-identical to applying the lanes as W
    sequential 1-op rounds.  ``kv_step`` is exactly that with W = 1.

    Corruption caveat: lanes verify against the PRE-round tree, so
    when two lanes' paths share an out-of-band-corrupted internal
    node, a sequential application could let the first lane's read
    repair heal the shared path before the second lane's gate runs;
    the wide round instead excludes the replica on BOTH lanes and
    flags it in ``tree_corrupt`` — strictly more conservative (an
    unhealed path is never trusted), healed by the same repair/scrub
    machinery one round later.
    """
    e, ml = state.epoch.shape
    s = state.obj_epoch.shape[-1]
    w = kind.shape[1]
    heard = ctx.heard                                        # [E, Ml]
    heard3 = heard[:, :, None]                               # [E, Ml, 1]
    leader_up = ctx.leader_up[:, None]                       # [E, 1]
    lead_epoch = ctx.lead_epoch[:, None]
    epoch_ok = ctx.epoch_ok[:, None]
    if exp_epoch is None:
        exp_epoch = jnp.zeros_like(kind)
    if exp_seq is None:
        exp_seq = jnp.zeros_like(kind)

    is_put = kind == OP_PUT
    is_get = kind == OP_GET
    is_cas = kind == OP_CAS
    is_rmw = kind == OP_RMW
    active = is_put | is_get | is_cas | is_rmw
    slot_valid = (slot >= 0) & (slot < s)                    # [E, W]
    slot_c = jnp.clip(slot, 0, s - 1)

    # Per-replica object at each lane's slot: ONE gather per plane
    # (invalid slots read the absent object).
    def at_slot(plane):
        return jnp.take_along_axis(
            plane, slot_c[:, None, :], axis=2)               # [E, Ml, W]
    sv = slot_valid[:, None, :]
    pe = jnp.where(sv, at_slot(state.obj_epoch), 0)
    ps = jnp.where(sv, at_slot(state.obj_seq), 0)
    pv = jnp.where(sv, at_slot(state.obj_val), 0)

    # Integrity gate (tree-is-truth, synctree.erl:44-73): the object
    # must match its leaf, and the slot's root-ward path must verify.
    leaf = jnp.take_along_axis(
        state.tree_leaf, slot_c[:, None, :, None], axis=2)   # [E,Ml,W,L]
    leaf_ok = (leaf == hashk.obj_leaf_hash(pe, ps, pv)).all(-1)
    path_bad = _verify_path(state.tree_leaf, state.tree_node, slot_c)
    replica_ok = heard3 & leaf_ok & ~path_bad                # [E, Ml, W]
    tree_corrupt = ((path_bad | ~leaf_ok) & heard3
                    & (active & slot_valid)[:, None, :]).any(-1)

    # Peer-axis reductions run on the transposed [E, W, Ml] layout
    # (reduce_peers/quorum_met_batch contract: peers trailing).
    ok_t = replica_ok.transpose(0, 2, 1)                     # [E, W, Ml]

    # Read: newest object among valid replicas (hash extra-check).
    # ``obj_found`` is "some object exists" — possibly a tombstone
    # (val == 0, the device notfound-object); ``found`` is the
    # client-visible hit.  Tombstones carry full version discipline
    # (they win/lose by (epoch, seq) and replicate like any object)
    # but read back as notfound, exactly like the reference's notfound
    # obj (peer.erl:1568-1584).
    rd_epoch, rd_seq, rd_val, obj_found = _latest_among(
        pe.transpose(0, 2, 1), ps.transpose(0, 2, 1),
        pv.transpose(0, 2, 1), ok_t, axis_name)              # each [E, W]
    found = obj_found & (rd_val != 0)
    n_ok = reduce_peers(ok_t.astype(jnp.int32), axis_name)   # [E, W]
    all_ok = n_ok == ctx.n_member[:, None]

    get_gate = is_get & leader_up & (lease_ok | epoch_ok)
    stale = obj_found & (rd_epoch != lead_epoch)
    # Stale-epoch rewrite (update_key): needs the quorum either way.
    # A stale tombstone is rewritten at the current epoch too.
    rewrite = get_gate & stale & epoch_ok
    # Notfound with NO object anywhere: when every member replica
    # answered (valid) notfound, serve it without writing
    # (all_or_quorum full-response fast path, peer.erl:1568-1584);
    # otherwise a notfound tombstone must commit at the current epoch
    # so a stale straggler write cannot later win (update_key with
    # notfound, :1564-1596).  The tombstone additionally needs a
    # QUORUM of hash-valid notfound answers (non-valid heard replicas
    # count as nacks) — the reference's update_key read round fails on
    # the hash extra-check rather than erasing data the integrity gate
    # excluded; without this, corrupting the leaves of every holder
    # would let a single GET tombstone over a committed object.
    # Out-of-range slots never held data: plain notfound.
    nf = get_gate & ~obj_found
    nf_quorum = _quorum_met(
        ok_t, jnp.broadcast_to(heard[:, None, :], ok_t.shape),
        jnp.broadcast_to(state.view_mask[:, None],
                         (e, w) + state.view_mask.shape[1:]),
        axis_name)                                           # [E, W]
    nf_write = nf & slot_valid & ~all_ok & epoch_ok & nf_quorum
    get_ok = ((get_gate & obj_found & (~stale | rewrite))
              | (nf & (all_ok | ~slot_valid | nf_write)))

    # Commit path (shared by put, CAS, rewrite and notfound
    # tombstone).  CAS compares the expected version against the
    # slot's CURRENT stored version atomically within this round (the
    # do_kupdate (epoch, seq) equality, peer.erl:259-270 — atomic
    # because no other lane in the round touches this slot);
    # expecting (0, 0) on an absent slot is create-if-missing
    # (do_kput_once, :278-284).  A tombstone counts as an existing
    # version for the compare (ksafe_delete reads the tombstone's vsn)
    # but val 0 still reads back notfound.
    put_commit = is_put & epoch_ok & slot_valid
    exp_absent = (exp_epoch == 0) & (exp_seq == 0)
    # (0, 0) matches a tombstone as well as true absence — put-once
    # succeeds over a notfound-valued object (do_kput_once,
    # peer.erl:278-284) — and TRUE absence additionally needs a quorum
    # of hash-valid notfound answers (same nf_quorum guard as the GET
    # tombstone path): without it, corrupting every holder's leaves
    # would let a (0,0) CAS overwrite committed data the integrity
    # gate excluded.
    vsn_match = ((obj_found & (rd_epoch == exp_epoch)
                  & (rd_seq == exp_seq))
                 | (exp_absent & obj_found & (rd_val == 0))
                 | (exp_absent & ~obj_found & nf_quorum))
    cas_commit = is_cas & epoch_ok & slot_valid & vsn_match

    # Device RMW (OP_RMW): fn(cur, operand) committed in THIS round —
    # the fused kmodify.  ``cur`` is the round's own latest-object
    # read (tombstones and verified absence read as 0, the engine's
    # notfound value), so concurrent RMWs of one slot serialize
    # through round order with no conflict window.  Absence must be
    # VERIFIED (the same nf_quorum guard as the (0,0)-CAS create):
    # treating not-found-because-every-holder-is-corrupt as 0 would
    # overwrite committed data the integrity gate excluded.
    fn = exp_epoch                                           # [E, W]
    cur = jnp.where(obj_found, rd_val, 0)
    new_rmw = jnp.select(
        [fn == RMW_ADD, fn == RMW_SUB, fn == RMW_MAX, fn == RMW_MIN,
         fn == RMW_SET, fn == RMW_BAND, fn == RMW_BOR,
         fn == RMW_BXOR],
        [cur + val, cur - val, jnp.maximum(cur, val),
         jnp.minimum(cur, val), val, cur & val, cur | val, cur ^ val],
        default=val)                  # RMW_PIA commits the operand
    rmw_absent = ((obj_found & (rd_val == 0))
                  | (~obj_found & nf_quorum))
    rmw_known = obj_found | nf_quorum
    rmw_commit = (is_rmw & epoch_ok & slot_valid
                  & jnp.where(fn == RMW_PIA, rmw_absent, rmw_known))

    commit = (put_commit | cas_commit | rewrite | nf_write
              | rmw_commit)                                  # [E, W]
    wval = jnp.where(is_put | is_cas, val,
                     jnp.where(is_rmw, new_rmw,
                               jnp.where(rewrite, rd_val, 0)))

    # Commit seqs advance in lane order (obj_sequence, peer.erl:1776-
    # 1791): lane w's seq is ctr + (commits among lanes <= w), exactly
    # the values W sequential rounds would assign.
    ranks = jnp.cumsum(commit.astype(jnp.int32), axis=1)     # [E, W]
    new_seq = state.obj_seq_ctr[:, None] + ranks

    # Read repair (maybe_repair, peer.erl:1518-1536): a successful
    # current-epoch read heals reachable replicas that lag the winning
    # version or failed the integrity gate (re-writing the slot also
    # recomputes their hash path, healing tree corruption).
    plain_read = get_ok & obj_found & ~rewrite               # [E, W]
    divergent = heard3 & ((pe != rd_epoch[:, None, :])
                          | (ps != rd_seq[:, None, :])
                          | ~leaf_ok | path_bad)
    repair = plain_read[:, None, :] & divergent              # [E, Ml, W]

    w_epoch = jnp.where(commit, lead_epoch, rd_epoch)        # [E, W]
    w_seq = jnp.where(commit, new_seq, rd_seq)
    w_val = jnp.where(commit, wval, rd_val)
    do_write = (commit[:, None, :] & heard3) | repair        # [E, Ml, W]

    # Scatter, not full-plane where: per round only the touched slot
    # columns move through HBM (in place inside the kv scan's carry).
    # Non-writing lanes aim out of bounds and are dropped, so clipped
    # invalid slots can never collide with a real lane's write.
    eidx = jnp.arange(e, dtype=jnp.int32)[:, None, None]
    midx = jnp.arange(ml, dtype=jnp.int32)[None, :, None]
    sl2 = jnp.where(do_write, slot_c[:, None, :], s)         # [E, Ml, W]

    def set_slot(plane, new):
        return plane.at[eidx, midx, sl2].set(
            jnp.broadcast_to(new[:, None, :], (e, ml, w)), mode="drop")

    obj_epoch = set_slot(state.obj_epoch, w_epoch)
    obj_seq = set_slot(state.obj_seq, w_seq)
    obj_val = set_slot(state.obj_val, w_val)
    obj_seq_ctr = state.obj_seq_ctr + ranks[:, -1]

    # Synchronous tree maintenance: leaves + root-ward paths, same
    # round.  Lanes sharing a path parent recompute it identically
    # from the post-scatter children, so duplicate targets agree.
    new_leaf = hashk.obj_leaf_hash(w_epoch, w_seq, w_val)    # [E, W, L]
    tree_leaf, tree_node = _write_path(
        state.tree_leaf, state.tree_node, slot_c, new_leaf, do_write)

    # Version reported for any served object INCLUDING tombstones —
    # the reference's kget hands back the notfound obj with its vsn,
    # which is what ksafe_delete's CAS compares against
    # (client.erl:kget → peer.erl:1568-1584 tombstone objects).
    out_epoch = jnp.where(commit, lead_epoch,
                          jnp.where(get_ok & obj_found, rd_epoch, 0))
    out_seq = jnp.where(commit, new_seq,
                        jnp.where(get_ok & obj_found, rd_seq, 0))
    res = KvResult(
        committed=commit,
        get_ok=get_ok,
        found=found & get_ok,
        # reads report the winning value; a committed RMW reports the
        # value it COMPUTED (the host mirror/WAL needs it without a
        # follow-up read)
        value=jnp.where(rmw_commit, new_rmw,
                        jnp.where(get_ok & found, rd_val, 0)),
        obj_vsn=jnp.stack([out_epoch, out_seq], -1),
        quorum_ok=jnp.broadcast_to(ctx.epoch_ok[:, None], commit.shape),
        tree_corrupt=tree_corrupt,
    )
    new_state = state._replace(obj_epoch=obj_epoch, obj_seq=obj_seq,
                               obj_val=obj_val, obj_seq_ctr=obj_seq_ctr,
                               tree_leaf=tree_leaf, tree_node=tree_node)
    return new_state, res


@functools.partial(jax.jit, static_argnames=("axis_name",))
def kv_step(state: EngineState, kind: jax.Array, slot: jax.Array,
            val: jax.Array, lease_ok: jax.Array, up: jax.Array,
            axis_name: Optional[str] = None,
            exp_epoch: Optional[jax.Array] = None,
            exp_seq: Optional[jax.Array] = None
            ) -> Tuple[EngineState, KvResult]:
    """One K/V protocol round per ensemble, batched over E.

    kind [E] int32 (OP_NOOP/OP_GET/OP_PUT/OP_CAS/OP_RMW); slot [E]
    int32; val [E] int32 (payload for puts/CAS; the int32 operand for
    RMW); exp_epoch/exp_seq [E] int32 (the CAS expected version — for
    OP_RMW rows exp_epoch instead carries the mod-fun table code
    (RMW_*); ignored for other kinds, default 0); lease_ok [E] bool
    (host lease check, check_lease peer.erl:1493-1516); up [E, Ml]
    bool.

    Semantics per ensemble:
    - PUT: one quorum round.  Proposal (lead_epoch, ctr+1); member
      replicas whose epoch matches ack (valid_request, peer.erl
      :869-871 — stale-epoch followers nack); on majority in every
      view, all heard member replicas apply the write (put_obj,
      :1669-1698), their tree leaf + hash path update in the same
      round (update_hash/send_update_hash, :1700-1715), and the
      counter advances (obj_sequence, :1776-1791).
    - GET: if lease_ok, leased local read; else the quorum epoch-check
      round gates it (:1460-1468).  Replicas failing the integrity
      gate (leaf/path hash mismatch) are excluded; the value returned
      is the newest version among the remaining replicas
      (get_latest_obj + hash extra-check, :1623-1662); a stale-epoch
      winner is rewritten at the current epoch through the quorum
      machinery (update_key, :1564-1596); a current-epoch read heals
      lagging/corrupt replicas (maybe_repair, :1518-1536); a notfound
      with unreachable members commits a tombstone (all_or_quorum,
      :1568-1584) — all batched across ensembles.
    - RMW: the fused kmodify (do_kmodify, peer.erl:303-317).  One
      quorum round reads the slot's latest hash-valid value, applies
      the registered table fun (exp_epoch = fun code, val = operand)
      and commits the result at (lead_epoch, next seq) — read and
      write atomic within the round, so device RMWs never
      CAS-conflict.  Arithmetic funs read absence/tombstones as 0;
      RMW_PIA (put-if-absent) commits only over verified absence or
      a tombstone; a fun result of 0 writes the tombstone.  The
      committed value is reported in ``KvResult.value``.
    """
    ctx = _kv_context(state, up, axis_name)
    state, res = _kv_round(
        state, ctx, kind[:, None], slot[:, None], val[:, None],
        lease_ok[:, None], axis_name,
        None if exp_epoch is None else exp_epoch[:, None],
        None if exp_seq is None else exp_seq[:, None])
    return _adopt_epochs(state, ctx), _squeeze_lane(res)


def _squeeze_lane(res: KvResult) -> KvResult:
    """Collapse a W=1 wide result back to the scalar [E] shapes
    (tree_corrupt is already lane-reduced to [E, Ml])."""
    return res._replace(
        committed=res.committed[:, 0], get_ok=res.get_ok[:, 0],
        found=res.found[:, 0], value=res.value[:, 0],
        obj_vsn=res.obj_vsn[:, 0], quorum_ok=res.quorum_ok[:, 0])


def _adopt_epochs(state: EngineState, ctx: _KvCtx) -> EngineState:
    """Follower epoch catch-up — the ``following({commit, Fact})``
    adoption (peer.erl:794-836): a heard member whose ballot epoch
    trails a live leader's adopts it at the END of the launch (it was
    a nack for THIS launch's quorums, exactly like a stale follower
    nacking until the commit round reaches it, and acks from the
    next).  Without this a peer returning from downtime would stay a
    permanent nack until the next election."""
    heal = (ctx.heard & ctx.leader_up[:, None]
            & (state.epoch < ctx.lead_epoch[:, None]))
    return state._replace(
        epoch=jnp.where(heal, ctx.lead_epoch[:, None], state.epoch))


@functools.partial(jax.jit, static_argnames=("axis_name",))
def kv_step_scan(state: EngineState, kind: jax.Array, slot: jax.Array,
                 val: jax.Array, lease_ok: jax.Array, up: jax.Array,
                 axis_name: Optional[str] = None,
                 exp_epoch: Optional[jax.Array] = None,
                 exp_seq: Optional[jax.Array] = None
                 ) -> Tuple[EngineState, KvResult]:
    """K sequential K/V rounds per ensemble in one launch.

    kind/slot/val (and exp_epoch/exp_seq when any op is OP_CAS):
    [K, E]; lease_ok: [K, E]; up: [E, Ml] (held fixed across the K
    rounds).  Sequentiality per ensemble preserves the per-key
    serialization the reference gets from key-hashed workers (async/3,
    peer.erl:1220-1225) — and makes each CAS's read-compare-write
    atomic.  Results are stacked [K, E].

    Ballot state (epoch/leader/views) is invariant across the rounds,
    so the round context — including its peer-axis collectives — is
    computed once outside the scan.
    """
    ctx = _kv_context(state, up, axis_name)
    if exp_epoch is None:
        exp_epoch = jnp.zeros_like(kind)
    if exp_seq is None:
        exp_seq = jnp.zeros_like(kind)

    def body(st, op):
        k, sl, v, lz, xe, xs = op
        st2, r = _kv_round(st, ctx, k[:, None], sl[:, None], v[:, None],
                           lz[:, None], axis_name, xe[:, None],
                           xs[:, None])
        return st2, _squeeze_lane(r)

    state, res = jax.lax.scan(
        body, state, (kind, slot, val, lease_ok, exp_epoch, exp_seq))
    return _adopt_epochs(state, ctx), res


@functools.partial(jax.jit, static_argnames=("axis_name",))
def kv_step_scan_wide(state: EngineState, kind: jax.Array,
                      slot: jax.Array, val: jax.Array,
                      lease_ok: jax.Array, up: jax.Array,
                      axis_name: Optional[str] = None,
                      exp_epoch: Optional[jax.Array] = None,
                      exp_seq: Optional[jax.Array] = None
                      ) -> Tuple[EngineState, KvResult]:
    """G sequential WIDE rounds of W conflict-free lanes per launch.

    kind/slot/val/lease_ok (and exp_epoch/exp_seq): ``[G, E, W]``.
    The host schedules each flush's ops so a round's valid slots are
    distinct within every ensemble (duplicate-slot ops land in later
    rounds — occurrence-index grouping), which keeps per-key
    serialization while amortizing the round's fixed cost (context
    reuse, quorum reduces, gather/scatter launch overhead) over W ops
    instead of 1.  Results are stacked ``[G, E, W]``.

    Equivalent by construction to ``kv_step_scan`` over the same ops
    flattened to ``[G*W, E]`` in (group, lane) order — differentially
    tested in tests/test_engine_wide.py.

    PRECONDITION (caller contract, not checked inside jit): within
    every ``[g, e]`` row, the slots of valid ops (kind != OP_NOOP)
    must be DISTINCT.  Duplicate scatter targets with differing values
    in one round produce nondeterministic state (JAX leaves duplicate-
    index scatter order unspecified).  The host scheduler
    (ops/schedule.py) guarantees this by occurrence-index grouping;
    direct kernel callers (mesh.ShardedEngine included) must do the
    same, or run :func:`validate_wide_plane` on the concrete planes
    (enabled in the service via ``RETPU_VALIDATE_WIDE=1``).
    """
    ctx = _kv_context(state, up, axis_name)
    if exp_epoch is None:
        exp_epoch = jnp.zeros_like(kind)
    if exp_seq is None:
        exp_seq = jnp.zeros_like(kind)

    def body(st, op):
        k, sl, v, lz, xe, xs = op
        st2, r = _kv_round(st, ctx, k, sl, v, lz, axis_name, xe, xs)
        return st2, r

    state, res = jax.lax.scan(
        body, state, (kind, slot, val, lease_ok, exp_epoch, exp_seq))
    return _adopt_epochs(state, ctx), res


def validate_wide_plane(kind, slot) -> None:
    """Check the wide-round conflict-free precondition on CONCRETE
    ``[G, E, W]`` planes: within one ``[g, e]`` row, ops with
    kind != OP_NOOP and slot >= 0 must target distinct slots.  This is
    deliberately STRICTER than the kernel's write gate (slot_valid
    also requires slot < n_slots, engine.py ``_kv_round``): the
    validator has no n_slots, and it mirrors the scheduler's chaining
    rule exactly — schedule.py chains any slot >= 0 and gives slot < 0
    ops forced-unique keys — so a plane the scheduler would emit never
    trips it.  Raises ValueError with the first offending
    (group, ensemble, slot).  Host-side only (not traceable); the
    service runs it under ``RETPU_VALIDATE_WIDE=1``.
    """
    kind = np.asarray(kind)
    slot = np.asarray(slot)
    g, e, w = kind.shape
    valid = (kind != OP_NOOP) & (slot >= 0)
    # sentinel-out non-writing lanes (legal slots are >= 0, so distinct
    # negative sentinels can never collide with a real slot), then look
    # for duplicate slots per row
    s = np.where(valid, slot, -1 - np.arange(w))
    s_sorted = np.sort(s, axis=-1)
    dup = (s_sorted[..., 1:] == s_sorted[..., :-1]).any(-1)
    if dup.any():
        gi, ei = np.argwhere(dup)[0]
        row = slot[gi, ei][valid[gi, ei]]
        vals, counts = np.unique(row, return_counts=True)
        raise ValueError(
            f"wide plane violates the conflict-free precondition: "
            f"group {gi}, ensemble {ei} has duplicate valid slot "
            f"{int(vals[counts > 1][0])} (kv_step_scan_wide docstring)")


# ---------------------------------------------------------------------------
# Result-plane compaction (active-column gather)


def gather_result_columns(res: KvResult,
                          active_idx: jax.Array) -> KvResult:
    """Active-column compaction of a packed-layout result: gather the
    per-round ensemble axis of the CLIENT result planes down to the
    active column set — ``[K, E] → [K, A]`` (``[G·W, E] → [G·W, A]``
    for a wide launch already reshaped to round-major rows).

    ``active_idx [A]`` holds the global column indices the flush
    actually scheduled ops into, A pow2-bucketed by the host for
    compile reuse (padding entries repeat index 0 and are ignored by
    the host unpack).  Only the planes a client op consumes move:
    ``quorum_ok`` (lease renewal reads EVERY column's epoch-check
    outcome) and ``tree_corrupt`` (corrupt-plane flags of *inactive*
    columns must still reach the scrub path; the ``E·M`` mask is
    bit-packed and cheap) deliberately stay full width.  Compaction is
    a pure re-indexing: the gathered planes are bit-identical to the
    full-width pack's active columns, and inactive columns carry only
    the all-false/zero NOOP results the host reconstructs at unpack.
    """
    def take(x):
        return jnp.take(x, active_idx, axis=1)
    return res._replace(
        committed=take(res.committed), get_ok=take(res.get_ok),
        found=take(res.found), value=take(res.value),
        obj_vsn=take(res.obj_vsn))


# ---------------------------------------------------------------------------
# Integrity maintenance kernels (exchange / repair, §2.3)


@functools.partial(jax.jit, static_argnames=("axis_name",))
def verify_trees(state: EngineState, axis_name: Optional[str] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Full integrity sweep per replica (the BFS ``verify``,
    synctree.erl:549-571, one fused pass): recompute every upper level
    from the stored leaves and every leaf from the stored object.

    Returns ``(node_bad [E, Ml], leaf_bad [E, Ml])`` — upper-tree
    corruption vs object/leaf divergence.
    """
    del axis_name  # per-replica local; no collectives needed
    expect_upper = build_uppers(state.tree_leaf)
    node_bad = (expect_upper != state.tree_node).any(-1).any(-1)
    expect_leaf = hashk.obj_leaf_hash(state.obj_epoch, state.obj_seq,
                                      state.obj_val)
    leaf_bad = (expect_leaf != state.tree_leaf).any(-1).any(-1)
    return node_bad, leaf_bad


@jax.jit
def rebuild_trees(state: EngineState, mask: jax.Array) -> EngineState:
    """Rebuild replicas' trees from their object stores (the repair =
    segment delete + full rehash, riak_ensemble_peer_tree.erl:264-277).
    ``mask [E, Ml]`` selects replicas; others untouched."""
    leaves = hashk.obj_leaf_hash(state.obj_epoch, state.obj_seq,
                                 state.obj_val)
    tree_leaf = jnp.where(mask[:, :, None, None], leaves, state.tree_leaf)
    tree_node = jnp.where(mask[:, :, None, None], build_uppers(tree_leaf),
                          state.tree_node)
    return state._replace(tree_leaf=tree_leaf, tree_node=tree_node)


def _pmax2(x: jax.Array, axis_name: Optional[str]) -> jax.Array:
    """Max over the peer axis (axis 1) of [E, Ml, S] → [E, S]."""
    m = x.max(1)
    if axis_name is not None:
        m = jax.lax.pmax(m, axis_name)
    return m


@functools.partial(jax.jit, static_argnames=("axis_name",))
def exchange_step(state: EngineState, run: jax.Array, up: jax.Array,
                  axis_name: Optional[str] = None
                  ) -> Tuple[EngineState, jax.Array, jax.Array]:
    """Whole-store anti-entropy in one kernel — the tree exchange
    (riak_ensemble_exchange.erl:67-98) redesigned for the batch axis.

    The reference walks differing tree buckets level by level and
    adopts remote-newer objects per key.  On device the whole slot
    axis is one masked max-reduce: for every slot, the newest
    hash-valid object among reachable replicas wins
    (``valid_obj_hash(B, A)`` gate, exchange.erl:91-96), every
    reachable replica adopts it, and adopting replicas rebuild their
    trees.  Gated per ensemble on ``run`` AND a reachable majority
    (trust_majority, exchange.erl:109-126).

    Returns ``(state', diverged [E, Ml], synced [E])`` — which replicas
    held divergent/invalid data, and which ensembles completed.
    """
    member = state.view_mask.any(1)
    heard = up & member
    met = _quorum_met(heard, heard, state.view_mask, axis_name)
    adopt = run & met                                        # [E]

    # Source validity is the OBJECT hash (the leaf — valid_obj_hash
    # compares obj hashes, exchange.erl:91-96).  A replica whose upper
    # tree is corrupt still has trustworthy objects (its leaves vouch
    # for them); its tree gets rebuilt below, matching the reference's
    # repair-by-rehash-from-data (peer_tree.erl:264-277) rather than
    # data discard.
    expect_leaf = hashk.obj_leaf_hash(state.obj_epoch, state.obj_seq,
                                      state.obj_val)
    leaf_ok = (expect_leaf == state.tree_leaf).all(-1)       # [E, Ml, S]
    node_ok = (build_uppers(state.tree_leaf)
               == state.tree_node).all(-1).all(-1)           # [E, Ml]
    h = heard[:, :, None] & leaf_ok & (state.obj_seq > 0)

    neg = jnp.int32(-1)
    emax = _pmax2(jnp.where(h, state.obj_epoch, neg), axis_name)  # [E, S]
    smax = _pmax2(jnp.where(h & (state.obj_epoch == emax[:, None, :]),
                            state.obj_seq, neg), axis_name)
    on_max = (h & (state.obj_epoch == emax[:, None, :])
              & (state.obj_seq == smax[:, None, :]))
    vmax = _pmax2(jnp.where(on_max, state.obj_val,
                            jnp.iinfo(jnp.int32).min), axis_name)
    found = smax > 0                                         # [E, S]
    w_epoch = jnp.where(found, emax, 0)
    w_seq = jnp.where(found, smax, 0)
    w_val = jnp.where(found, vmax, 0)

    # Adopt ONLY where a hash-valid winner exists: a slot with no
    # valid holder (e.g. every copy's leaf is damaged) is left for
    # host-driven repair — exchange must never erase data it cannot
    # replace.
    tgt = (adopt[:, None, None] & heard[:, :, None]
           & found[:, None, :])                              # [E, Ml, S]
    mismatch = ((state.obj_epoch != w_epoch[:, None, :])
                | (state.obj_seq != w_seq[:, None, :])
                | (state.obj_val != w_val[:, None, :]))
    diverged = ((mismatch | ~leaf_ok)
                & adopt[:, None, None] & heard[:, :, None]).any(-1) | \
        (~node_ok & adopt[:, None] & heard)
    obj_epoch = jnp.where(tgt, w_epoch[:, None, :], state.obj_epoch)
    obj_seq = jnp.where(tgt, w_seq[:, None, :], state.obj_seq)
    obj_val = jnp.where(tgt, w_val[:, None, :], state.obj_val)

    # Refresh leaves for adopted slots only: a damaged leaf at a
    # no-winner slot must stay mismatched (rehashing it would bless
    # the corrupt object as valid).  Upper levels rebuild from the
    # resulting leaves, healing tree corruption (repair-by-rehash).
    leaves = hashk.obj_leaf_hash(obj_epoch, obj_seq, obj_val)
    rebuild = adopt[:, None] & heard                         # [E, Ml]
    fix_leaf = tgt | (leaf_ok & rebuild[:, :, None])
    tree_leaf = jnp.where(fix_leaf[..., None], leaves, state.tree_leaf)
    tree_node = jnp.where(rebuild[:, :, None, None],
                          build_uppers(tree_leaf), state.tree_node)
    new_state = state._replace(obj_epoch=obj_epoch, obj_seq=obj_seq,
                               obj_val=obj_val, tree_leaf=tree_leaf,
                               tree_node=tree_node)
    return new_state, diverged, adopt


@jax.jit
def reset_rows(state: EngineState, mask: jax.Array,
               new_view: jax.Array) -> EngineState:
    """Recycle ensemble rows for fresh ensembles — the device half of
    dynamic ensemble creation (``riak_ensemble_manager:create_ensemble``,
    manager.erl:157-166, re-designed for fixed device arrays: a
    logical ensemble maps to a physical row; destroy frees the row,
    create resets and re-views it).

    mask [E] bool — rows being (re)created; new_view [E, M] bool —
    their initial single view.  Reset clears the object store, trees
    (rebuilt over the empty store), leader, seq counters and the
    views list; the ballot ``epoch`` is deliberately KEPT — epochs
    stay monotone per physical row, so any straggler op addressed to
    the destroyed tenant can never outrank the new tenant's ballots
    (the same reuse discipline the service applies to key slots).
    """
    zero = jnp.int32(0)
    head_view = jnp.concatenate(
        [new_view[:, None, :],
         jnp.zeros_like(state.view_mask[:, 1:, :])], axis=1)
    m3 = mask[:, None, None]
    st = state._replace(
        fact_seq=jnp.where(mask[:, None], zero, state.fact_seq),
        leader=jnp.where(mask, jnp.int32(-1), state.leader),
        view_mask=jnp.where(m3, head_view, state.view_mask),
        view_vsn=jnp.where(mask, state.view_vsn + 1, state.view_vsn),
        pend_vsn=jnp.where(mask, zero, state.pend_vsn),
        commit_vsn=jnp.where(mask, zero, state.commit_vsn),
        obj_seq_ctr=jnp.where(mask, zero, state.obj_seq_ctr),
        obj_epoch=jnp.where(m3, zero, state.obj_epoch),
        obj_seq=jnp.where(m3, zero, state.obj_seq),
        obj_val=jnp.where(m3, zero, state.obj_val),
    )
    return rebuild_trees(st, jnp.broadcast_to(
        mask[:, None], state.epoch.shape))


# ---------------------------------------------------------------------------
# Membership reconfiguration kernel (joint consensus, ladder #5)


def _reconfig_gate(state: EngineState, up: jax.Array,
                   axis_name: Optional[str]
                   ) -> Tuple[jax.Array, jax.Array]:
    """(heard [E, Ml], commit quorum in every CURRENT view [E]) — the
    try_commit gate (peer.erl:776-788) on epoch-matching acks."""
    member_now = state.view_mask.any(1)                      # [E, Ml]
    heard = up & member_now
    has_leader = state.leader >= 0
    gidx = _global_peer_idx(state.epoch.shape[1], axis_name)
    is_leader = gidx[None, :] == state.leader[:, None]
    lead_epoch = reduce_peers(jnp.where(is_leader, state.epoch, 0),
                              axis_name)
    ack = heard & (state.epoch == lead_epoch[:, None])
    commit_ok = (_quorum_met(ack, heard, state.view_mask, axis_name)
                 & has_leader)
    return heard, commit_ok


@functools.partial(jax.jit, static_argnames=("axis_name",))
def reconfig_propose(state: EngineState, propose: jax.Array,
                     new_view: jax.Array, vsn: jax.Array, up: jax.Array,
                     axis_name: Optional[str] = None
                     ) -> Tuple[EngineState, jax.Array]:
    """Batched ``update_members`` + ``maybe_change_views``
    (peer.erl:655-672, 1115-1135): CONS the proposed view onto the
    views list and adopt the manager's pending version.

    propose [E] bool; new_view [E, Ml] bool; vsn [E] int32 — the
    pending change's version from the manager/root (gossip side);
    up [E, Ml] bool.  Per ensemble, the install happens iff:

    - a commit quorum holds in EVERY current view (the try_commit
      gate — a joint ensemble may take FURTHER changes before
      transitioning, exactly like consing onto the views list);
    - ``vsn > pend_vsn`` (stale/duplicate pending changes are ignored,
      the maybe_change_views vsn guard, :1117-1121);
    - the proposed view is non-empty and the views list has a free
      slot (the device bounds list depth at V; a full list nacks and
      the host retries after a transition — backpressure the
      reference gets implicitly from transition frequency).

    Effect: views = [new | views], ``view_vsn`` bumps, ``pend_vsn``
    adopts ``vsn``, fact seq bumps on the replicas that heard it.
    Returns (state', installed [E]).
    """
    heard, commit_ok = _reconfig_gate(state, up, axis_name)
    new_nonempty = reduce_peers(new_view.astype(jnp.int32),
                                axis_name) > 0               # [E]
    # Free capacity: the last (oldest) slot must be unused.
    tail_used = reduce_peers(
        state.view_mask[:, -1, :].astype(jnp.int32), axis_name) > 0
    vsn_ok = vsn > state.pend_vsn
    install = propose & commit_ok & new_nonempty & ~tail_used & vsn_ok

    shifted = jnp.concatenate(
        [new_view[:, None, :], state.view_mask[:, :-1, :]], axis=1)
    view_mask = jnp.where(install[:, None, None], shifted,
                          state.view_mask)
    bump = install[:, None] & heard
    return state._replace(
        view_mask=view_mask,
        view_vsn=jnp.where(install, state.view_vsn + 1, state.view_vsn),
        pend_vsn=jnp.where(install, vsn, state.pend_vsn),
        fact_seq=jnp.where(bump, state.fact_seq + 1, state.fact_seq),
    ), install


@functools.partial(jax.jit, static_argnames=("axis_name",))
def reconfig_transition(state: EngineState, run: jax.Array,
                        up: jax.Array,
                        axis_name: Optional[str] = None
                        ) -> Tuple[EngineState, jax.Array]:
    """Batched ``maybe_transition``/``transition`` (peer.erl:751-774,
    1199-1214): once the joint configuration has a commit quorum in
    EVERY view, collapse the list to the head view alone and record
    ``commit_vsn = pend_vsn`` (the dance's final step,
    doc/Readme.md:106-153).  Returns (state', collapsed [E])."""
    heard, commit_ok = _reconfig_gate(state, up, axis_name)
    is_joint = reduce_peers(
        state.view_mask[:, 1:, :].any(1).astype(jnp.int32), axis_name) > 0
    collapse = run & is_joint & commit_ok

    head_only = jnp.concatenate(
        [state.view_mask[:, :1, :],
         jnp.zeros_like(state.view_mask[:, 1:, :])], axis=1)
    view_mask = jnp.where(collapse[:, None, None], head_only,
                          state.view_mask)
    bump = collapse[:, None] & heard
    return state._replace(
        view_mask=view_mask,
        view_vsn=jnp.where(collapse, state.view_vsn + 1, state.view_vsn),
        commit_vsn=jnp.where(collapse, state.pend_vsn, state.commit_vsn),
        fact_seq=jnp.where(bump, state.fact_seq + 1, state.fact_seq),
    ), collapse


@functools.partial(jax.jit, static_argnames=("axis_name",))
def reconfig_step(state: EngineState, propose: jax.Array,
                  new_view: jax.Array, up: jax.Array,
                  axis_name: Optional[str] = None
                  ) -> Tuple[EngineState, jax.Array, jax.Array]:
    """One reconfig phase per ensemble, batched over E — the fused
    convenience over :func:`reconfig_propose` /
    :func:`reconfig_transition`: ensembles with ``propose`` cons the
    new view (vsn auto-derived as pend_vsn+1, i.e. the manager's next
    pending version), the rest transition if joint and able.

    propose  [E] bool; new_view [E, Ml] bool; up [E, Ml] bool.
    Returns (state', installed [E], collapsed [E]).  Leaders whose
    commit gate fails keep their current views (the host steps them
    down / retries, as the reference does on failed try_commit).
    """
    state, installed = reconfig_propose(
        state, propose, new_view, state.pend_vsn + 1, up,
        axis_name=axis_name)
    state, collapsed = reconfig_transition(state, ~propose, up,
                                           axis_name=axis_name)
    return state, installed, collapsed


# ---------------------------------------------------------------------------
# Fused full step (election + K ops) — the "training step" analog


def _full_step_body(state: EngineState, elect: jax.Array, cand: jax.Array,
                    kind: jax.Array, slot: jax.Array, val: jax.Array,
                    lease_ok: jax.Array, up: jax.Array,
                    axis_name: Optional[str] = None,
                    exp_epoch: Optional[jax.Array] = None,
                    exp_seq: Optional[jax.Array] = None
                    ) -> Tuple[EngineState, jax.Array, KvResult]:
    """Election round (where needed) followed by K K/V rounds, fused.

    This is the flagship jitted step: the host decides *which*
    ensembles need elections (failure detection is host-side), the
    device does all the protocol math.
    """
    state, won = elect_step(state, elect, cand, up, axis_name=axis_name)
    state, res = kv_step_scan(state, kind, slot, val, lease_ok, up,
                              axis_name=axis_name, exp_epoch=exp_epoch,
                              exp_seq=exp_seq)
    return state, won, res


full_step = jax.jit(_full_step_body, static_argnames=("axis_name",))

#: ``full_step`` with the state argument DONATED (``donate_argnums``):
#: back-to-back launches alias the output state buffers onto the
#: input's instead of allocating + copying the E×M(×S) planes each
#: launch.  The caller's input ``EngineState`` is CONSUMED — any
#: retained reference (rollback snapshots included) is invalid after
#: the call on backends that honor donation; backends that don't
#: (older CPU runtimes) fall back to a copy with a one-time warning.
#: Used by the service's pipelined launch path (RETPU_DONATE).
full_step_donate = jax.jit(_full_step_body,
                           static_argnames=("axis_name",),
                           donate_argnums=(0,))


def _full_step_wide_body(state: EngineState, elect: jax.Array,
                         cand: jax.Array, kind: jax.Array,
                         slot: jax.Array, val: jax.Array,
                         lease_ok: jax.Array, up: jax.Array,
                         axis_name: Optional[str] = None,
                         exp_epoch: Optional[jax.Array] = None,
                         exp_seq: Optional[jax.Array] = None
                         ) -> Tuple[EngineState, jax.Array, KvResult]:
    """``full_step`` with ``[G, E, W]`` conflict-free op planes (see
    :func:`kv_step_scan_wide`) — the wide-scheduled flagship step.

    Carries :func:`kv_step_scan_wide`'s precondition: valid slots must
    be distinct within every ``[g, e]`` row (see its docstring;
    :func:`validate_wide_plane` checks concrete planes)."""
    state, won = elect_step(state, elect, cand, up, axis_name=axis_name)
    state, res = kv_step_scan_wide(
        state, kind, slot, val, lease_ok, up, axis_name=axis_name,
        exp_epoch=exp_epoch, exp_seq=exp_seq)
    return state, won, res


full_step_wide = jax.jit(_full_step_wide_body,
                         static_argnames=("axis_name",))

#: donated-state variant of :func:`full_step_wide` (see
#: :data:`full_step_donate` for the aliasing contract).
full_step_wide_donate = jax.jit(_full_step_wide_body,
                                static_argnames=("axis_name",),
                                donate_argnums=(0,))


# ---------------------------------------------------------------------------
# Active-column SLICED full step (the shrunk [K, A] launch grid)


def _slice_columns(state: EngineState, active_idx: jax.Array,
                   up: jax.Array) -> Tuple[EngineState, jax.Array]:
    """Gather the A active ensembles' rows out of every state plane
    (and the up mask): ``[E, ...] → [A, ...]``.  Padding entries
    (index E, out of range) clip to row E-1 — harmless, their op
    lanes are NOOP/elect-False so they never write, and the scatter
    drops them."""
    e = state.epoch.shape[0]
    idx_c = jnp.clip(active_idx, 0, e - 1)
    sub = jax.tree.map(lambda x: jnp.take(x, idx_c, axis=0), state)
    return sub, jnp.take(up, idx_c, axis=0)


def _scatter_columns(state: EngineState, sub: EngineState,
                     active_idx: jax.Array) -> EngineState:
    """Scatter the stepped sub-state back into the full planes.
    Padding entries aim out of bounds (index E) and are DROPPED;
    real indices are distinct, so the scatter is conflict-free.
    With the full state donated, this lowers to an in-place update
    of the A touched rows instead of a full-plane copy."""
    return jax.tree.map(
        lambda full, s: full.at[active_idx].set(s, mode="drop"),
        state, sub)


def _full_step_sliced_body(state: EngineState, active_idx: jax.Array,
                           elect: jax.Array, cand: jax.Array,
                           kind: jax.Array, slot: jax.Array,
                           val: jax.Array, lease_ok: jax.Array,
                           up: jax.Array,
                           axis_name: Optional[str] = None,
                           exp_epoch: Optional[jax.Array] = None,
                           exp_seq: Optional[jax.Array] = None
                           ) -> Tuple[EngineState, jax.Array, KvResult]:
    """:data:`full_step` on the ACTIVE COLUMNS ONLY — the shrunk
    launch grid.  One hot ensemble forces the [K, E] grid to its
    queue depth even when most columns idle; ensembles are fully
    independent in every K/V and election kernel (the batch-axis
    premise), so the step runs bit-identically on the gathered
    ``[A, ...]`` sub-state with ``[K, A]`` op planes — compute, HBM
    traffic and the result surface all scale with the live working
    set instead of E.

    ``active_idx [A]`` (A pow2-bucketed; padding = E, dropped at
    scatter) selects the columns; ``elect``/``cand`` are ``[A]``,
    the op planes ``[K, A]``, ``up`` stays full ``[E, M]`` (gathered
    on device — it is cached there between failure-detector
    changes).  The caller must include every electing column in the
    active set, and must treat the results as A-width (won/quorum/
    corrupt planes come back ``[A(...)]``; the host scatters them).

    Semantic note (vs the full-grid step): follower epoch catch-up
    (``_adopt_epochs``) and lease-renewing quorum confirmations run
    only for active columns — an idle ensemble's lease lapses and
    its stragglers heal on its NEXT active launch, which is exactly
    when the heal is first observable.  Single-shard launches only
    (a mesh-sharded E axis cannot gather across shards without
    resharding; the mesh service keeps the full grid and compacts
    the packed result instead).
    """
    sub, up_a = _slice_columns(state, active_idx, up)
    sub, won, res = _full_step_body(
        sub, elect, cand, kind, slot, val, lease_ok, up_a,
        axis_name=axis_name, exp_epoch=exp_epoch, exp_seq=exp_seq)
    return _scatter_columns(state, sub, active_idx), won, res


def _full_step_wide_sliced_body(state: EngineState,
                                active_idx: jax.Array,
                                elect: jax.Array, cand: jax.Array,
                                kind: jax.Array, slot: jax.Array,
                                val: jax.Array, lease_ok: jax.Array,
                                up: jax.Array,
                                axis_name: Optional[str] = None,
                                exp_epoch: Optional[jax.Array] = None,
                                exp_seq: Optional[jax.Array] = None
                                ) -> Tuple[EngineState, jax.Array,
                                           KvResult]:
    """:func:`_full_step_sliced_body` with ``[G, A, W]`` conflict-free
    wide op planes (see :func:`kv_step_scan_wide`; same active-set
    contract as the scalar sliced step)."""
    sub, up_a = _slice_columns(state, active_idx, up)
    sub, won, res = _full_step_wide_body(
        sub, elect, cand, kind, slot, val, lease_ok, up_a,
        axis_name=axis_name, exp_epoch=exp_epoch, exp_seq=exp_seq)
    return _scatter_columns(state, sub, active_idx), won, res


full_step_sliced = jax.jit(_full_step_sliced_body,
                           static_argnames=("axis_name",))

#: donated-state variant (see :data:`full_step_donate`): the scatter
#: back into the donated full planes is an in-place A-row update.
full_step_sliced_donate = jax.jit(_full_step_sliced_body,
                                  static_argnames=("axis_name",),
                                  donate_argnums=(0,))

full_step_wide_sliced = jax.jit(_full_step_wide_sliced_body,
                                static_argnames=("axis_name",))

full_step_wide_sliced_donate = jax.jit(_full_step_wide_sliced_body,
                                       static_argnames=("axis_name",),
                                       donate_argnums=(0,))


# ---------------------------------------------------------------------------
# Compile/cost introspection (observability plane, ARCHITECTURE §11)


def lowered_cost_analysis(fn, *args, **kwargs):
    """XLA cost analysis of ``fn`` lowered at these argument shapes
    — WITHOUT a backend compile (``Lowered.cost_analysis`` runs the
    HLO cost model on the lowering, a few ms even for the full step).
    Returns ``{"flops": f, "bytes_accessed": b}`` with whatever keys
    the backend reports, or None when the lowering/analysis is
    unsupported (mesh placements, older jaxlibs) — telemetry capture
    must degrade, never raise into a warmup.

    Used by ``BatchedEnsembleService.warmup`` to record per-(K, A)-
    bucket cost gauges next to the compile-event log, so a bucket's
    device cost and its compile cost live on the same surface.
    """
    try:
        lower = getattr(fn, "lower", None)
        if lower is None:
            return None
        ca = lower(*args, **kwargs).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None
        out = {}
        if "flops" in ca:
            out["flops"] = float(ca["flops"])
        if "bytes accessed" in ca:
            out["bytes_accessed"] = float(ca["bytes accessed"])
        return out or None
    except Exception:
        return None
