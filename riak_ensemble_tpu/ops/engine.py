"""Batched consensus engine — the vmapped ballot matrix.

The reference runs one Erlang gen_fsm process per peer per ensemble
(``src/riak_ensemble_peer.erl``); independent consensus groups are the
parallelism axis (SURVEY §2.7).  Here that axis is literal: the ballot
state of E ensembles x M peers lives in device arrays, and the protocol
transitions are jitted array kernels:

- :func:`elect_step` — batched leader election: phase-1 prepare
  (``prepare/2``, peer.erl:579-596; NextEpoch = epoch+1, :877-885) and
  phase-2 new_epoch (``prelead/2``, :609-620) fused into one kernel,
  with the quorum predicate of ``riak_ensemble_msg:quorum_met/5``
  (msg.erl:377-418) as a masked majority-reduce.
- :func:`kv_step` — batched steady-state K/V data path: the leased
  local read (``do_get_fsm`` fast path, peer.erl:1460-1462,1493-1516),
  the quorum epoch-check read (``check_epoch`` round, :1493-1516), the
  quorum replicated write (``put_obj``: local put + blocking_send_all
  {put,...} + wait_for_quorum, peer.erl:1669-1698), the quorum
  latest-object read (``get_latest_obj``, :1623-1662) and the
  stale-epoch rewrite (``update_key``, :1564-1596) — the
  "thundering herd" of first-touch rewrites after an election is
  batched across all ensembles in one kernel step (SURVEY §7).
- :func:`kv_step_scan` — K sequential ops per ensemble per launch via
  ``lax.scan`` (amortizes dispatch; per-key serialization analog of the
  key-hashed worker pool, peer.erl:1220-1225).

Peer-axis reductions go through :func:`quorum.reduce_peers` / :func:`_pmax`, which
lower to ``jax.lax.psum``/``pmax`` over a mesh axis when ``axis_name``
is given — under ``shard_map`` over a ``('ens', 'peer')`` mesh the vote
count literally rides the ICI all-reduce (see
:mod:`riak_ensemble_tpu.parallel.mesh`).  Host-side concerns — timers,
leases (monotonic clock), failure detection, membership gossip — stay
in the host runtime; the ``up`` and ``lease_ok`` masks are how the host
injects them into the kernels.

All integers are int32 (TPU-native; x64 stays disabled).  Object
payloads are int32 handles — real values live in the host/backend
object store keyed by (slot, epoch, seq); the device arrays carry the
version discipline, which is what consensus is about.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from riak_ensemble_tpu.ops import quorum as quorum_lib
from riak_ensemble_tpu.ops.quorum import (
    quorum_met_batch, reduce_peers, views_to_mask,
)

# Op kinds for kv_step.
OP_NOOP = 0
OP_GET = 1
OP_PUT = 2


class EngineState(NamedTuple):
    """Ballot + replicated-store state for E ensembles x M peers.

    Leading axes: E (ensemble) shardable over mesh axis 'ens', M (peer)
    shardable over mesh axis 'peer'.  With sharded M, each shard holds
    its local peer slice; ``leader``/``obj_seq_ctr`` are replicated
    along 'peer'.
    """

    epoch: jax.Array        # [E, M] int32  per-peer current epoch
    fact_seq: jax.Array     # [E, M] int32  per-peer fact seq
    leader: jax.Array       # [E]    int32  global leader peer idx, -1 none
    view_mask: jax.Array    # [E, V, M] bool  joint-consensus views
    obj_seq_ctr: jax.Array  # [E]    int32  leader per-epoch obj counter
    obj_epoch: jax.Array    # [E, M, S] int32  replica store: obj epochs
    obj_seq: jax.Array      # [E, M, S] int32  replica store: obj seqs
    obj_val: jax.Array      # [E, M, S] int32  replica store: payloads


class KvResult(NamedTuple):
    committed: jax.Array   # [E] bool  put (or rewrite) reached quorum
    get_ok: jax.Array      # [E] bool  read served (lease or epoch quorum)
    found: jax.Array       # [E] bool  read found an object
    value: jax.Array       # [E] int32 read payload (0 if not found)
    obj_vsn: jax.Array     # [E, 2] int32 (epoch, seq) of the read/put obj


def init_state(n_ensembles: int, n_peers: int, n_slots: int,
               n_views: int = 2,
               views: Optional[Sequence[Sequence[int]]] = None) -> EngineState:
    """Fresh state: no leader, epoch 0, empty stores.

    ``views`` is a list of views (each a list of global peer indices)
    applied to every ensemble; default one view of all peers.
    """
    e, m, s, v = n_ensembles, n_peers, n_slots, n_views
    if views is None:
        vm = np.zeros((v, m), dtype=bool)
        vm[0, :] = True
    else:
        assert len(views) <= v
        vm = views_to_mask(views, v, m)
    return EngineState(
        epoch=jnp.zeros((e, m), jnp.int32),
        fact_seq=jnp.zeros((e, m), jnp.int32),
        leader=jnp.full((e,), -1, jnp.int32),
        view_mask=jnp.broadcast_to(jnp.asarray(vm), (e, v, m)),
        obj_seq_ctr=jnp.zeros((e,), jnp.int32),
        obj_epoch=jnp.zeros((e, m, s), jnp.int32),
        obj_seq=jnp.zeros((e, m, s), jnp.int32),
        obj_val=jnp.zeros((e, m, s), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Peer-axis reductions (ICI collectives under shard_map)


def _pmax(x: jax.Array, axis_name: Optional[str]) -> jax.Array:
    m = x.max(-1)
    if axis_name is not None:
        m = jax.lax.pmax(m, axis_name)
    return m


def _global_peer_idx(m_local: int, axis_name: Optional[str]) -> jax.Array:
    """Global peer indices of the local peer slice ([M_local] int32)."""
    idx = jnp.arange(m_local, dtype=jnp.int32)
    if axis_name is not None:
        idx = idx + jax.lax.axis_index(axis_name).astype(jnp.int32) * m_local
    return idx


def _quorum_met(ack: jax.Array, heard: jax.Array, view_mask: jax.Array,
                axis_name: Optional[str]) -> jax.Array:
    """Majority in EVERY active view (msg.erl:377-418), via the shared
    batched predicate :func:`quorum.quorum_met_batch`.

    ack [E, Ml] bool (epoch-matching up members — the caller's own vote
    is already included, so self_idx=-1); heard [E, Ml] bool (up
    members — heard-but-not-acking peers are nacks); view_mask
    [E, V, Ml] bool -> [E] bool.
    """
    res = quorum_met_batch(
        ack, heard & ~ack, view_mask,
        jnp.full(ack.shape[:-1], -1, jnp.int32),
        required="quorum", axis_name=axis_name)
    return res == quorum_lib.MET


def _latest_at_slot(state: EngineState, slot_oh: jax.Array,
                    heard: jax.Array, axis_name: Optional[str]
                    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Batched ``get_latest_obj`` (peer.erl:1623-1662): the newest
    (epoch, seq) object at a slot among the heard member replicas, via
    a three-stage masked max-reduce over the peer axis.

    Returns (epoch [E], seq [E], val [E], found [E]).
    """
    sel = slot_oh[:, None, :]                                # [E, 1, S]
    pe = (state.obj_epoch * sel).sum(-1)                     # [E, Ml]
    ps = (state.obj_seq * sel).sum(-1)
    pv = (state.obj_val * sel).sum(-1)
    exists = ps > 0                                          # seq>=1 once written
    h = heard & exists
    neg = jnp.int32(-1)
    emax = _pmax(jnp.where(h, pe, neg), axis_name)           # [E]
    smax = _pmax(jnp.where(h & (pe == emax[:, None]), ps, neg), axis_name)
    on_max = h & (pe == emax[:, None]) & (ps == smax[:, None])
    vmax = _pmax(jnp.where(on_max, pv, jnp.iinfo(jnp.int32).min), axis_name)
    found = smax > 0
    return (jnp.maximum(emax, 0), jnp.maximum(smax, 0),
            jnp.where(found, vmax, 0), found)


# ---------------------------------------------------------------------------
# Election kernel


@functools.partial(jax.jit, static_argnames=("axis_name",))
def elect_step(state: EngineState, elect: jax.Array, cand: jax.Array,
               up: jax.Array, axis_name: Optional[str] = None
               ) -> Tuple[EngineState, jax.Array]:
    """Batched two-phase leader election for the ensembles in ``elect``.

    elect [E] bool — run an election in this ensemble this step.
    cand  [E] int32 — global peer index of the candidate (the reference
        picks whichever peer's randomized election timer fires first,
        peer.erl:493-505; the host supplies that choice).
    up    [E, Ml] bool — host availability mask (down/suspended peers
        never ack; the analog of synthesized nacks, msg.erl:134-138).

    Phase 1 (prepare, peer.erl:579-588): NextEpoch = max(epochs)+1;
    member peers with epoch < NextEpoch ack with their fact.  Phase 2
    (prelead new_epoch, :609-620): on quorum, members adopt NextEpoch,
    fact seq resets to 0, per-epoch obj counter resets (local_commit
    resets obj_seq, peer.erl:891-909).  Returns (state', elected [E]).
    """
    e, ml = state.epoch.shape
    gidx = _global_peer_idx(ml, axis_name)
    member = state.view_mask.any(1)                          # [E, Ml]
    heard = up & member
    next_epoch = _pmax(jnp.where(heard, state.epoch, -1), axis_name) + 1
    # Prepare acceptance is epoch < NextEpoch (peer.erl:506-519); with
    # NextEpoch = max(heard epochs)+1 computed from the same heard set,
    # every heard peer accepts by construction — refusal would need a
    # concurrent higher ballot, which sequential kernel launches over
    # consistent state rule out.
    ack = heard
    # The candidate must itself be an up member (it leads the round);
    # a host race handing in a dead/non-member candidate must not
    # produce a leader whose replica never adopted the new epoch.
    cand_heard = reduce_peers(
        ((gidx[None, :] == cand[:, None]) & heard).astype(jnp.int32),
        axis_name) > 0
    won = (_quorum_met(ack, heard, state.view_mask, axis_name)
           & elect & (cand >= 0) & cand_heard)

    adopt = won[:, None] & heard                             # [E, Ml]
    epoch = jnp.where(adopt, next_epoch[:, None], state.epoch)
    fact_seq = jnp.where(adopt, 0, state.fact_seq)
    leader = jnp.where(won, cand, state.leader)
    obj_seq_ctr = jnp.where(won, 0, state.obj_seq_ctr)
    return state._replace(epoch=epoch, fact_seq=fact_seq, leader=leader,
                          obj_seq_ctr=obj_seq_ctr), won


# ---------------------------------------------------------------------------
# K/V kernel


class _KvCtx(NamedTuple):
    """Loop-invariant K/V round context.

    Everything here depends only on ballot state (epoch/leader/views)
    and the ``up`` mask — none of which a K/V round mutates — so a
    scan of K rounds computes it (and its ~4 peer-axis collectives)
    exactly once (kv_step_scan).
    """

    heard: jax.Array        # [E, Ml] up members
    leader_up: jax.Array    # [E] the leader itself is up (it serves ops)
    lead_epoch: jax.Array   # [E] proposal epoch (leader's epoch)
    epoch_ok: jax.Array     # [E] epoch-check round reached quorum


def _kv_context(state: EngineState, up: jax.Array,
                axis_name: Optional[str]) -> _KvCtx:
    e, ml = state.epoch.shape
    gidx = _global_peer_idx(ml, axis_name)                   # [Ml]
    is_leader = gidx[None, :] == state.leader[:, None]       # [E, Ml]
    has_leader = state.leader >= 0                           # [E]
    member = state.view_mask.any(1)
    heard = up & member
    # Leader's epoch, replicated to every shard (the proposal epoch).
    lead_epoch = reduce_peers(jnp.where(is_leader, state.epoch, 0),
                              axis_name)
    # Every op is served BY the leader (leased reads are the leader's
    # local read, puts include the leader's local put — peer.erl:1669-
    # 1698); a down leader serves nothing, whatever the quorum says.
    # This is also what makes commits durable under leased reads: a
    # committed write always includes the leader's own replica.
    leader_up = reduce_peers((is_leader & heard).astype(jnp.int32),
                             axis_name) > 0
    # Epoch-check acks: shared by put replication and non-leased reads.
    ack = heard & (state.epoch == lead_epoch[:, None])
    epoch_ok = (_quorum_met(ack, heard, state.view_mask, axis_name)
                & has_leader & leader_up)
    return _KvCtx(heard=heard, leader_up=leader_up & has_leader,
                  lead_epoch=lead_epoch, epoch_ok=epoch_ok)


def _kv_round(state: EngineState, ctx: _KvCtx, kind: jax.Array,
              slot: jax.Array, val: jax.Array, lease_ok: jax.Array,
              axis_name: Optional[str]) -> Tuple[EngineState, KvResult]:
    """One K/V protocol round given a precomputed context."""
    s = state.obj_epoch.shape[-1]
    heard, leader_up = ctx.heard, ctx.leader_up
    lead_epoch, epoch_ok = ctx.lead_epoch, ctx.epoch_ok

    is_put = kind == OP_PUT
    is_get = kind == OP_GET
    slot_valid = (slot >= 0) & (slot < s)

    # Read: newest object among heard replicas.
    slot_oh = (jnp.arange(s, dtype=jnp.int32)[None, :]
               == slot[:, None]).astype(jnp.int32)
    rd_epoch, rd_seq, rd_val, found = _latest_at_slot(
        state, slot_oh, heard, axis_name)

    get_gate = is_get & leader_up & (lease_ok | epoch_ok)
    # Stale-epoch rewrite (update_key): needs the quorum either way.
    rewrite = get_gate & found & (rd_epoch != lead_epoch) & epoch_ok
    get_ok = get_gate & (~(found & (rd_epoch != lead_epoch)) | rewrite)

    # Write path (shared by put and rewrite).
    new_seq = state.obj_seq_ctr + 1                          # [E]
    put_commit = is_put & epoch_ok & slot_valid
    commit = put_commit | rewrite
    wval = jnp.where(is_put, val, rd_val)                    # [E]
    do_write = commit[:, None] & heard                       # [E, Ml]
    wmask = (do_write[:, :, None] & (slot_oh[:, None, :] > 0))
    obj_epoch = jnp.where(wmask, lead_epoch[:, None, None], state.obj_epoch)
    obj_seq = jnp.where(wmask, new_seq[:, None, None], state.obj_seq)
    obj_val = jnp.where(wmask, wval[:, None, None], state.obj_val)
    obj_seq_ctr = jnp.where(commit, new_seq, state.obj_seq_ctr)

    out_epoch = jnp.where(commit, lead_epoch,
                          jnp.where(get_ok, rd_epoch, 0))
    out_seq = jnp.where(commit, new_seq, jnp.where(get_ok, rd_seq, 0))
    res = KvResult(
        committed=commit,
        get_ok=get_ok,
        found=found & get_ok,
        value=jnp.where(get_ok & found, rd_val, 0),
        obj_vsn=jnp.stack([out_epoch, out_seq], -1),
    )
    new_state = state._replace(obj_epoch=obj_epoch, obj_seq=obj_seq,
                               obj_val=obj_val, obj_seq_ctr=obj_seq_ctr)
    return new_state, res


@functools.partial(jax.jit, static_argnames=("axis_name",))
def kv_step(state: EngineState, kind: jax.Array, slot: jax.Array,
            val: jax.Array, lease_ok: jax.Array, up: jax.Array,
            axis_name: Optional[str] = None
            ) -> Tuple[EngineState, KvResult]:
    """One K/V protocol round per ensemble, batched over E.

    kind [E] int32 (OP_NOOP/OP_GET/OP_PUT); slot [E] int32; val [E]
    int32 (payload for puts); lease_ok [E] bool (host lease check,
    check_lease peer.erl:1493-1516); up [E, Ml] bool.

    Semantics per ensemble:
    - PUT: one quorum round.  Proposal (lead_epoch, ctr+1); member
      replicas whose epoch matches ack (valid_request, peer.erl
      :869-871 — stale-epoch followers nack); on majority in every
      view, all heard member replicas apply the write (put_obj,
      :1669-1698) and the counter advances (obj_sequence, :1776-1791).
    - GET: if lease_ok, leased local read; else the quorum epoch-check
      round gates it (:1460-1468).  The value returned is the newest
      version among heard replicas (get_latest_obj, :1623-1662); if
      that version's epoch is stale, it is rewritten at the current
      epoch through the same quorum machinery (update_key,
      :1564-1596) — batched across ensembles.
    """
    ctx = _kv_context(state, up, axis_name)
    return _kv_round(state, ctx, kind, slot, val, lease_ok, axis_name)


@functools.partial(jax.jit, static_argnames=("axis_name",))
def kv_step_scan(state: EngineState, kind: jax.Array, slot: jax.Array,
                 val: jax.Array, lease_ok: jax.Array, up: jax.Array,
                 axis_name: Optional[str] = None
                 ) -> Tuple[EngineState, KvResult]:
    """K sequential K/V rounds per ensemble in one launch.

    kind/slot/val: [K, E]; lease_ok: [K, E]; up: [E, Ml] (held fixed
    across the K rounds).  Sequentiality per ensemble preserves the
    per-key serialization the reference gets from key-hashed workers
    (async/3, peer.erl:1220-1225).  Results are stacked [K, E].

    Ballot state (epoch/leader/views) is invariant across the rounds,
    so the round context — including its peer-axis collectives — is
    computed once outside the scan.
    """
    ctx = _kv_context(state, up, axis_name)

    def body(st, op):
        k, sl, v, lz = op
        st2, r = _kv_round(st, ctx, k, sl, v, lz, axis_name)
        return st2, r

    return jax.lax.scan(body, state, (kind, slot, val, lease_ok))


# ---------------------------------------------------------------------------
# Membership reconfiguration kernel (joint consensus, ladder #5)


@functools.partial(jax.jit, static_argnames=("axis_name",))
def reconfig_step(state: EngineState, propose: jax.Array,
                  new_view: jax.Array, up: jax.Array,
                  axis_name: Optional[str] = None
                  ) -> Tuple[EngineState, jax.Array, jax.Array]:
    """Batched joint-consensus membership change.

    The reference's update_members → transition dance (peer.erl:655-672,
    751-774): a proposed view is CONSED onto the views list, quorums
    must hold in EVERY view while joint (msg.erl:377-418 recursion —
    here view slot 1 keeps the old view), and once the joint
    configuration has committed, views collapse to the new one alone.
    One call does one phase per ensemble, batched over E:

    - ensembles with ``propose`` and a single active view: install the
      joint configuration (new view into slot 0, old into slot 1) if a
      commit quorum holds in the OLD view (try_commit gate);
    - ensembles already joint (both view slots active): collapse to
      slot 0 alone if a commit quorum holds in BOTH views
      (should_transition/transition, :751-774).

    propose  [E] bool; new_view [E, Ml] bool; up [E, Ml] bool.
    Returns (state', installed [E], collapsed [E]).  Leaders whose
    commit gate fails keep their current views (the host steps them
    down / retries, as the reference does on failed try_commit).
    """
    member_now = state.view_mask.any(1)                      # [E, Ml]
    heard = up & member_now
    # Peer-axis predicates must be global under sharding (a shard only
    # sees its local peer slice).
    is_joint = reduce_peers(
        state.view_mask[:, 1, :].astype(jnp.int32), axis_name) > 0  # [E]
    new_nonempty = reduce_peers(new_view.astype(jnp.int32),
                                axis_name) > 0               # [E]
    has_leader = state.leader >= 0

    # Commit gate in the CURRENT configuration (epoch-matching acks).
    gidx = _global_peer_idx(state.epoch.shape[1], axis_name)
    is_leader = gidx[None, :] == state.leader[:, None]
    lead_epoch = reduce_peers(jnp.where(is_leader, state.epoch, 0),
                              axis_name)
    ack = heard & (state.epoch == lead_epoch[:, None])
    commit_ok = (_quorum_met(ack, heard, state.view_mask, axis_name)
                 & has_leader)

    install = propose & ~is_joint & commit_ok & new_nonempty
    collapse = is_joint & commit_ok & ~propose

    old_v0 = state.view_mask[:, 0, :]
    # install: slot0=new, slot1=old;  collapse: slot0 stays, slot1=0
    v0 = jnp.where(install[:, None], new_view, old_v0)
    v1 = jnp.where(install[:, None], old_v0,
                   jnp.where(collapse[:, None], False,
                             state.view_mask[:, 1, :]))
    view_mask = jnp.stack([v0, v1], axis=1)
    if state.view_mask.shape[1] > 2:
        view_mask = jnp.concatenate(
            [view_mask, state.view_mask[:, 2:, :]], axis=1)
    # fact seq advances on a committed view change (try_commit
    # increments; we fold install/collapse into one seq bump on the
    # member replicas that heard it).
    bump = (install | collapse)[:, None] & heard
    fact_seq = jnp.where(bump, state.fact_seq + 1, state.fact_seq)
    return (state._replace(view_mask=view_mask, fact_seq=fact_seq),
            install, collapse)


# ---------------------------------------------------------------------------
# Fused full step (election + K ops) — the "training step" analog


@functools.partial(jax.jit, static_argnames=("axis_name",))
def full_step(state: EngineState, elect: jax.Array, cand: jax.Array,
              kind: jax.Array, slot: jax.Array, val: jax.Array,
              lease_ok: jax.Array, up: jax.Array,
              axis_name: Optional[str] = None
              ) -> Tuple[EngineState, jax.Array, KvResult]:
    """Election round (where needed) followed by K K/V rounds, fused.

    This is the flagship jitted step: the host decides *which*
    ensembles need elections (failure detection is host-side), the
    device does all the protocol math.
    """
    state, won = elect_step(state, elect, cand, up, axis_name=axis_name)
    state, res = kv_step_scan(state, kind, slot, val, lease_ok, up,
                              axis_name=axis_name)
    return state, won, res
