"""Deterministic discrete-event host runtime.

The reference runs on Erlang/OTP: peers are gen_fsm processes, quorum
collectors and K/V FSMs are spawned processes, timers are
``send_after``, and tests freeze processes with
``erlang:suspend_process`` (``test/basic_test.erl:15-21``).  This module
provides those capabilities as a seeded, virtual-time event simulator:

- :class:`Actor` — addressable event handler bound to a (virtual) node.
  Peers, managers, storage, and tree servers are actors.
- :class:`Task` — a generator-based coroutine (the analog of a spawned
  worker/collector process): ``yield future`` suspends until the future
  resolves; ``yield runtime.sleep(d)`` sleeps.
- :class:`Network` — delivery policy: per-message latency, partitions
  (``test/sc.erl:1012-1036``), and a drop hook mirroring the
  compiled-in drop table ``riak_ensemble_msg:maybe_send_request``
  (``msg.erl:111-128``).
- Suspension parity: a suspended actor's messages and timer firings are
  backlogged and delivered in order on resume, like a suspended Erlang
  process's mailbox.

Everything is deterministic given the seed: the event queue is ordered
by (time, insertion seq).  Virtual seconds run in microseconds of real
time, so the integration suite exercises multi-second protocol
timelines (elections, lease expiry, gossip convergence) instantly.
"""

from __future__ import annotations

import heapq
import random
from typing import Any, Callable, Dict, Generator, List, Optional, Tuple


class Future:
    __slots__ = ("done", "value", "_waiters")

    def __init__(self) -> None:
        self.done = False
        self.value: Any = None
        self._waiters: List[Callable[[Any], None]] = []

    def resolve(self, value: Any) -> None:
        if self.done:
            return
        self.done = True
        self.value = value
        waiters, self._waiters = self._waiters, []
        # Every waiter runs even if an earlier one raises (the list is
        # already swapped out, so a skipped waiter could never fire);
        # the errors re-raise afterwards — all of them, as a group
        # when there are several — so no bug loses its signal.
        # KeyboardInterrupt/SystemExit abort immediately.
        errs: List[Exception] = []
        for w in waiters:
            try:
                w(value)
            except Exception as exc:
                errs.append(exc)
        if len(errs) == 1:
            raise errs[0]
        if errs:
            raise ExceptionGroup("future waiter errors", errs)

    def add_waiter(self, fn: Callable[[Any], None]) -> None:
        if self.done:
            fn(self.value)
        else:
            self._waiters.append(fn)


class Timer:
    __slots__ = ("cancelled", "fire_at")

    def __init__(self, fire_at: float) -> None:
        self.cancelled = False
        self.fire_at = fire_at

    def cancel(self) -> None:
        self.cancelled = True


class Actor:
    """Base class for addressable event handlers.

    Subclasses implement :meth:`handle` (the gen_fsm/gen_server event
    callback).  ``name`` is any hashable address; ``node`` scopes the
    actor to a virtual node for partitions and node-down semantics.
    """

    def __init__(self, runtime: "Runtime", name: Any, node: str) -> None:
        self.runtime = runtime
        self.name = name
        self.node = node
        self.suspended = False
        self.alive = True
        self._backlog: List[Any] = []
        runtime.register(self)

    # -- messaging ---------------------------------------------------------

    def send(self, dst: Any, msg: Any) -> None:
        """Send over the (virtual) network from this actor's node."""
        self.runtime.net_send(self.node, dst, msg)

    def send_local(self, dst: Any, msg: Any) -> None:
        """Same-node send: no network policy, but still async."""
        self.runtime.post(dst, msg)

    def send_after(self, delay: float, msg: Any) -> Timer:
        """Timer message to self (erlang:send_after)."""
        return self.runtime.send_after(delay, self.name, msg)

    # -- lifecycle ---------------------------------------------------------

    def handle(self, msg: Any) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def on_stop(self) -> None:
        """Cleanup hook when the actor is stopped/killed."""

    def stop(self) -> None:
        self.runtime.stop_actor(self.name)

    def _deliver(self, msg: Any) -> None:
        if not self.alive:
            return
        if self.suspended:
            self._backlog.append(msg)
            return
        self.handle(msg)


class Task:
    """Generator coroutine driven by the runtime.

    The generator yields :class:`Future` objects; the runtime resumes it
    with the future's value.  Yielding ``None`` re-schedules immediately
    (a cooperative yield point).
    """

    __slots__ = ("gen", "runtime", "alive", "name")

    def __init__(self, runtime: "Runtime", gen: Generator,
                 name: str = "task") -> None:
        self.runtime = runtime
        self.gen = gen
        self.alive = True
        self.name = name

    def kill(self) -> None:
        if self.alive:
            self.alive = False
            self.gen.close()

    def _step(self, send_value: Any) -> None:
        if not self.alive:
            return
        try:
            yielded = self.gen.send(send_value)
        except StopIteration:
            self.alive = False
            return
        if yielded is None:
            self.runtime.defer(lambda: self._step(None))
        elif isinstance(yielded, Future):
            yielded.add_waiter(
                lambda v: self.runtime.defer(lambda: self._step(v)))
        else:  # pragma: no cover - programming error
            raise TypeError(f"task {self.name} yielded {yielded!r}")


class Network:
    """Delivery policy between virtual nodes."""

    def __init__(self, runtime: "Runtime") -> None:
        self.runtime = runtime
        #: set of frozenset({a, b}) pairs that cannot communicate
        self.cut_links: set = set()
        #: test drop hook: fn(src_node, dst_name, msg) -> bool (drop?)
        self.drop_hook: Optional[Callable[[str, Any, Any], bool]] = None
        self.min_latency = 1e-4
        self.max_latency = 5e-4
        #: adversarial reorder window (chaos mode); 0 = off
        self.chaos_window = 0.0
        self.chaos_local = 0.0
        #: fault-injection plan (riak_ensemble_tpu.faults.FaultPlan):
        #: DIRECTIONAL drop (the one-way partition ``partition()``
        #: cannot express) + per-link injected delay, applied in
        #: net_send on top of the base latency.  Lazily created by
        #: :meth:`fault_plan`; healed with everything else.
        self.plan = None

    def chaos(self, window: float = 0.05, local: float = 0.0) -> None:
        """PULSE-analog delivery permutation: every cross-node message
        gets an independent uniform delay in ``[0, window)``, so any
        two messages in flight within the window can deliver in either
        order — the seeded RNG makes each seed one reproducible total
        order of deliveries.  ``local`` adds the same treatment to
        same-node sends (stronger than Erlang, which guarantees
        per-pair signal order; protocols gated on reqids must still
        converge).  The window should dwarf max_latency and stay well
        under the protocol timeouts (tick/lease) or chaos turns into
        blanket message loss."""
        self.chaos_window = window
        self.chaos_local = local

    def partition(self, group_a: List[str], group_b: List[str]) -> None:
        """Cut all links between two node groups (sc.erl:1012-1022)."""
        for a in group_a:
            for b in group_b:
                self.cut_links.add(frozenset((a, b)))

    def fault_plan(self):
        """The network's fault-injection plan, created on first use
        (seeded from the runtime's RNG for reproducible schedules)."""
        if self.plan is None:
            from riak_ensemble_tpu import faults

            self.plan = faults.FaultPlan(
                seed=self.runtime.rng.randrange(1 << 30))
        return self.plan

    def partition_oneway(self, srcs: List[str],
                         dsts: List[str]) -> None:
        """Cut links in ONE direction only: frames ``src→dst`` drop,
        ``dst→src`` still deliver — the classic failover killer the
        symmetric :meth:`partition` cannot express."""
        plan = self.fault_plan()
        for a in srcs:
            for b in dsts:
                plan.drop(a, b)

    def heal(self) -> None:
        self.cut_links.clear()
        if self.plan is not None:
            self.plan.heal()

    def can_reach(self, src: str, dst: str) -> bool:
        return src == dst or frozenset((src, dst)) not in self.cut_links

    def latency(self) -> float:
        if self.chaos_window > 0.0:
            return self.runtime.rng.uniform(0.0, self.chaos_window)
        return self.runtime.rng.uniform(self.min_latency, self.max_latency)

    def local_latency(self) -> float:
        if self.chaos_local > 0.0:
            return self.runtime.rng.uniform(0.0, self.chaos_local)
        return 0.0


class Runtime:
    def __init__(self, seed: int = 0) -> None:
        self.now = 0.0
        self.rng = random.Random(seed)
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self.actors: Dict[Any, Actor] = {}
        self.net = Network(self)
        self.trace: Optional[Callable[[str, Any], None]] = None
        self._monitors: Dict[Any, List[Callable[[Any], None]]] = {}

    # -- registry ----------------------------------------------------------

    def register(self, actor: Actor) -> None:
        assert actor.name not in self.actors, f"duplicate actor {actor.name}"
        self.actors[actor.name] = actor

    def whereis(self, name: Any) -> Optional[Actor]:
        return self.actors.get(name)

    def stop_actor(self, name: Any) -> None:
        actor = self.actors.pop(name, None)
        if actor is not None:
            actor.alive = False
            actor.on_stop()
            for fn in self._monitors.pop(name, []):
                self.defer(lambda fn=fn: fn(name))

    def monitor(self, name: Any, callback: Callable[[Any], None]) -> None:
        """erlang:monitor analog: callback(name) fires (deferred) when
        the named actor is stopped.  Monitoring a dead/unknown actor
        fires immediately (the DOWN-on-monitor semantic)."""
        if name not in self.actors:
            self.defer(lambda: callback(name))
            return
        self._monitors.setdefault(name, []).append(callback)

    def demonitor(self, name: Any,
                  callback: Callable[[Any], None]) -> None:
        """Remove a monitor registered with :meth:`monitor` — needed
        whenever the monitoring side finishes first, or a long-lived
        monitored actor accumulates dead callbacks forever."""
        fns = self._monitors.get(name)
        if fns is None:
            return
        try:
            fns.remove(callback)
        except ValueError:
            pass
        if not fns:
            del self._monitors[name]

    def suspend(self, name: Any) -> None:
        """Freeze an actor (erlang:suspend_process analog)."""
        self.actors[name].suspended = True

    def resume(self, name: Any) -> None:
        actor = self.actors[name]
        if not actor.suspended:
            return
        actor.suspended = False
        backlog, actor._backlog = actor._backlog, []
        for msg in backlog:
            self.post(actor.name, msg)

    # -- scheduling --------------------------------------------------------

    def _push(self, at: float, fn: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (at, self._seq, fn))

    def defer(self, fn: Callable[[], None]) -> None:
        """Run fn at the current time, after already-queued events."""
        self._push(self.now, fn)

    def schedule(self, delay: float, fn: Callable[[], None]) -> Timer:
        timer = Timer(self.now + delay)

        def fire() -> None:
            if not timer.cancelled:
                fn()

        self._push(timer.fire_at, fire)
        return timer

    def send_after(self, delay: float, dst: Any, msg: Any) -> Timer:
        return self.schedule(delay, lambda: self.post(dst, msg))

    def sleep(self, delay: float) -> Future:
        fut = Future()
        self.schedule(delay, lambda: fut.resolve(None))
        return fut

    def with_timeout(self, fut: Future, timeout: float,
                     timeout_value: Any = "timeout") -> Future:
        """Future resolving to fut's value, or timeout_value after
        `timeout` seconds (the gen_fsm call-timeout analog)."""
        out = Future()
        fut.add_waiter(out.resolve)
        self.schedule(timeout, lambda: out.resolve(timeout_value))
        return out

    def post(self, dst: Any, msg: Any) -> None:
        """Deliver msg to actor dst at the current time (local send)."""
        def deliver() -> None:
            actor = self.actors.get(dst)
            if actor is not None:
                if self.trace:
                    self.trace("deliver", (dst, msg))
                actor._deliver(msg)

        self.defer(deliver)

    def net_send(self, src_node: str, dst: Any, msg: Any) -> None:
        """Network send with latency/partition/drop policy applied."""
        actor = self.actors.get(dst)
        dst_node = actor.node if actor is not None else None
        if dst_node is not None and not self.net.can_reach(src_node, dst_node):
            return
        if self.net.drop_hook is not None and \
                self.net.drop_hook(src_node, dst, msg):
            return
        delay = self.net.local_latency() if dst_node == src_node \
            else self.net.latency()
        plan = self.net.plan
        if plan is not None and dst_node is not None \
                and dst_node != src_node and plan.active():
            # fault plane: directional drop, then injected per-link
            # delay stacked on the base latency (virtual time — the
            # schedule stays deterministic under the seeded plan RNG)
            if plan.should_drop(src_node, dst_node):
                return
            delay += plan.delay_s(src_node, dst_node)
        self.send_after(delay, dst, msg)

    def spawn_task(self, gen: Generator, name: str = "task") -> Task:
        task = Task(self, gen, name)
        self.defer(lambda: task._step(None))
        return task

    # -- execution ---------------------------------------------------------

    def run_for(self, duration: float) -> None:
        self.run_until_time(self.now + duration)

    def run_until_time(self, deadline: float) -> None:
        while self._heap and self._heap[0][0] <= deadline:
            at, _, fn = heapq.heappop(self._heap)
            self.now = max(self.now, at)
            fn()
        self.now = max(self.now, deadline)

    def run_until(self, pred: Callable[[], bool], max_time: float = 60.0,
                  poll: float = 0.01) -> bool:
        """Advance until pred() is true (checked every `poll` virtual
        seconds); returns False on virtual-time budget exhaustion."""
        deadline = self.now + max_time
        while self.now < deadline:
            if pred():
                return True
            self.run_until_time(min(self.now + poll, deadline))
        return pred()

    def await_future(self, fut: Future, timeout: float = 60.0) -> Any:
        """Drive the loop until fut resolves (external/test entry point).
        Raises TimeoutError on virtual-time timeout."""
        ok = self.run_until(lambda: fut.done, max_time=timeout, poll=0.001)
        if not ok:
            raise TimeoutError("future not resolved in virtual time budget")
        return fut.value
