"""Public K/V client API.

Re-implementation of ``src/riak_ensemble_client.erl``: thin wrappers
over the peer K/V operations, routed to the ensemble leader through
the router pool, with raw protocol results translated to
``("error", reason)`` tuples (translate, client.erl:119-132) and a
local enabled-check returning ``("error", "unavailable")`` when the
node's cluster is not enabled (maybe, client.erl:134-143).

``kmodify`` is intentionally not exposed (root-ensemble internal use
only — client.erl:22-24).

This is the SCALAR actor-plane client (one op, one FSM round).  The
scale path's network client is :class:`riak_ensemble_tpu.svcnode.
ServiceClient`, whose ``kput_many``/``kget_many`` are slab-native:
batches in the slab subset ride the zero-copy ``kput_slab``/
``kget_slab`` wire verbs straight into the service's slab-resident
enqueue half (docs/ARCHITECTURE.md §12).
"""

from __future__ import annotations

from typing import Any

from riak_ensemble_tpu import funref
from riak_ensemble_tpu import router as routerlib
from riak_ensemble_tpu.manager import manager_name
from riak_ensemble_tpu.runtime import Runtime
from riak_ensemble_tpu.types import NOTFOUND, Obj


def translate(result: Any):
    """client.erl:119-132."""
    if isinstance(result, tuple) and result[0] == "ok":
        return result
    if result in ("unavailable", "timeout", "failed"):
        return ("error", result)
    return ("error", "timeout")


class Client:
    """K/V operations issued from one node of the cluster."""

    def __init__(self, runtime: Runtime, node: str) -> None:
        self.runtime = runtime
        self.node = node

    def _maybe(self, fn):
        mgr = self.runtime.whereis(manager_name(self.node))
        if mgr is None or not mgr.enabled():
            return ("error", "unavailable")
        return fn()

    def _sync(self, ensemble, event, timeout: float):
        return translate(routerlib.sync_send_event(
            self.runtime, self.node, ensemble, event, timeout))

    # -- API (client.erl:34-116) ---------------------------------------

    def kget(self, ensemble, key, timeout: float = 10.0, opts=()):
        """Linearizable read.  When ``Config.trust_lease`` holds, the
        ensemble leader answers from its local state inside an
        unexpired lease without a fresh quorum round (peer.erl's
        leased read; the batched scale plane's analog is the
        lease-protected fast path in
        :mod:`riak_ensemble_tpu.parallel.batched_host`, surfaced over
        the wire by :mod:`riak_ensemble_tpu.svcnode`)."""
        return self._maybe(lambda: self._sync(
            ensemble, ("get", key, tuple(opts)), timeout))

    def kupdate(self, ensemble, key, current: Obj, new,
                timeout: float = 10.0):
        return self._maybe(lambda: self._sync(
            ensemble, ("put", key, funref.ref("peer:kupdate"),
                       [current, new]), timeout))

    def kput_once(self, ensemble, key, value, timeout: float = 10.0):
        return self._maybe(lambda: self._sync(
            ensemble, ("put", key, funref.ref("peer:kput_once"), [value]),
            timeout))

    def kover(self, ensemble, key, value, timeout: float = 10.0):
        return self._maybe(lambda: self._sync(
            ensemble, ("overwrite", key, value), timeout))

    def kdelete(self, ensemble, key, timeout: float = 10.0):
        return self.kover(ensemble, key, NOTFOUND, timeout)

    def ksafe_delete(self, ensemble, key, current: Obj,
                     timeout: float = 10.0):
        return self.kupdate(ensemble, key, current, NOTFOUND, timeout)
