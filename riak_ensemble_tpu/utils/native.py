"""Loader for the C++ native library (``native/``).

Builds ``libretpu_native.so`` on first use via make (the image ships
g++; no pybind11, so the ABI is plain C + ctypes) and memoizes the
handle.  ``load()`` returns None if the toolchain is unavailable —
callers must degrade to their documented Python fallbacks, mirroring
how the reference degrades when its NIF fails to load
(riak_ensemble_clock.erl:30-42 falls back by crashing the lease path;
we degrade more gracefully).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_resolve_lib: Optional[ctypes.CDLL] = None
_resolve_tried = False

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
SONAME = os.path.join(NATIVE_DIR, "libretpu_native.so")
RESOLVE_SONAME = os.path.join(NATIVE_DIR, "_retpu_resolve.so")


def build_target(target: str, artifact: str) -> bool:
    """Run make for one target in ``native/``; True iff the artifact
    exists afterwards AND is confirmed fresh.  make is invoked even
    when the artifact already exists — a fast no-op when fresh, a
    rebuild when its source changed (stale .so files otherwise survive
    source edits forever).  Shared by the ctypes library below and
    wire.py's codec loader.

    Failure discipline (advisor r2): every path on which make could
    NOT confirm the artifact (nonzero rc, make missing, timeout)
    refuses an existing artifact unless its mtime already postdates
    every source in ``native/`` — a stale codec .so diverging from the
    Python oracle is strictly worse than the pure-Python fallback.
    """
    try:
        proc = subprocess.run(["make", "-C", NATIVE_DIR, target],
                              capture_output=True, timeout=120)
        return proc.returncode == 0 and os.path.exists(artifact)
    except Exception:
        return _artifact_fresh(artifact)


def _artifact_fresh(artifact: str) -> bool:
    """True iff ``artifact`` exists and is newer than every source
    file in ``native/`` (the no-toolchain freshness check)."""
    try:
        art_m = os.path.getmtime(artifact)
    except OSError:
        return False
    try:
        for name in os.listdir(NATIVE_DIR):
            if name.endswith((".cc", ".c", ".h")) or name == "Makefile":
                if os.path.getmtime(
                        os.path.join(NATIVE_DIR, name)) > art_m:
                    return False
    except OSError:
        return False
    return True


def _build() -> bool:
    return build_target("all", SONAME)


def load() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if needed; None if the
    native toolchain is unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(SONAME)
        except OSError:
            return None
        # clock
        lib.retpu_monotonic_time_ns.restype = ctypes.c_int64
        lib.retpu_monotonic_time_ms.restype = ctypes.c_int64
        lib.retpu_clock_is_boottime.restype = ctypes.c_int
        # treestore
        lib.retpu_store_open.restype = ctypes.c_void_p
        lib.retpu_store_open.argtypes = [ctypes.c_char_p]
        lib.retpu_store_close.argtypes = [ctypes.c_void_p]
        lib.retpu_store_put.restype = ctypes.c_int
        lib.retpu_store_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32]
        lib.retpu_store_get.restype = ctypes.c_int64
        lib.retpu_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.retpu_store_delete.restype = ctypes.c_int
        lib.retpu_store_delete.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.retpu_store_count.restype = ctypes.c_uint64
        lib.retpu_store_count.argtypes = [ctypes.c_void_p]
        lib.retpu_store_key_at.restype = ctypes.c_int64
        lib.retpu_store_key_at.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.retpu_store_sync.argtypes = [ctypes.c_void_p]
        lib.retpu_store_flush.argtypes = [ctypes.c_void_p]
        lib.retpu_store_compact.argtypes = [ctypes.c_void_p]
        # arena batch put (the resolve kernel's WAL path) — older .so
        # builds may predate it, so probe instead of assuming
        if hasattr(lib, "retpu_store_put_many"):
            lib.retpu_store_put_many.restype = ctypes.c_int
            lib.retpu_store_put_many.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64]
        _lib = lib
        return _lib


def load_resolve() -> Optional[ctypes.CDLL]:
    """The native resolve kernel (``native/resolvekernel.cc``),
    building its explicit make target on first use; None when the
    toolchain is unavailable or the build fails — callers degrade to
    the pure-Python resolve path (never a crash, never a test
    failure).  A separate .so from :func:`load` on purpose: a resolve-
    kernel build break must not take the clock/treestore library
    down."""
    global _resolve_lib, _resolve_tried
    with _lock:
        if _resolve_lib is not None or _resolve_tried:
            return _resolve_lib
        _resolve_tried = True
        if not build_target("_retpu_resolve.so", RESOLVE_SONAME):
            return None
        try:
            lib = ctypes.CDLL(RESOLVE_SONAME)
        except OSError:
            return None
        try:
            p = ctypes.c_void_p
            lib.retpu_resolve_version.restype = ctypes.c_int
            lib.retpu_resolve_unpack.restype = ctypes.c_int
            lib.retpu_resolve_unpack.argtypes = [
                p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, p, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, p, p, p, p, p, p,
                p, p]
            lib.retpu_resolve_mirrors.restype = ctypes.c_int
            lib.retpu_resolve_mirrors.argtypes = [
                ctypes.c_int32, ctypes.c_int32, p, p, p, p, p, p, p,
                p, p, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, p, p, p, p, p]
            lib.retpu_wal_encode.restype = ctypes.c_int64
            lib.retpu_wal_encode.argtypes = [
                ctypes.c_int64, ctypes.c_int32, p, p, p, p, p, p,
                p, p, p, p, p, p, p, p, p, p, ctypes.c_int64, p]
            lib.retpu_delta_sections.restype = ctypes.c_int
            lib.retpu_delta_sections.argtypes = [
                ctypes.c_int32, ctypes.c_int32, p, p, p, p, p, p,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int32, p, p, p, p, p, p,
                p, p, p]
            if lib.retpu_resolve_version() < 1:
                return None
        except AttributeError:
            # stale .so predating a symbol: fall back rather than
            # serving half an ABI
            return None
        # enqueue half (native/enqueuekernel.cc, same .so): PROBED,
        # not required — a stale .so predating the enqueue kernel
        # still serves the resolve half; enqueue_native.get() checks
        # the symbol itself and degrades to the numpy pack alone.
        if hasattr(lib, "retpu_enqueue_pack"):
            p = ctypes.c_void_p
            lib.retpu_enqueue_version.restype = ctypes.c_int
            lib.retpu_enqueue_pack.restype = ctypes.c_int
            lib.retpu_enqueue_pack.argtypes = [
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                p, p, p, p, p, p, p, p, p, p, p, p, p]
            if hasattr(lib, "retpu_enqueue_gather"):
                lib.retpu_enqueue_gather.restype = ctypes.c_int
                lib.retpu_enqueue_gather.argtypes = [
                    ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                    p, p, p, p, p, p, p, p, p, p, p, p, p]
        # commutative-lane fold (ARCHITECTURE §18) — probed: a stale
        # .so predating it still serves the other halves;
        # resolve_native's comm_fold returns None when the symbol is
        # absent and the Python fold runs instead.
        if hasattr(lib, "retpu_comm_fold"):
            p = ctypes.c_void_p
            lib.retpu_comm_fold.restype = ctypes.c_int
            lib.retpu_comm_fold.argtypes = (
                [ctypes.c_int32, ctypes.c_int32] + [p] * 16)
        _resolve_lib = lib
        return _resolve_lib
