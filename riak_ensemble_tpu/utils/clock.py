"""Monotonic clock backing the leader lease.

The C++ module (``native/clock.cc``) is the production source — the
role of the reference's only C NIF (c_src/riak_ensemble_clock.c):
CLOCK_BOOTTIME-preferred readings immune to wall-clock jumps and
suspend/resume gaps, consumed by the lease check
(riak_ensemble_lease.erl:76-88).  Falls back to Python's
``time.clock_gettime(CLOCK_BOOTTIME)`` / ``time.monotonic_ns`` when
the native library can't be built.
"""

from __future__ import annotations

import time

from riak_ensemble_tpu.utils import native


def _py_monotonic_ns() -> int:
    try:
        return time.clock_gettime_ns(time.CLOCK_BOOTTIME)  # type: ignore[attr-defined]
    except (AttributeError, OSError):
        return time.monotonic_ns()


def monotonic_time_ns() -> int:
    lib = native.load()
    if lib is not None:
        t = lib.retpu_monotonic_time_ns()
        if t >= 0:
            return t
    return _py_monotonic_ns()


def monotonic_time_ms() -> int:
    """riak_ensemble_clock:monotonic_time_ms/0."""
    return monotonic_time_ns() // 1_000_000


def monotonic_time() -> float:
    """Seconds as float — the host runtime's clock interface (inject
    into :class:`riak_ensemble_tpu.lease.Lease` in production; the
    virtual runtime injects simulated time instead)."""
    return monotonic_time_ns() / 1e9


def is_boottime() -> bool:
    lib = native.load()
    if lib is not None:
        return bool(lib.retpu_clock_is_boottime())
    return hasattr(time, "CLOCK_BOOTTIME")
