"""Host utilities: native-library loader, monotonic clock, tracing."""
