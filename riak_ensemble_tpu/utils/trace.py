"""Tracing & introspection.

The reference has no tracing beyond compiled-out ``?OUT`` macros
(peer.erl:63-64, msg.erl:38-39) and the get_info/tree_info
introspection calls — SURVEY §5 marks real tracing as the reference's
gap to fill.  This module provides:

- :class:`Tracer` — structured event recorder hooked into the
  runtime's trace callback: per-op spans (kind, ensemble, key,
  start/end, outcome), message-delivery events, and counters; ring-
  buffered so long runs stay bounded.
- :func:`dump_ensemble` — per-ensemble state dump across peers
  (fsm state, epoch/seq, leader, views, tree trust/readiness) — the
  get_info surface (peer.erl:183-206) aggregated cluster-wide.
"""

from __future__ import annotations

import collections
import itertools
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

_span_ids = itertools.count(1)


@dataclass
class Span:
    span_id: int
    kind: str
    ensemble: Any
    detail: Any
    start: float
    end: Optional[float] = None
    outcome: Any = None

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start


@dataclass
class Tracer:
    """Attach with ``Tracer(runtime).install()``.

    Both rings are BOUNDED: ``events`` at ``max_events`` and
    ``finished`` spans at ``max_finished`` (the finished list used to
    grow forever on long runs — a tracer left installed on a serving
    node leaked a Span per op).  Counters are exact over the whole
    run either way; percentile reports cover the retained window.

    Pass ``registry`` (an :class:`riak_ensemble_tpu.obs.registry.
    MetricsRegistry`) to fold the tracer into the unified obs plane:
    event counts mirror into ``retpu_trace_events_total`` (labeled by
    kind) and finished span durations feed the
    ``retpu_trace_span_ms`` histogram, so `metrics` consumers see
    tracer activity without touching this object."""

    runtime: Any
    max_events: int = 100_000
    events: Deque[Tuple[float, str, Any]] = field(default_factory=collections.deque)
    counters: Dict[str, int] = field(default_factory=dict)
    spans: Dict[int, Span] = field(default_factory=dict)
    finished: Deque[Span] = field(default_factory=collections.deque)
    max_finished: int = 10_000
    registry: Any = None

    def __post_init__(self) -> None:
        self.finished = collections.deque(self.finished,
                                          maxlen=self.max_finished)

    def install(self) -> "Tracer":
        self.runtime.trace = self._on_event
        return self

    def uninstall(self) -> None:
        if self.runtime.trace == self._on_event:
            self.runtime.trace = None

    # -- runtime hook ------------------------------------------------------

    def _on_event(self, kind: str, payload: Any) -> None:
        self.counters[kind] = self.counters.get(kind, 0) + 1
        self.events.append((self.runtime.now, kind, payload))
        while len(self.events) > self.max_events:
            self.events.popleft()
        if self.registry is not None:
            self.registry.counter(
                "retpu_trace_events_total",
                "runtime trace events by kind",
                label_name="kind").labels(kind).inc()

    # -- spans -------------------------------------------------------------

    def begin(self, kind: str, ensemble: Any, detail: Any = None) -> int:
        sid = next(_span_ids)
        self.spans[sid] = Span(sid, kind, ensemble, detail,
                               self.runtime.now)
        return sid

    def finish(self, span_id: int, outcome: Any) -> Optional[Span]:
        span = self.spans.pop(span_id, None)
        if span is None:
            return None
        span.end = self.runtime.now
        span.outcome = outcome
        self.finished.append(span)
        self.counters[f"span:{span.kind}"] = \
            self.counters.get(f"span:{span.kind}", 0) + 1
        if self.registry is not None and span.duration is not None:
            self.registry.histogram(
                "retpu_trace_span_ms",
                "tracer span durations by kind",
                label_name="kind").labels(
                    span.kind).record(span.duration * 1e3)
        return span

    # -- reports -----------------------------------------------------------

    def percentiles(self, kind: str, qs=(0.5, 0.99)) -> Dict[float, float]:
        durations = sorted(s.duration for s in self.finished
                           if s.kind == kind and s.duration is not None)
        if not durations:
            return {}
        out = {}
        for q in qs:
            idx = min(len(durations) - 1, int(q * len(durations)))
            out[q] = durations[idx]
        return out

    def summary(self) -> Dict[str, Any]:
        by_kind: Dict[str, int] = {}
        for s in self.finished:
            by_kind[s.kind] = by_kind.get(s.kind, 0) + 1
        return {"counters": dict(self.counters),
                "finished_spans": by_kind,
                "open_spans": len(self.spans)}


def peer_info(peer) -> Dict[str, Any]:
    """get_info analog (peer.erl:183-189,1905-1910)."""
    return {
        "id": peer.id,
        "state": peer.fsm_state,
        "epoch": peer.epoch,
        "seq": peer.seq,
        "leader": peer.leader,
        "views": peer.views,
        "members": peer.members,
        "tree_trust": peer.tree_trust,
        "tree_ready": peer.tree_ready,
        "suspended": peer.suspended,
    }


def dump_ensemble(runtime, ensemble) -> List[Dict[str, Any]]:
    """Cluster-wide state dump for one ensemble — every live peer's
    info, leader-first."""
    from riak_ensemble_tpu.peer import Peer

    infos = [peer_info(a) for a in list(runtime.actors.values())
             if isinstance(a, Peer) and a.ensemble == ensemble]
    infos.sort(key=lambda i: (i["state"] != "leading", repr(i["id"])))
    return infos
