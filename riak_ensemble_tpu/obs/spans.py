"""Cross-process flush tracing: flush ids + the span store.

Every device launch is stamped with a process-monotonic ``flush_id``
at enqueue.  The id propagates through the two-phase launch pipeline
(enqueue half → resolve half ride the same ``_InFlightLaunch``) and
over the replication wire (a trailing field of every ``abatch``
entry), so one id names the SAME flush on the leader and on every
replica — the Dapper trace-id discipline, scoped to the flush (the
unit of causality in this system: one flush = one device round = one
replicated entry).

The store is append-cheap and bounded: per flush id, a dict of
``role -> [(span_name, seconds), ...]`` plus whatever shape metadata
the recorder attached.  Roles are ``"leader"`` and ``"replica"``
(replica spans carry the recording service's lane tag when several
share the process).  :func:`timeline` answers the joined record —
the obs API a test or bench asks "where did flush N's time go,
end to end?".

Per-process scope: in-process replica servers (tests, the bench
smoke shape) share this store with their leader, so the join is
immediate.  Subprocess replicas record into their own process's
store; the leader's id still names their spans, and the join happens
wherever both exports land.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Tuple

__all__ = ["next_flush_id", "SpanStore", "SPANS", "timeline"]

#: process-wide monotonic flush ids — shared by every service in the
#: process so leader and in-process replica launches never collide
_flush_ids = itertools.count(1)


def next_flush_id() -> int:
    return next(_flush_ids)


class SpanStore:
    """Bounded per-process store of per-flush span timelines."""

    def __init__(self, max_flushes: int = 4096) -> None:
        self.max_flushes = max_flushes
        self._lock = threading.Lock()
        self._flushes: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        #: highest flush id the ring has ever evicted — the
        #: evicted/unknown miss boundary (a fid at or below it that
        #: is absent ROLLED OFF; above it, it was never recorded
        #: here — "the replica hasn't seen it yet" vs "too late")
        self._evict_high = 0
        #: lookup misses by reason (exported as
        #: ``retpu_span_misses_total{reason=...}``) — the fleet
        #: puller's signal for distinguishing lag from loss
        self.misses: Dict[str, int] = {"evicted": 0, "unknown": 0}

    def record(self, flush_id: int, role: str,
               spans: List[Tuple[str, float]],
               **info: Any) -> None:
        """Append one side's spans for a flush.  ``spans`` is a list
        of ``(name, seconds)``; ``info`` (batch shape, seq, lane, ...)
        merges into the role's metadata.  Thread-safe: replica server
        threads and the leader's flush loop share the store."""
        if not flush_id:
            return
        with self._lock:
            rec = self._flushes.get(flush_id)
            if rec is None:
                rec = self._flushes[flush_id] = {}
                while len(self._flushes) > self.max_flushes:
                    old_fid, _old = self._flushes.popitem(last=False)
                    if old_fid > self._evict_high:
                        self._evict_high = old_fid
            side = rec.setdefault(role, {"spans": []})
            side["spans"].extend(
                (str(n), float(d)) for n, d in spans)
            for k, v in info.items():
                side[k] = v

    def _miss_reason(self, flush_id: int) -> str:
        """Why a lookup missed (call under the lock): ``evicted`` for
        ids at or below the ring's eviction high-water (recorded once,
        rolled off — includes never-recorded ids in that range, the
        honest limit of a bounded ring), ``unknown`` above it (never
        seen HERE — on a replica that usually means "hasn't arrived
        yet")."""
        reason = ("evicted" if 0 < flush_id <= self._evict_high
                  else "unknown")
        self.misses[reason] += 1
        return reason

    def timeline(self, flush_id: int) -> Dict[str, Any]:
        """The joined per-flush record: ``{"flush_id": N, "leader":
        {...}, "replica": {...}}`` with per-role span lists.  A flush
        the store cannot answer returns a STRUCTURED miss —
        ``{"flush_id": N, "miss": "evicted"|"unknown"}`` — instead of
        bare None, and counts into :attr:`misses`: the fleet puller
        must distinguish "rolled off the ring" from "this host never
        saw it"."""
        with self._lock:
            rec = self._flushes.get(flush_id)
            if rec is None:
                return {"flush_id": int(flush_id),
                        "miss": self._miss_reason(flush_id)}
            out: Dict[str, Any] = {"flush_id": flush_id}
            for role, side in rec.items():
                out[role] = {"spans": list(side["spans"]),
                             **{k: v for k, v in side.items()
                                if k != "spans"}}
            return out

    def flush_ids(self) -> List[int]:
        with self._lock:
            return list(self._flushes)

    def span_values(self, flush_ids, role: str,
                    name: str) -> List[float]:
        """Every recorded duration (seconds) of span ``name`` under
        ``role`` across ``flush_ids``, one lock acquisition for the
        whole batch — the runtime controller's bulk read (e.g. the
        ``repl_ack`` samples of the last cadence window's flushes).
        Missing roles/spans contribute nothing: a flush whose ack is
        still pending simply isn't a sample yet.  A flush id entirely
        absent from the store counts a structured miss (evicted vs
        unknown) like :meth:`timeline` — and still contributes no
        sample."""
        out: List[float] = []
        with self._lock:
            for fid in flush_ids:
                rec = self._flushes.get(fid)
                if rec is None:
                    self._miss_reason(fid)
                    continue
                side = rec.get(role)
                if side is None:
                    continue
                out.extend(d for n, d in side["spans"] if n == name)
        return out


#: the process-global store every service records into
SPANS = SpanStore()


def timeline(flush_id: int) -> Dict[str, Any]:
    """Module-level convenience over the global store (misses come
    back structured — check ``tl.get("miss")``, not ``is None``)."""
    return SPANS.timeline(flush_id)
