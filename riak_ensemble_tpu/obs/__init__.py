"""Unified observability plane (round 6).

SURVEY §5 marks real tracing as the reference's gap to fill —
riak_ensemble ships only compiled-out ``?OUT`` macros and the
get_info/count_quorum introspection calls.  Before this round the
scale path answered that gap piecemeal: ``stats()`` dicts hand-built
per service, ``perf_counter()`` pairs scattered through the flush
path, and bench-side one-off attribution that could not be asked
anything after the run.  This package is the single plane the whole
stack reports into:

- :mod:`.registry` — a low-overhead metrics registry (counters,
  gauges, fixed-bucket histograms with O(log B) record; a label
  dimension for per-tenant attribution), exported as plain JSON and
  Prometheus text format (svcnode's ``metrics`` verb).
- :mod:`.spans` — the monotonic per-process ``flush_id`` allocator
  and a bounded store of per-flush span timelines.  Every launch is
  stamped at enqueue; the id rides the replication wire (a
  trailing field of each ``abatch`` entry), so leader-side
  enqueue/step/d2h/unpack/WAL/delta-build spans and replica-side
  validate/scatter/rebuild/WAL spans join into ONE causal timeline
  per flush (the Dapper propagation model, scoped to the flush).
- :mod:`.flightrec` — a flight recorder: bounded ring of complete
  per-flush records (marks, batch shape, active-set occupancy,
  payload bytes, queue depths) with an anomaly trigger — any flush
  slower than ``trigger_ratio`` × the rolling p50 snapshots the ring
  plus a box fingerprint to a dump file, so the next mixed-rung
  anomaly is diagnosable instead of a shrug.
- :mod:`.fingerprint` — the box fingerprint (cpu count, loadavg,
  jax/jaxlib versions, ``RETPU_*`` knobs) every flight dump and every
  bench JSON embeds, so cross-round comparisons stop being faith.
- :mod:`.opslo` — per-op SLO tracing (round 9): every keyed op's
  submit→enqueue→flush-join→settle→ack stamps in bounded numpy slab
  rings keyed by ``flush_id``, feeding client-perceived latency
  histograms per op kind and per tenant; each flush's slowest rows
  attach to the span store so ``timeline(fid)`` resolves a tail op
  down to its stage split.
- :mod:`.compilewatch` — compile-event hooks around every jitted
  step/pack/scatter variant (executable-cache-size deltas, exact, not
  a latency heuristic): warmup coverage gaps surface as
  ``retpu_compile_events_total{phase="serve"}`` instead of a
  dispatch-p99 mystery.
- :mod:`.controller` — the obs-ACTUATED runtime controller (round
  12): consumes the surfaces above on a flush-count cadence and
  drives ``pipeline_depth``/``repl_window``/tenant admission, with a
  bounded decision journal exported back through this same plane
  (``retpu_autotune_*`` gauges, the ``health()`` ``controller``
  section, flight-dump ``controller_decisions``, Chrome-trace export
  via ``tools/trace_export.py``).  ``RETPU_AUTOTUNE=0`` (the default)
  keeps it observe-only-constructed and bit-identical to the
  pre-controller service.

- :mod:`.fleet` — fleet-scope joining (round 13): per-link NTP-style
  clock-offset estimation (every ``obsq`` sideband round-trip feeds
  it), Prometheus multi-host merge under a ``host`` label, and the
  clock-aligned cross-host timeline (``svc.fleet_timeline(fid)`` —
  leader and replica spans on ONE axis, honest to the offset bound).
- :mod:`.watchdog` — the standing anomaly watchdog (round 13):
  leader-side, controller-cadence, walks pulled fleet timelines for
  ack-before-apply skew, persistently slow replica spans, and clock
  drift; findings journal through the PR 12 ``DecisionJournal``
  export surfaces.  ``RETPU_WATCHDOG=0`` disarms the standing pull.

Knobs: ``RETPU_OBS=0`` disables hot-path recording (instruments stay
constructed; record calls short-circuit — the bench's A/B arm);
``RETPU_OBS_DUMP_DIR`` directs flight-recorder dumps (unset keeps
them in memory only).  Stores are PER PROCESS: in-process replica
servers share the span store with their leader, subprocess replicas
export their half through their own ``metrics``/dump surface and the
join happens on ``flush_id``.
"""

from __future__ import annotations

import os

from riak_ensemble_tpu.obs.compilewatch import (COMPILE_EVENTS,
                                                CompileWatch)
from riak_ensemble_tpu.obs.controller import (DecisionJournal,
                                              RuntimeController)
from riak_ensemble_tpu.obs.fingerprint import box_fingerprint
from riak_ensemble_tpu.obs.fleet import (ClockOffset, align_timeline,
                                         merge_prometheus)
from riak_ensemble_tpu.obs.flightrec import FlightRecorder
from riak_ensemble_tpu.obs.opslo import OpSloRing
from riak_ensemble_tpu.obs.registry import (Counter, Gauge, Histogram,
                                            MetricsRegistry,
                                            MS_BUCKETS)
from riak_ensemble_tpu.obs.spans import (SPANS, SpanStore,
                                         next_flush_id, timeline)
from riak_ensemble_tpu.obs.watchdog import AnomalyWatchdog

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "MS_BUCKETS", "FlightRecorder", "SpanStore", "SPANS",
           "next_flush_id", "timeline", "box_fingerprint", "enabled",
           "dump_dir", "OpSloRing", "CompileWatch", "COMPILE_EVENTS",
           "RuntimeController", "DecisionJournal", "ClockOffset",
           "align_timeline", "merge_prometheus", "AnomalyWatchdog"]


def enabled() -> bool:
    """Whether hot-path recording is on (``RETPU_OBS=0`` opts out).

    Read the environment each call — services CACHE the answer at
    construction (one attribute test per flush beats an environ
    lookup), so an A/B arm flips the knob and builds a fresh
    service."""
    return os.environ.get("RETPU_OBS", "1") != "0"


def dump_dir():
    """Flight-recorder dump directory (``RETPU_OBS_DUMP_DIR``); None
    keeps anomaly snapshots in memory only."""
    return os.environ.get("RETPU_OBS_DUMP_DIR") or None
