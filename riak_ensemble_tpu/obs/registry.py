"""Metrics registry: counters, gauges, fixed-bucket histograms.

Design constraints, in order:

1. **Cheap to record.**  A counter inc is one attribute add; a
   histogram record is one bisect into a FIXED bucket ladder plus two
   adds — no allocation, no locking on the hot path (CPython's GIL
   makes the single adds atomic enough for monitoring counters; the
   flush path is single-threaded per service anyway).
2. **Cheap when off.**  Instruments exist either way; callers gate
   their record calls on a cached ``enabled()`` bool, so the
   ``RETPU_OBS=0`` arm pays one attribute test per flush.
3. **Pull, don't push.**  Most service counters already live as plain
   attributes on the hot path (``flushes``, ``ops_served``, ...).
   Rather than double-writing them, the registry supports CALLBACK
   instruments (a gauge/counter whose value is read at export time)
   and COLLECTORS (a function contributing whole labeled metric
   families at export time — the per-tenant arrays export this way,
   so the hot path touches numpy, never dicts of label children).

Exports: :meth:`MetricsRegistry.snapshot` (plain JSON-able dict —
the svcnode ``metrics`` verb's default) and
:meth:`MetricsRegistry.render_prometheus` (text exposition format).
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "MS_BUCKETS", "percentile_from_counts", "family"]

#: default latency ladder (milliseconds): log-spaced upper bounds
#: from 50 µs to 30 s — wide enough for a leased read and a wedged
#: d2h alike; 18 buckets keeps a [E, B] per-tenant plane small.
MS_BUCKETS: Tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def percentile_from_counts(counts, edges, q: float) -> float:
    """Bucket-resolution quantile estimate over fixed-bucket counts
    (``len(counts) == len(edges) + 1``; the final count is the +Inf
    overflow): linear interpolation inside the landing bucket, with
    the overflow bucket reported as its lower bound (there is no
    honest upper edge past the ladder).  The ONE estimator behind
    both :meth:`Histogram.percentile` and the per-tenant latency
    planes — two copies would silently diverge."""
    total = 0
    for c in counts:
        total += c
    if not total:
        return 0.0
    target = q * total
    acc = 0
    lo = 0.0
    for i, c in enumerate(counts):
        if i >= len(edges):
            return lo  # overflow bucket: no upper edge to lerp to
        hi = float(edges[i])
        if acc + c >= target:
            if not c:
                return hi
            return lo + (hi - lo) * (target - acc) / c
        acc += c
        lo = hi
    return lo


def family(typ: str, help: str, values: Dict[Any, Any],
           label: str = "tenant") -> Dict[str, Any]:
    """Build one collector-family dict in the shape
    :meth:`MetricsRegistry.collect` requires — the ONE place that
    shape lives, so the collectors in batched_host/repgroup can't
    drift from it.  ``values`` maps label value (or None for the
    unlabeled sample) to the metric value; ``label`` names the label
    dimension in the Prometheus exposition."""
    return {"type": typ, "help": help, "values": values,
            "label": label}


class Counter:
    """Monotonic counter; optionally labeled via :meth:`labels`
    (``label_name`` names the dimension in the exposition — "tenant"
    for the per-tenant families, "kind" for the tracer fold)."""

    __slots__ = ("name", "help", "value", "label_name", "_children")

    def __init__(self, name: str, help: str = "",
                 label_name: str = "tenant") -> None:
        self.name = name
        self.help = help
        self.value = 0
        self.label_name = label_name
        self._children: Optional[Dict[str, "Counter"]] = None

    def inc(self, n: float = 1) -> None:
        self.value += n

    def labels(self, label: str) -> "Counter":
        if self._children is None:
            self._children = {}
        child = self._children.get(label)
        if child is None:
            child = self._children[label] = Counter(
                self.name, self.help, self.label_name)
        return child

    def remove_label(self, label: str) -> bool:
        """Drop one labeled child series (tenant recycle: the label's
        owner is gone, and a retained child would keep exporting a
        dead tenant's counts forever)."""
        if self._children is None:
            return False
        return self._children.pop(label, None) is not None

    def _samples(self):
        if self._children:
            for label, child in self._children.items():
                yield label, child.value
        if self.value or not self._children:
            yield None, self.value


class Gauge:
    """Point-in-time value: set directly, or backed by a callback
    read at export time (the pull discipline — hot-path attributes
    stay plain attributes)."""

    __slots__ = ("name", "help", "value", "fn")

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None) -> None:
        self.name = name
        self.help = help
        self.value = 0.0
        self.fn = fn

    def set(self, v: float) -> None:
        self.value = v

    def read(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return self.value


class Histogram:
    """Fixed-bucket histogram: ``record`` is a bisect into the
    precomputed upper-bound ladder plus two adds.  ``+Inf`` overflow
    rides an implicit final bucket.  Percentiles are bucket-resolution
    estimates (linear interpolation inside the landing bucket) —
    exactly what a fixed-bucket design can honestly claim."""

    __slots__ = ("name", "help", "buckets", "counts", "count", "sum",
                 "label_name", "_children")

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = MS_BUCKETS,
                 label_name: str = "tenant") -> None:
        self.name = name
        self.help = help
        self.buckets = tuple(float(b) for b in buckets)
        assert list(self.buckets) == sorted(set(self.buckets)), \
            "histogram buckets must be strictly increasing"
        self.counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.label_name = label_name
        self._children: Optional[Dict[str, "Histogram"]] = None

    def record(self, v: float) -> None:
        self.counts[bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v

    def labels(self, label: str) -> "Histogram":
        if self._children is None:
            self._children = {}
        child = self._children.get(label)
        if child is None:
            child = self._children[label] = Histogram(
                self.name, self.help, self.buckets, self.label_name)
        return child

    def remove_label(self, label: str) -> bool:
        """Drop one labeled child series (see Counter.remove_label)."""
        if self._children is None:
            return False
        return self._children.pop(label, None) is not None

    def percentile(self, q: float) -> float:
        """Bucket-resolution estimate of the q-quantile (0 < q <= 1);
        see :func:`percentile_from_counts`."""
        return percentile_from_counts(self.counts, self.buckets, q)

    def _snapshot(self) -> Dict[str, Any]:
        return {"count": self.count, "sum": round(self.sum, 6),
                "buckets": dict(zip(
                    [*map(str, self.buckets), "+Inf"], self.counts)),
                "p50": round(self.percentile(0.5), 6),
                "p99": round(self.percentile(0.99), 6)}


class MetricsRegistry:
    """One process-or-service-scoped family of instruments.

    Get-or-create accessors keep wiring idempotent; :meth:`collect`
    registers an export-time contributor for labeled families whose
    hot-path representation is something cheaper than label children
    (the per-tenant numpy planes).  Collector functions return
    ``{name: {"type": t, "help": h, "values": {label_or_None: v}}}``.
    """

    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._collectors: List[Callable[[], Dict[str, Any]]] = []

    # -- get-or-create accessors -------------------------------------------

    def counter(self, name: str, help: str = "",
                label_name: str = "tenant") -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = Counter(name, help, label_name)
        return c

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = Gauge(name, help, fn)
        elif fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = MS_BUCKETS,
                  label_name: str = "tenant") -> Histogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = Histogram(name, help, buckets,
                                              label_name)
        return h

    def collect(self, fn: Callable[[], Dict[str, Any]]) -> None:
        self._collectors.append(fn)

    def remove_labeled(self, label: str) -> int:
        """Drop every labeled child series recorded under ``label``
        across all counters and histograms — the ensemble-row recycle
        hook: a recycled tenant's ledger row is zeroed, and any
        labeled series created under its label must go with it, or
        the registry keeps exporting (and a successor tenant reusing
        the label inherits) a dead tenant's samples.  Collector
        families are untouched — they re-derive their label sets at
        export time.  Returns how many series were dropped."""
        dropped = 0
        for c in self._counters.values():
            dropped += c.remove_label(label)
        for h in self._hists.values():
            dropped += h.remove_label(label)
        return dropped

    # -- export -------------------------------------------------------------

    def _collected(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for fn in self._collectors:
            try:
                out.update(fn())
            except Exception:
                continue  # a broken collector must not kill export
        return out

    def names(self) -> List[str]:
        """Every registered metric name (collector families included)
        — the docs ratchet's source of truth."""
        return sorted({*self._counters, *self._gauges, *self._hists,
                       *self._collected()})

    def snapshot(self) -> Dict[str, Any]:
        """Plain-container snapshot (wire- and JSON-encodable).  The
        unlabeled sample of a labeled family exports under the empty
        label ``""`` — ``str(None)`` would forge a tenant literally
        named "None", indistinguishable from a real one."""

        def by_label(samples: Dict[Any, Any]) -> Any:
            if list(samples) == [None]:
                return samples[None]
            return {("" if k is None else str(k)): v
                    for k, v in samples.items()}

        out: Dict[str, Any] = {}
        for name, c in self._counters.items():
            out[name] = by_label(dict(c._samples()))
        for name, g in self._gauges.items():
            v = g.read()
            # non-finite reads (a broken callback returns NaN) map to
            # None: the snapshot must stay strict-JSON-serializable
            out[name] = v if v == v and abs(v) != float("inf") \
                else None
        for name, h in self._hists.items():
            snap = h._snapshot()
            if h._children:
                snap["by_label"] = {label: ch._snapshot()
                                    for label, ch in h._children.items()}
            out[name] = snap
        for name, fam in self._collected().items():
            out[name] = by_label(fam["values"])
        return out

    def render_prometheus(self, host: Optional[str] = None) -> str:
        """Prometheus text exposition format, version 0.0.4.

        ``host`` labels every sample with ``host="..."`` (the
        fleet-scrape dimension: one leader scrape answering for the
        whole group tells its hosts apart by this label —
        ``obs.fleet.merge_prometheus`` applies the same injection to
        replica-rendered texts, so local and pulled sections agree)."""
        lines: List[str] = []
        if host is not None:
            from riak_ensemble_tpu.obs import fleet as _fleet
            plain = self.render_prometheus()
            return "\n".join(
                _fleet.inject_host_label(ln, host)
                for ln in plain.splitlines()) + "\n"

        def head(name: str, typ: str, help: str) -> None:
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {typ}")

        def fmt(v: Any) -> str:
            f = float(v)
            if f != f:
                return "NaN"  # a broken callback gauge reads NaN —
            if f in (float("inf"), float("-inf")):  # the scrape must
                return "+Inf" if f > 0 else "-Inf"  # survive it
            return repr(int(f)) if f == int(f) else repr(f)

        def esc(label: Any) -> str:
            # exposition-format label escaping: tenant labels are
            # arbitrary user strings, and one unescaped quote would
            # make Prometheus reject the WHOLE scrape
            return (str(label).replace("\\", "\\\\")
                    .replace('"', '\\"').replace("\n", "\\n"))

        for name, c in self._counters.items():
            head(name, "counter", c.help)
            for label, v in c._samples():
                lines.append(
                    f'{name}{{{c.label_name}="{esc(label)}"}} {fmt(v)}'
                    if label is not None else f"{name} {fmt(v)}")
        for name, g in self._gauges.items():
            head(name, "gauge", g.help)
            lines.append(f"{name} {fmt(g.read())}")
        for name, h in self._hists.items():
            head(name, "histogram", h.help)
            # the parent's own series renders whenever it holds
            # direct records, even alongside labeled children —
            # snapshot() exports both, and the two surfaces must
            # never disagree about what was recorded
            series = ([(None, h)] if not h._children or h.count
                      else [])
            series += list(h._children.items()) if h._children else []
            for label, hh in series:
                sel = (f'{h.label_name}="{esc(label)}",'
                       if label is not None else "")
                acc = 0
                for edge, cnt in zip([*h.buckets, "+Inf"], hh.counts):
                    acc += cnt
                    lines.append(
                        f'{name}_bucket{{{sel}le="{edge}"}} {acc}')
                lines.append(f"{name}_sum{{{sel[:-1]}}} {fmt(hh.sum)}"
                             if sel else f"{name}_sum {fmt(hh.sum)}")
                lines.append(
                    f"{name}_count{{{sel[:-1]}}} {hh.count}"
                    if sel else f"{name}_count {hh.count}")
        for name, fam in self._collected().items():
            head(name, fam.get("type", "gauge"), fam.get("help", ""))
            lname = fam.get("label", "tenant")
            for label, v in fam["values"].items():
                lines.append(
                    f'{name}{{{lname}="{esc(label)}"}} {fmt(v)}'
                    if label is not None else f"{name} {fmt(v)}")
        return "\n".join(lines) + "\n"
