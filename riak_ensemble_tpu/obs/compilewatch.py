"""Compile-event telemetry for jitted hot-path programs.

Every jitted step/pack/scatter variant the launch path dispatches is
wrapped in a :class:`CompileWatch`: before and after each call the
wrapper reads the jitted callable's executable-cache size, and a
growth means THIS call paid an XLA compile.  The event records which
program, the argument shape signature (the (K, A) bucket, in
practice) and the wall time the call took — so a ``warmup()``
coverage gap or a first-use compile at a fresh bucket becomes a
visible ``retpu_compile_events_total{phase="serve"}`` increment and
a named log entry instead of an unexplained dispatch-p99 spike.

The detection is exact, not a latency heuristic: ``jax.jit``
callables expose ``_cache_size()`` (the per-function executable
count).  Callables without it (plain Python closures, the mesh
pack wrapper) pass through unwatched.  The cache is per PROCESS and
per jitted function object — services sharing module-level step
programs share their compiles, which is precisely what the warmup
story needs to observe.

Cost: one C-level ``_cache_size()`` call before and after each
launch dispatch; the shape signature is only computed on a miss.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = ["CompileWatch", "COMPILE_EVENTS", "signature"]

#: process-global bounded log of compile events (newest last) — the
#: flight recorder's compile-event section reads the service-local
#: log, this one serves debugging across services in one process
COMPILE_EVENTS: "deque[Dict[str, Any]]" = deque(maxlen=256)


def signature(args: tuple, kwargs: dict) -> str:
    """Compact shape signature of a call's array arguments, e.g.
    ``"f32[4,64];i32[4,64]"`` truncated to the first few leaves —
    enough to name the (K, A) bucket that compiled.  Computed only on
    a cache miss."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves((args, kwargs))
    except Exception:
        leaves = list(args)
    parts: List[str] = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        dt = getattr(leaf, "dtype", None)
        dt = getattr(dt, "name", str(dt)) if dt is not None else "?"
        parts.append(f"{dt}[{','.join(map(str, shape))}]")
        if len(parts) >= 6:
            parts.append("...")
            break
    return ";".join(parts)


class CompileWatch:
    """Callable wrapper that reports executable-cache misses.

    ``on_miss`` (if given) receives the event dict after it is
    appended to :data:`COMPILE_EVENTS`; attribute access (``lower``,
    ``_cache_size``, ...) passes through to the wrapped callable so
    AOT helpers keep working on the watched object.
    """

    __slots__ = ("fn", "name", "on_miss")

    def __init__(self, fn: Callable, name: str,
                 on_miss: Optional[Callable[[Dict[str, Any]], None]]
                 = None) -> None:
        self.fn = fn
        self.name = name
        self.on_miss = on_miss

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        fn = self.fn
        cs = getattr(fn, "_cache_size", None)
        if cs is None:
            return fn(*args, **kwargs)
        try:
            before = cs()
        except Exception:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        try:
            missed = cs() > before
        except Exception:
            missed = False
        if missed:
            ev = {
                "fn": self.name,
                "shapes": signature(args, kwargs),
                "compile_ms": round(dt * 1e3, 3),
                "t_unix": time.time(),
            }
            COMPILE_EVENTS.append(ev)
            if self.on_miss is not None:
                try:
                    self.on_miss(ev)
                except Exception:
                    pass  # telemetry must never fail the launch
        return out

    def __getattr__(self, item: str) -> Any:
        return getattr(self.fn, item)
