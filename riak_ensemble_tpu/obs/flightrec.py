"""Flight recorder: a bounded ring of complete per-flush records with
an anomaly trigger.

Every settled launch appends one record — its latency marks, flush
id, batch shape, active-set occupancy, payload bytes and queue
depths.  The ring answers "what were the last N flushes doing" at any
moment; the TRIGGER makes it useful after the fact: any flush slower
than ``trigger_ratio`` × the rolling p50 (default 5×, over the last
``window`` records, armed only past ``min_samples``) snapshots the
whole ring plus a box fingerprint.  With ``RETPU_OBS_DUMP_DIR`` set
the snapshot is also written to a JSON dump file (atomic rename);
either way it is retained in memory (``dumps``, bounded).

This is what turns the next mixed-rung anomaly (r4→r5: −32% ops/s,
p99 11×, cause never established) from a shrug into a diagnosis: the
dump names the slow flush's dominating mark, shows the flushes
around it, and pins the box state it happened on.
"""

from __future__ import annotations

import json
import os
import time
from bisect import bisect_left, insort
from collections import deque
from typing import Any, Dict, List, Optional

from riak_ensemble_tpu.obs.fingerprint import box_fingerprint

__all__ = ["FlightRecorder", "DUMP_SCHEMA", "META_FIELDS",
           "DERIVED_MARKS", "dump_keep"]


def dump_keep(default: int = 64) -> int:
    """How many dump FILES a dump directory retains
    (``RETPU_OBS_DUMP_KEEP``; <= 0 disables rotation).  Read per
    dump, not cached: rotation is cold-path by construction (behind
    the trigger's rate limit), and a soak harness lowering the cap
    mid-run should win immediately.  Without this cap a long wedge
    soak with a flapping trigger fills the disk — one dump file every
    ``min_dump_interval_s`` forever."""
    try:
        return int(os.environ.get("RETPU_OBS_DUMP_KEEP", default))
    except ValueError:
        return default

#: v2 added the per-op SLO ring tail (``slow_ops``: the slowest acked
#: ops with their stage splits), the service's recent
#: ``compile_events``, and the active fault-injection plan
#: (``injected_faults`` — so an anomaly captured mid-nemesis indicts
#: the nemesis); v3 added the runtime controller's recent
#: ``controller_decisions`` (so a dump captured while the controller
#: was moving knobs shows WHICH knob moved and why); v4 adds the
#: FLEET sections — ``hosts`` (each replica's matching span records
#: for the fids in this ring, pulled by the leader at trigger time),
#: ``clock_offsets`` (the per-link offset estimates those records
#: align under) and ``watchdog_findings`` — turning "the ack was
#: slow" into "replica B's wal_sync held the quorum" in ONE file.
#: All sections come from the recorder's ``extras`` callback (empty
#: when no extras provider is attached / the service is standalone).
DUMP_SCHEMA = "retpu-flight-dump-v4"

#: DERIVED latency marks — sums/subdivisions of other marks
#: ('enqueue' = h2d + dispatch; resolve_native/resolve_fallback =
#: the resolve half's per-arm share; enqueue_native/enqueue_fallback
#: = the ENQUEUE half's lane-build + op-plane-pack share attributed
#: to whichever pack arm ran, already inside queue_wait).  THE
#: canonical list: the service's total sums
#: (batched_host.DERIVED_MARKS) and the flight recorder's
#: dominant-mark argmax both derive from it, so a new derived mark
#: can never be additive in one place and excluded in the other (it
#: would dominate every tail attribution).
DERIVED_MARKS = ("enqueue", "resolve_native", "resolve_fallback",
                 "enqueue_native", "enqueue_fallback")

#: per-flush record fields that are shape/identity metadata or
#: derived marks, not additive latency components — shared with
#: bench's tail attribution so the two dominant-mark argmaxes can
#: never drift apart
META_FIELDS = ("k", "total") + DERIVED_MARKS + (
    "flush_id", "t", "a_width", "payload_bytes", "queued_rounds",
    "in_flight")


class FlightRecorder:
    """Per-service flush ring + anomaly dumps.

    ``record`` cost: one deque append, one bisect-maintained sorted
    window update (O(window) list shift worst case, window = 128),
    one comparison.  The trigger baseline is the EXACT median of the
    last ``window`` totals — recomputed per record, not on a
    ``refresh_every`` cadence: the old cached p50 lagged a load shift
    by up to a full refresh period, so the quiet stretch after a
    slow-flush spike kept comparing against the spike's inflated
    baseline and real 5x anomalies in that window never armed.  With
    the windowed median the threshold re-arms as fast as the window
    slides (``refresh_every`` is accepted for constructor
    compatibility and ignored).
    """

    def __init__(self, capacity: int = 256, window: int = 128,
                 trigger_ratio: float = 5.0, min_samples: int = 32,
                 refresh_every: int = 16,
                 min_dump_interval_s: float = 5.0,
                 max_dumps: int = 8,
                 dump_dir: Optional[str] = None,
                 name: str = "svc",
                 extras: Optional[Any] = None) -> None:
        self.records: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self.trigger_ratio = float(trigger_ratio)
        self.min_samples = int(min_samples)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.name = name
        self._dump_dir = dump_dir
        #: optional zero-arg callback returning extra dump sections
        #: (the service supplies its per-op ring tail + compile-event
        #: log); attached post-construction by the owning service so
        #: a test-replaced recorder still gets the sections
        self.extras = extras
        self._totals: "deque[float]" = deque(maxlen=window)
        #: the same window, sorted (bisect-maintained) — the median
        #: read is one index access
        self._sorted: List[float] = []
        #: anomaly observability: trigger count and the retained
        #: snapshots (bounded; a pathological box must not hoard
        #: rings), newest last
        self.anomalies = 0
        self.dumps: "deque[Dict[str, Any]]" = deque(maxlen=max_dumps)
        self._last_dump_t = -1e9

    def dump_dir(self) -> Optional[str]:
        if self._dump_dir is not None:
            return self._dump_dir
        return os.environ.get("RETPU_OBS_DUMP_DIR") or None

    def record(self, rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Append one per-flush record (must carry ``total`` seconds;
        ``flush_id`` and the marks ride along verbatim).  Returns the
        anomaly snapshot if this flush tripped the trigger, else
        None."""
        total = float(rec.get("total", 0.0))
        self.records.append(rec)
        p50 = self._p50
        armed = (len(self._totals) >= self.min_samples
                 and p50 > 0.0
                 and total > self.trigger_ratio * p50)
        # the slow flush itself joins the window AFTER the check, so
        # a burst of slow flushes keeps triggering against the
        # still-windowed baseline rather than instantly normalizing
        # itself away
        if len(self._totals) == self._totals.maxlen:
            old = self._totals[0]
            del self._sorted[bisect_left(self._sorted, old)]
        self._totals.append(total)
        insort(self._sorted, total)
        if not armed:
            return None
        # count EVERY trigger firing (the anomaly metric's contract);
        # the rate limit below only bounds how often a firing also
        # snapshots the ring — during a sustained incident the
        # counter keeps telling the truth while dumps stay bounded
        self.anomalies += 1
        now = time.monotonic()
        if now - self._last_dump_t < self.min_dump_interval_s:
            return None
        self._last_dump_t = now
        return self._dump(rec, total)

    @property
    def _p50(self) -> float:
        """Exact median of the current window (index access into the
        bisect-maintained sorted copy)."""
        s = self._sorted
        return s[len(s) // 2] if s else 0.0

    def _dump(self, rec: Dict[str, Any],
              total: float) -> Dict[str, Any]:
        marks = {k: v for k, v in rec.items()
                 if isinstance(v, (int, float))}
        cause = max((k for k in marks if k not in META_FIELDS),
                    key=lambda k: marks[k], default=None)
        snap = {
            "schema": DUMP_SCHEMA,
            "name": self.name,
            "t_unix": time.time(),
            "trigger": {
                "flush_id": rec.get("flush_id"),
                "total_s": total,
                "rolling_p50_s": self._p50,
                "ratio": round(total / self._p50, 2),
                "threshold": self.trigger_ratio,
                "dominant_mark": cause,
            },
            "ring": [dict(r) for r in self.records],
            "box": box_fingerprint(),
            # per-op tail + compile-event + injected-fault +
            # controller-decision + fleet sections (schema v4): empty
            # when no extras provider is attached
            "slow_ops": [],
            "compile_events": [],
            "injected_faults": {},
            "controller_decisions": [],
            "hosts": {},
            "clock_offsets": {},
            "watchdog_findings": [],
        }
        if self.extras is not None:
            try:
                snap.update(self.extras())
            except Exception:
                pass  # a broken extras hook must not fail the dump
        self.dumps.append(snap)
        d = self.dump_dir()
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                # pid in the name: leader and subprocess-replica
                # services share dump dirs and restart their
                # flush-id/anomaly ordinals, and a colliding name
                # would os.replace the very evidence a dump preserves
                path = os.path.join(
                    d, f"flight_{self.name}_{os.getpid()}_"
                       f"{rec.get('flush_id', 0)}_{self.anomalies}"
                       ".json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                os.replace(tmp, path)  # atomic: a killed process
                snap["path"] = path    # never leaves a torn dump
                self._rotate(d)
            except OSError:
                pass  # a full/readonly disk must not fail the flush
        return snap

    @staticmethod
    def _rotate(d: str) -> None:
        """Oldest-first dump rotation: keep at most
        :func:`dump_keep` ``flight_*.json`` files in the dump dir
        (atomic per-file unlink — a reader holding an open fd keeps
        its data; a concurrent writer's ``.tmp`` never matches).
        Shared dirs rotate COLLECTIVELY: leader + subprocess-replica
        recorders pointing at one directory enforce one cap, which is
        exactly what bounds the disk."""
        keep = dump_keep()
        if keep <= 0:
            return
        try:
            paths = [os.path.join(d, f) for f in os.listdir(d)
                     if f.startswith("flight_") and f.endswith(".json")]
        except OSError:
            return
        if len(paths) <= keep:
            return

        def age(p):
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0  # racing unlink: treat as oldest

        paths.sort(key=lambda p: (age(p), p))
        for p in paths[:-keep]:
            try:
                os.unlink(p)
            except OSError:
                pass  # a racing rotator already took it

    def marks_tail(self, n: int) -> List[Dict[str, Any]]:
        """The newest ``n`` records (oldest first) — the bench's
        tail-attribution source."""
        recs = list(self.records)
        return recs[-n:] if n else []
