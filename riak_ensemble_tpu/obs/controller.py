"""Obs-actuated runtime controller (docs/ARCHITECTURE.md §14).

PRs 6-9 built the senses — per-flush spans, per-op SLO rings,
per-tenant attribution, compile events, fault gauges — and this
module is the first thing that ACTS on them.  The PR 9 faultsweep
proved the optimal ``pipeline_depth``/``repl_window`` is
link-dependent (depth 2 worth 1.222x at 5 ms injected ack RTT, noise
at 1 ms), so any static default is wrong somewhere; the noisy-tenant
rung proved a hot tenant's row share is what a quiet tenant's p99
pays for.  Three actuators close those loops:

- :class:`AckRttTuner` — auto-tunes ``pipeline_depth``/``repl_window``
  from the measured ``repl_ack`` spans in the span store (the SAME
  samples ``obs.timeline(fid)`` shows a human), with hysteresis (a
  dead band between the up/down thresholds), a bounded step (one
  depth unit per evaluation), a flush-count cadence, and a
  leader-only gate (a replica lane has no ack path to tune).
- :class:`TenantGuard` — a per-tenant flush-admission token bucket
  fed by the PR 6 attribution plane: when one tenant's share of the
  window's ops crosses the guard threshold, its rows get a per-flush
  round cap (the service's token bucket), shrinking the batch depth
  its queue can force on everyone else — the quiet tenants' p99 is
  the SLO being defended.  Released with hysteresis when the share
  drops back.
- :class:`faults.SoakSchedule` (the chaos gate) — runs the silent
  wedge soak (:func:`riak_ensemble_tpu.faults.wedge_soak`, the same
  blackhole mode the ``slow``-marked nemesis sweeps exercise) on a
  clock schedule and asserts wedge detection stays within
  2 x ``PeerLink.IO_TIMEOUT`` — chaos as a standing regression gate.

Every decision is itself observable through the plane that triggered
it: the bounded :class:`DecisionJournal` records (cause metric,
observed value, old -> new knob, flush id) per decision, exported as
the ``retpu_autotune_*`` gauge family, the ``health()``
``controller`` section, the flight-dump ``controller_decisions``
section, and Chrome-trace instants via ``tools/trace_export.py``.
:func:`replay` reconstructs the final knob state from the journal
alone — the bench ASSERTS that reconstruction against the live knobs,
so "the journal explains every knob change" is a tested property,
not a hope.

Knobs: ``RETPU_AUTOTUNE`` (default ``0`` — off for one release; the
off arm is the bit-identical oracle, the native-kernel discipline),
``RETPU_AUTOTUNE_CADENCE`` (flushes between evaluations),
``RETPU_TENANT_GUARD`` (``0`` disarms the admission actuator alone).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

from riak_ensemble_tpu.obs import registry as obs_registry
from riak_ensemble_tpu.obs import spans as obs_spans

__all__ = ["DecisionJournal", "AckRttTuner", "TenantGuard",
           "RuntimeController", "replay", "enabled", "cadence",
           "tenant_guard_enabled"]


def enabled() -> bool:
    """Whether the controller actuates (``RETPU_AUTOTUNE=1``).  OFF
    by default for one release: the off arm must stay bit-identical
    to the pre-controller service (results, mirror slabs, wire
    bytes) — the same oracle discipline as the native kernels.
    Services cache the answer at construction."""
    return os.environ.get("RETPU_AUTOTUNE", "0") == "1"


def cadence(default: int = 64) -> int:
    """Flushes between controller evaluations
    (``RETPU_AUTOTUNE_CADENCE``, floor 1)."""
    try:
        return max(1, int(os.environ.get("RETPU_AUTOTUNE_CADENCE",
                                         str(default))))
    except ValueError:
        return default


def tenant_guard_enabled() -> bool:
    """Whether the tenant-admission actuator is armed alongside the
    controller (``RETPU_TENANT_GUARD``, default on; only meaningful
    while ``RETPU_AUTOTUNE=1`` arms the controller itself)."""
    return os.environ.get("RETPU_TENANT_GUARD", "1") != "0"


class DecisionJournal:
    """Bounded ring of controller decisions — the system's self-tuning
    made as observable as its flushes.

    One entry per decision: a monotonically increasing ``seq`` (so a
    consumer can detect ring overflow), wall time, the flush id the
    triggering evaluation ran at, the actuator, the CAUSE metric and
    its observed value, and the knob's ``old -> new`` transition.
    ``seq`` survives ring eviction; :func:`replay` folds entries into
    the final knob map."""

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = int(capacity)
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self.total = 0
        self.by_actuator: Dict[str, int] = {}

    def note(self, actuator: str, cause: str, observed: float,
             knob: Optional[str] = None, old: Any = None,
             new: Any = None, flush_id: int = 0,
             **info: Any) -> Dict[str, Any]:
        self.total += 1
        self.by_actuator[actuator] = \
            self.by_actuator.get(actuator, 0) + 1
        ev = {
            "seq": self.total,
            "t": time.time(),
            "flush_id": int(flush_id),
            "actuator": str(actuator),
            "cause": str(cause),
            "observed": (round(float(observed), 6)
                         if observed is not None else None),
            "knob": knob,
            "old": old,
            "new": new,
        }
        ev.update(info)
        self._ring.append(ev)
        return ev

    def snapshot(self) -> List[Dict[str, Any]]:
        """Oldest-first copies of the retained entries (plain
        containers — wire/JSON encodable)."""
        return [dict(ev) for ev in self._ring]

    def tail(self, n: int) -> List[Dict[str, Any]]:
        evs = list(self._ring)
        return [dict(ev) for ev in (evs[-n:] if n else [])]


def replay(events, initial: Dict[str, Any]) -> Dict[str, Any]:
    """Reconstruct the knob state from journal events alone: fold
    every knob-bearing decision's ``old -> new`` over ``initial``,
    checking each transition's ``old`` against the folded state (a
    mismatch means the journal does NOT explain the knob history —
    the bench's reconstruction assertion fails loudly, not softly).
    """
    state = dict(initial)
    for ev in events:
        knob = ev.get("knob")
        if knob is None:
            continue
        if knob in state and state[knob] != ev.get("old"):
            raise ValueError(
                f"journal replay mismatch: decision seq "
                f"{ev.get('seq')} claims {knob} was {ev.get('old')!r} "
                f"but the folded state holds {state[knob]!r}")
        state[knob] = ev.get("new")
    return state


class AckRttTuner:
    """Hysteresis + bounded-step tuner for the replication pipeline
    knobs, driven by measured ``repl_ack`` span p50.

    Decision table (evaluated once per cadence window, leader-only):

    - p50 >= ``up_ms`` and depth < ``max_depth``: step depth +1 and
      widen ``repl_window`` to ``2 x depth`` — the link is slow
      enough that overlapping ship N with flush N+1 pays (the PR 9
      faultsweep's measured regime).
    - depth above its baseline AND the link healed: step depth -1
      (window shrinks toward its own baseline).  "Healed" is
      ``p50 <= down_ms`` OR ``p50 <= down_frac x`` the p50 that
      triggered the last up-step — the RELATIVE clause matters
      because ``repl_ack`` includes the replica's apply cost, which
      never goes to zero: on a box whose loopback ack floor is
      ~2 ms, an absolute 1 ms threshold would pin an elevated depth
      forever after the injected delay vanished.
    - between the heal condition and ``up_ms``: HOLD.  The dead band
      is the hysteresis: a link hovering at one threshold cannot
      flap the knob every window.

    One bounded step per evaluation; ``min_samples`` acks required
    before any move (a quiet window is not evidence).  Baselines are
    the knob values observed at arm time, so the tuner converges back
    to the operator's configuration on heal instead of inventing its
    own floor."""

    CAUSE = "repl_ack_ms_p50"

    def __init__(self, up_ms: float = 4.0, down_ms: float = 1.0,
                 down_frac: float = 0.5,
                 max_depth: int = 4, min_samples: int = 4) -> None:
        assert down_ms < up_ms, "hysteresis needs down_ms < up_ms"
        assert 0.0 < down_frac < 1.0
        self.up_ms = float(up_ms)
        self.down_ms = float(down_ms)
        self.down_frac = float(down_frac)
        self.max_depth = int(max_depth)
        self.min_samples = int(min_samples)
        self.last_p50_ms: Optional[float] = None
        #: the windowed p50 that justified the most recent up-step —
        #: the relative heal condition's reference
        self._up_p50_ms: Optional[float] = None

    def evaluate(self, svc: Any, samples_s: List[float],
                 journal: DecisionJournal,
                 flush_id: int) -> List[Dict[str, Any]]:
        if len(samples_s) < self.min_samples:
            return []
        ms = sorted(samples_s)
        p50 = ms[len(ms) // 2] * 1e3
        self.last_p50_ms = p50
        depth = int(svc.pipeline_depth)
        base_depth = getattr(svc, "_autotune_base_depth", depth)
        base_window = getattr(svc, "_autotune_base_window",
                              int(getattr(svc, "repl_window", 1)))
        healed = p50 <= self.down_ms or (
            self._up_p50_ms is not None
            and p50 <= self.down_frac * self._up_p50_ms)
        out: List[Dict[str, Any]] = []
        if p50 >= self.up_ms and depth < self.max_depth:
            self._up_p50_ms = p50
            new_depth = depth + 1
            svc.set_pipeline_depth(new_depth)
            out.append(journal.note(
                "ack_rtt", self.CAUSE, p50, knob="pipeline_depth",
                old=depth, new=new_depth, flush_id=flush_id,
                direction="up"))
            want_w = max(base_window, 2 * new_depth)
            old_w = int(svc.repl_window)
            if want_w != old_w and hasattr(svc, "set_repl_window"):
                svc.set_repl_window(want_w)
                out.append(journal.note(
                    "ack_rtt", self.CAUSE, p50, knob="repl_window",
                    old=old_w, new=want_w, flush_id=flush_id,
                    direction="up"))
        elif healed and depth > base_depth:
            new_depth = depth - 1
            svc.set_pipeline_depth(new_depth)
            out.append(journal.note(
                "ack_rtt", self.CAUSE, p50, knob="pipeline_depth",
                old=depth, new=new_depth, flush_id=flush_id,
                direction="down"))
            want_w = (base_window if new_depth <= base_depth
                      else max(base_window, 2 * new_depth))
            old_w = int(svc.repl_window)
            if want_w != old_w and hasattr(svc, "set_repl_window"):
                svc.set_repl_window(want_w)
                out.append(journal.note(
                    "ack_rtt", self.CAUSE, p50, knob="repl_window",
                    old=old_w, new=want_w, flush_id=flush_id,
                    direction="down"))
        return out


class TenantGuard:
    """Flush-admission guard: cap a noisy tenant's per-flush row
    share via the service's token bucket.

    Fed by the attribution plane's op counters (``tenant_ops`` deltas
    over the cadence window).  When one tenant's share of the
    window's ops reaches ``share_high`` — and other tenants were
    active, so there is someone to defend — its rows get a per-flush
    admission cap of ``cap_frac x max_k`` rounds (floor 1).  The cap
    is a TOKEN BUCKET on the service (refilled per flush, burst
    2x), so a capped tenant still gets steady throughput — it just
    can't force every flush to its own max batch depth.  Released
    when the share falls to ``share_low`` (hysteresis band again).
    """

    CAUSE = "tenant_ops_share"

    def __init__(self, share_high: float = 0.7,
                 share_low: float = 0.45,
                 cap_frac: float = 0.5,
                 min_ops: int = 64) -> None:
        assert share_low < share_high
        self.share_high = float(share_high)
        self.share_low = float(share_low)
        self.cap_frac = float(cap_frac)
        self.min_ops = int(min_ops)
        #: rows currently capped, keyed by tenant label
        self.throttled: Dict[str, List[int]] = {}
        self.last_top_share: Optional[float] = None

    def evaluate(self, svc: Any, window_ops,
                 journal: DecisionJournal,
                 flush_id: int) -> List[Dict[str, Any]]:
        import numpy as np

        total = int(window_ops.sum())
        out: List[Dict[str, Any]] = []
        if total < self.min_ops:
            return out
        # group rows by tenant label exactly the way the attribution
        # exports do — a multi-row tenant is ONE tenant here too
        shares: Dict[str, float] = {}
        rows_of: Dict[str, List[int]] = {}
        for e in np.nonzero(window_ops)[0].tolist():
            lbl = svc.tenant_label(e)
            shares[lbl] = shares.get(lbl, 0.0) \
                + float(window_ops[e]) / total
            rows_of.setdefault(lbl, []).append(e)
        if not shares:
            return out
        top = max(shares, key=shares.get)
        self.last_top_share = round(shares[top], 4)
        cap = max(1, int(svc.max_k * self.cap_frac))
        if (shares[top] >= self.share_high
                and len(shares) > 1 and top not in self.throttled):
            self.throttled[top] = rows_of[top]
            out.append(journal.note(
                "tenant_guard", self.CAUSE, shares[top],
                knob=f"admission_cap[{top}]", old=None, new=cap,
                flush_id=flush_id, tenant=top, rows=rows_of[top]))
        for lbl in list(self.throttled):
            if shares.get(lbl, 0.0) <= self.share_low:
                rows = self.throttled.pop(lbl)
                out.append(journal.note(
                    "tenant_guard", self.CAUSE,
                    shares.get(lbl, 0.0),
                    knob=f"admission_cap[{lbl}]", old=cap, new=None,
                    flush_id=flush_id, tenant=lbl, rows=rows))
        if out:
            caps: Dict[int, int] = {}
            for rows in self.throttled.values():
                for e in rows:
                    caps[e] = cap
            svc.set_admission_caps(caps or None)
        return out


class RuntimeController:
    """The per-service control loop: consumes the service's own obs
    surfaces on a flush-count cadence and drives the knobs, with
    every decision journaled.

    Constructed by EVERY service (so the ``retpu_autotune_*`` gauge
    family is always registered — zeros when off, the fault-gauge
    discipline); it only ACTS while ``enabled`` is True.  The hot
    path pays one attribute test per flush when off and one integer
    compare per flush when on; evaluations run at most every
    ``cadence`` flushes."""

    def __init__(self, svc: Any,
                 tuner: Optional[AckRttTuner] = None,
                 guard: Optional[TenantGuard] = None,
                 soak_interval_s: float = 0.0,
                 journal_capacity: int = 256) -> None:
        from riak_ensemble_tpu import faults  # no import cycle at top

        self.svc = svc
        self.enabled = enabled()
        self.cadence = cadence()
        self.guard_enabled = tenant_guard_enabled()
        self.tuner = tuner if tuner is not None else AckRttTuner()
        self.guard = guard if guard is not None else TenantGuard()
        #: the standing chaos gate; disarmed by default (interval 0)
        #: — armed explicitly via :meth:`arm_soak` or the soak
        #: constructor arg, never inherited from the environment
        self.soak = faults.SoakSchedule(soak_interval_s)
        self.journal = DecisionJournal(journal_capacity)
        self.evals = 0
        self._since_eval = 0
        self._in_eval = False
        self._last_ops = None  # per-row op counts at last evaluation
        self._window_fids: List[int] = []
        # remember the operator's configuration as the heal target
        # (re-anchored by the service's set_autotune on every arm, so
        # knobs moved after construction become the new floor)
        svc._autotune_base_depth = int(svc.pipeline_depth)
        svc._autotune_base_window = int(getattr(svc, "repl_window", 1))

    # -- cadence ------------------------------------------------------------

    def tick(self, flush_id: int = 0) -> None:
        """Per-settled-flush hook (the service calls this only while
        the controller is enabled): count the flush into the window
        and evaluate every ``cadence`` flushes."""
        if flush_id:
            self._window_fids.append(int(flush_id))
        self._since_eval += 1
        if self._since_eval >= self.cadence:
            self.evaluate()

    def arm_soak(self, interval_s: float, runner: Any = None,
                 clock: Any = None) -> None:
        """Arm (or re-arm) the standing chaos gate."""
        from riak_ensemble_tpu import faults

        self.soak = faults.SoakSchedule(interval_s, runner=runner,
                                        clock=clock)

    def evaluate(self) -> List[Dict[str, Any]]:
        """One control-loop evaluation over the window since the last
        one.  Returns the decisions taken (possibly empty).

        Re-entrancy: actuation (a depth change, a soak heartbeat)
        settles in-flight launches, whose settle hooks tick the
        cadence — a nested tick must never start a second evaluation
        under the first one's feet."""
        if self._in_eval:
            return []
        self._in_eval = True
        try:
            return self._evaluate()
        finally:
            self._in_eval = False

    def _evaluate(self) -> List[Dict[str, Any]]:
        import numpy as np

        svc = self.svc
        self.evals += 1
        self._since_eval = 0
        fids, self._window_fids = self._window_fids, []
        fid = fids[-1] if fids else 0
        decisions: List[Dict[str, Any]] = []
        # (a) ack-RTT depth/window tuning — leader-only (a deposed or
        # replica lane must not grow in-flight state), and only where
        # an ack path exists at all
        is_leader = getattr(svc, "is_leader", True)
        if is_leader and getattr(svc, "_links", None):
            samples = obs_spans.SPANS.span_values(
                fids, "leader", "repl_ack")
            decisions += self.tuner.evaluate(svc, samples,
                                             self.journal, fid)
        # (b) tenant-admission guard, off the attribution plane
        if self.guard_enabled:
            ops = np.asarray(svc.tenant_ops, dtype=np.int64)
            if self._last_ops is None or len(self._last_ops) != len(ops):
                window = ops.copy()
            else:
                window = np.maximum(ops - self._last_ops, 0)
            self._last_ops = ops.copy()
            decisions += self.guard.evaluate(svc, window,
                                             self.journal, fid)
        # (c) the standing chaos gate (disarmed unless an interval
        # was set): the soak result is a journaled decision too
        result = self.soak.maybe_run(svc)
        if result is not None:
            decisions.append(self.journal.note(
                "chaos", "wedge_soak_detect_s",
                result.get("detect_s", 0.0) or 0.0,
                flush_id=fid, ok=bool(result.get("ok")),
                result=result))
        return decisions

    # -- export surfaces ----------------------------------------------------

    def collect(self) -> Dict[str, Any]:
        """Registry collector: the ``retpu_autotune_*`` family —
        ALWAYS registered (zeros while off), so a dashboard's queries
        keep their shape when the controller arms."""
        def fam(typ, help, val):
            return obs_registry.family(typ, help, {None: val})

        throttled_rows = sum(len(r) for r in
                             self.guard.throttled.values())
        return {
            "retpu_autotune_enabled": fam(
                "gauge", "1 while the runtime controller actuates "
                "(RETPU_AUTOTUNE)", int(self.enabled)),
            "retpu_autotune_evals_total": fam(
                "counter", "controller evaluations run", self.evals),
            "retpu_autotune_decisions_total": fam(
                "counter", "journaled controller decisions",
                self.journal.total),
            "retpu_autotune_pipeline_depth": fam(
                "gauge", "current launch pipeline depth (the "
                "controller's depth actuator target)",
                int(self.svc.pipeline_depth)),
            "retpu_autotune_repl_window": fam(
                "gauge", "current replication ack window",
                int(getattr(self.svc, "repl_window", 1))),
            "retpu_autotune_ack_rtt_ms": fam(
                "gauge", "last evaluated repl-ack p50 (ms; 0 before "
                "any ack-bearing window)",
                round(self.tuner.last_p50_ms or 0.0, 3)),
            "retpu_autotune_tenant_throttled_rows": fam(
                "gauge", "ensemble rows currently under a "
                "tenant-guard admission cap", throttled_rows),
            "retpu_autotune_soak_runs_total": fam(
                "counter", "standing chaos-gate soaks run",
                self.soak.runs),
            "retpu_autotune_soak_failures_total": fam(
                "counter", "soaks whose wedge-detection assertion "
                "failed", self.soak.failures),
        }

    def health_section(self) -> Dict[str, Any]:
        """The ``health()`` verb's ``controller`` section — the same
        numbers the gauges export, plus the last decision, in one
        poll-safe dict."""
        evs = self.journal.tail(1)
        return {
            "enabled": bool(self.enabled),
            "cadence_flushes": int(self.cadence),
            "evals": int(self.evals),
            "decisions": int(self.journal.total),
            "pipeline_depth": int(self.svc.pipeline_depth),
            "repl_window": int(getattr(self.svc, "repl_window", 1)),
            "ack_rtt_ms": (round(self.tuner.last_p50_ms, 3)
                           if self.tuner.last_p50_ms is not None
                           else None),
            "tenant_throttled": {lbl: list(rows) for lbl, rows
                                 in self.guard.throttled.items()},
            "soak": {
                "interval_s": self.soak.interval_s,
                "runs": self.soak.runs,
                "failures": self.soak.failures,
                "last_ok": (None if self.soak.last is None
                            else bool(self.soak.last.get("ok"))),
            },
            "last_decision": evs[0] if evs else None,
        }

    def flight_section(self) -> List[Dict[str, Any]]:
        """The flight-dump ``controller_decisions`` section: the
        newest journaled decisions, oldest first."""
        return self.journal.tail(16)
