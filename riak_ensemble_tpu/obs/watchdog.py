"""Standing anomaly watchdog over the fleet obs plane.

The fleet surfaces (``obs/fleet.py``) make cross-host evidence
pullable; this module is the leader-side consumer that WALKS it on a
cadence, looking for the three anomaly classes a human would
otherwise only find in a post-mortem:

- **ack-before-apply skew**: a flush whose host quorum settled on
  the leader measurably BEFORE any replica's aligned apply/WAL work
  could have finished — beyond the link's offset bound plus slack.
  Either the clock estimate is broken or an ack path is lying;
  both deserve a journal entry, not silence.
- **persistently slow replica span**: one host's window-median for a
  replica span (``wal_sync``, ``apply``, ``scatter``, ``validate``)
  exceeding ``slow_ratio`` × its own long-run EWMA for
  ``slow_windows`` consecutive evaluations — "replica B's wal_sync
  held the quorum" as a standing detection instead of a dump-reading
  exercise.
- **clock-offset drift**: a link's offset estimate moving more than
  ``drift_ms`` between evaluations (beyond the two bounds) — the
  box-level smell (VM migration, clock step, thermal throttle) that
  silently invalidates every cross-host comparison.

The watchdog NEVER blocks the flush path: each evaluation first
harvests whatever ``obsq`` timeline pulls completed since the last
one, then posts the next round of pulls and returns — responses ride
the PeerLink receiver threads and are consumed a cadence later.
Findings journal through the PR 12 :class:`DecisionJournal` export
discipline: ``retpu_watchdog_*`` gauges (always registered), a
``health()`` ``watchdog`` section, and the flight-dump
``watchdog_findings`` section.  ``RETPU_WATCHDOG=0`` disarms the
standing pull entirely (the fleet A/B's off arm); the verbs stay
available either way.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from riak_ensemble_tpu.obs import controller as obs_controller
from riak_ensemble_tpu.obs import fleet as obs_fleet
from riak_ensemble_tpu.obs import spans as obs_spans
from riak_ensemble_tpu.obs import registry as obs_registry

__all__ = ["AnomalyWatchdog", "enabled", "REPLICA_SPANS"]

#: replica-side spans the slow-host detector tracks
REPLICA_SPANS = ("validate", "apply", "scatter", "rebuild", "wal_sync")


def enabled() -> bool:
    """Whether the standing fleet pull + anomaly walk is armed
    (``RETPU_WATCHDOG``, default on; leader-with-links only either
    way).  Services cache the answer at construction — the bench's
    ``fleet_obs_overhead`` off arm."""
    return os.environ.get("RETPU_WATCHDOG", "1") != "0"


class AnomalyWatchdog:
    """Leader-side fleet anomaly walker (one per ReplicatedService;
    constructed always so its gauge family registers, ticking only
    while armed AND leading with links)."""

    def __init__(self, svc: Any, cadence: Optional[int] = None,
                 slow_ratio: float = 3.0, slow_windows: int = 3,
                 drift_ms: float = 50.0, skew_slack_ms: float = 1.0,
                 max_fids: int = 8,
                 journal_capacity: int = 128) -> None:
        self.svc = svc
        self.enabled = enabled()
        #: evaluation cadence in settled flushes — deliberately the
        #: controller's knob (`RETPU_AUTOTUNE_CADENCE`): the watchdog
        #: is the observe-only sibling of the control loop and shares
        #: its notion of "a window"
        self.cadence = (int(cadence) if cadence is not None
                        else obs_controller.cadence())
        self.slow_ratio = float(slow_ratio)
        self.slow_windows = int(slow_windows)
        self.drift_ms = float(drift_ms)
        self.skew_slack_ms = float(skew_slack_ms)
        self.max_fids = int(max_fids)
        self.journal = obs_controller.DecisionJournal(journal_capacity)
        self.evals = 0
        #: STANDING-pull bookkeeping (exported under
        #: ``source="watchdog"``): timeline pulls this walker posted,
        #: and pulls that completed (or expired) without a usable
        #: payload.  One-off verb/dump pulls count on the service
        #: (``fleet_verb_pulls``, ``source="verb"``) — conflating
        #: them would let a triggered dump on a RETPU_WATCHDOG=0
        #: service look like a standing pull
        self.pulls = 0
        self.pull_failures = 0
        #: finding counts by kind (the labeled counter family)
        self.findings: Dict[str, int] = {
            "ack_apply_skew": 0, "replica_slow_span": 0,
            "clock_drift": 0}
        self._since = 0
        self._window_fids: List[int] = []
        #: in-flight pulls: (link, fids, ticket, posted_mono) —
        #: harvested next evaluation; bounded (one per link per
        #: window) and EXPIRED after ``PULL_EXPIRE_S``: a silent
        #: fault plan discards frames without ever firing their
        #: tickets, and un-expiring orphans would hit the pending
        #: cap and wedge the standing pull past the heal
        self._pending: List[Any] = []
        #: per-(host, span) long-run EWMA seconds + consecutive slow
        #: window streaks
        self._ewma: Dict[Any, float] = {}
        self._streak: Dict[Any, int] = {}
        #: last evaluation's offset estimate per host (drift check)
        self._last_offset: Dict[str, Dict[str, Any]] = {}

    # -- cadence -------------------------------------------------------------

    def tick(self, flush_id: int) -> None:
        """Per-settled-flush hook (leader-side; the service gates on
        armed + leading + links): count the flush, evaluate every
        ``cadence`` flushes.  Never blocks — pulls are posted, their
        responses harvested a window later."""
        if flush_id:
            self._window_fids.append(int(flush_id))
        self._since += 1
        if self._since >= self.cadence:
            self.evaluate()

    #: an in-flight pull older than this is an orphan (a silent
    #: blackhole consumed the frame and the ticket will never fire):
    #: dropped as a failure so the pending cap can't wedge the
    #: standing pull past the heal
    PULL_EXPIRE_S = 60.0

    def evaluate(self) -> List[Dict[str, Any]]:
        svc = self.svc
        self.evals += 1
        self._since = 0
        fids = self._window_fids[-self.max_fids:]
        self._window_fids = []
        out: List[Dict[str, Any]] = []
        # 1) harvest completed pulls from the PREVIOUS window;
        # expire orphans (silent drops never fire their tickets)
        now = time.monotonic()
        still: List[Any] = []
        window: Dict[str, Dict[int, Any]] = {}
        for link, pfids, ticket, posted in self._pending:
            if not ticket.event.is_set():
                if now - posted > self.PULL_EXPIRE_S:
                    self.pull_failures += 1
                    continue
                still.append((link, pfids, ticket, posted))
                continue
            payload = svc._obsq_result(link, ticket)
            if not isinstance(payload, dict):
                self.pull_failures += 1
                continue
            window.setdefault(link.label, {}).update(
                {int(f): tl for f, tl in payload.items()})
        self._pending = still
        if window:
            out += self._analyze(window)
        out += self._check_drift()
        # 2) post this window's pulls (one per connected link; an
        # unanswered pull simply stays pending — next harvest)
        if fids and len(self._pending) < 4 * max(
                len(getattr(svc, "_links", ())), 1):
            for link in getattr(svc, "_links", ()):
                if not link.connected:
                    continue
                t = link.post(("obsq", "timeline", list(fids)))
                self.pulls += 1
                self._pending.append((link, list(fids), t, now))
        return out

    # -- detectors -----------------------------------------------------------

    def _offsets(self) -> Dict[str, Dict[str, Any]]:
        # ONE implementation of the clock section — the service's
        # (fleet answers and these gauges must never drift apart)
        fn = getattr(self.svc, "_clock_section", None)
        return fn() if fn is not None else {}

    def _analyze(self, window: Dict[str, Dict[int, Any]]
                 ) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        offsets = self._offsets()
        span_samples: Dict[Any, List[float]] = {}
        #: per-fid {host: (skew_ms, allowance_ms)} — aggregated
        #: ACROSS hosts before the causality verdict
        skews: Dict[int, Dict[str, Any]] = {}
        for host, by_fid in window.items():
            est = offsets.get(host) or {}
            for fid, tl in by_fid.items():
                if not isinstance(tl, dict) or tl.get("miss"):
                    continue
                s = self._host_skew(fid, tl, est, host)
                if s is not None:
                    skews.setdefault(fid, {})[host] = s
                for role, side in tl.items():
                    if not isinstance(side, dict):
                        continue
                    # only THIS host's own lane counts toward its
                    # samples: in-process replicas answer the shared
                    # process-global store, so a pulled timeline can
                    # carry OTHER lanes' roles too — attributing
                    # those here would dilute a slow host into its
                    # healthy neighbors' baselines
                    if obs_fleet.role_host(str(role), "") != host:
                        continue
                    for name, dur in side.get("spans", ()):
                        if name in REPLICA_SPANS:
                            span_samples.setdefault(
                                (host, name), []).append(float(dur))
        out += self._check_skew(skews)
        out += self._check_slow(span_samples)
        return out

    def _host_skew(self, fid: int, tl: Dict[str, Any],
                   est: Dict[str, Any], host: str):
        """One host's (skew_ms, allowance_ms) for a quorum-confirmed
        flush: its OWN lane's earliest aligned apply anchor minus the
        leader's settle anchor, against the link's offset bound +
        slack; None when either side has no anchor (or the flush
        never confirmed a quorum — an unconfirmed flush has no ack
        to audit).  Roles belonging to other lanes (shared-store
        in-process replicas) are ignored — their anchors live on
        other links' clocks."""
        if "offset_ms" not in est:
            return None
        leader = obs_spans.SPANS.timeline(fid)
        if not isinstance(leader, dict) or leader.get("miss"):
            return None
        lside = leader.get("leader") or {}
        if not lside.get("quorum_ok") or lside.get("t_mono") is None:
            return None
        worst = None
        for role, side in tl.items():
            if not (isinstance(side, dict)
                    and str(role).startswith("replica")):
                continue
            if obs_fleet.role_host(str(role), "") != host:
                continue
            t_r = side.get("t_mono")
            if t_r is None:
                continue
            aligned = float(t_r) - est["offset_ms"] / 1e3
            skew_ms = (aligned - float(lside["t_mono"])) * 1e3
            if worst is None or skew_ms < worst:
                worst = skew_ms
        if worst is None:
            return None
        return (worst, est.get("bound_ms", 0.0) + self.skew_slack_ms)

    def _check_skew(self, skews: Dict[int, Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
        """Causality verdict per flush, over ALL hosts: a finding
        only when EVERY link contributed an anchored skew and every
        one exceeds its allowance — the quorum ack arrived before ANY
        apply could have finished.  A single late host is a healthy
        non-quorum straggler (majority settles don't wait for it),
        never a finding."""
        out: List[Dict[str, Any]] = []
        n_links = len(getattr(self.svc, "_links", ()))
        for fid, per_host in skews.items():
            if len(per_host) < max(n_links, 1):
                continue  # a host we couldn't read may hold the alibi
            if not all(s > a for s, a in per_host.values()):
                continue
            least = min(s for s, _a in per_host.values())
            self.findings["ack_apply_skew"] += 1
            out.append(self.journal.note(
                "watchdog", "ack_apply_skew_ms", least, flush_id=fid,
                hosts={h: {"skew_ms": round(s, 3),
                           "allowance_ms": round(a, 3)}
                       for h, (s, a) in per_host.items()},
                kind="ack_apply_skew"))
        return out

    def _check_slow(self, span_samples: Dict[Any, List[float]]
                    ) -> List[Dict[str, Any]]:
        """Per-(host, span) window median vs the pair's own long-run
        EWMA; ``slow_windows`` consecutive violations journal."""
        out: List[Dict[str, Any]] = []
        for key, vals in span_samples.items():
            vals.sort()
            med = vals[len(vals) // 2]
            base = self._ewma.get(key)
            if base is None:
                self._ewma[key] = med
                continue
            if base > 0.0 and med > self.slow_ratio * base:
                streak = self._streak.get(key, 0) + 1
                self._streak[key] = streak
                # a persistent offender re-journals once per streak
                # crossing, then every slow_windows windows — bounded
                # noise during a long incident, never silence
                if streak % self.slow_windows == 0:
                    self.findings["replica_slow_span"] += 1
                    host, span = key
                    out.append(self.journal.note(
                        "watchdog", "span_slow_ratio",
                        med / base, host=host, span=span,
                        window_p50_ms=round(med * 1e3, 3),
                        baseline_ms=round(base * 1e3, 3),
                        streak=streak, kind="replica_slow_span"))
            else:
                self._streak.pop(key, None)
                # only HEALTHY windows update the baseline: folding a
                # slow window in would normalize the very regression
                # being detected
                self._ewma[key] = 0.8 * base + 0.2 * med
        return out

    def _check_drift(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        current = self._offsets()
        for host, est in current.items():
            prev = self._last_offset.get(host)
            if (prev and "offset_ms" in prev
                    and "offset_ms" in est):
                delta = abs(est["offset_ms"] - prev["offset_ms"])
                allowance = max(
                    self.drift_ms,
                    est.get("bound_ms", 0.0)
                    + prev.get("bound_ms", 0.0))
                if delta > allowance:
                    self.findings["clock_drift"] += 1
                    out.append(self.journal.note(
                        "watchdog", "clock_offset_drift_ms", delta,
                        host=host, kind="clock_drift",
                        offset_ms=est["offset_ms"],
                        prev_offset_ms=prev["offset_ms"]))
        self._last_offset = current
        return out

    # -- export surfaces -----------------------------------------------------

    def collect(self) -> Dict[str, Any]:
        """Registry collector: the ``retpu_watchdog_*`` + per-link
        clock families — always registered (empty/zero while the
        group has no links), the fault-gauge discipline."""
        offs = self._offsets()
        return {
            "retpu_watchdog_evals_total": obs_registry.family(
                "counter", "fleet watchdog evaluations run",
                {None: self.evals}),
            "retpu_watchdog_findings_total": obs_registry.family(
                "counter", "journaled watchdog anomaly findings",
                dict(self.findings), label="kind"),
            "retpu_fleet_pulls_total": obs_registry.family(
                "counter", "obsq sideband pulls posted to replica "
                "links (watchdog = the standing walker; verb = "
                "one-off fleet verbs and correlated dumps)",
                {"watchdog": self.pulls,
                 "verb": getattr(self.svc, "fleet_verb_pulls", 0)},
                label="source"),
            "retpu_fleet_pull_failures_total": obs_registry.family(
                "counter", "obsq pulls that completed (or expired) "
                "without a usable payload",
                {"watchdog": self.pull_failures,
                 "verb": getattr(self.svc,
                                 "fleet_verb_pull_failures", 0)},
                label="source"),
            # label "peer", NOT "host": the fleet scrape injects a
            # host="<answering process>" label into every sample,
            # and a second label under the same name would make
            # Prometheus reject the whole merged document
            "retpu_clock_offset_ms": obs_registry.family(
                "gauge", "estimated per-link clock offset (replica "
                "monotonic minus leader monotonic)",
                {h: e["offset_ms"] for h, e in offs.items()
                 if "offset_ms" in e}, label="peer"),
            "retpu_clock_offset_bound_ms": obs_registry.family(
                "gauge", "uncertainty bound the offset estimate is "
                "honest to (half best round-trip + drift allowance)",
                {h: e["bound_ms"] for h, e in offs.items()
                 if "bound_ms" in e}, label="peer"),
        }

    def health_section(self) -> Dict[str, Any]:
        evs = self.journal.tail(1)
        return {
            "enabled": bool(self.enabled),
            "cadence_flushes": int(self.cadence),
            "evals": int(self.evals),
            "pulls": int(self.pulls),
            "pull_failures": int(self.pull_failures),
            "findings": dict(self.findings),
            "clock": self._offsets(),
            "last_finding": evs[0] if evs else None,
        }

    def flight_section(self) -> List[Dict[str, Any]]:
        """The flight-dump ``watchdog_findings`` section."""
        return self.journal.tail(16)
