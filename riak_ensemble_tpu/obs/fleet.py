"""Fleet-scope observability: clock offsets, merged scrapes, aligned
cross-host timelines.

PRs 6/8/11 built a complete obs plane — metrics, health,
``obs.timeline(fid)``, flight dumps, the controller journal — but
every surface is PER PROCESS: diagnosing a 3-host replicated group
meant ssh-ing each host and eyeballing three unaligned monotonic
clocks.  This module is the host-joining half:

- :class:`ClockOffset` — per-link offset estimation in the NTP
  style: every ``obsq`` sideband round-trip over a
  :class:`~riak_ensemble_tpu.parallel.repgroup.PeerLink` yields
  ``(t0, t_remote, t1)`` monotonic stamps (send, remote handle,
  response arrival); the midpoint estimate ``t_remote - (t0+t1)/2``
  is correct to within ``(t1-t0)/2`` REGARDLESS of path asymmetry —
  the classic bound — so span alignment can always be read against
  an honest uncertainty.  A bounded sample window smooths over
  queue-dwell outliers (the best sample is the one with the smallest
  bound, widened by a drift allowance as it ages).
- :func:`merge_prometheus` — fold several hosts' Prometheus text
  renders into ONE scrape document: families grouped (one
  ``# TYPE`` per family, the exposition-format requirement), every
  sample gaining a ``host="..."`` label, so one leader scrape
  answers for the whole group.
- :func:`align_timeline` — the cross-host ``obs.timeline(fid)``:
  each role's span list is anchored at its recorder's monotonic
  stamp (``t_mono``, stamped at record time — spans lay out
  sequentially ENDING there, the same ordinal-within-role layout
  ``tools/trace_export.py`` documents), replica anchors are mapped
  onto the LEADER's clock through the link offsets, and the result
  is one axis with per-role ``(name, start_s, dur_s)`` triples plus
  the offset bounds the alignment is honest to.

Nothing here touches the wire or a service directly — repgroup owns
the ``obsq`` request plumbing and svcnode the client verbs; this
module is pure data plumbing so every piece is unit-testable without
a socket.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ClockOffset", "merge_prometheus", "inject_host_label",
           "align_timeline", "role_host"]


class ClockOffset:
    """NTP-midpoint offset estimator for one leader→replica link.

    ``update(t0, t_remote, t1)`` feeds one sideband round-trip: the
    request's wire-send monotonic stamp, the remote's monotonic stamp
    while handling it, and the response's arrival stamp (all three
    already exist on the PeerLink ticket path).  The offset estimate
    ``t_remote - (t0 + t1) / 2`` assumes a symmetric path; its error
    is bounded by ``(t1 - t0) / 2`` for ANY asymmetry (the remote
    stamp provably lies inside the [t0, t1] window), which is the
    bound every consumer gets alongside the estimate.

    Smoothing is drift-window best-sample: keep the last ``window``
    samples, widen each sample's bound by ``drift * age`` (monotonic
    clocks on distinct hosts drift apart — NTP-disciplined boxes stay
    under ~50 ppm; the default allowance is generous), and answer the
    sample with the smallest widened bound.  A burst of queue-dwell
    outliers (big ``t1 - t0``) therefore never displaces a recent
    tight sample, and a link that stops being pulled honestly reports
    a growing bound instead of a stale certainty.
    """

    #: drift allowance applied per second of sample age (200 ppm —
    #: an order of magnitude above NTP-disciplined reality, so the
    #: widened bound errs toward honesty)
    DRIFT = 200e-6

    def __init__(self, window: int = 64) -> None:
        #: (t_mid_local, offset_s, half_rtt_s), newest last.
        #: Lock-guarded: updates land from settle/harvest/executor
        #: threads while scrape threads iterate for estimates — an
        #: unguarded deque raises "mutated during iteration" exactly
        #: when the system is busy (both paths are cold)
        self._samples: "deque[Tuple[float, float, float]]" = \
            deque(maxlen=window)
        self._lock = threading.Lock()
        #: total round-trips folded in (monotone; survives windowing)
        self.samples = 0

    def update(self, t0: float, t_remote: float, t1: float) -> None:
        """Fold one round-trip's stamps; nonsensical windows
        (``t1 < t0``) are dropped rather than poisoning the window."""
        if t1 < t0:
            return
        with self._lock:
            self._samples.append(((t0 + t1) / 2.0,
                                  t_remote - (t0 + t1) / 2.0,
                                  (t1 - t0) / 2.0))
            self.samples += 1

    def estimate(self, now: Optional[float] = None
                 ) -> Optional[Tuple[float, float]]:
        """``(offset_s, bound_s)`` — remote_clock − local_clock, and
        the uncertainty the estimate is honest to — or None before
        any sample.  The winning sample is the one with the smallest
        age-widened bound."""
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return None
        now = time.monotonic() if now is None else now
        best: Optional[Tuple[float, float]] = None
        for t_mid, off, half in samples:
            bound = half + max(0.0, now - t_mid) * self.DRIFT
            if best is None or bound < best[1]:
                best = (off, bound)
        return best

    def section(self) -> Dict[str, Any]:
        """Wire-encodable summary (the ``clock`` section of fleet
        answers): offset/bound in ms + sample count, or a bare
        ``{"samples": 0}`` before any round-trip."""
        est = self.estimate()
        if est is None:
            return {"samples": 0}
        return {"offset_ms": round(est[0] * 1e3, 4),
                "bound_ms": round(est[1] * 1e3, 4),
                "samples": int(self.samples)}


# -- Prometheus merge --------------------------------------------------------

def _label_end(line: str, start: int) -> int:
    """Index of the ``}`` closing the labelset opened at ``start``
    (which must point at ``{``), honoring quoted label values with
    escapes — a tenant label may legally contain ``}``."""
    i = start + 1
    in_q = False
    while i < len(line):
        c = line[i]
        if in_q:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_q = False
        elif c == '"':
            in_q = True
        elif c == "}":
            return i
        i += 1
    raise ValueError(f"unterminated labelset: {line!r}")


def _esc(label: Any) -> str:
    return (str(label).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def inject_host_label(line: str, host: str) -> str:
    """One sample line with ``host="..."`` prepended to its labelset
    (created when absent).  Header/comment lines — and samples
    ALREADY carrying a ``host`` label (a re-merged fleet section) —
    pass through: duplicate label names make Prometheus reject the
    whole document.  (The check is exact: a label VALUE can never
    contain an unescaped quote, so the raw substring ``host="`` only
    ever matches the label NAME.)"""
    if not line or line.startswith("#"):
        return line
    brace = line.find("{")
    space = line.find(" ")
    hsel = f'host="{_esc(host)}"'
    if brace != -1 and (space == -1 or brace < space):
        end = _label_end(line, brace)
        inner = line[brace + 1:end]
        if inner.startswith('host="') or ',host="' in inner:
            return line  # already host-labeled: idempotent merge
        sep = "," if inner else ""
        return f"{line[:brace + 1]}{hsel}{sep}{line[brace + 1:]}"
    if space == -1:
        raise ValueError(f"not a sample line: {line!r}")
    return f"{line[:space]}{{{hsel}}}{line[space:]}"


def _family_of(sample_name: str) -> str:
    """The family a sample line's metric name belongs to (histogram
    series render as ``<fam>_bucket``/``_sum``/``_count``)."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            return sample_name[:-len(suffix)]
    return sample_name


def merge_prometheus(sections: Dict[str, Optional[str]]) -> str:
    """Fold per-host Prometheus text renders (``{host_label: text}``;
    None values — unreachable hosts — are skipped) into one
    exposition document: every sample gains its host's ``host=``
    label, and families sharing a name across hosts merge under ONE
    ``# HELP``/``# TYPE`` header (first writer wins — the format
    forbids repeated TYPE lines), ordered by first appearance."""
    order: List[str] = []
    fams: Dict[str, Dict[str, Any]] = {}

    def fam_for(name: str) -> Dict[str, Any]:
        fam = fams.get(name)
        if fam is None:
            fam = fams[name] = {"headers": [], "hdr_host": None,
                                "samples": []}
            order.append(name)
        return fam

    for host in sorted(sections):
        text = sections[host]
        if not text:
            continue
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                fam = fam_for(line.split(None, 3)[2])
                # one header set per family: first contributing host
                # wins (repeated # TYPE lines are format violations)
                if fam["hdr_host"] in (None, host):
                    fam["hdr_host"] = host
                    fam["headers"].append(line)
                continue
            name = _family_of(line.split("{", 1)[0].split(" ", 1)[0])
            fam_for(name)["samples"].append(
                inject_host_label(line, host))
    out: List[str] = []
    for name in order:
        fam = fams[name]
        out.extend(fam["headers"])
        out.extend(fam["samples"])
    return "\n".join(out) + "\n"


# -- cross-host timeline alignment -------------------------------------------

def role_host(role: str, self_label: str) -> Optional[str]:
    """The host label a span-store role records under: the leader's
    own label for ``"leader"``, the lane tag for
    ``"replica@host:port"``, None for an untagged ``"replica"`` (a
    single-lane test store — alignment then has no offset to apply)."""
    if role == "leader":
        return self_label
    if role.startswith("replica@"):
        return role[len("replica@"):]
    return None


def align_timeline(flush_id: int, sides: Dict[str, Any],
                   offsets: Dict[str, Dict[str, Any]],
                   self_label: str) -> Dict[str, Any]:
    """One flush's merged role records on ONE (leader-clock) axis.

    ``sides`` is the merged ``SpanStore.timeline`` shape
    (``role -> {"spans": [...], ...info}``) with replica roles pulled
    from their own hosts' stores; ``offsets`` maps host label to a
    :meth:`ClockOffset.section` dict.  Each role's spans lay out
    sequentially ENDING at its ``t_mono`` anchor (the record-time
    stamp both record sites attach) mapped onto the leader clock;
    roles without an anchor (legacy records) report ``aligned:
    False`` and anchor at the axis origin.  Starts are re-based so
    the earliest aligned span starts at 0 (``base_s`` carries the
    subtracted leader-clock value)."""
    roles: Dict[str, Any] = {}
    ends: Dict[str, Optional[float]] = {}
    for role, side in sides.items():
        if role == "flush_id" or not isinstance(side, dict):
            continue
        host = role_host(role, self_label)
        t_mono = side.get("t_mono")
        aligned_end: Optional[float] = None
        bound_ms = 0.0
        if t_mono is not None:
            aligned_end = float(t_mono)
            if role != "leader":
                est = offsets.get(host) if host else None
                if est and "offset_ms" in est:
                    aligned_end -= est["offset_ms"] / 1e3
                    bound_ms = float(est.get("bound_ms", 0.0))
                else:
                    aligned_end = None  # no offset: can't place it
        ends[role] = aligned_end
        roles[role] = {"host": host, "bound_ms": bound_ms,
                       "aligned": aligned_end is not None,
                       "side": side}
    # axis origin: earliest aligned span start (end − role total)
    starts = []
    for role, info in roles.items():
        if ends[role] is None:
            continue
        total = sum(max(float(d), 0.0)
                    for _n, d in info["side"].get("spans", []))
        starts.append(ends[role] - total)
    base = min(starts) if starts else 0.0
    out_roles: Dict[str, Any] = {}
    for role, info in roles.items():
        side = info.pop("side")
        spans = side.get("spans", [])
        total = sum(max(float(d), 0.0) for _n, d in spans)
        t = (ends[role] - base - total) if ends[role] is not None \
            else 0.0
        laid: List[List[Any]] = []
        for name, dur in spans:
            d = max(float(dur), 0.0)
            laid.append([str(name), round(t, 6), round(d, 6)])
            t += d
        info["spans"] = laid
        info["end_s"] = (round(ends[role] - base, 6)
                         if ends[role] is not None else None)
        info.update({k: v for k, v in side.items()
                     if k not in ("spans", "t_mono")})
        out_roles[role] = info
    return {
        "flush_id": int(flush_id),
        "schema": "retpu-fleet-timeline-v1",
        "base_s": round(base, 6),
        "clock": dict(offsets),
        "roles": out_roles,
    }
