"""Box fingerprint: the environment a measurement actually ran in.

The r4→r5 mixed-rung comparison went sideways because two rounds'
numbers were silently captured on differently-loaded boxes (the same
runner executed r5's mixed rung at ~4× r4's per-batch time).  Every
flight-recorder dump and every bench JSON now embeds this
fingerprint, so a cross-round delta can be checked against the box
before it is believed.

Static fields (host, cpu count, versions, knobs) are cached;
load-dependent fields (loadavg) are re-read per call.  jax/jaxlib
versions come from package metadata, NOT ``import jax`` — the
fingerprint must never be the thing that initializes a backend.
"""

from __future__ import annotations

import os
import platform
import socket
import sys
from typing import Any, Dict

__all__ = ["box_fingerprint"]

_static: Dict[str, Any] = {}


def _pkg_version(name: str) -> str:
    try:
        from importlib.metadata import version
        return version(name)
    except Exception:
        return "unknown"


def box_fingerprint() -> Dict[str, Any]:
    """A plain JSON-able dict identifying the box + software + knob
    state.  Cheap after the first call."""
    if not _static:
        _static.update({
            "schema": "retpu-box-fingerprint-v1",
            "hostname": socket.gethostname(),
            "platform": platform.platform(),
            "machine": platform.machine(),
            "python": sys.version.split()[0],
            "cpu_count": os.cpu_count(),
            "jax": _pkg_version("jax"),
            "jaxlib": _pkg_version("jaxlib"),
            "numpy": _pkg_version("numpy"),
        })
    out = dict(_static)
    try:
        la1, la5, la15 = os.getloadavg()
        out["loadavg"] = [round(la1, 2), round(la5, 2),
                          round(la15, 2)]
    except (OSError, AttributeError):
        out["loadavg"] = None
    out["jax_platforms_env"] = os.environ.get("JAX_PLATFORMS")
    out["retpu_knobs"] = {k: v for k, v in os.environ.items()
                          if k.startswith("RETPU_")}
    return out
