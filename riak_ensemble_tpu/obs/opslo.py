"""Per-op SLO tracing: client-perceived latency in bounded numpy
slab rings.

PR 6's spans answer "where did FLUSH N's time go"; the north star is
judged by what a *client* sees, which is a per-OP quantity: an op's
life is submit (the API call starts assigning slots/handles) →
enqueue (it entered the service queue) → flush-join (a flush took it
and stamped it with the launch's ``flush_id``) → settle (the launch's
result planes — and, on a replicated leader, the host quorum — are
in) → ack (its future resolved).  The queue wait before the join and
the quorum wait after the settle are exactly the components the
flush-granular record cannot attribute to a caller.

The representation keeps the tenant-ledger discipline: NO per-op
dicts.  One ring row per taken ENTRY (a ``kput_many`` batch is one
row weighted by its op count — every op in it shares the same five
stamps by construction), parallel numpy arrays for the five
timestamps plus kind/ensemble/weight/flush_id, capacity a power of
two, old rows silently overwritten.  Rows materialize at FLUSH-JOIN
time in one vectorized pass per flush (:meth:`OpSloRing.open_rows`
— the submit/enqueue timestamps ride the pending entry itself, so
the enqueue hot path pays zero ring work), and every later stage is
one fancy-index assignment.  The ring itself holds no histograms —
the service folds the latencies :meth:`OpSloRing.settle_ack` returns
into its registry's ``retpu_op_latency_ms`` (labeled by kind) and
its per-tenant ``[E, B]`` plane, so there is exactly ONE fold target
per dimension and the surfaces cannot drift.

Joins: rows carry the PR 6 ``flush_id``, so ``obs.timeline(fid)``
resolves an op's client-perceived tail down to its stage split
("this op's 80 ms was 60 ms queue_wait + 15 ms device") next to the
flush's own span record — the service attaches each flush's slowest
rows to the span store under ``slow_ops``.

``RETPU_SLO_RING`` sizes the ring (default 4096 rows; rounded up to
a power of two).  ``RETPU_OBS=0`` disables stamping entirely (the
service never constructs record calls).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["OpSloRing", "KIND_NAMES", "KIND_FAST_READ", "STAGES",
           "ring_capacity"]

#: ring kind codes: 0..4 are the engine op codes verbatim
#: (noop/get/put/cas/rmw — see ops/engine.py); 5 is the synthetic
#: mirror-served leased read, which never rides a flush.
KIND_NAMES: Tuple[str, ...] = ("noop", "get", "put", "cas", "rmw",
                               "get_fast")
KIND_FAST_READ = 5

#: the stage-split names, in life order (durations between adjacent
#: stamps): assign = submit→enqueue (slot/handle allocation),
#: queue_wait = enqueue→join, flush = join→settle (device round +
#: pipeline + host-quorum wait), ack = settle→ack (future fan-out).
STAGES: Tuple[str, ...] = ("assign", "queue_wait", "flush", "ack")


def ring_capacity(default: int = 4096) -> int:
    """``RETPU_SLO_RING`` rounded up to a power of two (floor 64).
    ``0`` disables per-op tracing alone (the rest of the obs plane
    stays live — the bench's op-trace A/B arm) and returns 0."""
    try:
        n = int(os.environ.get("RETPU_SLO_RING", default))
    except ValueError:
        n = default
    if n <= 0:
        return 0
    n = max(64, n)
    cap = 1
    while cap < n:
        cap <<= 1
    return cap


class OpSloRing:
    """Bounded per-service ring of per-entry SLO stamps.

    Rows are identified by a monotonically increasing id; the
    physical slot is ``id & (cap - 1)``.  A row overwritten before
    its ack is simply lost (bounded-ring semantics); the ack-side
    fold guards against reading a recycled row by requiring its
    stamps to be monotone.
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        cap = ring_capacity() if capacity is None else int(capacity)
        assert cap & (cap - 1) == 0, "ring capacity must be pow2"
        self.cap = cap
        self.mask = cap - 1
        z = lambda dt: np.zeros((cap,), dt)  # noqa: E731
        self.t_submit = z(np.float64)
        self.t_enq = z(np.float64)
        self.t_join = z(np.float64)
        self.t_settle = z(np.float64)
        self.t_ack = z(np.float64)
        self.kind = z(np.int16)
        self.ens = z(np.int32)
        self.n = z(np.int32)
        self.fid = z(np.int64)
        self._next = 0

    # -- flush side ---------------------------------------------------------

    def record_flush(self, kinds, enss, ns, t_subs, t_enqs, fid: int,
                     t_join: float, t_settle: float, t_ack: float
                     ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Record one settled flush's taken entries in ONE vectorized
        pass: rows open, all five stamps land, and the per-row
        client-perceived latency comes back as ``(physical_rows,
        latency_ms)`` for the service to fold into its per-kind /
        per-tenant histograms.

        Rows materialize at SETTLE time, not at enqueue or join: the
        per-entry timestamps ride the pending entry itself (``t_sub``
        at the API call, ``t_enq`` at push — both already there for
        the queue-wait mark) and the flush-level join/settle/ack
        times are shared by every entry of the flush, so the enqueue
        and flush hot paths pay ZERO ring work and the whole flush
        costs nine fancy-index assignments (the measured difference
        between ~0 and a real keyed-rung overhead).  Entries that
        never settle (failed at enqueue, abandoned launches) never
        occupy a row; a batch split across flushes is two entries
        recording under their own flush ids, weights conserved.

        The column inputs accept plain sequences OR the service's
        enqueue-time pending-slab columns verbatim (the slab enqueue
        path collects kind/ens/weight/t_sub/t_enq per entry while
        building its op lanes — docs/ARCHITECTURE.md §12): stamps
        keep riding even though the entries' futures resolve from
        completion-slab rows rather than per-op fan-out."""
        n = len(kinds)
        if not n:
            return None
        base = self._next
        self._next = base + n
        rows = np.arange(base, base + n, dtype=np.int64) & self.mask
        te = np.asarray(t_enqs)
        ts = np.asarray(t_subs)
        ts = np.where(ts > 0.0, ts, te)  # scalar ops: submit = enqueue
        self.t_submit[rows] = ts
        self.t_enq[rows] = te
        self.t_join[rows] = t_join
        self.t_settle[rows] = t_settle
        self.t_ack[rows] = t_ack
        self.kind[rows] = kinds
        self.ens[rows] = enss
        self.n[rows] = ns
        self.fid[rows] = fid
        return rows, (t_ack - ts) * 1e3

    # -- query side ---------------------------------------------------------

    def row_view(self, row_id: int) -> Dict[str, Any]:
        """One row's stamps + derived stage split (test/debug
        surface)."""
        return self._row_dict(row_id & self.mask)

    def _row_dict(self, r: int) -> Dict[str, Any]:
        sub, enq = self.t_submit[r], self.t_enq[r]
        joi, stl, ack = self.t_join[r], self.t_settle[r], self.t_ack[r]
        stages = {
            "assign": max(0.0, (enq - sub) * 1e3),
            "queue_wait": max(0.0, (joi - enq) * 1e3) if joi else 0.0,
            "flush": max(0.0, (stl - joi) * 1e3) if stl else 0.0,
            "ack": max(0.0, (ack - stl) * 1e3) if ack else 0.0,
        }
        return {  # plain Python scalars: rides the wire codec / JSON
            "kind": KIND_NAMES[int(self.kind[r])],
            "ens": int(self.ens[r]),
            "n": int(self.n[r]),
            "flush_id": int(self.fid[r]),
            "ms": (round(max(0.0, float(ack - sub)) * 1e3, 3)
                   if ack else None),
            "stages_ms": {k: round(float(v), 3)
                          for k, v in stages.items()},
        }

    def slowest(self, top: int = 5) -> List[Dict[str, Any]]:
        """The ``top`` slowest ACKED rows still in the ring (slowest
        first), each with its stage split and flush id — the flight
        dump's per-op tail section.  One O(cap) numpy scan; export
        time only."""
        lat = np.where(self.t_ack > 0.0,
                       self.t_ack - self.t_submit, -1.0)
        if not (lat > 0.0).any():
            return []
        order = np.argsort(lat)[::-1][:top]
        return [self._row_dict(int(r)) for r in order
                if lat[r] > 0.0]
