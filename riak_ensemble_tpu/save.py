"""Paranoid blob persistence: 4 CRC-framed copies across 2 files.

Mirrors ``src/riak_ensemble_save.erl``: each file holds
``[CRC:32][Size:32][Data]`` (forward copy) followed by
``[Data][CRC:32][Size:32]`` (trailing copy, read back-to-front); the
same image is written to ``<file>`` and ``<file>.backup``
(save.erl:31-47).  Read tries forward copy, trailing copy, then the
backup file (save.erl:49-98).  Writes go through tmp+fsync+rename with
read-back verification (riak_ensemble_util:replace_file, util.erl:36-50).
"""

from __future__ import annotations

import os
import zlib
from typing import Optional


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _replace_file(path: str, payload: bytes) -> None:
    """tmp + fsync + rename + read-back verify (util.erl:36-50)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)
    with open(path, "rb") as f:
        assert f.read() == payload, f"read-back verify failed for {path}"


def write(path: str, data: bytes) -> None:
    meta = _crc(data).to_bytes(4, "big") + len(data).to_bytes(4, "big")
    payload = meta + data + data + meta
    _replace_file(path, payload)
    _replace_file(path + ".backup", payload)


def _safe_read(raw: bytes) -> Optional[bytes]:
    # Forward copy: CRC, size, data.
    if len(raw) >= 8:
        crc = int.from_bytes(raw[0:4], "big")
        size = int.from_bytes(raw[4:8], "big")
        data = raw[8:8 + size]
        if len(data) == size and _crc(data) == crc:
            return data
    # Trailing copy: ...data, CRC, size at the very end.
    if len(raw) > 8:
        crc = int.from_bytes(raw[-8:-4], "big")
        size = int.from_bytes(raw[-4:], "big")
        if size <= len(raw) - 8:
            data = raw[-8 - size:-8]
            if _crc(data) == crc:
                return data
    return None


def read(path: str) -> Optional[bytes]:
    for p in (path, path + ".backup"):
        try:
            with open(p, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        data = _safe_read(raw)
        if data is not None:
            return data
    return None
