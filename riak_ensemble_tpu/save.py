"""Paranoid blob persistence: 4 CRC-framed copies across 2 files.

Mirrors ``src/riak_ensemble_save.erl``: each file holds
``[CRC:32][Size:32][Data]`` (forward copy) followed by
``[Data][CRC:32][Size:32]`` (trailing copy, read back-to-front); the
same image is written to ``<file>`` and ``<file>.backup``
(save.erl:31-47).  Read tries forward copy, trailing copy, then the
backup file (save.erl:49-98).  Writes go through tmp+fsync+rename with
read-back verification (riak_ensemble_util:replace_file, util.erl:36-50),
then fsync the parent DIRECTORY — a rename without a directory fsync is
not crash-durable on ext4/xfs (docs/ARCHITECTURE.md §15).

This is also a seam of the storage fault plane (§15): every write
consults the ``ckpt`` path class (injected EIO/ENOSPC/torn writes),
every read passes the bit-flip corruption filter BEFORE the CRC check
(so an injected silent corruption must be caught by the 4-copy
format, never returned), and callers persisting checkpoint state pass
``crash_class="ckpt"`` to arm the ``ckpt_tmp_write``/``ckpt_rename``
crash points.
"""

from __future__ import annotations

import errno as _errno
import os
import zlib
from typing import Optional

from riak_ensemble_tpu import faults


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed/created entry inside it
    survives power loss.  Platforms that refuse O_RDONLY directory
    fds (or fsync on them: EINVAL/ENOTSUP/EBADF...) degrade to the
    pre-round-15 behavior rather than failing the write — but the
    REAL bad-disk errnos (EIO/ENOSPC) re-raise: swallowing them
    would report a rename durable that the dying disk never made so
    (review r15), defeating the §15 degradation signal."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError as exc:
        if exc.errno in (_errno.EIO, _errno.ENOSPC):
            raise
        return
    try:
        os.fsync(fd)
    except OSError as exc:
        if exc.errno in (_errno.EIO, _errno.ENOSPC):
            raise
    finally:
        os.close(fd)


def _replace_file(path: str, payload: bytes,
                  crash_class: Optional[str] = None) -> None:
    """tmp + fsync + rename + dir fsync + read-back verify
    (util.erl:36-50).  ``crash_class`` arms the two checkpoint crash
    points around the rename barrier."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    faults.storage_raise("ckpt", "write")
    tmp = path + ".tmp"
    cut = faults.torn_limit("ckpt")
    with open(tmp, "wb") as f:
        f.write(payload if cut is None else payload[:cut])
        f.flush()
        faults.storage_raise("ckpt", "fsync")
        os.fsync(f.fileno())
    if cut is not None:
        raise OSError(_errno.EIO,
                      f"injected torn checkpoint write at byte {cut}")
    if crash_class:
        faults.crashpoint(crash_class + "_tmp_write")
    os.rename(tmp, path)
    fsync_dir(os.path.dirname(path))
    if crash_class:
        faults.crashpoint(crash_class + "_rename")
    with open(path, "rb") as f:
        assert f.read() == payload, f"read-back verify failed for {path}"


def write(path: str, data: bytes,
          crash_class: Optional[str] = None) -> None:
    meta = _crc(data).to_bytes(4, "big") + len(data).to_bytes(4, "big")
    payload = meta + data + data + meta
    _replace_file(path, payload, crash_class)
    _replace_file(path + ".backup", payload, crash_class)


def _safe_read(raw: bytes) -> Optional[bytes]:
    # Forward copy: CRC, size, data.
    if len(raw) >= 8:
        crc = int.from_bytes(raw[0:4], "big")
        size = int.from_bytes(raw[4:8], "big")
        data = raw[8:8 + size]
        if len(data) == size and _crc(data) == crc:
            return data
    # Trailing copy: ...data, CRC, size at the very end.
    if len(raw) > 8:
        crc = int.from_bytes(raw[-8:-4], "big")
        size = int.from_bytes(raw[-4:], "big")
        if size <= len(raw) - 8:
            data = raw[-8 - size:-8]
            if _crc(data) == crc:
                return data
    return None


def read(path: str) -> Optional[bytes]:
    for p in (path, path + ".backup"):
        try:
            with open(p, "rb") as f:
                raw = f.read()
        except OSError:
            continue
        data = _safe_read(faults.read_filter("ckpt", raw))
        if data is not None:
            return data
    return None
