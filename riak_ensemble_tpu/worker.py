"""Per-peer async worker pool for K/V FSMs.

Mirrors ``src/riak_ensemble_peer_worker.erl`` + the peer's worker
management (``riak_ensemble_peer.erl:1220-1265``):

- Work is routed by ``hash(key) % n_workers`` (``async/3``,
  peer.erl:1220-1225) — same-key operations serialize on one worker,
  distinct keys run concurrently.
- Each worker runs one K/V FSM generator at a time, FIFO.
- ``pause``/``unpause`` is the barrier used while a view change
  commits (peer_worker.erl:53-68); paused workers finish nothing until
  unpaused.
- ``reset`` (leader step-down, peer.erl:1247-1259) kills in-flight
  FSMs and drops queued ones — a blocked FSM's client request dies with
  it and surfaces as a client timeout, exactly as in the reference.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Generator, Optional

from riak_ensemble_tpu.runtime import Future, Runtime, Task


class Worker:
    def __init__(self, runtime: Runtime) -> None:
        self.runtime = runtime
        self.queue: deque = deque()
        self.current: Optional[Task] = None
        self.paused: Optional[Future] = None

    def submit(self, genfunc: Callable[[], Generator]) -> None:
        self.queue.append(genfunc)
        self._pump()

    def _pump(self) -> None:
        if self.current is not None and self.current.alive:
            return
        if self.paused is not None or not self.queue:
            return
        genfunc = self.queue.popleft()
        self.current = self.runtime.spawn_task(self._wrap(genfunc), "kv-fsm")

    def _wrap(self, genfunc):
        try:
            yield from genfunc()
        finally:
            self.current = None
            self.runtime.defer(self._pump)

    def pause(self) -> None:
        if self.paused is None:
            self.paused = Future()

    def unpause(self) -> None:
        if self.paused is not None:
            fut, self.paused = self.paused, None
            fut.resolve(None)
            self._pump()

    def reset(self) -> None:
        """Kill in-flight FSM and drop the queue (reset_workers)."""
        self.queue.clear()
        if self.current is not None:
            self.current.kill()
            self.current = None
        self.paused = None


class WorkerPool:
    def __init__(self, runtime: Runtime, n_workers: int) -> None:
        self.runtime = runtime
        self.workers = [Worker(runtime) for _ in range(n_workers)]

    def async_(self, key, genfunc) -> None:
        """Route by key hash (peer.erl:1220-1225); crc32 keeps the
        partition stable across processes (python hash() is seeded)."""
        import zlib
        idx = zlib.crc32(repr(key).encode()) % len(self.workers)
        self.workers[idx].submit(genfunc)

    def pause(self) -> None:
        for w in self.workers:
            w.pause()

    def unpause(self) -> None:
        for w in self.workers:
            w.unpause()

    def reset(self) -> None:
        for w in self.workers:
            w.reset()
