"""Chrome-trace / Perfetto export of flush timelines + controller
decisions.

``obs.timeline(fid)`` answers one flush's joined leader + replica
span record as a dict; this tool renders MANY of them — plus the
runtime controller's decision journal — as a Chrome trace-event JSON
(the ``chrome://tracing`` / Perfetto ``traceEvents`` array format),
so "where did the last N flushes' time go, and when did the
controller move a knob" becomes a picture instead of a dict-reading
exercise.

Timeline semantics (honest, documented): span records carry
DURATIONS, not absolute start stamps — the store is
allocation-free on the hot path by design.  The export therefore
lays each flush's spans out SEQUENTIALLY per role from a per-flush
base tick, and advances the base by the flush's widest role before
the next flush: within a flush, every span's extent is
measurement-accurate and roles align at the flush base; ACROSS
flushes the spacing is ordinal (flush order), not wall-clock.
Controller journal events render as instant events on a
``controller`` track at the base tick of the flush they were
journaled against.

Two entry points:

- In-process API (tests, bench, a REPL next to a live service):
  ``trace_events(fids)`` / ``export(path, fids, decisions=...)``
  read the process-global span store directly.
- CLI over a flight-recorder dump (the cross-process path — dumps
  are JSON files, the span store is not)::

      python tools/trace_export.py --flight-dump dump.json \
          -o trace.json

  renders the dump's per-flush ring records (their latency marks
  are the same spans, minus replica sides) and its
  ``controller_decisions`` section; a correlated (schema v4) dump's
  per-host fleet sections render as additional per-host tracks.

Round 13 adds the FLEET path: :func:`fleet_trace_events` (and the
``--fleet-timelines`` CLI input) renders clock-ALIGNED fleet
timelines — ``svc.fleet_timeline(fid)`` answers — as one merged
trace with per-HOST tracks placed at their aligned leader-axis
times (the one case where cross-track positions ARE wall-clock,
honest to each role's ``bound_ms``).

Load the output in Perfetto (ui.perfetto.dev) or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["trace_events", "flight_dump_events", "export", "main"]

_US = 1e6  # seconds -> trace microseconds


def _span_events(role: str, spans, base_us: float, fid: int,
                 pid: str) -> List[Dict[str, Any]]:
    """One role's spans as complete ("X") events stacked
    sequentially from the flush base."""
    out: List[Dict[str, Any]] = []
    t = base_us
    for name, dur_s in spans:
        dur_us = max(float(dur_s), 0.0) * _US
        out.append({"name": str(name), "ph": "X", "ts": t,
                    "dur": dur_us, "pid": pid, "tid": str(role),
                    "args": {"flush_id": fid}})
        t += dur_us
    return out


def trace_events(flush_ids: Iterable[int],
                 decisions: Iterable[Dict[str, Any]] = (),
                 store: Optional[Any] = None,
                 pid: str = "retpu") -> List[Dict[str, Any]]:
    """Render ``obs.timeline(fid)`` records for ``flush_ids`` (plus
    controller journal ``decisions``) as a trace-event list.  Flushes
    missing from the store are skipped; decisions whose flush never
    recorded a timeline anchor at the end of the rendered range."""
    from riak_ensemble_tpu import obs

    store = store if store is not None else obs.SPANS
    events: List[Dict[str, Any]] = []
    base_of: Dict[int, float] = {}
    base = 0.0
    for fid in sorted(set(int(f) for f in flush_ids)):
        tl = store.timeline(fid)
        if not tl or tl.get("miss"):
            continue  # evicted/unknown fid: a structured miss, not
            #           a record (the store counted it)
        base_of[fid] = base
        widest = 0.0
        for role, side in tl.items():
            if role == "flush_id":
                continue
            spans = side.get("spans", [])
            events.extend(_span_events(role, spans, base, fid, pid))
            widest = max(widest,
                         sum(max(float(d), 0.0) for _n, d in spans))
        # one metadata marker per flush so the viewer can jump by id
        events.append({"name": f"flush {fid}", "ph": "i", "s": "t",
                       "ts": base, "pid": pid, "tid": "flush",
                       "args": {k: v for k, v in
                                (tl.get("leader") or {}).items()
                                if k != "spans"}})
        # next flush starts past this one's widest role (µs), with
        # breathing room — the ordinal cross-flush spacing
        base += max(widest * _US, 1.0) * 1.25
    for ev in decisions:
        ts = base_of.get(int(ev.get("flush_id", 0)), base)
        knob = ev.get("knob") or ev.get("actuator", "decision")
        events.append({"name": f"autotune {knob}", "ph": "i",
                       "s": "g", "ts": ts, "pid": pid,
                       "tid": "controller", "args": dict(ev)})
    return events


def flight_dump_events(dump: Dict[str, Any],
                       pid: str = "retpu") -> List[Dict[str, Any]]:
    """The cross-process path: render a flight-recorder dump's ring
    records (their latency marks, leader-side only — a dump has no
    replica store) + its ``controller_decisions`` section."""
    from riak_ensemble_tpu.obs import flightrec

    events: List[Dict[str, Any]] = []
    base_of: Dict[int, float] = {}
    base = 0.0
    for rec in dump.get("ring", []):
        fid = int(rec.get("flush_id", 0))
        spans = [(c, v) for c, v in rec.items()
                 if isinstance(v, (int, float))
                 and c not in flightrec.META_FIELDS]
        base_of[fid] = base
        events.extend(_span_events("leader", spans, base, fid, pid))
        events.append({"name": f"flush {fid}", "ph": "i", "s": "t",
                       "ts": base, "pid": pid, "tid": "flush",
                       "args": {k: rec.get(k) for k in
                                ("k", "a_width", "payload_bytes",
                                 "queued_rounds", "in_flight")}})
        base += max(sum(max(float(d), 0.0) for _n, d in spans),
                    1e-6) * _US * 1.25
    for ev in dump.get("controller_decisions", []):
        ts = base_of.get(int(ev.get("flush_id", 0)), base)
        knob = ev.get("knob") or ev.get("actuator", "decision")
        events.append({"name": f"autotune {knob}", "ph": "i",
                       "s": "g", "ts": ts, "pid": pid,
                       "tid": "controller", "args": dict(ev)})
    return events


def fleet_trace_events(timelines: Iterable[Dict[str, Any]],
                       pid_prefix: str = "") -> List[Dict[str, Any]]:
    """Render ALIGNED fleet timelines (``svc.fleet_timeline(fid)``
    dicts — the ``retpu-fleet-timeline-v1`` shape) as ONE merged
    Chrome/Perfetto trace with per-HOST tracks.

    Unlike :func:`trace_events`' ordinal layout, fleet timelines
    carry absolute starts on the leader's clock (each role's spans
    aligned through its link's offset estimate), so events here are
    placed at their ALIGNED times: ``pid`` = host label (one Perfetto
    track group per host), ``tid`` = role, and each role carries its
    ``bound_ms`` in args so a reader knows how much to trust a
    cross-track comparison.  Timelines of several flushes merge onto
    one axis by their own ``base_s`` deltas (all bases are
    leader-clock seconds)."""
    events: List[Dict[str, Any]] = []
    tls = [t for t in timelines
           if isinstance(t, dict) and t.get("roles")]
    if not tls:
        return events
    base0 = min(float(t.get("base_s", 0.0)) for t in tls)
    for tl in tls:
        fid = int(tl.get("flush_id", 0))
        shift = (float(tl.get("base_s", 0.0)) - base0) * _US
        for role, info in tl["roles"].items():
            host = info.get("host") or "?"
            pid = f"{pid_prefix}{host}"
            for name, start_s, dur_s in info.get("spans", []):
                events.append({
                    "name": str(name), "ph": "X",
                    "ts": shift + max(float(start_s), 0.0) * _US,
                    "dur": max(float(dur_s), 0.0) * _US,
                    "pid": pid, "tid": str(role),
                    "args": {"flush_id": fid,
                             "aligned": bool(info.get("aligned")),
                             "bound_ms": info.get("bound_ms", 0.0)}})
    return events


def export(path: str, flush_ids: Iterable[int],
           decisions: Iterable[Dict[str, Any]] = (),
           store: Optional[Any] = None) -> Dict[str, Any]:
    """Write the Chrome-trace JSON for ``flush_ids`` (+ journal
    ``decisions``) to ``path``; returns the written document."""
    doc = {
        "traceEvents": trace_events(flush_ids, decisions, store),
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "riak_ensemble_tpu tools/trace_export.py",
            "timeline_semantics":
                "per-flush spans sequential from a per-flush base; "
                "cross-flush spacing is ordinal, not wall-clock",
        },
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--flight-dump",
                     help="a flight-recorder dump JSON "
                          "(RETPU_OBS_DUMP_DIR file) to render; a "
                          "schema-v4 dump's per-host fleet sections "
                          "render as additional per-host tracks")
    src.add_argument("--fleet-timelines",
                     help="a JSON file holding one (or a list of) "
                          "clock-ALIGNED fleet timeline dict(s) — "
                          "the ('fleet','timeline',fid) verb's "
                          "answer — rendered with per-host tracks "
                          "at aligned times")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output trace path (default trace.json)")
    args = ap.parse_args(argv)
    path = args.flight_dump or args.fleet_timelines
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"trace_export: unreadable input: {exc}",
              file=sys.stderr)
        return 1
    if args.fleet_timelines:
        tls = data if isinstance(data, list) else [data]
        doc = {
            "traceEvents": fleet_trace_events(tls),
            "displayTimeUnit": "ms",
            "otherData": {
                "source": "riak_ensemble_tpu tools/trace_export.py",
                "timeline_semantics":
                    "per-host tracks at clock-aligned leader-axis "
                    "times; trust cross-track deltas to each role's "
                    "bound_ms",
            },
        }
    else:
        events = flight_dump_events(data)
        # a correlated (schema v4) dump carries per-host span
        # sections: render them as their own host tracks next to the
        # leader ring (ordinal layout — a dump has no aligned axis,
        # only the clock_offsets section to read them against)
        for host, section in (data.get("hosts") or {}).items():
            if not isinstance(section, dict):
                continue
            hbase = 0.0
            # JSON stringified the flush-id keys: order numerically
            # (lexicographic would put fid 9 after 10); roles of one
            # flush share its base like trace_events, and the base
            # advances once per flush by its widest role
            for fid, tl in sorted(
                    (section.get("spans") or {}).items(),
                    key=lambda kv: int(kv[0])):
                if not isinstance(tl, dict) or tl.get("miss"):
                    continue
                widest = 0.0
                for role, side in tl.items():
                    if role == "flush_id" or not isinstance(side,
                                                            dict):
                        continue
                    spans = side.get("spans", [])
                    events.extend(_span_events(
                        role, spans, hbase, int(fid), str(host)))
                    widest = max(widest,
                                 sum(max(float(d), 0.0)
                                     for _n, d in spans))
                hbase += max(widest * _US, 1.0) * 1.25
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"source_dump_schema": data.get("schema"),
                          "clock_offsets":
                              data.get("clock_offsets") or {}},
        }
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    print(f"trace_export: {len(doc['traceEvents'])} events -> "
          f"{args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
