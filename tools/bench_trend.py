"""Bench-trend ratchet: fold every recorded bench round into ONE
trajectory table, and fail loudly when the record degrades.

Five-plus rounds of ``BENCH_r0*.json`` ride the repo, but until now
the only way to see the trajectory (24k → 436k → ...) was a human
re-reading JSON — and a malformed round, a silently-empty field, or
an out-of-band regression shipped without anyone noticing.  This
tool is the ratchet:

- ``python tools/bench_trend.py`` prints the trajectory table —
  headline ops/sec, the keyed/mixed/repgroup rungs, the measured
  speedup A/Bs, obs overhead, the ``escale_cpu`` E-scaling points,
  and each round's box-fingerprint key (so a cross-round delta is
  read against the box before being believed).
- ``python tools/bench_trend.py --check`` exits non-zero when any
  round file is missing its headline, malformed, or when the NEWEST
  round regressed out-of-band against the best earlier round whose
  box fingerprint matches (``--tolerance``, default 0.5: the newest
  same-box headline must stay above half the best — loose on
  purpose; boxes wobble, 2x cliffs don't happen by accident).
- The smoke tripwire (``tests/test_bench_smoke.py``) compares the
  CURRENT smoke-shape keyed rung against the best same-fingerprint
  point recorded in ``BENCH_SMOKE_TREND.json`` via
  :func:`smoke_best` — a tier-1 catch for host-path regressions that
  only round-time bench rungs would otherwise see.

Box-fingerprint matching uses (cpu_count, jax, jaxlib, platform):
hostnames are container-random, loadavg is weather.  Rounds captured
before fingerprints existed (r1-r5) report key ``None`` and never
match — the check then validates structure only, which is the honest
claim for them.

The trajectory is GROUPED by box fingerprint (:func:`box_groups`):
the table draws an explicit boundary line wherever consecutive
rounds changed boxes, and ``--check`` compares the newest round only
against earlier rounds with the SAME fingerprint — it never ratchets
across a fingerprint change (a faster/slower box is weather, not a
regression; pinned by a two-synthetic-fingerprint regression test).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["TrendError", "load_rounds", "trajectory", "check",
           "fingerprint_key", "smoke_points", "smoke_best",
           "render_table", "box_groups", "SMOKE_TREND_FILE"]

ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")
SMOKE_TREND_FILE = "BENCH_SMOKE_TREND.json"
SMOKE_TREND_SCHEMA = "retpu-bench-smoke-trend-v1"

#: trajectory columns pulled from each round's parsed JSON (missing
#: values render as "-"; only ``value`` is REQUIRED by --check)
COLUMNS: Tuple[Tuple[str, str], ...] = (
    ("value", "ops/s"),
    ("keyed_batched_ops_per_sec", "keyed"),
    ("mixed_ops_per_sec", "mixed"),
    ("repgroup_ops_per_sec", "repgrp"),
    ("read_fastpath_speedup", "read_x"),
    ("skewed_compaction_speedup", "compact_x"),
    ("repl_delta_speedup", "delta_x"),
    ("resolve_native_speedup", "native_x"),
    # slab enqueue half + completion slab vs the per-entry/per-op
    # oracle arm (bench run_native_enqueue_ab; ROADMAP item 4's
    # ratchet column — absent in rounds predating it renders "-")
    ("enqueue_native_speedup", "enqueue_x"),
    ("obs_overhead_pct", "obs_%"),
    # depth-2 vs depth-1 ops/s at the stage's deepest injected
    # per-link RTT point (>=1 ms; bench --stage faultsweep.  >=1.0 =
    # the pipelining claim holds against an adversarially slow link)
    ("faultsweep_depth2_speedup", "fault_x"),
    # controller arm vs the best static (depth, window) across the
    # autotune A/B's injected-RTT points (bench --stage autotune;
    # >=0.95 = the controller converged within the 5% acceptance
    # band at every link it was measured on)
    ("autotune_vs_best_static", "autotune_x"),
    # restart-to-serving ms at the 512-ens rung (bench --stage
    # recovery: checkpoint restore + WAL replay + first-op warmup —
    # the RTO half of the §15 crash contract.  LOWER is better; the
    # --check band polices it same-fingerprint like the headline)
    ("recovery_ms", "recov_ms"),
    # client-batch ingestion scaling from 1 proxy to the high proxy
    # count (bench --stage ingress; §16 serving plane.  HIGHER is
    # better; --check polices it same-fingerprint like the headline)
    ("ingress_x", "ingress_x"),
    # mesh scaling efficiency at the >=10k-ens escale rung: mesh
    # ops/s over (devices x the single-shard reference at equal
    # per-shard load).  HIGHER is better; --check polices it
    # same-fingerprint — and the fingerprint includes device_count,
    # so points from different mesh widths never compare (§17)
    ("escale_eff", "esc_eff"),
    # commutative-lane advantage on the contended-counter storm:
    # ordered ack p50 / comm ack p50 (bench --stage commrepl; §18).
    # HIGHER is better; --check polices it same-fingerprint like the
    # headline, rounds predating the stage exempt
    ("rmw_comm_x", "comm_x"),
)


class TrendError(Exception):
    """A bench round is missing/malformed, or the newest same-box
    round regressed out-of-band — the ratchet's loud failure."""


def fingerprint_key(box: Optional[Dict[str, Any]]
                    ) -> Optional[Tuple]:
    """Comparable box identity from an ``obs.box_fingerprint`` dict
    (None when the round predates fingerprints).

    ``device_count`` joined the fingerprint with the mesh escale
    ladder: an 8-device mesh point must never ratchet against a
    single-device round (same box, completely different serving
    shape).  Rounds recorded before the field exists carry None
    there and only compare among themselves."""
    if not isinstance(box, dict):
        return None
    return (box.get("cpu_count"), box.get("jax"), box.get("jaxlib"),
            box.get("platform") or box.get("jax_platforms"),
            box.get("device_count"))


def load_rounds(root: str) -> List[Dict[str, Any]]:
    """Every ``BENCH_rNN.json`` under ``root``, parsed and validated
    (strict: an unreadable file or a round without its headline
    ``value`` raises :class:`TrendError` — an empty trajectory must
    never ship silently)."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as fh:
                raw = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            raise TrendError(f"{path}: unreadable round JSON "
                             f"({exc})") from exc
        parsed = raw.get("parsed") if isinstance(raw, dict) else None
        if not isinstance(parsed, dict):
            raise TrendError(f"{path}: no 'parsed' result object — "
                             "the round recorded nothing")
        if not isinstance(parsed.get("value"), (int, float)):
            raise TrendError(f"{path}: headline 'value' missing or "
                             f"non-numeric: {parsed.get('value')!r}")
        out.append({
            "round": int(m.group(1)),
            "file": os.path.basename(path),
            "parsed": parsed,
            "box_key": fingerprint_key(parsed.get("box")),
        })
    return sorted(out, key=lambda r: r["round"])


def trajectory(rounds: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One flat row per round: the COLUMNS fields + escale points +
    the fingerprint key."""
    rows = []
    for r in rounds:
        p = r["parsed"]
        row: Dict[str, Any] = {"round": r["round"], "file": r["file"]}
        for key, _label in COLUMNS:
            row[key] = p.get(key)
        esc = p.get("escale_cpu") or {}
        row["escale"] = {e: (pt or {}).get("ops_per_sec")
                         for e, pt in esc.items()} if esc else {}
        row["box_key"] = r["box_key"]
        row["platform"] = p.get("platform")
        rows.append(row)
    return rows


def box_groups(rows: List[Dict[str, Any]]
               ) -> List[Tuple[Optional[Tuple], List[Dict[str, Any]]]]:
    """Consecutive runs of rounds sharing a box fingerprint, in
    round order: ``[(box_key, [row, ...]), ...]``.  This is the unit
    absolute-ms comparisons are valid WITHIN (ROADMAP: cross-round
    absolute comparisons are box-bound); the table renderer draws an
    explicit boundary between runs, and the ratchet never compares
    across one."""
    groups: List[Tuple[Optional[Tuple], List[Dict[str, Any]]]] = []
    for row in rows:
        key = row.get("box_key")
        if groups and groups[-1][0] == key:
            groups[-1][1].append(row)
        else:
            groups.append((key, [row]))
    return groups


def _box_label(key: Optional[Tuple]) -> str:
    return "-" if key is None else f"cpu{key[0]}"


def render_table(rows: List[Dict[str, Any]]) -> str:
    """The trajectory table, with an EXPLICIT boundary line wherever
    consecutive rounds ran on different box fingerprints — a reader
    eyeballing a column must see where the box changed before
    believing a delta (absolute-ms comparisons are box-bound)."""
    heads = ["rnd"] + [label for _k, label in COLUMNS] \
        + ["escale", "box"]
    table = [heads]
    for row in rows:
        def fmt(v):
            if v is None:
                return "-"
            if isinstance(v, float):
                return f"{v:,.1f}" if abs(v) >= 100 else f"{v:g}"
            return str(v)
        esc = ",".join(f"{e}:{fmt(v)}" for e, v in row["escale"].items())
        table.append([str(row["round"])]
                     + [fmt(row[k]) for k, _l in COLUMNS]
                     + [esc or "-", _box_label(row["box_key"])])
    widths = [max(len(r[i]) for r in table)
              for i in range(len(heads))]
    body = ["  ".join(c.rjust(w) for c, w in zip(r, widths))
            for r in table]
    lines = body[:1]
    lines.append("  ".join("-" * w for w in widths))
    # stitch data lines back in with box boundaries between the
    # fingerprint runs (rows and body[1:] are index-aligned)
    i = 1
    groups = box_groups(rows)
    for gi, (key, grp) in enumerate(groups):
        if gi:
            prev = groups[gi - 1][0]
            lines.append(
                f"~~ box change: {_box_label(prev)} -> "
                f"{_box_label(key)} (absolute ms not comparable "
                f"across this line) ~~")
        for _row in grp:
            lines.append(body[i])
            i += 1
    return "\n".join(lines)


def check(root: str, tolerance: float = 0.5) -> Dict[str, Any]:
    """The ratchet: load every round strictly, then compare the
    newest round's headline against the best EARLIER round with the
    same box fingerprint.  Returns the report dict; raises
    :class:`TrendError` on malformed rounds, an empty trajectory, or
    a same-box regression below ``tolerance`` x best."""
    rounds = load_rounds(root)
    if not rounds:
        raise TrendError(f"no BENCH_rNN.json rounds under {root} — "
                         "the trajectory is empty")
    newest = rounds[-1]
    report: Dict[str, Any] = {
        "rounds": len(rounds),
        "newest_round": newest["round"],
        "newest_ops_per_sec": newest["parsed"]["value"],
        "comparable_rounds": 0,
        "best_same_box_ops_per_sec": None,
        "tolerance": tolerance,
    }
    key = newest["box_key"]
    if key is not None:
        same = [r for r in rounds[:-1] if r["box_key"] == key]
        report["comparable_rounds"] = len(same)
        if same:
            best = max(same, key=lambda r: r["parsed"]["value"])
            best_v = best["parsed"]["value"]
            report["best_same_box_ops_per_sec"] = best_v
            if newest["parsed"]["value"] < tolerance * best_v:
                raise TrendError(
                    f"out-of-band regression: round "
                    f"{newest['round']} headline "
                    f"{newest['parsed']['value']:.1f} ops/s is below "
                    f"{tolerance:.0%} of round {best['round']}'s "
                    f"{best_v:.1f} on the same box fingerprint")
            # recovery_ms ratchet (ISSUE 15): restart-to-serving is
            # LOWER-is-better, so the band inverts — the newest
            # same-box point must stay under best/tolerance (2x the
            # best at the default 0.5).  Rounds predating the stage
            # (no recovery_ms) neither ratchet nor fail.
            rec_v = newest["parsed"].get("recovery_ms")
            rec_same = [r["parsed"]["recovery_ms"] for r in same
                        if isinstance(r["parsed"].get("recovery_ms"),
                                      (int, float))]
            if isinstance(rec_v, (int, float)) and rec_same:
                best_rec = min(rec_same)
                report["best_same_box_recovery_ms"] = best_rec
                report["newest_recovery_ms"] = rec_v
                if rec_v * tolerance > best_rec:
                    raise TrendError(
                        f"out-of-band recovery regression: round "
                        f"{newest['round']} restart-to-serving "
                        f"{rec_v:.1f} ms exceeds 1/{tolerance:g} x "
                        f"the best same-box {best_rec:.1f} ms")
            # ingress_x ratchet (ISSUE 16): proxy-count ingestion
            # scaling is higher-is-better like the headline — the
            # newest same-box point must stay above tolerance x the
            # best earlier round's.  Rounds predating the stage (no
            # ingress_x) neither ratchet nor fail.
            ing_v = newest["parsed"].get("ingress_x")
            ing_same = [r["parsed"]["ingress_x"] for r in same
                        if isinstance(r["parsed"].get("ingress_x"),
                                      (int, float))]
            if isinstance(ing_v, (int, float)) and ing_same:
                best_ing = max(ing_same)
                report["best_same_box_ingress_x"] = best_ing
                report["newest_ingress_x"] = ing_v
                if ing_v < tolerance * best_ing:
                    raise TrendError(
                        f"out-of-band ingress regression: round "
                        f"{newest['round']} proxy-scaling "
                        f"{ing_v:.2f}x is below {tolerance:.0%} of "
                        f"the best same-box {best_ing:.2f}x")
            # escale_eff ratchet (ISSUE 17): mesh scaling efficiency
            # at the >=10k-ens rung is higher-is-better like the
            # headline.  device_count rides the fingerprint, so
            # efficiency points from different mesh widths are never
            # compared at all.  Rounds predating the mesh ladder
            # neither ratchet nor fail.
            eff_v = newest["parsed"].get("escale_eff")
            eff_same = [r["parsed"]["escale_eff"] for r in same
                        if isinstance(r["parsed"].get("escale_eff"),
                                      (int, float))]
            if isinstance(eff_v, (int, float)) and eff_same:
                best_eff = max(eff_same)
                report["best_same_box_escale_eff"] = best_eff
                report["newest_escale_eff"] = eff_v
                if eff_v < tolerance * best_eff:
                    raise TrendError(
                        f"out-of-band mesh-scaling regression: round "
                        f"{newest['round']} escale efficiency "
                        f"{eff_v:.2f} is below {tolerance:.0%} of "
                        f"the best same-box {best_eff:.2f}")
            # rmw_comm_x ratchet (ISSUE 18): the commutative lane's
            # ack-latency advantage on the contended-counter storm is
            # higher-is-better like the headline.  Rounds predating
            # the stage (no rmw_comm_x) neither ratchet nor fail.
            cx_v = newest["parsed"].get("rmw_comm_x")
            cx_same = [r["parsed"]["rmw_comm_x"] for r in same
                       if isinstance(r["parsed"].get("rmw_comm_x"),
                                     (int, float))]
            if isinstance(cx_v, (int, float)) and cx_same:
                best_cx = max(cx_same)
                report["best_same_box_rmw_comm_x"] = best_cx
                report["newest_rmw_comm_x"] = cx_v
                if cx_v < tolerance * best_cx:
                    raise TrendError(
                        f"out-of-band comm-lane regression: round "
                        f"{newest['round']} rmw_comm_x "
                        f"{cx_v:.2f}x is below {tolerance:.0%} of "
                        f"the best same-box {best_cx:.2f}x")
    return report


# -- the tier-1 smoke trend --------------------------------------------------


def smoke_points(root: str) -> List[Dict[str, Any]]:
    """Recorded smoke-rung points (``BENCH_SMOKE_TREND.json``);
    empty when the file is absent, :class:`TrendError` when it is
    present but malformed (a torn trend file must fail loudly, not
    read as 'no baseline')."""
    path = os.path.join(root, SMOKE_TREND_FILE)
    if not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        assert data.get("schema") == SMOKE_TREND_SCHEMA, data.get(
            "schema")
        points = data["points"]
        assert isinstance(points, list)
    except (OSError, json.JSONDecodeError, KeyError,
            AssertionError) as exc:
        raise TrendError(
            f"{path}: malformed smoke trend file ({exc})") from exc
    return points


def smoke_best(root: str, box_key: Optional[Tuple],
               shape: Dict[str, int]) -> Optional[float]:
    """Best recorded smoke ``keyed_batched_ops_per_sec`` whose box
    fingerprint AND shape match; None when nothing comparable is
    recorded (the tripwire then skips — a different box is not a
    regression)."""
    best = None
    for pt in smoke_points(root):
        if fingerprint_key(pt.get("box")) != box_key:
            continue
        if pt.get("shape") != shape:
            continue
        v = pt.get("keyed_batched_ops_per_sec")
        if isinstance(v, (int, float)) and (best is None or v > best):
            best = float(v)
    return best


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root holding the BENCH_*.json rounds")
    ap.add_argument("--check", action="store_true",
                    help="validate every round + same-box regression "
                         "band; non-zero exit on failure")
    ap.add_argument("--tolerance", type=float, default=0.5,
                    help="--check band: newest same-box headline "
                         "must exceed tolerance x best (default 0.5)")
    ap.add_argument("--json", action="store_true",
                    help="machine output (trajectory rows or the "
                         "check report)")
    args = ap.parse_args(argv)

    try:
        if args.check:
            report = check(args.dir, args.tolerance)
            print(json.dumps(report) if args.json else
                  "bench-trend check ok: " + json.dumps(report))
            return 0
        rows = trajectory(load_rounds(args.dir))
        print(json.dumps(rows) if args.json
              else render_table(rows))
        return 0
    except TrendError as exc:
        print(f"bench-trend: {exc}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
