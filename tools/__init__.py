# repo tooling (tools.bench_trend et al.) — importable from tests
