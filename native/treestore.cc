// Embedded ordered K/V storage engine for synctree persistence.
//
// The role the eleveldb C++ dependency plays for the reference
// (synctree_leveldb.erl: persistent Merkle-tree buckets, shared-DB
// registry, batched writes — synctree_leveldb.erl:52-83,141-152):
// an append-only CRC-framed write-ahead log with an in-memory ordered
// index (std::map) and snapshot compaction.  Writes are O(log n)
// in-memory plus one sequential log append (batched); recovery replays
// snapshot + log.  This is deliberately a log+index engine rather than
// a full LSM: synctree working sets are bucket-granular (width 16,
// ~1M segments) and the write pattern is small random upserts, which
// a sequential log absorbs at disk bandwidth.
//
// C ABI (ctypes): handles are opaque pointers; keys/values are
// arbitrary byte strings.  A shared-handle registry keyed by path
// mirrors the reference's shared-DB ETS registry so many trees can
// open one engine (synctree_leveldb.erl:52-83).

#include <fcntl.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// fsync the directory containing `path` so a just-renamed file's
// directory entry survives power loss (the tmp+rename+dirsync rite).
void sync_parent_dir(const std::string& path) {
  std::string dir = ".";
  auto slash = path.find_last_of('/');
  if (slash != std::string::npos) {
    dir = path.substr(0, slash);
  }
  int fd = open(dir.c_str(), O_RDONLY);
  if (fd >= 0) {
    fsync(fd);
    close(fd);
  }
}

// CRC-32 (IEEE), table-driven — the framing checksum.
uint32_t crc32(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) {
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) {
    c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

void append_u32(std::string* out, uint32_t v) {
  char buf[4] = {static_cast<char>(v >> 24), static_cast<char>(v >> 16),
                 static_cast<char>(v >> 8), static_cast<char>(v)};
  out->append(buf, 4);
}

uint32_t read_u32(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

struct Store {
  std::string path;        // snapshot file; log is path + ".log"
  std::map<std::string, std::string> data;
  FILE* log = nullptr;
  uint64_t log_records = 0;
  int refcount = 1;
  std::mutex mu;

  // Record framing: [crc32(body)][len][body]; body = op(1B) keylen(4B)
  // key [vallen(4B) val].  op: 1=put, 2=del.
  void append_record(uint8_t op, const std::string& key,
                     const std::string& val) {
    std::string body;
    body.push_back(static_cast<char>(op));
    append_u32(&body, static_cast<uint32_t>(key.size()));
    body.append(key);
    if (op == 1) {
      append_u32(&body, static_cast<uint32_t>(val.size()));
      body.append(val);
    }
    std::string frame;
    append_u32(&frame,
               crc32(reinterpret_cast<const uint8_t*>(body.data()),
                     body.size()));
    append_u32(&frame, static_cast<uint32_t>(body.size()));
    frame.append(body);
    fwrite(frame.data(), 1, frame.size(), log);
    log_records++;
  }

  bool replay_log() {
    std::string logpath = path + ".log";
    FILE* f = fopen(logpath.c_str(), "rb");
    if (!f) {
      return true;  // no log yet
    }
    std::vector<uint8_t> head(8);
    while (fread(head.data(), 1, 8, f) == 8) {
      uint32_t crc = read_u32(head.data());
      uint32_t len = read_u32(head.data() + 4);
      std::vector<uint8_t> body(len);
      if (fread(body.data(), 1, len, f) != len) {
        break;  // torn tail: stop at last good record
      }
      if (crc32(body.data(), len) != crc) {
        break;
      }
      if (len < 5) {
        break;
      }
      uint8_t op = body[0];
      uint32_t klen = read_u32(body.data() + 1);
      if (5 + klen > len) {
        break;
      }
      std::string key(reinterpret_cast<char*>(body.data() + 5), klen);
      if (op == 1) {
        if (5 + klen + 4 > len) {
          break;
        }
        uint32_t vlen = read_u32(body.data() + 5 + klen);
        if (5 + klen + 4 + vlen > len) {
          break;
        }
        data[key] = std::string(
            reinterpret_cast<char*>(body.data() + 5 + klen + 4), vlen);
      } else if (op == 2) {
        data.erase(key);
      }
      log_records++;
    }
    fclose(f);
    return true;
  }

  bool load_snapshot() {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) {
      return true;
    }
    std::vector<uint8_t> head(8);
    while (fread(head.data(), 1, 8, f) == 8) {
      uint32_t crc = read_u32(head.data());
      uint32_t len = read_u32(head.data() + 4);
      std::vector<uint8_t> body(len);
      if (fread(body.data(), 1, len, f) != len ||
          crc32(body.data(), len) != crc || len < 8) {
        break;
      }
      uint32_t klen = read_u32(body.data());
      if (4 + klen + 4 > len) {
        break;
      }
      uint32_t vlen = read_u32(body.data() + 4 + klen);
      if (4 + klen + 4 + vlen > len) {
        break;
      }
      std::string key(reinterpret_cast<char*>(body.data() + 4), klen);
      data[key] = std::string(
          reinterpret_cast<char*>(body.data() + 4 + klen + 4), vlen);
    }
    fclose(f);
    return true;
  }

  // Rewrite snapshot from the live map, truncate the log.  Crash-safe:
  // snapshot lands via rename; the log is only truncated afterwards.
  void compact() {
    std::string tmp = path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) {
      return;
    }
    for (const auto& kv : data) {
      std::string body;
      append_u32(&body, static_cast<uint32_t>(kv.first.size()));
      body.append(kv.first);
      append_u32(&body, static_cast<uint32_t>(kv.second.size()));
      body.append(kv.second);
      std::string frame;
      append_u32(&frame,
                 crc32(reinterpret_cast<const uint8_t*>(body.data()),
                       body.size()));
      append_u32(&frame, static_cast<uint32_t>(body.size()));
      frame.append(body);
      fwrite(frame.data(), 1, frame.size(), f);
    }
    // Durable ordering: snapshot bytes reach disk BEFORE the rename
    // publishes it, and the rename reaches disk (directory fsync)
    // BEFORE the log truncation discards the records it replaced.
    fflush(f);
    fsync(fileno(f));
    fclose(f);
    rename(tmp.c_str(), path.c_str());
    sync_parent_dir(path);
    if (log) {
      fclose(log);
    }
    std::string logpath = path + ".log";
    log = fopen(logpath.c_str(), "wb");  // truncate
    log_records = 0;
  }
};

std::mutex g_registry_mu;
std::unordered_map<std::string, Store*> g_registry;

constexpr uint64_t kCompactThreshold = 1 << 16;

}  // namespace

extern "C" {

void* retpu_store_open(const char* path) {
  std::lock_guard<std::mutex> g(g_registry_mu);
  auto it = g_registry.find(path);
  if (it != g_registry.end()) {
    it->second->refcount++;
    return it->second;
  }
  auto* s = new Store();
  s->path = path;
  s->load_snapshot();
  s->replay_log();
  std::string logpath = s->path + ".log";
  s->log = fopen(logpath.c_str(), "ab");
  if (!s->log) {
    delete s;
    return nullptr;
  }
  g_registry[path] = s;
  return s;
}

void retpu_store_close(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(g_registry_mu);
  if (--s->refcount > 0) {
    return;
  }
  g_registry.erase(s->path);
  {
    std::lock_guard<std::mutex> lg(s->mu);
    if (s->log) {
      fflush(s->log);
      fclose(s->log);
      s->log = nullptr;
    }
  }
  delete s;
}

int retpu_store_put(void* h, const uint8_t* key, uint32_t klen,
                    const uint8_t* val, uint32_t vlen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k(reinterpret_cast<const char*>(key), klen);
  std::string v(reinterpret_cast<const char*>(val), vlen);
  s->data[k] = v;
  s->append_record(1, k, v);
  if (s->log_records >= kCompactThreshold) {
    s->compact();
  }
  return 0;
}

// Arena batch put: `idx` holds n rows of (key_off, key_len, val_off,
// val_len) into `arena`; rows with key_len <= 0 are skipped (the
// resolve kernel emits those for uncommitted lanes).  One ctypes call
// and one lock acquisition appends a whole flush's WAL records with
// byte-identical framing to per-record retpu_store_put calls.
int retpu_store_put_many(void* h, const uint8_t* arena,
                         const int64_t* idx, int64_t n) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  for (int64_t i = 0; i < n; i++) {
    const int64_t klen = idx[i * 4 + 1];
    if (klen <= 0) {
      continue;
    }
    std::string k(reinterpret_cast<const char*>(arena + idx[i * 4]),
                  static_cast<size_t>(klen));
    std::string v(
        reinterpret_cast<const char*>(arena + idx[i * 4 + 2]),
        static_cast<size_t>(idx[i * 4 + 3]));
    s->data[k] = v;
    s->append_record(1, k, v);
    // per-record threshold check, matching retpu_store_put — a batch
    // crossing the bound must compact at the same record a sequence
    // of single puts would (the byte-identical-framing contract)
    if (s->log_records >= kCompactThreshold) {
      s->compact();
    }
  }
  return 0;
}

// Returns value length, or -1 if absent.  Caller provides the buffer;
// call with buf=null to size first (value may not change between the
// two calls from one Python thread holding the store).
int64_t retpu_store_get(void* h, const uint8_t* key, uint32_t klen,
                        uint8_t* buf, uint64_t buflen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->data.find(
      std::string(reinterpret_cast<const char*>(key), klen));
  if (it == s->data.end()) {
    return -1;
  }
  if (buf != nullptr && buflen >= it->second.size()) {
    memcpy(buf, it->second.data(), it->second.size());
  }
  return static_cast<int64_t>(it->second.size());
}

int retpu_store_delete(void* h, const uint8_t* key, uint32_t klen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k(reinterpret_cast<const char*>(key), klen);
  s->data.erase(k);
  s->append_record(2, k, std::string());
  return 0;
}

uint64_t retpu_store_count(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->data.size();
}

// Ordered iteration: copy key at `index` into buf (sized via
// buf=null), -1 when out of range.  Index-based (vs cursor) keeps the
// ABI trivial; Python iterates while mutating via snapshot indices.
int64_t retpu_store_key_at(void* h, uint64_t index, uint8_t* buf,
                           uint64_t buflen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (index >= s->data.size()) {
    return -1;
  }
  auto it = s->data.begin();
  std::advance(it, index);
  if (buf != nullptr && buflen >= it->first.size()) {
    memcpy(buf, it->first.data(), it->first.size());
  }
  return static_cast<int64_t>(it->first.size());
}

// Flush-only (no fsync): pushes libc-buffered log bytes into the OS
// page cache — the process-crash durability floor (the "buffer" WAL
// sync mode); power-loss durability still needs retpu_store_sync.
void retpu_store_flush(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->log) {
    fflush(s->log);
  }
}

void retpu_store_sync(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->log) {
    // fflush alone survives process crash but not OS crash/power loss;
    // the advertised durability contract needs the fsync.
    fflush(s->log);
    fsync(fileno(s->log));
  }
}

void retpu_store_compact(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->compact();
}

}  // extern "C"
