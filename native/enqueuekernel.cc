// Native half of the service's ENQUEUE path (the sibling of
// resolvekernel.cc, compiled into the same _retpu_resolve.so under
// the same utils/native.py loader discipline: plain-C ABI, ctypes,
// pure-Python fallback stays the oracle — RETPU_NATIVE_ENQUEUE=0).
//
// PR 7 moved the per-flush RESOLVE hot loop to C; its latency
// breakdown then showed the remaining host cost on the other side of
// the device round: packing the pending queue entries into the
// [K, E] op planes and fanning the results back out.  The service
// keeps each flush's pending ops as a PENDING SLAB — per-entry run
// descriptors (ensemble column, first plane row, run length, uniform
// kind) over concatenated per-op field lanes (slot, value/handle,
// CAS-expectation halves) — and this kernel walks the runs in ONE
// C++ traversal each way:
//
//   1. retpu_enqueue_pack    — pending slab -> the five [K, E] int32
//      op planes (replacing the per-entry numpy slice-assignment
//      walk).  Run descriptors, not flat per-op row/col lanes, so
//      the Python->C conversion cost scales with ENTRIES, not ops.
//   2. retpu_enqueue_gather  — result planes -> the per-flush
//      COMPLETION SLAB ([R] records in taken order: committed,
//      get_ok, found, value, vsn), replacing per-op scalar reads /
//      per-entry column slices at settle.
//
// Contract: outputs are BIT-IDENTICAL to the numpy fallback's
// (tests/test_native_enqueue.py sweeps the equivalence); pack planes
// arrive zero-initialized (padding rows and idle columns stay
// NOOP/zero exactly as the fallback leaves them).  A run outside the
// [K, E] grid returns -1 — the caller rebuilds through the numpy
// path, which raises the honest error.

#include <cstdint>

extern "C" {

// ABI version for stale-.so detection (utils/native.py probes the
// symbol; enqueue_native.py refuses < 2 — v1 took flat per-op
// row/col lanes).
int retpu_enqueue_version(void) { return 2; }

// Scatter the pending slab's runs into the five [K, E] int32 op
// planes.  Per entry i: rows [row0[i], row0[i]+len[i]) of column
// col[i] take kind[i] (uniform per entry — batches are one op kind)
// and the next len[i] values of each field lane (an RMW entry's expe
// carries its mod-fun table code, val its int32 operand — the exact
// field layout flush() always packed).
int retpu_enqueue_pack(int64_t n_ent, int32_t k, int32_t e,
                       const int32_t* col, const int32_t* row0,
                       const int32_t* len, const int32_t* kind,
                       const int32_t* slot, const int32_t* val,
                       const int32_t* expe, const int32_t* exps,
                       int32_t* kind_p, int32_t* slot_p,
                       int32_t* val_p, int32_t* expe_p,
                       int32_t* exps_p) {
  if (!col || !row0 || !len || !kind || !slot || !val || !expe ||
      !exps || !kind_p || !slot_p || !val_p || !expe_p || !exps_p) {
    return -1;
  }
  int64_t off = 0;
  for (int64_t i = 0; i < n_ent; i++) {
    const int32_t c = col[i];
    const int32_t r0 = row0[i];
    const int32_t n = len[i];
    if (c < 0 || c >= e || n < 0 || r0 < 0 || r0 + n > k) {
      return -1;
    }
    const int32_t kd = kind[i];
    for (int32_t j = 0; j < n; j++, off++) {
      const int64_t p = static_cast<int64_t>(r0 + j) * e + c;
      kind_p[p] = kd;
      slot_p[p] = slot[off];
      val_p[p] = val[off];
      expe_p[p] = expe[off];
      exps_p[p] = exps[off];
    }
  }
  return 0;
}

// Gather the flush's result planes through the same runs into the
// completion slab: out_* are preallocated [R] (vsn: [R, 2]) arrays
// in taken order.  committed/get_ok/found are numpy bool (u8)
// planes; value int32 [K, E]; vsn int32 [K, E, 2].
int retpu_enqueue_gather(int64_t n_ent, int32_t k, int32_t e,
                         const int32_t* col, const int32_t* row0,
                         const int32_t* len, const uint8_t* committed,
                         const uint8_t* get_ok, const uint8_t* found,
                         const int32_t* value, const int32_t* vsn,
                         uint8_t* out_ok, uint8_t* out_gok,
                         uint8_t* out_fnd, int32_t* out_val,
                         int32_t* out_vsn) {
  if (!col || !row0 || !len || !committed || !get_ok || !found ||
      !value || !vsn || !out_ok || !out_gok || !out_fnd || !out_val ||
      !out_vsn) {
    return -1;
  }
  int64_t off = 0;
  for (int64_t i = 0; i < n_ent; i++) {
    const int32_t c = col[i];
    const int32_t r0 = row0[i];
    const int32_t n = len[i];
    if (c < 0 || c >= e || n < 0 || r0 < 0 || r0 + n > k) {
      return -1;
    }
    for (int32_t j = 0; j < n; j++, off++) {
      const int64_t p = static_cast<int64_t>(r0 + j) * e + c;
      out_ok[off] = committed[p];
      out_gok[off] = get_ok[p];
      out_fnd[off] = found[p];
      out_val[off] = value[p];
      out_vsn[2 * off] = vsn[2 * p];
      out_vsn[2 * off + 1] = vsn[2 * p + 1];
    }
  }
  return 0;
}

}  // extern "C"
