// Monotonic clock module.
//
// The role of the reference's only C NIF (c_src/riak_ensemble_clock.c,
// 184 LoC): clock readings immune to wall-clock jumps, backing the
// leader-lease safety check (riak_ensemble_lease.erl:76-88).  Like the
// reference we prefer CLOCK_BOOTTIME on Linux — CLOCK_MONOTONIC stops
// while the machine is suspended, which would silently extend leases
// across a suspend/resume (the hazard discussed at
// c_src/riak_ensemble_clock.c:50-57).
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <ctime>

namespace {

int64_t read_clock(clockid_t id) {
  struct timespec ts;
  if (clock_gettime(id, &ts) != 0) {
    return -1;
  }
  return static_cast<int64_t>(ts.tv_sec) * 1000000000LL +
         static_cast<int64_t>(ts.tv_nsec);
}

}  // namespace

extern "C" {

// Nanoseconds from an arbitrary fixed origin; never jumps backward.
int64_t retpu_monotonic_time_ns() {
#ifdef CLOCK_BOOTTIME
  int64_t t = read_clock(CLOCK_BOOTTIME);
  if (t >= 0) {
    return t;
  }
#endif
  return read_clock(CLOCK_MONOTONIC);
}

// Milliseconds (the riak_ensemble_clock:monotonic_time_ms/0 analog).
int64_t retpu_monotonic_time_ms() {
  int64_t ns = retpu_monotonic_time_ns();
  return ns < 0 ? -1 : ns / 1000000LL;
}

// 1 when CLOCK_BOOTTIME is in use (introspection/tests).
int retpu_clock_is_boottime() {
#ifdef CLOCK_BOOTTIME
  return read_clock(CLOCK_BOOTTIME) >= 0 ? 1 : 0;
#else
  return 0;
#endif
}

}  // extern "C"
