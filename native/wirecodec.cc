// Native wire codec for the restricted TCP frame format.
//
// The reference ships terms over disterl, whose term codec is C inside
// the BEAM (decode constructs plain terms only, never code).  This
// module is that native codec for our transport: the same tag/varint
// format as riak_ensemble_tpu/wire.py, byte-exact on encode so native
// and Python frames are interchangeable on the wire, with the same
// allowlist property — decode builds values exclusively from plain
// containers and the registered protocol record types.
//
// Built as a CPython extension (no pybind11 in the image); wire.py
// loads it lazily and keeps the pure-Python implementation as both
// fallback and differential-test oracle.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int kMaxDepth = 32;  // matches wire._MAX_DEPTH

// Registered by wire.py at import: the record registry (class,
// field-name tuple) in code order, the NOTFOUND sentinel, and the
// WireError exception class.
PyObject *g_wire_error = nullptr;
PyObject *g_notfound = nullptr;
struct Record {
  PyObject *cls;     // strong ref
  PyObject *fields;  // strong ref, tuple of str
};
std::vector<Record> g_records;

int set_wire_error(const char *msg) {
  PyErr_SetString(g_wire_error ? g_wire_error : PyExc_ValueError, msg);
  return -1;
}

// ---------------------------------------------------------------- encode

struct Buf {
  std::string s;
  void put(char c) { s.push_back(c); }
  void put(const char *p, size_t n) { s.append(p, n); }
};

void put_uvarint(Buf &b, uint64_t n) {
  for (;;) {
    uint8_t x = n & 0x7F;
    n >>= 7;
    if (n) {
      b.put(static_cast<char>(x | 0x80));
    } else {
      b.put(static_cast<char>(x));
      return;
    }
  }
}

int encode_value(Buf &b, PyObject *v, int depth);

// Python's encoding: nbytes = (bit_length + 8) // 8 (min 1), then
// to_bytes(nbytes, "big", signed=True).  For a value that fits in
// long long we reproduce those bytes directly.
int encode_small_int(Buf &b, long long ll) {
  uint64_t mag = ll < 0 ? static_cast<uint64_t>(-(ll + 1)) + 1
                        : static_cast<uint64_t>(ll);
  int bl = 0;
  for (uint64_t m = mag; m; m >>= 1) ++bl;
  int n = (bl + 8) / 8;
  if (n < 1) n = 1;
  b.put('i');
  put_uvarint(b, static_cast<uint64_t>(n));
  for (int i = n - 1; i >= 0; --i) {
    int shift = 8 * i;
    uint8_t byte = shift < 64
        ? static_cast<uint8_t>(static_cast<uint64_t>(ll) >> shift)
        : (ll < 0 ? 0xFF : 0x00);
    b.put(static_cast<char>(byte));
  }
  return 0;
}

int encode_big_int(Buf &b, PyObject *v) {
  PyObject *bl_obj = PyObject_CallMethod(v, "bit_length", nullptr);
  if (!bl_obj) return -1;
  long long bl = PyLong_AsLongLong(bl_obj);
  Py_DECREF(bl_obj);
  if (bl < 0 && PyErr_Occurred()) return -1;
  long long n = (bl + 8) / 8;
  if (n < 1) n = 1;
  PyObject *raw = PyObject_CallMethod(
      v, "to_bytes", "(Ls)", n, "big");
  if (!raw) {
    // needs signed=True for negatives — retry with kwargs
    PyErr_Clear();
    PyObject *meth = PyObject_GetAttrString(v, "to_bytes");
    if (!meth) return -1;
    PyObject *args = Py_BuildValue("(Ls)", n, "big");
    PyObject *kw = Py_BuildValue("{s:O}", "signed", Py_True);
    raw = (args && kw) ? PyObject_Call(meth, args, kw) : nullptr;
    Py_XDECREF(args);
    Py_XDECREF(kw);
    Py_DECREF(meth);
    if (!raw) return -1;
  }
  char *p;
  Py_ssize_t len;
  if (PyBytes_AsStringAndSize(raw, &p, &len) < 0) {
    Py_DECREF(raw);
    return -1;
  }
  b.put('i');
  put_uvarint(b, static_cast<uint64_t>(len));
  b.put(p, static_cast<size_t>(len));
  Py_DECREF(raw);
  return 0;
}

// Always call to_bytes with signed=True (matches wire.py exactly,
// including for positives where the extra headroom byte appears).
int encode_int(Buf &b, PyObject *v) {
  int overflow = 0;
  long long ll = PyLong_AsLongLongAndOverflow(v, &overflow);
  if (!overflow && !(ll == -1 && PyErr_Occurred()))
    return encode_small_int(b, ll);
  PyErr_Clear();
  return encode_big_int(b, v);
}

int encode_float(Buf &b, PyObject *v) {
  double d = PyFloat_AS_DOUBLE(v);
  uint64_t bits;
  std::memcpy(&bits, &d, 8);
  b.put('f');
  for (int i = 7; i >= 0; --i)
    b.put(static_cast<char>(bits >> (8 * i)));
  return 0;
}

int encode_container(Buf &b, PyObject *v, char tag, int depth) {
  b.put(tag);
  Py_ssize_t n = PyObject_Size(v);
  if (n < 0) return -1;
  put_uvarint(b, static_cast<uint64_t>(n));
  PyObject *it = PyObject_GetIter(v);
  if (!it) return -1;
  PyObject *item;
  while ((item = PyIter_Next(it)) != nullptr) {
    int rc = encode_value(b, item, depth + 1);
    Py_DECREF(item);
    if (rc < 0) {
      Py_DECREF(it);
      return -1;
    }
  }
  Py_DECREF(it);
  return PyErr_Occurred() ? -1 : 0;
}

int encode_value(Buf &b, PyObject *v, int depth) {
  if (depth > kMaxDepth)
    return set_wire_error("value too deeply nested");
  if (v == Py_None) {
    b.put('N');
    return 0;
  }
  if (v == g_notfound) {
    b.put('0');
    return 0;
  }
  PyTypeObject *t = Py_TYPE(v);
  if (t == &PyBool_Type) {
    b.put(v == Py_True ? 'T' : 'F');
    return 0;
  }
  if (t == &PyLong_Type) return encode_int(b, v);
  if (t == &PyFloat_Type) return encode_float(b, v);
  if (t == &PyUnicode_Type) {
    Py_ssize_t len;
    const char *p = PyUnicode_AsUTF8AndSize(v, &len);
    if (!p) return -1;
    b.put('s');
    put_uvarint(b, static_cast<uint64_t>(len));
    b.put(p, static_cast<size_t>(len));
    return 0;
  }
  if (t == &PyBytes_Type) {
    char *p;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(v, &p, &len) < 0) return -1;
    b.put('b');
    put_uvarint(b, static_cast<uint64_t>(len));
    b.put(p, static_cast<size_t>(len));
    return 0;
  }
  if (t == &PyTuple_Type) return encode_container(b, v, 't', depth);
  if (t == &PyList_Type) return encode_container(b, v, 'l', depth);
  if (t == &PySet_Type) return encode_container(b, v, 'e', depth);
  if (t == &PyFrozenSet_Type) return encode_container(b, v, 'z', depth);
  if (t == &PyDict_Type) {
    b.put('d');
    put_uvarint(b, static_cast<uint64_t>(PyDict_Size(v)));
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &val)) {
      if (encode_value(b, key, depth + 1) < 0) return -1;
      if (encode_value(b, val, depth + 1) < 0) return -1;
    }
    return 0;
  }
  for (size_t code = 0; code < g_records.size(); ++code) {
    if (reinterpret_cast<PyObject *>(t) != g_records[code].cls) continue;
    b.put('R');
    put_uvarint(b, static_cast<uint64_t>(code));
    PyObject *fields = g_records[code].fields;
    Py_ssize_t nf = PyTuple_GET_SIZE(fields);
    for (Py_ssize_t i = 0; i < nf; ++i) {
      PyObject *fv = PyObject_GetAttr(v, PyTuple_GET_ITEM(fields, i));
      if (!fv) return -1;
      int rc = encode_value(b, fv, depth + 1);
      Py_DECREF(fv);
      if (rc < 0) return -1;
    }
    return 0;
  }
  PyErr_Format(g_wire_error ? g_wire_error : PyExc_ValueError,
               "type %s is not wire-encodable", t->tp_name);
  return -1;
}

// ---------------------------------------------------------------- decode

struct Reader {
  const uint8_t *buf;
  size_t len;
  size_t pos;

  int take(size_t n, const uint8_t **out) {
    if (n > len - pos) return set_wire_error("truncated frame");
    *out = buf + pos;
    pos += n;
    return 0;
  }

  int uvarint(uint64_t *out) {
    int shift = 0;
    uint64_t n = 0;
    for (;;) {
      const uint8_t *p;
      if (take(1, &p) < 0) return -1;
      // Bits shifted past 63 must be an error, not a silent wrap:
      // Python's unbounded int keeps the huge value and then fails
      // downstream, so wrapping here would make the two decoders
      // disagree on hostile frames (cross-node decode divergence).
      if (shift == 63 && (*p & 0x7F) > 1)
        return set_wire_error("varint too long");
      n |= static_cast<uint64_t>(*p & 0x7F) << shift;
      if (!(*p & 0x80)) {
        *out = n;
        return 0;
      }
      shift += 7;
      if (shift > 63) return set_wire_error("varint too long");
    }
  }
};

// Raw-frame context: the resolved memoryview slices a 'B' frame's
// buffer table describes (wire.py's raw-buffer section).  Slices are
// built once in py_decode and borrowed by 'r' tag resolution; they
// keep the input payload alive through the master memoryview.
struct RawCtx {
  std::vector<PyObject *> slices;  // strong refs, released by caller
};

PyObject *decode_value(Reader &r, int depth, RawCtx *ctx);

PyObject *decode_int(const uint8_t *p, size_t n) {
  if (n == 0) return PyLong_FromLong(0);  // matches int.from_bytes(b"")
  if (n <= 8) {
    // Accumulate unsigned (left-shifting a negative int64 is UB
    // before C++20; this decoder compiles as C++17) and bit-cast to
    // signed at the end — the sign-extension prefix makes the final
    // pattern the two's-complement value.
    uint64_t acc = (p[0] & 0x80) ? ~uint64_t{0} : 0;
    for (size_t i = 0; i < n; ++i)
      acc = (acc << 8) | static_cast<uint64_t>(p[i]);
    int64_t val;
    static_assert(sizeof(val) == sizeof(acc), "bit-cast width");
    memcpy(&val, &acc, sizeof(val));
    return PyLong_FromLongLong(val);
  }
  PyObject *raw = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(p), static_cast<Py_ssize_t>(n));
  if (!raw) return nullptr;
  PyObject *meth = PyObject_GetAttrString(
      reinterpret_cast<PyObject *>(&PyLong_Type), "from_bytes");
  PyObject *args = meth ? Py_BuildValue("(Os)", raw, "big") : nullptr;
  PyObject *kw = args ? Py_BuildValue("{s:O}", "signed", Py_True) : nullptr;
  PyObject *out = kw ? PyObject_Call(meth, args, kw) : nullptr;
  Py_XDECREF(kw);
  Py_XDECREF(args);
  Py_XDECREF(meth);
  Py_DECREF(raw);
  return out;
}

// Count-prefixed element sequence.  The count is hostile input: never
// preallocated — each element consumes >= 1 byte, so growth is
// bounded by the payload.
int decode_items(Reader &r, int depth, uint64_t n,
                 std::vector<PyObject *> *items, RawCtx *ctx) {
  items->reserve(n < 4096 ? n : 4096);
  for (uint64_t i = 0; i < n; ++i) {
    PyObject *item = decode_value(r, depth + 1, ctx);
    if (!item) {
      for (PyObject *o : *items) Py_DECREF(o);
      items->clear();
      return -1;
    }
    items->push_back(item);
  }
  return 0;
}

PyObject *wrap_unhashable(const char *what) {
  // matches wire.py: unhashable members are a malformed frame
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject *msg = value ? PyObject_Str(value) : nullptr;
  PyErr_Format(g_wire_error, "unhashable %s: %s", what,
               msg ? PyUnicode_AsUTF8(msg) : "TypeError");
  Py_XDECREF(msg);
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return nullptr;
}

PyObject *decode_value(Reader &r, int depth, RawCtx *ctx) {
  if (depth > kMaxDepth) {
    set_wire_error("frame too deep");
    return nullptr;
  }
  const uint8_t *tp;
  if (r.take(1, &tp) < 0) return nullptr;
  uint8_t tag = *tp;
  switch (tag) {
    case 'r': {
      uint64_t idx;
      if (r.uvarint(&idx) < 0) return nullptr;
      if (!ctx || idx >= ctx->slices.size()) {
        PyErr_Format(g_wire_error,
                     "buffer ref %llu outside raw frame",
                     static_cast<unsigned long long>(idx));
        return nullptr;
      }
      PyObject *mv = ctx->slices[static_cast<size_t>(idx)];
      Py_INCREF(mv);
      return mv;
    }
    case 'N':
      Py_RETURN_NONE;
    case 'T':
      Py_RETURN_TRUE;
    case 'F':
      Py_RETURN_FALSE;
    case '0':
      Py_INCREF(g_notfound);
      return g_notfound;
    case 'i': {
      uint64_t n;
      const uint8_t *p;
      if (r.uvarint(&n) < 0 || r.take(n, &p) < 0) return nullptr;
      return decode_int(p, n);
    }
    case 'f': {
      const uint8_t *p;
      if (r.take(8, &p) < 0) return nullptr;
      uint64_t bits = 0;
      for (int i = 0; i < 8; ++i) bits = (bits << 8) | p[i];
      double d;
      std::memcpy(&d, &bits, 8);
      return PyFloat_FromDouble(d);
    }
    case 's': {
      uint64_t n;
      const uint8_t *p;
      if (r.uvarint(&n) < 0 || r.take(n, &p) < 0) return nullptr;
      PyObject *out = PyUnicode_DecodeUTF8(
          reinterpret_cast<const char *>(p),
          static_cast<Py_ssize_t>(n), nullptr);
      if (!out && PyErr_ExceptionMatches(PyExc_UnicodeDecodeError)) {
        PyErr_Clear();
        set_wire_error("bad utf-8 in frame");
      }
      return out;
    }
    case 'b': {
      uint64_t n;
      const uint8_t *p;
      if (r.uvarint(&n) < 0 || r.take(n, &p) < 0) return nullptr;
      return PyBytes_FromStringAndSize(
          reinterpret_cast<const char *>(p), static_cast<Py_ssize_t>(n));
    }
    case 't':
    case 'l':
    case 'e':
    case 'z': {
      uint64_t n;
      if (r.uvarint(&n) < 0) return nullptr;
      std::vector<PyObject *> items;
      if (decode_items(r, depth, n, &items, ctx) < 0) return nullptr;
      if (tag == 't') {
        PyObject *out = PyTuple_New(static_cast<Py_ssize_t>(items.size()));
        if (!out) {
          for (PyObject *o : items) Py_DECREF(o);
          return nullptr;
        }
        for (size_t i = 0; i < items.size(); ++i)
          PyTuple_SET_ITEM(out, static_cast<Py_ssize_t>(i), items[i]);
        return out;
      }
      if (tag == 'l') {
        PyObject *out = PyList_New(static_cast<Py_ssize_t>(items.size()));
        if (!out) {
          for (PyObject *o : items) Py_DECREF(o);
          return nullptr;
        }
        for (size_t i = 0; i < items.size(); ++i)
          PyList_SET_ITEM(out, static_cast<Py_ssize_t>(i), items[i]);
        return out;
      }
      PyObject *out = tag == 'e' ? PySet_New(nullptr)
                                 : PyFrozenSet_New(nullptr);
      if (!out) {
        for (PyObject *o : items) Py_DECREF(o);
        return nullptr;
      }
      for (size_t i = 0; i < items.size(); ++i) {
        int rc = PySet_Add(out, items[i]);
        Py_DECREF(items[i]);
        if (rc < 0) {
          for (size_t j = i + 1; j < items.size(); ++j)
            Py_DECREF(items[j]);
          Py_DECREF(out);
          if (PyErr_ExceptionMatches(PyExc_TypeError))
            return wrap_unhashable("set member");
          return nullptr;
        }
      }
      return out;
    }
    case 'd': {
      uint64_t n;
      if (r.uvarint(&n) < 0) return nullptr;
      PyObject *out = PyDict_New();
      if (!out) return nullptr;
      for (uint64_t i = 0; i < n; ++i) {
        PyObject *key = decode_value(r, depth + 1, ctx);
        if (!key) {
          Py_DECREF(out);
          return nullptr;
        }
        PyObject *val = decode_value(r, depth + 1, ctx);
        if (!val) {
          Py_DECREF(key);
          Py_DECREF(out);
          return nullptr;
        }
        int rc = PyDict_SetItem(out, key, val);
        Py_DECREF(key);
        Py_DECREF(val);
        if (rc < 0) {
          Py_DECREF(out);
          if (PyErr_ExceptionMatches(PyExc_TypeError))
            return wrap_unhashable("dict key");
          return nullptr;
        }
      }
      return out;
    }
    case 'R': {
      uint64_t code;
      if (r.uvarint(&code) < 0) return nullptr;
      if (code >= g_records.size()) {
        PyErr_Format(g_wire_error, "unknown record code %llu",
                     static_cast<unsigned long long>(code));
        return nullptr;
      }
      PyObject *fields = g_records[code].fields;
      Py_ssize_t nf = PyTuple_GET_SIZE(fields);
      PyObject *kw = PyDict_New();
      if (!kw) return nullptr;
      for (Py_ssize_t i = 0; i < nf; ++i) {
        PyObject *val = decode_value(r, depth + 1, ctx);
        if (!val) {
          Py_DECREF(kw);
          return nullptr;
        }
        int rc = PyDict_SetItem(kw, PyTuple_GET_ITEM(fields, i), val);
        Py_DECREF(val);
        if (rc < 0) {
          Py_DECREF(kw);
          return nullptr;
        }
      }
      PyObject *empty = PyTuple_New(0);
      PyObject *out = empty
          ? PyObject_Call(g_records[code].cls, empty, kw) : nullptr;
      Py_XDECREF(empty);
      Py_DECREF(kw);
      return out;
    }
    default:
      PyErr_Format(g_wire_error, "unknown tag b'%c'",
                   tag >= 0x20 && tag < 0x7F ? tag : '?');
      return nullptr;
  }
}

// ---------------------------------------------------------------- module

PyObject *py_register(PyObject *, PyObject *args) {
  PyObject *records, *notfound, *wire_error;
  if (!PyArg_ParseTuple(args, "OOO", &records, &notfound, &wire_error))
    return nullptr;
  for (Record &rec : g_records) {
    Py_DECREF(rec.cls);
    Py_DECREF(rec.fields);
  }
  g_records.clear();
  Py_ssize_t n = PySequence_Size(records);
  if (n < 0) return nullptr;
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *pair = PySequence_GetItem(records, i);
    if (!pair) return nullptr;
    PyObject *cls = PySequence_GetItem(pair, 0);
    PyObject *fields = PySequence_GetItem(pair, 1);
    Py_DECREF(pair);
    if (!cls || !fields || !PyTuple_Check(fields)) {
      Py_XDECREF(cls);
      Py_XDECREF(fields);
      PyErr_SetString(PyExc_TypeError,
                      "records must be [(cls, (field, ...)), ...]");
      return nullptr;
    }
    g_records.push_back(Record{cls, fields});
  }
  Py_XDECREF(g_notfound);
  Py_INCREF(notfound);
  g_notfound = notfound;
  Py_XDECREF(g_wire_error);
  Py_INCREF(wire_error);
  g_wire_error = wire_error;
  Py_RETURN_NONE;
}

PyObject *py_encode(PyObject *, PyObject *v) {
  if (!g_wire_error) {
    PyErr_SetString(PyExc_RuntimeError, "wire codec not registered");
    return nullptr;
  }
  Buf b;
  b.s.reserve(256);
  if (encode_value(b, v, 0) < 0) return nullptr;
  return PyBytes_FromStringAndSize(b.s.data(),
                                   static_cast<Py_ssize_t>(b.s.size()));
}

// Decode a 'B'-tagged raw frame (wire.py's raw-buffer section):
// buffer-length table, term section, then the raw bytes — resolved as
// memoryview slices of the input object (zero-copy; the slices hold
// the payload alive through the master memoryview).
PyObject *decode_raw_frame(PyObject *arg, const uint8_t *buf,
                           size_t len) {
  Reader tr{buf, len, 1};  // past the 'B' tag
  uint64_t nbufs;
  if (tr.uvarint(&nbufs) < 0) return nullptr;
  std::vector<uint64_t> lens;
  uint64_t total = 0;
  for (uint64_t i = 0; i < nbufs; ++i) {
    uint64_t n;
    if (tr.uvarint(&n) < 0) return nullptr;
    if (n > len || total + n > len) {
      set_wire_error("raw-buffer table exceeds frame");
      return nullptr;
    }
    lens.push_back(n);
    total += n;
  }
  size_t data_start = len - static_cast<size_t>(total);
  if (data_start < tr.pos) {
    set_wire_error("raw-buffer table exceeds frame");
    return nullptr;
  }
  PyObject *master = PyMemoryView_FromObject(arg);
  if (!master) return nullptr;
  RawCtx ctx;
  int ok = 0;
  size_t off = data_start;
  for (uint64_t n : lens) {
    PyObject *lo = PyLong_FromSize_t(off);
    PyObject *hi = PyLong_FromSize_t(off + static_cast<size_t>(n));
    PyObject *slice = (lo && hi) ? PySlice_New(lo, hi, nullptr)
                                 : nullptr;
    Py_XDECREF(lo);
    Py_XDECREF(hi);
    PyObject *mv = slice ? PyObject_GetItem(master, slice) : nullptr;
    Py_XDECREF(slice);
    if (!mv) {
      ok = -1;
      break;
    }
    ctx.slices.push_back(mv);
    off += static_cast<size_t>(n);
  }
  PyObject *out = nullptr;
  if (ok == 0) {
    Reader r{buf, data_start, tr.pos};
    out = decode_value(r, 0, &ctx);
    if (out && r.pos != data_start) {
      Py_DECREF(out);
      out = nullptr;
      set_wire_error("trailing bytes in frame");
    }
  }
  for (PyObject *mv : ctx.slices) Py_DECREF(mv);
  Py_DECREF(master);
  return out;
}

PyObject *py_decode(PyObject *, PyObject *arg) {
  if (!g_wire_error) {
    PyErr_SetString(PyExc_RuntimeError, "wire codec not registered");
    return nullptr;
  }
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return nullptr;
  const uint8_t *buf = static_cast<const uint8_t *>(view.buf);
  size_t len = static_cast<size_t>(view.len);
  PyObject *out;
  if (len > 0 && buf[0] == 'B') {
    out = decode_raw_frame(arg, buf, len);
  } else {
    Reader r{buf, len, 0};
    out = decode_value(r, 0, nullptr);
    if (out && r.pos != r.len) {
      Py_DECREF(out);
      out = nullptr;
      set_wire_error("trailing bytes in frame");
    }
  }
  PyBuffer_Release(&view);
  return out;
}

PyMethodDef kMethods[] = {
    {"register", py_register, METH_VARARGS,
     "register(records, notfound, wire_error)"},
    {"encode", py_encode, METH_O, "encode(value) -> bytes"},
    {"decode", py_decode, METH_O, "decode(bytes) -> value"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_retpu_wire",
    "Native restricted wire codec (see native/wirecodec.cc)", -1,
    kMethods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__retpu_wire(void) {
  return PyModule_Create(&kModule);
}
