// Single-pass resolve kernel for the batched service's per-flush host
// hot loop (the wirecodec.cc/treestore.cc precedent: plain-C ABI,
// loaded through utils/native.py's ctypes builder, pure-Python
// fallback stays the oracle — RETPU_NATIVE_RESOLVE=0).
//
// One C++ traversal replaces four Python/numpy traversals of the same
// buffers per flush:
//   1. retpu_resolve_unpack   — packed d2h payload -> full-width result
//      planes (the np.unpackbits + fancy-index scatter pipeline of
//      batched_host.unpack_results), active-column scatter included;
//   2. retpu_resolve_mirrors  — committed-write scatter into the
//      _slot_vsn / _inline_value int32 mirror slabs (the per-op dict
//      writes of the resolve loops), with the same in-order, per-column
//      semantics as the Python loop (puts flip slots to handle class,
//      RMWs to inline, leased GET hits refresh);
//   3. retpu_wal_encode       — the flush's committed keyed WAL records
//      pickled (CPython protocol-4 byte-identical for the str/bytes/
//      int32 subset) into one preallocated byte arena that
//      parallel/wal.py appends verbatim;
//   4. retpu_delta_sections   — the PR-5 changed-slot delta-frame
//      sections (cols/counts/round/slot/val + packed rmw/quorum bits +
//      zlib-compatible section CRC) repgroup.build_delta_entry ships.
//
// Contract: every output is BYTE-IDENTICAL to the Python fallback's
// (tests/test_native_resolve.py fuzzes the equivalence).  All
// multi-byte integers are little-endian (x86/arm64 hosts; numpy
// native order — the same contract the delta wire sections already
// carry).

#include <cstdint>
#include <cstring>

#include <unordered_map>

namespace {

// zlib-compatible CRC-32 (same polynomial/reflection as zlib.crc32,
// mirroring treestore.cc's framing CRC).
uint32_t crc32_update(uint32_t crc, const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int j = 0; j < 8; j++) {
        c = (c & 1) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
      }
      table[i] = c;
    }
    init = true;
  }
  crc = ~crc;
  for (size_t i = 0; i < len; i++) {
    crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  }
  return ~crc;
}

// MSB-first bit read/write (numpy packbits/unpackbits default order).
inline int get_bit(const uint8_t* buf, int64_t idx) {
  return (buf[idx >> 3] >> (7 - (idx & 7))) & 1;
}

inline void set_bit(uint8_t* buf, int64_t idx) {
  buf[idx >> 3] |= static_cast<uint8_t>(1u << (7 - (idx & 7)));
}

inline int32_t read_i32le(const uint8_t* p) {
  int32_t v;
  memcpy(&v, p, 4);  // little-endian host
  return v;
}

// ---- CPython pickle protocol-4 emitter (restricted subset) ----------
//
// Templates verified against pickle.dumps(..., protocol=4):
//   PROTO \x80\x04, FRAME \x95 + u64le length (always emitted: every
//   record body exceeds the 4-byte framing floor), SHORT_BINUNICODE
//   \x8c / BINUNICODE X, SHORT_BINBYTES C / BINBYTES B, BININT1 K /
//   BININT2 M / BININT J, NONE N, TRUE \x88 / FALSE \x89, TUPLE3
//   \x87, MARK ( + TUPLE t, MEMOIZE \x94, STOP '.'.
// MEMOIZE uses the implicit next memo index, so no index bookkeeping
// is needed; object-identity sharing (BINGET) cannot occur because
// the Python side only routes records here whose key/payload types
// make sharing impossible (str keys vs bytes/None payloads).

inline size_t pk_int_size(int64_t x) {
  if (x >= 0 && x < 256) return 2;       // K <u8>
  if (x >= 0 && x < 65536) return 3;     // M <u16le>
  return 5;                              // J <i32le>
}

inline size_t pk_str_size(int64_t n) {   // utf8 byte length n
  return (n < 256 ? 2 : 5) + static_cast<size_t>(n) + 1;  // + MEMOIZE
}

inline size_t pk_bytes_size(int64_t n) {
  return (n < 256 ? 2 : 5) + static_cast<size_t>(n) + 1;  // + MEMOIZE
}

inline uint8_t* pk_emit_int(uint8_t* p, int64_t x) {
  if (x >= 0 && x < 256) {
    *p++ = 'K';
    *p++ = static_cast<uint8_t>(x);
  } else if (x >= 0 && x < 65536) {
    *p++ = 'M';
    *p++ = static_cast<uint8_t>(x & 0xFF);
    *p++ = static_cast<uint8_t>((x >> 8) & 0xFF);
  } else {
    *p++ = 'J';
    int32_t v = static_cast<int32_t>(x);
    memcpy(p, &v, 4);
    p += 4;
  }
  return p;
}

inline uint8_t* pk_emit_strbytes(uint8_t* p, bool is_bytes,
                                 const uint8_t* data, int64_t n) {
  if (n < 256) {
    *p++ = is_bytes ? 'C' : 0x8C;
    *p++ = static_cast<uint8_t>(n);
  } else {
    *p++ = is_bytes ? 'B' : 'X';
    uint32_t v = static_cast<uint32_t>(n);
    memcpy(p, &v, 4);
    p += 4;
  }
  memcpy(p, data, static_cast<size_t>(n));
  p += n;
  *p++ = 0x94;  // MEMOIZE
  return p;
}

inline uint8_t* pk_emit_header(uint8_t* p, uint64_t body_len) {
  *p++ = 0x80;
  *p++ = 0x04;
  *p++ = 0x95;  // FRAME
  memcpy(p, &body_len, 8);
  return p + 8;
}

}  // namespace

extern "C" {

// Build-smoke / ABI handshake for utils/native.py and the tests.
// 2 = commutative-lane fold (retpu_comm_fold) added.
int retpu_resolve_version() { return 2; }

// ---------------------------------------------------------------------
// 1) Packed-result unpack: one pass over the flat d2h payload.
//
// Layout (batched_host._pack_results_body): packbits([won hw |
// quorum hw | corrupt hw*m | committed k*aw | get_ok k*aw |
// found k*aw]) ++ int32le[value k*aw | (vsn_e k*aw | vsn_s k*aw)],
// hw = aw when `sliced` else e, aw = a_width when compacted else e.
// Outputs are caller-zeroed full-width planes; only real (non-pad)
// active columns are written — bit-identical to unpack_results'
// scatter.  Returns 0, or -1 when flat_len can't hold the layout.
int retpu_resolve_unpack(
    const uint8_t* flat, int64_t flat_len,
    int32_t e, int32_t m, int32_t k, int32_t want_vsn,
    const int32_t* active, int32_t n_active, int32_t a_width,
    int32_t sliced,
    uint8_t* won, uint8_t* quorum, uint8_t* corrupt,
    uint8_t* committed, uint8_t* get_ok, uint8_t* found,
    int32_t* value, int32_t* vsn) {
  const int64_t aw = active ? a_width : e;
  const int64_t hw = (sliced && active) ? aw : e;
  const int64_t nbits = 2 * hw + hw * m + 3 * k * aw;
  const int64_t hdr = (nbits + 7) / 8;
  const int64_t need = hdr + 4 * k * aw * (want_vsn ? 3 : 1);
  if (flat_len < need || e <= 0 || m < 0 || k < 0) return -1;
  if (active && (n_active > aw || n_active < 0)) return -1;

  int64_t b = 0;
  // Election / quorum / corrupt planes.
  if (!(sliced && active)) {
    for (int64_t i = 0; i < e; i++) won[i] = get_bit(flat, b++);
    for (int64_t i = 0; i < e; i++) quorum[i] = get_bit(flat, b++);
    for (int64_t i = 0; i < e * m; i++) corrupt[i] = get_bit(flat, b++);
  } else {
    // Sliced launch: rows are A-width, scattered through the active
    // index list; pad rows (i >= n_active) are dropped.
    for (int64_t i = 0; i < hw; i++) {
      int v = get_bit(flat, b++);
      if (i < n_active) won[active[i]] = static_cast<uint8_t>(v);
    }
    for (int64_t i = 0; i < hw; i++) {
      int v = get_bit(flat, b++);
      if (i < n_active) quorum[active[i]] = static_cast<uint8_t>(v);
    }
    for (int64_t i = 0; i < hw; i++) {
      for (int64_t j = 0; j < m; j++) {
        int v = get_bit(flat, b++);
        if (i < n_active) {
          corrupt[static_cast<int64_t>(active[i]) * m + j] =
              static_cast<uint8_t>(v);
        }
      }
    }
  }
  // Client planes [k, aw] -> [k, e].
  uint8_t* bit_planes[3] = {committed, get_ok, found};
  for (int p = 0; p < 3; p++) {
    uint8_t* out = bit_planes[p];
    for (int64_t r = 0; r < k; r++) {
      for (int64_t c = 0; c < aw; c++) {
        int v = get_bit(flat, b++);
        if (!active) {
          out[r * e + c] = static_cast<uint8_t>(v);
        } else if (c < n_active) {
          out[r * e + active[c]] = static_cast<uint8_t>(v);
        }
      }
    }
  }
  // Int planes.
  const uint8_t* ip = flat + hdr;
  for (int64_t r = 0; r < k; r++) {
    for (int64_t c = 0; c < aw; c++, ip += 4) {
      if (!active) {
        value[r * e + c] = read_i32le(ip);
      } else if (c < n_active) {
        value[r * e + active[c]] = read_i32le(ip);
      }
    }
  }
  if (want_vsn) {
    for (int half = 0; half < 2; half++) {
      for (int64_t r = 0; r < k; r++) {
        for (int64_t c = 0; c < aw; c++, ip += 4) {
          if (!active) {
            vsn[(r * e + c) * 2 + half] = read_i32le(ip);
          } else if (c < n_active) {
            vsn[(r * e + active[c]) * 2 + half] = read_i32le(ip);
          }
        }
      }
    }
  }
  return 0;
}

// ---------------------------------------------------------------------
// 2) Mirror scatter: the committed-write updates of the resolve loop,
// applied straight into the service's int32 mirror slabs.
//
// Per active column (cols/kcounts from the flush's taken list), lanes
// run in round order — exactly the Python loop's per-op order, so
// duplicate-slot writes land last-writer-wins identically:
//   put/CAS committed : vsn slab <- vsn plane, inline-value invalid
//                       (the slot flips back to handle storage);
//   RMW committed     : vsn slab <- vsn plane, inline value <- result
//                       value (0 = tombstone: invalidate);
//   GET ok (&& ack_reads): vsn slab refresh; inline-value refresh only
//                       for found, nonzero, device-native slots.
// Storage-class transitions WITHIN the flush are tracked in a local
// overlay over the read-only inline_cls slab (the Python loop remains
// the slab's writer — it maintains _inline_slots either way).
int retpu_resolve_mirrors(
    int32_t e_total, int32_t s_dim,
    const int32_t* kind, const int32_t* slot,
    const uint8_t* committed, const uint8_t* get_ok,
    const uint8_t* found, const int32_t* value, const int32_t* vsn,
    const int32_t* cols, const int32_t* kcounts, int32_t n_cols,
    int32_t ack_reads,
    int32_t op_put, int32_t op_cas, int32_t op_get, int32_t op_rmw,
    int32_t* vsn_np, uint8_t* vsn_ok,
    int32_t* inl_np, uint8_t* inl_ok,
    const uint8_t* inline_cls) {
  if (e_total <= 0 || s_dim <= 0) return -1;
  std::unordered_map<int64_t, uint8_t> overlay;
  for (int32_t ci = 0; ci < n_cols; ci++) {
    const int64_t c = cols[ci];
    const int32_t kc = kcounts[ci];
    for (int32_t j = 0; j < kc; j++) {
      const int64_t idx = static_cast<int64_t>(j) * e_total + c;
      const int32_t kd = kind[idx];
      const int32_t s = slot[idx];
      if (s < 0 || s >= s_dim) continue;
      const int64_t cell = c * s_dim + s;
      if (kd == op_put || kd == op_cas) {
        if (!committed[idx]) continue;
        if (vsn) {
          vsn_np[cell * 2] = vsn[idx * 2];
          vsn_np[cell * 2 + 1] = vsn[idx * 2 + 1];
          vsn_ok[cell] = 1;
        }
        inl_ok[cell] = 0;
        overlay[cell] = 0;
      } else if (kd == op_rmw) {
        if (!committed[idx]) continue;
        if (vsn) {
          vsn_np[cell * 2] = vsn[idx * 2];
          vsn_np[cell * 2 + 1] = vsn[idx * 2 + 1];
          vsn_ok[cell] = 1;
        }
        const int32_t v = value[idx];
        if (v != 0) {
          inl_np[cell] = v;
          inl_ok[cell] = 1;
        } else {
          inl_ok[cell] = 0;  // computed tombstone
        }
        overlay[cell] = 1;
      } else if (kd == op_get) {
        if (!get_ok[idx] || !ack_reads) continue;
        if (vsn) {
          vsn_np[cell * 2] = vsn[idx * 2];
          vsn_np[cell * 2 + 1] = vsn[idx * 2 + 1];
          vsn_ok[cell] = 1;
        }
        const int32_t v = value[idx];
        if (found[idx] && v != 0) {
          auto it = overlay.find(cell);
          const uint8_t cls =
              (it != overlay.end()) ? it->second : inline_cls[cell];
          if (cls) {
            inl_np[cell] = v;
            inl_ok[cell] = 1;
          }
        }
      }
    }
  }
  return 0;
}

// ---------------------------------------------------------------------
// 3) WAL record encode: pickle the flush's committed keyed records
// into one preallocated arena.
//
// Per lane (taken order): key pickle ("kv", e, slot) and value pickle
// (key_obj, handle|computed_value, epoch, seq, payload, inline) —
// byte-identical to pickle.dumps(..., protocol=4) for the routed
// subset (str/bytes keys, bytes/None payloads, int32 ints).
// Uncommitted lanes get out_idx lengths of 0 and emit nothing.
// Returns bytes used, or -1 when `cap` would overflow (the Python
// side sizes the arena exactly, so -1 is a logic error there).
int64_t retpu_wal_encode(
    int64_t n, int32_t e_total,
    const int32_t* lane_j, const int32_t* lane_e,
    const int32_t* lane_slot, const int32_t* lane_f2,
    const uint8_t* lane_inline, const uint8_t* key_is_bytes,
    const int64_t* key_off, const int64_t* key_len,
    const uint8_t* key_arena,
    const int64_t* pay_off, const int64_t* pay_len,
    const uint8_t* pay_arena,
    const uint8_t* committed, const int32_t* value,
    const int32_t* vsn,
    uint8_t* arena, int64_t cap, int64_t* out_idx) {
  uint8_t* p = arena;
  uint8_t* const end = arena + cap;
  for (int64_t i = 0; i < n; i++) {
    const int64_t idx =
        static_cast<int64_t>(lane_j[i]) * e_total + lane_e[i];
    if (!committed[idx]) {
      out_idx[i * 4] = 0;
      out_idx[i * 4 + 1] = 0;
      out_idx[i * 4 + 2] = 0;
      out_idx[i * 4 + 3] = 0;
      continue;
    }
    // -- key: ("kv", e, slot) ---------------------------------------
    const int64_t ev = lane_e[i];
    const int64_t sv = lane_slot[i];
    const uint64_t kbody =
        5 + pk_int_size(ev) + pk_int_size(sv) + 3;
    if (p + 11 + kbody > end) return -1;
    const uint8_t* kstart = p;
    p = pk_emit_header(p, kbody);
    *p++ = 0x8C;  // SHORT_BINUNICODE "kv"
    *p++ = 2;
    *p++ = 'k';
    *p++ = 'v';
    *p++ = 0x94;
    p = pk_emit_int(p, ev);
    p = pk_emit_int(p, sv);
    *p++ = 0x87;  // TUPLE3
    *p++ = 0x94;
    *p++ = '.';
    out_idx[i * 4] = kstart - arena;
    out_idx[i * 4 + 1] = p - kstart;
    // -- value: (key, f2, epoch, seq, payload, inline) --------------
    const bool inl = lane_inline[i] != 0;
    const int64_t f2 = inl ? value[idx] : lane_f2[i];
    const int64_t ve = vsn[idx * 2];
    const int64_t vs = vsn[idx * 2 + 1];
    const int64_t kl = key_len[i];
    const int64_t pl = pay_len[i];  // -1 = None
    uint64_t vbody = 1                      // MARK
        + pk_str_size(kl)                   // key (str or bytes: same size)
        + pk_int_size(f2) + pk_int_size(ve) + pk_int_size(vs)
        + (pl < 0 ? 1 : pk_bytes_size(pl))  // payload
        + 1                                 // bool
        + 3;                                // TUPLE + MEMOIZE + STOP
    if (p + 11 + vbody > end) return -1;
    const uint8_t* vstart = p;
    p = pk_emit_header(p, vbody);
    *p++ = '(';  // MARK
    p = pk_emit_strbytes(p, key_is_bytes[i] != 0,
                         key_arena + key_off[i], kl);
    p = pk_emit_int(p, f2);
    p = pk_emit_int(p, ve);
    p = pk_emit_int(p, vs);
    if (pl < 0) {
      *p++ = 'N';
    } else {
      p = pk_emit_strbytes(p, true, pay_arena + pay_off[i], pl);
    }
    *p++ = inl ? 0x88 : 0x89;  // TRUE / FALSE
    *p++ = 't';                // TUPLE
    *p++ = 0x94;
    *p++ = '.';
    out_idx[i * 4 + 2] = vstart - arena;
    out_idx[i * 4 + 3] = p - vstart;
  }
  return p - arena;
}

// ---------------------------------------------------------------------
// 4) Changed-slot delta-frame sections (repgroup.build_delta_entry):
// committed cells in column-major (ensemble asc, round asc) order —
// the lexsort((jj, ee)) order — emitting the cols/counts/round/slot/
// val sections, the packed rmw/quorum bit vectors and the chained
// zlib CRC over the section bytes in wire order.
// out_meta = {ncells, ncols}; section buffers are caller-allocated at
// worst case (k*e cells) and consumed at the returned counts.
int retpu_delta_sections(
    int32_t k, int32_t e_dim,
    const uint8_t* committed, const int32_t* value,
    const int32_t* kind, const int32_t* slot, const int32_t* opval,
    const uint8_t* quorum,
    int32_t op_put, int32_t op_cas, int32_t op_rmw,
    int32_t j_bytes, int32_t s_bytes,
    uint16_t* cols, uint16_t* counts,
    uint8_t* jj, uint8_t* slots, int32_t* vals, uint8_t* rmw_bits,
    uint8_t* q_bits,
    int64_t* out_meta, uint32_t* out_crc) {
  if ((j_bytes != 1 && j_bytes != 2) ||
      (s_bytes != 1 && s_bytes != 2)) {
    return -1;
  }
  int64_t ncells = 0;
  int64_t ncols = 0;
  const int64_t rmw_cap = (static_cast<int64_t>(k) * e_dim + 7) / 8;
  memset(rmw_bits, 0, static_cast<size_t>(rmw_cap));
  for (int64_t c = 0; c < e_dim; c++) {
    int64_t col_count = 0;
    for (int64_t j = 0; j < k; j++) {
      const int64_t idx = j * e_dim + c;
      if (!committed[idx]) continue;
      if (j_bytes == 1) {
        jj[ncells] = static_cast<uint8_t>(j);
      } else {
        uint16_t v = static_cast<uint16_t>(j);
        memcpy(jj + ncells * 2, &v, 2);
      }
      if (s_bytes == 1) {
        slots[ncells] = static_cast<uint8_t>(slot[idx]);
      } else {
        uint16_t v = static_cast<uint16_t>(slot[idx]);
        memcpy(slots + ncells * 2, &v, 2);
      }
      const int32_t kd = kind[idx];
      vals[ncells] = (kd == op_put || kd == op_cas) ? opval[idx]
                                                    : value[idx];
      if (kd == op_rmw) set_bit(rmw_bits, ncells);
      ncells++;
      col_count++;
    }
    if (col_count) {
      cols[ncols] = static_cast<uint16_t>(c);
      counts[ncols] = static_cast<uint16_t>(col_count);
      ncols++;
    }
  }
  const int64_t qb = (e_dim + 7) / 8;
  memset(q_bits, 0, static_cast<size_t>(qb));
  for (int64_t i = 0; i < e_dim; i++) {
    if (quorum[i]) set_bit(q_bits, i);
  }
  uint32_t crc = 0;
  crc = crc32_update(crc, reinterpret_cast<const uint8_t*>(cols),
                     static_cast<size_t>(ncols) * 2);
  crc = crc32_update(crc, reinterpret_cast<const uint8_t*>(counts),
                     static_cast<size_t>(ncols) * 2);
  crc = crc32_update(crc, jj, static_cast<size_t>(ncells) * j_bytes);
  crc = crc32_update(crc, slots,
                     static_cast<size_t>(ncells) * s_bytes);
  crc = crc32_update(crc, reinterpret_cast<const uint8_t*>(vals),
                     static_cast<size_t>(ncells) * 4);
  crc = crc32_update(crc, rmw_bits,
                     static_cast<size_t>((ncells + 7) / 8));
  crc = crc32_update(crc, q_bits, static_cast<size_t>(qb));
  out_meta[0] = ncells;
  out_meta[1] = ncols;
  *out_crc = crc;
  return 0;
}

// ---------------------------------------------------------------------
// 5) Commutative-lane per-column fold (repgroup.build_comm_entry's
// Python fold, one pass; docs/ARCHITECTURE.md §18): for every
// candidate column, coalesce its committed OP_RMW cells per slot in
// FIRST-SEEN slot order, folding operands with the exact int32
// semantics of funref.fold_seed/fold_operand (sub enters negated —
// MERGE_ADD normalization — under uint32 wraparound arithmetic).
// Each surviving cell carries (slot, merge class, folded operand,
// rank of the slot's LAST committed op within the column, that op's
// round index).  A candidate column where one slot mixes merge
// classes is DISQUALIFIED: omitted from out_cols entirely (the
// caller ships it through the ordered sections).
//
// merge_of[16]: RMW fun code -> merge class, -1 = ordered (built from
// funref.MERGE_OF — merge-class codes pinned by funref.MERGE_*);
// negate[16]: 1 = the operand enters the fold negated (RMW_SUB).
// out buffers are caller-allocated: cols/counts/nops at e_dim,
// slots/funs/ops/rl/jl at the flush's committed-cell count.
// out_meta = {n_qual_cols, n_cells}.
int retpu_comm_fold(
    int32_t k, int32_t e_dim,
    const uint8_t* committed, const int32_t* exp_e,
    const int32_t* slot, const int32_t* val,
    const uint8_t* cand,
    const int32_t* merge_of, const uint8_t* negate,
    int32_t* out_cols, int32_t* out_counts, int32_t* out_nops,
    int32_t* out_slots, uint8_t* out_funs, int32_t* out_ops,
    int32_t* out_rl, int32_t* out_jl,
    int64_t* out_meta) {
  if (k < 0 || e_dim <= 0) return -1;
  int64_t ncols = 0;
  int64_t ncells = 0;
  std::unordered_map<int32_t, int64_t> first;  // slot -> cell index
  for (int64_t c = 0; c < e_dim; c++) {
    if (!cand[c]) continue;
    first.clear();
    const int64_t base = ncells;
    int32_t nops = 0;
    bool ok = true;
    for (int64_t j = 0; j < k; j++) {
      const int64_t idx = j * e_dim + c;
      if (!committed[idx]) continue;
      const int32_t code = exp_e[idx];
      const int32_t mcls =
          (code >= 0 && code < 16) ? merge_of[code] : -1;
      if (mcls < 0) {  // cand miscomputed: conservatively ordered
        ok = false;
        break;
      }
      const int32_t v = val[idx];
      const int32_t nv = negate[code]
          ? static_cast<int32_t>(0u - static_cast<uint32_t>(v))
          : v;
      const int32_t rank = nops++;
      auto it = first.find(slot[idx]);
      if (it == first.end()) {
        first.emplace(slot[idx], ncells);
        out_slots[ncells] = slot[idx];
        out_funs[ncells] = static_cast<uint8_t>(mcls);
        out_ops[ncells] = nv;
        out_rl[ncells] = rank;
        out_jl[ncells] = static_cast<int32_t>(j);
        ncells++;
      } else {
        const int64_t ci = it->second;
        if (out_funs[ci] != mcls) {  // mixed classes on one slot
          ok = false;
          break;
        }
        int32_t acc = out_ops[ci];
        switch (mcls) {
          case 0:  // MERGE_ADD (int32 wraparound)
            acc = static_cast<int32_t>(static_cast<uint32_t>(acc) +
                                       static_cast<uint32_t>(nv));
            break;
          case 1:  // MERGE_MAX
            acc = acc > nv ? acc : nv;
            break;
          case 2:  // MERGE_MIN
            acc = acc < nv ? acc : nv;
            break;
          case 3:  // MERGE_AND
            acc = acc & nv;
            break;
          default:  // MERGE_OR
            acc = acc | nv;
            break;
        }
        out_ops[ci] = acc;
        out_rl[ci] = rank;
        out_jl[ci] = static_cast<int32_t>(j);
      }
    }
    if (!ok) {
      ncells = base;  // drop the column's partial cells
      continue;
    }
    out_cols[ncols] = static_cast<int32_t>(c);
    out_counts[ncols] = static_cast<int32_t>(ncells - base);
    out_nops[ncols] = nops;
    ncols++;
  }
  out_meta[0] = ncols;
  out_meta[1] = ncells;
  return 0;
}

}  // extern "C"
