"""Native C++ components: monotonic clock (the clock-NIF role,
c_src/riak_ensemble_clock.c) and the treestore engine (the eleveldb
role, synctree_leveldb.erl) — plus ensemble_tests_pure.erl parity
(clock monotonicity).
"""

import pytest

from riak_ensemble_tpu.synctree import native_store
from riak_ensemble_tpu.utils import clock, native

needs_native = pytest.mark.skipif(native.load() is None,
                                  reason="native toolchain unavailable")


# -- clock (ensemble_tests_pure.erl monotonicity test) ----------------------


def test_clock_monotonic():
    readings = [clock.monotonic_time_ns() for _ in range(1000)]
    assert all(b >= a for a, b in zip(readings, readings[1:]))
    assert readings[-1] > 0


def test_clock_ms_coherent():
    ms = clock.monotonic_time_ms()
    ns = clock.monotonic_time_ns()
    assert 0 <= ns // 1_000_000 - ms < 10_000


@needs_native
def test_native_clock_loaded():
    lib = native.load()
    t1 = lib.retpu_monotonic_time_ns()
    t2 = lib.retpu_monotonic_time_ns()
    assert 0 < t1 <= t2


# -- treestore engine -------------------------------------------------------


@needs_native
def test_store_basic(tmp_path):
    be = native_store.NativeBackend(str(tmp_path / "t.db"))
    assert be.fetch(("x",)) is None
    be.store(("x",), {"a": b"1"})
    assert be.fetch(("x",)) == {"a": b"1"}
    assert be.exists(("x",))
    be.store(("x",), {"a": b"2"})
    assert be.fetch(("x",)) == {"a": b"2"}
    be.delete(("x",))
    assert not be.exists(("x",))
    be.close()


@needs_native
def test_store_reload_and_compact(tmp_path):
    path = str(tmp_path / "t.db")
    be = native_store.NativeBackend(path)
    for i in range(500):
        be.store((1, i), i.to_bytes(4, "big"))
    for i in range(0, 500, 2):
        be.delete((1, i))
    be.compact()
    for i in range(500, 600):
        be.store((1, i), i.to_bytes(4, "big"))
    be.sync()
    assert be.count() == 250 + 100
    be.close()

    # reopen: snapshot + log replay reconstruct the same contents
    be2 = native_store.NativeBackend(path)
    assert be2.count() == 350
    assert be2.fetch((1, 1)) == (1).to_bytes(4, "big")
    assert be2.fetch((1, 0)) is None
    assert be2.fetch((1, 599)) == (599).to_bytes(4, "big")
    assert len(list(be2.keys())) == 350
    be2.close()


@needs_native
def test_store_shared_registry(tmp_path):
    """Two opens of one path share a single engine
    (synctree_leveldb.erl:52-83 shared-DB registry)."""
    path = str(tmp_path / "shared.db")
    a = native_store.NativeBackend(path)
    b = native_store.NativeBackend(path)
    a.store("k", b"v")
    assert b.fetch("k") == b"v"
    a.close()
    assert b.fetch("k") == b"v"  # refcounted: engine still open
    b.close()


@needs_native
def test_store_torn_tail_recovery(tmp_path):
    """A torn final log record is discarded; prior records survive
    (the WAL-framing guarantee the 4-copy CRC save format provides for
    facts — save.erl:49-56 spirit)."""
    path = str(tmp_path / "torn.db")
    be = native_store.NativeBackend(path)
    be.store("a", b"1")
    be.store("b", b"2")
    be.sync()
    be.close()

    with open(path + ".log", "ab") as f:
        f.write(b"\x00\x01\x02")  # garbage partial frame

    be2 = native_store.NativeBackend(path)
    assert be2.fetch("a") == b"1"
    assert be2.fetch("b") == b"2"
    assert be2.count() == 2
    be2.close()


# -- synctree over the native engine ---------------------------------------


@needs_native
def test_synctree_on_native_backend(tmp_path):
    from riak_ensemble_tpu.synctree.tree import SyncTree

    path = str(tmp_path / "tree.db")
    be = native_store.NativeBackend(path)
    t = SyncTree(tree_id=b"p1", segments=16**3, backend=be)
    for i in range(100, 0, -1):
        assert t.insert(i, (i * 10).to_bytes(8, "big")) is None
    assert t.get(42) == (420).to_bytes(8, "big")
    top = t.top_hash
    be.sync()
    be.close()

    be2 = native_store.NativeBackend(path)
    t2 = SyncTree(tree_id=b"p1", segments=16**3, backend=be2)
    assert t2.top_hash == top
    assert t2.get(42) == (420).to_bytes(8, "big")
    assert t2.verify()
    be2.close()


# -- resolve kernel (native/resolvekernel.cc) -------------------------------


def test_resolve_kernel_build_smoke():
    """The explicit $(RESOLVESO) make target builds and exports the
    full resolve-kernel ABI; a missing toolchain degrades to None
    (never an exception) — the graceful-degradation contract of
    utils/native.load_resolve."""
    lib = native.load_resolve()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    assert lib.retpu_resolve_version() >= 1
    for sym in ("retpu_resolve_unpack", "retpu_resolve_mirrors",
                "retpu_wal_encode", "retpu_delta_sections"):
        assert hasattr(lib, sym), sym


def test_enqueue_kernel_build_smoke():
    """The sibling enqueue kernel (native/enqueuekernel.cc) rides the
    SAME $(RESOLVESO) target and .so: when the resolve library builds,
    the enqueue symbols must be there too (a stale .so without them
    degrades through enqueue_native.get() -> None, never a crash)."""
    lib = native.load_resolve()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    assert hasattr(lib, "retpu_enqueue_pack")
    assert lib.retpu_enqueue_version() >= 1


@needs_native
def test_store_put_many_matches_per_record(tmp_path):
    """The arena batch append (the resolve kernel's WAL path) must
    leave byte-identical log files and store contents to per-record
    puts."""
    import numpy as np

    recs = [(b"k%d" % i, b"v%d" % (i * 7)) for i in range(20)]
    a = native_store.NativeBackend(str(tmp_path / "a.db"))
    for k, v in recs:
        a.store_raw(k, v)
    a.sync()
    a.close()
    arena = b"".join(k + v for k, v in recs)
    idx = []
    off = 0
    for k, v in recs:
        idx.append((off, len(k), off + len(k), len(v)))
        off += len(k) + len(v)
    # interleave a skipped (uncommitted) row: key_len 0 rows drop
    idx.insert(3, (0, 0, 0, 0))
    b = native_store.NativeBackend(str(tmp_path / "b.db"))
    b.put_many_raw(np.frombuffer(arena, np.uint8),
                   np.asarray(idx, np.int64))
    b.sync()
    b.close()
    la = open(str(tmp_path / "a.db") + ".log", "rb").read()
    lb = open(str(tmp_path / "b.db") + ".log", "rb").read()
    assert la == lb
    b2 = native_store.NativeBackend(str(tmp_path / "b.db"))
    assert b2.count() == len(recs)
    b2.close()


@needs_native
@pytest.mark.parametrize("seed", range(3))
def test_store_randomized_against_dict_model(tmp_path, seed):
    """Property sweep for the C++ store: random puts/overwrites/
    deletes interleaved with sync, compaction, and full close/reopen
    cycles must match a plain dict model exactly — keys, values, and
    counts (the synctree_eqc-style differential check for the
    eleveldb-role component)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    path = str(tmp_path / f"prop{seed}.db")
    be = native_store.NativeBackend(path)
    model = {}
    keyspace = [("k", int(i)) for i in range(40)]

    for step in range(600):
        r = rng.random()
        key = keyspace[int(rng.integers(len(keyspace)))]
        if r < 0.55:
            val = {"v": bytes(rng.integers(0, 256, int(rng.integers(0, 24)),
                                           dtype=np.uint8)),
                   "n": int(rng.integers(1 << 30))}
            be.store(key, val)
            model[key] = val
        elif r < 0.75:
            be.delete(key)
            model.pop(key, None)
        elif r < 0.85:
            be.sync()
        elif r < 0.93:
            be.compact()
        else:
            be.close()
            be = native_store.NativeBackend(path)  # reopen: WAL replay

        if step % 97 == 0:  # periodic full-state comparison
            assert be.count() == len(model)
            for k in keyspace:
                assert be.fetch(k) == model.get(k), (seed, step, k)

    be.close()
    be = native_store.NativeBackend(path)
    assert be.count() == len(model)
    assert sorted(map(repr, be.keys())) == sorted(map(repr, model))
    for k, v in model.items():
        assert be.fetch(k) == v
    be.close()
