"""Dynamic replication-group host membership (VERDICT r4 missing #1).

The reference reconfigures an ensemble's member set across machines at
runtime via joint consensus — add/remove/replace with multi-view
quorums until collapse (riak_ensemble_peer.erl:655-672 update_members,
:751-774 transition; acceptance shape: test/replace_members_test.erl
replacing root/2/3 -> 4/5/6).  These tests drive the host-granularity
analog on :mod:`riak_ensemble_tpu.parallel.repgroup`:

- grow a 3-host group to 5 LIVE under client load (zero failed acks),
- replace a kill -9'd host with a fresh blank one, zero acked-write
  loss, with the joiner proven to carry a quorum afterwards,
- a linearizability sweep green across the transition window,
- ``update_members`` on a repgroup no longer raises.
"""

import os
import signal
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import conftest  # noqa: F401

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.linearizability import KeyModel  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import WallRuntime  # noqa: E402
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

N_ENS = 4
#: generous: the phase writes allocate ~12 distinct keys per ensemble
N_SLOTS = 32

#: the in-process leader's identity in member lists (a pure identity:
#: replicas only dial it for failover ranking, which these tests don't
#: enable)
LEADER_ADDR = ("leader.test", 1)


def _spawn_replica(data_dir: str, repl_port: int = 0,
                   client_port: int = 0):
    child = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        from riak_ensemble_tpu.parallel import repgroup
        repgroup.main(["--n-ens", "{N_ENS}", "--group-size", "3",
                       "--n-slots", "{N_SLOTS}", "--fast",
                       "--repl-port", "{repl_port}",
                       "--client-port", "{client_port}",
                       "--data-dir", {data_dir!r}])
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True, env=env)
    line = p.stdout.readline()
    assert line, p.stderr.read()[-3000:]
    parts = dict(kv.split("=") for kv in line.split()[2:])
    return p, int(parts["repl"]), int(parts["client"])


def _make_leader(tmp_path, repl_ports, ack_timeout=15.0):
    svc = repgroup.ReplicatedService(
        WallRuntime(), N_ENS, 1, N_SLOTS, group_size=3,
        peers=[("127.0.0.1", p) for p in repl_ports],
        ack_timeout=ack_timeout, config=fast_test_config(),
        data_dir=str(tmp_path / "leader"), self_addr=LEADER_ADDR)
    repgroup.warmup_kernels(svc)
    assert svc.takeover(), "takeover needs a majority of replicas"
    return svc


def _settle(svc, futs, flushes=8):
    for _ in range(flushes):
        if all(f.done for f in futs):
            break
        svc.flush()
    assert all(f.done for f in futs)
    return [f.value for f in futs]


def _drive_until(svc, cond, deadline=120.0, what="condition"):
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        svc.heartbeat()
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"{what} never reached: "
                         f"{svc.membership_status()} / "
                         f"{svc.stats()['group']}")


def _collapsed_to(svc, hosts):
    def cond():
        st = svc.membership_status()
        return (not st["transition"] and st["joint"] is None
                and st["hosts"] is not None
                and set(map(tuple, st["hosts"])) == set(hosts))
    return cond


def _synced(svc, n):
    return lambda: svc.stats()["group"]["peers_synced"] >= n


def _kill(procs, name):
    p = procs[name][0]
    if p.poll() is None:
        p.send_signal(signal.SIGKILL)
        p.wait()


def test_grow_3_to_5_live_under_load(tmp_path):
    """Grow the host set 3 -> 5 while clients keep writing: no failed
    acks through the transition, both joiners sync and are counted —
    proven by killing BOTH original replicas afterwards (the remaining
    leader + 2 joiners are a majority of 5 only if the joiners carry
    full state) — and zero acked writes lost."""
    procs, dirs = {}, {}
    try:
        for name in ("r1", "r2"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        svc = _make_leader(tmp_path,
                           [procs["r1"][1], procs["r2"][1]])
        acked = {}

        def put_ok(phase, n=8):
            futs = []
            for i in range(n):
                e, key = i % N_ENS, f"{phase}-{i}"
                val = b"%s/%d" % (phase.encode(), i)
                futs.append((e, key, val, svc.kput(e, key, val)))
            _settle(svc, [f for *_, f in futs])
            for e, key, val, f in futs:
                assert f.value[0] == "ok", (phase, key, f.value)
                acked[(e, key)] = val

        put_ok("pre")

        for name in ("r3", "r4"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        old = [LEADER_ADDR, ("127.0.0.1", procs["r1"][1]),
               ("127.0.0.1", procs["r2"][1])]
        new = old + [("127.0.0.1", procs["r3"][1]),
                     ("127.0.0.1", procs["r4"][1])]
        svc.update_members(new)

        # client load DURING the transition: every ack must be real
        for wave in range(6):
            put_ok(f"mid{wave}", n=4)
        _drive_until(svc, _collapsed_to(svc, new), what="collapse")
        assert svc.stats()["group"]["quorum_failures"] == 0, \
            svc.stats()["group"]
        put_ok("post")
        _drive_until(svc, _synced(svc, 4), what="4 peers synced")

        # the joiners are REAL members: kill both original replicas —
        # leader + r3 + r4 is a majority of 5 only with synced joiners
        _kill(procs, "r1")
        _kill(procs, "r2")
        put_ok("final")
        futs = [(e, key, val, svc.kget(e, key))
                for (e, key), val in acked.items()]
        _settle(svc, [f for *_, f in futs], flushes=12)
        for e, key, val, f in futs:
            assert f.value == ("ok", val), \
                f"acked write lost at {(e, key)}: {f.value!r}"
        assert svc.group_size == 5
        svc.stop()
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


def test_replace_dead_host_with_blank(tmp_path):
    """Replace a kill -9'd host with a fresh blank one: the transition
    commits on old/new majorities that never include the dead host,
    the blank joiner instals the full state before being counted, and
    after collapse it carries the quorum (the other replica killed) —
    zero acked-write loss end to end.  The acceptance shape of
    replace_members_test.erl at host granularity."""
    procs, dirs = {}, {}
    try:
        for name in ("r1", "r2"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        svc = _make_leader(tmp_path,
                           [procs["r1"][1], procs["r2"][1]])
        acked = {}

        def put_ok(phase, n=6):
            futs = []
            for i in range(n):
                e, key = i % N_ENS, f"{phase}-{i}"
                val = b"%s/%d" % (phase.encode(), i)
                futs.append((e, key, val, svc.kput(e, key, val)))
            _settle(svc, [f for *_, f in futs])
            for e, key, val, f in futs:
                assert f.value[0] == "ok", (phase, key, f.value)
                acked[(e, key)] = val

        put_ok("pre")
        _kill(procs, "r2")
        put_ok("one-down")  # 2/3 majority still commits

        dirs["r3"] = str(tmp_path / "r3")
        procs["r3"] = _spawn_replica(dirs["r3"])  # blank
        new = [LEADER_ADDR, ("127.0.0.1", procs["r1"][1]),
               ("127.0.0.1", procs["r3"][1])]
        svc.update_members(new)
        put_ok("during")
        _drive_until(svc, _collapsed_to(svc, new), what="collapse")
        _drive_until(svc, _synced(svc, 2), what="r1+r3 synced")
        put_ok("post")

        # the blank joiner now carries the quorum on its own
        _kill(procs, "r1")
        put_ok("final")
        futs = [(e, key, val, svc.kget(e, key))
                for (e, key), val in acked.items()]
        _settle(svc, [f for *_, f in futs], flushes=12)
        for e, key, val, f in futs:
            assert f.value == ("ok", val), \
                f"acked write lost at {(e, key)}: {f.value!r}"
        st = svc.membership_status()
        assert ("127.0.0.1", procs["r2"][1]) not in set(
            map(tuple, st["hosts"]))
        svc.stop()
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


@pytest.mark.parametrize("seed", conftest.soak_seeds([2201]))
def test_linearizable_across_membership_transition(tmp_path, seed):
    """sc.erl across the transition window: random put/get load runs
    while the group grows 3 -> 5; every acked write must be readable
    afterwards (KeyModel raises Violation on lost/stale values);
    host-quorum 'failed' writes stay ambiguous via timeout_write."""
    rng = np.random.default_rng(seed)
    procs, dirs = {}, {}
    models = {}
    vals = iter(range(1, 100000))

    def model(e, k):
        return models.setdefault((e, k), KeyModel(f"{e}/k{k}"))

    try:
        for name in ("r1", "r2"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        svc = _make_leader(tmp_path,
                           [procs["r1"][1], procs["r2"][1]],
                           ack_timeout=6.0)
        started = False
        new = None
        for rnd in range(10):
            if rnd == 3:  # mid-run: start the grow transition
                for name in ("r3", "r4"):
                    dirs[name] = str(tmp_path / name)
                    procs[name] = _spawn_replica(dirs[name])
                new = [LEADER_ADDR,
                       ("127.0.0.1", procs["r1"][1]),
                       ("127.0.0.1", procs["r2"][1]),
                       ("127.0.0.1", procs["r3"][1]),
                       ("127.0.0.1", procs["r4"][1])]
                svc.update_members(new)
                started = True
            pending = []
            for _ in range(6):
                e = int(rng.integers(N_ENS))
                k = int(rng.integers(3))
                m = model(e, k)
                if rng.random() < 0.6:
                    v = next(vals)
                    op = m.invoke_write(v)
                    pending.append(("put", m, op,
                                    svc.kput(e, f"k{k}",
                                             v.to_bytes(4, "big"))))
                else:
                    pending.append(("get", m, None,
                                    svc.kget(e, f"k{k}")))
            for _ in range(10):
                if all(f.done for *_, f in pending):
                    break
                svc.flush()
            for kind, m, op, f in pending:
                assert f.done
                res = f.value
                if kind == "put":
                    if isinstance(res, tuple) and res[0] == "ok":
                        m.ack_write(op)
                    else:
                        m.timeout_write(op)
                else:
                    if isinstance(res, tuple) and res[0] == "ok":
                        v = res[1]
                        m.ack_read(v if v is NOTFOUND
                                   else int.from_bytes(v, "big"))
        assert started
        _drive_until(svc, _collapsed_to(svc, new), what="collapse")
        # read back every key through the post-transition group
        pending = [(m, svc.kget(e, f"k{k}"))
                   for (e, k), m in models.items()]
        for _ in range(12):
            if all(f.done for _, f in pending):
                break
            svc.flush()
        for m, f in pending:
            assert f.done and isinstance(f.value, tuple) \
                and f.value[0] == "ok", f.value
            v = f.value[1]
            m.ack_read(v if v is NOTFOUND
                       else int.from_bytes(v, "big"))
        svc.stop()
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


def test_update_members_forms(tmp_path):
    """update_members on a repgroup no longer raises: the host form
    runs the transition machinery (no-op when the set is unchanged);
    the two-arg per-ensemble view form still works in single-lane
    mode and raises a TYPED, documented error on a real group."""
    rt = Runtime(seed=3)
    solo = repgroup.ReplicatedService(
        rt, N_ENS, 1, N_SLOTS, group_size=1,
        config=fast_test_config(), self_addr=("solo.test", 1))
    # two-arg view form delegates to the base class in single-lane mode
    sel = np.zeros((N_ENS,), bool)
    view = np.ones((N_ENS, 1), bool)
    solo.update_members(sel, view)  # no raise
    # host form: unchanged set is a no-op (requires leadership)
    solo._is_leader = True
    solo.update_members([("solo.test", 1)])
    assert solo.membership_status()["transition"] is False
    solo.stop()


def test_leader_transitions_itself_out(tmp_path):
    """The reference peer shuts down when it is not a member of the
    final view (transition, peer.erl:756-774): a leader may run a
    transition that REMOVES ITSELF.  The collapse commits under the
    joint rule, the ex-leader steps down (deposed), and a remaining
    member promotes and serves every acked write."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    procs, dirs = {}, {}
    try:
        for name in ("r1", "r2"):
            dirs[name] = str(tmp_path / name)
            procs[name] = _spawn_replica(dirs[name])
        svc = _make_leader(tmp_path,
                           [procs["r1"][1], procs["r2"][1]])
        acked = {}
        futs = []
        for i in range(8):
            e, key, val = i % N_ENS, f"k{i}", b"v%d" % i
            futs.append(svc.kput(e, key, val))
            acked[(e, key)] = val
        _settle(svc, futs)
        assert all(f.value[0] == "ok" for f in futs)

        # transition the leader OUT: new set = the two replicas only
        new = [("127.0.0.1", procs["r1"][1]),
               ("127.0.0.1", procs["r2"][1])]
        svc.update_members(new)
        try:
            _drive_until(svc, lambda: svc._deposed,
                         what="ex-member leader step-down")
        except repgroup.DeposedError:
            pass  # the step-down landed between cond checks
        assert svc._deposed, "ex-member leader never stepped down"
        st = svc.membership_status()
        assert st["joint"] is None and \
            set(map(tuple, st["hosts"])) == set(new), st

        # a remaining member promotes under the 2-host config and
        # serves every acked write
        r1_repl, r1_client = procs["r1"][1], procs["r1"][2]
        with socket.create_connection(
                ("127.0.0.1", r1_repl), timeout=120.0) as s:
            s.settimeout(120.0)
            repgroup.send_frame(
                s, ("promote", [("127.0.0.1", procs["r2"][1])]))
            resp = repgroup.recv_frame(s)
        assert resp[0] == "ok", resp

        async def read_all():
            c = svcnode.ServiceClient("127.0.0.1", r1_client)
            await c.connect()
            for (e, key), val in acked.items():
                r = await c.kget(e, key, timeout=120.0)
                assert r == ("ok", val), (key, r)
            r = await c.kput(0, "post", b"new", timeout=120.0)
            assert r[0] == "ok", r
            await c.close()
        asyncio.run(read_all())
        svc.stop()
    finally:
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
