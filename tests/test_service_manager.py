"""Consensus-managed scale-plane membership (VERDICT r3 #3): tenants
flow through the root ensemble + gossip, placement derives from the
svcnode directory, and reconciliation loops converge every node's
batched service — joining a new svcnode rebalances tenants via gossip
alone (manager.erl:610-641 / check_peers:697-715 for the scale
plane)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import service_directory as sd  # noqa: E402
from riak_ensemble_tpu import service_manager as sm  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService)
from riak_ensemble_tpu.testing import ManagedCluster  # noqa: E402

N_ENS, N_PEERS, N_SLOTS = 16, 3, 8
TENANTS = [f"tenant{i}" for i in range(10)]


def _bring_up(mc, node, name, registry):
    svc = BatchedEnsembleService(
        mc.runtime, N_ENS, N_PEERS, N_SLOTS, tick=0.05,
        config=fast_test_config(), dynamic=True)
    rec = sm.ServiceReconciler(mc.runtime, mc.mgr(node), svc, name,
                               registry.get, poll=0.2)
    registry[name] = rec
    r = sd.register_service(mc.mgr(node), mc.runtime, name,
                            "127.0.0.1", 7000 + len(registry),
                            (N_ENS, N_PEERS, N_SLOTS))
    assert r == "ok", r
    return svc, rec


def _settle_fut(mc, fut, t=60.0):
    ok = mc.runtime.run_until(lambda: fut.done, t)
    assert ok, "future never resolved"
    return fut.value


def test_join_rebalances_tenants_via_gossip_alone():
    mc = ManagedCluster(seed=5, nodes=("node0", "node1"))
    mc.enable("node0")
    mc.join("node1", "node0")
    registry = {}
    svc0, rec0 = _bring_up(mc, "node0", "svc@node0", registry)

    # tenants enter the cluster through the ROOT ensemble
    for t in TENANTS:
        assert sm.create_tenant(mc.mgr("node0"), mc.runtime, t) == "ok"

    # the single svcnode adopts everything (reconciliation, not a
    # direct create_ensemble call)
    ok = mc.runtime.run_until(
        lambda: all(svc0.resolve_ensemble(t) is not None
                    for t in TENANTS), 60.0)
    assert ok, "tenants never reconciled onto the only svcnode"

    # real data in every tenant
    written = {}
    futs = []
    for i, t in enumerate(TENANTS):
        ens = svc0.resolve_ensemble(t)
        val = b"payload-%d" % i
        futs.append(svc0.kput(ens, "k", val))
        written[t] = val
    for f in futs:
        assert _settle_fut(mc, f)[0] == "ok"

    # -- join a second svcnode: ONE registration through the root;
    #    everything after rides gossip + local reconciliation --------
    svc1, rec1 = _bring_up(mc, "node1", "svc@node1", registry)

    both = ["svc@node0", "svc@node1"]
    moved = [t for t in TENANTS if sm.place(t, both) == "svc@node1"]
    stayed = [t for t in TENANTS if t not in moved]
    assert moved and stayed, "rendezvous should split the tenants"

    def converged():
        return (all(svc1.resolve_ensemble(t) is not None
                    and svc0.resolve_ensemble(t) is None
                    for t in moved)
                and all(svc0.resolve_ensemble(t) is not None
                        and svc1.resolve_ensemble(t) is None
                        for t in stayed)
                and not rec1._importing)
    assert mc.runtime.run_until(converged, 120.0), (
        "rebalance never converged: "
        f"moved={[(t, svc0.resolve_ensemble(t), svc1.resolve_ensemble(t)) for t in moved]}")

    # handoff carried the data: moved tenants read back on the NEW
    # owner; stayed tenants untouched on the old one
    for t, svc in [(t, svc1) for t in moved] + \
                  [(t, svc0) for t in stayed]:
        f = svc.kget(svc.resolve_ensemble(t), "k")
        assert _settle_fut(mc, f) == ("ok", written[t]), t

    # -- consensus-managed per-tenant view change ---------------------
    target = stayed[0]
    r = sm.set_tenant_view(mc.mgr("node0"), mc.runtime, target,
                           [True, True, False])
    assert r == "ok", r
    ens = svc0.resolve_ensemble(target)
    ok = mc.runtime.run_until(
        lambda: (svc0.member_np[ens] == [True, True, False]).all(),
        60.0)
    assert ok, "registry view change never reconciled into the device"
    # data survives the joint-consensus transition
    f = svc0.kget(ens, "k")
    assert _settle_fut(mc, f) == ("ok", written[target])

    # -- retire through the root: every copy converges away ----------
    r = sm.retire_tenant(mc.mgr("node0"), mc.runtime, moved[0])
    assert r == "ok", r
    ok = mc.runtime.run_until(
        lambda: (svc1.resolve_ensemble(moved[0]) is None
                 and svc0.resolve_ensemble(moved[0]) is None), 60.0)
    assert ok, "retired tenant still running somewhere"

    rec0.stop()
    rec1.stop()
    svc0.stop()
    svc1.stop()


def test_tenant_placement_is_stable_and_minimal():
    """Rendezvous properties the rebalance story depends on: same
    inputs → same owner everywhere; adding a node only ever moves
    tenants TO the new node."""
    one = ["a"]
    two = ["a", "b"]
    owners_one = {t: sm.place(t, one) for t in TENANTS}
    owners_two = {t: sm.place(t, two) for t in TENANTS}
    assert all(o == "a" for o in owners_one.values())
    for t in TENANTS:
        assert owners_two[t] in ("a", "b")
        if owners_two[t] != owners_one[t]:
            assert owners_two[t] == "b"
    # and the registered-directory order can't change the answer
    assert {t: sm.place(t, ["b", "a"]) for t in TENANTS} == owners_two


def test_handoff_survives_capacity_pressure_and_late_offers():
    """Review r4: (a) a capacity-failed adoption must keep the
    handoff payload for the retry tick (not drop it with the popped
    inbox entry); (b) a handoff arriving AFTER an empty adoption
    merges create-if-missing — local writes made since stay newest."""
    mc = ManagedCluster(seed=6, nodes=("node0",))
    mc.enable("node0")
    registry = {}
    # a 2-row service: capacity pressure is real
    svc = BatchedEnsembleService(
        mc.runtime, 2, N_PEERS, N_SLOTS, tick=0.05,
        config=fast_test_config(), dynamic=True)
    rec = sm.ServiceReconciler(mc.runtime, mc.mgr("node0"), svc,
                               "svc@node0", registry.get, poll=0.2)
    registry["svc@node0"] = rec
    r = sd.register_service(mc.mgr("node0"), mc.runtime, "svc@node0",
                            "127.0.0.1", 7100, (2, N_PEERS, N_SLOTS))
    assert r == "ok", r

    # fill both rows with registry tenants (the reconciler keeps
    # registered tenants and destroys strays, so blockers must be
    # real), then hand a third tenant off: its adoption must fail on
    # capacity WITHOUT losing the payload
    for b in ("blocker0", "blocker1"):
        assert sm.create_tenant(mc.mgr("node0"), mc.runtime, b) == "ok"
    ok = mc.runtime.run_until(
        lambda: all(svc.resolve_ensemble(b) is not None
                    for b in ("blocker0", "blocker1")), 60.0)
    assert ok
    assert sm.create_tenant(mc.mgr("node0"), mc.runtime, "t-cap") \
        == "ok"
    rec.offer_handoff("t-cap", [("k", b"precious")])
    mc.runtime.run_for(5.0)
    assert svc.resolve_ensemble("t-cap") is None  # no capacity yet
    assert rec._inbox.get("t-cap"), "payload dropped under capacity"

    # free a row through the registry: adoption completes WITH data
    assert sm.retire_tenant(mc.mgr("node0"), mc.runtime, "blocker0") \
        == "ok"
    ok = mc.runtime.run_until(
        lambda: (svc.resolve_ensemble("t-cap") is not None
                 and not rec._importing), 60.0)
    assert ok
    f = svc.kget(svc.resolve_ensemble("t-cap"), "k")
    assert _settle_fut(mc, f) == ("ok", b"precious")

    # late handoff into a LIVE tenant: local data wins per key,
    # absent keys fill in
    ens = svc.resolve_ensemble("t-cap")
    f = svc.kput(ens, "local", b"newer")
    assert _settle_fut(mc, f)[0] == "ok"
    rec.offer_handoff("t-cap", [("local", b"stale"),
                                ("extra", b"carried")])
    ok = mc.runtime.run_until(
        lambda: "t-cap" not in rec._inbox and not rec._importing,
        60.0)
    assert ok
    f1 = svc.kget(ens, "local")
    f2 = svc.kget(ens, "extra")
    assert _settle_fut(mc, f1) == ("ok", b"newer")
    assert _settle_fut(mc, f2) == ("ok", b"carried")

    rec.stop()
    svc.stop()


def test_all_false_views_rejected_and_contained():
    """Review r4: an all-False view is rejected at the registry entry
    points, and a malformed record that sneaks in anyway must not
    crash the reconciliation loop."""
    mc = ManagedCluster(seed=7, nodes=("node0",))
    mc.enable("node0")
    with pytest.raises(ValueError):
        sm.create_tenant(mc.mgr("node0"), mc.runtime, "bad",
                         view=[False, False, False])

    registry = {}
    svc, rec = _bring_up(mc, "node0", "svc@node0", registry)
    # sneak a malformed record straight into the registry (bypassing
    # the validating entry point)
    fut = mc.mgr("node0").create_ensemble(
        sm.tenant_id("sneaky"), None, [], sm.TENANT_MOD,
        ([False, False, False],), 30.0)
    assert mc.runtime.await_future(fut, 35.0) == "ok"
    assert sm.create_tenant(mc.mgr("node0"), mc.runtime, "good") \
        == "ok"
    # the loop survives the bad record and still reconciles others
    ok = mc.runtime.run_until(
        lambda: svc.resolve_ensemble("good") is not None, 60.0)
    assert ok, "reconciler died on a malformed view"
    assert svc.resolve_ensemble("sneaky") is None
    rec.stop()
    svc.stop()


def test_versions_survive_tenant_handoff():
    """VERDICT r4 missing #2 / directive #4: a placement move carries
    {epoch, seq} with the values (replace_members_test.erl:26-30
    semantics — consensus moves, objects keep their versions).  A CAS
    token read BEFORE a reconciler-driven move must work AFTER it,
    and post-move writes must version-dominate the installed
    objects."""
    mc = ManagedCluster(seed=11, nodes=("node0", "node1"))
    mc.enable("node0")
    mc.join("node1", "node0")
    registry = {}
    svc0, rec0 = _bring_up(mc, "node0", "svc@node0", registry)
    for t in TENANTS:
        assert sm.create_tenant(mc.mgr("node0"), mc.runtime, t) == "ok"
    ok = mc.runtime.run_until(
        lambda: all(svc0.resolve_ensemble(t) is not None
                    for t in TENANTS), 60.0)
    assert ok

    # write, then capture each tenant's CAS token pre-move
    tokens = {}
    for i, t in enumerate(TENANTS):
        ens = svc0.resolve_ensemble(t)
        assert _settle_fut(mc, svc0.kput(ens, "k",
                                         b"v%d" % i))[0] == "ok"
        r = _settle_fut(mc, svc0.kget_vsn(ens, "k"))
        assert r[0] == "ok" and r[1] == b"v%d" % i, r
        tokens[t] = r[2]
        assert tokens[t] != (0, 0)

    # join node1: rendezvous moves a subset; handoff must preserve vsn
    svc1, rec1 = _bring_up(mc, "node1", "svc@node1", registry)
    both = ["svc@node0", "svc@node1"]
    moved = [t for t in TENANTS if sm.place(t, both) == "svc@node1"]
    assert moved
    ok = mc.runtime.run_until(
        lambda: all(svc1.resolve_ensemble(t) is not None
                    and svc0.resolve_ensemble(t) is None
                    for t in moved) and not rec1._importing, 120.0)
    assert ok, "rebalance never converged"

    for t in moved:
        i = TENANTS.index(t)
        ens = svc1.resolve_ensemble(t)
        # the version travelled with the value
        r = _settle_fut(mc, svc1.kget_vsn(ens, "k"))
        assert r == ("ok", b"v%d" % i, tokens[t]), (t, r, tokens[t])
        # THE criterion: the pre-move CAS token still works
        r = _settle_fut(mc, svc1.kupdate(ens, "k", tokens[t],
                                         b"updated-%d" % i))
        assert r[0] == "ok", (t, r)
        new_vsn = r[1]
        # post-move versions strictly dominate the installed ones
        assert tuple(new_vsn) > tuple(tokens[t]), (new_vsn, tokens[t])
        # and the stale token is now correctly refused
        r = _settle_fut(mc, svc1.kupdate(ens, "k", tokens[t],
                                         b"stale"))
        assert r == "failed", r
        r = _settle_fut(mc, svc1.kget(ens, "k"))
        assert r == ("ok", b"updated-%d" % i), r

    rec0.stop()
    rec1.stop()
    svc0.stop()
    svc1.stop()


def test_leaderless_export_pairs_payload_with_committed_version():
    """ADVICE r5 regression: _export on a LEADERLESS row must not read
    versions from lane 0 — lane 0 can lag a quorum-committed write
    (it was down when the write committed), and pairing the newest
    payload with its stale (epoch, seq) voids every CAS token minted
    from the true version after the install.  The export must carry
    the per-slot max (epoch, seq) across up lanes."""
    from riak_ensemble_tpu.runtime import Runtime

    runtime = Runtime(seed=42)
    svc = BatchedEnsembleService(runtime, 4, N_PEERS, N_SLOTS,
                                 tick=0.005,
                                 config=fast_test_config(),
                                 dynamic=True)
    ens = svc.create_ensemble("t0")
    assert ens is not None

    def settle(fut, t=30.0):
        return runtime.await_future(fut, t)

    # v1 commits on every lane
    assert settle(svc.kput(ens, "k", b"v1"))[0] == "ok"
    # lane 0 goes down; v2 commits on the surviving quorum only —
    # lane 0's device copy now holds v1's stale (epoch, seq)
    svc.set_peer_up(ens, 0, False)
    r = settle(svc.kput(ens, "k", b"v2"))
    assert r[0] == "ok", r
    token = settle(svc.kget_vsn(ens, "k"))
    assert token[0] == "ok" and token[1] == b"v2"
    vsn = token[2]

    # the export-time window: no leader (e.g. mid-failover)
    svc.leader_np[ens] = -1
    rec = sm.ServiceReconciler(runtime, None, svc, "svc@x",
                               lambda _n: None, poll=None)
    data = rec._export(ens)
    by_key = {e[0]: e for e in data}
    assert by_key["k"][1] == b"v2"
    # THE regression: the exported version is the committed one, not
    # lane 0's stale copy
    assert tuple(by_key["k"][2]) == tuple(vsn), (by_key["k"], vsn)

    # and the CAS token survives the export → install move
    svc2 = BatchedEnsembleService(runtime, 4, N_PEERS, N_SLOTS,
                                  tick=0.005,
                                  config=fast_test_config(),
                                  dynamic=True)
    row = svc2.create_ensemble("t0")
    res = svc2.install_objs(row, [(key, ver, payload)
                                  for key, payload, ver in data])
    assert all(r[0] == "ok" for r in res)
    r = settle(svc2.kupdate(row, "k", vsn, b"v3"))
    assert r[0] == "ok", r
    assert settle(svc2.kget(row, "k")) == ("ok", b"v3")
    svc.stop()
    svc2.stop()
