"""The obs-actuated runtime controller (docs/ARCHITECTURE.md §14).

Covers: the decision journal (bounded ring, replay reconstruction),
the ack-RTT depth/window tuner under deterministic injected RTT
changes on virtual time (steps up at 5 ms, back down on heal,
hysteresis prevents flapping, and journal/gauges/health agree on
EVERY transition), the tenant-admission token bucket (both the
guard's install/release decisions and the service-side flush
admission), the chaos-gate schedule on a virtual clock, the runtime
knob setters, the flight-recorder windowed-p50 re-arm fix, the
registry ``remove_labeled`` recycle fix, and the acceptance
equivalence: ``RETPU_AUTOTUNE=0`` is bit-identical to a
controller-armed service whose actuation thresholds are unreachable.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from riak_ensemble_tpu import faults, obs  # noqa: E402
from riak_ensemble_tpu.obs import controller as ctl  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)


# -- decision journal ---------------------------------------------------------

def test_journal_ring_bounded_and_replay():
    j = ctl.DecisionJournal(capacity=4)
    for i in range(10):
        j.note("ack_rtt", "repl_ack_ms_p50", float(i),
               knob="pipeline_depth", old=i, new=i + 1, flush_id=i)
    assert j.total == 10
    evs = j.snapshot()
    assert len(evs) == 4  # ring bound
    assert [e["seq"] for e in evs] == [7, 8, 9, 10]  # seq survives
    # replay over the FULL history reconstructs the final knob
    full = ctl.DecisionJournal()
    for i in range(3):
        full.note("ack_rtt", "repl_ack_ms_p50", 5.0,
                  knob="pipeline_depth", old=1 + i, new=2 + i)
    state = ctl.replay(full.snapshot(), {"pipeline_depth": 1})
    assert state == {"pipeline_depth": 4}


def test_journal_replay_mismatch_is_loud():
    j = ctl.DecisionJournal()
    j.note("ack_rtt", "repl_ack_ms_p50", 5.0,
           knob="pipeline_depth", old=2, new=3)
    with pytest.raises(ValueError, match="replay mismatch"):
        ctl.replay(j.snapshot(), {"pipeline_depth": 1})


# -- the ack-RTT tuner on deterministic synthetic spans -----------------------

class _StubGroup:
    """Duck-typed replicated leader: just the surface the controller
    actuates and reads."""

    class _Link:
        label = "stub:1"

    def __init__(self):
        self.pipeline_depth = 1
        self.repl_window = 1
        self.max_k = 8
        self.is_leader = True
        self._links = [self._Link()]
        self.tenant_ops = np.zeros((4,), np.int64)

    def tenant_label(self, e):
        return f"ens{e}"

    def set_pipeline_depth(self, d):
        old, self.pipeline_depth = self.pipeline_depth, max(1, int(d))
        return old

    def set_repl_window(self, w):
        old, self.repl_window = self.repl_window, max(1, int(w))
        return old

    def set_admission_caps(self, caps):
        self.caps = caps


def _controller(stub) -> ctl.RuntimeController:
    c = ctl.RuntimeController(stub)
    c.enabled = True
    c.cadence = 4
    return c


def _drive_window(c, ack_ms):
    """One cadence window of flushes whose repl_ack spans measure
    ``ack_ms`` — deterministic synthetic samples in the real span
    store, exactly where the live leader records them."""
    for _ in range(c.cadence):
        fid = obs.next_flush_id()
        obs.SPANS.record(fid, "leader", [("repl_ack", ack_ms / 1e3)])
        c.tick(fid)


def _check_surfaces_agree(c, stub):
    """Journal, gauges and health must tell the same story after
    every transition."""
    fam = c.collect()
    assert fam["retpu_autotune_pipeline_depth"]["values"][None] \
        == stub.pipeline_depth
    assert fam["retpu_autotune_repl_window"]["values"][None] \
        == stub.repl_window
    assert fam["retpu_autotune_decisions_total"]["values"][None] \
        == c.journal.total
    h = c.health_section()
    assert h["pipeline_depth"] == stub.pipeline_depth
    assert h["repl_window"] == stub.repl_window
    assert h["decisions"] == c.journal.total
    replayed = ctl.replay(
        [e for e in c.journal.snapshot()
         if e.get("knob") in ("pipeline_depth", "repl_window")],
        {"pipeline_depth": stub._base_depth,
         "repl_window": stub._base_window})
    assert replayed == {"pipeline_depth": stub.pipeline_depth,
                        "repl_window": stub.repl_window}


def test_tuner_steps_up_at_5ms_down_on_heal():
    stub = _StubGroup()
    c = _controller(stub)
    stub._base_depth, stub._base_window = 1, 1
    # window 1: 5 ms injected ack RTT -> one bounded step up
    _drive_window(c, 5.0)
    assert stub.pipeline_depth == 2
    assert stub.repl_window == 4  # widened to 2 x depth
    assert c.journal.total == 2  # depth + window, each journaled
    _check_surfaces_agree(c, stub)
    last = c.journal.snapshot()[-1]
    assert last["cause"] == "repl_ack_ms_p50"
    assert last["observed"] == pytest.approx(5.0)
    # the step is BOUNDED: 5 ms again moves one more unit, not a jump
    _drive_window(c, 5.0)
    assert stub.pipeline_depth == 3
    _check_surfaces_agree(c, stub)
    # heal: sub-threshold RTT walks back down toward the baseline,
    # one bounded step per window, window restored at base depth
    _drive_window(c, 0.3)
    assert stub.pipeline_depth == 2
    _drive_window(c, 0.3)
    assert stub.pipeline_depth == 1
    assert stub.repl_window == 1
    _check_surfaces_agree(c, stub)
    # fully healed: further quiet windows change nothing (never
    # below the operator's baseline)
    _drive_window(c, 0.3)
    assert stub.pipeline_depth == 1
    assert c.journal.snapshot()[-1]["direction"] == "down"


def test_tuner_hysteresis_prevents_flapping():
    stub = _StubGroup()
    c = _controller(stub)
    stub._base_depth, stub._base_window = 1, 1
    _drive_window(c, 5.0)  # up to depth 2 (heal reference = 5 ms)
    n = c.journal.total
    # the dead band: p50 hovering between the heal condition
    # (max(down_ms 1.0, 0.5 x the 5 ms that stepped up) = 2.5) and
    # the up threshold (4.0) must HOLD the knob, not flap it
    for ms in (3.0, 3.5, 2.8, 3.9, 2.6):
        _drive_window(c, ms)
        assert stub.pipeline_depth == 2, f"flapped at {ms} ms"
    assert c.journal.total == n, "hold windows journaled decisions"
    _check_surfaces_agree(c, stub)
    # the RELATIVE heal clause: 2 ms is above down_ms (1.0) but at
    # 40% of the up-step's 5 ms reference — the ack floor (replica
    # apply cost) never reaches an absolute threshold on every box
    _drive_window(c, 2.0)
    assert stub.pipeline_depth == 1
    _check_surfaces_agree(c, stub)


def test_tuner_needs_samples_and_leadership():
    stub = _StubGroup()
    c = _controller(stub)
    stub._base_depth, stub._base_window = 1, 1
    # a quiet window (too few ack samples) is not evidence
    fid = obs.next_flush_id()
    obs.SPANS.record(fid, "leader", [("repl_ack", 0.005)])
    for i in range(c.cadence):
        c.tick(fid if i == 0 else 0)
    assert stub.pipeline_depth == 1 and c.journal.total == 0
    # a deposed lane must not grow in-flight state
    stub.is_leader = False
    _drive_window(c, 5.0)
    assert stub.pipeline_depth == 1 and c.journal.total == 0


# -- the tenant guard ---------------------------------------------------------

def test_tenant_guard_install_release_with_hysteresis():
    stub = _StubGroup()
    c = _controller(stub)
    c.guard.min_ops = 10
    stub.caps = "unset"
    # hot row 0 at 90% share -> capped
    stub.tenant_ops = np.array([90, 5, 5, 0], np.int64)
    for _ in range(c.cadence):
        c.tick(obs.next_flush_id())
    assert stub.caps == {0: stub.max_k // 2}
    assert c.guard.throttled == {"ens0": [0]}
    ev = c.journal.snapshot()[-1]
    assert ev["actuator"] == "tenant_guard"
    assert ev["cause"] == "tenant_ops_share"
    assert ev["observed"] == pytest.approx(0.9)
    assert c.collect()[
        "retpu_autotune_tenant_throttled_rows"]["values"][None] == 1
    # mid-band share (between low 0.45 and high 0.7): HOLD
    stub.tenant_ops += np.array([60, 20, 20, 0], np.int64)
    for _ in range(c.cadence):
        c.tick(obs.next_flush_id())
    assert c.guard.throttled, "guard released inside the dead band"
    # share collapses below the low threshold -> released
    stub.tenant_ops += np.array([10, 45, 45, 0], np.int64)
    for _ in range(c.cadence):
        c.tick(obs.next_flush_id())
    assert c.guard.throttled == {}
    assert stub.caps is None
    assert c.journal.snapshot()[-1]["new"] is None  # the release


def test_admission_token_bucket_caps_flush_take():
    """The service-side half: a capped row's queue stops forcing the
    flush depth to its own max — quiet rows flush at their own small
    k while the hot backlog drains at the bucket rate."""
    svc = BatchedEnsembleService(WallRuntime(), 4, 1, 16, tick=None,
                                 max_ops_per_tick=8)
    try:
        svc.set_admission_caps({0: 2})
        futs = [svc.kput_many(0, [f"k{i}" for i in range(8)],
                              [b"v"] * 8),
                svc.kput(1, "q", b"qv")]
        svc.flush()
        # burst (2x cap) admits 4 of the hot row's 8 rounds; the
        # quiet row's single op rides the same flush
        assert svc._queue_rounds[0] == 4
        assert futs[1].done
        flushes = 1
        while any(svc.queues):
            svc.flush()
            flushes += 1
            assert flushes < 20
        assert all(f.done for f in futs)
        assert flushes >= 3  # bucket-rate drain, not one mega-flush
        res = futs[0].value
        assert all(r[0] == "ok" for r in res)
        # clearing the caps restores the uncapped single-flush take
        svc.set_admission_caps(None)
        f2 = svc.kput_many(0, [f"n{i}" for i in range(8)],
                           [b"w"] * 8)
        svc.flush()
        assert f2.done
    finally:
        svc.stop()


# -- the chaos gate -----------------------------------------------------------

def test_soak_schedule_virtual_clock():
    now = [0.0]
    ran = []

    def runner(target):
        ran.append(target)
        return {"ok": len(ran) != 2, "detect_s": 0.1}

    s = faults.SoakSchedule(10.0, runner=runner, clock=lambda: now[0])
    assert not s.due()
    assert s.maybe_run("svc") is None
    now[0] = 10.5
    r = s.maybe_run("svc")
    assert r is not None and r["ok"] and ran == ["svc"]
    assert s.maybe_run("svc") is None  # re-armed, not due yet
    now[0] = 21.0
    r = s.maybe_run("svc")
    assert r is not None and not r["ok"]
    assert (s.runs, s.failures) == (2, 1)

    def bad(_t):
        raise RuntimeError("soak crashed")

    s2 = faults.SoakSchedule(1.0, runner=bad, clock=lambda: now[0])
    now[0] += 2.0
    r = s2.maybe_run("svc")
    assert r is not None and not r["ok"] and "error" in r
    assert s2.failures == 1  # a crashing soak is a verdict, not a
    # serving-loop crash
    assert faults.SoakSchedule(0.0).due() is False  # disarmed


def test_wedge_soak_restores_plan_and_bounds_detection():
    class _Link:
        IO_TIMEOUT = 1.0
        label = "peer:9"

    class _Svc:
        _links = [_Link()]

        def __init__(self):
            self.beats = []

        def heartbeat(self):
            # first beat runs under the blackhole: quorum lost
            self.beats.append(faults.plan())
            return len(self.beats) != 1

    prev = faults.install(faults.FaultPlan())
    try:
        svc = _Svc()
        r = faults.wedge_soak(svc)
        assert r["ok"], r
        assert r["bound_s"] == pytest.approx(2.0)
        assert r["detect_s"] <= r["bound_s"]
        # the blackhole beat saw the SILENT soak plan; the heal beat
        # ran with the outer plan restored
        assert svc.beats[0].silent is True
        assert svc.beats[0] is not prev
        assert svc.beats[1] is prev
        assert faults.plan() is prev
    finally:
        faults.clear()
    # a lane without links has no ack path to wedge: skipped, ok
    class _NoLinks:
        _links = []
    assert faults.wedge_soak(_NoLinks())["ok"] is True


def test_controller_journals_soak_results():
    stub = _StubGroup()
    c = _controller(stub)
    now = [100.0]
    c.arm_soak(5.0, runner=lambda t: {"ok": True, "detect_s": 0.2},
               clock=lambda: now[0])
    now[0] = 106.0
    for _ in range(c.cadence):
        c.tick(obs.next_flush_id())
    evs = [e for e in c.journal.snapshot()
           if e["actuator"] == "chaos"]
    assert len(evs) == 1 and evs[0]["ok"] is True
    assert evs[0]["cause"] == "wedge_soak_detect_s"
    assert c.collect()[
        "retpu_autotune_soak_runs_total"]["values"][None] == 1


@pytest.mark.slow
def test_live_wedge_soak_on_replicated_group(tmp_path):
    """The standing chaos gate on a REAL 2-host group: a silent ack
    blackhole (the RETPU_FAULT_SILENT=1 mode) must be OBSERVED as a
    lost quorum within 2 x IO_TIMEOUT, the group must heal, and the
    controller must journal the verdict."""
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel import repgroup

    server = repgroup.ReplicaServer(4, 2, 8,
                                    data_dir=str(tmp_path / "r1"),
                                    config=fast_test_config())
    svc = repgroup.ReplicatedService(
        WallRuntime(), 4, 1, 8, group_size=2,
        peers=[("127.0.0.1", server.repl_port)],
        ack_timeout=3.0, max_ops_per_tick=4,
        config=fast_test_config(), data_dir=str(tmp_path / "leader"))
    try:
        repgroup.warmup_kernels(svc)
        assert svc.takeover()
        f = svc.kput(0, "k", b"v")
        while not f.done:
            svc.flush()
        r = faults.wedge_soak(svc)
        assert r["ok"], r
        assert r["quorum_ok_under_blackhole"] is False
        assert r["detect_s"] <= r["bound_s"]
        assert r["healed_quorum_ok"] is True
        assert faults.plan() is None  # outer (no-plan) state restored
        # the controller journals the same soak when scheduled
        svc.set_autotune(True)
        now = [0.0]
        svc.controller.arm_soak(1.0, clock=lambda: now[0])
        now[0] = 2.0
        decisions = svc.controller.evaluate()
        chaos = [e for e in decisions if e["actuator"] == "chaos"]
        assert len(chaos) == 1 and chaos[0]["ok"] is True
        # and the group still serves
        f2 = svc.kput(1, "k2", b"v2")
        while not f2.done:
            svc.flush()
        assert f2.value[0] == "ok"
    finally:
        svc.stop()
        server.stop()


# -- knob setters on a live service ------------------------------------------

def test_set_pipeline_depth_safe_mid_stream():
    svc = BatchedEnsembleService(WallRuntime(), 4, 1, 8, tick=None,
                                 max_ops_per_tick=4)
    try:
        futs = [svc.kput(e, "a", b"1") for e in range(4)]
        assert svc.set_pipeline_depth(2) == 1
        futs += [svc.kput(e, "b", b"2") for e in range(4)]
        while any(svc.queues):
            svc.flush()
        svc.flush()  # settle the tail of the deeper pipeline
        assert all(f.done for f in futs)
        assert svc.set_pipeline_depth(1) == 2
        assert not svc._inflight_launches  # drained at the change
        got = svc.kget(0, "b")
        while not got.done:
            svc.flush()
        assert got.value == ("ok", b"2")
    finally:
        svc.stop()


# -- flight recorder: windowed p50 re-arms after a load shift -----------------

def test_flightrec_windowed_p50_rearms_after_spike():
    fr = obs.FlightRecorder(window=8, min_samples=8,
                            trigger_ratio=5.0,
                            min_dump_interval_s=0.0)
    for i in range(8):
        fr.record({"flush_id": i, "total": 0.01})
    # a sustained slow phase, then back to quiet: once the spike
    # slides out of the window the baseline must decay with it
    for i in range(8):
        fr.record({"flush_id": 100 + i, "total": 0.5})
    for i in range(8):
        fr.record({"flush_id": 200 + i, "total": 0.01})
    assert fr._p50 == pytest.approx(0.01)  # fully decayed
    # ... so a 5x-of-quiet flush triggers at the RIGHT threshold
    snap = fr.record({"flush_id": 300, "total": 0.06})
    assert snap is not None, "post-spike anomaly missed: stale p50"
    assert snap["trigger"]["rolling_p50_s"] == pytest.approx(0.01)
    assert "controller_decisions" in snap  # dump schema v3 section


# -- registry label recycle ---------------------------------------------------

def test_remove_labeled_drops_series():
    reg = obs.MetricsRegistry()
    h = reg.histogram("retpu_test_ms")
    c = reg.counter("retpu_test_total")
    h.labels("tenantA").record(1.0)
    c.labels("tenantA").inc()
    c.labels("tenantB").inc()
    assert reg.remove_labeled("tenantA") == 2
    snap = reg.snapshot()
    assert "tenantA" not in snap["retpu_test_ms"].get("by_label", {})
    assert snap["retpu_test_total"] == {"tenantB": 1}
    assert "tenantA" not in reg.render_prometheus()
    assert reg.remove_labeled("tenantA") == 0  # idempotent


def test_row_recycle_drops_tenant_labeled_series():
    svc = BatchedEnsembleService(WallRuntime(), 4, 1, 8, tick=None,
                                 max_ops_per_tick=4, dynamic=True)
    try:
        row = svc.create_ensemble("acme")
        assert row is not None
        f = svc.kput(row, "k", b"v")
        while not f.done:
            svc.flush()
        # a labeled series recorded under the tenant's label (the
        # registry's label dimension exists for exactly this)
        svc.obs_registry.histogram("retpu_op_latency_ms") \
            .labels("acme").record(1.0)
        assert svc.destroy_ensemble("acme")
        snap = svc.obs_registry.snapshot()
        assert "acme" not in snap["retpu_op_latency_ms"] \
            .get("by_label", {}), "recycled tenant's series leaked"
        assert "acme" not in snap.get("retpu_tenant_ops_total", {})
    finally:
        svc.stop()


def test_multi_row_tenant_series_survive_sibling_recycle():
    """A tenant spanning several ensemble rows is ONE tenant in every
    export: recycling one of its rows must not reset the survivors'
    labeled series — only the LAST row's recycle drops them."""
    svc = BatchedEnsembleService(WallRuntime(), 4, 1, 8, tick=None,
                                 max_ops_per_tick=4, dynamic=True)
    try:
        r1 = svc.create_ensemble("t1")
        r2 = svc.create_ensemble("t2")
        svc.set_tenant_label(r1, "acme")
        svc.set_tenant_label(r2, "acme")
        svc.obs_registry.histogram("retpu_op_latency_ms") \
            .labels("acme").record(1.0)
        assert svc.destroy_ensemble("t1")
        snap = svc.obs_registry.snapshot()
        assert "acme" in snap["retpu_op_latency_ms"] \
            .get("by_label", {}), \
            "live multi-row tenant's series dropped on sibling recycle"
        assert svc.destroy_ensemble("t2")  # the last 'acme' row
        snap = svc.obs_registry.snapshot()
        assert "acme" not in snap["retpu_op_latency_ms"] \
            .get("by_label", {})
    finally:
        svc.stop()


def test_arm_time_baseline_recaptured_on_set_autotune():
    """The tuner's heal floor is the ARM-time configuration: knobs an
    operator moved between construction and arming must become the
    new baseline, never be walked back down to the constructed one."""
    svc = BatchedEnsembleService(WallRuntime(), 4, 1, 8, tick=None,
                                 max_ops_per_tick=4)
    try:
        assert svc._autotune_base_depth == 1
        svc.set_pipeline_depth(3)
        svc.set_autotune(True)
        assert svc._autotune_base_depth == 3
        # a fully-healed window must NOT step below the armed floor
        tuner = ctl.AckRttTuner()
        j = ctl.DecisionJournal()
        assert tuner.evaluate(svc, [0.0001] * 8, j, flush_id=1) == []
        assert svc.pipeline_depth == 3
        svc.set_autotune(False)
    finally:
        svc.stop()


# -- acceptance: RETPU_AUTOTUNE=0 is bit-identical ---------------------------

def _controller_equiv_run(tmp_path, tag, armed):
    """One arm of the equivalence sweep: a mixed keyed stream on a
    fresh service; returns (results, mirror slabs)."""
    env_before = os.environ.get("RETPU_AUTOTUNE")
    os.environ["RETPU_AUTOTUNE"] = "1" if armed else "0"
    try:
        svc = BatchedEnsembleService(
            WallRuntime(), 8, 1, 16, tick=None, max_ops_per_tick=8,
            data_dir=str(tmp_path / tag))
        if armed:
            # armed, but every actuation threshold unreachable: the
            # controller runs its cadence and decides NOTHING — the
            # acceptance arm whose behavior must be bit-identical
            svc.controller.cadence = 2
            svc.controller.tuner.up_ms = 1e12
            svc.controller.tuner.down_ms = -1.0
            svc.controller.guard.share_high = 2.0
            svc.controller.guard.share_low = 1.5
    finally:
        if env_before is None:
            os.environ.pop("RETPU_AUTOTUNE", None)
        else:
            os.environ["RETPU_AUTOTUNE"] = env_before
    results = []
    try:
        futs = []
        for e in range(8):
            futs.append(svc.kput_many(
                e, [f"k{j}" for j in range(6)],
                [b"v%d" % j for j in range(6)]))
        while any(svc.queues):
            svc.flush()
        from riak_ensemble_tpu import funref
        futs.append(svc.kmodify(0, "ctr", funref.ref("rmw:add", 7),
                                0))
        futs.append(svc.kdelete(1, "k3"))
        futs.append(svc.kget_many(2, [f"k{j}" for j in range(6)]))
        while any(svc.queues) or not all(f.done for f in futs):
            svc.flush()
        results = [f.value for f in futs]
        slabs = (svc._slot_vsn_np.copy(), svc._slot_vsn_ok.copy(),
                 svc._inline_np.copy())
        if armed:
            assert svc.controller.evals > 0, \
                "armed arm never evaluated — the sweep proved nothing"
            assert svc.controller.journal.total == 0
        return results, slabs
    finally:
        svc.stop()


def test_autotune_off_bit_identical_to_unreachable_thresholds(
        tmp_path):
    """The §14 oracle discipline: the controller-armed service with
    unreachable actuation thresholds produces bit-identical results
    and mirror slabs to RETPU_AUTOTUNE=0 — so the off arm (the
    default for one release) is provably the same service."""
    res_off, slabs_off = _controller_equiv_run(tmp_path, "off", False)
    res_on, slabs_on = _controller_equiv_run(tmp_path, "on", True)
    assert res_off == res_on
    for a, b in zip(slabs_off, slabs_on):
        assert np.array_equal(a, b), "mirror slabs diverged"
