"""Streamed Merkle exchange across a REAL process boundary at 1M
segments (VERDICT r3 #7) — the ``test/synctree_remote.erl:24-38``
analog: two OS processes, each holding a 1M-segment device tree, a
level-by-level descent over the wire, and an asserted traffic ledger:
O(width · height · diffs), never O(keys)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import hash as hashk  # noqa: E402
from riak_ensemble_tpu.synctree import remote_sync  # noqa: E402

SEGS = 16 ** 5  # 1M segments — the reference synctree's design scale
WIDTH = 16
N_DIFFS = 37
SEED = 424242


def _base_leaves():
    """Deterministic identical base tree on both sides."""
    idx = jnp.arange(SEGS, dtype=jnp.uint32)
    return hashk.leaf_hash(idx, idx * 7 + 1)


def _mutations():
    rng = np.random.default_rng(SEED)
    ids = rng.choice(SEGS, N_DIFFS, replace=False).astype(np.int32)
    new = jnp.asarray(
        rng.integers(0, 2 ** 32, (N_DIFFS, hashk.LANES)).astype(
            np.uint32))
    return jnp.asarray(ids), new


_CHILD = textwrap.dedent(f"""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from riak_ensemble_tpu.ops import hash as hashk
    from riak_ensemble_tpu.synctree import remote_sync

    SEGS = {SEGS}; WIDTH = {WIDTH}; N_DIFFS = {N_DIFFS}; SEED = {SEED}
    idx = jnp.arange(SEGS, dtype=jnp.uint32)
    leaves = hashk.leaf_hash(idx, idx * 7 + 1)
    levels = hashk.build(leaves, width=WIDTH)
    rng = np.random.default_rng(SEED)
    ids = rng.choice(SEGS, N_DIFFS, replace=False).astype(np.int32)
    new = jnp.asarray(rng.integers(0, 2 ** 32,
                      (N_DIFFS, hashk.LANES)).astype(np.uint32))
    levels = hashk.update(levels, jnp.asarray(ids), new, width=WIDTH)
    jax.block_until_ready(levels)
    srv = remote_sync.TreeSyncServer(levels)
    print(f"port={{srv.port}}", flush=True)
    import time
    time.sleep(600)
""")


def test_streamed_exchange_1m_segments_across_processes():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", _CHILD],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)
    try:
        line = proc.stdout.readline()
        assert line.startswith("port="), proc.stderr.read()[-3000:]
        port = int(line.split("=")[1])

        local = hashk.build(_base_leaves(), width=WIDTH)
        jax.block_until_ready(local)
        found, stats = remote_sync.sync_diff(local, "127.0.0.1", port,
                                             width=WIDTH)

        # -- correctness: exactly the mutated segments found ----------
        ids, _ = _mutations()
        assert sorted(found.tolist()) == sorted(
            np.asarray(ids).tolist())

        # -- the traffic bound (synctree.erl:372-417 premise) ---------
        height = len(local)          # root..leaves level count
        # one request per level + meta, regardless of key count
        assert stats["messages"] <= height + 1, stats
        # visited nodes match the DEVICE-side cost model exactly:
        # children of differing parents only
        remote_levels = hashk.update(local, *(_mutations()),
                                     width=WIDTH)
        expect_cost = np.asarray(
            hashk.exchange_cost(local, remote_levels, width=WIDTH))
        assert stats["visited"] == expect_cost.tolist(), stats
        # O(width·height·diffs) bytes — and monumentally smaller than
        # shipping the key space (the O(keys) failure mode)
        node_bytes = hashk.LANES * 4
        bound = (1 + N_DIFFS * WIDTH * height) * node_bytes * 2
        assert stats["bytes_rx"] <= bound, (stats, bound)
        tree_bytes = SEGS * node_bytes
        assert stats["bytes_rx"] < tree_bytes / 100, \
            f"exchange shipped {stats['bytes_rx']}B of a " \
            f"{tree_bytes}B key space"
    finally:
        proc.kill()


def test_exchange_identical_trees_costs_one_node():
    """Equal trees: the descent stops at the root — height messages
    never happen, only the root compare."""
    segs = 16 ** 3
    idx = jnp.arange(segs, dtype=jnp.uint32)
    levels = hashk.build(hashk.leaf_hash(idx, idx), width=WIDTH)
    srv = remote_sync.TreeSyncServer(levels)
    try:
        found, stats = remote_sync.sync_diff(levels, "127.0.0.1",
                                             srv.port, width=WIDTH)
        assert found.size == 0
        assert stats["visited"][0] == 1
        assert sum(stats["visited"]) == 1  # nothing below the root
    finally:
        srv.close()


def test_exchange_shape_mismatch_rejected():
    segs = 16 ** 2
    idx = jnp.arange(segs, dtype=jnp.uint32)
    levels = hashk.build(hashk.leaf_hash(idx, idx), width=WIDTH)
    srv = remote_sync.TreeSyncServer(levels)
    try:
        idx2 = jnp.arange(segs * WIDTH, dtype=jnp.uint32)
        bigger = hashk.build(hashk.leaf_hash(idx2, idx2), width=WIDTH)
        with pytest.raises(ValueError):
            remote_sync.sync_diff(bigger, "127.0.0.1", srv.port,
                                  width=WIDTH)
    finally:
        srv.close()
