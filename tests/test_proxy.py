"""Proxy/ingress tier (docs/ARCHITECTURE.md §16): svcnode-protocol
forwarding through a stateless hop.

Covers the slab-verb edge cases the forwarding hop must not disturb —
empty batches, a client frame at EXACTLY the max-frame boundary
(and one byte over), non-ascii key batches falling back to the
legacy list verbs — plus the leader-discovery story: a proxy racing
a leader step-down re-resolves on the not-leader rejection and
retries transparently, and the reconnect satellite on
:class:`ServiceClient` survives a dropped socket.
"""

import asyncio
import socket
import struct
import time

import pytest

jax = pytest.importorskip("jax")

import numpy as np  # noqa: E402

from riak_ensemble_tpu import svcnode, wire  # noqa: E402
from riak_ensemble_tpu import proxy as proxy_mod  # noqa: E402
from riak_ensemble_tpu.config import Config, fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.svcnode import _HDR, _MAX_FRAME  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def test_proxy_forwards_all_verbs_and_slab_edges():
    """One svcnode + one proxy: the whole keyed surface forwards,
    the slab lane survives the hop (including empty batches and the
    non-ascii fallback to list verbs), notfound stays authoritative,
    and proxy_stats counts the traffic."""
    async def scenario():
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config())
        px = await proxy_mod.serve_proxy([(server.host, server.port)])
        c = svcnode.ServiceClient(px.host, px.port)
        await c.connect()

        r = await c.kput(0, "k", b"v1")
        assert r[0] == "ok", r
        assert await c.kget(0, "k") == ("ok", b"v1")
        r = await c.kget_vsn(0, "k")
        assert r[0] == "ok" and r[1] == b"v1"

        # the slab lane end to end: ascii keys / bytes values ride
        # kput_slab/kget_slab through the proxy's Raw re-wrap
        keys = [f"s{i}" for i in range(6)]
        vals = [b"x%d" % i for i in range(6)]
        rs = await c.kput_many(1, keys, vals)
        assert all(r[0] == "ok" for r in rs), rs
        rs = await c.kget_many(1, keys)
        assert [r[1] for r in rs] == vals
        rs = await c.kget_many(1, keys, want_vsn=True)
        assert all(r[0] == "ok" and len(r[2]) == 2 for r in rs)

        # empty batches: the degenerate slab shape answers [] and
        # leaves the connection healthy
        assert await c.kget_many(1, []) == []
        assert await c.kput_many(1, [], []) == []
        r = await c.call_parts(
            "kget_slab", 1, wire.Raw(b""), wire.Raw(b""))
        assert r == []

        # non-ascii keys leave the slab subset: the client falls back
        # to the legacy list verbs, results unchanged through the hop
        rs = await c.kput_many(1, ["ключ"], [b"v"])
        assert rs[0][0] == "ok", rs
        rs = await c.kget_many(1, ["ключ", "s0"])
        assert rs == [("ok", b"v"), ("ok", b"x0")], rs

        assert await c.kget(0, "absent") == ("ok", NOTFOUND)
        # the proxy's own verb is answered locally, never forwarded
        ps = await c.call("proxy_stats")
        assert ps["clients"] == 1
        assert ps["forwarded"] > 0
        assert ps["upstream"] == f"{server.host}:{server.port}"
        assert ps["backpressure"] == {"inflight_stalls": 0,
                                      "write_buf_drops": 0}
        # forwarded stats carry the engine's backpressure row (the
        # svcnode satellite)
        st = await c.stats()
        assert st["svc_backpressure"] == {"inflight_stalls": 0,
                                          "write_buf_drops": 0}
        await c.close()
        await px.stop()
        await server.stop()

    asyncio.run(scenario())


def test_proxy_max_frame_boundary_arena():
    """A client slab frame at EXACTLY _MAX_FRAME forwards and
    commits (the proxy re-frames only the tiny header, so the
    upstream frame cannot outgrow the client's when the client's
    req id is the larger encoding); one byte over drops the
    connection without disturbing the next client."""
    async def scenario():
        server = await svcnode.serve(2, 3, 8, port=0,
                                     config=fast_test_config())
        px = await proxy_mod.serve_proxy([(server.host, server.port)])
        rid = 2 ** 40  # encodes no smaller than any proxy-side rid

        def build(vlen):
            key = "bigk"
            parts = wire.encode_parts(
                (rid, "kput_slab", 0,
                 wire.Raw(np.asarray([len(key)], "<i4")),
                 wire.Raw(key.encode("ascii")),
                 wire.Raw(np.asarray([vlen], "<i4")),
                 wire.Raw(bytes(vlen))))
            return parts, sum(memoryview(p).nbytes for p in parts)

        vlen = _MAX_FRAME - 4096
        for _ in range(8):  # converge on the exact boundary (varint
            parts, length = build(vlen)  # header widths shift a bit)
            if length == _MAX_FRAME:
                break
            vlen += _MAX_FRAME - length
        assert length == _MAX_FRAME, (length, _MAX_FRAME)

        reader, writer = await asyncio.open_connection(px.host,
                                                       px.port)
        writer.write(_HDR.pack(length))
        for p in parts:
            writer.write(p)
        await writer.drain()
        head = await reader.readexactly(_HDR.size)
        (n,) = _HDR.unpack(head)
        resp = wire.decode(await reader.readexactly(n))
        assert resp[0] == rid
        assert resp[1][0][0] == "ok", resp
        writer.close()

        # one byte past the cap: hostile length, connection dropped
        reader, writer = await asyncio.open_connection(px.host,
                                                       px.port)
        writer.write(_HDR.pack(_MAX_FRAME + 1))
        await writer.drain()
        assert await reader.read(1) == b""
        writer.close()

        # the serving plane stayed healthy through both: normal ops
        # keep flowing on a fresh connection.  (Reading the boundary
        # VALUE back in one frame would trip the engine's slow-reader
        # write-buffer guard — responses are capped at _MAX_WRITE_BUF,
        # a deliberate pre-existing bound; the boundary case under
        # test is the REQUEST frame through the hop.)
        c = svcnode.ServiceClient(px.host, px.port)
        await c.connect()
        assert (await c.kput(0, "small", b"s"))[0] == "ok"
        assert await c.kget(0, "small") == ("ok", b"s")
        await c.close()
        await px.stop()
        await server.stop()

    asyncio.run(scenario())


def test_service_client_reconnects_with_backoff():
    """The reconnect satellite: a previously-connected client whose
    socket drops redials before the next op (safe for every verb —
    nothing was dispatched), counts the reconnect, and an explicitly
    closed client stays DISCONNECTED."""
    async def scenario():
        server = await svcnode.serve(2, 3, 8, port=0,
                                     config=fast_test_config())
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        assert (await c.kput(0, "k", b"v"))[0] == "ok"

        c._writer.close()  # the drop: kernel-level, client unaware
        await asyncio.sleep(0.05)
        assert await c.kget(0, "k") == ("ok", b"v")
        assert c.reconnects >= 1

        # never-connected and closed clients keep the documented
        # DISCONNECTED contract (no redial loops)
        fresh = svcnode.ServiceClient(server.host, server.port)
        assert await fresh.kget(0, "k") == fresh.DISCONNECTED
        await c.close()
        assert await c.kget(0, "k") == c.DISCONNECTED
        await server.stop()

    asyncio.run(scenario())


# -- leader step-down race ---------------------------------------------------

_CFG = Config(ensemble_tick=0.05, lease_duration=1.5,
              probe_delay=0.1, storage_delay=0.005,
              storage_tick=0.5, gossip_tick=0.2)


def _control(port, frame, timeout=120.0):
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        repgroup.send_frame(s, frame)
        return repgroup.recv_frame(s)


def test_proxy_rides_out_leader_step_down(tmp_path):
    """The DeposedError re-resolve story: a proxy fronting a 3-host
    group keeps serving the SAME client connection across an
    in-place leader handoff — the deposed host's not-leader
    rejections (never dispatched) retry transparently against the
    freshly discovered leader."""
    srvs = [repgroup.ReplicaServer(
        2, 3, 8, data_dir=str(tmp_path / f"r{i}"), config=_CFG)
        for i in range(3)]
    ports = [s.repl_port for s in srvs]
    try:
        resp = _control(ports[0], ("promote",
                                   [("127.0.0.1", ports[1]),
                                    ("127.0.0.1", ports[2])]))
        assert resp[0] == "ok", resp

        async def scenario():
            px = await proxy_mod.serve_proxy(
                [("127.0.0.1", s.client_port) for s in srvs],
                discover_timeout=60.0)
            c = svcnode.ServiceClient(px.host, px.port)
            await c.connect()
            r = await c.kput(0, "pre", b"1", timeout=120.0)
            assert r[0] == "ok", r
            first = px.link.leader_addr
            assert first == ("127.0.0.1", srvs[0].client_port)

            # in-place handoff while the proxy's connection is live
            resp2 = await asyncio.get_running_loop().run_in_executor(
                None, lambda: _control(
                    ports[1], ("promote",
                               [("127.0.0.1", ports[0]),
                                ("127.0.0.1", ports[2])])))
            assert resp2[0] == "ok", resp2

            # the same client connection keeps working: the proxy
            # eats the not-leader rejection, re-resolves, retries
            deadline = time.monotonic() + 60.0
            while True:
                r = await c.kput(0, "post", b"2", timeout=120.0)
                if isinstance(r, tuple) and r[0] == "ok":
                    break
                # a 'failed' can leak out while the fresh leader
                # re-syncs its host quorum; never a stuck not-leader
                assert r != ("error", "not-leader"), r
                assert time.monotonic() < deadline, r
                await asyncio.sleep(0.5)
            assert px.link.leader_addr == \
                ("127.0.0.1", srvs[1].client_port)
            assert px.link.rediscoveries >= 1
            # acked data readable through the new leader via the hop
            assert await c.kget(0, "pre", timeout=120.0) == \
                ("ok", b"1")
            ps = await c.call("proxy_stats")
            assert ps["not_leader_retries"] >= 1
            await c.close()
            await px.stop()

        asyncio.run(scenario())
    finally:
        for s in srvs:
            s.stop()
