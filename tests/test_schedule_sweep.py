"""Interleaving exploration: the PULSE/race-detection analog.

The reference hooks Quviq PULSE to explore message interleavings
(pulse_replace_module, peer.erl:56-57; SURVEY §5).  Our deterministic
seeded runtime provides the same lever: every seed is a different —
but reproducible — total order of message deliveries and timer
firings, and widening the latency band widens the reordering window.
This sweep runs the core failover scenario across many schedules; any
failing seed is a reproducible race.
"""

import pytest

from riak_ensemble_tpu.testing import Cluster, make_peers


@pytest.mark.parametrize("seed", range(60, 76))
def test_failover_under_schedule_fuzzing(seed):
    c = Cluster(seed=seed)
    # Widen the delivery window with the seed: up to 20x the default
    # latency spread, letting commits/probes/votes interleave wildly.
    c.runtime.net.max_latency = 5e-4 * (1 + (seed % 4) * 6)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")

    c.kput_ok("ens", "k", b"v1")
    c.suspend_peer("ens", leader)

    def new_leader():
        lid = c.leader_id("ens")
        return lid is not None and lid != leader
    assert c.runtime.run_until(new_leader, 60.0), f"seed {seed}"
    c.wait_stable("ens")
    assert c.kget_value("ens", "k") == b"v1"

    c.resume_peer("ens", leader)
    c.runtime.run_for(2.0)
    c.kput_ok("ens", "k", b"v2")
    assert c.kget_value("ens", "k") == b"v2"
