"""Interleaving exploration: the PULSE/race-detection analog.

The reference hooks Quviq PULSE to explore message interleavings
(pulse_replace_module, peer.erl:56-57; SURVEY §5).  Our deterministic
seeded runtime provides the same lever twice over:

- every seed is a different — but reproducible — total order of
  message deliveries and timer firings;
- ``Network.chaos`` is the adversarial delivery-order permuter: each
  cross-node message gets an independent uniform delay inside a
  window that dwarfs normal latency (and optionally same-node sends
  get the same treatment, which is STRONGER reordering than Erlang's
  per-pair signal order), so any two in-flight messages can deliver
  in either order.

The sweep runs four scenarios — leader failover, membership churn
under load, synctree corruption + exchange, and read-path CAS races —
across seeds × chaos windows.  Any failing seed is a reproducible
race.
"""

import pytest

from riak_ensemble_tpu.testing import Cluster, ManagedCluster, make_peers
from riak_ensemble_tpu.types import NOTFOUND, PeerId


@pytest.mark.parametrize("seed", range(60, 80))
def test_failover_under_schedule_fuzzing(seed):
    c = Cluster(seed=seed)
    # Widen the delivery window with the seed: up to 20x the default
    # latency spread, letting commits/probes/votes interleave wildly.
    c.runtime.net.max_latency = 5e-4 * (1 + (seed % 4) * 6)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")

    c.kput_ok("ens", "k", b"v1")
    c.suspend_peer("ens", leader)

    def new_leader():
        lid = c.leader_id("ens")
        return lid is not None and lid != leader
    assert c.runtime.run_until(new_leader, 60.0), f"seed {seed}"
    c.wait_stable("ens")
    assert c.kget_value("ens", "k") == b"v1"

    c.resume_peer("ens", leader)
    c.runtime.run_for(2.0)
    c.kput_ok("ens", "k", b"v2")
    assert c.kget_value("ens", "k") == b"v2"


@pytest.mark.parametrize("seed", range(80, 90))
def test_failover_under_chaos_permuter(seed):
    """The failover story again, but with the true permuter on: a
    20 ms reorder window (vs 0.5 ms normal latency, under the 50 ms
    tick) plus same-node send jitter."""
    c = Cluster(seed=seed)
    c.runtime.net.chaos(window=0.02, local=0.002)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")

    c.kput_ok("ens", "k", b"v1")
    c.suspend_peer("ens", leader)
    assert c.runtime.run_until(
        lambda: c.leader_id("ens") not in (None, leader), 60.0), \
        f"seed {seed}"
    c.wait_stable("ens")
    assert c.kget_value("ens", "k") == b"v1"
    c.resume_peer("ens", leader)
    c.runtime.run_for(2.0)
    c.kput_ok("ens", "k", b"v2")
    assert c.kget_value("ens", "k") == b"v2"


@pytest.mark.parametrize("seed", range(90, 98))
def test_membership_churn_under_chaos(seed):
    """update_members add→remove cycles racing client writes with the
    permuter on: the joint-consensus dance (pending/views vsns, the
    manager-driven peer starts) must converge under arbitrary
    vote/commit reordering."""
    mc = ManagedCluster(seed=seed)
    mc.runtime.net.chaos(window=0.01, local=0.001)
    mc.ens_start(3)
    extra = PeerId(4, mc.node0)
    assert mc.kput("k", b"v0")[0] == "ok"

    base = [PeerId("root", mc.node0), PeerId(2, mc.node0),
            PeerId(3, mc.node0)]
    for i in range(2):
        r = mc.update_members("root", [("add", extra)])
        assert r == "ok", (seed, i, r)
        mc.wait_members("root", base + [extra])
        mc.wait_stable("root")
        assert mc.kput("k", b"v%d" % i)[0] == "ok"
        r = mc.update_members("root", [("del", extra)])
        assert r == "ok", (seed, i, r)
        assert mc.runtime.run_until(
            lambda: extra not in mc.mgr(mc.node0).get_members("root"),
            60.0, poll=0.1), (seed, i, "del never transitioned")
        mc.wait_stable("root")
        r = mc.kget("k")
        assert r[0] == "ok" and r[1].value == b"v%d" % i, (seed, i, r)


@pytest.mark.parametrize("seed", range(100, 108))
def test_corruption_exchange_under_chaos(seed):
    """Synctree corruption detected and healed while the exchange's
    level-batched round trips are being reordered by the permuter; the
    reads must never surface notfound for a committed key
    (corrupt_segment_test postcondition)."""
    mc = ManagedCluster(seed=seed)
    mc.runtime.net.chaos(window=0.01, local=0.001)
    mc.ens_start(3)
    assert mc.kput("corrupt", b"test")[0] == "ok"
    leader = mc.wait_leader("root")
    mc.tree_of("root", leader).tree.corrupt("corrupt")

    def never_notfound():
        r = mc.kget("corrupt")
        if r[0] == "ok":
            assert r[1].value is not NOTFOUND, f"seed {seed}: notfound"
            return r[1].value == b"test"
        return False
    assert mc.runtime.run_until(never_notfound, 60.0), f"seed {seed}"


@pytest.mark.parametrize("seed", range(110, 118))
def test_read_path_cas_races_under_chaos(seed):
    """Interleaved CAS updates, deletes, and reads with the permuter
    on and a mid-run leader freeze: every CAS outcome must be
    ok/failed (no hangs), and the final read must return the last
    acked write."""
    c = Cluster(seed=seed)
    c.runtime.net.chaos(window=0.015, local=0.001)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")

    c.kput_ok("ens", "k", b"v0")
    last = b"v0"
    for i in range(1, 6):
        if i == 3:
            c.suspend_peer("ens", leader)
            assert c.runtime.run_until(
                lambda: c.leader_id("ens") not in (None, leader), 60.0)
            c.wait_stable("ens")
        r = c.kget("ens", "k")
        assert r[0] == "ok", (seed, i, r)
        cur = r[1]
        out = c.kupdate("ens", "k", cur, b"v%d" % i)
        if isinstance(out, tuple) and out[0] == "ok":
            last = b"v%d" % i
        else:
            assert out in ("failed", "timeout") or out[0] == "error", \
                (seed, i, out)
    c.resume_peer("ens", leader)
    c.wait_stable("ens")
    assert c.kget_value("ens", "k") == last


@pytest.mark.parametrize("seed", range(120, 128))
def test_backend_death_under_chaos(seed):
    """The handle_down → reset → step_down path while the permuter
    reorders the recovery traffic: the leader's storage helper dies
    mid-load, and the committed value must survive the reset +
    re-election no matter how probes/votes/repair reads interleave
    (module_handle_down, peer.erl:1919-1948)."""
    from riak_ensemble_tpu.backend import BasicBackend, register_backend
    from riak_ensemble_tpu.runtime import Actor

    class _Store(Actor):
        def handle(self, msg):
            pass

    class ChaosHelperBackend(BasicBackend):
        def __init__(self, ensemble, peer_id, args=()):
            super().__init__(ensemble, peer_id, ())
            runtime, node = args
            self.helper_name = ("cstore", ensemble, repr(peer_id))
            if runtime.whereis(self.helper_name) is None:
                _Store(runtime, self.helper_name, node)

        def monitored(self):
            return (self.helper_name,)

        def handle_down(self, ref, pid, reason):
            if ref == self.helper_name:
                self.data = {}
                return ("reset",)
            return False

    register_backend("chaos-helper", ChaosHelperBackend)
    c = Cluster(seed=seed)
    c.runtime.net.chaos(window=0.015, local=0.001)
    peers = make_peers(3)
    c.create_ensemble("ens", peers, backend="chaos-helper",
                      backend_args=(c.runtime, peers[0].node))
    leader = c.wait_stable("ens")
    c.kput_ok("ens", "k", b"v1")

    c.runtime.stop_actor(c.peer("ens", leader).mod.helper_name)
    c.runtime.run_for(0.5)
    c.wait_stable("ens")
    c.read_until("ens", "k", b"v1")
    c.kput_ok("ens", "k", b"v2")
    assert c.kget_value("ens", "k") == b"v2", f"seed {seed}"


@pytest.mark.parametrize("seed", range(130, 138))
def test_partition_heal_under_chaos(seed):
    """sc.erl's partition nemesis composed with the permuter: the
    leader is isolated in a minority; the majority side must depose it
    and keep serving; after heal, the old leader rejoins without
    resurrecting stale state (partition_nodes/heal_nodes,
    test/sc.erl:1012-1036)."""
    c = Cluster(seed=seed)
    c.runtime.net.chaos(window=0.01, local=0.001)
    peers = make_peers(3)
    c.create_ensemble("ens", peers)
    leader = c.wait_stable("ens")
    c.kput_ok("ens", "k", b"v1")

    lead_node = leader.node
    others = [p.node for p in peers if p.node != lead_node]
    c.runtime.net.partition([lead_node], others)
    assert c.runtime.run_until(
        lambda: c.leader_id("ens") not in (None, leader), 90.0), \
        f"seed {seed}: majority never elected"
    c.wait_stable("ens")
    c.read_until("ens", "k", b"v1")
    c.kput_ok("ens", "k", b"v2")

    c.runtime.net.heal()
    c.wait_stable("ens")
    c.read_until("ens", "k", b"v2")
    c.kput_ok("ens", "k", b"v3")
    assert c.kget_value("ens", "k") == b"v3", f"seed {seed}"
