"""Slab enqueue half + per-flush completion slab: two-arm equivalence
vs the per-entry/per-op oracle (docs/ARCHITECTURE.md §12).

The contract under test mirrors test_native_resolve.py's: with the
slab path on (``RETPU_NATIVE_ENQUEUE=1``, the default) and off, the
same mixed op stream must produce BIT-IDENTICAL ``[K, E]`` op planes
at every launch, identical client results in issue order, identical
mirror slabs, and the fast-read gate must see slab-enqueued writes
exactly as it saw dict-noted ones.  The per-entry pack + per-op
future fan-out are the oracle; the slab path is an optimization,
never a semantic.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")

from riak_ensemble_tpu import funref
from riak_ensemble_tpu.parallel import enqueue_native
from riak_ensemble_tpu.parallel.batched_host import (
    BatchedEnsembleService, WallRuntime,
)

needs_kernel = pytest.mark.skipif(
    enqueue_native.get() is None,
    reason="native enqueue kernel unavailable (no toolchain)")


def _workload(svc, rng, n_ens, k, rounds):
    """A mixed keyed op stream covering every lane shape the pack
    must carry: batched puts/gets/CAS/tombstone-deletes, scalar
    puts/gets/updates (CAS expectations in the exp planes), device
    RMW batches (exp_e carries the mod-fun code) and RMW-to-zero
    tombstones.  Returns every future's resolved value in issue
    order."""
    out = []
    futs = []
    add1 = funref.ref("rmw:add", 1)
    set_zero = funref.ref("rmw:set", 0)
    for r in range(rounds):
        for e in range(n_ens):
            keys = [f"k{(r + i) % 11}" for i in range(k)]
            vals = [b"v%d.%d" % (r, i) for i in range(k)]
            pick = rng.integers(0, 8)
            if pick == 0:
                futs.append(svc.kput_many(e, keys, vals))
            elif pick == 1:
                futs.append(svc.kget_many(
                    e, keys, want_vsn=bool(rng.integers(0, 2))))
            elif pick == 2:
                futs.append(svc.kupdate_many(
                    e, keys[:2], [(0, 0), (0, 0)], vals[:2]))
            elif pick == 3:
                futs.append(svc.kdelete_many(e, keys[:3]))
            elif pick == 4:
                futs.append(svc.kmodify_many(
                    e, [f"ctr{r % 3}", f"ctr{(r + 1) % 3}"], add1, 0))
            elif pick == 5:
                # tombstone RMW: a computed 0 recycles the slot
                futs.append(svc.kmodify(e, f"ctr{r % 3}", set_zero, 0))
            elif pick == 6:
                futs.append(svc.kupdate(e, keys[0], (0, 0), vals[0]))
                futs.append(svc.kdelete(e, keys[2]))
            else:
                futs.append(svc.kput(e, keys[0], vals[0]))
                futs.append(svc.kget(e, keys[1]))
        while any(svc.queues):
            svc.flush()
    svc.flush()
    for f in futs:
        assert f.done
        out.append(f.value)
    return out


def _run_arm(arm, seed, monkeypatch, pipeline_depth=1):
    """One service per arm; captures every launch's op planes (the
    bit-identity surface) plus results + mirror/index slabs."""
    monkeypatch.setenv("RETPU_NATIVE_ENQUEUE", arm)
    monkeypatch.setenv("RETPU_FAST_READS", "0")  # every read = round
    rng = np.random.default_rng(seed)
    svc = BatchedEnsembleService(WallRuntime(), 6, 3, 16, tick=None,
                                 max_ops_per_tick=8,
                                 pipeline_depth=pipeline_depth)
    planes = []
    orig = svc._launch_enqueue

    def spy(kind, slot, val, k, want_vsn, exp_e=None, exp_s=None,
            **kw):
        planes.append((np.array(kind, np.int32),
                       np.array(slot, np.int32),
                       np.array(val, np.int32),
                       None if exp_e is None
                       else np.array(exp_e, np.int32),
                       None if exp_s is None
                       else np.array(exp_s, np.int32)))
        return orig(kind, slot, val, k, want_vsn, exp_e=exp_e,
                    exp_s=exp_s, **kw)

    monkeypatch.setattr(svc, "_launch_enqueue", spy)
    results = _workload(svc, rng, 6, 4, rounds=6)
    state = {
        "results": results,
        "planes": planes,
        "vsn_ok": svc._slot_vsn_ok.copy(),
        "vsn_np": svc._slot_vsn_np.copy(),
        "inl_ok": svc._inline_value_ok.copy(),
        "inl_np": svc._inline_value_np.copy(),
        "inline_np": svc._inline_np.copy(),
        "inline_sets": [sorted(s) for s in svc._inline_slots],
        "pending_writes": [list(r) for r in svc._pending_writes],
        "queued_handle": [list(r)
                          for r in svc._queued_handle_writes],
        "slot_handle": [dict(d) for d in svc.slot_handle],
        "stats": svc.stats(),
    }
    svc.stop()
    return state


@pytest.mark.parametrize("seed", range(2))
@pytest.mark.parametrize("depth", (1, 2))
def test_two_arm_equivalence(seed, depth, monkeypatch):
    """The whole enqueue half, end to end, at pipeline depths 1 and
    2: identical client results, BIT-IDENTICAL op planes launch by
    launch, identical mirror slabs and storage-class sets, and both
    write-noting slabs drained to zero."""
    a = _run_arm("1", seed, monkeypatch, pipeline_depth=depth)
    b = _run_arm("0", seed, monkeypatch, pipeline_depth=depth)
    na = a["stats"]["native_enqueue"]
    nb = b["stats"]["native_enqueue"]
    assert na["slab_path"] and not nb["slab_path"]
    assert na["flushes"] + na["fallback_flushes"] > 0, \
        "slab arm never packed through lanes"
    assert nb["flushes"] == nb["fallback_flushes"] == 0
    assert a["stats"]["completion_slab"]["wakes"] > 0
    assert b["stats"]["completion_slab"]["wakes"] == 0
    assert a["results"] == b["results"]
    assert len(a["planes"]) == len(b["planes"])
    for i, (pa, pb) in enumerate(zip(a["planes"], b["planes"])):
        for name, x, y in zip(("kind", "slot", "val", "exp_e",
                               "exp_s"), pa, pb):
            if x is None:
                assert y is None, (i, name)
                continue
            assert np.array_equal(x, y), (seed, depth, i, name)
    for fld in ("vsn_ok", "inl_ok", "inline_np"):
        assert np.array_equal(a[fld], b[fld]), fld
    assert a["pending_writes"] == b["pending_writes"]
    assert a["queued_handle"] == b["queued_handle"]
    assert np.array_equal(a["vsn_np"][a["vsn_ok"]],
                          b["vsn_np"][b["vsn_ok"]])
    assert np.array_equal(a["inl_np"][a["inl_ok"]],
                          b["inl_np"][b["inl_ok"]])
    assert a["inline_sets"] == b["inline_sets"]
    assert a["slot_handle"] == b["slot_handle"]
    # every queued write was un-noted by exactly one resolve/fail arm
    assert not any(map(any, a["pending_writes"]))
    assert not any(map(any, a["queued_handle"]))


@needs_kernel
def test_kernel_arm_actually_ran(monkeypatch):
    """With the toolchain present the slab arm's pack must run the
    C++ kernel, not the numpy fallback."""
    monkeypatch.setenv("RETPU_NATIVE_ENQUEUE", "1")
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    f = svc.kput_many(0, ["a", "b"], [b"1", b"2"])
    while not f.done:
        svc.flush()
    assert svc.native_enqueue_flushes > 0
    assert svc.fallback_enqueue_flushes == 0
    svc.stop()


def test_completion_slab_one_wake_per_flush(monkeypatch):
    """One wake per settled op-carrying flush, rounds conserved —
    under pipeline_depth=2 AND a batch split across three flushes
    (the K cap lands inside it twice)."""
    monkeypatch.setenv("RETPU_NATIVE_ENQUEUE", "1")
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 64, tick=None,
                                 max_ops_per_tick=4,
                                 pipeline_depth=2)
    keys = [f"k{i}" for i in range(10)]
    f = svc.kput_many(0, keys, [b"v%d" % i for i in range(10)])
    while not f.done:
        svc.flush()
    svc.flush()  # drain the pipeline tail
    assert [r[0] for r in f.value] == ["ok"] * 10
    # 10 rounds through a K cap of 4 = 3 launches, each exactly one
    # wake; every taken round appears in exactly one slab
    assert svc.completion_wakes == 3
    assert svc.completion_rows == 10
    svc.stop()


def test_knob_pins_oracle(monkeypatch):
    """RETPU_NATIVE_ENQUEUE=0 pins the per-entry pack + per-op
    fan-out at construction: no lanes, no wakes, same answers."""
    monkeypatch.setenv("RETPU_NATIVE_ENQUEUE", "0")
    assert enqueue_native.get() is None
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    assert not svc._enq_slab
    f = svc.kput_many(0, ["k"], [b"v"])
    g = svc.kget(0, "k")
    while not (f.done and g.done):
        svc.flush()
    assert f.value == [("ok", (1, 1))]
    assert svc.completion_wakes == 0
    assert svc.native_enqueue_flushes == 0
    assert svc.fallback_enqueue_flushes == 0
    svc.stop()


def test_missing_so_degrades_to_numpy_pack(monkeypatch):
    """A missing/unbuildable kernel .so keeps the SLAB path (it is
    numpy, not C++) with the fancy-index pack arm — never a crash,
    never the per-op oracle by accident.  Simulated by pinning the
    loader's memo to 'tried and failed'."""
    monkeypatch.setenv("RETPU_NATIVE_ENQUEUE", "1")
    monkeypatch.setattr(enqueue_native, "_instance", None)
    monkeypatch.setattr(enqueue_native, "_instance_tried", True)
    assert enqueue_native.get() is None
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    assert svc._enq_slab and svc._native_enqueue is None
    f = svc.kput_many(0, ["k"], [b"v"])
    g = svc.kget_many(0, ["k"])
    while not (f.done and g.done):
        svc.flush()
    assert f.value == [("ok", (1, 1))]
    assert svc.fallback_enqueue_flushes > 0
    assert svc.native_enqueue_flushes == 0
    assert svc.completion_wakes > 0
    svc.stop()


def test_leased_read_racing_slab_write_falls_back(monkeypatch):
    """PR 4 fast-read gate regression (the satellite's contract): a
    slab-enqueued write must be visible to the gate at _push time —
    a leased read of the slot falls back to the device round, which
    orders it after the write."""
    monkeypatch.setenv("RETPU_NATIVE_ENQUEUE", "1")
    svc = BatchedEnsembleService(WallRuntime(), 2, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    f = svc.kput_many(0, ["k"], [b"v0"])
    while not f.done:
        svc.flush()  # first round pays the XLA compile (lease lapses)
    f = svc.kput_many(0, ["k"], [b"v1"])
    while not f.done:
        svc.flush()  # warm round: quorum confirms, lease renews in ms
    # leased mirror hit while nothing is pending
    g0 = svc.kget(0, "k")
    assert g0.done and g0.value == ("ok", b"v1")
    assert svc.read_fastpath_hits >= 1
    # slab-enqueued write, not yet flushed: the gate must see it NOW
    f2 = svc.kput_many(0, ["k"], [b"v2"])
    g = svc.kget(0, "k")
    assert not g.done, "read served around a pending slab write"
    assert svc.read_fastpath_miss_reasons.get("pending_write", 0) >= 1
    while not (f2.done and g.done):
        svc.flush()
    assert g.value == ("ok", b"v2")
    svc.stop()
