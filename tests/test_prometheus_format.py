"""Prometheus exposition-format conformance (text format 0.0.4).

``render_prometheus`` is a scrape surface: one malformed line makes
Prometheus reject the WHOLE scrape.  This test parses the rendered
text with a strict line grammar — labeled histograms' cumulative
``_bucket``/``+Inf``/``_sum``/``_count`` families, NaN gauges from
broken callbacks, escaped tenant labels — so the format can't
silently drift under refactors (the exposition-conformance
satellite).
"""

import math
import re

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import obs  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)

NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
#: exposition value grammar: decimal/scientific floats, integers,
#: NaN and signed Inf (what Prometheus' strconv accepts)
VALUE = r"(?:[+-]?(?:\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|\.\d+)|NaN|[+-]Inf)"
#: label VALUE: backslash-escaped; raw newlines/quotes are illegal
LABEL_VAL = r'"(?:[^"\\\n]|\\\\|\\"|\\n)*"'
LABEL = rf"{NAME}={LABEL_VAL}"
SAMPLE_RE = re.compile(
    rf"^({NAME})(?:\{{({LABEL}(?:,{LABEL})*)?\}})? ({VALUE})$")
HELP_RE = re.compile(rf"^# HELP ({NAME}) [^\n]*$")
TYPE_RE = re.compile(
    rf"^# TYPE ({NAME}) (counter|gauge|histogram|summary|untyped)$")
LABEL_SPLIT_RE = re.compile(rf"({NAME})=({LABEL_VAL})(?:,|$)")


def parse_exposition(txt: str):
    """Strict parse: every line must be a HELP/TYPE comment or a
    sample matching the grammar.  Returns (samples, types) where
    samples is [(name, {label: rawvalue}, value_str)]."""
    assert txt.endswith("\n"), "exposition must end with a newline"
    samples = []
    types = {}
    for line in txt.split("\n")[:-1]:
        if line.startswith("# HELP"):
            assert HELP_RE.match(line), f"bad HELP line: {line!r}"
            continue
        if line.startswith("# TYPE"):
            m = TYPE_RE.match(line)
            assert m, f"bad TYPE line: {line!r}"
            assert m.group(1) not in types, \
                f"duplicate TYPE for {m.group(1)}"
            types[m.group(1)] = m.group(2)
            continue
        m = SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, labelblob, value = m.group(1), m.group(2), m.group(3)
        labels = dict(LABEL_SPLIT_RE.findall(labelblob or ""))
        samples.append((name, labels, value))
    return samples, types


def base_name(name: str) -> str:
    for suf in ("_bucket", "_sum", "_count"):
        if name.endswith(suf):
            return name[:-len(suf)]
    return name


def _check_histogram_series(samples, hist_name, sel_labels):
    """One (histogram, label-set) series: cumulative nondecreasing
    buckets ending at le=+Inf == _count, plus _sum and _count."""
    def match(labels):
        # exact series selector: the non-le labels must equal the
        # selector (so {} picks the parent's own unlabeled series)
        return {k: v for k, v in labels.items()
                if k != "le"} == sel_labels

    buckets = [(lbl["le"], float(v)) for n, lbl, v in samples
               if n == f"{hist_name}_bucket" and match(lbl)]
    assert buckets, (hist_name, sel_labels)
    counts = [v for _le, v in buckets]
    assert counts == sorted(counts), \
        f"{hist_name}: buckets must be cumulative: {buckets}"
    les = [le for le, _v in buckets]  # raw label values keep quotes
    assert les[-1] == '"+Inf"', f"{hist_name}: last le must be +Inf"
    # the finite edges are strictly increasing numbers
    fin = [float(le.strip('"')) for le in les[:-1]]
    assert fin == sorted(set(fin)), les
    count = [float(v) for n, lbl, v in samples
             if n == f"{hist_name}_count" and match(lbl)]
    total = [float(v) for n, lbl, v in samples
             if n == f"{hist_name}_sum" and match(lbl)]
    assert len(count) == 1 and len(total) == 1, \
        f"{hist_name}: need exactly one _count and _sum per series"
    assert counts[-1] == count[0], \
        f"{hist_name}: +Inf bucket {counts[-1]} != _count {count[0]}"


def test_exposition_grammar_labeled_hist_nan_gauge_escaping():
    """A registry exercising every exposition feature at once parses
    under the strict grammar: labeled + unlabeled histogram series,
    a NaN gauge (broken callback), counters with hostile tenant
    labels, and a collector family."""
    r = obs.MetricsRegistry()
    c = r.counter("retpu_x_total", "a counter")
    c.inc(2)
    c.labels('evil"quote').inc(1)
    c.labels("new\nline\\slash").inc(4)
    r.gauge("retpu_broken_gauge", "callback dies",
            fn=lambda: 1 / 0)  # reads NaN
    r.gauge("retpu_neg_gauge").set(-2.5)
    h = r.histogram("retpu_h_ms", "labeled hist",
                    buckets=(0.5, 5.0, 50.0))
    h.record(0.1)  # parent-direct records AND labeled children
    h.labels("hot").record(3.0)
    h.labels("hot").record(7000.0)  # +Inf overflow
    h.labels('quiet"t').record(0.2)
    r.collect(lambda: {"retpu_fam_total": {
        "type": "counter", "help": "fam",
        "values": {"a b": 1, None: 7}}})
    txt = r.render_prometheus()
    samples, types = parse_exposition(txt)

    # TYPE declared for every sampled family, before its samples
    sampled = {base_name(n) for n, _l, _v in samples}
    assert sampled <= set(types), sampled - set(types)
    for name, labels, _v in samples:
        if base_name(name) != name:
            assert types[base_name(name)] == "histogram", name

    # counters: hostile labels escaped, values intact
    cx = {tuple(sorted(lbl.items())): v for n, lbl, v in samples
          if n == "retpu_x_total"}
    assert (("tenant", '"evil\\"quote"'),) in cx
    assert (("tenant", '"new\\nline\\\\slash"'),) in cx
    assert cx[()] == "2"

    # NaN gauge renders literal NaN (and parses under the grammar)
    nan = [v for n, _l, v in samples if n == "retpu_broken_gauge"]
    assert nan == ["NaN"] and math.isnan(float(nan[0]))
    neg = [v for n, _l, v in samples if n == "retpu_neg_gauge"]
    assert float(neg[0]) == -2.5

    # histogram series: the labeled children AND the parent's own
    # direct series, each cumulative with +Inf == _count
    _check_histogram_series(samples, "retpu_h_ms",
                            {"tenant": '"hot"'})
    _check_histogram_series(samples, "retpu_h_ms",
                            {"tenant": '"quiet\\"t"'})
    parent = [s for s in samples
              if s[0] == "retpu_h_ms_bucket" and "tenant" not in s[1]]
    assert parent, "parent-direct histogram series missing"
    _check_histogram_series(
        samples, "retpu_h_ms",
        {})  # unlabeled selector sees the parent series first
    # collector family: labeled + unlabeled samples
    fam = {lbl.get("tenant"): v for n, lbl, v in samples
           if n == "retpu_fam_total"}
    assert fam['"a b"'] == "1" and fam[None] == "7"


def test_exposition_grammar_live_service():
    """The real service registry (op-latency kind histogram, tenant
    collectors, compile counters, NaN backend-mem gauge on CPU)
    renders a scrape that parses clean under the same grammar."""
    svc = BatchedEnsembleService(WallRuntime(), 4, 3, 8, tick=None,
                                 max_ops_per_tick=4)
    svc.set_tenant_label(0, 'ten"ant')
    futs = [svc.kput_many(0, ["a", "b"], [b"1", b"2"]),
            svc.kget(1, "x")]
    while any(svc.queues):
        svc.flush()
    assert all(f.done for f in futs)
    txt = svc.obs_registry.render_prometheus()
    samples, types = parse_exposition(txt)
    names = {n for n, _l, _v in samples}
    assert "retpu_flushes_total" in names
    assert "retpu_compile_events_total" in names
    # per-op latency histogram: per-kind series, cumulative
    assert types["retpu_op_latency_ms"] == "histogram"
    _check_histogram_series(samples, "retpu_op_latency_ms",
                            {"kind": '"put"'})
    # CPU backend: the memory gauge reads NaN, and the scrape
    # survives it
    mem = [v for n, _l, v in samples
           if n == "retpu_backend_mem_bytes"]
    assert len(mem) == 1
    svc.stop()


def test_parse_rejects_malformed_lines():
    """The grammar itself has teeth: raw quotes/newlines in label
    values, bare words, and missing values all fail the parse."""
    for bad in ('retpu_x{tenant="a"b"} 1\n',
                "retpu_x 1 2 3 junk\n",
                "retpu_x{tenant=unquoted} 1\n",
                "retpu_x\n",
                "# TYPE retpu_x flavor\n"):
        with pytest.raises(AssertionError):
            parse_exposition(bad)
