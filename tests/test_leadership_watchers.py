"""leadership_watchers.erl parity: watch_leader_status notifications
and dead-watcher cleanup (test/leadership_watchers.erl:8-48).
"""

from riak_ensemble_tpu.peer import peer_name
from riak_ensemble_tpu.runtime import Actor
from riak_ensemble_tpu.testing import ManagedCluster


class Watcher(Actor):
    def __init__(self, runtime, name, node) -> None:
        super().__init__(runtime, name, node)
        self.statuses = []

    def handle(self, msg):
        self.statuses.append(msg)

    def last_status(self):
        return self.statuses[-1][0] if self.statuses else None


def test_leadership_watchers():
    mc = ManagedCluster(seed=26)
    mc.ens_start(3)
    node = mc.node0

    leader = mc.leader_id("root")
    lname = peer_name("root", leader)
    lpeer = mc.peer("root", leader)
    assert len(lpeer.watchers) == 0

    w1 = Watcher(mc.runtime, ("watcher", 1), node)
    mc.runtime.post(lname, ("watch_leader_status", w1.name))
    mc.runtime.run_for(0.1)
    assert len(lpeer.watchers) == 1
    assert w1.last_status() == "is_leading"

    # stop watching
    mc.runtime.post(lname, ("stop_watching", w1.name))
    mc.runtime.run_for(0.1)
    assert len(lpeer.watchers) == 0

    # watch again
    mc.runtime.post(lname, ("watch_leader_status", w1.name))
    mc.runtime.run_for(0.1)
    assert len(lpeer.watchers) == 1
    assert w1.last_status() == "is_leading"

    # suspend leader; new leader elected; resumed ex-leader notifies
    # is_not_leading
    mc.suspend_peer("root", leader)
    mc.wait_stable("root")
    mc.resume_peer("root", leader)

    def not_leading():
        mc.runtime.run_for(0.05)
        return w1.last_status() == "is_not_leading"
    assert mc.runtime.run_until(not_leading, 60.0, poll=0.1)

    # a second watcher registers; after it dies it is pruned
    w2 = Watcher(mc.runtime, ("watcher", 2), node)
    mc.runtime.post(lname, ("watch_leader_status", w2.name))
    mc.runtime.run_for(0.1)
    assert len(lpeer.watchers) == 2

    mc.runtime.stop_actor(w2.name)

    def pruned():
        mc.runtime.run_for(0.05)
        return len(lpeer.watchers) == 1
    assert mc.runtime.run_until(pruned, 60.0, poll=0.1), \
        "dead watcher not removed"
