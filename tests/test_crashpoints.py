"""Crash-point recovery sweeps (docs/ARCHITECTURE.md §15, ISSUE 15).

``RETPU_CRASHPOINT=<barrier>[:<nth>]`` kills a process with
``os._exit`` at a named durability barrier; these tests aim that at
every barrier the write path crosses and assert the recovery
contract after restart:

- **no fsync-acked write lost** — every key whose future resolved
  'ok' before the kill reads back exactly;
- **linearizability across the restart** — the one in-flight write
  the kill interrupted is the KeyModel 'maybe' case: it may have
  committed (crash after the fsync) or not (crash before), so its
  key must read either its value or NOTFOUND, never garbage and
  never a third value;
- **the restarted service serves** — a post-restore write acks and
  reads back.

The deterministic single-barrier sweep and the torn-tail replay fuzz
ride tier-1; the randomized kill sweep and the live 3-host
corruption-repair / replica-crash scenarios carry ``slow``.
"""

import os
import pickle
import random
import shutil
import signal
import subprocess
import sys
import textwrap
import time

import pytest

import conftest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from riak_ensemble_tpu import faults  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.parallel.wal import PyLogStore  # noqa: E402
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: scalar-put child: prints TRY before each submit and ACK after each
#: 'ok', so the parent can split "fsync-acked" (must survive) from
#: "in flight at the kill" (may have committed)
_PUT_CHILD = """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService)
    from riak_ensemble_tpu.runtime import Runtime
    rt = Runtime(seed=1)
    svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 data_dir={data!r})
    for i in range(6):
        print("TRY", i, flush=True)
        r = rt.await_future(svc.kput(i % 2, "k%d" % i, b"v%d" % i),
                            10.0)
        if r[0] == "ok":
            print("ACK", i, flush=True)
    print("SURVIVED", flush=True)
    os._exit(0)
"""

#: checkpoint child: acked working set, then save() — the kill lands
#: inside the checkpoint's tmp-write/rename/CURRENT-flip sequence
_CKPT_CHILD = """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService)
    from riak_ensemble_tpu.runtime import Runtime
    rt = Runtime(seed=1)
    svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 data_dir={data!r})
    for i in range(3):
        r = rt.await_future(svc.kput(i % 2, "k%d" % i, b"v%d" % i),
                            10.0)
        assert r[0] == "ok", r
        print("ACK", i, flush=True)
    print("SAVING", flush=True)
    svc.save()
    print("SURVIVED", flush=True)
    os._exit(0)
"""


def _run_child(template: str, data: str, crashpoint: str):
    env = dict(os.environ, RETPU_CRASHPOINT=crashpoint,
               JAX_PLATFORMS="cpu")
    child = textwrap.dedent(template.format(repo=REPO, data=data))
    return subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=240)


def _acked_and_inflight(stdout: str):
    acked, tried = [], []
    for line in stdout.splitlines():
        parts = line.split()
        if parts and parts[0] == "ACK":
            acked.append(int(parts[1]))
        elif parts and parts[0] == "TRY":
            tried.append(int(parts[1]))
    inflight = [i for i in tried if i not in acked]
    return acked, inflight


def _restore(data: str, seed: int = 99):
    rt = Runtime(seed=seed)
    svc = BatchedEnsembleService.restore(
        rt, data, tick=0.005, config=fast_test_config(),
        data_dir=data)
    return rt, svc


# -- the deterministic kill-at-every-barrier sweep (tier-1) -------------------


@pytest.mark.parametrize("barrier", [
    "wal_append:2",      # kill before the batch's records are appended
    "wal_fsync_pre:2",   # appended, not yet forced to disk
    "wal_fsync_post:2",  # durable, ack never sent — the 'maybe' case
])
def test_kill_at_wal_barrier_recovers(tmp_path, barrier):
    data = str(tmp_path / "data")
    proc = _run_child(_PUT_CHILD, data, barrier)
    assert proc.returncode == faults.CRASH_EXIT, \
        (proc.returncode, proc.stderr[-2000:])
    assert "SURVIVED" not in proc.stdout
    acked, inflight = _acked_and_inflight(proc.stdout)
    assert acked, "the barrier killed the child before any ack"

    rt, svc = _restore(data)
    # (a) no fsync-acked write lost
    for i in acked:
        got = rt.await_future(svc.kget(i % 2, "k%d" % i), 5.0)
        assert got == ("ok", b"v%d" % i), \
            f"acked write k{i} lost/stale after {barrier}: {got!r}"
    # (b) the in-flight write is the KeyModel 'maybe': its value or
    # NOTFOUND, never anything else
    for i in inflight:
        got = rt.await_future(svc.kget(i % 2, "k%d" % i), 5.0)
        assert got[0] == "ok"
        assert got[1] in (b"v%d" % i, NOTFOUND), \
            f"in-flight k{i} read garbage after {barrier}: {got!r}"
    # (c) the restarted service serves
    assert rt.await_future(svc.kput(0, "post", b"p"), 5.0)[0] == "ok"
    assert rt.await_future(svc.kget(0, "post"), 5.0) == ("ok", b"p")
    svc.stop()


@pytest.mark.parametrize("barrier", [
    "ckpt_tmp_write:1",  # host blob tmp written, never renamed
    "ckpt_rename:1",     # host blob live, CURRENT not flipped
    "ckpt_rename:3",     # CURRENT flipped, backup/rotation never ran
])
def test_kill_inside_checkpoint_recovers(tmp_path, barrier):
    """ISSUE 15 satellite: the ckpt_rename crash-point test — a kill
    anywhere inside save()'s tmp-write → rename → CURRENT-flip
    sequence leaves either the old (WAL-backed) or the new
    checkpoint image fully restorable, with zero acked writes lost
    either way (the 4-copy + CURRENT-pointer crash atomicity, now
    exercised at its exact barriers, dir-fsync included)."""
    data = str(tmp_path / "data")
    proc = _run_child(_CKPT_CHILD, data, barrier)
    assert proc.returncode == faults.CRASH_EXIT, \
        (proc.returncode, proc.stderr[-2000:])
    assert "SAVING" in proc.stdout and "SURVIVED" not in proc.stdout

    rt, svc = _restore(data)
    for i in range(3):
        got = rt.await_future(svc.kget(i % 2, "k%d" % i), 5.0)
        assert got == ("ok", b"v%d" % i), \
            f"acked write k{i} lost across {barrier}: {got!r}"
    assert rt.await_future(svc.kput(1, "post", b"p"), 5.0)[0] == "ok"
    svc.stop()


def test_kill_at_tree_save_barrier(tmp_path):
    """The synctree store's durability barrier: a kill at tree_save
    (post-append, pre-fsync) must leave every previously-synced
    record replayable and the torn state detected, not served.  No
    jax in the child — this one is cheap."""
    path = str(tmp_path / "t" / "tree")
    child = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        from riak_ensemble_tpu.synctree.backends import FileBackend
        be = FileBackend({path!r})
        be.store("k0", "v0")
        be.sync()
        print("SYNCED k0", flush=True)
        be.store("k1", "v1")
        be.sync()
        print("SURVIVED", flush=True)
    """)
    env = dict(os.environ, RETPU_CRASHPOINT="tree_save:2")
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == faults.CRASH_EXIT, \
        (proc.returncode, proc.stderr[-2000:])
    assert "SYNCED k0" in proc.stdout
    assert "SURVIVED" not in proc.stdout

    from riak_ensemble_tpu.synctree.backends import FileBackend
    be = FileBackend(path)
    assert be.fetch("k0") == "v0", "synced record lost at tree_save"
    assert be.fetch("k1") in ("v1", None)  # flushed, never fsynced
    be.close()


# -- torn-tail replay fuzz (ISSUE 15 satellite) -------------------------------


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_torn_tail_replay_fuzz(tmp_path, seed):
    """Random truncation/garbage offsets over a generated
    multi-record log: replay must stop EXACTLY at the tear — every
    record wholly before it intact (latest-per-key semantics), every
    record at/after it dropped, and appends after the reopen
    replayable (the truncate-at-tear contract of PyLogStore)."""
    rng = random.Random(seed)
    base = str(tmp_path / "base")
    st = PyLogStore(base)
    bounds = [4]  # frame end offsets (file starts with 4-byte magic)
    records = []
    for i in range(rng.randint(6, 14)):
        key = f"k{rng.randint(0, 4)}"
        val = "v%d" % i * rng.randint(1, 30)
        if rng.random() < 0.2:
            st.delete(key)
            records.append((key, None))
        else:
            st.store(key, val)
            records.append((key, val))
        bounds.append(st._f.tell())
    st.sync()
    st.close()
    size = os.path.getsize(base)
    assert bounds[-1] == size

    for case in range(8):
        cut = rng.randint(4, size)
        garbage = (rng.random() < 0.5)
        p = str(tmp_path / f"fuzz{case}")
        shutil.copyfile(base, p)
        with open(p, "r+b") as f:
            f.truncate(cut)
            if garbage:
                f.seek(0, 2)
                f.write(bytes(rng.getrandbits(8)
                              for _ in range(rng.randint(1, 40))))
        # expected: exactly the records whose frames END at/below cut
        n_complete = sum(1 for b in bounds[1:] if b <= cut)
        expect = {}
        for key, val in records[:n_complete]:
            if val is None:
                expect.pop(key, None)
            else:
                expect[key] = val
        st2 = PyLogStore(p)
        got = {k: st2.fetch(k) for k in st2.keys()}
        assert got == expect, \
            (f"seed {seed} case {case}: cut {cut}/{size} "
             f"garbage={garbage}: replay did not stop at the tear")
        # the reopened log keeps serving appends across another cycle
        st2.store("post", f"p{case}")
        st2.sync()
        st2.close()
        st3 = PyLogStore(p)
        assert st3.fetch("post") == f"p{case}"
        for k, v in expect.items():
            assert st3.fetch(k) == v
        st3.close()


# -- randomized kill sweep (slow lane) ----------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("seed", conftest.soak_seeds([7101, 7102,
                                                      7103]))
def test_randomized_crashpoint_kill_sweep(tmp_path, seed):
    """The randomized half of the kill sweep: a random barrier and
    hit count, a random keyed workload (scalar + batch puts,
    deletes), the child recording TRY/ACK to an fsync'd side log.
    After the kill the parent restores and checks the KeyModel rule
    per key: the last acked value — or any value tried after that
    ack (an in-flight op has no linearization upper bound), or
    NOTFOUND if a tried delete could explain it.  Nothing else."""
    rng = random.Random(seed)
    barrier = rng.choice(["wal_append", "wal_fsync_pre",
                          "wal_fsync_post"])
    nth = rng.randint(1, 4)
    data = str(tmp_path / "data")
    acklog = str(tmp_path / "acks")
    child = textwrap.dedent(f"""
        import os, pickle, sys
        sys.path.insert(0, {REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from riak_ensemble_tpu.config import fast_test_config
        from riak_ensemble_tpu.parallel.batched_host import (
            BatchedEnsembleService)
        from riak_ensemble_tpu.runtime import Runtime
        rng = np.random.default_rng({seed})
        rt = Runtime(seed={seed})
        svc = BatchedEnsembleService(rt, 3, 3, 8, tick=0.005,
                                     config=fast_test_config(),
                                     data_dir={data!r})
        ack_f = open({acklog!r}, "ab")
        def record(*row):
            ack_f.write(pickle.dumps(row))
            ack_f.flush(); os.fsync(ack_f.fileno())
        for n in range(30):
            e = int(rng.integers(3))
            r = rng.random()
            if r < 0.55:
                key = f"k{{int(rng.integers(5))}}"
                val = b"v%d" % int(rng.integers(1000))
                record("try", "put", e, key, val)
                if rt.await_future(svc.kput(e, key, val),
                                   10.0)[0] == "ok":
                    record("ack", "put", e, key, val)
            elif r < 0.75:
                keys = [f"b{{i}}" for i in range(3)]
                vals = [b"w%d" % int(rng.integers(1000))
                        for _ in range(3)]
                for kk, vv in zip(keys, vals):
                    record("try", "put", e, kk, vv)
                res = rt.await_future(
                    svc.kput_many(e, keys, vals), 10.0)
                for kk, vv, rr in zip(keys, vals, res):
                    if rr[0] == "ok":
                        record("ack", "put", e, kk, vv)
            else:
                key = f"k{{int(rng.integers(5))}}"
                record("try", "del", e, key, None)
                rr = rt.await_future(svc.kdelete(e, key), 10.0)
                if isinstance(rr, tuple) and rr[0] == "ok":
                    record("ack", "del", e, key, None)
        print("DONE", flush=True)
        os._exit(0)
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RETPU_CRASHPOINT=f"{barrier}:{nth}")
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode in (0, faults.CRASH_EXIT), \
        proc.stderr[-2000:]

    # per-(ens, key) model: last acked value, plus every value tried
    # AFTER that ack (the 'maybe' set a crash may have committed)
    acked = {}
    maybe = {}
    with open(acklog, "rb") as f:
        while True:
            try:
                kind, op, e, key, val = pickle.load(f)
            except EOFError:
                break
            k = (e, key)
            want = NOTFOUND if op == "del" else val
            if kind == "ack":
                acked[k] = want
                maybe[k] = set()
            else:
                maybe.setdefault(k, set()).add(want)

    rt2 = Runtime(seed=seed + 1000)
    svc2 = BatchedEnsembleService.restore(
        rt2, data, tick=0.005, config=fast_test_config(),
        data_dir=data)
    for (e, key) in set(acked) | set(maybe):
        got = rt2.await_future(svc2.kget(e, key), 5.0)
        assert got[0] == "ok", (e, key, got)
        allowed = set(maybe.get((e, key), set()))
        if (e, key) in acked:
            allowed.add(acked[(e, key)])
        else:
            allowed.add(NOTFOUND)  # never acked: may never have run
        assert got[1] in allowed, \
            (f"{barrier}:{nth} seed {seed}: {(e, key)} read "
             f"{got[1]!r}, allowed {allowed!r}")
    assert rt2.await_future(svc2.kput(0, "post", b"p"),
                            5.0)[0] == "ok"
    svc2.stop()


# -- live 3-host scenarios (slow lane) ----------------------------------------


def _flip_bytes(path: str, fracs=(0.45, 0.8)) -> bool:
    size = os.path.getsize(path)
    if size < 16:
        return False
    with open(path, "r+b") as f:
        for frac in fracs:
            off = max(4, int(size * frac) - 1)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0x5A]))
    return True


@pytest.mark.slow
def test_replica_wal_corruption_detected_and_repaired_from_live_replica(
        tmp_path):
    """THE corruption acceptance scenario on a live 3-host group:
    kill a replica, flip bits in its on-disk WAL (silent disk
    corruption while down), restart it.  The store's CRC gate must
    detect the corruption at replay (truncate/quarantine — never
    serve it), the leader re-syncs the survivor from live state, and
    the proof is the handoff: with the OTHER replica killed, the
    once-corrupt host carries the commit quorum alone and every
    acked write reads back exactly."""
    from test_repgroup import (_make_leader, _restart, _settle,
                               _spawn_replica, _wait_synced)

    procs, dirs = {}, {}
    for name in ("r1", "r2"):
        dirs[name] = str(tmp_path / name)
        procs[name] = _spawn_replica(dirs[name])
    svc = _make_leader(tmp_path, [procs["r1"][1], procs["r2"][1]])
    acked = {}

    def put_ok(phase, n=6):
        futs = []
        for i in range(n):
            e, key = i % 4, f"{phase}-{i}"
            val = b"%s/%d" % (phase.encode(), i)
            futs.append((e, key, val, svc.kput(e, key, val)))
        _settle(svc, [f for *_, f in futs], flushes=10)
        for e, key, val, f in futs:
            assert f.value[0] == "ok", (phase, key, f.value)
            acked[(e, key)] = val

    try:
        put_ok("pre")
        p1, _, _ = procs["r1"]
        p1.send_signal(signal.SIGKILL)
        p1.wait()
        put_ok("during")  # commits continue on the leader + r2 quorum

        # silent corruption while r1 is down: flip bits in every WAL
        # store file under its data dir
        flipped = 0
        for root, _dirs, files in os.walk(dirs["r1"]):
            for fn in files:
                if os.path.basename(root).startswith("wal.") \
                        and not fn.endswith(".tmp"):
                    flipped += _flip_bytes(os.path.join(root, fn))
        assert flipped, f"no WAL store files found under {dirs['r1']}"

        _restart(procs, dirs, "r1")
        _wait_synced(svc, 2)

        # the once-corrupt host must now carry the quorum alone
        p2, _, _ = procs["r2"]
        p2.send_signal(signal.SIGKILL)
        p2.wait()
        put_ok("after")

        futs = [(e, key, val, svc.kget(e, key))
                for (e, key), val in acked.items()]
        _settle(svc, [f for *_, f in futs], flushes=10)
        for e, key, val, f in futs:
            assert f.value == ("ok", val), \
                (f"acked write lost or corrupt value served at "
                 f"{(e, key)}: {f.value!r}")
    finally:
        try:
            svc.stop()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()


@pytest.mark.slow
def test_replica_killed_at_apply_barrier_catches_up(tmp_path):
    """replica_apply_pre_ack: a replica dies exactly between its
    durable apply and the ack.  The leader keeps committing on the
    remaining quorum; the restarted replica re-syncs (its WAL holds
    the un-acked apply — the retransmit/seq discipline must absorb
    it, not double-apply), and then carries the quorum alone with
    zero acked writes lost."""
    from test_repgroup import (_make_leader, _restart, _settle,
                               _spawn_replica, _wait_synced)

    procs, dirs = {}, {}
    os.environ["RETPU_CRASHPOINT"] = "replica_apply_pre_ack:2"
    try:
        dirs["r1"] = str(tmp_path / "r1")
        procs["r1"] = _spawn_replica(dirs["r1"])
    finally:
        os.environ.pop("RETPU_CRASHPOINT", None)
    dirs["r2"] = str(tmp_path / "r2")
    procs["r2"] = _spawn_replica(dirs["r2"])
    svc = _make_leader(tmp_path, [procs["r1"][1], procs["r2"][1]],
                       ack_timeout=5.0)
    acked = {}

    def put_ok(phase, n=6):
        futs = []
        for i in range(n):
            e, key = i % 4, f"{phase}-{i}"
            val = b"%s/%d" % (phase.encode(), i)
            futs.append((e, key, val, svc.kput(e, key, val)))
        _settle(svc, [f for *_, f in futs], flushes=12)
        for e, key, val, f in futs:
            assert f.value[0] == "ok", (phase, key, f.value)
            acked[(e, key)] = val

    try:
        put_ok("pre")
        # drive applies (heartbeats are empty applies) until the
        # barrier fires — the crash needs a live stream to cross it
        end = time.monotonic() + 90.0
        while procs["r1"][0].poll() is None \
                and time.monotonic() < end:
            svc.heartbeat()
            time.sleep(0.05)
        assert procs["r1"][0].poll() == faults.CRASH_EXIT, \
            "replica never died at replica_apply_pre_ack"
        put_ok("during")

        _restart(procs, dirs, "r1")
        _wait_synced(svc, 2)
        p2, _, _ = procs["r2"]
        p2.send_signal(signal.SIGKILL)
        p2.wait()
        put_ok("after")

        futs = [(e, key, val, svc.kget(e, key))
                for (e, key), val in acked.items()]
        _settle(svc, [f for *_, f in futs], flushes=10)
        for e, key, val, f in futs:
            assert f.value == ("ok", val), \
                f"acked write lost at {(e, key)}: {f.value!r}"
    finally:
        try:
            svc.stop()
        except Exception:  # noqa: BLE001 — teardown best effort
            pass
        for p, _, _ in procs.values():
            if p.poll() is None:
                p.kill()
