"""corrupt_{segment,upper,follower,exchange}_test.erl parity: synctree
corruption at segment/inner levels, on leader/follower/all peers.

The system must DETECT corruption ({corrupted, Level, Bucket} on the
verified read path, synctree.erl:302-340), step the peer down into
repair (leading_kv tree_corrupted → step_down(repair),
peer.erl:1276-1277), repair + re-exchange (peer_tree do_repair,
exchange), and resume serving reads — and a read must NEVER return a
bogus notfound for a key that was written (the invariant stated in
test/corrupt_segment_test.erl:24-27).

Corruption is injected two ways, mirroring the reference's intercepts
(test/synctree_intercepts.erl):
- post-hoc via ``SyncTree.corrupt``/``corrupt_upper`` (the
  synctree:corrupt/2 deliberate-corruption hook), and
- on the write path, wrapping the tree backend's ``store`` (the
  m_store intercept), later restored like ``m_store_normal``.
"""

import pytest

from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import NOTFOUND, PeerId


def _kget_never_notfound(mc, key, max_time=60.0):
    """read_until with the data-loss assertion."""
    mc.read_until(key, max_time)
    r = mc.kget(key)
    assert r[0] == "ok" and r[1].value is not NOTFOUND


def _corrupt_store_hook(tree, victim_key):
    """Write-path corruption (synctree_intercepts corrupt_segment):
    flip a byte of the victim's leaf hash as it lands in storage."""
    backend = tree.backend
    orig_store = backend.store
    leaf_level = tree.height + 1

    def store(loc, value):
        # loc is (level, bucket), tree-id-prefixed when the tree is
        # namespaced — level is always the second-to-last element.
        if isinstance(loc, tuple) and loc[-2] == leaf_level and \
                isinstance(value, dict) and victim_key in value:
            value = dict(value)
            h = value[victim_key]
            value[victim_key] = bytes([h[0] ^ 0xFF]) + h[1:]
        orig_store(loc, value)

    backend.store = store
    return lambda: setattr(backend, "store", orig_store)


def test_corrupt_segment_on_leader():
    """corrupt_segment_test: leader's segment corrupted on the write
    path; detection → repair → healed reads."""
    mc = ManagedCluster(seed=30)
    mc.ens_start(3)
    leader = mc.leader_id("root")
    tree = mc.tree_of("root", leader).tree

    restore = _corrupt_store_hook(tree, "corrupt")
    r = mc.kput("corrupt", b"test")
    assert r[0] == "ok", r
    restore()

    _kget_never_notfound(mc, "corrupt")


def test_corrupt_segment_posthoc():
    """Deliberate post-write corruption of the leader's leaf entry."""
    mc = ManagedCluster(seed=31)
    mc.ens_start(3)
    assert mc.kput("corrupt", b"test")[0] == "ok"

    leader = mc.leader_id("root")
    mc.tree_of("root", leader).tree.corrupt("corrupt")

    _kget_never_notfound(mc, "corrupt")


def test_corrupt_upper():
    """corrupt_upper_test: inner-node corruption two levels above the
    segment on a 5-peer ensemble heals."""
    mc = ManagedCluster(seed=32)
    mc.ens_start(5)
    assert mc.kput("corrupt", b"test")[0] == "ok"

    leader = mc.leader_id("root")
    tree = mc.tree_of("root", leader).tree
    tree.corrupt_upper("corrupt", level=tree.height - 1)

    _kget_never_notfound(mc, "corrupt")


def test_corrupt_follower():
    """corrupt_follower_test: followers' segments corrupted, then the
    (clean) leader suspended so a corrupted follower must win an
    election — via repair/exchange — and serve the key."""
    mc = ManagedCluster(seed=33)
    mc.ens_start(3)
    node = mc.node0
    assert mc.kput("corrupt", b"test")[0] == "ok"
    assert mc.kput("corrupt", b"test2")[0] == "ok"
    assert mc.kget("corrupt")[0] == "ok"

    leader = mc.leader_id("root")
    members = [PeerId("root", node), PeerId(2, node), PeerId(3, node)]
    for m in members:
        if m != leader:
            mc.tree_of("root", m).tree.corrupt("corrupt")

    mc.suspend_peer("root", leader)
    mc.runtime.run_for(2.0)
    mc.resume_peer("root", leader)
    mc.wait_stable("root")

    _kget_never_notfound(mc, "corrupt", max_time=120.0)
    r = mc.kget("corrupt")
    assert r[1].value == b"test2"


def test_corrupt_exchange():
    """corrupt_exchange_test: EVERY peer's segment corrupted; trees
    must repair (no trusted majority → all-trust path,
    riak_ensemble_exchange.erl:128-145) and reads heal."""
    mc = ManagedCluster(seed=34)
    mc.ens_start(3)
    node = mc.node0
    assert mc.kput("corrupt", b"test")[0] == "ok"

    members = [PeerId("root", node), PeerId(2, node), PeerId(3, node)]
    for m in members:
        mc.tree_of("root", m).tree.corrupt("corrupt")

    _kget_never_notfound(mc, "corrupt", max_time=120.0)
