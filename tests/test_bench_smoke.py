"""bench.py regression smoke (tier-1, fast): exercise the RMW rung
and the mixed runner in-process at tiny shapes, so a bench.py break
(signature drift, a renamed stats key, an op-kind mix that can't
commit) fails HERE instead of only at round time.

Deliberately small: sub-second measured windows over tiny [K, E]
planes — this pins that the runners RUN and report sane shapes, not
what the numbers are.
"""

import pytest

jax = pytest.importorskip("jax")

import bench  # noqa: E402


def test_rmw_rung_smoke():
    out = bench.run_rmw_service(n_ens=2, n_peers=3, n_slots=8, k=3,
                                seconds=0.05)
    assert out["rmw_device_ops_per_sec"] > 0
    assert out["rmw_host_ops_per_sec"] > 0
    assert out["rmw_device_speedup"] > 0
    # the device arm's contract: one flush per storm round, zero
    # conflicts; the host arm pays the read→CAS retry cycle
    assert out["rmw_device_flushes_per_round"] == 1.0
    assert out["rmw_device_conflicts"] == 0
    assert out["rmw_host_flushes_per_round"] >= 1.0


def test_mixed_rung_smoke():
    out = bench.run_mixed_service(n_ens=4, n_peers=3, n_slots=8, k=4,
                                  seconds=0.05)
    assert out["mixed_ops_per_sec"] > 0
    assert out["mixed_p99_ms"] >= out["mixed_p50_ms"] >= 0
    assert 0 < out["mixed_commit_fraction"] <= 1


def test_skewed_rung_smoke():
    """The compaction-regression tripwire: at the smoke shape the
    skewed rung's per-flush packed payload must stay under 25% of the
    full-width K·E layout's — a change that silently re-inflates the
    d2h transfer (compaction bypassed, active set mis-computed, pack
    layout regressed) fails tier-1 here.  warm/baseline off: the
    smoke pins shapes and the payload ratio, not the speedup."""
    out = bench.run_skewed_service(n_ens=128, n_peers=3, n_slots=8,
                                   k=8, seconds=0.05, warm=False,
                                   baseline=False)
    assert out["skewed_ops_per_sec"] > 0
    assert 0 < out["grid_occupancy"] < 0.25
    assert out["payload_bytes_per_flush"] > 0
    assert (out["payload_bytes_per_flush"]
            < 0.25 * out["payload_bytes_full_width_per_flush"]), out


def test_read_rung_smoke():
    """The read fast-path regression tripwire: on the uncontended
    read workload (disjoint read/write key sets) the fast-path
    hit-rate must exceed 90%, and the fastpath-off A/B arm must pass
    the fast-vs-device equivalence check (run_read_service asserts
    value equality internally and reports the count)."""
    out = bench.run_read_service(n_ens=32, n_peers=3, n_slots=8, k=8,
                                 seconds=0.2, warm=False)
    assert out["read_hit_rate"] > 0.9, out
    assert out["read_fastpath_hits"] > 0
    assert out["read_equivalence_ok"] is True
    assert out["read_equivalence_checked"] > 0
    # both arms measured, sane rates; the headline speedup is pinned
    # at round time (512-ens shape), not at smoke scale
    assert out["read_baseline_only_ops_per_sec"] > 0
    assert out["read_only_ops_per_sec"] > 0
    assert out["read_fastpath_speedup"] > 0


def test_mixed_tail_attribution_smoke():
    """The mixed rung names a dominant latency mark for every
    >5x-p50 batch (the tail-attribution satellite): keys present and
    internally consistent — cause counts sum to the tail count."""
    out = bench.run_mixed_service(n_ens=4, n_peers=3, n_slots=8, k=4,
                                  seconds=0.05)
    assert "mixed_tail_batches" in out
    causes = out["mixed_tail_causes"]
    assert sum(causes.values()) == out["mixed_tail_batches"]
    if out["mixed_tail_batches"]:
        assert out["mixed_tail_top_cause"] in causes
    else:
        assert out["mixed_tail_top_cause"] is None


def test_obs_overhead_smoke():
    """The obs-plane overhead tripwire: the headline pipelined loop
    with recording ON must stay within shouting distance of the
    RETPU_OBS=0 arm even at smoke shapes.  The tier-1 bound is
    deliberately loose (smoke samples are tiny batches on a noisy
    CI box — the measured per-batch delta is ~0); the 3% acceptance
    bound is pinned at round time on the real shape via the
    batch-granular interleaved-median A/B this same runner
    performs."""
    out = bench.run_obs_overhead(16, 3, 8, 4, seconds=0.4)
    assert out["obs_on_ops_per_sec"] > 0
    assert out["obs_off_ops_per_sec"] > 0
    assert (out["obs_on_ops_per_sec"]
            > 0.4 * out["obs_off_ops_per_sec"]), out


def test_op_trace_overhead_smoke():
    """The per-op SLO tracing A/B on the keyed rung: both arms run,
    the traced arm really recorded per-op samples, and tracing
    doesn't crater throughput even at smoke shapes (the 2% bound is
    pinned at round time on the real shape — smoke batches on a CI
    box measure noise, so the tier-1 bound stays loose)."""
    out = bench.run_op_trace_overhead(16, 3, 8, 4, seconds=0.4)
    assert out["op_trace_on_ops_per_sec"] > 0
    assert out["op_trace_off_ops_per_sec"] > 0
    assert out["op_trace_samples_recorded"] > 0, \
        "traced arm recorded no per-op samples"
    assert (out["op_trace_on_ops_per_sec"]
            > 0.4 * out["op_trace_off_ops_per_sec"]), out


def test_fleet_obs_overhead_smoke():
    """The fleet-federation A/B (ARCHITECTURE §11): both replicated
    arms run, the ON arm really posted obsq pulls and refreshed the
    per-link clock estimates, the OFF arm pulled nothing.  The 2%
    acceptance bound is pinned at round time on the real shape —
    smoke batches on a CI box measure noise, so the tier-1 bound
    stays loose."""
    out = bench.run_fleet_obs_overhead(0.4)
    assert out["fleet_obs_on_ops_per_sec"] > 0
    assert out["fleet_obs_off_ops_per_sec"] > 0
    assert out["fleet_obs_pulls"] > 0
    assert out["fleet_obs_watchdog_evals"] > 0
    assert out["fleet_obs_clock_samples"] > 0
    assert (out["fleet_obs_on_ops_per_sec"]
            > 0.4 * out["fleet_obs_off_ops_per_sec"]), out


def test_bench_trend_check():
    """The bench-trend ratchet rides tier-1 (the CI/tooling
    satellite): a missing/malformed BENCH round JSON, an empty
    trajectory, or an out-of-band same-box regression in the
    recorded rounds fails HERE instead of shipping an unreadable
    trajectory into the next round."""
    import os

    from tools import bench_trend

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = bench_trend.check(repo)
    assert report["rounds"] >= 5, report
    assert report["newest_ops_per_sec"] > 0
    # the trajectory table renders every recorded round
    rows = bench_trend.trajectory(bench_trend.load_rounds(repo))
    assert len(rows) == report["rounds"]
    assert all(isinstance(r["value"], (int, float)) for r in rows)


def test_bench_trend_check_rejects_malformed(tmp_path):
    """The ratchet is loud: a torn/headline-less round file raises,
    it does not read as an empty trajectory."""
    import json

    import pytest as _pytest

    from tools import bench_trend

    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path))  # no rounds at all
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path))
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "parsed": {"no_value": True}}))
    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path))
    # a same-box regression below the band trips the ratchet
    box = {"cpu_count": 2, "jax": "j", "jaxlib": "jl",
           "platform": "p"}
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "parsed": {"value": 100.0, "box": box}}))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"n": 2, "parsed": {"value": 10.0, "box": box}}))
    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path), tolerance=0.5)
    # within the band: ok, and the report names the comparison
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"n": 2, "parsed": {"value": 80.0, "box": box}}))
    rep = bench_trend.check(str(tmp_path), tolerance=0.5)
    assert rep["comparable_rounds"] == 1
    assert rep["best_same_box_ops_per_sec"] == 100.0


def test_recovery_rung_smoke():
    """The --stage recovery runner (ARCHITECTURE §15): checkpoint +
    WAL tail + restart really measure, the phases decompose the
    headline, and the tail write replayed from the WAL is served —
    the restart-to-serving number can never be a restore that lost
    the tail."""
    out = bench.run_recovery(0.2, smoke=True)
    assert out["recovery_ms"] > 0
    assert out["recovery_restore_ms"] > 0
    assert out["recovery_first_op_ms"] > 0
    assert out["recovery_ms"] >= out["recovery_restore_ms"]
    assert out["recovery_wal_records"] > 0, \
        "no WAL tail: the rung measured a checkpoint-only restart"
    assert out["recovery_shape"]["n_ens"] == 16


def test_bench_trend_polices_recovery_ms(tmp_path):
    """The recov_ms column's ratchet (ISSUE 15): lower-is-better, so
    a same-box restart-to-serving blowup past 1/tolerance x the best
    earlier round trips --check; rounds predating the stage neither
    ratchet nor fail."""
    import json

    import pytest as _pytest

    from tools import bench_trend

    box = {"cpu_count": 2, "jax": "j", "jaxlib": "jl",
           "platform": "p"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box,
                    "recovery_ms": 500.0}}))
    # regression: 1200 ms vs best 500 ms at tolerance 0.5 (2x band)
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box,
                    "recovery_ms": 1200.0}}))
    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path), tolerance=0.5)
    # inside the band: ok, and the report names the comparison
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box,
                    "recovery_ms": 800.0}}))
    rep = bench_trend.check(str(tmp_path), tolerance=0.5)
    assert rep["best_same_box_recovery_ms"] == 500.0
    assert rep["newest_recovery_ms"] == 800.0
    # a newest round predating the stage (no recovery_ms) passes
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box}}))
    bench_trend.check(str(tmp_path), tolerance=0.5)
    # the column renders in the trajectory
    rows = bench_trend.trajectory(bench_trend.load_rounds(
        str(tmp_path)))
    assert rows[0]["recovery_ms"] == 500.0
    assert rows[2]["recovery_ms"] is None


def test_ingress_rung_smoke():
    """The --stage ingress runner (§16): a promoted 3-host group
    behind real subprocess proxies and a subprocess client herd.
    Both A/Bs produce nonzero rates at the tiny shape (the RATIOS
    are round-time claims — every smoke host shares one GIL), the
    spread arm really was served from replica mirrors, and the
    per-tier evidence rode ONE fleet pull off the leader."""
    out = bench.run_ingress(0.5, smoke=True)
    arms = out["ingress_arms"]
    assert set(arms) == {"1", "2"}, arms
    for arm in arms.values():
        assert arm["batches_per_sec"] > 0
        assert arm["read_ops_per_sec"] > 0
        assert arm["write_ops_per_sec"] > 0
        assert arm["errors"] == 0, arm
    assert out["ingress_x"] > 0
    assert out["ingress_write_hold"] is not None
    flw = out["follower_read_arms"]
    assert flw["leader_only"]["read_ops_per_sec"] > 0
    assert flw["followers"]["read_ops_per_sec"] > 0
    assert flw["followers"]["write_ops_per_sec"] == 0
    # the replicas' own counters prove mirror-served reads (scraped
    # through the single ("fleet", "metrics") pull)
    assert out["follower_reads_served_total"] > 0
    assert out["ingress_engine_p99_ms"] is not None
    assert out["ingress_shape"]["smoke"] is True


def test_bench_trend_polices_ingress_x(tmp_path):
    """The ingress_x column's ratchet (ISSUE 16): higher-is-better,
    so a same-box proxy-scaling collapse below tolerance x the best
    earlier round trips --check; rounds predating the stage neither
    ratchet nor fail."""
    import json

    import pytest as _pytest

    from tools import bench_trend

    box = {"cpu_count": 2, "jax": "j", "jaxlib": "jl",
           "platform": "p"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box, "ingress_x": 2.0}}))
    # regression: 0.6x vs best 2.0x at tolerance 0.5 (half-of-best)
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box, "ingress_x": 0.6}}))
    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path), tolerance=0.5)
    # inside the band: ok, and the report names the comparison
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box, "ingress_x": 1.5}}))
    rep = bench_trend.check(str(tmp_path), tolerance=0.5)
    assert rep["best_same_box_ingress_x"] == 2.0
    assert rep["newest_ingress_x"] == 1.5
    # a newest round predating the stage (no ingress_x) passes
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box}}))
    bench_trend.check(str(tmp_path), tolerance=0.5)
    # the column renders in the trajectory
    rows = bench_trend.trajectory(bench_trend.load_rounds(
        str(tmp_path)))
    assert rows[0]["ingress_x"] == 2.0
    assert rows[2]["ingress_x"] is None


def test_commrepl_rung_smoke():
    """The --stage commrepl runner (§18): the contended-counter
    storm, comm lane vs ordered A/B on an in-process 3-host group.
    The smoke pins that both arms RUN, the comm arm really shipped
    merge entries and settled early acks, both arms converge to the
    identical final KV state, and the bytes-per-entry tripwire: on
    the hot-slot shape the coalesced merge stream must undercut the
    ordered delta stream per entry — a layout regression that
    re-inflates the merge section fails tier-1 here."""
    out = bench.run_commrepl(0.5, smoke=True)
    assert out["commrepl_ops_per_sec"] > 0
    assert out["commrepl_ack_p99_ms"] >= out["commrepl_ack_p50_ms"] \
        >= 0
    assert out["commrepl_merge_entries"] > 0, out
    assert out["commrepl_merge_cells"] > 0, out
    assert out["commrepl_early_acks"] > 0, out
    assert out["commrepl_coalesce_ratio"] >= 1.0
    assert out["rmw_comm_x"] > 0
    assert out["commrepl_convergence_ok"] is True, out
    assert (out["commrepl_bytes_per_entry"]
            < out["commrepl_ordered_bytes_per_entry"]), out
    assert out["commrepl_shape"]["smoke"] is True


def test_bench_trend_polices_rmw_comm_x(tmp_path):
    """The rmw_comm_x column's ratchet (ISSUE 18): higher-is-better,
    so a same-box comm-lane collapse below tolerance x the best
    earlier round trips --check; rounds predating the stage neither
    ratchet nor fail."""
    import json

    import pytest as _pytest

    from tools import bench_trend

    box = {"cpu_count": 2, "jax": "j", "jaxlib": "jl",
           "platform": "p"}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box, "rmw_comm_x": 2.0}}))
    # regression: 0.6x vs best 2.0x at tolerance 0.5 (half-of-best)
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box, "rmw_comm_x": 0.6}}))
    with _pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path), tolerance=0.5)
    # inside the band: ok, and the report names the comparison
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box, "rmw_comm_x": 1.5}}))
    rep = bench_trend.check(str(tmp_path), tolerance=0.5)
    assert rep["best_same_box_rmw_comm_x"] == 2.0
    assert rep["newest_rmw_comm_x"] == 1.5
    # a newest round predating the stage (no rmw_comm_x) passes
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": {"value": 100.0, "box": box}}))
    bench_trend.check(str(tmp_path), tolerance=0.5)
    # the column renders in the trajectory
    rows = bench_trend.trajectory(bench_trend.load_rounds(
        str(tmp_path)))
    assert rows[0]["rmw_comm_x"] == 2.0
    assert rows[2]["rmw_comm_x"] is None


def test_bench_smoke_trend_tripwire():
    """The current smoke rung vs the best same-fingerprint recorded
    point (BENCH_SMOKE_TREND.json), within a tolerance band: a
    host-path regression that halves the keyed rung on the SAME box
    fails tier-1 here.  A different box (no matching fingerprint)
    skips — cross-box comparisons are weather, not regressions."""
    import os

    from riak_ensemble_tpu.obs import box_fingerprint
    from tools import bench_trend

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shape = {"n_ens": 32, "n_peers": 3, "n_slots": 8, "k": 8}
    best = bench_trend.smoke_best(
        repo, bench_trend.fingerprint_key(box_fingerprint()), shape)
    if best is None:
        pytest.skip("no same-fingerprint smoke point recorded in "
                    "BENCH_SMOKE_TREND.json")
    rate = bench.run_keyed_batched_only(seconds=0.5, **shape)
    # 4x band: wide enough for loadavg weather on a shared box,
    # tight enough to catch a real host-path cliff
    assert rate > best / 4.0, (
        f"keyed smoke rung {rate:.0f} ops/s fell out of band vs the "
        f"recorded same-box best {best:.0f} (tolerance 4x)")


def test_native_resolve_ab_smoke():
    """The native-resolve A/B runner: both arms run, the native arm
    really takes the kernel (or the runner says the toolchain is
    absent), the breakdown carries the resolve components, and the
    WAL tempdir is cleaned up.  Ratio bounds stay loose — smoke
    shapes on a CI box measure noise; the real number is pinned at
    round time on the 512-ens rung."""
    out = bench.run_native_resolve_ab(16, 3, 8, 4, seconds=0.4)
    if not out.get("resolve_native_available"):
        pytest.skip("native resolve kernel unavailable")
    assert out["resolve_native_ops_per_sec"] > 0
    assert out["resolve_fallback_ops_per_sec"] > 0
    assert out["resolve_native_speedup"] > 0.4, out
    bd = out["resolve_native_latency_breakdown"]
    assert "resolve" in bd and "wal" in bd, bd
    assert "resolve_native" in bd, bd


def test_escale_point_smoke():
    """The E-scaling stage runner at a tiny shape: reports the
    pipelined and keyed-batched points with sane fields (the 1k/2k
    CPU points in the round JSON come from this exact runner)."""
    out = bench.run_escale_point(8, 3, 8, 4, seconds=0.2)
    assert out["n_ens"] == 8
    assert out["ops_per_sec"] > 0
    assert out["keyed_batched_ops_per_sec"] > 0
    assert out["p99_ms"] >= out["p50_ms"] >= 0


def test_obs_metric_names_documented():
    """The stats-schema ratchet (the test_env_knobs pattern applied
    to metric names): every metric a service registry can export must
    be listed in docs/ARCHITECTURE.md §11, and every `retpu_*` name
    the §11 tables document must still exist — so a new metric can't
    ship undocumented and a renamed one can't haunt the docs."""
    import os
    import re

    from riak_ensemble_tpu import obs
    from riak_ensemble_tpu.parallel.batched_host import (
        BatchedEnsembleService, WallRuntime)
    from riak_ensemble_tpu.parallel.repgroup import ReplicatedService
    from riak_ensemble_tpu.utils.trace import Tracer

    svc = BatchedEnsembleService(WallRuntime(), 2, 1, 4, tick=None,
                                 max_ops_per_tick=2)
    grp = ReplicatedService(WallRuntime(), 2, 1, 4, group_size=1)
    # the tracer's registry-fold names register on first use
    class _RT:
        now = 0.0
        trace = None
    tr = Tracer(_RT(), registry=svc.obs_registry).install()
    tr._on_event("probe", {})
    tr.finish(tr.begin("probe", 0), "ok")
    code_names = set(svc.obs_registry.names()) \
        | set(grp.obs_registry.names())
    svc.stop()
    grp.stop()
    assert code_names, "metric-name scan found nothing"

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "docs", "ARCHITECTURE.md"),
              encoding="utf-8") as fh:
        arch = fh.read()
    documented = set(re.findall(r"`(retpu_[a-z0-9_]+)`", arch))
    missing = code_names - documented
    assert not missing, (
        f"undocumented metric name(s) {sorted(missing)}: add them to "
        "docs/ARCHITECTURE.md §11 'Observability plane'")
    stale = documented - code_names
    assert not stale, (
        f"ARCHITECTURE.md documents removed metric(s) "
        f"{sorted(stale)}: drop the row or restore the metric")


def test_repgroup_rung_smoke():
    """The delta-replication regression tripwire (ARCHITECTURE §10):
    at the smoke shape (in-process replica hosts, skewed write set)
    the apply stream must (a) leave every replica lane bit-equal to
    the leader's — delta/full equivalence — and (b) ship under 25% of
    the full-plane figure per entry, so a change that silently
    re-inflates the stream (delta path bypassed, sections widened,
    fallback over-triggering) fails tier-1 here.  baseline off: the
    smoke pins the contract, not the speedup (that's round time's
    RETPU_REPL_DELTA=0 A/B arm)."""
    out = bench.run_repgroup(1.0, smoke=True, baseline=False)
    assert out["repgroup_ops_per_sec"] > 0
    assert out["repl_equivalence_ok"] is True, out
    assert out["repl_delta_entries"] > 0
    assert (out["repl_bytes_per_entry"]
            < 0.25 * out["repl_bytes_per_entry_full_plane"]), out


def test_faultsweep_cheap_arms_smoke():
    """Tier-1 tripwire for the faultsweep plumbing at the cheap end:
    the fsync-delay arm really delays the WAL barrier (counted,
    slower than baseline within noise), and the noisy-tenant arm
    attributes hot vs quiet ops with a real quiet p99.  The RTT
    depth-sweep arms spin up replica groups (seconds each) — they run
    in the slow lane and at round time."""
    from riak_ensemble_tpu import faults

    base = bench._faultsweep_fsync_arm(8, 8, 8, 0.3, 0.0)
    slow = bench._faultsweep_fsync_arm(8, 8, 8, 0.3, 3.0)
    assert faults.active_plan() is None  # the arms clean up
    assert base["ops_per_sec"] > 0 and slow["ops_per_sec"] > 0
    assert base["fsync_delays"] == 0
    assert slow["fsync_delays"] > 0, \
        "fsync arm ran but the barrier was never delayed"
    nt = bench._noisy_tenant_arm(16, 8, 8, 0.3, compact=True)
    assert nt["hot_ops"] > nt["quiet_ops"] > 0
    assert nt["quiet_p99_ms"] is not None
    assert nt["ops_per_sec"] > 0


@pytest.mark.slow
def test_faultsweep_smoke():
    """The full fault-injection rung runner (ARCHITECTURE §13): both
    RTT arms and depths run, the injected-delay counters prove the
    fault plane really fired inside the measured loops, the fsync arm
    shows a real (bounded-from-below) slowdown, the noisy-tenant A/B
    reports both compaction arms, and the fault config is embedded.
    Ratio bounds stay loose: smoke shapes on a CI box measure noise —
    the depth-2-wins-under-RTT acceptance is pinned at round time on
    the full shape."""
    from riak_ensemble_tpu import faults

    out = bench.run_faultsweep(0.4, smoke=True)
    fs = out["faultsweep"]
    assert faults.active_plan() is None  # the runner cleans up
    sweep = fs["rtt_sweep"]
    assert [p["rtt_ms"] for p in sweep] == [0.0, 1.0]
    for p in sweep:
        assert p["depth1_ops_per_sec"] > 0
        assert p["depth2_ops_per_sec"] > 0
        assert p["depth2_speedup"] > 0.4, p
    assert fs["fsync"]["baseline_ops_per_sec"] > 0
    assert fs["fsync"]["injected_fsync_delays"] > 0, \
        "fsync arm ran but the barrier was never delayed"
    assert fs["fsync"]["slowdown"] > 0.8, fs["fsync"]
    nt = fs["noisy_tenant"]
    assert nt["hot_ops"] > nt["quiet_ops"] > 0
    assert nt["quiet_p99_ms_compact"] is not None
    assert nt["quiet_p99_ms_nocompact"] is not None
    assert nt["quiet_p99_ratio"] > 0
    assert fs["fault_config"]["fsync_ms"] == 2.0
    assert out["faultsweep_depth2_speedup"] is not None


def test_autotune_guard_arm_smoke():
    """Tier-1 tripwire for the controller's tenant-guard plumbing at
    the cheap end (ARCHITECTURE §14): the guarded noisy-tenant arm
    must journal a real admission decision against the hot tenant
    and report both tenants' latencies.  The RTT convergence arms
    spin up replica groups (seconds each) — slow lane + round time."""
    nt = bench._noisy_tenant_arm(16, 8, 8, 0.3, compact=True,
                                 guard=True)
    assert nt["ops_per_sec"] > 0
    assert nt["hot_ops"] > nt["quiet_ops"] > 0
    assert nt["guard_decisions"], "guard armed but never decided"
    ev = nt["guard_decisions"][0]
    assert ev["actuator"] == "tenant_guard"
    assert ev["cause"] == "tenant_ops_share"
    assert ev["observed"] >= 0.7
    assert nt["throttled_rows"].get("hot"), nt["throttled_rows"]


@pytest.mark.slow
def test_autotune_smoke():
    """The full autotune A/B runner (ARCHITECTURE §14): static and
    controller arms run at both smoke RTT points, the journal
    reconstruction holds (asserted INSIDE the runner per arm), and
    the guard rung reports both arms.  Ratio bounds stay loose —
    smoke shapes on a CI box measure noise; the within-5%-of-best-
    static acceptance is pinned at round time on the full shape."""
    from riak_ensemble_tpu import faults

    out = bench.run_autotune(0.4, smoke=True)
    assert faults.active_plan() is None  # the arms clean up
    at = out["autotune"]
    assert [p["rtt_ms"] for p in at["points"]] == [0.0, 2.0]
    for p in at["points"]:
        assert p["controller_ops_per_sec"] > 0
        assert all(v > 0 for v in p["static_ops_per_sec"].values())
        assert p["journal_reconstructed"] is True
        assert p["vs_best_static"] > 0.3, p
    assert out["autotune_vs_best_static"] > 0.3
    tg = at["tenant_guard"]
    assert tg["guard_decisions"]
    assert tg["quiet_p99_ms_guarded"] > 0
    assert tg["quiet_p99_ms_unguarded"] > 0
