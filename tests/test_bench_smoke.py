"""bench.py regression smoke (tier-1, fast): exercise the RMW rung
and the mixed runner in-process at tiny shapes, so a bench.py break
(signature drift, a renamed stats key, an op-kind mix that can't
commit) fails HERE instead of only at round time.

Deliberately small: sub-second measured windows over tiny [K, E]
planes — this pins that the runners RUN and report sane shapes, not
what the numbers are.
"""

import pytest

jax = pytest.importorskip("jax")

import bench  # noqa: E402


def test_rmw_rung_smoke():
    out = bench.run_rmw_service(n_ens=2, n_peers=3, n_slots=8, k=3,
                                seconds=0.05)
    assert out["rmw_device_ops_per_sec"] > 0
    assert out["rmw_host_ops_per_sec"] > 0
    assert out["rmw_device_speedup"] > 0
    # the device arm's contract: one flush per storm round, zero
    # conflicts; the host arm pays the read→CAS retry cycle
    assert out["rmw_device_flushes_per_round"] == 1.0
    assert out["rmw_device_conflicts"] == 0
    assert out["rmw_host_flushes_per_round"] >= 1.0


def test_mixed_rung_smoke():
    out = bench.run_mixed_service(n_ens=4, n_peers=3, n_slots=8, k=4,
                                  seconds=0.05)
    assert out["mixed_ops_per_sec"] > 0
    assert out["mixed_p99_ms"] >= out["mixed_p50_ms"] >= 0
    assert 0 < out["mixed_commit_fraction"] <= 1
