"""read_tombstone_test.erl parity: the tombstone-avoidance
optimization (test/read_tombstone_test.erl:16-47).

A notfound read normally writes a tombstone in case an unseen partial
write exists.  If the leader waits ``notfound_read_delay`` for replies
from EVERY peer and all say notfound, the tombstone write is skipped
(all_or_quorum required mode, msg.erl:282-317; update_key skip,
peer.erl:1568-1584).  With a member suspended, full responses can't
arrive and the tombstone must be written.
"""

from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import NOTFOUND, PeerId


def _has_tombstone(mc, member, key) -> bool:
    """debug_local_get analog: a tombstone is an Obj wrapping NOTFOUND
    in the backend, vs no entry at all."""
    peer = mc.peer("root", member)
    assert peer is not None
    return key in peer.mod.data


def test_tombstone_avoidance():
    mc = ManagedCluster(seed=25)
    mc.ens_start(3)
    mc.config.notfound_read_delay = 3.0

    node = mc.node0
    leader = mc.leader_id("root")
    members = [PeerId("root", node), PeerId(2, node), PeerId(3, node)]
    followers = [m for m in members if m != leader]

    # All peers respond: read returns notfound with NO tombstones.
    r = mc.kget("test")
    assert r[0] == "ok" and r[1].value is NOTFOUND
    mc.runtime.run_for(1.0)
    for m in members:
        assert not _has_tombstone(mc, m, "test"), f"tombstone on {m}"

    # One member suspended + no delay: tombstones must be written on
    # the active peers.
    mc.config.notfound_read_delay = 0.0
    mc.suspend_peer("root", followers[1])
    r = mc.kget("test2")
    assert r[0] == "ok" and r[1].value is NOTFOUND
    mc.resume_peer("root", followers[1])

    def tombstoned():
        mc.runtime.run_for(0.05)
        return _has_tombstone(mc, leader, "test2") and \
            _has_tombstone(mc, followers[0], "test2")
    assert mc.runtime.run_until(tombstoned, 30.0, poll=0.1), \
        "active peers missing tombstones"
