"""drop_write_test.erl parity: silently-dropped follower backend
writes are healed by the read path (test/drop_write_test.erl:8-19).

The intercept (riak_ensemble_basic_backend_intercepts.erl drop_put)
acks puts of keys prefixed "drop" without storing them — on every peer
except the one literally named "root".  After the leader (holding the
only durable copy) is suspended and a new leader elected among the
data-less peers, reads must heal via the quorum read + epoch-rewrite
path once the old leader returns, and must never return notfound.
"""

import pytest

from riak_ensemble_tpu import backend as backendlib
from riak_ensemble_tpu.backend import BasicBackend
from riak_ensemble_tpu.testing import ManagedCluster


def test_drop_write_healed_by_read(monkeypatch):
    orig_put = BasicBackend.put

    def drop_put(self, key, obj, from_):
        if isinstance(key, str) and key.startswith("drop") and \
                self.peer_id.name != "root":
            backendlib.reply(from_, obj)  # ack without storing
        else:
            orig_put(self, key, obj, from_)

    monkeypatch.setattr(BasicBackend, "put", drop_put)

    mc = ManagedCluster(seed=23)
    mc.ens_start(5)

    leader = mc.leader_id("root")
    r = mc.kput("drop", b"test")
    assert r[0] == "ok", r
    assert mc.kget("drop")[0] == "ok"

    mc.suspend_peer("root", leader)
    mc.wait_stable("root")
    mc.resume_peer("root", leader)
    mc.read_until("drop")
