"""Engine-state checkpoint/restore via orbax (the facts-persistence
role, SURVEY §5 checkpoint/resume, for the batched engine)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import checkpoint as ckpt  # noqa: E402
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402


def test_save_restore_roundtrip(tmp_path):
    e, m, s = 32, 5, 8
    state = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    state, res = eng.kv_step(state, kind, jnp.zeros((e,), jnp.int32),
                             jnp.full((e,), 42, jnp.int32),
                             jnp.ones((e,), bool), up)
    assert bool(np.asarray(res.committed).all())

    path = str(tmp_path / "ckpt")
    ckpt.save(path, state)
    restored = ckpt.load(path, template=eng.init_state(e, m, s))

    for a, b in zip(state, restored):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # A restored state is immediately serveable — no probe phase.
    gk = jnp.full((e,), eng.OP_GET, jnp.int32)
    _, res2 = eng.kv_step(restored, gk, jnp.zeros((e,), jnp.int32),
                          jnp.zeros((e,), jnp.int32),
                          jnp.ones((e,), bool), up)
    assert bool(np.asarray(res2.get_ok).all())
    assert (np.asarray(res2.value) == 42).all()
