"""Engine-state checkpoint/restore via orbax (the facts-persistence
role, SURVEY §5 checkpoint/resume, for the batched engine)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import checkpoint as ckpt  # noqa: E402
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402


def test_save_restore_roundtrip(tmp_path):
    e, m, s = 32, 5, 8
    state = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    state, res = eng.kv_step(state, kind, jnp.zeros((e,), jnp.int32),
                             jnp.full((e,), 42, jnp.int32),
                             jnp.ones((e,), bool), up)
    assert bool(np.asarray(res.committed).all())

    path = str(tmp_path / "ckpt")
    ckpt.save(path, state)
    restored = ckpt.load(path, template=eng.init_state(e, m, s))

    for a, b in zip(state, restored):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # A restored state is immediately serveable — no probe phase.
    gk = jnp.full((e,), eng.OP_GET, jnp.int32)
    _, res2 = eng.kv_step(restored, gk, jnp.zeros((e,), jnp.int32),
                          jnp.zeros((e,), jnp.int32),
                          jnp.ones((e,), bool), up)
    assert bool(np.asarray(res2.get_ok).all())
    assert (np.asarray(res2.value) == 42).all()


def test_save_restore_sharded_state(tmp_path):
    """Checkpointing a mesh-sharded EngineState (orbax handles the
    shardings) and restoring it into a sharded template — the
    multi-host checkpoint contract on the virtual mesh."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from riak_ensemble_tpu.ops import checkpoint as ckpt
    from riak_ensemble_tpu.ops import engine as eng
    from riak_ensemble_tpu.parallel.mesh import ShardedEngine, make_mesh

    if jax.device_count() < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")

    se = ShardedEngine(make_mesh(4, 2))
    e, m = 8, 4
    state = se.init_state(e, m, 8, views=[list(range(m))])
    up = jnp.ones((e, m), bool)
    state, won = se.elect_step(state, jnp.ones((e,), bool),
                               jnp.zeros((e,), jnp.int32), up)
    kind = jnp.full((2, e), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((2, e), jnp.int32)
    val = jnp.asarray(np.arange(2 * e).reshape(2, e) + 1, jnp.int32)
    state, _ = se.kv_step_scan(state, kind, slot, val,
                               jnp.ones((2, e), bool), up)

    path = str(tmp_path / "sharded")
    ckpt.save(path, state)
    restored = ckpt.load(path, template=se.init_state(
        e, m, 8, views=[list(range(m))]))

    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the restored (sharded) state keeps serving
    kind_g = jnp.full((1, e), eng.OP_GET, jnp.int32)
    restored, res = se.kv_step_scan(restored, kind_g,
                                    jnp.zeros((1, e), jnp.int32),
                                    jnp.zeros((1, e), jnp.int32),
                                    jnp.ones((1, e), bool), up)
    assert np.asarray(res.get_ok).all()
    np.testing.assert_array_equal(np.asarray(res.value)[0],
                                  np.arange(e) + e + 1)
