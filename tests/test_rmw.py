"""Single-round device RMW: the fused kmodify (ISSUE 2 tentpole).

The reference runs kmodify's mod-fun inside the leader's FSM so a
read-modify-write commits in one consensus round (do_kmodify,
peer.erl:303-317).  The batched analog is the engine's ``OP_RMW`` op
kind: the round reads the slot's latest hash-valid value, applies a
registered mod-fun table entry (funref.RMW_*) and commits the result
under the same round's seq discipline — so device RMWs cost ONE flush
and can never CAS-conflict.  Pinned here:

- engine-level semantics of every table fun (vs an int32 numpy
  reference), including absence/tombstone-as-0 and put-if-absent;
- the service fast path: a table-resolvable kmodify commits in one
  flush round (asserted), N concurrent increments of one key converge
  to exactly +N with zero conflicts in that same flush;
- device-table vs host-fallback equivalence: the same fun sequence
  produces the same values AND the same (epoch, seq) versions;
- the host path's contention storm stays bounded (chained CAS +
  jittered backoff) and surfaces ``rmw_conflicts``;
- WAL durability of device-native (inline) keys across restore.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu import funref  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402


def _elected(e=2, m=3, s=8):
    st = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    st, won = eng.elect_step(st, jnp.ones((e,), bool),
                             jnp.zeros((e,), jnp.int32), up)
    assert np.asarray(won).all()
    return st, up


def _rmw(st, up, code, opd, slot=0):
    e = st.leader.shape[0]
    return eng.kv_step(
        st, jnp.full((e,), eng.OP_RMW, jnp.int32),
        jnp.full((e,), slot, jnp.int32),
        jnp.full((e,), opd, jnp.int32),
        jnp.zeros((e,), bool), up,
        exp_epoch=jnp.full((e,), code, jnp.int32),
        exp_seq=jnp.zeros((e,), jnp.int32))


def _get(st, up, slot=0):
    e = st.leader.shape[0]
    return eng.kv_step(
        st, jnp.full((e,), eng.OP_GET, jnp.int32),
        jnp.full((e,), slot, jnp.int32), jnp.zeros((e,), jnp.int32),
        jnp.zeros((e,), bool), up)


def test_engine_rmw_fun_table_semantics():
    """Every table fun against an int32 numpy oracle, chained over one
    slot (each round reads the previous round's commit)."""
    st, up = _elected()
    i32 = funref.i32  # int32 wraparound oracle
    cur = 0
    prog = [(eng.RMW_ADD, 5), (eng.RMW_ADD, 2 ** 31 - 1),  # wraps
            (eng.RMW_SUB, 7), (eng.RMW_MAX, 100), (eng.RMW_MIN, 42),
            (eng.RMW_BOR, 0b1010), (eng.RMW_BAND, 0b0110),
            (eng.RMW_BXOR, -1), (eng.RMW_SET, 1234)]
    ops = {eng.RMW_ADD: lambda c, o: i32(c + o),
           eng.RMW_SUB: lambda c, o: i32(c - o),
           eng.RMW_MAX: max, eng.RMW_MIN: min,
           eng.RMW_BOR: lambda c, o: c | o,
           eng.RMW_BAND: lambda c, o: c & o,
           eng.RMW_BXOR: lambda c, o: c ^ o,
           eng.RMW_SET: lambda c, o: o}
    for code, opd in prog:
        st, r = _rmw(st, up, code, opd)
        cur = ops[code](cur, opd)
        assert np.asarray(r.committed).all(), (code, opd)
        assert (np.asarray(r.value) == cur).all(), (code, opd)
    st, g = _get(st, up)
    assert (np.asarray(g.value) == int(cur)).all()


def test_engine_rmw_absent_and_put_if_absent():
    st, up = _elected()
    # arithmetic on an absent slot reads 0
    st, r = _rmw(st, up, eng.RMW_ADD, 7, slot=3)
    assert np.asarray(r.committed).all()
    assert (np.asarray(r.value) == 7).all()
    # put-if-absent over a live value: no commit, nothing written
    st, r = _rmw(st, up, eng.RMW_PIA, 99, slot=3)
    assert not np.asarray(r.committed).any()
    st, g = _get(st, up, slot=3)
    assert (np.asarray(g.value) == 7).all()
    # put-if-absent on a fresh slot commits the operand
    st, r = _rmw(st, up, eng.RMW_PIA, 99, slot=4)
    assert np.asarray(r.committed).all()
    # an RMW computing 0 writes the tombstone: reads are notfound
    st, r = _rmw(st, up, eng.RMW_SET, 0, slot=3)
    assert np.asarray(r.committed).all()
    st, g = _get(st, up, slot=3)
    assert np.asarray(g.get_ok).all()
    assert not np.asarray(g.found).any()
    # ...and put-if-absent succeeds over the tombstone
    st, r = _rmw(st, up, eng.RMW_PIA, 5, slot=3)
    assert np.asarray(r.committed).all()


def test_engine_rmw_needs_leader_quorum():
    e, m = 2, 3
    st = eng.init_state(e, m, 8)  # leaderless
    up = jnp.ones((e, m), bool)
    st, r = _rmw(st, up, eng.RMW_ADD, 1)
    assert not np.asarray(r.committed).any()


def _svc(n_ens=2, **kw):
    runtime = Runtime(seed=7)
    svc = BatchedEnsembleService(runtime, n_ens, 3, n_slots=8,
                                 tick=None,
                                 config=fast_test_config(), **kw)
    return runtime, svc


def _drive(svc, futs, flushes=60):
    n = 0
    while not all(f.done for f in futs):
        assert n < flushes, "futures did not resolve"
        svc.flush()
        n += 1
    return n


def test_kmodify_device_fastpath_single_flush():
    """Acceptance: a table-resolvable kmodify commits in ONE flush
    round — enqueue, one flush() call, resolved."""
    _rt, svc = _svc()
    f = svc.kmodify(0, "ctr", funref.ref("rmw:add", 5), 0)
    assert not f.done
    assert _drive(svc, [f]) == 1, "device kmodify took > 1 flush"
    assert f.value[0] == "ok"
    assert svc.rmw_device_fastpath == 1
    g = svc.kget(0, "ctr")
    _drive(svc, [g])
    assert g.value == ("ok", 5)
    # versions ride like any committed write (CAS tokens work)
    gv = svc.kget_vsn(0, "ctr")
    _drive(svc, [gv])
    assert gv.value == ("ok", 5, tuple(f.value[1]))


def test_kmodify_device_concurrent_increments_converge():
    """N concurrent increments of one key on the device path: one
    flush, zero CAS conflicts, exactly +N, distinct versions."""
    _rt, svc = _svc()
    n = 6
    futs = [svc.kmodify(0, "ctr", funref.ref("rmw:add", 1), 0)
            for _ in range(n)]
    assert _drive(svc, futs) == 1, "device RMWs took > 1 flush"
    assert all(f.value[0] == "ok" for f in futs)
    assert len({tuple(f.value[1]) for f in futs}) == n
    assert svc.rmw_conflicts == 0
    assert svc.rmw_device_fastpath == n
    g = svc.kget(0, "ctr")
    _drive(svc, [g])
    assert g.value == ("ok", n)


def test_kmodify_many_device_batch():
    _rt, svc = _svc()
    keys = [f"k{i}" for i in range(5)]
    f = svc.kmodify_many(0, keys, funref.ref("rmw:add", 3))
    assert _drive(svc, [f]) == 1
    assert [r[0] for r in f.value] == ["ok"] * 5
    g = svc.kget_many(0, keys)
    _drive(svc, [g])
    assert g.value == [("ok", 3)] * 5
    # second wave accumulates
    f = svc.kmodify_many(0, keys, funref.ref("rmw:add", 4))
    _drive(svc, [f])
    g = svc.kget_many(0, keys)
    _drive(svc, [g])
    assert g.value == [("ok", 7)] * 5


def test_kmodify_many_host_fallback_callable():
    """A non-table fun falls back to per-key kmodify chains under the
    one batch future — same results, host path."""
    _rt, svc = _svc()
    keys = [f"k{i}" for i in range(4)]
    f = svc.kmodify_many(0, keys, lambda vsn, cur: int(cur) + 2)
    _drive(svc, [f])
    assert [r[0] for r in f.value] == ["ok"] * 4
    g = svc.kget_many(0, keys)
    _drive(svc, [g])
    assert g.value == [("ok", 2)] * 4
    assert svc.rmw_device_fastpath == 0


def test_device_vs_host_equivalence_sweep():
    """The same fun/operand sequence through the device table and
    through host callables with identical int32 semantics must yield
    the same values AND the same (epoch, seq) versions — both paths
    commit exactly once per op, so the seq discipline lines up."""
    rng = np.random.default_rng(42)
    names = ["rmw:add", "rmw:sub", "rmw:max", "rmw:min", "rmw:set",
             "rmw:band", "rmw:bor", "rmw:bxor"]
    prog = [(names[rng.integers(len(names))],
             int(rng.integers(-1000, 1000)), f"key{rng.integers(3)}")
            for _ in range(30)]

    _rt, dev_svc = _svc()
    _rt2, host_svc = _svc()
    for name, opd, key in prog:
        fd = dev_svc.kmodify(0, key, funref.ref(name, opd), 0)
        host_fn = funref.resolve(funref.ref(name, opd))
        fh = host_svc.kmodify(0, key, lambda v, c, fn=host_fn: fn(v, c),
                              0)
        _drive(dev_svc, [fd])
        _drive(host_svc, [fh])
        assert fd.value == fh.value, (name, opd, key)
    assert dev_svc.rmw_device_fastpath == len(prog)
    assert host_svc.rmw_device_fastpath == 0
    for key in {k for _n, _o, k in prog}:
        gd = dev_svc.kget_vsn(0, key)
        gh = host_svc.kget_vsn(0, key)
        _drive(dev_svc, [gd])
        _drive(host_svc, [gh])
        assert gd.value == gh.value, key


def test_host_contention_storm_bounded_rounds():
    """Host-path stampede on one key: chained CAS + jittered backoff
    keep total rounds bounded and every increment lands."""
    _rt, svc = _svc()
    n = 6

    def incr(vsn, cur):
        return int(cur) + 1

    futs = [svc.kmodify(0, "ctr", incr, 0, retries=2 * n + 4)
            for _ in range(n)]
    rounds = _drive(svc, futs, flushes=6 * n)
    assert all(f.value[0] == "ok" for f in futs), [f.value for f in futs]
    g = svc.kget(0, "ctr")
    _drive(svc, [g])
    assert g.value == ("ok", n)
    # bounded: with same-flush chaining one flush call retires at
    # least one winner, so the storm converges in <= ~2 calls per op
    # plus backoff slack — far below the retry ceiling
    assert rounds <= 4 * n, rounds
    assert svc.rmw_conflicts >= n - 1


def test_mixed_storage_put_flips_inline_and_back():
    """kput over a device-native key flips it to handle storage (and
    makes RMW take the host path); a fresh RMW after delete flips it
    back."""
    _rt, svc = _svc()
    f = svc.kmodify(0, "k", funref.ref("rmw:add", 9), 0)
    _drive(svc, [f])
    p = svc.kput(0, "k", b"payload")
    _drive(svc, [p])
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", b"payload")
    # table fun over bytes: host fallback, contained failure
    f2 = svc.kmodify(0, "k", funref.ref("rmw:add", 1), 0)
    _drive(svc, [f2])
    assert f2.value == "failed"
    d = svc.kdelete(0, "k")
    _drive(svc, [d])
    f3 = svc.kmodify(0, "k", funref.ref("rmw:add", 4), 0)
    _drive(svc, [f3])
    assert f3.value[0] == "ok"
    g3 = svc.kget(0, "k")
    _drive(svc, [g3])
    assert g3.value == ("ok", 4)


def test_put_if_absent_service_semantics():
    _rt, svc = _svc()
    f = svc.kmodify(0, "k", funref.ref("rmw:put_if_absent", 11), 0)
    _drive(svc, [f])
    assert f.value[0] == "ok"
    f2 = svc.kmodify(0, "k", funref.ref("rmw:put_if_absent", 22), 0)
    _drive(svc, [f2])
    assert f2.value == "failed"
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", 11)


def test_rmw_computed_tombstone_reads_notfound():
    """A fun result of 0 IS the tombstone (engine-wide 0-is-notfound
    encoding): the key reads NOTFOUND, and a later RMW revives it
    from 0."""
    from riak_ensemble_tpu.types import NOTFOUND

    _rt, svc = _svc()
    f = svc.kmodify(0, "k", funref.ref("rmw:add", 9), 0)
    _drive(svc, [f])
    f2 = svc.kmodify(0, "k", funref.ref("rmw:set", 0), 0)
    _drive(svc, [f2])
    assert f2.value[0] == "ok"
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", NOTFOUND)
    # the tombstoned slot recycles like a committed delete (no slot
    # leak on the device arm — review regression)
    svc.flush()
    assert "k" not in svc.key_slot[0]
    assert len(svc.free_slots[0]) == svc.n_slots
    f3 = svc.kmodify(0, "k", funref.ref("rmw:add", 3), 0)
    _drive(svc, [f3])
    g2 = svc.kget(0, "k")
    _drive(svc, [g2])
    assert g2.value == ("ok", 3)


def test_put_if_absent_refuses_live_zero_payload():
    """Review regression: put-if-absent on a host-payload key holding
    the live int 0 must REFUSE (do_kput_once contract) — the host
    fallback routes through the (0,0)-CAS, never through the
    cur==0-is-absent int mirror."""
    _rt, svc = _svc()
    p = svc.kput(0, "k", 0)  # live host payload int 0
    _drive(svc, [p])
    f = svc.kmodify(0, "k", funref.ref("rmw:put_if_absent", 7), 0)
    _drive(svc, [f])
    assert f.value == "failed"
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", 0)


def test_host_fallback_table_fun_computing_zero_tombstones():
    """Review regression: a TABLE fun that computes 0 on a
    host-payload key mirrors the device path's 0-is-tombstone — the
    key reads NOTFOUND, not ('ok', 0)."""
    from riak_ensemble_tpu.types import NOTFOUND

    _rt, svc = _svc()
    p = svc.kput(0, "k", 5)  # handle storage: device path ineligible
    _drive(svc, [p])
    f = svc.kmodify(0, "k", funref.ref("rmw:sub", 5), 0)
    _drive(svc, [f])
    assert f.value[0] == "ok"
    assert svc.rmw_device_fastpath == 0
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", NOTFOUND)


def test_numpy_operand_takes_device_path():
    """Review regression: numpy integer operands/defaults must not
    silently demote to the host retry path."""
    import numpy as _np

    _rt, svc = _svc()
    f = svc.kmodify(0, "k", funref.ref("rmw:add", _np.int32(4)),
                    _np.int32(0))
    assert _drive(svc, [f]) == 1
    assert f.value[0] == "ok"
    assert svc.rmw_device_fastpath == 1


def test_put_if_absent_arbitrary_payload_routes_kput_once():
    """Review regression: put-if-absent routes by NAME, not by
    int32-operand resolvability — a non-int operand must still take
    the (0,0)-CAS (refusing live values, int 0 included), and it
    doubles as create-if-missing for arbitrary payloads."""
    _rt, svc = _svc()
    p = svc.kput(0, "k", 0)  # live host payload int 0
    _drive(svc, [p])
    f = svc.kmodify(0, "k", funref.ref("rmw:put_if_absent", b"cfg"), 0)
    _drive(svc, [f])
    assert f.value == "failed"
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", 0)
    f2 = svc.kmodify(0, "fresh",
                     funref.ref("rmw:put_if_absent", b"cfg"), 0)
    _drive(svc, [f2])
    assert f2.value[0] == "ok"
    g2 = svc.kget(0, "fresh")
    _drive(svc, [g2])
    assert g2.value == ("ok", b"cfg")


def test_device_put_if_absent_refusal_fails_fast():
    """Review regression: a device put-if-absent refused by a slot
    provably holding a live value must not burn ``retries`` device
    rounds on a deterministic outcome."""
    _rt, svc = _svc()
    f = svc.kmodify(0, "k", funref.ref("rmw:add", 5), 0)
    _drive(svc, [f])
    f2 = svc.kmodify(0, "k", funref.ref("rmw:put_if_absent", 9), 0,
                     retries=8)
    rounds = _drive(svc, [f2])
    assert f2.value == "failed"
    assert rounds <= 2, rounds
    assert svc.rmw_device_fastpath == 2  # one add + ONE pia attempt


def test_nonzero_default_keeps_host_path():
    """default != 0 cannot use the engine's absent-reads-as-0 rule —
    the host path honors it."""
    _rt, svc = _svc()
    f = svc.kmodify(0, "k", funref.ref("rmw:add", 1), 100)
    _drive(svc, [f])
    assert f.value[0] == "ok"
    assert svc.rmw_device_fastpath == 0
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", 101)


def test_inline_keys_survive_wal_restore(tmp_path):
    """Device-native values are continuously durable: kill the
    service after acked RMWs (no checkpoint) and restore from the
    WAL — values, versions and the inline marking survive."""
    d = str(tmp_path / "svc")
    rt, svc = _svc(data_dir=d, wal_sync="buffer")
    f = svc.kmodify(0, "ctr", funref.ref("rmw:add", 5), 0)
    f2 = svc.kmodify(0, "ctr", funref.ref("rmw:add", 6), 0)
    p = svc.kput(0, "blob", b"bytes")
    _drive(svc, [f, f2, p])
    assert f2.value[0] == "ok"
    svc._wal.close()

    rt2 = Runtime(seed=8)
    svc2 = BatchedEnsembleService.restore(
        rt2, d, tick=None, config=fast_test_config(), data_dir=d,
        wal_sync="buffer")
    g = svc2.kget_vsn(0, "ctr")
    gb = svc2.kget(0, "blob")
    _drive(svc2, [g, gb])
    # the restart's election re-versions on first read (the
    # update_key rewrite — same as any restored key), so only the
    # VALUE is pinned; the version must be a fresh, valid one
    assert g.value[:2] == ("ok", 11)
    assert tuple(g.value[2]) > (0, 0)
    assert gb.value == ("ok", b"bytes")
    # still device-native: the fast path resumes in one flush
    f3 = svc2.kmodify(0, "ctr", funref.ref("rmw:add", 1), 0)
    assert _drive(svc2, [f3]) == 1
    assert f3.value[0] == "ok"
    g2 = svc2.kget(0, "ctr")
    _drive(svc2, [g2])
    assert g2.value == ("ok", 12)


def test_bulk_execute_rmw_rows():
    """OP_RMW through the bulk array surface: fun codes ride the
    exp_epoch plane, the committed computed value comes back in the
    value plane."""
    _rt, svc = _svc()
    e = svc.n_ens
    kind = np.full((2, e), eng.OP_RMW, np.int32)
    slot = np.zeros((2, e), np.int32)
    val = np.asarray([[10] * e, [3] * e], np.int32)
    exp_e = np.asarray([[eng.RMW_ADD] * e, [eng.RMW_SUB] * e],
                       np.int32)
    exp_s = np.zeros((2, e), np.int32)
    committed, _get_ok, _found, value = svc.execute(
        kind, slot, val, exp_epoch=exp_e, exp_seq=exp_s)
    assert committed.all()
    assert (value[0] == 10).all() and (value[1] == 7).all()


def test_rmw_replicates_through_apply_stream(tmp_path):
    """The replica side of the replication group: an OP_RMW lane in a
    shipped apply frame lands as a keyed inline record + a
    device-native mirror on the replica — the kind plane tells it
    which rounds are RMW, and the committed value comes from its OWN
    result planes (bit-equal by determinism).  A later promotion of
    this lane serves the counter."""
    from riak_ensemble_tpu import wire
    from riak_ensemble_tpu.parallel import repgroup
    from riak_ensemble_tpu.parallel.batched_host import _PendingOp
    from riak_ensemble_tpu.runtime import Future
    from riak_ensemble_tpu.types import NOTFOUND

    rt = Runtime(seed=9)
    svc = BatchedEnsembleService(rt, 2, 1, n_slots=8, tick=None,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "r"),
                                 wal_sync="buffer")
    core = repgroup.ReplicaCore(svc)
    assert core.handle_promise(1)[1] is True
    e_n = svc.n_ens
    kind = np.full((1, e_n), eng.OP_RMW, np.int32)
    slot = np.zeros((1, e_n), np.int32)
    val = np.full((1, e_n), 7, np.int32)
    exp_e = np.full((1, e_n), eng.RMW_ADD, np.int32)
    exp_s = np.zeros((1, e_n), np.int32)
    entries = [(e, [_PendingOp(eng.OP_RMW, 0, 7, Future(), "ctr", 1)])
               for e in range(e_n)]
    meta = repgroup._entries_meta(entries, kind, slot, svc.values)
    frame = repgroup.build_apply_frame(
        1, 1, 1, True, np.ones((e_n,), bool), np.zeros((e_n,), bool),
        kind, slot, val, exp_e, exp_s, meta)
    # the frame must survive the restricted wire codec verbatim
    frame = wire.decode(wire.encode(frame))
    resp = core.handle_apply(frame)
    assert resp[0] == "applied", resp
    for e in range(e_n):
        assert svc.key_slot[e]["ctr"] == 0
        assert 0 in svc._inline_slots[e]
        assert svc.slot_handle[e][0] == -1
    # promoted-lane read serves the device-computed value
    g = svc.kget(0, "ctr")
    _drive(svc, [g])
    assert g.value == ("ok", 7)
    # a replicated RMW TOMBSTONE (computed 0) drops the replica's
    # keyed mapping like a delete — retaining it would alias the key
    # onto the slot's next tenant after the leader recycles it
    kind2 = np.full((1, e_n), eng.OP_RMW, np.int32)
    val2 = np.zeros((1, e_n), np.int32)
    exp_e2 = np.full((1, e_n), eng.RMW_SET, np.int32)
    entries2 = [(e, [_PendingOp(eng.OP_RMW, 0, 0, Future(), "ctr", 2)])
                for e in range(e_n)]
    meta2 = repgroup._entries_meta(entries2, kind2, slot, svc.values)
    frame2 = repgroup.build_apply_frame(
        1, 2, 1, True, np.zeros((e_n,), bool), np.zeros((e_n,), bool),
        kind2, slot, val2, exp_e2, exp_s, meta2)
    resp = core.handle_apply(wire.decode(wire.encode(frame2)))
    assert resp[0] == "applied", resp
    for e in range(e_n):
        assert "ctr" not in svc.key_slot[e]
        assert 0 not in svc.slot_handle[e]
    # ...and the WAL replay of the tombstone record agrees: the key
    # stays unmapped and the slot returns to the free pool
    svc._wal.close()
    svc2 = BatchedEnsembleService.restore(
        Runtime(seed=10), str(tmp_path / "r"), tick=None,
        config=fast_test_config(), data_dir=str(tmp_path / "r"),
        wal_sync="buffer")
    assert "ctr" not in svc2.key_slot[0]
    assert len(svc2.free_slots[0]) == svc2.n_slots
    g2 = svc2.kget(0, "ctr")
    _drive(svc2, [g2])
    assert g2.value == ("ok", NOTFOUND)


def test_kmodify_after_unflushed_kput_keeps_host_path():
    """Review regression: eligibility must see QUEUED host-payload
    writes, not just committed ones — a device RMW racing a
    same-flush kput would do int32 arithmetic on the put's payload
    HANDLE (silent corruption) and release the payload."""
    _rt, svc = _svc()
    p = svc.kput(0, "k", b"payload")  # queued, not yet flushed
    f = svc.kmodify(0, "k", funref.ref("rmw:add", 1), 0)
    _drive(svc, [p, f])
    assert p.value[0] == "ok"
    # host fallback it is: rmw:add over a bytes payload fails
    # contained instead of corrupting the handle
    assert f.value == "failed"
    assert svc.rmw_device_fastpath == 0
    g = svc.kget(0, "k")
    _drive(svc, [g])
    assert g.value == ("ok", b"payload")
    # ...and the queue-state bookkeeping drains with the ops
    assert not any(svc._queued_handle_writes[0])


def test_tenant_export_settles_pipeline_first():
    """Review regression: at pipeline_depth > 1 an export taken while
    a committed write is still in flight must settle the launch
    pipeline first — otherwise destroy's own drain would ACK a write
    the export omitted (acked write lost across the handoff)."""
    from riak_ensemble_tpu import service_manager as sm

    runtime = Runtime(seed=12)
    svc = BatchedEnsembleService(runtime, 2, 3, n_slots=8, tick=None,
                                 config=fast_test_config(),
                                 dynamic=True, pipeline_depth=2,
                                 max_ops_per_tick=1)
    ens = svc.create_ensemble("t")
    p1 = svc.kput(ens, "a", b"v1")
    p2 = svc.kput(ens, "b", b"v2")
    svc.flush()  # takes p1; the launch stays in flight at depth 2
    assert not p1.done
    rec = sm.ServiceReconciler(runtime, None, svc, "svc@x",
                               lambda _n: None, poll=None)
    by_key = {e[0]: e for e in rec._export(ens)}
    assert p1.done and p1.value[0] == "ok"
    assert by_key["a"][1] == b"v1"
    _drive(svc, [p2])


def test_destroy_purges_parked_kmodify_retries():
    """Review regression: a backed-off kmodify retry parked past
    destroy_ensemble must fail with the tenant, not fire later
    against the row's NEW tenant (its create-if-missing CAS would
    commit the dead tenant's value there)."""
    from riak_ensemble_tpu.runtime import Future

    runtime = Runtime(seed=13)
    svc = BatchedEnsembleService(runtime, 2, 3, n_slots=8, tick=None,
                                 config=fast_test_config(),
                                 dynamic=True)
    ens = svc.create_ensemble("t")
    fut = Future()
    fired = []
    svc._retry_at.append((svc._flush_calls + 1, ens, fut,
                          lambda: fired.append(1)))
    assert svc.destroy_ensemble("t")
    assert fut.done and fut.value == "failed"
    svc.create_ensemble("u")
    for _ in range(3):
        svc.flush()
    assert not fired


def test_tenant_export_carries_inline_values():
    """The tenant-handoff export reads payloads through slot_handle —
    device-native (inline RMW) slots must export their engine-array
    value, not trip over the -1 sentinel."""
    from riak_ensemble_tpu import service_manager as sm

    runtime = Runtime(seed=11)
    svc = BatchedEnsembleService(runtime, 2, 3, n_slots=8, tick=None,
                                 config=fast_test_config(),
                                 dynamic=True)
    ens = svc.create_ensemble("t1")
    f = svc.kmodify(ens, "ctr", funref.ref("rmw:add", 41), 0)
    p = svc.kput(ens, "blob", b"bytes")
    _drive(svc, [f, p])
    assert f.value[0] == "ok" and p.value[0] == "ok"
    rec = sm.ServiceReconciler(runtime, None, svc, "svc@x",
                               lambda _n: None, poll=None)
    by_key = {e[0]: e for e in rec._export(ens)}
    assert by_key["ctr"][1] == 41
    assert tuple(by_key["ctr"][2]) == tuple(f.value[1])
    assert by_key["blob"][1] == b"bytes"
    # version-preserving reinstall serves the value (handle storage
    # on the new owner; value + CAS-token continuity is the contract)
    ens2 = svc.create_ensemble("t2")
    res = svc.install_objs(ens2, [(k, v[2], v[1])
                                  for k, v in by_key.items()])
    assert all(r[0] == "ok" for r in res)
    g = svc.kget(ens2, "ctr")
    _drive(svc, [g])
    assert g.value == ("ok", 41)


def test_kmodify_device_over_the_wire():
    """svcnode ships the table funref as plain data; the SERVER
    fast-paths it (no code on the wire, one engine round
    server-side), and kmodify_many rides the same dispatch."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    async def scenario():
        server = await svcnode.serve(2, 3, 8, port=0,
                                     config=fast_test_config())
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        r = await c.kmodify(0, "ctr", funref.ref("rmw:add", 5), 0)
        assert r[0] == "ok", r
        r = await c.kmodify(0, "ctr", funref.ref("rmw:add", 6), 0)
        assert r[0] == "ok", r
        assert await c.kget(0, "ctr") == ("ok", 11)
        rm = await c.kmodify_many(0, ["a", "b"],
                                  funref.ref("rmw:set", 3))
        assert [x[0] for x in rm] == ["ok", "ok"], rm
        assert await c.kget_many(0, ["a", "b"]) == [("ok", 3)] * 2
        assert server.svc.rmw_device_fastpath == 4
        await c.close()
        await server.stop()

    asyncio.run(scenario())


def test_funref_device_entry_resolution():
    assert funref.device_entry(funref.ref("rmw:add", 3)) == \
        (funref.RMW_ADD, 3)
    # bools, wrong arity, out-of-range operands, unknown names: no
    # device entry (host path keeps them)
    assert funref.device_entry(("fn", "rmw:add", (True,))) is None
    assert funref.device_entry(("fn", "rmw:add", ())) is None
    assert funref.device_entry(("fn", "rmw:add", (1, 2))) is None
    assert funref.device_entry(("fn", "rmw:add", (1 << 31,))) is None
    assert funref.device_entry(("fn", "no:such", (1,))) is None
    assert funref.device_entry(lambda v, c: c) is None
    # registered host mirrors share the registry (wire-resolvable)
    fn = funref.resolve(funref.ref("rmw:add", 1))
    assert fn((0, 0), 41) == 42
    assert fn((0, 0), 2 ** 31 - 1) == -(2 ** 31)  # int32 wrap
