"""lease_test.erl parity: the lease trusted/untrusted/expired/
epoch-nacked matrix (test/lease_test.erl:8-46).

Reads take the lease fast path only when ``trust_lease`` is set and
the leader's lease is unexpired (check_lease, peer.erl:1493-1516);
otherwise they fall back to a quorum ``check_epoch`` round, which the
``check_epoch_false`` intercept (riak_ensemble_peer_intercepts.erl)
turns into follower nacks.
"""

import pytest

from riak_ensemble_tpu.peer import Peer
from riak_ensemble_tpu.testing import ManagedCluster


def test_lease_matrix(monkeypatch):
    mc = ManagedCluster(seed=24)
    mc.ens_start(3)

    r = mc.kput("test", b"test")
    assert r[0] == "ok", r

    # 1. lease trusted: local fast-path read
    assert mc.kget("test")[0] == "ok"

    # 2. lease not trusted: quorum check_epoch round still succeeds
    mc.config.trust_lease = False
    assert mc.kget("test")[0] == "ok"

    # 3. lease not trusted AND followers nack epoch checks: reads fail
    orig_check = Peer._check_epoch
    monkeypatch.setattr(Peer, "_check_epoch",
                        lambda self, leader, epoch: False)
    assert mc.kget("test") == ("error", "timeout")

    # 4. lease trusted again: fast path dodges the nacking followers.
    #    The failure above forced a step-down; wait for stability, and
    #    read twice — a leader change forces the first read through an
    #    epoch rewrite which ignores the lease (lease_test.erl:29-35).
    mc.config.trust_lease = True
    mc.wait_stable("root")

    def fast_path_read():
        mc.wait_stable("root")
        return mc.kget("test")[0] == "ok"
    assert mc.runtime.run_until(fast_path_read, 60.0, poll=0.2)
    assert mc.kget("test")[0] == "ok"

    # 5. simulated expired lease (duration 0): fast path gone, quorum
    #    round nacked by the still-active intercept → error.  The
    #    reference pins follower_timeout explicitly alongside
    #    (lease_test.erl:37-38) — otherwise the derived 4x-lease
    #    follower timeout collapses to 0 and followers churn.
    mc.config.follower_timeout = 1.0
    mc.config.lease_duration = 0.0
    mc.runtime.run_for(1.0)
    r = mc.kget("test")
    assert r[0] == "error", r

    # 6. remove the intercept: quorum epoch checks work again even
    #    with no lease
    monkeypatch.setattr(Peer, "_check_epoch", orig_check)
    mc.wait_stable("root")

    def quorum_read():
        mc.wait_stable("root")
        return mc.kget("test")[0] == "ok"
    assert mc.runtime.run_until(quorum_read, 60.0, poll=0.2)
    assert mc.kget("test")[0] == "ok"
