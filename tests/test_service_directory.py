"""The scale plane in the cluster directory (one cluster story).

Registration of a batched service flows through the root ensemble's
consensus (create_ensemble, manager.erl:157-166) and gossip
replicates it; any node resolves the service address from its local
directory; reconciliation starts NO actor peers for directory-only
entries; and the resolved address really dials a live svcnode.
"""

import asyncio

import numpy as np  # noqa: F401
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import service_directory as sd  # noqa: E402
from riak_ensemble_tpu import svcnode  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.testing import ManagedCluster  # noqa: E402
from riak_ensemble_tpu.types import PeerId  # noqa: E402


def test_registration_propagates_and_starts_no_peers():
    mc = ManagedCluster(seed=9, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")

    r = sd.register_service(mc.mgr("node0"), mc.runtime, "kvplane",
                            "10.0.0.7", 7601, (10_000, 5, 128))
    assert r == "ok", r

    # every node resolves once the root push/gossip lands
    ok = mc.runtime.run_until(
        lambda: all(sd.resolve_service(mc.mgr(n), "kvplane")
                    is not None
                    for n in ("node0", "node1", "node2")), 60.0)
    assert ok, "service registration never gossiped"
    assert sd.resolve_service(mc.mgr("node0"), "kvplane") == {
        "host": "10.0.0.7", "port": 7601, "shape": (10_000, 5, 128)}
    assert sd.list_services(mc.mgr("node2")) == {
        "kvplane": {"host": "10.0.0.7", "port": 7601,
                    "shape": (10_000, 5, 128)}}

    # directory-only: reconciliation must start no actor peers for it
    mc.runtime.run_for(5.0)
    for n in ("node0", "node1", "node2"):
        assert not any(ens == sd.service_id("kvplane")
                       for ens, _pid in mc.mgr(n).local_peers), \
            "actor peers started for a directory-only ensemble"

    # unknown names resolve None; actor-plane ensembles don't alias
    assert sd.resolve_service(mc.mgr("node0"), "nope") is None
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("actor-ens", peers)
    assert sd.resolve_service(mc.mgr("node0"), "actor-ens") is None


def test_resolved_address_dials_a_live_svcnode():
    """End to end across the planes: register the REAL address of a
    live svcnode in the simulated cluster's directory, resolve it on
    another node, dial it, and run K/V traffic."""

    async def scenario():
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config())
        # cluster (virtual time) registers the real TCP endpoint
        mc = ManagedCluster(seed=10, nodes=("node0", "node1"))
        mc.enable("node0")
        mc.join("node1", "node0")
        assert sd.register_service(mc.mgr("node0"), mc.runtime, "plane",
                                   server.host, server.port,
                                   (4, 3, 8)) == "ok"
        assert mc.runtime.run_until(
            lambda: sd.resolve_service(mc.mgr("node1"), "plane")
            is not None, 60.0)
        addr = sd.resolve_service(mc.mgr("node1"), "plane")

        c = svcnode.ServiceClient(addr["host"], addr["port"])
        await c.connect()
        assert (await c.kput(0, "k", b"v"))[0] == "ok"
        assert await c.kget(0, "k") == ("ok", b"v")
        await c.close()
        await server.stop()

    asyncio.run(scenario())
