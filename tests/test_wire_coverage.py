"""Wire-encodability invariant: EVERY message that crosses a node
boundary in any protocol flow must survive the restricted codec, or
the real TCP transport would silently drop it (the failure mode that
broke cross-node joins when kmodify still carried closures).

The simulator's Network.drop_hook sees every net_send; this harness
encodes+decodes each genuinely cross-node frame and fails the test on
the first refusal, while full protocol stories run: bootstrap/join,
ensemble create, K/V (incl. CAS + delete), leader failover, synctree
corruption + cross-peer exchange, and membership changes."""

import pytest

from riak_ensemble_tpu import wire
from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import NOTFOUND, PeerId


class _WireAudit:
    def __init__(self, runtime):
        self.runtime = runtime
        self.checked = 0
        self.failures = []
        runtime.net.drop_hook = self._hook

    def _hook(self, src_node, dst, msg) -> bool:
        actor = self.runtime.actors.get(dst)
        dst_node = actor.node if actor is not None else None
        if dst_node is not None and dst_node != src_node:
            try:
                out = wire.decode(wire.encode((dst, msg)))
                assert out == (dst, msg)
                self.checked += 1
            except Exception as exc:  # collect, don't mask the flow
                self.failures.append((dst, repr(msg)[:200], repr(exc)))
        return False  # never drop


def test_all_cross_node_protocol_messages_are_wire_safe():
    mc = ManagedCluster(seed=77, nodes=("node0", "node1", "node2"))
    audit = _WireAudit(mc.runtime)

    # bootstrap + join (root kmodify funrefs cross nodes)
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("wa", peers)
    mc.wait_stable("wa")

    # K/V incl. CAS + deletes (client funrefs + replication + facts)
    c = mc.client("node0")
    assert c.kover("wa", "k", b"v1")[0] == "ok"
    r = c.kget("wa", "k")
    assert r[0] == "ok"
    assert c.kupdate("wa", "k", r[1], b"v2")[0] == "ok"
    assert c.kput_once("wa", "fresh", b"once")[0] == "ok"
    assert c.kdelete("wa", "k")[0] == "ok"

    # leader failover (probe/prepare/new_epoch/commit fan-outs)
    leader = mc.leader_id("wa")
    mc.suspend_peer("wa", leader)
    assert mc.runtime.run_until(
        lambda: mc.leader_id("wa") not in (None, leader), 60.0, poll=0.1)
    mc.resume_peer("wa", leader)
    mc.wait_stable("wa")

    # synctree corruption -> cross-peer exchange (tree xcalls)
    lead2 = mc.wait_leader("wa")

    def wrote():
        return c.kover("wa", "cx", b"data")[0] == "ok"
    assert mc.runtime.run_until(wrote, 60.0, poll=0.2)
    mc.tree_of("wa", lead2).tree.corrupt("cx")

    def healed():
        r = c.kget("wa", "cx")
        return r[0] == "ok" and r[1].value == b"data"
    assert mc.runtime.run_until(healed, 60.0, poll=0.1)

    # membership change (update_members / gossip / pending views)
    extra = PeerId(9, "node1")
    r = mc.update_members("wa", [("add", extra)])
    assert r == "ok", r
    mc.wait_members("wa", peers + [extra])
    r = mc.update_members("wa", [("del", extra)])
    assert r == "ok", r
    mc.wait_stable("wa")

    assert not audit.failures, audit.failures[:5]
    # the audit really saw the traffic
    assert audit.checked > 500, audit.checked
