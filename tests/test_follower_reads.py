"""Follower-served leased reads (docs/ARCHITECTURE.md §16).

A replica answers ``kget*`` from its delta-maintained host mirrors
under an epoch-fenced read lease the leader grants/renews on
quorum-confirmed settles.  These tests drive the three properties the
protocol must hold:

- **serve**: a granted replica answers every read verb with the
  leader's committed values (notfound included), and the window
  expires within ``lease()`` of the last confirmed settle;
- **linearizability**: with a single writer bumping a counter key,
  no replica-served read ever returns a value older than the last
  write whose ack completed before the read started — through a
  one-way partition (acks blackholed) and its heal;
- **fencing**: a higher promise revokes the window immediately (the
  leader-handoff fence), regardless of remaining lease time.

The follower-reads-OFF arm ships byte-identical frames to HEAD and
rejects replica reads exactly as before — covered by the existing
repgroup/repl_delta suites, which run with the knob off.
"""

import os
import socket
import struct
import threading
import time

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from riak_ensemble_tpu import faults, wire  # noqa: E402
from riak_ensemble_tpu.config import Config  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    WallRuntime)

N_ENS = 4
N_SLOTS = 8
GROUP = 3
#: long enough that a driven leader renews faster than expiry, short
#: enough that expiry tests stay quick
LEASE = 1.5

_HDR = struct.Struct(">I")


def _cfg() -> Config:
    return Config(ensemble_tick=0.05, lease_duration=LEASE,
                  probe_delay=0.1, storage_delay=0.005,
                  storage_tick=0.5, gossip_tick=0.2)


def _ask(port, *frame, timeout=30.0):
    """One svcnode-protocol round-trip on a fresh socket."""
    sock = socket.create_connection(("127.0.0.1", port),
                                    timeout=timeout)
    try:
        payload = wire.encode(frame)
        sock.sendall(_HDR.pack(len(payload)) + payload)
        hdr = b""
        while len(hdr) < 4:
            b = sock.recv(4 - len(hdr))
            if not b:
                raise ConnectionError("closed")
            hdr += b
        (n,) = _HDR.unpack(hdr)
        buf = b""
        while len(buf) < n:
            b = sock.recv(n - len(buf))
            if not b:
                raise ConnectionError("closed")
            buf += b
        return wire.decode(buf)[1]
    finally:
        sock.close()


def _settle(svc, futs, budget=30.0):
    end = time.time() + budget
    while not all(f.done for f in futs) and time.time() < end:
        svc.flush()
    assert all(f.done for f in futs), "futures never settled"
    return [f.value for f in futs]


def _renew(svc, rounds=3):
    """Grants ride the NEXT frame after the settle that issued them:
    a couple of heartbeats deliver + confirm them everywhere."""
    for _ in range(rounds):
        svc.heartbeat()
        svc._drain_pending(block_all=True)
        time.sleep(0.02)


def _wait_serving(svc, port, ens, key, deadline_s=15.0):
    """Heartbeat until the replica behind ``port`` serves — how many
    rounds a grant takes to land depends on ack arrival order (a
    settle counts whoever acked before its quorum fired)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        r = _ask(port, 0, "kget", ens, key)
        if r != ("error", "not-leader"):
            return r
        _renew(svc, rounds=1)
    raise AssertionError("replica never started serving")


@pytest.fixture(scope="module")
def group(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("flw")
    faults.clear()
    srvs = [repgroup.ReplicaServer(
        N_ENS, GROUP, N_SLOTS, data_dir=str(tmp / f"r{i}"),
        config=_cfg(), follower_reads=True) for i in (1, 2)]
    svc = repgroup.ReplicatedService(
        WallRuntime(), N_ENS, 1, N_SLOTS, group_size=GROUP,
        peers=[("127.0.0.1", s.repl_port) for s in srvs],
        ack_timeout=15.0, config=_cfg(),
        data_dir=str(tmp / "leader"), follower_reads=True)
    repgroup.warmup_kernels(svc)
    assert svc.takeover(), "takeover needs a replica majority"
    yield svc, srvs
    faults.clear()
    for s in srvs:
        s.stop()
    svc.stop()


def test_follower_serves_all_read_verbs_then_lease_expires(group):
    svc, srvs = group
    futs = [svc.kput(1, f"k{i}", f"v{i}".encode()) for i in range(4)]
    assert all(r[0] == "ok" for r in _settle(svc, futs))
    port = srvs[0].client_port
    assert _wait_serving(svc, port, 1, "k1") == ("ok", b"v1")
    assert _ask(port, 1, "kget", 1, "k1") == ("ok", b"v1")
    r = _ask(port, 2, "kget_vsn", 1, "k2")
    assert r[0] == "ok" and r[1] == b"v2" and len(r[2]) == 2
    assert _ask(port, 3, "kget_many", 1, ["k0", "k3"]) == \
        [("ok", b"v0"), ("ok", b"v3")]
    # slab verb through the same lease gate (little-endian int32
    # length table, the wire contract)
    import numpy as np
    keys = ["k0", "k1"]
    lens = np.asarray([len(k) for k in keys], "<i4").tobytes()
    arena = "".join(keys).encode("ascii")
    assert _ask(port, 4, "kget_slab", 1, lens, arena) == \
        [("ok", b"v0"), ("ok", b"v1")]
    # an absent key is an authoritative notfound, not a fallback
    assert _ask(port, 5, "kget", 1, "absent") == \
        ("ok", repgroup.NOTFOUND)
    assert srvs[0].svc.group_stats["follower_reads_served"] >= 5
    # both replicas hold grants once the pipeline settles fully
    assert len(svc._flw_grants) == 2
    # idle past the lease: the window lapses and reads re-route
    time.sleep(LEASE + 0.2)
    assert _ask(port, 6, "kget", 1, "k1") == ("error", "not-leader")
    assert srvs[0].svc.group_stats["follower_reads_blocked"] >= 1
    # a driven leader renews: serving resumes
    assert _wait_serving(svc, port, 1, "k1") == ("ok", b"v1")


def test_follower_reads_linearizable_through_one_way_partition(group):
    """Single-writer counter: no replica-served read may return a
    value older than the last ack the writer observed before the
    read started — including across an ack-blackhole partition of
    the serving replica (its window must lapse before its mirrors
    can go stale relative to new acks) and the heal."""
    svc, srvs = group
    port = srvs[0].client_port
    label = f"127.0.0.1:{srvs[0].repl_port}"
    state = {"floor": 0, "stop": False}
    errors = []

    def reader():
        last = 0
        while not state["stop"]:
            floor = state["floor"]
            r = _ask(port, 99, "kget", 2, "ctr")
            if r == ("error", "not-leader"):
                time.sleep(0.01)
                continue
            if r[0] != "ok" or r[1] is repgroup.NOTFOUND:
                errors.append(f"unexpected reply {r!r}")
                break
            v = int(r[1])
            if v < floor:
                errors.append(
                    f"stale read: got {v}, acked floor was {floor}")
                break
            if v < last:
                errors.append(f"non-monotonic read: {v} after {last}")
                break
            last = v
            time.sleep(0.005)

    _settle(svc, [svc.kput(2, "ctr", b"0")])
    _wait_serving(svc, port, 2, "ctr")
    t = threading.Thread(target=reader, daemon=True)
    t.start()
    val = 0

    def write_some(n):
        nonlocal val
        for _ in range(n):
            val += 1
            r = _settle(svc, [svc.kput(2, "ctr",
                                       str(val).encode())])[0]
            assert r[0] == "ok", r
            state["floor"] = val
            _renew(svc, rounds=1)

    write_some(8)
    # one-way partition: replica 0's ACKS blackhole (it still
    # receives and applies frames, the leader just can't count it —
    # so its grants freeze and its window must lapse)
    plan = faults.install(faults.FaultPlan(silent=True))
    plan.drop(label, faults.LOCAL)
    try:
        write_some(4)
        time.sleep(LEASE + 0.2)
        assert _ask(port, 98, "kget", 2, "ctr") == \
            ("error", "not-leader")
    finally:
        faults.clear()
    # heal: grants resume, serving resumes, floor invariant held
    write_some(4)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _ask(port, 97, "kget", 2, "ctr") != ("error",
                                                "not-leader"):
            break
        _renew(svc, rounds=1)
    assert _ask(port, 96, "kget", 2, "ctr") == ("ok",
                                                str(val).encode())
    state["stop"] = True
    t.join(timeout=10.0)
    assert not errors, errors
    # the barrier accounting surfaced the stalls it took
    assert svc.group_stats["follower_lease_write_blocks"] >= 1


def test_higher_promise_fences_follower_window_immediately(group):
    """The leader-handoff fence: granting a higher promise revokes
    the replica's window BEFORE the grant is answered — a new
    leader's first write can never race a stale leased read.  (Runs
    last: the promise deposes the module leader.)"""
    svc, srvs = group
    port = srvs[0].client_port
    assert _wait_serving(svc, port, 1, "k1") == ("ok", b"v1")
    # the repl port speaks raw (op, args...) frames; _ask's leading
    # "op" slot doubles as the verb and the [1] it returns is the
    # granted flag of ("promised", granted, ...)
    granted = _ask(srvs[0].repl_port, "promise", svc._ge + 7)
    assert granted is True
    assert srvs[0].core.serve_until == 0.0
    assert _ask(port, 2, "kget", 1, "k1") == ("error", "not-leader")
