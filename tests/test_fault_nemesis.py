"""One-directional-partition nemesis sweeps (docs/ARCHITECTURE.md
§13) — the last unported sc.erl fault mode, driven against BOTH
consensus planes:

- the scalar peer plane: ``Workload(oneway_partitions=True)`` on the
  deterministic simulator (elections, probes, quorum rounds all cross
  asymmetric cuts; virtual clock — the fast tier-1 smoke);
- the replication group: a live 3-host group where a leader's quorum
  traffic is blackholed in ONE direction while its client surface
  stays up — proving the no-dual-leader-ack-window property (a
  deposed leader must stop acking before the new leader's first
  commit) with the linearizability KeyModel watching every op.

Fast deterministic variants (fixed seed, bounded rounds) run in
tier-1; the randomized multi-round sweeps carry the ``slow`` marker
(soak lane: ``-m slow``, seeds widened via RETPU_SOAK_SEEDS).
"""

import os
import signal
import socket
import time

import numpy as np
import pytest

import conftest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import faults  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.linearizability import (  # noqa: E402
    KeyModel, Violation, Workload)
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import WallRuntime  # noqa: E402
from riak_ensemble_tpu.testing import ManagedCluster  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND, PeerId  # noqa: E402

N_ENS = 4
N_SLOTS = 8


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    faults.clear()
    yield
    faults.clear()


# -- scalar peer plane: one-way partitions on the simulator ------------------


def _three_node_cluster(seed):
    mc = ManagedCluster(seed=seed, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("sc", peers)
    mc.wait_stable("sc")
    return mc


@pytest.mark.parametrize("seed", [4202])
def test_scalar_oneway_partition_workload_smoke(seed):
    """Tier-1 deterministic smoke: the full random workload with the
    ONE-WAY partition nemesis arm enabled on a 3-node ensemble —
    every acked write observable, no stale/phantom read, and the
    asymmetric cuts really fired (plan counters)."""
    mc = _three_node_cluster(seed)
    w = Workload(mc, "sc", n_workers=3, n_keys=3, ops_per_worker=25,
                 op_timeout=1.5, seed=seed, nemesis_hold=(0.3, 1.5),
                 oneway_partitions=True)
    w.run(partitions=True)
    assert sum(w.op_counts.values()) >= 75
    plan = mc.runtime.net.plan
    assert plan is not None, "one-way nemesis arm never engaged"
    assert plan.dropped_frames > 0, \
        "one-way cuts were installed but no frame ever crossed them"
    # healed at the end: the evidence stays, the rules are gone
    assert not plan.active()


@pytest.mark.slow
@pytest.mark.parametrize("seed", conftest.soak_seeds([4301, 4302,
                                                      4303]))
def test_scalar_oneway_partition_workload_sweep(seed):
    """Soak-lane sweep: longer workloads, member churn AND one-way
    partitions together — the full adversarial schedule."""
    mc = _three_node_cluster(seed)
    w = Workload(mc, "sc", n_workers=3, n_keys=4, ops_per_worker=60,
                 op_timeout=1.0, seed=seed, nemesis_hold=(0.5, 2.5),
                 member_churn=True, oneway_partitions=True)
    w.run(partitions=True)
    assert sum(w.op_counts.values()) >= 180
    # whether the one-way arm fired is schedule-dependent under soak
    # seeds (member churn shares the probability space); when it did,
    # the evidence must be coherent — healed rules, counted drops
    plan = mc.runtime.net.plan
    if plan is not None:
        assert not plan.active()


# -- replication group: in-process live 3-host harness -----------------------


def _inproc_group(tmp_path, ack_timeout=3.0):
    """Leader (in-process service) + two in-process ReplicaServer
    hosts — real sockets, real protocol, one jit cache.  Each
    replica's own future links get a distinct fault label so
    directional rules can target the OLD leader's links alone."""
    servers = []
    for i in (1, 2):
        s = repgroup.ReplicaServer(
            N_ENS, 3, N_SLOTS, data_dir=str(tmp_path / f"r{i}"),
            config=fast_test_config())
        s.svc.fault_label = f"replica{i}"
        servers.append(s)
    svc = repgroup.ReplicatedService(
        WallRuntime(), N_ENS, 1, N_SLOTS, group_size=3,
        peers=[("127.0.0.1", s.repl_port) for s in servers],
        ack_timeout=ack_timeout, config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover(), "takeover needs a replica majority"
    return svc, servers


def _settle(svc, futs, flushes=12):
    for _ in range(flushes):
        if all(f.done for f in futs):
            break
        try:
            svc.flush()
        except repgroup.DeposedError:
            break
    return [f.value if f.done else None for f in futs]


def _control(port, frame, timeout=60.0):
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        repgroup.send_frame(s, frame)
        return repgroup.recv_frame(s)


@pytest.mark.slow
def test_repgroup_oneway_blackhole_fences_deposed_leader(tmp_path):
    """THE acceptance scenario: the leader's quorum traffic is
    blackholed in the RETURN direction (its applies still reach the
    replicas — they may even apply! — but every ack vanishes), while
    its client surface stays up.  From the first blackholed flush on
    it must ack NOTHING; a replica promotes itself and commits; the
    linearizability model checks every key across the handoff — zero
    dual-leader ack window.

    Slow lane (3 live hosts, ~20 s): tier-1 carries the fast
    deterministic variants instead — the scalar one-way Workload
    smoke above and the link-level injection tests in
    test_repgroup_link.py — so the 870 s window stays safe."""
    svc, (r1, r2) = _inproc_group(tmp_path, ack_timeout=2.0)
    models = {}

    def model(key):
        return models.setdefault(key, KeyModel(key))

    try:
        # -- phase 1: healthy acked writes -----------------------------
        futs = []
        for i in range(4):
            m = model(f"pre{i}")
            op = m.invoke_write(b"p%d" % i)
            futs.append((m, op, svc.kput(i % N_ENS, f"pre{i}",
                                         b"p%d" % i)))
        _settle(svc, [f for *_x, f in futs])
        for m, op, f in futs:
            assert f.value[0] == "ok", f.value
            m.ack_write(op)

        # -- phase 2: inbound blackhole (acks dropped, sends deliver) --
        plan = faults.install(faults.FaultPlan())
        for link in svc._links:
            plan.drop(link.label, faults.LOCAL)

        dark = []
        for i in range(4):
            m = model(f"dark{i}")
            op = m.invoke_write(b"d%d" % i)
            dark.append((m, op, svc.kput(i % N_ENS, f"dark{i}",
                                         b"d%d" % i)))
        _settle(svc, [f for *_x, f in dark])
        for m, op, f in dark:
            assert f.done and (not isinstance(f.value, tuple)
                               or f.value[0] != "ok"), \
                f"acked through a blackholed quorum: {f.value!r}"
            # the apply reached the replicas — it may have landed:
            # ambiguous, exactly like an ack timeout
            m.timeout_write(op)
        g = svc.stats()["group"]
        assert g["quorum_failures"] > 0, g
        assert g["link_injected_drops"] > 0, g
        # the operator-facing evidence: health names the nemesis
        h = svc.health()
        assert h["injected"]["active"] is True
        assert any(l["injected_drops"] > 0
                   for l in h["group"]["links"]), h["group"]["links"]

        # -- phase 3: replica 1 promotes itself and commits ------------
        resp = _control(r1.repl_port,
                        ("promote", [("127.0.0.1", r2.repl_port)]))
        assert resp[0] == "ok", resp
        assert resp[1] > svc._ge

        import asyncio

        from riak_ensemble_tpu import svcnode

        async def new_leader_io():
            c = svcnode.ServiceClient("127.0.0.1", r1.client_port)
            await c.connect()
            # the new leader's FIRST commit
            m = model("newldr")
            op = m.invoke_write(b"n0")
            r = await c.kput(0, "newldr", b"n0", timeout=60.0)
            assert r[0] == "ok", r
            m.ack_write(op)
            # read back EVERY key through the new leader, checked
            # against the model: every pre-blackhole ack observable,
            # dark writes plausible-or-absent, nothing phantom
            for key, m in sorted(models.items()):
                r = await c.kget(0 if key == "newldr"
                                 else int(key[-1]) % N_ENS, key,
                                 timeout=60.0)
                assert r[0] == "ok", (key, r)
                m.ack_read(r[1])
            await c.close()

        asyncio.run(new_leader_io())

        # -- phase 4: the deposed leader still cannot ack --------------
        # (its nack responses are blackholed too, so it cannot even
        # OBSERVE the deposition — the classic asymmetry; it must
        # keep failing, never acking)
        m = model("stale")
        op = m.invoke_write(b"s0")
        f = svc.kput(0, "stale", b"s0")
        _settle(svc, [f])
        assert f.done and (not isinstance(f.value, tuple)
                           or f.value[0] != "ok"), \
            f"deposed leader acked after the rival's commit: {f.value!r}"
        m.timeout_write(op)

        # heal: the old leader's next contact observes the fencing
        plan.heal()
        try:
            for _ in range(6):
                svc.heartbeat()
                if svc._deposed:
                    break
                time.sleep(0.1)
        except repgroup.DeposedError:
            pass
        assert svc._deposed, "healed leader never observed the fence"
    finally:
        faults.clear()
        try:
            svc.stop()
        except repgroup.DeposedError:
            pass
        for s in (r1, r2):
            s.stop()


@pytest.mark.slow
@pytest.mark.parametrize("seed", conftest.soak_seeds([5101, 5102]))
def test_repgroup_oneway_nemesis_sweep(tmp_path, seed):
    """Randomized directional-fault sweep on a live 3-host group
    (replica hosts = real OS processes): each round the nemesis
    toggles a one-directional drop (either direction of either
    link), injects 1-3 ms of link RTT, or heals — while random
    put/get load runs through the leader under the KeyModel.  Ends
    healed: every model key reads back plausible through the leader,
    then replica 1 takes over and the same read-back must hold
    through the NEW leader (the nemesis schedule cannot have forked
    history across the handoff)."""
    from test_repgroup import _spawn_replica

    rng = np.random.default_rng(seed)
    plan = faults.install(faults.FaultPlan(seed=int(seed)))
    procs = {}
    for name in ("r1", "r2"):
        procs[name] = _spawn_replica(str(tmp_path / name))
    svc = repgroup.ReplicatedService(
        WallRuntime(), 4, 1, 8, group_size=3,
        peers=[("127.0.0.1", procs["r1"][1]),
               ("127.0.0.1", procs["r2"][1])],
        ack_timeout=3.0, config=fast_test_config(),
        data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    assert svc.takeover()
    labels = [l.label for l in svc._links]
    models = {}
    vals = iter(range(1, 10 ** 6))

    def model(e, k):
        return models.setdefault((e, k), KeyModel(f"{e}/k{k}"))

    try:
        for rnd in range(14):
            r = rng.random()
            lab = labels[int(rng.integers(len(labels)))]
            if r < 0.30:
                if rng.random() < 0.5:
                    plan.drop(faults.LOCAL, lab)   # requests die
                else:
                    plan.drop(lab, faults.LOCAL)   # acks die
            elif r < 0.45:
                plan.set_rtt(faults.LOCAL, lab,
                             float(rng.uniform(1.0, 3.0)))
            elif r < 0.75:
                plan.heal()

            pending = []
            for _ in range(6):
                e = int(rng.integers(4))
                k = int(rng.integers(3))
                m = model(e, k)
                if rng.random() < 0.6:
                    v = next(vals)
                    op = m.invoke_write(v)
                    pending.append(
                        ("put", m, op,
                         svc.kput(e, f"k{k}", v.to_bytes(4, "big"))))
                else:
                    pending.append(("get", m, None,
                                    svc.kget(e, f"k{k}")))
            _settle(svc, [f for *_x, f in pending], flushes=10)
            for kind, m, op, f in pending:
                res = f.value if f.done else None
                ok = isinstance(res, tuple) and res[0] == "ok"
                if kind == "put":
                    if ok:
                        m.ack_write(op)
                    else:
                        m.timeout_write(op)  # may have applied
                elif ok:
                    v = res[1]
                    m.ack_read(v if v is NOTFOUND
                               else int.from_bytes(v, "big"))

        # quiesce: heal, re-sync, read back through the leader
        plan.heal()
        end = time.monotonic() + 90.0
        while time.monotonic() < end:
            svc.heartbeat()
            if svc.stats()["group"]["peers_synced"] >= 2:
                break
            time.sleep(0.1)
        pending = [(m, svc.kget(e, f"k{k}"))
                   for (e, k), m in models.items()]
        _settle(svc, [f for _m, f in pending], flushes=12)
        for m, f in pending:
            assert f.done and isinstance(f.value, tuple) \
                and f.value[0] == "ok", f.value
            v = f.value[1]
            m.ack_read(v if v is NOTFOUND
                       else int.from_bytes(v, "big"))

        # handoff: replica 1 takes over; history must not have forked
        resp = _control(procs["r1"][1],
                        ("promote", [("127.0.0.1", procs["r2"][1])]))
        assert resp[0] == "ok", resp

        import asyncio

        from riak_ensemble_tpu import svcnode

        async def read_through_new_leader():
            c = svcnode.ServiceClient("127.0.0.1", procs["r1"][2])
            await c.connect()
            for (e, k), m in sorted(models.items()):
                r = await c.kget(e, f"k{k}", timeout=60.0)
                assert r[0] == "ok", ((e, k), r)
                v = r[1]
                m.ack_read(v if v is NOTFOUND
                           else int.from_bytes(v, "big"))
            await c.close()

        asyncio.run(read_through_new_leader())
        assert plan.dropped_frames > 0 or plan.delayed_frames > 0
    finally:
        faults.clear()
        try:
            svc.stop()
        except repgroup.DeposedError:
            pass
        for p, _rp, _cp in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
