"""App facade bring-up (riak_ensemble_app/sup analog) and the tracing
subsystem (SURVEY §5: tracing is the reference's gap we fill).
"""

from riak_ensemble_tpu import app
from riak_ensemble_tpu.config import fast_test_config
from riak_ensemble_tpu.runtime import Runtime
from riak_ensemble_tpu.types import PeerId
from riak_ensemble_tpu.utils.trace import Tracer, dump_ensemble, peer_info


def test_app_two_node_bringup():
    runtime = Runtime(seed=40)
    cfg = fast_test_config()
    n0 = app.start(runtime, "node0", cfg)
    n1 = app.start(runtime, "node1", cfg)

    assert n0.enable() == "ok"
    assert not n1.enabled()
    assert n1.join("node0") == "ok"
    assert runtime.run_until(lambda: n1.enabled(), 30.0, poll=0.1)

    peers = [PeerId(0, "node0"), PeerId(1, "node1")]
    assert n0.create_ensemble("kv", peers) == "ok"
    assert runtime.run_until(
        lambda: any(k[0] == "kv" for k in n1.manager.local_peers),
        60.0, poll=0.1)

    c = n0.client()

    def write_ok():
        return c.kover("kv", "k", b"v", timeout=5.0)[0] == "ok"
    assert runtime.run_until(write_ok, 60.0, poll=0.2)
    r = n1.client().kget("kv", "k")
    assert r[0] == "ok" and r[1].value == b"v"


def test_tracer_spans_and_dump():
    runtime = Runtime(seed=41)
    cfg = fast_test_config()
    n0 = app.start(runtime, "node0", cfg)
    tracer = Tracer(runtime).install()
    assert n0.enable() == "ok"

    c = n0.client()
    sid = tracer.begin("kover", "root", "k")

    def write_ok():
        return c.kover("root", "k", b"v", timeout=5.0)[0] == "ok"
    assert runtime.run_until(write_ok, 30.0, poll=0.2)
    span = tracer.finish(sid, "ok")
    assert span.duration is not None and span.duration >= 0
    assert tracer.summary()["finished_spans"]["kover"] == 1
    # runtime deliveries were traced
    assert tracer.counters.get("deliver", 0) > 0
    assert tracer.percentiles("kover")[0.5] >= 0

    infos = dump_ensemble(runtime, "root")
    assert len(infos) == 1
    assert infos[0]["state"] == "leading"
    assert infos[0]["id"] == PeerId("root", "node0")
    assert peer_info(n0.manager.local_peers[("root",
                                             PeerId("root", "node0"))])
