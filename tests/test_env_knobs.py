"""Env-knob documentation tripwire.

Every ``RETPU_*`` environment variable the source tree reads must
appear in README.md's "Tuning knobs" table, and every knob the table
documents must still exist in code — so a new knob can't ship
undocumented and a removed one can't haunt the docs.  (Four knobs
shipped undocumented before this table existed; this test is the
ratchet.)
"""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: source roots scanned for knob reads (tests excluded: a test may
#: reference hypothetical knobs in strings)
SOURCE_ROOTS = ("riak_ensemble_tpu", "bench.py", "tpu_attempt.py",
                "__graft_entry__.py")

KNOB_RE = re.compile(r"RETPU_[A-Z0-9_]+")


def _source_files():
    for root in SOURCE_ROOTS:
        path = os.path.join(REPO, root)
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"]
            for f in filenames:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def _knobs_in_code():
    knobs = set()
    for path in _source_files():
        with open(path, encoding="utf-8") as fh:
            knobs.update(KNOB_RE.findall(fh.read()))
    return knobs


def _knobs_in_readme_table():
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    # table rows look like: | `RETPU_FOO` | default | effect |
    return set(re.findall(r"^\|\s*`(RETPU_[A-Z0-9_]+)`",
                          readme, re.MULTILINE))


def test_every_code_knob_is_documented():
    code = _knobs_in_code()
    documented = _knobs_in_readme_table()
    assert code, "knob scan found nothing — SOURCE_ROOTS broken?"
    missing = code - documented
    assert not missing, (
        f"undocumented RETPU_* knob(s) {sorted(missing)}: add a row "
        "to README.md's 'Tuning knobs (environment)' table")


def test_every_documented_knob_exists_in_code():
    stale = _knobs_in_readme_table() - _knobs_in_code()
    assert not stale, (
        f"README documents removed knob(s) {sorted(stale)}: drop the "
        "row or restore the knob")
