"""Vectorized keyed client path (kput_many/kget_many).

VERDICT r2 #5: the scalar keyed path is bounded by per-op Python
(futures, op objects, per-op resolve).  The batch API keeps keyed
semantics — arbitrary keys, per-key results in order, slot recycling,
WAL durability — while packing/resolving through array slices.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def make(n_ens=4, n_peers=3, n_slots=32, **kw):
    rt = Runtime(seed=61)
    svc = BatchedEnsembleService(rt, n_ens, n_peers, n_slots,
                                 tick=0.005, config=fast_test_config(),
                                 **kw)
    return rt, svc


def settle(rt, fut, timeout=10.0):
    return rt.await_future(fut, timeout)


def test_batch_roundtrip_ordered():
    rt, svc = make()
    keys = [f"k{i}" for i in range(20)]
    vals = [b"v%d" % i for i in range(20)]
    res = settle(rt, svc.kput_many(1, keys, vals))
    assert len(res) == 20
    assert all(r[0] == "ok" for r in res)
    # versions are per-key and monotone within the ensemble
    seqs = [r[1][1] for r in res]
    assert seqs == sorted(seqs)

    got = settle(rt, svc.kget_many(1, keys + ["nope"]))
    assert got[:20] == [("ok", b"v%d" % i) for i in range(20)]
    assert got[20] == ("ok", NOTFOUND)
    svc.stop()


def test_batch_larger_than_max_k_splits_across_flushes():
    rt, svc = make(n_slots=256)
    svc.max_k = 8
    keys = [f"k{i}" for i in range(50)]   # > 6 flushes at K=8
    res = settle(rt, svc.kput_many(0, keys, [b"x%d" % i
                                             for i in range(50)]))
    assert len(res) == 50 and all(r[0] == "ok" for r in res)
    got = settle(rt, svc.kget_many(0, keys))
    assert got == [("ok", b"x%d" % i) for i in range(50)]
    svc.stop()


def test_batch_capacity_fail_and_duplicates():
    rt, svc = make(n_ens=1, n_slots=2)
    # 3 distinct keys into 2 slots: the slotless key fails, the rest
    # ack; a duplicate key serializes (both ok, last write wins)
    res = settle(rt, svc.kput_many(
        0, ["a", "b", "c", "a"], [b"1", b"2", b"3", b"4"]))
    assert res[0][0] == "ok" and res[1][0] == "ok"
    assert res[2] == "failed"            # no slot
    assert res[3][0] == "ok"             # duplicate of a: same slot
    assert settle(rt, svc.kget_many(0, ["a", "b"])) == \
        [("ok", b"4"), ("ok", b"2")]
    svc.stop()


def test_batch_interleaves_with_scalar_ops():
    rt, svc = make()
    f1 = svc.kput(2, "s", b"scalar")
    fb = svc.kput_many(2, ["b1", "b2"], [b"x", b"y"])
    f2 = svc.kget(2, "s")
    assert settle(rt, f1)[0] == "ok"
    assert all(r[0] == "ok" for r in settle(rt, fb))
    assert settle(rt, f2) == ("ok", b"scalar")
    assert settle(rt, svc.kget_many(2, ["b1", "s", "b2"])) == \
        [("ok", b"x"), ("ok", b"scalar"), ("ok", b"y")]
    svc.stop()


def test_batch_acked_writes_survive_crash(tmp_path):
    rt, svc = make(data_dir=str(tmp_path / "d"))
    res = settle(rt, svc.kput_many(
        3, [f"k{i}" for i in range(10)],
        [b"w%d" % i for i in range(10)]))
    assert all(r[0] == "ok" for r in res)
    svc.stop()
    svc._wal.close()

    rt2 = Runtime(seed=62)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "d"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "d"))
    got = settle(rt2, svc2.kget_many(3, [f"k{i}" for i in range(10)]))
    assert got == [("ok", b"w%d" % i) for i in range(10)]
    svc2.stop()


def test_batch_delete_recycle_interop():
    """Slots freed by scalar deletes are reusable by later batches."""
    rt, svc = make(n_ens=1, n_slots=2)
    assert all(r[0] == "ok" for r in settle(
        rt, svc.kput_many(0, ["a", "b"], [b"1", b"2"])))
    assert settle(rt, svc.kdelete(0, "a"))[0] == "ok"
    res = settle(rt, svc.kput_many(0, ["c"], [b"3"]))
    assert res[0][0] == "ok"
    assert settle(rt, svc.kget_many(0, ["a", "b", "c"])) == \
        [("ok", NOTFOUND), ("ok", b"2"), ("ok", b"3")]
    svc.stop()


def test_missing_keys_consume_no_device_rounds():
    """Review finding: slotless/unknown keys must resolve immediately
    (no placeholder rounds, no flush dependency) — the docstring
    contract."""
    rt, svc = make(n_ens=1, n_slots=1)
    # all-unknown get resolves synchronously, queues stay empty
    fut = svc.kget_many(0, ["a", "b", "c"])
    assert fut.done
    assert fut.value == [("ok", NOTFOUND)] * 3
    assert svc._queue_rounds[0] == 0 and not svc.queues[0]

    # mixed: only the allocatable key queues a round
    fut = svc.kput_many(0, ["x", "y"], [b"1", b"2"])
    assert not fut.done
    assert svc._queue_rounds[0] == 1     # y had no slot: pre-failed
    res = settle(rt, fut)
    assert res[0][0] == "ok" and res[1] == "failed"
    svc.stop()


def test_kget_many_want_vsn():
    """Batch reads honor the kget_vsn contract."""
    rt, svc = make(n_ens=1)
    put = settle(rt, svc.kput_many(0, ["a", "b"], [b"1", b"2"]))
    got = settle(rt, svc.kget_many(0, ["a", "b", "nope"],
                                   want_vsn=True))
    assert got[0] == ("ok", b"1", tuple(put[0][1]))
    assert got[1] == ("ok", b"2", tuple(put[1][1]))
    assert got[2] == ("ok", NOTFOUND, (0, 0))
    svc.stop()


def test_stats_queued_ops_counts_batch_rounds():
    """Review finding: stats() must count ROUNDS, not queue entries —
    a 30-key batch is 30 queued ops, not 1."""
    rt, svc = make(n_ens=1, n_slots=64)
    svc.kput_many(0, [f"k{i}" for i in range(30)],
                  [b"v"] * 30)
    assert svc.stats()["queued_ops"] == 30
    while any(svc.queues):
        svc.flush()
    assert svc.stats()["queued_ops"] == 0
    svc.stop()


def test_kput_many_length_mismatch_rejected():
    """Network-exposed trust boundary: mismatched keys/values raise
    (never a silently-truncated batch whose future can't resolve)."""
    rt, svc = make(n_ens=1)
    with pytest.raises(ValueError):
        svc.kput_many(0, ["a", "b"], [b"1"])
    svc.stop()


def test_watcher_unwatches_itself_mid_callback():
    """A one-shot watcher deregistering inside its callback must not
    skip sibling watchers (snapshot iteration)."""
    rt, svc = make(n_ens=1)
    events = []

    def one_shot(e, old, new):
        if old == new:
            return  # skip the registration-time status notify
        svc.unwatch_leader(0, one_shot)
        events.append(("one", old, new))

    svc.watch_leader(0, one_shot)
    svc.watch_leader(0, lambda e, old, new: events.append(("two", old,
                                                           new)))
    n = len(events)
    assert settle(rt, svc.kput(0, "k", b"v"))[0] == "ok"
    fired = events[n:]
    assert ("one", -1, int(svc.leader_np[0])) in fired
    assert ("two", -1, int(svc.leader_np[0])) in fired
    # one_shot is gone; two remains
    assert svc._leader_watchers[0] != []
    assert one_shot not in svc._leader_watchers[0]
    svc.stop()


def test_kupdate_many_cas_semantics():
    """Batch CAS: per-key version compare, (0,0) = create-if-missing,
    stale versions fail cleanly, chains survive crash."""
    rt, svc = make(n_ens=1)
    put = settle(rt, svc.kput_many(0, ["a", "b"], [b"1", b"2"]))
    vsn_a, vsn_b = tuple(put[0][1]), tuple(put[1][1])

    res = settle(rt, svc.kupdate_many(
        0, ["a", "b", "c"],
        [vsn_a, (9, 9), (0, 0)],         # ok / stale / create
        [b"a2", b"b2", b"c1"]))
    assert res[0][0] == "ok"
    assert res[1] == "failed"            # stale vsn: definitive reject
    assert res[2][0] == "ok"             # create-if-missing
    assert settle(rt, svc.kget_many(0, ["a", "b", "c"])) == \
        [("ok", b"a2"), ("ok", b"2"), ("ok", b"c1")]
    # the stale CAS's payload must not leak
    assert len(svc.values) == 3
    svc.stop()


def test_kdelete_many_and_recycle():
    rt, svc = make(n_ens=1, n_slots=3)
    assert all(r[0] == "ok" for r in settle(
        rt, svc.kput_many(0, ["a", "b", "c"], [b"1", b"2", b"3"])))
    res = settle(rt, svc.kdelete_many(0, ["a", "c", "nope"]))
    assert res[0][0] == "ok" and res[1][0] == "ok"
    assert res[2] == ("ok", NOTFOUND)
    assert settle(rt, svc.kget_many(0, ["a", "b", "c"])) == \
        [("ok", NOTFOUND), ("ok", b"2"), ("ok", NOTFOUND)]
    # slots recycled: two fresh keys fit in the 3-slot ensemble
    res = settle(rt, svc.kput_many(0, ["x", "y"], [b"8", b"9"]))
    assert all(r[0] == "ok" for r in res)
    assert len(svc.values) == 3  # b, x, y — deleted payloads released
    svc.stop()


def test_batch_cas_and_delete_survive_crash(tmp_path):
    rt, svc = make(n_ens=1, data_dir=str(tmp_path / "d"))
    put = settle(rt, svc.kput_many(0, ["a", "b"], [b"1", b"2"]))
    assert all(r[0] == "ok" for r in put)
    up = settle(rt, svc.kupdate_many(0, ["a"], [tuple(put[0][1])],
                                     [b"a2"]))
    assert up[0][0] == "ok"
    dl = settle(rt, svc.kdelete_many(0, ["b"]))
    assert dl[0][0] == "ok"
    svc.stop()
    svc._wal.close()

    rt2 = Runtime(seed=63)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "d"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "d"))
    assert settle(rt2, svc2.kget_many(0, ["a", "b"])) == \
        [("ok", b"a2"), ("ok", NOTFOUND)]
    svc2.stop()


def test_batch_ops_on_dead_ensemble_fail():
    """All four batch ops reject a destroyed ensemble with 'failed' —
    never a fake ('ok', NOTFOUND) for an unserved delete."""
    rt = Runtime(seed=64)
    svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 dynamic=True)
    e = svc.create_ensemble("x")
    assert svc.destroy_ensemble("x")
    assert svc.kput_many(e, ["k"], [b"v"]).value == ["failed"]
    assert svc.kget_many(e, ["k"]).value == ["failed"]
    assert svc.kupdate_many(e, ["k"], [(0, 0)], [b"v"]).value == \
        ["failed"]
    assert svc.kdelete_many(e, ["k"]).value == ["failed"]
    svc.stop()
