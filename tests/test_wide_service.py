"""End-to-end wide scheduling (RETPU_WIDE): the batched service over
full_step_wide must be client-indistinguishable from the scalar scan
for conflict-free flushes — same commits, same reads, same versions —
and must realize a valid serialization (per-key order preserved,
per-key vsn monotone) for duplicate chains.  ``wide_launches`` pins
that the wide path actually ran (a vacuous scalar-vs-scalar A/B
passes for the wrong reason)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, warmup_kernels)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def _mk(wide: bool, **kw):
    rt = Runtime(seed=5)
    svc = BatchedEnsembleService(rt, n_ens=6, n_peers=3, n_slots=16,
                                 tick=None, max_ops_per_tick=8, **kw)
    svc._wide = wide  # the env flag, set directly for the A/B
    return rt, svc


def _drain(rt, svc, futs, rounds=10):
    for _ in range(rounds):
        svc.flush()
        rt.run_for(0.005)
        if all(f.done for f in futs):
            return
    assert all(f.done for f in futs), "futures never resolved"


def _workload(rt, svc, seed):
    """Mixed keyed workload with DISTINCT keys per flush (conflict-free
    — the wide path's bread and butter); put and get flushes drained
    separately so no flush chains a put with its own get."""
    rng = np.random.default_rng(seed)
    out = []
    for step in range(5):
        puts = []
        for e in range(svc.n_ens):
            keys = [f"k{i}" for i in rng.choice(6, 3, replace=False)]
            puts.append(svc.kput_many(
                e, keys, [int(rng.integers(1, 99)) for _ in keys]))
        _drain(rt, svc, puts)
        gets = []
        for e in range(svc.n_ens):
            keys = [f"k{i}" for i in rng.choice(6, 3, replace=False)]
            gets.append(svc.kget_many(e, keys, want_vsn=True))
            if rng.random() < 0.3:
                gets.append(svc.kdelete(e, keys[0]))
        _drain(rt, svc, gets)
        out.extend(f.value for f in puts)
        out.extend(f.value for f in gets)
    return out


@pytest.mark.parametrize("seed", [0, 1])
def test_wide_service_matches_scalar(seed):
    rt_a, svc_a = _mk(wide=False)
    rt_b, svc_b = _mk(wide=True)
    hist_a = _workload(rt_a, svc_a, seed)
    hist_b = _workload(rt_b, svc_b, seed)
    assert hist_a == hist_b
    assert svc_a.wide_launches == 0
    # The A/B is only meaningful if the wide service actually took the
    # wide path (put+get-same-key flushes would chain past the G<=2
    # gate and silently compare scalar against scalar).
    assert svc_b.wide_launches > 0


def test_wide_duplicate_chain_is_a_valid_serialization():
    """kput_many with a duplicate key executes as the (group, lane)
    order: per-key vsns stay monotone, the LAST same-key put wins, and
    every op acks — the seq interleaving across different keys may
    differ from the scalar scan (the reference's key-hashed workers
    have the same freedom), which is why this asserts semantics, not
    cross-mode equality."""
    rt, svc = _mk(wide=True)
    f = svc.kput_many(0, ["a", "a", "b"], [1, 2, 3])
    _drain(rt, svc, [f])
    rs = f.value
    assert all(r[0] == "ok" for r in rs), rs
    vsn_a1, vsn_a2 = tuple(rs[0][1]), tuple(rs[1][1])
    assert vsn_a2 > vsn_a1  # per-key monotone
    g = svc.kget_many(0, ["a", "b"], want_vsn=True)
    _drain(rt, svc, [g])
    (st_a, val_a, got_a), (st_b, val_b, got_b) = g.value
    assert (st_a, val_a) == ("ok", 2)       # last duplicate won
    assert tuple(got_a) == vsn_a2
    assert (st_b, val_b) == ("ok", 3)


def test_wide_execute_bulk_matches_scalar():
    results = []
    for wide in (False, True):
        rt, svc = _mk(wide)
        rt.run_for(1.0)
        svc.flush()  # elections
        k, e = 8, svc.n_ens
        rng2 = np.random.default_rng(3)
        kind = rng2.choice([eng.OP_PUT, eng.OP_GET, eng.OP_NOOP],
                           (k, e), p=[0.5, 0.4, 0.1]).astype(np.int32)
        # distinct slots per column: every plane schedules G=1 (the
        # cross-slot seq order then matches the scalar scan exactly)
        slot = np.stack([rng2.permutation(svc.n_slots)[:k]
                         for _ in range(e)], axis=1).astype(np.int32)
        val = rng2.integers(1, 1 << 20, (k, e), dtype=np.int32)
        out = svc.execute(kind, slot, val)
        results.append(tuple(np.asarray(x).tolist() for x in out))
        if wide:
            assert svc.wide_launches > 0
    assert results[0] == results[1]


def test_wide_gate_falls_back_on_deep_duplicates():
    """> 2 occurrence groups must take the scalar path (only G<=2 wide
    programs are warmed)."""
    rt, svc = _mk(True)
    k, e = 6, svc.n_ens
    kind = np.full((k, e), eng.OP_PUT, np.int32)
    slot = np.zeros((k, e), np.int32)  # 6-deep duplicate chain
    val = np.ones((k, e), np.int32)
    assert svc._wide_plan(kind, slot, val, k, None, None) is None
    # while a duplicate-free flush schedules G=1
    slot2 = np.tile(np.arange(k, dtype=np.int32)[:, None], (1, e))
    plan = svc._wide_plan(kind, slot2, val, k, None, None)
    assert plan is not None and plan.kind.shape[0] == 1
    assert plan.lease_ok is None  # service lease rides [E]-broadcast


def test_wide_warmup_covers_gated_shapes():
    rt, svc = _mk(True)
    warmup_kernels(svc)  # must not raise; compiles wide programs too


def test_wide_dynamic_lifecycle():
    rt, svc = _mk(True, dynamic=True)
    h = svc.create_ensemble("orders")
    rt.run_for(0.5)
    svc.flush()
    f = svc.kput(svc.ensemble_row("orders"), "a", b"1") \
        if hasattr(svc, "ensemble_row") else svc.kput(h, "a", b"1")
    _drain(rt, svc, [f])
    assert f.value[0] == "ok", f.value
