"""End-to-end wide scheduling (RETPU_WIDE): the batched service over
full_step_wide must be client-indistinguishable from the scalar scan —
same commits, same reads, same versions — across keyed batches, CAS,
deletes, duplicates (which force multi-group plans) and the dynamic
lifecycle."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, warmup_kernels)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402


def _mk(monkeypatch, wide: bool, **kw):
    rt = Runtime(seed=5)
    svc = BatchedEnsembleService(rt, n_ens=6, n_peers=3, n_slots=16,
                                 tick=None, max_ops_per_tick=8, **kw)
    svc._wide = wide  # the env flag, set directly for the A/B
    return rt, svc


def _drive(rt, svc, pending):
    while pending:
        svc.flush()
        done = [p for p in pending if p[1].done]
        pending = [p for p in pending if not p[1].done]
        rt.run_for(0.01)
    return pending


def _workload(rt, svc, seed):
    """A mixed keyed workload; returns the resolved future values in
    issue order (the client-visible history)."""
    rng = np.random.default_rng(seed)
    out = []
    futs = []
    for step in range(6):
        for e in range(svc.n_ens):
            keys = [f"k{rng.integers(0, 6)}" for _ in range(3)]
            futs.append(svc.kput_many(e, keys,
                                      [int(rng.integers(1, 99))
                                       for _ in keys]))
            futs.append(svc.kget_many(e, keys))
            if rng.random() < 0.5:
                futs.append(svc.kget(e, "k0"))
            if rng.random() < 0.3:
                futs.append(svc.kdelete(e, keys[0]))
        for _ in range(6):
            svc.flush()
            rt.run_for(0.005)
    for f in futs:
        assert f.done, "workload future never resolved"
        out.append(f.value)
    return out


@pytest.mark.parametrize("seed", [0, 1])
def test_wide_service_matches_scalar(monkeypatch, seed):
    rt_a, svc_a = _mk(monkeypatch, wide=False)
    rt_b, svc_b = _mk(monkeypatch, wide=True)
    hist_a = _workload(rt_a, svc_a, seed)
    hist_b = _workload(rt_b, svc_b, seed)
    assert hist_a == hist_b


def test_wide_execute_bulk_matches_scalar():
    rng = np.random.default_rng(3)
    results = []
    for wide in (False, True):
        rt, svc = _mk(None, wide)
        rt.run_for(1.0)
        svc.flush()  # elections
        k, e = 8, svc.n_ens
        rng2 = np.random.default_rng(3)
        kind = rng2.choice([eng.OP_PUT, eng.OP_GET, eng.OP_NOOP],
                           (k, e), p=[0.5, 0.4, 0.1]).astype(np.int32)
        slot = rng2.integers(0, svc.n_slots, (k, e), dtype=np.int32)
        slot[3] = slot[2]  # forced duplicate row -> G >= 2 plan
        val = rng2.integers(1, 1 << 20, (k, e), dtype=np.int32)
        out = svc.execute(kind, slot, val)
        results.append(tuple(np.asarray(x).tolist() for x in out))
    assert results[0] == results[1]


def test_wide_gate_falls_back_on_deep_duplicates():
    """> 2 occurrence groups must take the scalar path (only G<=2 wide
    programs are warmed)."""
    rt, svc = _mk(None, True)
    k, e = 6, svc.n_ens
    kind = np.full((k, e), eng.OP_PUT, np.int32)
    slot = np.zeros((k, e), np.int32)  # 6-deep duplicate chain
    val = np.ones((k, e), np.int32)
    assert svc._wide_plan(kind, slot, val, k, None, None) is None
    # while a duplicate-free flush schedules G=1
    slot2 = np.tile(np.arange(k, dtype=np.int32)[:, None], (1, e))
    plan = svc._wide_plan(kind, slot2, val, k, None, None)
    assert plan is not None and plan.kind.shape[0] == 1


def test_wide_warmup_covers_gated_shapes():
    rt, svc = _mk(None, True)
    warmup_kernels(svc)  # must not raise; compiles wide programs too


def test_wide_dynamic_lifecycle():
    rt, svc = _mk(None, True, dynamic=True)
    h = svc.create_ensemble("orders")
    rt.run_for(0.5)
    svc.flush()
    f = svc.kput(svc.ensemble_row("orders"), "a", b"1") \
        if hasattr(svc, "ensemble_row") else svc.kput(h, "a", b"1")
    for _ in range(8):
        svc.flush()
        rt.run_for(0.01)
        if f.done:
            break
    assert f.done and f.value[0] == "ok", f.value
