"""Single-shard ↔ mesh serving equivalence (the shard-wise pack path).

The mesh engine (8 virtual CPU devices, 'ens'-sharded, peer axis
unsharded) must be BIT-IDENTICAL to the single-shard oracle over mixed
put/CAS/RMW/tombstone streams — results, device state, host mirror
slabs, and WAL bytes — including compacted (per-shard active-column
bucketing) and wide-group flushes.  Plus the mesh serving-path
contracts: warmup covers the mesh step/pack variants (CompileWatch
asserts zero serve-phase compiles), and checkpoints round-trip across
shard counts (8→1 and 1→8) bit-equal.

Marked ``mesh`` so the suite can run as its own session
(``pytest -m mesh``); the forced 8-device CPU mesh comes from
conftest.py's XLA_FLAGS bootstrap (process-wide by design — the flag
must precede the jax import).
"""

import os
import shutil
import tempfile

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import funref  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime, mesh_ens_shards,
)
from riak_ensemble_tpu.parallel.mesh import mesh_engine  # noqa: E402

pytestmark = pytest.mark.mesh

if jax.device_count() < 8:  # pragma: no cover - driver contract
    pytest.skip("needs the 8-device virtual CPU mesh",
                allow_module_level=True)


def _mk(n_ens, n_slots=8, n_peers=3, mesh=False, **kw):
    engine = mesh_engine(8) if mesh else None
    return BatchedEnsembleService(WallRuntime(), n_ens, n_peers,
                                  n_slots, tick=None, engine=engine,
                                  **kw)


def _drive(svc, futs):
    while not all(f.done for f in futs):
        svc.flush()
    return [f.value for f in futs]


def _mixed_stream(svc, phase, rows):
    """One phase of the mixed workload on the given ensemble rows:
    puts, CAS (hit + miss), RMW, deletes (tombstones), gets."""
    futs = []
    for e in rows:
        futs.append(svc.kput(e, "a", b"A%d" % (phase + e)))
        futs.append(svc.kput(e, "b", b"B"))
        futs.append(svc.kput_once(e, "once", b"first"))
    _drive(svc, futs)
    vsns = _drive(svc, [svc.kget_vsn(e, "b") for e in rows])
    futs = [svc.kupdate(e, "b", vsn[2], b"B%d" % phase)
            for e, vsn in zip(rows, vsns)]          # CAS hit
    futs += [svc.kupdate(e, "b", (1, 1 << 30), b"never")
             for e in rows]                          # CAS miss
    futs += [svc.kmodify(e, "ctr", funref.RMW_ADD, 3 + phase)
             for e in rows]
    _drive(svc, futs)
    futs = [svc.kdelete(e, "a") for e in rows]
    futs += [svc.kget(e, "b") for e in rows]
    futs += [svc.kget(e, "a") for e in rows]
    return _drive(svc, futs)


def _assert_device_state_equal(a, b):
    for name, xa, xb in zip(a.state._fields, a.state, b.state):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb),
                                      err_msg=f"state.{name}")
    np.testing.assert_array_equal(a.leader_np, b.leader_np)


def _assert_state_equal(a, b):
    """Device state plus the host read-path mirrors — for arms that
    served identical op streams from birth (the mirrors are lazy
    caches, so this is only meaningful for lockstep services)."""
    _assert_device_state_equal(a, b)
    np.testing.assert_array_equal(a._slot_vsn_np, b._slot_vsn_np)
    np.testing.assert_array_equal(a._inline_value_np,
                                  b._inline_value_np)
    np.testing.assert_array_equal(a._inline_value_ok,
                                  b._inline_value_ok)


def _wal_bytes(data_dir):
    out = {}
    for root, _dirs, files in os.walk(data_dir):
        for f in files:
            if f.startswith("wal"):
                with open(os.path.join(root, f), "rb") as fh:
                    out[f] = fh.read()
    return out


def test_shardwise_pack_selected():
    svc = _mk(16, mesh=True)
    try:
        assert mesh_ens_shards(svc.engine) == 8
        assert svc._mesh_shards == 8
        assert getattr(svc._pack, "fn", svc._pack)
    finally:
        svc.stop()


def test_mesh_equals_oracle_mixed_stream():
    """Bit-identical results + state + mirrors + WAL bytes over a
    mixed put/CAS/RMW/tombstone stream (uncompacted full-width
    flushes: every row active)."""
    da = tempfile.mkdtemp(prefix="mesh_eq_a_")
    db = tempfile.mkdtemp(prefix="mesh_eq_b_")
    oracle = _mk(16, mesh=False, data_dir=da)
    meshed = _mk(16, mesh=True, data_dir=db)
    try:
        rows = range(16)
        for phase in range(2):
            ra = _mixed_stream(oracle, phase, rows)
            rb = _mixed_stream(meshed, phase, rows)
            assert ra == rb, f"phase {phase} results diverge"
        _assert_state_equal(oracle, meshed)
        wa, wb = _wal_bytes(da), _wal_bytes(db)
        assert wa and wa == wb, "WAL bytes diverge"
    finally:
        oracle.stop()
        meshed.stop()
        shutil.rmtree(da, ignore_errors=True)
        shutil.rmtree(db, ignore_errors=True)


def test_mesh_equals_oracle_compacted_flush():
    """Per-shard active-column compaction (E=128, a few hot rows →
    A_loc strictly below E/8) must stay bit-identical to the oracle,
    and must actually compact (payload below full width)."""
    oracle = _mk(128, mesh=False)
    meshed = _mk(128, mesh=True)
    try:
        rows = [0, 3, 17, 63, 64, 127]  # spans shards incl. empties
        ra = _mixed_stream(oracle, 0, rows)
        rb = _mixed_stream(meshed, 0, rows)
        assert ra == rb
        _assert_state_equal(oracle, meshed)
        assert meshed.payload_bytes < meshed.payload_bytes_full_width
        # the shard-wise path really took the per-shard branch
        assert meshed._occ_launches > 0
        assert meshed._occ_sum < meshed._occ_launches
    finally:
        oracle.stop()
        meshed.stop()


def test_mesh_equals_oracle_wide_flush():
    """Wide-group flushes (distinct-slot ops coalesced into [G, E, W]
    planes) through the mesh step must match the oracle."""
    oracle = _mk(16, mesh=False, max_ops_per_tick=8)
    meshed = _mk(16, mesh=True, max_ops_per_tick=8)
    try:
        for svc in (oracle, meshed):
            svc._wide = True
        results = []
        for svc in (oracle, meshed):
            futs = [svc.kput_many(e, ["w%d" % j for j in range(4)],
                                  [b"v%d" % j for j in range(4)])
                    for e in range(16)]
            _drive(svc, futs)
            futs = [svc.kget_many(e, ["w%d" % j for j in range(4)])
                    for e in range(16)]
            results.append(_drive(svc, futs))
            assert svc.wide_launches > 0, "wide path never engaged"
        assert results[0] == results[1]
        _assert_state_equal(oracle, meshed)
    finally:
        oracle.stop()
        meshed.stop()


def test_mesh_warmup_zero_serve_compiles():
    """Satellite 1: warmup compiles the mesh step AND the shard-wise
    pack variants (per-shard (K, A) buckets included) so serving a
    mixed stream afterwards records ZERO serve-phase compile events
    (CompileWatch-asserted)."""
    svc = _mk(128, mesh=True)
    try:
        svc.warmup()
        assert svc._c_compile.labels("warmup").value > 0
        serve0 = svc._c_compile.labels("serve").value
        _mixed_stream(svc, 0, [0, 3, 17, 63, 127])  # compacted
        _mixed_stream(svc, 1, range(128))           # full width
        served = svc._c_compile.labels("serve").value - serve0
        events = [e for e in svc._compile_log
                  if e["phase"] == "serve"]
        assert served == 0, f"serve-phase compiles leaked: {events}"
    finally:
        svc.stop()


@pytest.mark.parametrize("direction", ["8to1", "1to8"])
def test_checkpoint_across_shard_counts(direction):
    """Satellite 2: a checkpoint taken under one device placement
    restores bit-equal under the other (mesh 8-shard ↔ single-shard),
    including the host mirrors and a post-restore serving round."""
    src_mesh = direction == "8to1"
    d = tempfile.mkdtemp(prefix="mesh_ckpt_")
    src = _mk(16, mesh=src_mesh, data_dir=d)
    dst = None
    try:
        _mixed_stream(src, 0, range(16))
        src.save()
        dst = BatchedEnsembleService.restore(
            WallRuntime(), d, tick=None,
            engine=mesh_engine(8) if not src_mesh else None)
        _assert_device_state_equal(src, dst)
        # The restored placement actually serves: reads return the
        # checkpointed data and writes commit.  (No cross-arm version
        # equality here — restore is lease-less by design, so the
        # restored side re-elects into a higher epoch than the
        # still-running source.)
        got = _drive(dst, [dst.kget(e, "b") for e in range(16)])
        assert got == [("ok", b"B0")] * 16
        put = _drive(dst, [dst.kput(e, "p1", b"post") for e in
                           range(16)])
        assert all(r[0] == "ok" for r in put)
    finally:
        src.stop()
        if dst is not None:
            dst.stop()
        shutil.rmtree(d, ignore_errors=True)
