"""Continuous durability of acked service writes (WAL).

The reference never acks a write that isn't on disk
(``riak_ensemble_basic_backend.erl:120-125`` synchronous save_data;
facts coalesce within 50 ms, ``riak_ensemble_storage.erl:86-103``).
These tests pin the same contract on the scale path: every write whose
future resolved 'ok' survives a crash — including a kill -9 with no
checkpoint ever taken — and replays into a serveable service.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

jax = pytest.importorskip("jax")
pytest.importorskip("orbax.checkpoint")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.parallel.wal import (  # noqa: E402
    PyLogStore, ServiceWAL,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_durable(tmp_path, n_ens=4, n_peers=3, n_slots=4, **kw):
    runtime = Runtime(seed=11)
    svc = BatchedEnsembleService(
        runtime, n_ens, n_peers, n_slots, tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"), **kw)
    return runtime, svc


def settle(runtime, fut, timeout=5.0):
    return runtime.await_future(fut, timeout)


def crash(svc):
    """Simulate a crash: release the WAL handle (so restore re-reads
    the on-disk bytes, not a shared in-memory map) WITHOUT any
    checkpoint/flush cleanup."""
    svc.stop()
    if svc._wal is not None:
        svc._wal.close()


# -- PyLogStore unit ---------------------------------------------------------


def test_pylogstore_roundtrip_and_latest_wins(tmp_path):
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store(("kv", 0, 1), ("a", 7))
    st.store(("kv", 0, 1), ("a", 8))   # latest record per key wins
    st.store(("kv", 1, 0), ("b", 9))
    st.delete(("kv", 1, 0))
    st.sync()
    st.close()

    st2 = PyLogStore(p)
    assert st2.count() == 1
    assert st2.fetch(("kv", 0, 1)) == ("a", 8)
    assert st2.fetch(("kv", 1, 0)) is None
    st2.close()


def test_pylogstore_torn_tail_dropped(tmp_path):
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store("k1", "v1")
    st.store("k2", "v2")
    st.sync()
    st.close()
    size = os.path.getsize(p)
    with open(p, "r+b") as f:       # tear the last record mid-frame
        f.truncate(size - 3)
        f.seek(0, 2)
        f.write(b"\x00garbage")     # and splat junk after the tear

    st2 = PyLogStore(p)
    assert st2.fetch("k1") == "v1"  # intact prefix survives
    assert st2.fetch("k2") is None  # torn record dropped, not mangled
    st2.close()


# -- service crash / restore -------------------------------------------------


def test_acked_writes_survive_crash_without_any_checkpoint(tmp_path):
    """kill before the FIRST save(): restore comes from META + WAL."""
    runtime, svc = make_durable(tmp_path)
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"v%d" % e))[0] == "ok"
    assert settle(runtime, svc.kput(0, "other", b"x"))[0] == "ok"
    assert settle(runtime, svc.kdelete(3, "k"))[0] == "ok"
    crash(svc)

    rt2 = Runtime(seed=12)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    for e in range(3):
        assert settle(rt2, svc2.kget(e, "k")) == ("ok", b"v%d" % e)
    assert settle(rt2, svc2.kget(0, "other")) == ("ok", b"x")
    assert settle(rt2, svc2.kget(3, "k")) == ("ok", NOTFOUND)
    # restored service keeps serving (and logging) writes
    assert settle(rt2, svc2.kput(1, "k", b"post"))[0] == "ok"
    assert settle(rt2, svc2.kget(1, "k")) == ("ok", b"post")


def test_acked_writes_survive_crash_after_checkpoint(tmp_path):
    """Checkpoint + later WAL records compose: post-checkpoint acks
    replay over the checkpoint image."""
    runtime, svc = make_durable(tmp_path)
    assert settle(runtime, svc.kput(0, "a", b"1"))[0] == "ok"
    assert settle(runtime, svc.kput(1, "z", b"z1"))[0] == "ok"
    svc.save()
    assert settle(runtime, svc.kput(0, "b", b"2"))[0] == "ok"
    assert settle(runtime, svc.kdelete(0, "a"))[0] == "ok"
    assert settle(runtime, svc.kput(1, "z", b"z2"))[0] == "ok"
    crash(svc)

    rt2 = Runtime(seed=13)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    assert settle(rt2, svc2.kget(0, "a")) == ("ok", NOTFOUND)
    assert settle(rt2, svc2.kget(0, "b")) == ("ok", b"2")
    assert settle(rt2, svc2.kget(1, "z")) == ("ok", b"z2")


def test_slot_recycled_to_new_key_across_crash(tmp_path):
    """A checkpoint-era key whose slot was recycled to ANOTHER key
    after the checkpoint must read notfound after replay (stale
    mapping sweep) while the new key serves."""
    runtime, svc = make_durable(tmp_path, n_ens=1, n_peers=3, n_slots=1)
    assert settle(runtime, svc.kput(0, "old", b"o"))[0] == "ok"
    svc.save()
    assert settle(runtime, svc.kdelete(0, "old"))[0] == "ok"
    # single slot: the delete's recycle must free it for the new key
    assert settle(runtime, svc.kput(0, "new", b"n"))[0] == "ok"
    crash(svc)

    rt2 = Runtime(seed=14)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    assert settle(rt2, svc2.kget(0, "new")) == ("ok", b"n")
    assert settle(rt2, svc2.kget(0, "old")) == ("ok", NOTFOUND)
    assert len(svc2.free_slots[0]) == 0


def test_membership_change_survives_crash(tmp_path):
    runtime, svc = make_durable(tmp_path, n_ens=2, n_peers=5)
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    nv = np.ones((2, 5), bool)
    nv[:, 4] = False
    assert svc.update_members(np.ones(2, bool), nv).all()
    crash(svc)

    rt2 = Runtime(seed=15)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    assert (svc2.member_np == nv).all()
    # device view agrees: peer 4 down must not block quorum
    svc2.set_peer_up(0, 4, False)
    svc2.set_peer_up(1, 4, False)
    assert settle(rt2, svc2.kget(0, "k")) == ("ok", b"v")
    assert settle(rt2, svc2.kput(1, "m", b"w"))[0] == "ok"


def test_wal_rotates_on_save_and_old_generations_pruned(tmp_path):
    runtime, svc = make_durable(tmp_path)
    assert settle(runtime, svc.kput(0, "a", b"1"))[0] == "ok"
    assert svc._wal.count > 0
    svc.save()
    assert svc._wal.count == 0          # fresh generation
    names = os.listdir(tmp_path / "data")
    assert sum(n.startswith("wal.") for n in names) == 1
    assert f"wal.{svc._current_ckpt(str(tmp_path / 'data'))}" in names
    svc.stop()


def test_wal_auto_compacts_into_checkpoint(tmp_path):
    runtime, svc = make_durable(tmp_path, n_slots=8,
                                wal_compact_records=3)
    for i in range(6):
        assert settle(runtime,
                      svc.kput(0, f"k{i}", b"v%d" % i))[0] == "ok"
    # records crossed the bound -> a checkpoint happened, WAL rotated
    assert svc._current_ckpt(str(tmp_path / "data")) >= 1
    assert svc._wal.count < 3
    crash(svc)
    rt2 = Runtime(seed=16)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    for i in range(6):
        assert settle(rt2, svc2.kget(0, f"k{i}")) == ("ok", b"v%d" % i)


def test_bulk_execute_writes_survive_crash(tmp_path):
    """Host-array execute() commits are WAL'd (the result is the ack)
    and replay as inline payloads."""
    from riak_ensemble_tpu.ops import engine as eng

    runtime, svc = make_durable(tmp_path, n_ens=4, n_slots=4)
    kind = np.full((2, 4), eng.OP_PUT, np.int32)
    slot = np.tile(np.array([[0], [1]], np.int32), (1, 4))
    val = np.arange(1, 9, dtype=np.int32).reshape(2, 4)
    committed, _, _, _ = svc.execute(kind, slot, val)
    assert committed.all()
    crash(svc)

    rt2 = Runtime(seed=17)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=None,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    gk = np.full((2, 4), eng.OP_GET, np.int32)
    committed, get_ok, found, value = svc2.execute(
        gk, slot, np.zeros((2, 4), np.int32))
    assert get_ok.all() and found.all()
    np.testing.assert_array_equal(value, val)


def test_kill9_subprocess_acked_writes_survive(tmp_path):
    """The gold test: a separate OS process acks writes then dies via
    os._exit (no cleanup, no atexit, no checkpoint); the parent
    restores from disk and finds every acked write."""
    data = str(tmp_path / "data")
    child = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        from riak_ensemble_tpu.config import fast_test_config
        from riak_ensemble_tpu.parallel.batched_host import (
            BatchedEnsembleService)
        from riak_ensemble_tpu.runtime import Runtime
        rt = Runtime(seed=1)
        svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                     config=fast_test_config(),
                                     data_dir={data!r})
        futs = [svc.kput(e, "k%d" % i, b"v%d%d" % (e, i))
                for e in range(2) for i in range(3)]
        for f in futs:
            assert rt.await_future(f, 5.0)[0] == "ok", f.value
        print("ACKED", flush=True)
        os._exit(1)   # kill -9 analog: nothing runs after the acks
    """)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=240)
    assert "ACKED" in proc.stdout, proc.stderr[-2000:]
    assert proc.returncode == 1

    rt2 = Runtime(seed=18)
    svc2 = BatchedEnsembleService.restore(
        rt2, data, tick=0.005, config=fast_test_config(), data_dir=data)
    for e in range(2):
        for i in range(3):
            assert settle(rt2, svc2.kget(e, "k%d" % i)) == \
                ("ok", b"v%d%d" % (e, i))


def test_pure_python_wal_fallback(tmp_path, monkeypatch):
    """With the native treestore unavailable the PyLogStore path gives
    the same durability."""
    from riak_ensemble_tpu.synctree import native_store

    monkeypatch.setattr(native_store, "available", lambda: False)
    runtime, svc = make_durable(tmp_path)
    assert isinstance(svc._wal._store, PyLogStore)
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    crash(svc)

    rt2 = Runtime(seed=19)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    assert settle(rt2, svc2.kget(0, "k")) == ("ok", b"v")


def test_wal_generation_api(tmp_path):
    w = ServiceWAL.open_gen(str(tmp_path), 0)
    w.log([(("kv", 0, 0), ("k", 1, 1, 1, b"v", False))])
    assert w.count == 1
    w2 = ServiceWAL.rotate(str(tmp_path), 1, w)
    assert w2.count == 0
    assert os.path.isdir(ServiceWAL.gen_path(str(tmp_path), 1))
    assert not os.path.isdir(ServiceWAL.gen_path(str(tmp_path), 0))
    w2.close()


def test_pylogstore_double_crash_records_after_tear_survive(tmp_path):
    """Review finding: a torn tail must be TRUNCATED at reopen, or
    every record appended after it is unreachable at the next replay
    (acked writes silently lost on the second crash)."""
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store("k1", "v1")
    st.store("k2", "v2")
    st.sync()
    st.close()
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - 3)          # crash #1: torn k2 record

    st2 = PyLogStore(p)               # reopen truncates the tear
    assert st2.fetch("k1") == "v1" and st2.fetch("k2") is None
    st2.store("k3", "v3")             # acked after the first crash
    st2.sync()
    st2.close()                       # crash #2 (clean close is fine)

    st3 = PyLogStore(p)
    assert st3.fetch("k1") == "v1"
    assert st3.fetch("k3") == "v3", "record after torn tail lost"
    st3.close()


def test_pylogstore_foreign_prefix_starts_fresh(tmp_path):
    """Review finding: a non-MAGIC prefix must not be appended to —
    records after it would never replay.  The foreign bytes move
    aside and the log starts fresh."""
    p = str(tmp_path / "log")
    with open(p, "wb") as f:
        f.write(b"NOTAWALFILE")
    st = PyLogStore(p)
    assert st.quarantines == 1
    st.store("k", "v")
    st.sync()
    st.close()
    st2 = PyLogStore(p)
    assert st2.fetch("k") == "v"
    st2.close()
    assert os.path.exists(p + ".corrupt.0")


def test_pylogstore_second_quarantine_keeps_first_evidence(
        tmp_path, monkeypatch):
    """ISSUE 15 satellite: a second corruption must not clobber the
    first quarantined log — monotonic ``.corrupt.<n>`` suffixes, and
    the count rides stats() via ServiceWAL."""
    from riak_ensemble_tpu.synctree import native_store

    monkeypatch.setattr(native_store, "available", lambda: False)
    p = str(tmp_path / "w" / "wal")
    for i in (0, 1):
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(b"GARBAGE-%d" % i)
        w = ServiceWAL(str(tmp_path / "w"))
        assert w.stats()["quarantines"] == 1
        w.close()
    names = sorted(n for n in os.listdir(tmp_path / "w")
                   if ".corrupt." in n)
    assert names == ["wal.corrupt.0", "wal.corrupt.1"]
    with open(str(tmp_path / "w" / "wal.corrupt.0"), "rb") as f:
        assert f.read() == b"GARBAGE-0", "first evidence clobbered"


def test_buffer_mode_reaches_kernel_before_ack(tmp_path, monkeypatch):
    """Review finding: buffer mode promises process-crash safety, so
    log() must flush userspace buffers (another process / a fresh
    reader must see the records without any close)."""
    from riak_ensemble_tpu.synctree import native_store

    monkeypatch.setattr(native_store, "available", lambda: False)
    w = ServiceWAL(str(tmp_path / "w"), sync_mode="buffer")
    w.log([(("kv", 0, 0), ("k", 1, 1, 1, b"v", False))])
    # a fresh reader of the same file (no close on the writer!)
    rd = PyLogStore(os.path.join(str(tmp_path / "w"), "wal"))
    assert rd.fetch(("kv", 0, 0)) is not None, \
        "buffered record never reached the kernel"
    rd.close()
    w.close()


def test_recycled_row_inherits_no_pipeline_or_down_marks(tmp_path):
    """Review finding: a recycled row must not inherit the dead
    tenant's pending membership change or peer-down marks."""
    from riak_ensemble_tpu.runtime import Runtime

    rt = Runtime(seed=41)
    svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 dynamic=True)
    e = svc.create_ensemble("old")
    # leaderless desired change: no leader yet -> stays desired
    nv = np.zeros((2, 3), bool)
    nv[:, :2] = True
    sel = np.zeros(2, bool)
    sel[e] = True
    svc.update_members(sel, nv)
    assert svc._desired_mask[e]
    svc.set_peer_up(e, 2, False)      # old-tenant down mark
    assert svc.destroy_ensemble("old")

    e2 = svc.create_ensemble("new")
    assert e2 == e
    assert not svc._desired_mask[e2] and not svc._pending_mask[e2]
    assert svc.up[e2].all()
    # elect + serve with FULL membership; a later all-False-sel
    # update_members call must not re-propose the dead tenant's view
    f = svc.kput(e2, "k", b"v")
    assert rt.await_future(f, 5.0)[0] == "ok"
    svc.update_members(np.zeros(2, bool), nv)
    assert (svc.member_np[e2] == np.ones(3, bool)).all(), \
        "dead tenant's membership change applied to the new tenant"
    svc.stop()


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_crash_point_fuzz_no_acked_write_lost(tmp_path, seed):
    """Randomized crash-point fuzz: a child process runs a random
    keyed workload (puts/deletes/batch puts, interleaved across
    ensembles), appends every ACKED op to its own fsync'd side log
    the instant the future resolves, and os._exit()s at a random op
    count.  The parent restores from the data dir and asserts the
    final acked state of every key is exactly what the restored
    service serves — the sc.erl 'Data loss!' check (test/sc.erl:
    835-880) applied to crash recovery."""
    data = str(tmp_path / "data")
    acklog = str(tmp_path / "acks")
    child = textwrap.dedent(f"""
        import os, pickle, sys
        sys.path.insert(0, {REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from riak_ensemble_tpu.config import fast_test_config
        from riak_ensemble_tpu.parallel.batched_host import (
            BatchedEnsembleService)
        from riak_ensemble_tpu.runtime import Runtime

        rng = np.random.default_rng({seed})
        rt = Runtime(seed={seed})
        svc = BatchedEnsembleService(rt, 3, 3, 8, tick=0.005,
                                     config=fast_test_config(),
                                     data_dir={data!r})
        ack_f = open({acklog!r}, "ab")
        def record(op, e, key, val):
            ack_f.write(pickle.dumps((op, e, key, val)))
            ack_f.flush(); os.fsync(ack_f.fileno())

        stop_at = int(rng.integers(5, 40))
        done = 0
        while done < stop_at:
            e = int(rng.integers(3))
            r = rng.random()
            if r < 0.5:
                key = f"k{{int(rng.integers(5))}}"
                val = b"v%d" % int(rng.integers(1000))
                if rt.await_future(svc.kput(e, key, val),
                                   10.0)[0] == "ok":
                    record("put", e, key, val)
            elif r < 0.7:
                keys = [f"b{{i}}" for i in range(3)]
                vals = [b"w%d" % int(rng.integers(1000))
                        for _ in range(3)]
                res = rt.await_future(
                    svc.kput_many(e, keys, vals), 10.0)
                for kk, vv, rr in zip(keys, vals, res):
                    if rr[0] == "ok":
                        record("put", e, kk, vv)
            else:
                key = f"k{{int(rng.integers(5))}}"
                rr = rt.await_future(svc.kdelete(e, key), 10.0)
                if isinstance(rr, tuple) and rr[0] == "ok":
                    record("del", e, key, None)
            done += 1
        print("CRASHED_AT", done, flush=True)
        os._exit(1)
    """)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=300)
    assert "CRASHED_AT" in proc.stdout, proc.stderr[-2000:]

    # final acked value per (ens, key), in ack order
    import pickle
    expect = {}
    with open(acklog, "rb") as f:
        while True:
            try:
                op, e, key, val = pickle.load(f)
            except EOFError:
                break
            if op == "put":
                expect[(e, key)] = val
            else:
                expect[(e, key)] = None

    rt2 = Runtime(seed=seed + 100)
    svc2 = BatchedEnsembleService.restore(
        rt2, data, tick=0.005, config=fast_test_config(),
        data_dir=data)
    for (e, key), val in expect.items():
        got = settle(rt2, svc2.kget(e, key))
        assert got[0] == "ok", (e, key, got)
        want = NOTFOUND if val is None else val
        assert got[1] == want, \
            f"acked write lost/stale at {(e, key)}: {got[1]!r} != {want!r}"
    svc2.stop()


def test_buffer_mode_delete_reaches_kernel_before_ack(tmp_path,
                                                      monkeypatch):
    """ADVICE r3: delete() must honor buffer mode's process-crash
    floor exactly like log() — a destroy's kv deletions sitting in the
    userspace stdio buffer would die with the process and replay the
    destroyed tenant's records into a recycled row."""
    from riak_ensemble_tpu.synctree import native_store

    monkeypatch.setattr(native_store, "available", lambda: False)
    w = ServiceWAL(str(tmp_path / "w"), sync_mode="buffer")
    w.log([(("kv", 0, 0), ("k", 1, 1, 1, b"v", False))])
    w.delete([("kv", 0, 0)])
    # a fresh reader of the same file (no close on the writer!)
    rd = PyLogStore(os.path.join(str(tmp_path / "w"), "wal"))
    assert rd.fetch(("kv", 0, 0)) is None, \
        "buffered deletion never reached the kernel"
    rd.close()
    w.close()


def test_device_resident_execute_unlogged_is_observable(tmp_path):
    """ADVICE r3: a WAL-enabled service serving device-resident
    execute() calls silently weakens the durability contract (no WAL
    record; RPO = checkpoint cadence).  That must be observable: a
    one-time trace event plus a stats() flag."""
    import jax.numpy as jnp

    from riak_ensemble_tpu.ops import engine as eng

    events = []
    runtime, svc = make_durable(tmp_path)
    runtime.trace = lambda kind, payload: events.append((kind, payload))
    k = 2
    kind = jnp.full((k, svc.n_ens), eng.OP_PUT, jnp.int32)
    slot = jnp.zeros((k, svc.n_ens), jnp.int32)
    val = jnp.ones((k, svc.n_ens), jnp.int32)
    assert svc.stats()["execute_unlogged"] is False
    svc.execute(kind, slot, val)
    svc.execute(kind, slot, val)
    unlogged = [e for e in events if e[0] == "svc_execute_unlogged"]
    assert len(unlogged) == 1, "exactly one one-time trace event"
    assert svc.stats()["execute_unlogged"] is True
    # host-array calls still WAL-log: the flag marks the weaker path's
    # use, it does not disable durability for the strong one
    before = svc._wal.count
    svc.execute(np.full((1, svc.n_ens), eng.OP_PUT, np.int32),
                np.zeros((1, svc.n_ens), np.int32),
                np.full((1, svc.n_ens), 7, np.int32))
    assert svc._wal.count > before
    svc.stop()
