"""Host↔engine bridge: engine-backed ensembles behind the service API,
with host-side failure detection driving batched elections.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def make_service(n_ens=64, n_peers=5, n_slots=16):
    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, n_ens, n_peers, n_slots,
                                 tick=0.005, config=fast_test_config())
    return runtime, svc


def settle(runtime, fut, timeout=5.0):
    return runtime.await_future(fut, timeout)


def test_put_get_roundtrip_across_ensembles():
    runtime, svc = make_service()
    futs = [(e, svc.kput(e, "k", f"v{e}".encode()))
            for e in range(svc.n_ens)]
    for e, fut in futs:
        r = settle(runtime, fut)
        assert r[0] == "ok", (e, r)
    for e in range(svc.n_ens):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", f"v{e}".encode())
    # unknown key
    assert settle(runtime, svc.kget(0, "nope")) == ("ok", NOTFOUND)
    assert svc.flushes >= 1


def test_delete_recycles_slot():
    runtime, svc = make_service(n_ens=1, n_slots=2)
    assert settle(runtime, svc.kput(0, "a", b"1"))[0] == "ok"
    assert settle(runtime, svc.kput(0, "b", b"2"))[0] == "ok"
    # full: next new key fails
    assert settle(runtime, svc.kput(0, "c", b"3")) == "failed"
    assert settle(runtime, svc.kdelete(0, "a"))[0] == "ok"
    assert settle(runtime, svc.kput(0, "c", b"3"))[0] == "ok"
    assert settle(runtime, svc.kget(0, "a")) == ("ok", NOTFOUND)
    assert settle(runtime, svc.kget(0, "c")) == ("ok", b"3")


def test_leader_failure_reelection():
    runtime, svc = make_service(n_ens=8)
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
    leaders = np.asarray(svc.state.leader).copy()
    assert (leaders >= 0).all()

    # kill every leader replica (host failure detector)
    for e in range(8):
        svc.set_peer_up(e, int(leaders[e]), False)
    # expire leases so reads can't ride the old lease
    svc.lease_until[:] = 0.0
    runtime.run_for(0.1)  # a few ticks: elections fold into flushes

    new_leaders = np.asarray(svc.state.leader)
    assert (new_leaders != leaders).all(), "no re-election"
    for e in range(8):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", b"v"), (e, r)
    # writes work under the new leaders too
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"v2"))[0] == "ok"


def test_no_quorum_no_service():
    runtime, svc = make_service(n_ens=4, n_peers=5)
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
    # majority down
    for e in range(4):
        for p in (0, 1, 2):
            svc.set_peer_up(e, p, False)
    svc.lease_until[:] = 0.0
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"x")) == "failed"
        assert settle(runtime, svc.kget(e, "k")) == "failed"
    # heal: service resumes (election re-folds in)
    for e in range(4):
        for p in (0, 1, 2):
            svc.set_peer_up(e, p, True)
    runtime.run_for(0.1)
    for e in range(4):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", b"v"), (e, r)


def test_batching_amortizes_flushes():
    runtime, svc = make_service(n_ens=32)
    futs = []
    for e in range(32):
        for i in range(8):
            futs.append(svc.kput(e, f"k{i}", b"x"))
    runtime.run_for(0.2)
    assert all(f.done and f.value[0] == "ok" for f in futs)
    # 8 ops per ensemble served in ~= 8/k flush rounds, not 256 calls
    assert svc.flushes < 50
    assert svc.ops_served == 256
