"""Host↔engine bridge: engine-backed ensembles behind the service API,
with host-side failure detection driving batched elections.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def make_service(n_ens=64, n_peers=5, n_slots=16):
    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, n_ens, n_peers, n_slots,
                                 tick=0.005, config=fast_test_config())
    return runtime, svc


def settle(runtime, fut, timeout=5.0):
    return runtime.await_future(fut, timeout)


def test_put_get_roundtrip_across_ensembles():
    runtime, svc = make_service()
    futs = [(e, svc.kput(e, "k", f"v{e}".encode()))
            for e in range(svc.n_ens)]
    for e, fut in futs:
        r = settle(runtime, fut)
        assert r[0] == "ok", (e, r)
    for e in range(svc.n_ens):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", f"v{e}".encode())
    # unknown key
    assert settle(runtime, svc.kget(0, "nope")) == ("ok", NOTFOUND)
    assert svc.flushes >= 1


def test_delete_recycles_slot():
    runtime, svc = make_service(n_ens=1, n_slots=2)
    assert settle(runtime, svc.kput(0, "a", b"1"))[0] == "ok"
    assert settle(runtime, svc.kput(0, "b", b"2"))[0] == "ok"
    # full: next new key fails
    assert settle(runtime, svc.kput(0, "c", b"3")) == "failed"
    assert settle(runtime, svc.kdelete(0, "a"))[0] == "ok"
    assert settle(runtime, svc.kput(0, "c", b"3"))[0] == "ok"
    assert settle(runtime, svc.kget(0, "a")) == ("ok", NOTFOUND)
    assert settle(runtime, svc.kget(0, "c")) == ("ok", b"3")


def test_leader_failure_reelection():
    runtime, svc = make_service(n_ens=8)
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
    leaders = np.asarray(svc.state.leader).copy()
    assert (leaders >= 0).all()

    # kill every leader replica (host failure detector)
    for e in range(8):
        svc.set_peer_up(e, int(leaders[e]), False)
    # expire leases so reads can't ride the old lease
    svc.lease_until[:] = 0.0
    runtime.run_for(0.1)  # a few ticks: elections fold into flushes

    new_leaders = np.asarray(svc.state.leader)
    assert (new_leaders != leaders).all(), "no re-election"
    for e in range(8):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", b"v"), (e, r)
    # writes work under the new leaders too
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"v2"))[0] == "ok"


def test_no_quorum_no_service():
    runtime, svc = make_service(n_ens=4, n_peers=5)
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
    # majority down
    for e in range(4):
        for p in (0, 1, 2):
            svc.set_peer_up(e, p, False)
    svc.lease_until[:] = 0.0
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"x")) == "failed"
        assert settle(runtime, svc.kget(e, "k")) == "failed"
    # heal: service resumes (election re-folds in)
    for e in range(4):
        for p in (0, 1, 2):
            svc.set_peer_up(e, p, True)
    runtime.run_for(0.1)
    for e in range(4):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", b"v"), (e, r)


def test_batching_amortizes_flushes():
    runtime, svc = make_service(n_ens=32)
    futs = []
    for e in range(32):
        for i in range(8):
            futs.append(svc.kput(e, f"k{i}", b"x"))
    runtime.run_for(0.2)
    assert all(f.done and f.value[0] == "ok" for f in futs)
    # 8 ops per ensemble served in ~= 8/k flush rounds, not 256 calls
    assert svc.flushes < 50
    assert svc.ops_served == 256


def test_read_only_load_keeps_lease_renewed():
    """A leader serving only reads renews its lease via the epoch-check
    quorum (leader_tick renewal, peer.erl:1092-1095) — read-only load
    must not fall off the lease fast path."""
    runtime, svc = make_service(n_ens=4)
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
    lease0 = svc.lease_until.copy()
    # Read-only traffic past the original lease horizon.
    deadline = float(lease0.max()) + 3 * svc.config.lease()
    while runtime.now < deadline:
        for e in range(4):
            assert settle(runtime, svc.kget(e, "k")) == ("ok", b"v")
        runtime.run_for(svc.config.lease() / 4)
    assert (svc.lease_until > lease0).all(), "reads did not renew leases"


def test_service_heals_device_corruption():
    """Corruption injected into a replica's store is detected by the
    engine's integrity gate, served around, and healed by the service's
    exchange flow (tree_corrupted -> repair -> exchange)."""
    runtime, svc = make_service(n_ens=4)
    futs = {}
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"v"))[0] == "ok"
        futs[e] = settle(runtime, svc.kput(e, "j", b"w"))
    # Damage peer 2's object for "k" on every ensemble, out-of-band.
    slot_k = [svc.key_slot[e]["k"] for e in range(4)]
    ov = svc.state.obj_val
    for e in range(4):
        ov = ov.at[e, 2, slot_k[e]].set(424242)
    svc.state = svc.state._replace(obj_val=ov)
    # Out-of-band device damage is only visible to a DEVICE round (a
    # leased fast read serves the host committed mirror — like cold
    # slots, damage waits for the next device access or scrub): expire
    # the leases before each read so every one takes the round and
    # trips the gate (a flush's quorum round re-leases every column).
    # Reads still serve the committed value; repair kicks in.
    for e in range(4):
        svc.lease_until[:] = 0.0
        assert settle(runtime, svc.kget(e, "k")) == ("ok", b"v")
    assert svc.corruptions > 0   # detected on device, surfaced to host
    from riak_ensemble_tpu.ops import engine as eng
    node_bad, leaf_bad = eng.verify_trees(svc.state)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_service_composes_with_sharded_engine():
    """The same service runs over a ShardedEngine on the virtual
    8-device mesh (the scale-out path, VERDICT round-1 item 3)."""
    from riak_ensemble_tpu.parallel.mesh import ShardedEngine, make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    from riak_ensemble_tpu.runtime import Runtime
    runtime = Runtime(seed=51)
    se = ShardedEngine(make_mesh(4, 2))
    svc = BatchedEnsembleService(runtime, n_ens=8, n_peers=4, n_slots=16,
                                 tick=0.005, config=fast_test_config(),
                                 engine=se)
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", f"v{e}".encode()))[0] == "ok"
    for e in range(8):
        assert settle(runtime, svc.kget(e, "k")) == ("ok", f"v{e}".encode())
    # Failover on the mesh: kill the leaders, service re-elects.
    leaders = np.asarray(svc.state.leader).copy()
    for e in range(8):
        svc.set_peer_up(e, int(leaders[e]), False)
    svc.lease_until[:] = 0.0
    runtime.run_for(0.1)
    assert (np.asarray(svc.state.leader) != leaders).all()
    for e in range(8):
        assert settle(runtime, svc.kget(e, "k")) == ("ok", f"v{e}".encode())


def test_delete_then_put_same_flush_keeps_put():
    """A delete and a later put for the same key riding one flush:
    the delete's deferred slot recycle must NOT free the slot the put
    re-wrote, or the committed put becomes unreachable (found by the
    service linearizability sweep, seed 702)."""
    runtime, svc = make_service(n_ens=1, n_slots=4)
    assert settle(runtime, svc.kput(0, "k", b"v1"))[0] == "ok"
    fd = svc.kdelete(0, "k")
    fp = svc.kput(0, "k", b"v2")
    assert settle(runtime, fd)[0] == "ok"
    assert settle(runtime, fp)[0] == "ok"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v2")
    # and a lone delete still recycles its slot
    assert settle(runtime, svc.kdelete(0, "k"))[0] == "ok"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", NOTFOUND)
    assert len(svc.free_slots[0]) == 4


def test_committed_overwrites_release_payloads():
    """The host payload store must not grow per committed overwrite or
    delete — superseded handles are released when the new write
    commits."""
    runtime, svc = make_service(n_ens=1, n_slots=4)
    for i in range(20):
        assert settle(runtime, svc.kput(0, "k", b"v%d" % i))[0] == "ok"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v19")
    assert len(svc.values) <= 2, len(svc.values)
    assert settle(runtime, svc.kdelete(0, "k"))[0] == "ok"
    for i in range(10):
        assert settle(runtime, svc.kput(0, "x", b"x%d" % i))[0] == "ok"
    assert len(svc.values) <= 2, len(svc.values)


def test_handles_recycled_not_monotonic():
    """Released payload handles return to a pool (device handles are
    int32 and 0 is the tombstone; a wrapping counter would eventually
    alias live handles)."""
    runtime, svc = make_service(n_ens=1, n_slots=2)
    for i in range(30):
        assert settle(runtime, svc.kput(0, "k", b"v%d" % i))[0] == "ok"
    # 30 committed overwrites but only ~1 live payload: the handle
    # counter must not have advanced 30 times.
    assert svc._next_handle <= 4, svc._next_handle
    assert len(svc.values) <= 2


def test_service_update_members_end_to_end():
    """Membership change through the serving path: shrink 5 -> 3
    members (dropping the current leader), data survives, the next
    flush elects a new leader from the surviving membership, and ops
    keep flowing; then grow back to 5."""
    runtime, svc = make_service(n_ens=8, n_peers=5, n_slots=8)
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"v-%d" % e))[0] == "ok"

    leader0 = svc.leader_np.copy()
    assert (leader0 >= 0).all()
    # Drop the leader's peer from every ensemble's membership.
    new_view = np.ones((8, 5), bool)
    new_view[np.arange(8), leader0] = False
    changed = svc.update_members(np.ones(8, bool), new_view)
    assert changed.all(), changed
    assert (svc.member_np == new_view).all()
    # Old leaders were transitioned out -> elections pending.
    assert (svc.leader_np == -1).all()

    for e in range(8):
        r = settle(runtime, svc.kget(e, "k"))
        assert r == ("ok", b"v-%d" % e), (e, r)
    assert (svc.leader_np >= 0).all()
    assert np.take_along_axis(new_view, svc.leader_np[:, None],
                              1).all(), "leader outside new membership"

    # Grow back to the full membership and write through it.
    changed = svc.update_members(np.ones(8, bool), np.ones((8, 5), bool))
    assert changed.all()
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"w-%d" % e))[0] == "ok"
        assert settle(runtime, svc.kget(e, "k")) == ("ok", b"w-%d" % e)


def test_service_update_members_sharded_engine():
    """The same membership change composes with ShardedEngine on the
    virtual mesh."""
    import jax
    from riak_ensemble_tpu.parallel.mesh import ShardedEngine, make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    runtime = Runtime(seed=61)
    se = ShardedEngine(make_mesh(4, 2))
    svc = BatchedEnsembleService(runtime, 8, 8, n_slots=8, tick=0.005,
                                 config=fast_test_config(), engine=se)
    for e in range(8):
        assert settle(runtime, svc.kput(e, "k", b"x%d" % e))[0] == "ok"
    new_view = np.ones((8, 8), bool)
    new_view[:, 7] = False
    changed = svc.update_members(np.ones(8, bool), new_view)
    assert changed.all()
    for e in range(8):
        assert settle(runtime, svc.kget(e, "k")) == ("ok", b"x%d" % e)


def test_service_skewed_queues():
    """Heavily skewed load: one ensemble with a deep queue, the rest
    idle or light — padding rounds must not corrupt idle ensembles'
    state and every queued op resolves correctly."""
    runtime, svc = make_service(n_ens=16, n_peers=3, n_slots=8)
    futs = []
    for i in range(40):  # deep queue on ensemble 0 (> max_ops_per_tick)
        futs.append((b"d%d" % i, svc.kput(0, "hot", b"d%d" % i)))
    light = [(e, svc.kput(e, "cold", b"c%d" % e)) for e in (3, 9)]
    for _v, f in futs:
        assert settle(runtime, f)[0] == "ok"
    for e, f in light:
        assert settle(runtime, f)[0] == "ok"
    assert settle(runtime, svc.kget(0, "hot")) == ("ok", b"d39")
    for e in (3, 9):
        assert settle(runtime, svc.kget(e, "cold")) == ("ok", b"c%d" % e)
    for e in (1, 2, 15):
        assert settle(runtime, svc.kget(e, "hot")) == ("ok", NOTFOUND)


def test_service_flush_depth_buckets_to_pow2():
    """Distinct [K, E] shapes each cost an XLA compile; flush must
    bucket the batch depth to powers of two so skewed/varying queue
    lengths don't trigger compile churn (one program per depth)."""
    from riak_ensemble_tpu.parallel.batched_host import _LocalEngine

    seen = []

    class RecordingEngine(_LocalEngine):
        @staticmethod
        def full_step(state, elect, cand, kind, slot, val, lease_ok,
                      up, **kw):
            seen.append(int(kind.shape[0]))
            return _LocalEngine.full_step(
                state, elect, cand, kind, slot, val, lease_ok, up, **kw)

    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, 8, 3, 16, tick=None,
                                 config=fast_test_config(),
                                 engine=RecordingEngine())
    for depth in (1, 2, 3, 5, 7, 11, 13):
        futs = [svc.kput(0, f"k{i}", b"v") for i in range(depth)]
        while any(svc.queues):
            svc.flush()
        for f in futs:
            assert f.done and f.value[0] == "ok"
    assert seen, "no launches recorded"
    assert all(k & (k - 1) == 0 for k in seen), seen  # powers of two
    # 7 distinct raw depths collapse into at most 5 compiled shapes
    assert len(set(seen)) <= 5, seen


def test_service_update_members_blocked_collapse_lands_later():
    """Install commits under the old view while the NEW view lacks
    quorum, so the collapse blocks; after healing, a later call (pure
    retry, all-False sel) must land the leftover collapse and promote
    the host membership mirror (the joint view is collapsed by the
    FIRST launch's transition half — its outcome must not be lost)."""
    runtime, svc = make_service(n_ens=1, n_peers=5, n_slots=4)
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    leader = int(svc.leader_np[0])
    assert leader == 0  # lowest-index candidate wins

    svc.set_peer_up(0, 1, False)
    svc.set_peer_up(0, 2, False)
    nv = np.zeros((1, 5), bool)
    nv[0, :3] = True  # {0,1,2}: only 1/3 up -> collapse must block
    changed = svc.update_members(np.ones(1, bool), nv)
    assert not changed.any()
    assert svc._pending_mask[0]
    assert svc.member_np[0].all()  # mirror keeps the old view

    svc.set_peer_up(0, 1, True)
    svc.set_peer_up(0, 2, True)
    changed = svc.update_members(np.zeros(1, bool), nv)
    assert changed.all(), changed
    assert (svc.member_np[0] == nv[0]).all()
    assert not svc._pending_mask[0]
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v")


def test_service_update_members_blocked_install_retries():
    """A request made while no commit quorum exists (leader down, no
    election yet) cannot install; it stays desired and a later pure
    retry lands it after the next flush re-elects."""
    runtime, svc = make_service(n_ens=1, n_peers=5, n_slots=4)
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    svc.set_peer_up(0, int(svc.leader_np[0]), False)

    nv = np.zeros((1, 5), bool)
    nv[0, 1:4] = True
    changed = svc.update_members(np.ones(1, bool), nv)
    assert not changed.any()
    assert svc._desired_mask[0] and not svc._pending_mask[0]

    # A flush folds in the re-election; the retry then completes.
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v")
    changed = svc.update_members(np.zeros(1, bool), nv)
    assert changed.all(), changed
    assert (svc.member_np[0] == nv[0]).all()


def test_service_save_restore_roundtrip(tmp_path):
    """Full service checkpoint: device state via orbax + host mirrors
    via the CRC blob; a restored service serves the same data, holds
    no pre-crash lease, and keeps its membership pipeline."""
    runtime, svc = make_service(n_ens=4, n_peers=5, n_slots=4)
    for e in range(4):
        assert settle(runtime, svc.kput(e, "k", b"v%d" % e))[0] == "ok"
    assert settle(runtime, svc.kdelete(3, "k"))[0] == "ok"
    nv = np.ones((4, 5), bool)
    nv[:, 4] = False
    assert svc.update_members(np.ones(4, bool), nv).all()
    svc.save(str(tmp_path / "ckpt"))
    svc.stop()

    rt2 = Runtime(seed=99)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "ckpt"), tick=0.005,
        config=fast_test_config())
    assert (svc2.lease_until == 0).all()  # never trust pre-crash leases
    assert (svc2.member_np == nv).all()
    for e in range(3):
        assert settle(rt2, svc2.kget(e, "k")) == ("ok", b"v%d" % e)
    assert settle(rt2, svc2.kget(3, "k")) == ("ok", NOTFOUND)
    # and the restored service keeps serving writes
    assert settle(rt2, svc2.kput(0, "k", b"post"))[0] == "ok"
    assert settle(rt2, svc2.kget(0, "k")) == ("ok", b"post")


def test_service_update_members_queued_request_not_dropped():
    """A request targeting an ensemble whose earlier change is still
    joint is queued (not silently dropped): once the first change
    collapses, a retry proposes and lands the queued view."""
    runtime, svc = make_service(n_ens=1, n_peers=5, n_slots=4)
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"

    svc.set_peer_up(0, 1, False)
    svc.set_peer_up(0, 2, False)
    view_a = np.zeros((1, 5), bool)
    view_a[0, :3] = True          # collapse blocks: 1/3 up
    assert not svc.update_members(np.ones(1, bool), view_a).any()
    assert svc._pending_mask[0]

    view_b = np.zeros((1, 5), bool)
    view_b[0, [0, 3, 4]] = True   # new request while A is joint
    changed = svc.update_members(np.ones(1, bool), view_b)
    # A still cannot collapse (quorum still missing) and B must wait.
    assert not changed.any()
    assert svc._queued_mask[0]

    svc.set_peer_up(0, 1, True)
    svc.set_peer_up(0, 2, True)
    # Retry 1: A collapses, B advances to desired.
    changed = svc.update_members(np.zeros(1, bool), view_a)
    assert changed.all()
    assert (svc.member_np[0] == view_a[0]).all()
    # Retry 2: B proposes + lands.
    changed = svc.update_members(np.zeros(1, bool), view_a)
    assert changed.all()
    assert (svc.member_np[0] == view_b[0]).all()
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v")


def test_service_save_versioned_and_queued_ops_flushed(tmp_path):
    """Repeated saves keep a restorable checkpoint at every point
    (CURRENT pointer flips only after the new pair is complete), and
    queued-but-unflushed ops are resolved before snapshotting so no
    slot/handle side effects leak into the image."""
    runtime, svc = make_service(n_ens=1, n_peers=3, n_slots=2)
    assert settle(runtime, svc.kput(0, "a", b"1"))[0] == "ok"
    svc.save(str(tmp_path / "c"))
    # enqueue WITHOUT settling: save must flush it, not leak it
    fut = svc.kput(0, "b", b"2")
    svc.save(str(tmp_path / "c"))
    assert fut.done and fut.value[0] == "ok"
    svc.stop()

    import os
    names = sorted(os.listdir(tmp_path / "c"))
    assert "CURRENT" in names
    assert sum(n.startswith("ckpt.") for n in names) == 1  # old pruned

    rt2 = Runtime(seed=7)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "c"), tick=0.005, config=fast_test_config())
    assert settle(rt2, svc2.kget(0, "a")) == ("ok", b"1")
    assert settle(rt2, svc2.kget(0, "b")) == ("ok", b"2")
    # no leaked slots/handles: both keys live, store consistent
    assert len(svc2.values) == 2
    assert len(svc2.free_slots[0]) == 0
    assert settle(rt2, svc2.kdelete(0, "a"))[0] == "ok"
    assert settle(rt2, svc2.kput(0, "c", b"3"))[0] == "ok"


def test_service_cas_chain():
    """kupdate/ksafe_delete through the serving path: CAS on the vsn
    from kput/kget_vsn; stale CAS fails without touching data;
    tombstone vsn rides kget_vsn so delete-then-guard chains work."""
    runtime, svc = make_service(n_ens=2, n_peers=5, n_slots=4)
    r = settle(runtime, svc.kput(0, "k", b"v1"))
    assert r[0] == "ok"
    vsn1 = r[1]

    r = settle(runtime, svc.kupdate(0, "k", vsn1, b"v2"))
    assert r[0] == "ok"
    vsn2 = r[1]
    assert vsn2 != vsn1
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v2")

    # stale CAS: fails, value untouched, payload store clean
    assert settle(runtime, svc.kupdate(0, "k", vsn1, b"v3")) == "failed"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v2")

    # kget_vsn returns the same vsn a CAS needs
    r = settle(runtime, svc.kget_vsn(0, "k"))
    assert r == ("ok", b"v2", vsn2), r

    # version-guarded delete, then stale-guard delete fails
    assert settle(runtime, svc.ksafe_delete(0, "k", vsn1)) == "failed"
    r = settle(runtime, svc.ksafe_delete(0, "k", vsn2))
    assert r[0] == "ok"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", NOTFOUND)

    # create-if-missing: CAS against (0, 0) is kput_once
    r = settle(runtime, svc.kupdate(1, "fresh", (0, 0), b"first"))
    assert r[0] == "ok"
    assert settle(runtime, svc.kupdate(1, "fresh", (0, 0),
                                       b"second")) == "failed"
    assert settle(runtime, svc.kget(1, "fresh")) == ("ok", b"first")


def test_service_cas_failed_releases_payload():
    runtime, svc = make_service(n_ens=1, n_peers=3, n_slots=2)
    r = settle(runtime, svc.kput(0, "k", b"a"))
    vsn = r[1]
    assert settle(runtime, svc.kupdate(0, "k", (9, 9), b"b")) == "failed"
    assert settle(runtime, svc.kupdate(0, "k", vsn, b"c"))[0] == "ok"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"c")
    assert len(svc.values) == 1  # failed/superseded payloads released


def test_service_create_if_missing_on_recycled_slot():
    """A recycled slot keeps the previous key's tombstone on device;
    create-if-missing for a NEW key mapped onto it must still succeed
    (the engine's (0,0) matches tombstones, like do_kput_once)."""
    runtime, svc = make_service(n_ens=1, n_peers=3, n_slots=1)
    assert settle(runtime, svc.kput(0, "old", b"x"))[0] == "ok"
    assert settle(runtime, svc.kdelete(0, "old"))[0] == "ok"
    assert len(svc.free_slots[0]) == 1  # slot recycled, tombstone stays
    r = settle(runtime, svc.kupdate(0, "new", (0, 0), b"y"))
    assert r[0] == "ok", r
    assert settle(runtime, svc.kget(0, "new")) == ("ok", b"y")


def test_service_stats_and_trace():
    from riak_ensemble_tpu.utils.trace import Tracer

    runtime, svc = make_service(n_ens=2, n_peers=3, n_slots=4)
    tracer = Tracer(runtime).install()
    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    assert settle(runtime, svc.kget(1, "k")) == ("ok", NOTFOUND)
    st = svc.stats()
    assert st["flushes"] >= 1 and st["ops_served"] >= 1
    assert st["ensembles_with_leader"] == 2
    assert st["live_payloads"] == 1
    assert tracer.counters.get("svc_launch", 0) >= 1
    tracer.uninstall()


def test_service_execute_with_cas_planes():
    """Bulk array API: CAS planes flow through execute()."""
    runtime, svc = make_service(n_ens=4, n_peers=3, n_slots=8)
    from riak_ensemble_tpu.ops import engine as eng2

    kind = np.full((1, 4), eng2.OP_PUT, np.int32)
    slot = np.zeros((1, 4), np.int32)
    val = np.full((1, 4), 10, np.int32)
    committed, *_ = svc.execute(kind, slot, val)
    assert committed.all()
    # CAS expecting (epoch=1, seq=1) after the first commit
    kind[:] = eng2.OP_CAS
    val[:] = 20
    xe = np.ones((1, 4), np.int32)
    xs = np.ones((1, 4), np.int32)
    committed, *_ = svc.execute(kind, slot, val, exp_epoch=xe, exp_seq=xs)
    assert committed.all()
    # stale now
    committed, *_ = svc.execute(kind, slot, val, exp_epoch=xe, exp_seq=xs)
    assert not committed.any()
    kind[:] = eng2.OP_GET
    _, get_ok, found, value = svc.execute(kind, slot, np.zeros_like(val))
    assert get_ok.all() and found.all() and (value == 20).all()


def test_service_on_netruntime_asyncio():
    """The engine-backed service runs on the real-time asyncio runtime
    (NetRuntime) with wall-clock flush ticks — the single-host
    production composition of the DCN/host half and the device
    engine."""
    import asyncio

    from riak_ensemble_tpu.netruntime import NetRuntime

    async def scenario():
        runtime = NetRuntime("node0", {"node0": ("127.0.0.1", 0)})
        runtime.loop = asyncio.get_running_loop()
        svc = BatchedEnsembleService(runtime, 4, 3, n_slots=4,
                                     tick=0.01,
                                     config=fast_test_config())
        r = await runtime.await_future(svc.kput(0, "k", b"v"), 10.0)
        assert r[0] == "ok"
        vsn = r[1]
        r = await runtime.await_future(svc.kget(0, "k"), 10.0)
        assert r == ("ok", b"v")
        r = await runtime.await_future(
            svc.kupdate(0, "k", vsn, b"v2"), 10.0)
        assert r[0] == "ok"
        r = await runtime.await_future(svc.kget(0, "k"), 10.0)
        assert r == ("ok", b"v2")
        svc.stop()

    asyncio.run(scenario())


def test_launch_failure_fails_ops_instead_of_orphaning():
    """A device launch that raises (XLA error, dead backend) must fail
    every op taken for that flush — clients would otherwise block on
    their futures forever — and the service must keep working once
    the device recovers (request_failed analog, peer.erl:1274-1275)."""
    from riak_ensemble_tpu.parallel.batched_host import _LocalEngine

    class FlakyEngine(_LocalEngine):
        fail_next = False

        @classmethod
        def full_step(cls, *a, **kw):
            if cls.fail_next:
                cls.fail_next = False
                raise RuntimeError("injected device failure")
            return _LocalEngine.full_step(*a, **kw)

        # a RETPU_WIDE=1 run launches through the wide twin — the
        # injection must cover whichever flavor the flush takes
        @classmethod
        def full_step_wide(cls, *a, **kw):
            if cls.fail_next:
                cls.fail_next = False
                raise RuntimeError("injected device failure")
            return _LocalEngine.full_step_wide(*a, **kw)

    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, 4, 3, 8, tick=None,
                                 config=fast_test_config(),
                                 engine=FlakyEngine())
    ok = svc.kput(0, "a", b"1")
    svc.flush()
    assert ok.done and ok.value[0] == "ok"

    FlakyEngine.fail_next = True
    f1 = svc.kput(0, "b", b"2")
    # a leased read of an untouched key serves from the committed
    # mirror BEFORE the failing launch — the failure can't reach it
    f2 = svc.kget(0, "a")
    assert f2.done and f2.value == ("ok", b"1")
    # while one of the write-pended key rides the (failing) round
    f2b = svc.kget(0, "b")
    with pytest.raises(RuntimeError, match="injected"):
        svc.flush()
    assert f1.done and f1.value == "failed"
    assert f2b.done and f2b.value == "failed"
    # payload of the failed put released, slot queued for recycle
    assert len(svc.values) == 1  # only "a"'s committed payload

    # The device "recovers": the service keeps serving.
    f3 = svc.kput(0, "b", b"3")
    while any(svc.queues):
        svc.flush()
    assert f3.done and f3.value[0] == "ok"
    # the committed write is immediately visible to a leased read
    # (mirror-before-ack), no second round needed
    assert svc.kget(0, "b").value == ("ok", b"3")


def test_async_launch_failure_rolls_back_state():
    """Under async dispatch a real device failure surfaces at the d2h
    fetch, AFTER self.state was replaced with the failed launch's
    poisoned arrays; the launch path must roll back to the pre-launch
    state or every subsequent flush consumes the poison and fails
    forever."""
    from riak_ensemble_tpu.parallel.batched_host import _LocalEngine

    class AsyncPoisonEngine(_LocalEngine):
        poison_next = False

        @classmethod
        def full_step(cls, *a, **kw):
            state, won, res = _LocalEngine.full_step(*a, **kw)
            if cls.poison_next:
                cls.poison_next = False
                # The returned state LOOKS fine (it replaces
                # svc.state), but the result fetch blows up — the
                # async-dispatch failure shape.
                res = res._replace(value="poisoned-not-an-array")
            return state, won, res

    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, 4, 3, 8, tick=None,
                                 config=fast_test_config(),
                                 engine=AsyncPoisonEngine())
    assert_ok = svc.kput(0, "a", b"1")
    svc.flush()
    assert assert_ok.done and assert_ok.value[0] == "ok"
    good_state = svc.state

    AsyncPoisonEngine.poison_next = True
    f1 = svc.kput(0, "b", b"2")
    with pytest.raises(Exception):
        svc.flush()
    assert f1.done and f1.value == "failed"
    assert svc.state is good_state, "poisoned state was not rolled back"

    # Clean state: the service serves again immediately.
    f2 = svc.kput(0, "b", b"3")
    while any(svc.queues):
        svc.flush()
    assert f2.done and f2.value[0] == "ok"
    r = svc.kget(0, "a")
    while any(svc.queues):
        svc.flush()
    assert r.done and r.value == ("ok", b"1")


def test_raising_client_waiter_does_not_orphan_batch():
    """Future.resolve runs waiters synchronously; a client callback
    that raises must not abort the resolve loop (orphaning later ops)
    nor mask a device error on the failure path."""
    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, 2, 3, 8, tick=None,
                                 config=fast_test_config())
    bad = svc.kput(0, "a", b"1")
    bad.add_waiter(lambda _r: (_ for _ in ()).throw(ValueError("client bug")))
    good = svc.kput(1, "b", b"2")
    while any(svc.queues):
        svc.flush()   # must not raise: client bug is traced, not fatal
    assert bad.done and bad.value[0] == "ok"
    assert good.done and good.value[0] == "ok"


def test_all_waiters_run_despite_raising_waiter():
    """Future.resolve must run every waiter even when an earlier one
    raises — the waiter list is swapped out before iterating, so a
    skipped waiter could never fire again."""
    from riak_ensemble_tpu.runtime import Future

    ran = []
    f = Future()
    f.add_waiter(lambda _r: ran.append("a"))
    f.add_waiter(lambda _r: (_ for _ in ()).throw(ValueError("bug")))
    f.add_waiter(lambda _r: ran.append("b"))
    with pytest.raises(ValueError, match="bug"):
        f.resolve("x")
    assert ran == ["a", "b"]
    assert f.done and f.value == "x"


def test_burst_flush_does_not_wait_for_tick():
    """A queue reaching a full launch's depth flushes on the next
    runtime turn instead of waiting out the tick — batching must
    amortize, not add latency."""
    runtime = Runtime(seed=50)
    svc = BatchedEnsembleService(runtime, 2, 3, 16, tick=10.0,
                                 max_ops_per_tick=4,
                                 config=fast_test_config())
    futs = [svc.kput(0, f"k{i}", b"v") for i in range(4)]  # = max_k
    runtime.run_for(0.01)  # far less than the 10s tick
    assert all(f.done and f.value[0] == "ok" for f in futs), \
        "burst did not trigger an early flush"
    # a burst DEEPER than max_k drains fully too (chained kicks)
    deep = [svc.kput(0, f"d{i}", b"v") for i in range(11)]
    runtime.run_for(0.01)
    assert all(f.done and f.value[0] == "ok" for f in deep), \
        "multi-launch burst left a residue waiting for the tick"
    # below the threshold: ops wait for the (huge) tick — still queued
    f = svc.kput(1, "x", b"v")
    runtime.run_for(0.01)
    assert not f.done


def test_service_leader_watchers():
    """watch_leader (the scale-path watch_leader_status,
    peer.erl:212-218): fires on election-driven changes and
    membership-driven depositions; watcher exceptions are contained."""
    runtime, svc = make_service(n_ens=2, n_peers=3)
    events = []
    svc.watch_leader(0, lambda e, old, new: events.append((e, old, new)))
    svc.watch_leader(0, lambda e, old, new: 1 / 0)  # hostile watcher
    # registration notifies the CURRENT status immediately
    assert events == [(0, -1, -1)]

    assert settle(runtime, svc.kput(0, "k", b"v"))[0] == "ok"
    assert events[1] == (0, -1, int(svc.leader_np[0]))

    # leader dies -> next flush elects a new one -> watcher fires
    old_leader = int(svc.leader_np[0])
    svc.set_peer_up(0, old_leader, False)
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"v")
    assert events[-1][1] == old_leader
    assert events[-1][2] == int(svc.leader_np[0]) != old_leader

    # membership change that drops the leader deposes it (-1) before
    # the re-election flush.  The returned peer needs one commit round
    # to adopt the current epoch (the following({commit, Fact})
    # catch-up) before it counts toward the collapse quorum.
    svc.set_peer_up(0, old_leader, True)
    assert settle(runtime, svc.kput(0, "k", b"v2"))[0] == "ok"
    n = len(events)
    nv = np.ones((2, 3), bool)
    nv[0, int(svc.leader_np[0])] = False
    sel = np.zeros(2, bool)
    sel[0] = True
    assert svc.update_members(sel, nv)[0]
    assert any(ev[2] == -1 for ev in events[n:])
    # other-ensemble watchers never fired (no watcher on ens 1)
    assert all(ev[0] == 0 for ev in events)

    # unwatch: no further events after deregistration
    fn = svc._leader_watchers[0][0]
    assert svc.unwatch_leader(0, fn)
    assert not svc.unwatch_leader(0, fn)   # idempotent: already gone
    n2 = len(events)
    assert settle(runtime, svc.kget(0, "k"))[0] == "ok"  # re-elects
    assert len(events) == n2
    svc.stop()


def test_service_kput_once():
    """do_kput_once (peer.erl:278-284): create-if-missing through the
    (0,0) CAS — commits on absence or tombstone, rejects existing."""
    runtime, svc = make_service(n_ens=1, n_peers=3, n_slots=4)
    r = settle(runtime, svc.kput_once(0, "k", b"first"))
    assert r[0] == "ok"
    assert settle(runtime, svc.kput_once(0, "k", b"second")) == "failed"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"first")
    # over a tombstone it succeeds (the notfound-obj case)
    assert settle(runtime, svc.kdelete(0, "k"))[0] == "ok"
    r = settle(runtime, svc.kput_once(0, "k", b"third"))
    assert r[0] == "ok"
    assert settle(runtime, svc.kget(0, "k")) == ("ok", b"third")
    svc.stop()


def test_service_scrub_heals_cold_slot_damage():
    """scrub(): damage on a slot NO read ever touches is found by the
    full verify sweep and healed by the exchange — the AAE-cadence
    maintenance surface."""
    runtime, svc = make_service(n_ens=4, n_peers=3)
    for e in range(4):
        assert settle(runtime, svc.kput(e, "cold", b"c%d" % e))[0] == "ok"
        assert settle(runtime, svc.kput(e, "hot", b"h%d" % e))[0] == "ok"
    # damage the COLD slot's object on a minority replica + an upper
    # tree node on another — nothing reads them again before scrub
    s_cold = svc.key_slot[2]["cold"]
    svc.state = svc.state._replace(
        obj_val=svc.state.obj_val.at[2, 1, s_cold].set(123456))
    import jax.numpy as jnp
    svc.state = svc.state._replace(
        tree_node=svc.state.tree_node.at[3, 2, 0, :].set(
            jnp.uint32(0xBAD)))

    rep = svc.scrub()
    assert rep["replicas_damaged"] >= 2
    assert rep["replicas_healed"] == rep["replicas_damaged"]
    assert rep["ensembles_swept"] >= 2
    # clean now: a second scrub finds nothing, data intact
    assert svc.scrub() == {"replicas_damaged": 0,
                           "replicas_healed": 0, "ensembles_swept": 0}
    for e in range(4):
        assert settle(runtime, svc.kget(e, "cold")) == ("ok", b"c%d" % e)
        assert settle(runtime, svc.kget(e, "hot")) == ("ok", b"h%d" % e)
    svc.stop()


def test_periodic_scrub_cadence():
    """scrub_every_flushes: cold-slot damage heals without any
    explicit scrub call — the tick-driven AAE analog."""
    from riak_ensemble_tpu.config import fast_test_config
    from riak_ensemble_tpu.runtime import Runtime
    runtime = Runtime(seed=52)
    svc = BatchedEnsembleService(runtime, 2, 3, 8, tick=0.005,
                                 config=fast_test_config(),
                                 scrub_every_flushes=3)
    assert settle(runtime, svc.kput(0, "cold", b"c"))[0] == "ok"
    s = svc.key_slot[0]["cold"]
    svc.state = svc.state._replace(
        obj_val=svc.state.obj_val.at[0, 1, s].set(777777))
    # traffic on the OTHER ensemble drives flushes past the cadence
    for i in range(8):
        assert settle(runtime, svc.kput(1, f"k{i}", b"v"))[0] == "ok"
    from riak_ensemble_tpu.ops import engine as eng
    node_bad, leaf_bad = eng.verify_trees(svc.state)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())
    assert svc.repairs >= 1 or svc.corruptions >= 1
    assert settle(runtime, svc.kget(0, "cold")) == ("ok", b"c")
    svc.stop()


def test_restore_rebuilds_trees_on_hash_format_change(tmp_path):
    """Hash-format migration (round-5 ADVICE): a checkpoint written
    under a different device fold persists tree_leaf/tree_node that
    mismatch the running code's hashes.  Restore must detect the
    stamped format and rebuild every replica tree from the object
    store — otherwise _verify_path fails on every slot and reads of
    committed data fail cluster-wide (docs/MIGRATION.md)."""
    import pickle

    import jax.numpy as jnp

    from riak_ensemble_tpu import save as savelib
    from riak_ensemble_tpu.ops import hash as hashk

    runtime, svc = make_service(n_ens=2, n_peers=3, n_slots=4)
    for e in range(2):
        assert settle(runtime, svc.kput(e, "k", b"v%d" % e))[0] == "ok"
    # Simulate an image written under a different fold: scramble the
    # device trees in place, then checkpoint them verbatim.
    svc.state = svc.state._replace(
        tree_leaf=svc.state.tree_leaf ^ jnp.uint32(0xDEADBEEF),
        tree_node=svc.state.tree_node ^ jnp.uint32(0x0BADF00D))
    svc.save(str(tmp_path / "c"))
    svc.stop()

    d = tmp_path / "c"
    n = int(savelib.read(str(d / "CURRENT")).decode())
    host_path = str(d / f"ckpt.{n}" / "host")
    host = pickle.loads(savelib.read(host_path))
    assert host["hash_format"] == hashk.HASH_FORMAT

    # Control: format matches -> trees restored verbatim (scrambled),
    # committed reads do NOT come back ok (the read either fails or
    # retries past the budget — both prove the stale trees poison it).
    rt_bad = Runtime(seed=11)
    svc_bad = BatchedEnsembleService.restore(
        rt_bad, str(d), tick=0.005, config=fast_test_config())
    try:
        r = settle(rt_bad, svc_bad.kget(0, "k"), timeout=1.0)
        assert r != ("ok", b"v0"), r
    except TimeoutError:
        pass
    svc_bad.stop()

    # Stamp the old format: restore must rebuild and serve.
    host["hash_format"] = 2
    savelib.write(host_path, pickle.dumps(host, protocol=4))
    rt2 = Runtime(seed=12)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(d), tick=0.005, config=fast_test_config())
    for e in range(2):
        assert settle(rt2, svc2.kget(e, "k")) == ("ok", b"v%d" % e)
    assert settle(rt2, svc2.kput(0, "k", b"post"))[0] == "ok"
    assert settle(rt2, svc2.kget(0, "k")) == ("ok", b"post")
