"""Device-side integrity: the Merkle tree on the engine data path.

The reference's defining safety property is that the synctree gates
every K/V read and write (tree-is-truth, synctree.erl:44-73;
do_get_fsm/do_put_fsm tree reads, peer.erl:1370-1377; put_obj hash
updates, :1669-1698).  These tests drive the batched engine's form of
that property: corruption injected into a replica's object store or
tree is detected on device (``KvResult.tree_corrupt``), excluded from
read quorums, and healed by read repair / rebuild / exchange.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.ops import hash as hashk
from riak_ensemble_tpu.parallel.mesh import ShardedEngine, make_mesh

E, M, S = 4, 5, 16


def all_up():
    return jnp.ones((E, M), bool)


def elect_all(state, up=None):
    up = all_up() if up is None else up
    return eng.elect_step(
        state, jnp.ones((E,), bool), jnp.zeros((E,), jnp.int32), up)


def _put(st, slots, vals, up=None, lease=True):
    up = all_up() if up is None else up
    return eng.kv_step(
        st, jnp.full((E,), eng.OP_PUT, jnp.int32),
        jnp.asarray(slots, jnp.int32), jnp.asarray(vals, jnp.int32),
        jnp.full((E,), lease, bool), up)


def _get(st, slots, up=None, lease=True):
    up = all_up() if up is None else up
    return eng.kv_step(
        st, jnp.full((E,), eng.OP_GET, jnp.int32),
        jnp.asarray(slots, jnp.int32), jnp.zeros((E,), jnp.int32),
        jnp.full((E,), lease, bool), up)


def _seeded(slot=3, vals=(10, 20, 30, 40)):
    st, _ = elect_all(eng.init_state(E, M, S))
    st, res = _put(st, [slot] * E, list(vals))
    assert bool(res.committed.all())
    return st


def _corrupt_obj(st, peer, slot, val=999):
    """Flip a replica's stored object out-of-band (synctree_intercepts
    corrupt_segment analog): the tree leaf now disagrees."""
    return st._replace(obj_val=st.obj_val.at[:, peer, slot].set(val))


def _corrupt_node(st, peer, node=0):
    """Damage an upper tree node (corrupt_upper analog)."""
    return st._replace(
        tree_node=st.tree_node.at[:, peer, node, 0].set(jnp.uint32(0xDEAD)))


def test_write_maintains_tree():
    """Every committed put leaves leaf+path consistent (the
    always-up-to-date property, synctree.erl:44-73)."""
    st = _seeded()
    node_bad, leaf_bad = eng.verify_trees(st)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_corrupt_replica_detected_and_excluded():
    """A replica whose object diverges from its tree leaf fails the
    integrity gate; the read excludes it and serves the committed
    value (get_latest_obj hash extra-check, peer.erl:1646-1649)."""
    st = _corrupt_obj(_seeded(), peer=2, slot=3)
    _, leaf_bad = eng.verify_trees(st)
    assert bool(np.asarray(leaf_bad)[:, 2].all())
    st2, res = _get(st, [3] * E)
    assert bool(res.get_ok.all()) and bool(res.found.all())
    np.testing.assert_array_equal(res.value, [10, 20, 30, 40])
    # Detection surfaced to the host for exactly the corrupt replica.
    tc = np.asarray(res.tree_corrupt)
    assert tc[:, 2].all() and not tc[:, [0, 1, 3, 4]].any()


def test_read_repair_heals_corrupt_replica():
    """The same read that detects the corruption repairs it
    (maybe_repair, peer.erl:1518-1536): the replica re-adopts the
    winning version and its hash path is recomputed."""
    st = _corrupt_obj(_seeded(), peer=2, slot=3)
    st2, res = _get(st, [3] * E)
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, 2, 3],
                                  [10, 20, 30, 40])
    node_bad, leaf_bad = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())
    # Second read: clean, no corruption reported.
    _, res = _get(st2, [3] * E)
    assert not bool(np.asarray(res.tree_corrupt).any())


def test_corrupt_leader_replica_healed_from_followers():
    st = _corrupt_obj(_seeded(), peer=0, slot=3)  # leader is peer 0
    st2, res = _get(st, [3] * E)
    assert bool(res.get_ok.all())
    np.testing.assert_array_equal(res.value, [10, 20, 30, 40])
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, 0, 3],
                                  [10, 20, 30, 40])


def test_upper_node_corruption_detected_and_healed_on_access():
    """Damage to an inner tree node fails path verification on reads
    through it ({corrupted, Level, Bucket}, synctree.erl:322-340); the
    repair write recomputes the path, healing the node."""
    st = _corrupt_node(_seeded(), peer=1)
    node_bad, _ = eng.verify_trees(st)
    assert bool(np.asarray(node_bad)[:, 1].all())
    st2, res = _get(st, [3] * E)
    tc = np.asarray(res.tree_corrupt)
    assert tc[:, 1].all() and not tc[:, [0, 2, 3, 4]].any()
    assert bool(res.get_ok.all())
    np.testing.assert_array_equal(res.value, [10, 20, 30, 40])
    node_bad, leaf_bad = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_rebuild_trees_repairs_without_access():
    """Host-driven repair (peer_tree do_repair analog): rebuild flagged
    replicas' trees from their object stores."""
    st = _corrupt_node(_seeded(), peer=4)
    node_bad, _ = eng.verify_trees(st)
    st2 = eng.rebuild_trees(st, node_bad)
    node_bad2, leaf_bad2 = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad2).any())
    assert not bool(np.asarray(leaf_bad2).any())


def test_put_while_replica_corrupt_still_commits_and_heals_slot():
    """A put through a corrupt-slot replica overwrites the slot and its
    hash path — the write path never consults the stale object."""
    st = _corrupt_obj(_seeded(), peer=3, slot=3)
    st2, res = _put(st, [3] * E, [77] * E)
    assert bool(res.committed.all())
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, 3, 3], 77)
    node_bad, leaf_bad = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_read_repair_heals_lagging_replica():
    """drop_write analog: a replica that missed a committed write is
    healed by the next read (read_until, test/drop_write_test.erl)."""
    st = _seeded()
    # Age peer 1's replica (simulates a dropped backend write).
    st = st._replace(
        obj_seq=st.obj_seq.at[:, 1, 3].set(0),
        obj_val=st.obj_val.at[:, 1, 3].set(0),
        tree_leaf=st.tree_leaf.at[:, 1, 3].set(
            hashk.obj_leaf_hash(jnp.uint32(0), jnp.uint32(0),
                                jnp.uint32(0))))
    st = eng.rebuild_trees(st, jnp.asarray(np.eye(1, M, 1, dtype=bool)
                                           .repeat(E, 0)))
    st2, res = _get(st, [3] * E)
    assert bool(res.get_ok.all())
    np.testing.assert_array_equal(res.value, [10, 20, 30, 40])
    # The lagging replica adopted the winner (same version, no seq bump).
    np.testing.assert_array_equal(np.asarray(st2.obj_seq)[:, 1, 3], 1)
    assert not bool(res.committed.any())


def test_notfound_tombstone_when_member_unreachable():
    """all_or_quorum (msg.erl:282-317): a notfound read with every
    member responding serves without writing; with a member down it
    must commit a tombstone at the current epoch (peer.erl:1568-1584).
    """
    st, _ = elect_all(eng.init_state(E, M, S))
    st, res = _get(st, [5] * E)  # all members up: plain notfound
    assert bool(res.get_ok.all()) and not bool(res.found.any())
    assert not bool(res.committed.any())
    assert bool(np.asarray(st.obj_seq_ctr == 0).all())
    # Peer 4 down: tombstone commits (seq consumed).
    up = jnp.asarray(np.array([[1, 1, 1, 1, 0]] * E, dtype=bool))
    st2, res = _get(st, [5] * E, up=up)
    assert bool(res.get_ok.all()) and not bool(res.found.any())
    assert bool(res.committed.all())
    np.testing.assert_array_equal(np.asarray(st2.obj_seq_ctr), 1)
    # The tombstone replicated to reachable members with a hash update.
    np.testing.assert_array_equal(np.asarray(st2.obj_seq)[:, :4, 5], 1)
    node_bad, leaf_bad = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_exchange_converges_divergent_replicas():
    """Anti-entropy sweep (riak_ensemble_exchange analog): divergent
    and corrupt replicas adopt the newest hash-valid object per slot,
    trees rebuilt, divergence reported."""
    st = _seeded(slot=2, vals=(5, 6, 7, 8))
    st, _ = _put(st, [9] * E, [50] * E)
    # Peer 3 misses slot 9 entirely; peer 1 has a corrupt slot 2.
    st = st._replace(
        obj_seq=st.obj_seq.at[:, 3, 9].set(0),
        obj_epoch=st.obj_epoch.at[:, 3, 9].set(0),
        obj_val=st.obj_val.at[:, 3, 9].set(0))
    st = eng.rebuild_trees(
        st, jnp.asarray(np.eye(1, M, 3, dtype=bool).repeat(E, 0)))
    st = _corrupt_obj(st, peer=1, slot=2, val=666)
    st2, diverged, synced = eng.exchange_step(
        st, jnp.ones((E,), bool), all_up())
    assert bool(np.asarray(synced).all())
    dv = np.asarray(diverged)
    assert dv[:, 3].all() and dv[:, 1].all()
    assert not dv[:, [0, 2, 4]].any()
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, 3, 9], 50)
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, 1, 2],
                                  [5, 6, 7, 8])
    node_bad, leaf_bad = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_tombstone_reads_back_as_notfound():
    """The committed tombstone is a versioned object but stays
    client-invisible: later reads (all members back up) return
    notfound, not value 0."""
    st, _ = elect_all(eng.init_state(E, M, S))
    up = jnp.asarray(np.array([[1, 1, 1, 1, 0]] * E, dtype=bool))
    st, res = _get(st, [5] * E, up=up)
    assert bool(res.committed.all())          # tombstone committed
    st, res = _get(st, [5] * E)               # all up again
    assert bool(res.get_ok.all())
    assert not bool(res.found.any())
    assert not bool(res.committed.any())      # no second tombstone
    np.testing.assert_array_equal(res.value, 0)
    # A real put over the tombstone resurrects the key.
    st, res = _put(st, [5] * E, [11] * E)
    assert bool(res.committed.all())
    st, res = _get(st, [5] * E)
    assert bool(res.found.all())
    np.testing.assert_array_equal(res.value, 11)


def test_stale_tombstone_rewritten_at_current_epoch():
    """update_key applies to tombstones too: a tombstone from an old
    epoch is re-committed at the current one, still notfound."""
    st, _ = elect_all(eng.init_state(E, M, S))
    up = jnp.asarray(np.array([[1, 1, 1, 1, 0]] * E, dtype=bool))
    st, res = _get(st, [5] * E, up=up)        # epoch-1 tombstone
    st, _ = elect_all(st)                     # epoch 2
    st, res = _get(st, [5] * E)
    assert bool(res.committed.all())          # rewrite of the tombstone
    assert not bool(res.found.any())
    np.testing.assert_array_equal(np.asarray(st.obj_epoch)[:, :, 5], 2)


def test_exchange_preserves_data_when_no_valid_holder():
    """Exchange must never erase data it cannot replace: with every
    copy's tree upper levels corrupted (objects intact), the objects
    survive and the trees are rebuilt."""
    st = _seeded()
    for p in range(M):
        st = _corrupt_node(st, peer=p)
    st2, diverged, synced = eng.exchange_step(
        st, jnp.ones((E,), bool), all_up())
    assert bool(np.asarray(synced).all())
    # Objects intact, trees healed.
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, :, 3].T,
                                  np.tile([10, 20, 30, 40], (M, 1)))
    node_bad, leaf_bad = eng.verify_trees(st2)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_exchange_leaves_unreplaceable_slot_flagged():
    """A slot whose every copy is leaf-invalid has no valid winner:
    exchange leaves it (and its mismatched leaf) alone rather than
    blessing or erasing the data."""
    st = _seeded()
    for p in range(M):
        st = _corrupt_obj(st, peer=p, slot=3, val=600 + p)
    st2, diverged, synced = eng.exchange_step(
        st, jnp.ones((E,), bool), all_up())
    assert bool(np.asarray(synced).all())
    assert bool(np.asarray(diverged).all())
    # Data untouched, leaves still mismatched (replicas stay excluded).
    np.testing.assert_array_equal(
        np.asarray(st2.obj_val)[:, :, 3],
        np.tile(600 + np.arange(M), (E, 1)))
    _, leaf_bad = eng.verify_trees(st2)
    assert bool(np.asarray(leaf_bad).all())


def test_get_never_tombstones_over_integrity_excluded_data():
    """A GET whose integrity gate excluded the holders of a committed
    object must FAIL, not fabricate a quorum-committed notfound
    tombstone over the (recoverable) data."""
    st = _seeded()
    for p in range(M):
        st = _corrupt_obj(st, peer=p, slot=3, val=600 + p)
    st2, res = _get(st, [3] * E)
    assert not bool(res.get_ok.any())        # read errors, not notfound
    assert not bool(res.committed.any())     # and writes nothing
    np.testing.assert_array_equal(
        np.asarray(st2.obj_val)[:, :, 3],
        np.tile(600 + np.arange(M), (E, 1)))
    # Corruption surfaced for the host to run repair/exchange.
    assert bool(np.asarray(res.tree_corrupt).all())


def test_exchange_requires_majority():
    st = _seeded()
    up = jnp.asarray(np.array([[1, 1, 0, 0, 0]] * E, dtype=bool))
    st2, _, synced = eng.exchange_step(st, jnp.ones((E,), bool), up)
    assert not bool(np.asarray(synced).any())
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_exchange_ignores_invalid_newer_object():
    """valid_obj_hash gate (exchange.erl:91-96): a hash-invalid object
    must not win the exchange even if its version looks newest."""
    st = _seeded()
    # Fabricate a "newer" object on peer 2 without a matching leaf.
    st = st._replace(
        obj_epoch=st.obj_epoch.at[:, 2, 3].set(9),
        obj_seq=st.obj_seq.at[:, 2, 3].set(9),
        obj_val=st.obj_val.at[:, 2, 3].set(123))
    st2, diverged, synced = eng.exchange_step(
        st, jnp.ones((E,), bool), all_up())
    assert bool(np.asarray(synced).all())
    # The forged object lost to the committed one and was overwritten.
    np.testing.assert_array_equal(np.asarray(st2.obj_val)[:, 2, 3],
                                  [10, 20, 30, 40])
    np.testing.assert_array_equal(np.asarray(st2.obj_epoch)[:, 2, 3], 1)


def test_tree_sizes_layout():
    assert eng.tree_sizes(16) == (1,)
    assert eng.tree_sizes(128) == (8, 1)
    assert eng.tree_sizes(256) == (16, 1)
    assert eng.tree_sizes(4096) == (256, 16, 1)
    assert eng.tree_sizes(1) == (1,)


@pytest.mark.parametrize("s", [8, 16, 60, 128])
def test_tree_consistency_across_shapes(s):
    """build/update/verify agree for non-power-of-16 slot counts."""
    st, _ = eng.elect_step(
        eng.init_state(2, 3, s), jnp.ones((2,), bool),
        jnp.zeros((2,), jnp.int32), jnp.ones((2, 3), bool))
    for slot in [0, s // 2, s - 1]:
        st, res = eng.kv_step(
            st, jnp.full((2,), eng.OP_PUT, jnp.int32),
            jnp.full((2,), slot, jnp.int32),
            jnp.full((2,), slot + 1, jnp.int32),
            jnp.ones((2,), bool), jnp.ones((2, 3), bool))
        assert bool(res.committed.all())
    node_bad, leaf_bad = eng.verify_trees(st)
    assert not bool(np.asarray(node_bad).any())
    assert not bool(np.asarray(leaf_bad).any())


def test_sharded_integrity_matches_single_device():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    e, m, s = 8, 8, 16
    mesh = make_mesh(4, 2)
    se = ShardedEngine(mesh)
    views = [list(range(5))]

    def drive(elect_fn, kv_fn, exchange_fn, verify_fn, state):
        up = jnp.ones((e, m), bool)
        state, won = elect_fn(state, jnp.ones((e,), bool),
                              jnp.zeros((e,), jnp.int32), up)
        k = 2
        kind = jnp.full((k, e), eng.OP_PUT, jnp.int32)
        slot = jnp.broadcast_to(jnp.asarray([3, 7], jnp.int32)[:, None],
                                (k, e))
        val = jnp.asarray(np.arange(k * e).reshape(k, e) + 1, jnp.int32)
        lease = jnp.ones((k, e), bool)
        state, res = kv_fn(state, kind, slot, val, lease, up)
        # Diverge a replica, then exchange.
        state = state._replace(obj_val=state.obj_val.at[:, 1, 3].set(999))
        state, diverged, synced = exchange_fn(
            state, jnp.ones((e,), bool), up)
        nb, lb = verify_fn(state)
        return won, res, diverged, synced, nb, lb, state

    out_single = drive(eng.elect_step, eng.kv_step_scan, eng.exchange_step,
                       eng.verify_trees,
                       eng.init_state(e, m, s, views=views))
    out_sharded = drive(se.elect_step, se.kv_step_scan, se.exchange_step,
                        se.verify_trees,
                        se.init_state(e, m, s, views=views))
    for a, b in zip(jax.tree.leaves(out_single),
                    jax.tree.leaves(out_sharded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    won, res, diverged, synced, nb, lb, state = out_single
    assert bool(np.asarray(won).all())
    assert bool(np.asarray(res.committed).all())
    dv = np.asarray(diverged)
    assert dv[:, 1].all() and not dv[:, 0].any()
    assert not bool(np.asarray(nb).any()) and not bool(np.asarray(lb).any())
