"""Cluster-management layer: enable/join/remove, gossip convergence,
root-ensemble ops, ensemble creation, client API routing.

Covers riak_ensemble_manager/root/state semantics (SURVEY §2.4-2.5):
activation creates the root ensemble (manager.erl:498-516), join pulls
and adopts remote state then writes membership through the root
ensemble (manager.erl:311-334, root.erl:123-130), gossip spreads
cluster state with newest-vsn-wins merge (riak_ensemble_state.erl:
171-211), state_changed starts/stops local peers (manager.erl:610-641),
and the client API routes through the router pool to the leader
(client.erl, router.erl).
"""

import pytest

from riak_ensemble_tpu import state as statelib
from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import NOTFOUND, EnsembleInfo, PeerId


# ---------------------------------------------------------------------------
# pure cluster-state unit tests (riak_ensemble_state.erl semantics)


def test_state_vsn_guards():
    cs = statelib.new_state("cid")
    cs = statelib.add_member((0, 0), "n1", cs)
    assert cs is not None and cs.members == {"n1"}
    # same vsn rejected (strictly-newer-wins, state.erl:213-219)
    assert statelib.add_member((0, 0), "n2", cs) is None
    cs2 = statelib.add_member((0, 1), "n2", cs)
    assert cs2.members == {"n1", "n2"}
    cs3 = statelib.del_member((1, 0), "n1", cs2)
    assert cs3.members == {"n2"}


def test_state_ensemble_guards():
    cs = statelib.new_state("cid")
    info = EnsembleInfo(vsn=(0, 0), leader=None, views=(), seq=(0, 0))
    cs = statelib.set_ensemble("e1", info, cs)
    assert cs is not None
    # update_ensemble on unknown ensemble errors (state.erl:149-150)
    assert statelib.update_ensemble((1, 0), "nope", None, (), cs) is None
    p = PeerId(0, "n1")
    cs2 = statelib.update_ensemble((1, 0), "e1", p, ((p,),), cs)
    assert cs2.ensembles["e1"].leader == p
    # stale update rejected
    assert statelib.update_ensemble((0, 5), "e1", None, (), cs2) is None


def test_state_merge_newest_wins():
    a = statelib.new_state("cid")
    a = statelib.enable(a)
    a = statelib.add_member((0, 0), "n1", a)
    b = statelib.add_member((0, 1), "n2", a)
    merged = statelib.merge(a, b)
    assert merged.members == {"n1", "n2"}
    # foreign cluster id ignored once enabled (state.erl:172-174)
    foreign = statelib.add_member((9, 9), "evil", statelib.new_state("x"))
    assert statelib.merge(a, foreign).members == a.members


# ---------------------------------------------------------------------------
# full-stack manager tests


def test_enable_creates_root_ensemble():
    mc = ManagedCluster(seed=10, nodes=("node0",))
    mc.enable("node0")
    root_leader = mc.leader_id("root")
    assert root_leader == PeerId("root", "node0")
    assert mc.mgr("node0").enabled()
    # double-enable errors (manager.erl:296-310)
    assert mc.mgr("node0").enable() == "error"


def test_client_kv_through_root():
    mc = ManagedCluster(seed=11, nodes=("node0",))
    mc.enable("node0")
    c = mc.client("node0")
    r = c.kover("root", "k", b"v")
    assert r[0] == "ok"
    r = c.kget("root", "k")
    assert r[0] == "ok" and r[1].value == b"v"


def test_client_unavailable_when_disabled():
    mc = ManagedCluster(seed=12, nodes=("node0",))
    c = mc.client("node0")
    assert c.kget("root", "k") == ("error", "unavailable")


def test_join_and_gossip_convergence():
    mc = ManagedCluster(seed=13, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    assert mc.mgr("node0").cluster() == ["node0", "node1", "node2"]
    # all managers converge on the same member set via gossip
    for n in ("node1", "node2"):
        assert mc.mgr(n).cluster() == ["node0", "node1", "node2"]


def test_join_guards():
    mc = ManagedCluster(seed=14, nodes=("node0", "node1"))
    # joining a non-enabled cluster fails (join_allowed,
    # manager.erl:518-532)
    fut = mc.mgr("node1").join_async("node0", timeout=5.0)
    result = mc.runtime.await_future(fut, timeout=10.0)
    assert result == ("error", "remote_not_enabled")
    # self-join rejected (manager.erl join/2 same-node clause)
    mc.enable("node0")
    fut = mc.mgr("node0").join_async("node0", timeout=5.0)
    assert mc.runtime.await_future(fut, 10.0) == ("error", "same_node")
    # two independently-enabled clusters cannot merge
    mc.enable("node1")
    fut = mc.mgr("node1").join_async("node0", timeout=5.0)
    assert mc.runtime.await_future(fut, 10.0) == ("error",
                                                  "already_enabled")


def test_create_ensemble_starts_peers_via_gossip():
    mc = ManagedCluster(seed=15, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")

    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("ens1", peers)
    leader = mc.wait_stable("ens1")
    assert leader in peers

    c = mc.client("node1")
    assert c.kover("ens1", "key", b"val")[0] == "ok"
    r = c.kget("ens1", "key")
    assert r[0] == "ok" and r[1].value == b"val"
    # reads routed from a non-member node work too
    r2 = mc.client("node2").kget("ens1", "key")
    assert r2[0] == "ok" and r2[1].value == b"val"


def test_root_expand_and_remove():
    """Grow the root ensemble across joined nodes, then remove a node
    (replace_members flavor through the management API)."""
    mc = ManagedCluster(seed=16, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")

    adds = [("add", PeerId("root", "node1")),
            ("add", PeerId("root", "node2"))]
    r = mc.update_members("root", adds)
    assert r == "ok", r

    def root_peers_started():
        return all(
            mc.runtime.whereis(("peer", "root", PeerId("root", n)))
            is not None or
            any(k[0] == "root" for k in mc.mgr(n).local_peers)
            for n in ("node1", "node2"))
    assert mc.runtime.run_until(root_peers_started, 60.0, poll=0.1)
    mc.wait_stable("root")

    # writes still work with the expanded root
    c = mc.client("node0")
    assert c.kover("root", "rk", b"rv")[0] == "ok"

    # remove node2 from the cluster membership
    mc.remove("node0", "node2")
    assert mc.runtime.run_until(
        lambda: "node2" not in mc.mgr("node0").cluster_state.members,
        30.0, poll=0.1)


def test_failover_with_managed_cluster():
    """Leader failure under the full stack: suspend the ensemble
    leader, client ops keep working after re-election."""
    mc = ManagedCluster(seed=17, nodes=("node0", "node1", "node2"))
    mc.enable("node0")
    mc.join("node1", "node0")
    mc.join("node2", "node0")
    peers = [PeerId(i, f"node{i}") for i in range(3)]
    mc.create_ensemble("ens1", peers)
    leader = mc.wait_stable("ens1")

    c = mc.client("node0")
    assert c.kover("ens1", "k", b"v1")[0] == "ok"

    mc.suspend_peer("ens1", leader)

    def new_leader():
        lid = mc.leader_id("ens1")
        return lid is not None and lid != leader
    assert mc.runtime.run_until(new_leader, 60.0)
    mc.wait_stable("ens1")

    def readable():
        r = mc.client("node1").kget("ens1", "k", timeout=5.0)
        return r[0] == "ok" and r[1].value == b"v1"
    assert mc.runtime.run_until(readable, 60.0, poll=0.5)


def test_crashed_local_peer_restarted_by_reconciliation():
    """The peer-supervisor role (riak_ensemble_peer_sup, restarted by
    manager state_changed/check_peers, manager.erl:610-641,697-715):
    a local peer actor that dies is restarted by the manager's
    reconciliation pass, reloads its fact, re-probes, and the ensemble
    keeps serving."""
    from riak_ensemble_tpu.peer import peer_name
    from riak_ensemble_tpu.testing import ManagedCluster
    from riak_ensemble_tpu.types import PeerId

    mc = ManagedCluster(seed=23)
    mc.ens_start(3)
    assert mc.kput("k", b"v")[0] == "ok"

    victim = PeerId(2, mc.node0)
    name = peer_name("root", victim)
    assert mc.runtime.whereis(name) is not None
    mc.runtime.stop_actor(name)  # crash (no clean shutdown)
    assert mc.runtime.whereis(name) is None

    # Reconciliation notices wanted-but-missing and restarts it.
    assert mc.runtime.run_until(
        lambda: mc.runtime.whereis(name) is not None, 30.0), \
        "manager never restarted the crashed peer"
    mc.wait_stable("root")
    r = mc.kget("k")
    assert r[0] == "ok" and r[1].value == b"v"
    assert mc.kput("k", b"v2")[0] == "ok"


def test_crashed_leader_restarted_and_ensemble_recovers():
    """Crashing the LEADER actor: remaining peers elect (follower
    timeout -> probe -> election), reconciliation restarts the dead
    actor from its persisted fact, and it rejoins without clobbering
    the new leadership (restart -> reload_fact -> probe,
    peer.erl:2185-2195, 1842-1860)."""
    from riak_ensemble_tpu.peer import peer_name
    from riak_ensemble_tpu.testing import ManagedCluster

    mc = ManagedCluster(seed=29)
    mc.ens_start(3)
    assert mc.kput("k", b"v")[0] == "ok"
    leader = mc.wait_leader("root")
    name = peer_name("root", leader)
    mc.runtime.stop_actor(name)

    assert mc.runtime.run_until(
        lambda: mc.runtime.whereis(name) is not None, 30.0)
    assert mc.wait_stable("root") is not None
    r = mc.kget("k")
    assert r[0] == "ok" and r[1].value == b"v", r
    assert mc.kput("k", b"v2")[0] == "ok"
