"""Lease-protected read fast path (batched_host, ARCHITECTURE §9).

Unit coverage for the read router: a kget/kget_vsn/kget_many of a
keyed slot serves from the leader's committed host mirror — no OP_GET
row, no flush — iff the lease is margin-valid, the slot has no
queued/in-flight write, the row has a live leader and is not
corruption-flagged.  Every miss reason is pinned, visibility
(mirror-update-before-ack ⇒ read-your-acked-writes) is exercised
across pipeline depth 2 and RMW inline slots, and the replication
group's leader-only / host-lease / depose-invalidation gates are
covered without sockets.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import funref  # noqa: E402
from riak_ensemble_tpu.config import Config, fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def make(n_ens=4, n_peers=3, seed=7, **kw):
    runtime = Runtime(seed=seed)
    svc = BatchedEnsembleService(runtime, n_ens, n_peers, n_slots=8,
                                 tick=None, max_ops_per_tick=8,
                                 config=fast_test_config(), **kw)
    return runtime, svc


def settle(runtime, svc, fut):
    for _ in range(30):
        if fut.done:
            return fut.value
        svc.flush()
        runtime.run_for(0.001)
    raise AssertionError("future never resolved")


def test_hit_after_committed_write():
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v1"))[0] == "ok"
    g = svc.kget(0, "a")
    assert g.done and g.value == ("ok", b"v1")
    assert svc.read_fastpath_hits == 1
    assert svc.read_fastpath_misses == 0
    # kget_vsn hits too, with the committed version a CAS accepts
    gv = svc.kget_vsn(0, "a")
    assert gv.done and gv.value[:2] == ("ok", b"v1")
    vsn = gv.value[2]
    assert settle(runtime, svc,
                  svc.kupdate(0, "a", vsn, b"v2"))[0] == "ok"
    assert svc.kget(0, "a").value == ("ok", b"v2")


def test_pending_write_gate_and_read_your_acked_writes():
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v1"))[0] == "ok"
    p = svc.kput(0, "a", b"v2")
    # a read racing a queued write must NOT serve the mirror — it
    # falls back to the device round and orders after the write
    g = svc.kget(0, "a")
    assert not g.done
    assert svc.read_fastpath_miss_reasons["pending_write"] == 1
    settle(runtime, svc, g)
    assert p.value[0] == "ok" and g.value == ("ok", b"v2")
    # after the ack the mirror already carries the write: fast hit
    g2 = svc.kget(0, "a")
    assert g2.done and g2.value == ("ok", b"v2")


@pytest.mark.parametrize("depth", [1, 2])
def test_ack_waiter_sees_write_immediately(depth):
    """The mirror updates BEFORE the write future resolves, so a read
    issued from inside the ack waiter observes the write — including
    across the depth-2 launch pipeline's late resolve."""
    runtime, svc = make(pipeline_depth=depth)
    assert settle(runtime, svc, svc.kput(0, "a", b"v0"))[0] == "ok"
    seen = []

    def on_ack(_r):
        f = svc.kget(0, "a")
        seen.append((f.done, f.value if f.done else None))
    p = svc.kput(0, "a", b"v1")
    p.add_waiter(on_ack)
    settle(runtime, svc, p)
    assert p.value[0] == "ok"
    (done, value), = seen
    # fast hit (no pending write left, mirror fresh) with the value
    assert done and value == ("ok", b"v1")


def test_lease_expiry_and_margin_misses():
    runtime, svc = make()
    cfg = svc.config
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    assert svc.kget(0, "a").done
    # jump INSIDE the safety margin: lease not lapsed, but a correct
    # margin check refuses (the clock-skew guard)
    horizon = float(svc.lease_until[0]) - runtime.now
    runtime.run_for(horizon - cfg.read_margin() * 0.5)
    g = svc.kget(0, "a")
    assert not g.done
    assert svc.read_fastpath_miss_reasons["no_lease"] == 1
    settle(runtime, svc, g)  # the device round renews the lease
    assert g.value == ("ok", b"v")
    assert svc.kget(0, "a").done  # leased again
    # and a full lapse misses as well
    runtime.run_for(cfg.lease() * 3)
    assert not svc.kget(0, "a").done
    assert svc.read_fastpath_miss_reasons["no_lease"] == 2


def test_leader_down_then_reelection_revalidates_vsn_mirror():
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    lead = int(svc.leader_np[0])
    svc.set_peer_up(0, lead, False)
    g = svc.kget(0, "a")
    assert not g.done  # electing rows never serve
    assert svc.read_fastpath_miss_reasons["no_leader"] == 1
    settle(runtime, svc, g)  # election folds into this flush; the
    assert g.value == ("ok", b"v")  # same-launch read re-mirrors "a"
    assert int(svc.leader_np[0]) != lead
    # force ANOTHER election with no covering read of "a": the won
    # election must invalidate the row's vsn mirror (the epoch bump
    # re-versions objects lazily — a mirrored token would go stale)
    svc.set_peer_up(0, lead, True)
    svc.set_peer_up(0, int(svc.leader_np[0]), False)
    settle(runtime, svc, svc.kput(0, "other", b"x"))
    gv = svc.kget_vsn(0, "a")
    assert not gv.done
    assert svc.read_fastpath_miss_reasons["vsn_unmirrored"] == 1
    settle(runtime, svc, gv)  # device read re-mirrors the REWRITTEN
    gv2 = svc.kget_vsn(0, "a")  # version...
    assert gv2.done and gv2.value == gv.value
    # ...and the re-mirrored vsn is a live CAS token
    assert settle(runtime, svc, svc.kupdate(
        0, "a", gv2.value[2], b"v2"))[0] == "ok"
    # plain value reads stay fast throughout (the epoch rewrite
    # never changes values)
    assert svc.kget(0, "a").done


def test_inline_rmw_slots_serve_fast():
    runtime, svc = make()
    f = svc.kmodify(1, "ctr", funref.ref("rmw:add", 5), 0)
    settle(runtime, svc, f)
    assert f.value[0] == "ok"
    g = svc.kget(1, "ctr")
    assert g.done and g.value == ("ok", 5)
    gv = svc.kget_vsn(1, "ctr")
    assert gv.done and gv.value[1] == 5
    # fast answer == forced device answer
    svc.set_fast_reads(False)
    gd = svc.kget_vsn(1, "ctr")
    settle(runtime, svc, gd)
    assert gd.value == gv.value
    svc.set_fast_reads(True)
    # a put flips the slot back to handle storage; reads follow
    assert settle(runtime, svc, svc.kput(1, "ctr", b"blob"))[0] == "ok"
    assert svc.kget(1, "ctr").value == ("ok", b"blob")


def test_tombstone_reads_fast_with_real_vsn():
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    d = svc.kdelete(0, "a")
    settle(runtime, svc, d)
    assert d.value[0] == "ok"
    g = svc.kget(0, "a")
    # slot may already be recycled (then the key is unknown —
    # immediate NOTFOUND) or still mapped (fast tombstone read);
    # either way: NOTFOUND, no device round needed
    assert g.done and g.value == ("ok", NOTFOUND)


def test_corrupt_row_bypasses_fast_path():
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    assert svc.kget(0, "a").done
    svc._corrupt_rows[0] = True
    g = svc.kget(0, "a")
    assert not g.done
    assert svc.read_fastpath_miss_reasons["corrupt"] == 1
    settle(runtime, svc, g)
    assert g.value == ("ok", b"v")
    # other rows are unaffected
    assert settle(runtime, svc, svc.kput(1, "b", b"w"))[0] == "ok"
    assert svc.kget(1, "b").done


def test_corruption_detection_flags_and_exchange_clears():
    """Real in-round detection: damage a minority copy, force a
    device read; detection flags the row, the in-resolve exchange
    heals it and re-admits fast reads."""
    import jax.numpy as jnp

    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "k", b"v"))[0] == "ok"
    slot = svc.key_slot[0]["k"]
    svc.state = svc.state._replace(
        obj_val=svc.state.obj_val.at[0, 2, slot].set(424242))
    svc.lease_until[:] = 0.0  # force the device round
    g = svc.kget(0, "k")
    settle(runtime, svc, g)
    assert g.value == ("ok", b"v")
    assert svc.corruptions > 0
    # the exchange ran inside the same resolve and synced the row:
    # fast reads are re-admitted (lease renewed by that same flush)
    g2 = svc.kget(0, "k")
    assert g2.done and g2.value == ("ok", b"v")
    assert not svc._corrupt_rows.any()
    node_bad, leaf_bad = svc.engine.verify_trees(svc.state)
    assert not bool(jnp.asarray(node_bad).any())


def test_opt_outs():
    # programmatic
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    svc.set_fast_reads(False)
    g = svc.kget(0, "a")
    assert not g.done
    assert svc.read_fastpath_miss_reasons["disabled"] == 1
    settle(runtime, svc, g)
    svc.set_fast_reads(True)
    assert svc.kget(0, "a").done

    # config.trust_lease=False pins the path off even when enabled
    runtime2 = Runtime(seed=8)
    cfg = fast_test_config()
    cfg.trust_lease = False
    svc2 = BatchedEnsembleService(runtime2, 2, 3, n_slots=4,
                                  tick=None, config=cfg)
    assert settle(runtime2, svc2, svc2.kput(0, "a", b"v"))[0] == "ok"
    svc2.set_fast_reads(True)  # trust_lease overrides
    assert not svc2.kget(0, "a").done
    settle(runtime2, svc2, svc2.kget(0, "a"))


def test_env_opt_out(monkeypatch):
    monkeypatch.setenv("RETPU_FAST_READS", "0")
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    assert not svc.kget(0, "a").done
    assert svc.read_fastpath_miss_reasons["disabled"] == 1
    settle(runtime, svc, svc.kget(0, "a"))


def test_kget_many_mixed_fast_and_fallback():
    runtime, svc = make()
    r = settle(runtime, svc, svc.kput_many(
        0, ["a", "b"], [b"1", b"2"]))
    assert all(x[0] == "ok" for x in r)
    p = svc.kput(0, "b", b"2x")  # pending write parks only "b"
    m = svc.kget_many(0, ["a", "b", "zz"], want_vsn=True)
    assert not m.done  # "b" rides the round
    h0 = svc.read_fastpath_hits
    settle(runtime, svc, m)
    assert p.value[0] == "ok"
    assert m.value[0][:2] == ("ok", b"1")      # fast
    assert m.value[1][:2] == ("ok", b"2x")     # device, post-write
    assert m.value[2] == ("ok", NOTFOUND, (0, 0))  # unknown key
    assert svc.read_fastpath_hits == h0  # "a" counted at submit
    # order-preserving all-fast batch resolves synchronously
    m2 = svc.kget_many(0, ["b", "a"])
    assert m2.done and m2.value == [("ok", b"2x"), ("ok", b"1")]


def test_equivalence_random_ops_fast_vs_device():
    """After a random keyed workload, every key's fast answer equals
    its forced device-round answer (value AND version)."""
    rng = np.random.default_rng(42)
    runtime, svc = make(n_ens=3)
    keys = [f"k{i}" for i in range(4)]
    for _ in range(30):
        e = int(rng.integers(3))
        key = keys[int(rng.integers(4))]
        r = rng.random()
        if r < 0.5:
            fut = svc.kput(e, key, b"v%d" % int(rng.integers(1e6)))
        elif r < 0.7:
            fut = svc.kmodify(e, f"c{key}",
                              funref.ref("rmw:add", 3), 0)
        elif r < 0.85:
            fut = svc.kdelete(e, key)
        else:
            fut = svc.kget(e, key)
        if rng.random() < 0.4:
            settle(runtime, svc, fut)
    while any(svc.queues):
        svc.flush()
    svc.flush()
    for e in range(3):
        for key in keys + [f"c{k}" for k in keys]:
            fast = svc.kget_vsn(e, key)
            assert fast.done  # hit or immediate NOTFOUND
            svc.set_fast_reads(False)
            dev = svc.kget_vsn(e, key)
            settle(runtime, svc, dev)
            svc.set_fast_reads(True)
            assert fast.value == dev.value, (e, key)


def test_stats_surface():
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    svc.kget(0, "a")
    st = svc.stats()
    assert st["read_fastpath_hits"] == 1
    assert st["read_fastpath_misses"] == 0
    assert st["read_fastpath_miss_reasons"] == {}
    assert 0.0 <= st["lease_valid_fraction"] <= 1.0


def test_restore_starts_leaseless_then_recovers(tmp_path):
    runtime, svc = make()
    assert settle(runtime, svc, svc.kput(0, "a", b"v"))[0] == "ok"
    f = svc.kmodify(0, "ctr", funref.ref("rmw:add", 9), 0)
    settle(runtime, svc, f)
    svc.save(str(tmp_path / "ckpt"))
    rt2 = Runtime(seed=9)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "ckpt"), tick=None,
        config=fast_test_config())
    # restarts stay lease-less: no pre-crash lease is ever trusted
    g = svc2.kget(0, "a")
    assert not g.done
    assert svc2.read_fastpath_miss_reasons.get("no_lease", 0) >= 1
    settle(rt2, svc2, g)
    assert g.value == ("ok", b"v")
    # warmed again: values AND the inline slot serve fast (the
    # device read re-mirrored what the checkpoint couldn't)
    gi = svc2.kget(0, "ctr")
    if not gi.done:  # inline mirror re-warms via one device round
        settle(rt2, svc2, gi)
        gi = svc2.kget(0, "ctr")
    assert gi.done and gi.value == ("ok", 9)


# -- replication-group gates (no sockets: quorum monkeypatched) -------------


def _group_leader(trust=True):
    from riak_ensemble_tpu.parallel import repgroup

    runtime = Runtime(seed=11)
    svc = repgroup.ReplicatedService(
        runtime, 2, 1, 8, group_size=3, config=fast_test_config(),
        trust_host_lease=trust)
    svc._is_leader = True
    svc._ge = 1
    svc._quorum_from = lambda acked: True  # pretend replicas ack
    return runtime, svc


def test_repgroup_replica_never_serves_fast():
    from riak_ensemble_tpu.parallel import repgroup

    runtime = Runtime(seed=12)
    svc = repgroup.ReplicatedService(
        runtime, 2, 1, 8, group_size=3, config=fast_test_config(),
        trust_host_lease=True)
    svc.key_slot[0]["k"] = 3  # a mapped key on an unpromoted lane
    g = svc.kget(0, "k")
    assert not g.done
    assert svc.read_fastpath_miss_reasons["not_leader"] == 1


def test_repgroup_leader_host_lease_and_depose_invalidation():
    runtime, svc = _group_leader(trust=True)
    p = svc.kput(0, "k", b"v")
    settle(runtime, svc, p)
    assert p.value[0] == "ok"
    p2 = svc.kput(0, "k2", b"w")  # a second settled round: host
    settle(runtime, svc, p2)      # lease granted at warm cadence
    g = svc.kget(0, "k")
    assert g.done and g.value == ("ok", b"v")
    assert svc.stats()["group"]["host_lease_valid"] is True
    # a deposed leader invalidates BEFORE its next ack
    svc._note_depose(99)
    g2 = svc.kget(0, "k")
    assert not g2.done
    assert svc.read_fastpath_miss_reasons["not_leader"] == 1


def test_repgroup_host_lease_opt_in_default_off():
    runtime, svc = _group_leader(trust=False)
    p = svc.kput(0, "k", b"v")
    settle(runtime, svc, p)
    p2 = svc.kput(0, "k2", b"w")
    settle(runtime, svc, p2)
    # without trust_host_lease the strict reads-need-the-host-quorum
    # barrier stands: no fast serves on a group
    g = svc.kget(0, "k")
    assert not g.done
    assert svc.read_fastpath_miss_reasons[
        "no_host_lease_trust"] == 1


def test_repgroup_quorum_loss_revokes_host_lease():
    runtime, svc = _group_leader(trust=True)
    settle(runtime, svc, svc.kput(0, "k", b"v"))
    p2 = svc.kput(0, "k2", b"w")
    settle(runtime, svc, p2)
    assert svc.kget(0, "k").done
    svc._quorum_from = lambda acked: False  # replicas vanish
    p3 = svc.kput(0, "k3", b"x")
    settle(runtime, svc, p3)
    assert p3.value == "failed"  # no false acks
    g = svc.kget(0, "k")
    assert not g.done  # host lease revoked at the failed settle
    assert svc.read_fastpath_miss_reasons["no_lease"] >= 1
