"""Batched engine semantics: election, put/get, quorum edges, sharding.

Differential anchors: the scalar quorum predicate
(riak_ensemble_tpu.ops.quorum.quorum_met) and hand-derived protocol
facts from the reference (peer.erl call stacks, SURVEY §3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from riak_ensemble_tpu.ops import engine as eng
from riak_ensemble_tpu.ops.quorum import MET, quorum_met
from riak_ensemble_tpu.parallel.mesh import ShardedEngine, make_mesh

E, M, S = 4, 5, 16


def all_up():
    return jnp.ones((E, M), bool)


def elect_all(state, up=None):
    up = all_up() if up is None else up
    state, won = eng.elect_step(
        state, jnp.ones((E,), bool), jnp.zeros((E,), jnp.int32), up)
    return state, won


def test_election_establishes_leader_and_epoch():
    st = eng.init_state(E, M, S)
    st, won = elect_all(st)
    assert bool(won.all())
    np.testing.assert_array_equal(st.leader, np.zeros(E))
    # NextEpoch = max(epoch)+1 = 1, adopted by every up member.
    np.testing.assert_array_equal(st.epoch, np.ones((E, M)))
    # Re-election bumps epoch again.
    st, won = elect_all(st)
    assert bool(won.all())
    np.testing.assert_array_equal(st.epoch, 2 * np.ones((E, M)))


def test_election_fails_without_quorum():
    st = eng.init_state(E, M, S)
    up = jnp.asarray(np.array([[1, 1, 0, 0, 0]] * E, dtype=bool))
    st, won = elect_all(st, up)
    assert not bool(won.any())
    np.testing.assert_array_equal(st.leader, -np.ones(E))
    np.testing.assert_array_equal(st.epoch, np.zeros((E, M)))
    # 3/5 is a majority: succeeds.
    up = jnp.asarray(np.array([[1, 1, 1, 0, 0]] * E, dtype=bool))
    st, won = elect_all(st, up)
    assert bool(won.all())
    # Down peers did not adopt the new epoch.
    np.testing.assert_array_equal(st.epoch[:, 3:], np.zeros((E, 2)))


def _put(st, slots, vals, up=None, lease=True):
    up = all_up() if up is None else up
    return eng.kv_step(
        st, jnp.full((E,), eng.OP_PUT, jnp.int32),
        jnp.asarray(slots, jnp.int32), jnp.asarray(vals, jnp.int32),
        jnp.full((E,), lease, bool), up)


def _get(st, slots, up=None, lease=True):
    up = all_up() if up is None else up
    return eng.kv_step(
        st, jnp.full((E,), eng.OP_GET, jnp.int32),
        jnp.asarray(slots, jnp.int32), jnp.zeros((E,), jnp.int32),
        jnp.full((E,), lease, bool), up)


def test_put_then_get_roundtrip():
    st, _ = elect_all(eng.init_state(E, M, S))
    st, res = _put(st, [3] * E, [10, 20, 30, 40])
    assert bool(res.committed.all())
    np.testing.assert_array_equal(res.obj_vsn, [[1, 1]] * E)
    st, res = _get(st, [3] * E)
    assert bool(res.get_ok.all()) and bool(res.found.all())
    np.testing.assert_array_equal(res.value, [10, 20, 30, 40])
    # Unwritten slot reads notfound.
    st, res = _get(st, [5] * E)
    assert bool(res.get_ok.all()) and not bool(res.found.any())


def test_put_replicates_to_all_up_members():
    st, _ = elect_all(eng.init_state(E, M, S))
    st, res = _put(st, [0] * E, [7] * E)
    np.testing.assert_array_equal(st.obj_val[:, :, 0], 7 * np.ones((E, M)))
    np.testing.assert_array_equal(st.obj_seq[:, :, 0], np.ones((E, M)))


def test_put_needs_quorum_of_matching_epochs():
    st, _ = elect_all(eng.init_state(E, M, S))
    # Only 2/5 peers reachable: no quorum, no commit, no state change.
    up = jnp.asarray(np.array([[1, 1, 0, 0, 0]] * E, dtype=bool))
    st2, res = _put(st, [0] * E, [7] * E, up=up)
    assert not bool(res.committed.any())
    np.testing.assert_array_equal(st2.obj_seq[:, :, 0], np.zeros((E, M)))
    np.testing.assert_array_equal(st2.obj_seq_ctr, st.obj_seq_ctr)
    # Matches the scalar oracle: 1 valid reply + self < 3 = majority(5).
    assert quorum_met([("p1", "ok")], "p0",
                      [["p0", "p1", "p2", "p3", "p4"]]) != MET


def test_seq_monotonic_and_epoch_reset():
    st, _ = elect_all(eng.init_state(E, M, S))
    for i in range(3):
        st, res = _put(st, [i] * E, [i] * E)
        np.testing.assert_array_equal(res.obj_vsn[:, 1], (i + 1) * np.ones(E))
    # New election: epoch bumps, per-epoch obj counter resets
    # (local_commit, peer.erl:891-909).
    st, _ = elect_all(st)
    st, res = _put(st, [9] * E, [9] * E)
    np.testing.assert_array_equal(res.obj_vsn, [[2, 1]] * E)


def test_stale_epoch_read_rewrites_at_current_epoch():
    st, _ = elect_all(eng.init_state(E, M, S))
    st, _ = _put(st, [2] * E, [42] * E)
    st, _ = elect_all(st)  # epoch now 2; slot 2 holds an epoch-1 obj
    st, res = _get(st, [2] * E)
    assert bool(res.get_ok.all()) and bool(res.found.all())
    np.testing.assert_array_equal(res.value, 42 * np.ones(E))
    # update_key (peer.erl:1564-1596): object rewritten at epoch 2.
    np.testing.assert_array_equal(st.obj_epoch[:, :, 2], 2 * np.ones((E, M)))
    # Rewrite consumed seq 1 of the new epoch.
    np.testing.assert_array_equal(st.obj_seq_ctr, np.ones(E))


def test_election_rejects_down_or_foreign_candidate():
    st = eng.init_state(E, M, S)
    # Candidate 4 is down: even with a quorum of other acks, no win.
    up = jnp.asarray(np.array([[1, 1, 1, 1, 0]] * E, dtype=bool))
    st2, won = eng.elect_step(
        st, jnp.ones((E,), bool), jnp.full((E,), 4, jnp.int32), up)
    assert not bool(won.any())
    # Candidate outside the peer range likewise.
    st2, won = eng.elect_step(
        st, jnp.ones((E,), bool), jnp.full((E,), M + 3, jnp.int32),
        all_up())
    assert not bool(won.any())


def test_put_invalid_slot_not_committed():
    st, _ = elect_all(eng.init_state(E, M, S))
    st2, res = _put(st, [S + 1] * E, [1] * E)
    assert not bool(res.committed.any())
    np.testing.assert_array_equal(st2.obj_seq_ctr, st.obj_seq_ctr)


def test_rewrite_reports_committed():
    st, _ = elect_all(eng.init_state(E, M, S))
    st, _ = _put(st, [2] * E, [42] * E)
    st, _ = elect_all(st)
    st, res = _get(st, [2] * E)
    assert bool(res.committed.all())  # the update_key rewrite landed
    st, res = _get(st, [2] * E)
    assert not bool(res.committed.any())  # now current: plain read


def test_no_commit_without_leader_replica():
    """A put must include the leader's own replica write (put_obj always
    does the leader-local put, peer.erl:1669-1698); otherwise a later
    leased read could miss a committed write."""
    st, _ = elect_all(eng.init_state(E, M, S))
    st, _ = _put(st, [0] * E, [1] * E)  # A, replicated everywhere
    # Leader (peer 0) down: follower quorum exists, but no commit.
    up = jnp.asarray(np.array([[0, 1, 1, 1, 1]] * E, dtype=bool))
    st2, res = _put(st, [0] * E, [2] * E, up=up)
    assert not bool(res.committed.any())
    # And no read can be served while the leader is down, leased or not.
    st2, res = _get(st, [0] * E, up=up, lease=True)
    assert not bool(res.get_ok.any())
    # Leader back, minority up: leased read still sees A (value 1) —
    # never a half-committed B.
    up = jnp.asarray(np.array([[1, 0, 0, 0, 0]] * E, dtype=bool))
    st3, res = _get(st, [0] * E, up=up, lease=True)
    np.testing.assert_array_equal(res.value, np.ones(E))


def test_unleased_read_requires_epoch_quorum():
    st, _ = elect_all(eng.init_state(E, M, S))
    st, _ = _put(st, [1] * E, [5] * E)
    st, res = _get(st, [1] * E, lease=False)
    assert bool(res.get_ok.all())  # quorum reachable: read ok
    up = jnp.asarray(np.array([[1, 1, 0, 0, 0]] * E, dtype=bool))
    st, res = _get(st, [1] * E, up=up, lease=False)
    assert not bool(res.get_ok.any())  # no quorum, no lease: fail


def test_get_latest_obj_prefers_newest_version():
    """A replica holding a newer version than the leader wins the
    read (get_latest_obj max by (epoch, seq), backend.erl:132-143)."""
    st, _ = elect_all(eng.init_state(E, M, S))
    st, _ = _put(st, [0] * E, [1] * E)
    # Manually age the leader's replica (simulates a lost write).
    obj_seq = st.obj_seq.at[:, 0, 0].set(0)
    obj_val = st.obj_val.at[:, 0, 0].set(0)
    st = st._replace(obj_seq=obj_seq, obj_val=obj_val)
    st, res = _get(st, [0] * E, lease=False)
    np.testing.assert_array_equal(res.value, np.ones(E))


def test_joint_views_require_majority_in_every_view():
    # View A = {0,1,2}, view B = {2,3,4} (joint consensus).
    views = [[0, 1, 2], [2, 3, 4]]
    st = eng.init_state(E, M, S, views=views)
    # Up = {0,1,2}: majority of A (3/3) and of B (1/3 + nothing) -> fail.
    up = jnp.asarray(np.array([[1, 1, 1, 0, 0]] * E, dtype=bool))
    st1, won = elect_all(st, up)
    assert not bool(won.any())
    # Scalar oracle agrees (candidate p0 hears p1, p2).
    assert quorum_met([("p1", "ok"), ("p2", "ok")], "p0",
                      [["p0", "p1", "p2"], ["p2", "p3", "p4"]]) != MET
    # Up = {0,1,2,3}: A 3/3, B 2/3 -> majority in both.
    up = jnp.asarray(np.array([[1, 1, 1, 1, 0]] * E, dtype=bool))
    st2, won = elect_all(st, up)
    assert bool(won.all())


def test_scan_step_serializes_ops_per_ensemble():
    st, _ = elect_all(eng.init_state(E, M, S))
    k = 4
    kind = jnp.full((k, E), eng.OP_PUT, jnp.int32)
    kind = kind.at[3].set(eng.OP_GET)
    slot = jnp.zeros((k, E), jnp.int32)
    val = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[:, None], (k, E))
    lease = jnp.ones((k, E), bool)
    st, res = eng.kv_step_scan(st, kind, slot, val, lease, all_up())
    # Last-writer-wins within the scan; the final get sees op 2's value.
    np.testing.assert_array_equal(res.value[3], 2 * np.ones(E))
    np.testing.assert_array_equal(res.obj_vsn[3, :, 1], 3 * np.ones(E))


# ---------------------------------------------------------------------------
# Sharded engine on the virtual 8-device CPU mesh


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_matches_single_device(mesh_shape):
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    n_ens, n_peer = mesh_shape
    e, m = 8, 8  # M=8 divides every peer-axis size
    mesh = make_mesh(n_ens, n_peer)
    se = ShardedEngine(mesh)
    views = [list(range(5))]  # 5-member view inside an 8-wide peer axis

    def run(stepper, state):
        state, won = stepper.elect(state)
        k = 3
        kind = jnp.asarray(
            np.array([[eng.OP_PUT] * e, [eng.OP_PUT] * e, [eng.OP_GET] * e]),
            jnp.int32)
        slot = jnp.ones((k, e), jnp.int32)
        val = jnp.asarray(np.arange(k * e).reshape(k, e), jnp.int32)
        lease = jnp.ones((k, e), bool)
        up = jnp.ones((e, m), bool)
        state, res1 = stepper.kv(state, kind, slot, val, lease, up)
        # Second election: epoch bump, then reads with a down peer —
        # exercises the mixed-epoch _latest_at_slot pmax chain and the
        # batched stale-epoch rewrite under peer sharding.
        state, won2 = stepper.elect(state)
        up2 = jnp.asarray(
            np.tile(np.array([1, 0, 1, 1, 1, 1, 1, 1], bool), (e, 1)))
        kind2 = jnp.full((k, e), eng.OP_GET, jnp.int32)
        state, res2 = stepper.kv(state, kind2, slot, val, lease, up2)
        return won, res1, won2, res2

    class Single:
        def elect(self, st):
            return eng.elect_step(st, jnp.ones((e,), bool),
                                  jnp.zeros((e,), jnp.int32),
                                  jnp.ones((e, m), bool))

        def kv(self, st, *a):
            return eng.kv_step_scan(st, *a)

    class Sharded:
        def elect(self, st):
            return se.elect_step(st, jnp.ones((e,), bool),
                                 jnp.zeros((e,), jnp.int32),
                                 jnp.ones((e, m), bool))

        def kv(self, st, *a):
            return se.kv_step_scan(st, *a)

    out_single = run(Single(), eng.init_state(e, m, S, views=views))
    out_sharded = run(Sharded(), se.init_state(e, m, S, views=views))
    for a, b in zip(jax.tree.leaves(out_single), jax.tree.leaves(out_sharded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Sanity on content, not just equivalence: the final reads found the
    # rewritten object at the post-re-election epoch.
    _, _, won2, res2 = out_single
    assert bool(np.asarray(won2).all())
    assert bool(np.asarray(res2.get_ok).all())
    assert bool(np.asarray(res2.found).all())
    np.testing.assert_array_equal(np.asarray(res2.obj_vsn[..., 0]),
                                  2 * np.ones((3, e)))


@pytest.mark.parametrize("mesh_shape", [(4, 2), (8, 1)])
def test_sharded_reconfig_matches_single_device(mesh_shape):
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    n_ens, n_peer = mesh_shape
    e, m = 8, 8
    mesh = make_mesh(n_ens, n_peer)
    se = ShardedEngine(mesh)
    views = [list(range(5))]
    up = jnp.ones((e, m), bool)
    new_view = jnp.asarray(
        np.tile(np.array([1, 1, 1, 0, 0, 0, 0, 0], bool), (e, 1)))
    propose = jnp.ones((e,), bool)
    hold = jnp.zeros((e,), bool)

    def run(elect_fn, reconfig_fn, state):
        state, won = elect_fn(state, jnp.ones((e,), bool),
                              jnp.zeros((e,), jnp.int32), up)
        state, inst, _ = reconfig_fn(state, propose, new_view, up)
        state, _, coll = reconfig_fn(state, hold, new_view, up)
        return won, inst, coll, state

    out_single = run(eng.elect_step, eng.reconfig_step,
                     eng.init_state(e, m, S, views=views))
    out_sharded = run(se.elect_step, se.reconfig_step,
                      se.init_state(e, m, S, views=views))
    for a, b in zip(jax.tree.leaves(out_single),
                    jax.tree.leaves(out_sharded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    won, inst, coll, state = out_single
    assert bool(np.asarray(won).all())
    assert bool(np.asarray(inst).all())
    assert bool(np.asarray(coll).all())
    vm = np.asarray(state.view_mask)
    assert vm[:, 0, :3].all() and not vm[:, 0, 3:].any()
    assert not vm[:, 1, :].any()


def test_distributed_helpers_on_virtual_mesh():
    from riak_ensemble_tpu.parallel import distributed as dist

    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    se = dist.sharded_engine(n_peer=2)
    assert se.mesh.shape == {"ens": jax.device_count() // 2, "peer": 2}
    e, m = 8, 4
    state = se.init_state(e, m, 8, views=[list(range(m))])
    up = jnp.ones((e, m), bool)
    state, won = se.elect_step(state, jnp.ones((e,), bool),
                               jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())


# ---------------------------------------------------------------------------
# OP_CAS: compare-and-swap (do_kupdate + do_kput_once semantics)


class TestCas:
    def _setup(self, e=4, m=5):
        st = eng.init_state(e, m, S)
        up = jnp.ones((e, m), bool)
        st, won = eng.elect_step(st, jnp.ones((e,), bool),
                                 jnp.zeros((e,), jnp.int32), up)
        assert np.asarray(won).all()
        return st, up, e

    def _one(self, st, up, e, kind, slot, val, exp=None):
        k = jnp.full((1, e), kind, jnp.int32)
        sl = jnp.full((1, e), slot, jnp.int32)
        v = jnp.full((1, e), val, jnp.int32)
        lz = jnp.ones((1, e), bool)
        xe = xs = None
        if exp is not None:
            xe = jnp.full((1, e), exp[0], jnp.int32)
            xs = jnp.full((1, e), exp[1], jnp.int32)
        st, r = eng.kv_step_scan(st, k, sl, v, lz, up,
                                 exp_epoch=xe, exp_seq=xs)
        return st, jax.tree.map(lambda x: np.asarray(x)[0], r)

    def test_cas_on_current_vsn_commits(self):
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_PUT, 0, 11)
        vsn = (int(r.obj_vsn[0, 0]), int(r.obj_vsn[0, 1]))
        st, r = self._one(st, up, e, eng.OP_CAS, 0, 22, exp=vsn)
        assert r.committed.all()
        st, r = self._one(st, up, e, eng.OP_GET, 0, 0)
        assert (r.value == 22).all()

    def test_cas_on_stale_vsn_fails_value_untouched(self):
        st, up, e = self._setup()
        st, r1 = self._one(st, up, e, eng.OP_PUT, 0, 11)
        old = (int(r1.obj_vsn[0, 0]), int(r1.obj_vsn[0, 1]))
        st, _ = self._one(st, up, e, eng.OP_PUT, 0, 12)  # bumps vsn
        st, r = self._one(st, up, e, eng.OP_CAS, 0, 99, exp=old)
        assert not r.committed.any()
        assert not r.get_ok.any()
        st, r = self._one(st, up, e, eng.OP_GET, 0, 0)
        assert (r.value == 12).all()

    def test_cas_create_if_missing(self):
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_CAS, 3, 7, exp=(0, 0))
        assert r.committed.all()
        st, r = self._one(st, up, e, eng.OP_GET, 3, 0)
        assert (r.value == 7).all()
        # put-once: a second create-expecting-absent must fail
        st, r = self._one(st, up, e, eng.OP_CAS, 3, 8, exp=(0, 0))
        assert not r.committed.any()
        st, r = self._one(st, up, e, eng.OP_GET, 3, 0)
        assert (r.value == 7).all()

    def test_cas_delete_via_tombstone(self):
        """ksafe_delete: CAS to val 0 (tombstone) with the read vsn."""
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_PUT, 1, 5)
        vsn = (int(r.obj_vsn[0, 0]), int(r.obj_vsn[0, 1]))
        st, r = self._one(st, up, e, eng.OP_CAS, 1, 0, exp=vsn)
        assert r.committed.all()
        st, r = self._one(st, up, e, eng.OP_GET, 1, 0)
        assert r.get_ok.all() and not r.found.any()

    def test_cas_within_one_scan_serializes(self):
        """Two CAS with the same expected vsn riding one scan: the
        first wins, the second fails (per-key serialization)."""
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_PUT, 0, 1)
        ve, vs = int(r.obj_vsn[0, 0]), int(r.obj_vsn[0, 1])
        kind = jnp.full((2, e), eng.OP_CAS, jnp.int32)
        slot = jnp.zeros((2, e), jnp.int32)
        val = jnp.asarray(np.broadcast_to(np.array([[21], [22]]),
                                          (2, e)), jnp.int32)
        xe = jnp.full((2, e), ve, jnp.int32)
        xs = jnp.full((2, e), vs, jnp.int32)
        st, r = eng.kv_step_scan(st, kind, slot, val,
                                 jnp.ones((2, e), bool), up,
                                 exp_epoch=xe, exp_seq=xs)
        committed = np.asarray(r.committed)
        assert committed[0].all() and not committed[1].any()
        st, r = self._one(st, up, e, eng.OP_GET, 0, 0)
        assert (r.value == 21).all()

    def test_cas_after_failover_needs_fresh_read(self):
        """A new epoch's GET rewrites the object (update_key), so a
        CAS with the pre-failover vsn fails until re-read."""
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_PUT, 0, 9)
        old = (int(r.obj_vsn[0, 0]), int(r.obj_vsn[0, 1]))
        up2 = up.at[:, 0].set(False)
        st, won = eng.elect_step(st, jnp.ones((e,), bool),
                                 jnp.ones((e,), jnp.int32), up2)
        assert np.asarray(won).all()
        st, r = self._one(st, up2, e, eng.OP_GET, 0, 0)  # rewrites
        fresh = (int(r.obj_vsn[0, 0]), int(r.obj_vsn[0, 1]))
        assert fresh != old
        st, r = self._one(st, up2, e, eng.OP_CAS, 0, 33, exp=old)
        assert not r.committed.any()
        st, r = self._one(st, up2, e, eng.OP_CAS, 0, 33, exp=fresh)
        assert r.committed.all()


@pytest.mark.parametrize("mesh_shape", [(4, 2)])
def test_sharded_cas_matches_single_device(mesh_shape):
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    n_ens, n_peer = mesh_shape
    e, m = 8, 8
    se = ShardedEngine(make_mesh(n_ens, n_peer))
    views = [list(range(5))]

    def run(stepper, state):
        up = jnp.ones((e, m), bool)
        state, won = stepper.elect_step(
            state, jnp.ones((e,), bool), jnp.zeros((e,), jnp.int32), up)
        # put, then a matching CAS, then a stale CAS, then a get
        kind = jnp.asarray(np.stack(
            [np.full(e, eng.OP_PUT), np.full(e, eng.OP_CAS),
             np.full(e, eng.OP_CAS), np.full(e, eng.OP_GET)]), jnp.int32)
        slot = jnp.ones((4, e), jnp.int32)
        val = jnp.asarray(np.stack([np.full(e, 5), np.full(e, 6),
                                    np.full(e, 7), np.zeros(e)]),
                          jnp.int32)
        xe = jnp.ones((4, e), jnp.int32)       # epoch 1 after election
        xs = jnp.asarray(np.stack([np.zeros(e), np.ones(e),
                                   np.ones(e), np.zeros(e)]), jnp.int32)
        lease = jnp.ones((4, e), bool)
        state, res = stepper.kv_step_scan(state, kind, slot, val, lease,
                                          up, exp_epoch=xe, exp_seq=xs)
        return won, res, state

    class Single:
        elect_step = staticmethod(eng.elect_step)
        kv_step_scan = staticmethod(eng.kv_step_scan)

    a = run(Single(), eng.init_state(e, m, S, views=views))
    b = run(se, se.init_state(e, m, S, views=views))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    _, res, _ = a
    committed = np.asarray(res.committed)
    # matching CAS commits, stale CAS fails, get sees the CAS value
    assert committed[1].all() and not committed[2].any()
    np.testing.assert_array_equal(np.asarray(res.value[3]), 6)


class TestCasIntegrity:
    """CAS create-if-missing interacts with tombstones and the
    integrity gate exactly like the GET notfound dance."""

    def _setup(self, e=2, m=5):
        st = eng.init_state(e, m, S)
        up = jnp.ones((e, m), bool)
        st, won = eng.elect_step(st, jnp.ones((e,), bool),
                                 jnp.zeros((e,), jnp.int32), up)
        assert np.asarray(won).all()
        return st, up, e

    def _one(self, st, up, e, kind, slot, val, exp=(0, 0)):
        k = jnp.full((1, e), kind, jnp.int32)
        sl = jnp.full((1, e), slot, jnp.int32)
        v = jnp.full((1, e), val, jnp.int32)
        st, r = eng.kv_step_scan(
            st, k, sl, v, jnp.ones((1, e), bool), up,
            exp_epoch=jnp.full((1, e), exp[0], jnp.int32),
            exp_seq=jnp.full((1, e), exp[1], jnp.int32))
        return st, jax.tree.map(lambda x: np.asarray(x)[0], r)

    def test_cas_create_over_tombstone(self):
        """do_kput_once succeeds over a notfound-valued object
        (peer.py:1462-1467): a (0,0) CAS must too, or recycled slots
        (which keep the old key's tombstone) livelock creation."""
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_PUT, 0, 5)
        vsn = (int(r.obj_vsn[0, 0]), int(r.obj_vsn[0, 1]))
        st, r = self._one(st, up, e, eng.OP_CAS, 0, 0, exp=vsn)  # delete
        assert r.committed.all()
        st, r = self._one(st, up, e, eng.OP_CAS, 0, 7, exp=(0, 0))
        assert r.committed.all()
        st, r = self._one(st, up, e, eng.OP_GET, 0, 0)
        assert (r.value == 7).all()

    def test_cas_create_refused_when_all_holders_corrupt(self):
        """Corrupting every holder's stored object makes the slot look
        absent to the integrity gate; a (0,0) CAS must NOT commit over
        it (the nf_quorum guard, same as the GET tombstone path)."""
        st, up, e = self._setup()
        st, r = self._one(st, up, e, eng.OP_PUT, 3, 42)
        assert r.committed.all()
        # out-of-band damage on EVERY replica's object at the slot
        st = st._replace(obj_val=st.obj_val.at[:, :, 3].set(999))
        st, r = self._one(st, up, e, eng.OP_CAS, 3, 1, exp=(0, 0))
        assert not r.committed.any(), \
            "CAS overwrote data the integrity gate had excluded"


def test_returned_peer_adopts_epoch_and_rejoins_quorum():
    """following({commit, Fact}) catch-up (peer.erl:794-836): a peer
    whose ballot epoch trails the leader's nacks the launch it
    returns in, adopts the epoch at its end, and counts toward
    quorums from the next launch — without requiring an election."""
    e, m, s = 4, 3, 4
    state = eng.init_state(e, m, s)
    up = jnp.ones((e, m), bool)
    state, won = eng.elect_step(state, jnp.ones((e,), bool),
                                jnp.zeros((e,), jnp.int32), up)
    assert bool(np.asarray(won).all())

    # peer 2 "was down": regress its epoch to 0 everywhere
    state = state._replace(
        epoch=state.epoch.at[:, 2].set(0))

    # with peers 0+1 only a 2/3 quorum holds; a put commits, and the
    # launch's tail heals peer 2's epoch
    kind = jnp.full((e,), eng.OP_PUT, jnp.int32)
    state, res = eng.kv_step(state, kind, jnp.zeros((e,), jnp.int32),
                             jnp.full((e,), 7, jnp.int32),
                             jnp.zeros((e,), bool), up)
    assert bool(np.asarray(res.committed).all())
    lead_epoch = np.asarray(state.epoch)[:, 0]
    np.testing.assert_array_equal(np.asarray(state.epoch)[:, 2],
                                  lead_epoch)

    # now a quorum needing peer 2 succeeds: drop peer 1 — 0+2 form
    # the majority only if 2's epoch matches
    up2 = np.ones((e, m), bool)
    up2[:, 1] = False
    state, res = eng.kv_step(state, kind, jnp.zeros((e,), jnp.int32),
                             jnp.full((e,), 8, jnp.int32),
                             jnp.zeros((e,), bool), jnp.asarray(up2))
    assert bool(np.asarray(res.committed).all()), \
        "healed peer did not count toward the quorum"
