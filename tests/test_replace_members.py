"""replace_members_test.erl parity: full member replacement
root/2/3 → 4/5/6 and back (test/replace_members_test.erl:9-49).

Documents the reference's behavior that synctrees sync *metadata*, not
data (:26-30): after replacing every member, the new members have
exchanged tree hashes asserting the key exists, but no backend data —
so the read fails (never silently returns notfound) until the original
members return.
"""

from riak_ensemble_tpu.testing import ManagedCluster
from riak_ensemble_tpu.types import PeerId


def test_replace_members_root(tmp_path):
    # data_root so removed peers' backend data survives on disk and is
    # reloaded when the original members are re-added (the reference
    # basic backend always persists; memory-only would lose the data).
    mc = ManagedCluster(seed=22, data_root=str(tmp_path))
    mc.ens_start(3)
    node = mc.node0

    r = mc.kput("test", b"test")
    assert r[0] == "ok", r
    assert mc.kget("test")[0] == "ok"

    originals = [PeerId("root", node), PeerId(2, node), PeerId(3, node)]
    replacements = [PeerId(i, node) for i in (4, 5, 6)]

    changes = [("add", m) for m in replacements] + \
              [("del", m) for m in originals]
    r = mc.update_members("root", changes)
    assert r == "ok", r
    mc.wait_members("root", replacements)
    mc.wait_stable("root")

    # Trees synced metadata but not data: the get must FAIL (not
    # return notfound) because the hash says the key exists but no
    # replica has it (peer.erl get_latest_obj hash extra-check).
    def failing_get():
        r = mc.kget("test")
        assert not (r[0] == "ok" and r[1].value == b"test"), \
            "value should not be readable from empty replacements"
        return r == ("error", "failed")
    assert mc.runtime.run_until(failing_get, 60.0, poll=0.2), \
        "get did not fail cleanly on data-less members"

    # Leader may have stepped down after the failure; re-stabilize,
    # then restore the original membership.
    mc.wait_members("root", replacements)
    mc.wait_stable("root")

    changes2 = [("add", m) for m in originals] + \
               [("del", m) for m in replacements]
    r = mc.update_members("root", changes2)
    assert r == "ok", r
    mc.wait_members("root", originals)
    mc.wait_stable("root")

    # Data still lives on root/2/3: reads succeed again.
    def readable():
        r = mc.kget("test")
        return r[0] == "ok" and r[1].value == b"test"
    assert mc.runtime.run_until(readable, 60.0, poll=0.2)
