"""Network front-end for the batched service (svcnode): remote
clients reach the engine-backed K/V plane over TCP with the
restricted wire codec — the scale-path analog of netnode."""

import asyncio
import struct

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import svcnode  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


def test_svcnode_end_to_end():
    async def scenario():
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config())
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()

        r = await c.kput(0, "k", b"v1")
        assert r[0] == "ok"
        vsn = tuple(r[1])
        assert await c.kget(0, "k") == ("ok", b"v1")
        r = await c.kupdate(0, "k", vsn, b"v2")
        assert r[0] == "ok"
        assert await c.kget(0, "k") == ("ok", b"v2")
        r = await c.kget_vsn(0, "k")
        assert r[0] == "ok" and r[1] == b"v2"
        r = await c.ksafe_delete(0, "k", tuple(r[2]))
        assert r[0] == "ok"  # CAS-to-tombstone acks with the new vsn
        assert await c.kget(0, "k") == ("ok", NOTFOUND)
        assert await c.kdelete(0, "nope") == ("ok", NOTFOUND)

        # pipelining: many in-flight ops, out-of-order-safe by req id
        puts = [c.kput(e, f"p{i}", b"x%d" % i)
                for e in range(4) for i in range(5)]
        results = await asyncio.gather(*puts)
        assert all(r[0] == "ok" for r in results)
        gets = [c.kget(e, f"p{i}") for e in range(4) for i in range(5)]
        results = await asyncio.gather(*gets)
        assert [r[1] for r in results] == \
            [b"x%d" % i for _e in range(4) for i in range(5)]

        st = await c.stats()
        assert st["ops_served"] > 0 and st["ensembles_with_leader"] >= 1

        # the runtime-controller audit verb (ARCHITECTURE §14): the
        # health section + the decision journal, wire-encodable; a
        # stock boot is observe-only with an empty journal
        ctl = await c.controller()
        assert ctl["controller"]["enabled"] is False
        assert ctl["controller"]["pipeline_depth"] >= 1
        assert ctl["decisions"] == []
        h = await c.health()
        assert h["controller"] == ctl["controller"]

        # unknown op answers, connection stays usable
        assert await c.call("bogus-op") == ("error", "unknown-op")
        assert await c.kget(1, "p0") == ("ok", b"x0")

        # ensemble index is untrusted input: negative (would alias
        # via Python indexing) and out-of-range reject cleanly, as
        # does wrong arity — and the connection survives all three
        assert await c.call("kput", -1, "k", b"v") == \
            ("error", "bad-request")
        assert await c.call("kput", 99, "k", b"v") == \
            ("error", "bad-request")
        assert await c.call("kput", 0) == ("error", "bad-request")
        assert await c.kget(1, "p0") == ("ok", b"x0")

        await c.close()
        await server.stop()

    asyncio.run(scenario())


def test_svcnode_hostile_frames_drop_connection_only():
    async def scenario():
        server = await svcnode.serve(2, 3, 4, port=0,
                                     config=fast_test_config())
        # hostile: garbage payload -> server drops THIS connection
        reader, writer = await asyncio.open_connection(
            server.host, server.port)
        junk = b"\x93\x01\x02pickle-ish\xff"
        writer.write(struct.pack(">I", len(junk)) + junk)
        await writer.drain()
        assert await reader.read(1) == b""  # server closed it
        writer.close()

        # hostile: absurd length prefix -> dropped without allocation
        reader, writer = await asyncio.open_connection(
            server.host, server.port)
        writer.write(struct.pack(">I", (1 << 31) - 1))
        await writer.drain()
        assert await reader.read(1) == b""
        writer.close()

        # a well-behaved client is unaffected
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        assert (await c.kput(0, "k", b"v"))[0] == "ok"
        assert await c.kget(0, "k") == ("ok", b"v")
        await c.close()
        await server.stop()

    asyncio.run(scenario())


def _frame(msg):
    from riak_ensemble_tpu import wire

    payload = wire.encode(msg)
    return struct.pack(">I", len(payload)) + payload


def test_svcnode_inflight_backpressure_bounds_queued_ops(monkeypatch):
    """A client pipelining thousands of ops can never hold more than
    _MAX_INFLIGHT unresolved at the server (the read loop blocks on
    the semaphore; TCP flow control pushes back) — and the pipeline
    still completes exactly."""
    monkeypatch.setattr(svcnode, "_MAX_INFLIGHT", 8)

    async def scenario():
        server = await svcnode.serve(2, 3, 64, port=0,
                                     config=fast_test_config())
        svc = server.svc
        orig_flush = svc.flush
        seen = []

        def spy_flush():
            seen.append(sum(len(q) for q in svc.queues))
            return orig_flush()
        svc.flush = spy_flush

        reader, writer = await asyncio.open_connection(
            server.host, server.port)
        n = 400
        for i in range(n):
            writer.write(_frame((i, "kput", i % 2, f"k{i % 16}",
                                 b"v%d" % i)))
        await writer.drain()
        # read every response (order may interleave; correlate by id)
        got = set()
        while len(got) < n:
            head = await asyncio.wait_for(
                reader.readexactly(4), timeout=30)
            (length,) = struct.unpack(">I", head)
            frame = await asyncio.wait_for(
                reader.readexactly(length), timeout=30)
            from riak_ensemble_tpu import wire
            req_id, result = wire.decode(frame)
            assert result[0] == "ok", (req_id, result)
            got.add(req_id)
        assert got == set(range(n))
        # the cap held at every flush
        assert seen and max(seen) <= 8, max(seen)
        writer.close()
        await server.stop()

    asyncio.run(scenario())


def test_svcnode_nonreading_client_dropped_not_buffered(monkeypatch):
    """A client that pipelines reads but never drains its socket is
    disconnected once the server-side write buffer passes the cap —
    bounded memory — while a well-behaved client stays served."""
    monkeypatch.setattr(svcnode, "_MAX_WRITE_BUF", 4096)

    async def scenario():
        server = await svcnode.serve(1, 3, 4, port=0,
                                     config=fast_test_config())
        good = svcnode.ServiceClient(server.host, server.port)
        await good.connect()
        big = b"x" * 8192
        assert (await good.kput(0, "k", big))[0] == "ok"

        reader, writer = await asyncio.open_connection(
            server.host, server.port)
        # hostile: request far more response bytes than the cap and
        # never read them
        for i in range(2000):
            writer.write(_frame((i, "kget", 0, "k")))
        try:
            await writer.drain()
        except ConnectionError:
            pass  # already dropped mid-send: that's the point
        # Don't read while the responses pile up: give the server time
        # to exceed the cap and abort (RST discards the kernel receive
        # queue; only the small already-pulled StreamReader buffer can
        # still hand out bytes), THEN drain until the reset/EOF
        # surfaces.
        await asyncio.sleep(10)
        dropped = False
        for _ in range(60):
            try:
                b = await asyncio.wait_for(reader.read(1 << 20),
                                           timeout=2.0)
            except asyncio.TimeoutError:
                continue
            except ConnectionError:
                dropped = True
                break
            if b == b"":
                dropped = True
                break
        assert dropped, "non-reading client was never disconnected"
        writer.close()

        # the good client is unaffected
        assert await good.kget(0, "k") == ("ok", big)
        await good.close()
        await server.stop()

    asyncio.run(scenario())


def test_svcnode_batch_ops_over_the_wire():
    """kput_many/kget_many ride the TCP protocol: one frame, one
    response carrying the per-key result list in order."""
    async def scenario():
        server = await svcnode.serve(2, 3, 32, port=0,
                                     config=fast_test_config())
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        keys = [f"k{i}" for i in range(10)]
        res = await c.kput_many(1, keys, [b"v%d" % i for i in range(10)])
        assert len(res) == 10 and all(r[0] == "ok" for r in res)
        got = await c.kget_many(1, keys + ["nope"])
        assert [r[1] for r in got[:10]] == [b"v%d" % i for i in range(10)]
        assert got[10] == ("ok", NOTFOUND)
        # CAS + delete batches over the wire
        up = await c.kupdate_many(1, [keys[0]], [tuple(res[0][1])],
                                  [b"up0"])
        assert up[0][0] == "ok"
        assert await c.kget(1, keys[0]) == ("ok", b"up0")
        dl = await c.kdelete_many(1, [keys[1], "nope"])
        assert dl[0][0] == "ok" and dl[1] == ("ok", NOTFOUND)
        assert await c.kget(1, keys[1]) == ("ok", NOTFOUND)
        # versioned batch reads over the wire
        gv = await c.kget_many(1, [keys[0], "nope"], want_vsn=True)
        assert gv[0][:2] == ("ok", b"up0") and len(gv[0]) == 3
        assert gv[1] == ("ok", NOTFOUND, (0, 0))
        # bad ensemble index still rejected cleanly
        assert (await c.kput_many(-1, ["k"], [b"v"]))[0] == "error"
        await c.close()
        await server.stop()

    asyncio.run(scenario())


def test_svcnode_slab_verbs_and_fallback():
    """The zero-copy slab lane (kput_slab/kget_slab): all-str-ascii /
    all-bytes batches ride it transparently through the client's
    kput_many/kget_many; exotic batches (non-ascii keys, non-bytes
    payloads) fall back to the legacy list verbs with identical
    results; malformed slab tables answer bad-request without
    dropping the connection."""
    import numpy as np

    from riak_ensemble_tpu import wire

    async def scenario():
        server = await svcnode.serve(2, 3, 32, port=0,
                                     config=fast_test_config())
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        # slab route (asserted: the client really built a slab frame)
        assert c._key_slab(["a", "bb"]) is not None
        res = await c.kput_many(0, ["a", "bb"], [b"1", b"22"])
        assert [r[0] for r in res] == ["ok", "ok"]
        got = await c.kget_many(0, ["a", "bb", "zz"], want_vsn=True)
        assert got[0][:2] == ("ok", b"1") and len(got[0]) == 3
        assert got[2] == ("ok", NOTFOUND, (0, 0))
        # exotic batches bypass the slab subset, same results
        assert c._key_slab(["κλειδί"]) is None
        res = await c.kput_many(0, ["κλειδί", "plain"],
                                [b"nb", b"pv"])
        assert [r[0] for r in res] == ["ok", "ok"]
        assert await c.kget_many(0, ["κλειδί"]) == [("ok", b"nb")]
        res = await c.kput_many(0, ["obj"], ["not-bytes"])
        assert res[0][0] == "ok"
        assert await c.kget_many(0, ["obj"]) == [("ok", "not-bytes")]
        # hostile slab: length table exceeding its arena answers
        # bad-request (trust boundary), connection stays usable
        bad = await c.call_parts(
            "kput_slab", 0,
            wire.Raw(np.asarray([5], np.int32)), wire.Raw(b"ab"),
            wire.Raw(np.asarray([1], np.int32)), wire.Raw(b"x"))
        assert bad == ("error", "bad-request")
        bad = await c.call_parts(
            "kget_slab", 0,
            wire.Raw(np.asarray([-1], np.int32)), wire.Raw(b""))
        assert bad == ("error", "bad-request")
        assert await c.kget(0, "a") == ("ok", b"1")
        await c.close()
        await server.stop()

    asyncio.run(scenario())


def test_svcnode_restart_adopts_persisted_dynamic_mode(tmp_path):
    """ADVICE r3 (medium): restarting a --dynamic-persisted data_dir
    WITHOUT re-passing --dynamic must adopt the persisted mode (the
    restore docstring's 'persisted lifecycle mode WINS'), not crash at
    startup; an explicitly contradictory flag still fails loudly."""
    data = str(tmp_path / "d")

    async def first_boot():
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config(),
                                     dynamic=True, data_dir=data)
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        assert (await c.create_ensemble("tenant"))[0] == "ok"
        r = await c.resolve_ensemble("tenant")
        assert r[0] == "ok"
        ens = r[1]
        assert (await c.kput(ens, "k", b"v"))[0] == "ok"
        await c.close()
        await server.stop()

    async def restart_without_flag():
        # the operator restart path: no dynamic flag at all
        server = await svcnode.serve(4, 3, 8, port=0,
                                     config=fast_test_config(),
                                     data_dir=data)
        assert server.svc.dynamic is True  # persisted mode adopted
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        r = await c.resolve_ensemble("tenant")
        assert r[0] == "ok"
        assert await c.kget(r[1], "k") == ("ok", b"v")
        await c.close()
        await server.stop()

    asyncio.run(first_boot())
    asyncio.run(restart_without_flag())

    # a static-persisted dir restarted with an EXPLICIT --dynamic
    # still errors loudly (the mismatch is a genuine operator bug)
    static_dir = str(tmp_path / "s")

    async def static_boot():
        server = await svcnode.serve(2, 3, 4, port=0,
                                     config=fast_test_config(),
                                     data_dir=static_dir)
        await server.stop()

    async def conflicting_restart():
        with pytest.raises(ValueError):
            await svcnode.serve(2, 3, 4, port=0,
                                config=fast_test_config(),
                                dynamic=True, data_dir=static_dir)

    # ...and the False direction: an embedder explicitly asserting
    # static over a dynamic-persisted dir must ALSO error, not
    # silently come up dynamic (the tri-state contract)
    async def conflicting_static_assertion():
        with pytest.raises(ValueError):
            await svcnode.serve(4, 3, 8, port=0,
                                config=fast_test_config(),
                                dynamic=False, data_dir=data)

    asyncio.run(static_boot())
    asyncio.run(conflicting_restart())
    asyncio.run(conflicting_static_assertion())
