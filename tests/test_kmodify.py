"""kmodify on the batched service (VERDICT r3 #6): server-side
read→fn→CAS retry with the actor plane's funref/MFA discipline
(riak_ensemble_peer.erl:303-317, do_modify_fsm :1404-1416;
riak_ensemble_root.erl:74-90 runs all cluster ops through it)."""

import asyncio

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import funref, svcnode  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.testing import Cluster, make_peers  # noqa: E402
from riak_ensemble_tpu.types import NOTFOUND  # noqa: E402


@funref.register("test:incr")
def _incr(vsn, cur):
    return (int.from_bytes(cur, "big") + 1).to_bytes(4, "big")


@funref.register("test:fail-if-set")
def _fail_if_set(vsn, cur):
    return "failed" if cur != b"\0\0\0\0" else b"\0\0\0\1"


def _svc(tick=None, **kw):
    runtime = Runtime(seed=7)
    svc = BatchedEnsembleService(runtime, 2, 3, n_slots=8, tick=tick,
                                 config=fast_test_config(), **kw)
    return runtime, svc


def _drive(runtime, svc, futs, flushes=40):
    for _ in range(flushes):
        if all(f.done for f in futs):
            break
        svc.flush()
    assert all(f.done for f in futs)
    return [f.value for f in futs]


def test_kmodify_basic_and_default():
    runtime, svc = _svc()
    # absent key: fn sees the default
    f = svc.kmodify(0, "ctr", ("fn", "test:incr", ()),
                    (0).to_bytes(4, "big"))
    _drive(runtime, svc, [f])
    assert f.value[0] == "ok"
    g = svc.kget(0, "ctr")
    _drive(runtime, svc, [g])
    assert g.value == ("ok", (1).to_bytes(4, "big"))
    # present key: fn sees the committed value
    f = svc.kmodify(0, "ctr", ("fn", "test:incr", ()),
                    (0).to_bytes(4, "big"))
    _drive(runtime, svc, [f])
    assert f.value[0] == "ok"
    g = svc.kget(0, "ctr")
    _drive(runtime, svc, [g])
    assert g.value == ("ok", (2).to_bytes(4, "big"))


def test_kmodify_concurrent_increments_serialize():
    """N concurrent kmodifys of one key: all read the same version in
    the first flush, one CAS per device round wins, the losers retry
    — the final value must be exactly +N (the seq discipline the
    reference gets from running the fun inside the leader FSM)."""
    runtime, svc = _svc()
    zero = (0).to_bytes(4, "big")
    futs = [svc.kmodify(0, "ctr", ("fn", "test:incr", ()), zero)
            for _ in range(5)]
    _drive(runtime, svc, futs)
    assert all(f.value[0] == "ok" for f in futs), [f.value for f in futs]
    # all five acked versions are distinct (each saw a unique commit)
    assert len({tuple(f.value[1]) for f in futs}) == 5
    g = svc.kget(0, "ctr")
    _drive(runtime, svc, [g])
    assert g.value == ("ok", (5).to_bytes(4, "big"))


def test_kmodify_fn_abort_and_errors_write_nothing():
    runtime, svc = _svc()
    zero = (0).to_bytes(4, "big")
    f = svc.kmodify(0, "k", ("fn", "test:fail-if-set", ()), b"\0\0\0\7")
    _drive(runtime, svc, [f])
    assert f.value == "failed"
    g = svc.kget(0, "k")
    _drive(runtime, svc, [g])
    assert g.value == ("ok", NOTFOUND)  # aborted modify wrote nothing
    # unregistered funref name: immediate clean failure
    f = svc.kmodify(0, "k", ("fn", "no:such", ()), zero)
    assert f.done and f.value == "failed"

    # a raising mod_fun is contained (traced), resolves 'failed'
    def boom(vsn, cur):
        raise RuntimeError("user bug")
    f = svc.kmodify(0, "k", boom, zero)
    _drive(runtime, svc, [f])
    assert f.value == "failed"
    g = svc.kget(0, "k")
    _drive(runtime, svc, [g])
    assert g.value == ("ok", NOTFOUND)


def test_kmodify_over_the_wire():
    """svcnode ships the funref as plain data; the SERVER's registry
    resolves it (root.erl:82,104 MFA discipline — no code on the
    wire)."""
    async def scenario():
        server = await svcnode.serve(2, 3, 8, port=0,
                                     config=fast_test_config())
        c = svcnode.ServiceClient(server.host, server.port)
        await c.connect()
        zero = (0).to_bytes(4, "big")
        r = await c.kmodify(0, "ctr", funref.ref("test:incr"), zero)
        assert r[0] == "ok", r
        r = await c.kmodify(0, "ctr", funref.ref("test:incr"), zero)
        assert r[0] == "ok", r
        assert await c.kget(0, "ctr") == ("ok", (2).to_bytes(4, "big"))
        # unregistered name fails cleanly, connection survives
        r = await c.call("kmodify", 0, "ctr", ("fn", "no:such", ()),
                         zero)
        assert r == "failed"
        assert await c.kget(0, "ctr") == ("ok", (2).to_bytes(4, "big"))
        await c.close()
        await server.stop()

    asyncio.run(scenario())


def test_kmodify_parity_with_actor_plane():
    """Same observable semantics as the actor stack's kmodify: an
    increment chain over an absent key converges identically."""
    c = Cluster(seed=3)
    peers = make_peers(3)
    c.create_ensemble("e", peers)
    c.wait_stable("e")

    for expect in (1, 2, 3):
        r = c.kmodify("e", "ctr", lambda vsn, v: v + 1, 0)
        assert isinstance(r, tuple) and r[0] == "ok", r
        assert r[1].value == expect
        assert c.kget_value("e", "ctr") == expect

    runtime, svc = _svc()
    zero = NOTFOUND

    def incr_svc(vsn, cur):
        base = 0 if cur is NOTFOUND else int.from_bytes(cur, "big")
        return (base + 1).to_bytes(4, "big")

    for expect in (1, 2, 3):
        f = svc.kmodify(0, "ctr", incr_svc, NOTFOUND)
        _drive(runtime, svc, [f])
        assert f.value[0] == "ok"
        g = svc.kget(0, "ctr")
        _drive(runtime, svc, [g])
        assert g.value == ("ok", expect.to_bytes(4, "big"))
