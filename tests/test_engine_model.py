"""Differential test: the batched device engine vs an independent
scalar Python model of the same protocol semantics.

SURVEY §7 flags the FSM→kernel lift as the main correctness risk and
prescribes differential testing against a scalar oracle.  This model
is written per-ensemble/per-peer with plain loops — deliberately the
opposite implementation shape from the vectorized kernels — and the
test drives both through randomized interleavings of elections
(arbitrary up-masks, bogus candidates), K/V ops (invalid slots, leased
and unleased reads), joint views, and down-peer patterns, comparing
every output field and the full final state.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from riak_ensemble_tpu.ops import engine as eng  # noqa: E402


class ScalarModel:
    """Plain-Python re-derivation of the engine semantics."""

    def __init__(self, m, s, views):
        self.m, self.s = m, s
        self.views = [list(v) for v in views]  # lists of peer indices
        self.epoch = [0] * m
        self.fact_seq = [0] * m
        self.leader = -1
        self.ctr = 0
        self.store = [[(0, 0, 0)] * s for _ in range(m)]  # (epoch,seq,val)

    # -- quorum (msg.erl joint-view majority; no nacks distinct here) --

    def _met(self, ack):
        for view in self.views:
            if not view:
                continue
            thresh = len(view) // 2 + 1
            n_valid = sum(1 for p in view if ack[p])
            n_nack = 0
            if n_valid >= thresh:
                continue
            return False
        return True

    def members(self):
        out = set()
        for v in self.views:
            out.update(v)
        return out

    # -- election ------------------------------------------------------

    def elect(self, do_elect, cand, up):
        member = self.members()
        heard = [up[p] and p in member for p in range(self.m)]
        heard_epochs = [self.epoch[p] for p in range(self.m) if heard[p]]
        next_epoch = (max(heard_epochs) if heard_epochs else -1) + 1
        ack = heard
        won = (self._met(ack) and do_elect and cand >= 0
               and 0 <= cand < self.m and heard[cand])
        if won:
            for p in range(self.m):
                if heard[p]:
                    self.epoch[p] = next_epoch
                    self.fact_seq[p] = 0
            self.leader = cand
            self.ctr = 0
        return won

    # -- kv ------------------------------------------------------------

    def _context(self, up):
        member = self.members()
        heard = [up[p] and p in member for p in range(self.m)]
        has_leader = self.leader >= 0
        lead_epoch = self.epoch[self.leader] if has_leader else 0
        leader_up = has_leader and heard[self.leader]
        ack = [heard[p] and self.epoch[p] == lead_epoch
               for p in range(self.m)]
        epoch_ok = self._met(ack) and has_leader and leader_up
        return heard, leader_up, lead_epoch, epoch_ok

    def kv(self, kind, slot, val, lease_ok, up, ctx=None, exp=(0, 0)):
        heard, leader_up, lead_epoch, epoch_ok = \
            ctx if ctx is not None else self._context(up)
        is_put = kind == eng.OP_PUT
        is_get = kind == eng.OP_GET
        is_cas = kind == eng.OP_CAS
        slot_valid = 0 <= slot < self.s

        # newest among heard replicas at slot
        cands = []
        if slot_valid:
            cands = [self.store[p][slot] for p in range(self.m)
                     if heard[p] and self.store[p][slot][1] > 0]
        if cands:
            emax = max(c[0] for c in cands)
            smax = max(c[1] for c in cands if c[0] == emax)
            vmax = max(c[2] for c in cands
                       if c[0] == emax and c[1] == smax)
            rd_epoch, rd_seq, rd_val, obj_found = emax, smax, vmax, True
        else:
            rd_epoch = rd_seq = rd_val = 0
            obj_found = False
        # val == 0 is the device tombstone: full version discipline,
        # reads back as notfound.
        found = obj_found and rd_val != 0

        get_gate = is_get and leader_up and (lease_ok or epoch_ok)
        stale = obj_found and rd_epoch != lead_epoch
        rewrite = get_gate and stale and epoch_ok
        # all_or_quorum notfound dance: every member replica answered
        # notfound -> serve without writing; otherwise a tombstone must
        # commit at the current epoch (peer.erl:1568-1584).
        member = self.members()
        all_ok = all(heard[p] for p in member)
        nf = get_gate and not obj_found
        # tombstone needs a quorum of (hash-valid) notfound answers;
        # with no corruption in this model, valid answers = heard
        nf_quorum = self._met(heard)
        nf_write = (nf and slot_valid and not all_ok and epoch_ok
                    and nf_quorum)
        get_ok = ((get_gate and obj_found and ((not stale) or rewrite))
                  or (nf and (all_ok or not slot_valid or nf_write)))

        put_commit = is_put and epoch_ok and slot_valid
        # CAS: expected vsn vs the CURRENT stored winner, atomically
        # this round; (0, 0) matches a tombstone (put-once over
        # notfound) or true absence confirmed by a notfound quorum.
        exp_absent = tuple(exp) == (0, 0)
        vsn_match = ((obj_found and (rd_epoch, rd_seq) == tuple(exp))
                     or (exp_absent and obj_found and rd_val == 0)
                     or (exp_absent and not obj_found and nf_quorum))
        cas_commit = is_cas and epoch_ok and slot_valid and vsn_match
        commit = put_commit or cas_commit or rewrite or nf_write
        if commit:
            new_seq = self.ctr + 1
            wval = (val if (is_put or is_cas)
                    else (rd_val if rewrite else 0))
            for p in range(self.m):
                if heard[p]:
                    self.store[p][slot] = (lead_epoch, new_seq, wval)
            self.ctr = new_seq
            out_vsn = (lead_epoch, new_seq)
        elif get_ok and obj_found:
            # read repair: heal heard replicas lagging the winner
            # (maybe_repair, peer.erl:1518-1536); tombstones too
            for p in range(self.m):
                if heard[p] and self.store[p][slot] != (rd_epoch, rd_seq,
                                                        rd_val):
                    self.store[p][slot] = (rd_epoch, rd_seq, rd_val)
            # vsn reported for tombstones too (the notfound obj
            # carries its version, peer.erl:1568-1584)
            out_vsn = (rd_epoch, rd_seq)
        else:
            out_vsn = (0, 0)
        return {
            "committed": commit,
            "get_ok": get_ok,
            "found": found and get_ok,
            "value": rd_val if (get_ok and found) else 0,
            "obj_vsn": out_vsn,
        }

    def kv_scan(self, kinds, slots, vals, leases, up, exps=None):
        # context is computed once per launch (ballot state invariant)
        ctx = self._context(up)
        if exps is None:
            exps = [(0, 0)] * len(kinds)
        out = [self.kv(k, sl, v, lz, up, ctx, xp)
               for k, sl, v, lz, xp in zip(kinds, slots, vals, leases,
                                           exps)]
        self.adopt_epochs(ctx)
        return out

    def adopt_epochs(self, ctx):
        """following({commit, Fact}) catch-up at the END of a launch:
        heard members trailing a live leader's epoch adopt it (they
        nacked THIS launch, ack from the next)."""
        heard, leader_up, lead_epoch, _ = ctx
        if not leader_up:
            return
        for p in range(self.m):
            if heard[p] and self.epoch[p] < lead_epoch:
                self.epoch[p] = lead_epoch


def _random_views(rng, m):
    views = [sorted(rng.choice(m, size=rng.integers(2, m + 1),
                               replace=False).tolist())]
    if rng.random() < 0.4:  # joint consensus
        views.append(sorted(rng.choice(m, size=rng.integers(2, m + 1),
                                       replace=False).tolist()))
    return views


@pytest.mark.parametrize("seed", range(6))
def test_engine_matches_scalar_model(seed):
    rng = np.random.default_rng(seed)
    e, m, s, k = 24, 5, 8, 4
    views_per_ens = [_random_views(rng, m) for _ in range(e)]

    vm = np.zeros((e, 2, m), bool)
    for i, views in enumerate(views_per_ens):
        for vi, view in enumerate(views):
            vm[i, vi, list(view)] = True
    state = eng.init_state(e, m, s)._replace(view_mask=jnp.asarray(vm))
    models = [ScalarModel(m, s, views_per_ens[i]) for i in range(e)]

    for step in range(12):
        up_np = rng.random((e, m)) < 0.8
        if step == 0:
            up_np[:] = True  # first election must succeed somewhere
        up = jnp.asarray(up_np)

        if step % 3 == 0:
            elect_np = rng.random(e) < 0.7
            cand_np = rng.integers(-1, m, e)
            state, won = eng.elect_step(
                state, jnp.asarray(elect_np),
                jnp.asarray(cand_np, jnp.int32), up)
            won_np = np.asarray(won)
            for i in range(e):
                expect = models[i].elect(bool(elect_np[i]),
                                         int(cand_np[i]), up_np[i])
                assert won_np[i] == expect, (seed, step, i)
        else:
            kinds = rng.choice(
                [eng.OP_NOOP, eng.OP_GET, eng.OP_PUT, eng.OP_CAS],
                (k, e)).astype(np.int32)
            slots = rng.integers(-1, s + 1, (k, e)).astype(np.int32)
            vals = rng.integers(1, 1000, (k, e)).astype(np.int32)
            leases = rng.random((k, e)) < 0.5
            # CAS expected versions: mix of the pre-launch stored
            # winner (likely-succeeding), absent (0,0), and garbage.
            exp_e = np.zeros((k, e), np.int32)
            exp_s = np.zeros((k, e), np.int32)
            for j in range(k):
                for i in range(e):
                    if kinds[j, i] != eng.OP_CAS:
                        continue
                    mode = rng.random()
                    sl = slots[j, i]
                    if mode < 0.45 and 0 <= sl < s:
                        md = models[i]
                        cands = [md.store[p][sl] for p in range(m)
                                 if up_np[i, p] and md.store[p][sl][1] > 0]
                        if cands:
                            best = max(cands)
                            exp_e[j, i], exp_s[j, i] = best[0], best[1]
                    elif mode < 0.7:
                        pass  # (0, 0): create-if-missing attempt
                    else:
                        exp_e[j, i] = rng.integers(0, 4)
                        exp_s[j, i] = rng.integers(0, 6)
            state, res = eng.kv_step_scan(
                state, jnp.asarray(kinds), jnp.asarray(slots),
                jnp.asarray(vals), jnp.asarray(leases), up,
                exp_epoch=jnp.asarray(exp_e), exp_seq=jnp.asarray(exp_s))
            committed = np.asarray(res.committed)
            get_ok = np.asarray(res.get_ok)
            found = np.asarray(res.found)
            value = np.asarray(res.value)
            vsn = np.asarray(res.obj_vsn)
            for i in range(e):
                exp = models[i].kv_scan(
                    kinds[:, i], slots[:, i], vals[:, i], leases[:, i],
                    up_np[i],
                    exps=list(zip(exp_e[:, i], exp_s[:, i])))
                for j in range(k):
                    tag = (seed, step, i, j)
                    assert committed[j, i] == exp[j]["committed"], tag
                    assert get_ok[j, i] == exp[j]["get_ok"], tag
                    assert found[j, i] == exp[j]["found"], tag
                    assert value[j, i] == exp[j]["value"], tag
                    assert tuple(vsn[j, i]) == exp[j]["obj_vsn"], tag

    # Full final state must agree replica-for-replica.
    oe = np.asarray(state.obj_epoch)
    osq = np.asarray(state.obj_seq)
    ov = np.asarray(state.obj_val)
    ep = np.asarray(state.epoch)
    ld = np.asarray(state.leader)
    for i in range(e):
        assert ld[i] == models[i].leader
        for p in range(m):
            assert ep[i, p] == models[i].epoch[p], (i, p)
            for sl in range(s):
                assert (oe[i, p, sl], osq[i, p, sl], ov[i, p, sl]) == \
                    models[i].store[p][sl], (i, p, sl)
