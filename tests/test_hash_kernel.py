"""synctree_jax kernel: build/update equivalence, diff exactness,
corruption detection, exchange cost bound (SURVEY §5 long-context
analog; BASELINE.md ladder #4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from riak_ensemble_tpu.ops import hash as hashk

W = 4          # small width for exhaustive tests
S = W ** 3     # 64 segments


def rand_leaves(rng, n=S):
    return jnp.asarray(
        rng.integers(0, 2**32, (n, hashk.LANES), dtype=np.uint32))


def test_build_shapes():
    rng = np.random.default_rng(0)
    levels = hashk.build(rand_leaves(rng), width=W)
    assert [lv.shape[0] for lv in levels] == [1, W, W * W, S]


def test_update_matches_rebuild():
    """Incremental update == full rebuild (the always-up-to-date
    property must not drift from the ground truth)."""
    rng = np.random.default_rng(1)
    leaves = rand_leaves(rng)
    levels = hashk.build(leaves, width=W)

    seg_ids = jnp.asarray([3, 17, 17, 63])  # includes a duplicate
    new = rand_leaves(rng, 4)
    updated = hashk.update(levels, seg_ids, new, width=W)

    ref_leaves = np.asarray(leaves).copy()
    for i, seg in enumerate(np.asarray(seg_ids)):
        ref_leaves[seg] = np.asarray(new)[i]
    rebuilt = hashk.build(jnp.asarray(ref_leaves), width=W)

    for lu, lr in zip(updated, rebuilt):
        np.testing.assert_array_equal(np.asarray(lu), np.asarray(lr))


def test_diff_exact():
    rng = np.random.default_rng(2)
    leaves = rand_leaves(rng)
    a = hashk.build(leaves, width=W)
    changed = [5, 40]
    new = rand_leaves(rng, len(changed))
    b = hashk.update(a, jnp.asarray(changed), new, width=W)

    masks = hashk.diff_levels(a, b)
    leaf_mask = np.asarray(masks[-1])
    assert sorted(np.nonzero(leaf_mask)[0].tolist()) == changed
    # root differs too
    assert bool(np.asarray(masks[0])[0])


def test_diff_identical_is_empty():
    rng = np.random.default_rng(3)
    a = hashk.build(rand_leaves(rng), width=W)
    masks = hashk.diff_levels(a, a)
    assert not any(bool(np.asarray(m).any()) for m in masks)


def test_exchange_cost_bound():
    """One differing segment: the streamed exchange visits at most
    width buckets per level (O(width * height * diffs)), far below the
    S-bucket full scan."""
    rng = np.random.default_rng(4)
    a = hashk.build(rand_leaves(rng), width=W)
    b = hashk.update(a, jnp.asarray([11]), rand_leaves(rng, 1), width=W)
    costs = np.asarray(hashk.exchange_cost(a, b, width=W))
    assert costs[0] == 1
    assert (costs[1:] <= W).all()
    assert costs.sum() < S


def test_verify_detects_corruption():
    rng = np.random.default_rng(5)
    levels = list(hashk.build(rand_leaves(rng), width=W))
    clean = hashk.verify(tuple(levels), width=W)
    assert not any(bool(np.asarray(m).any()) for m in clean)

    # corrupt one inner bucket at level 2
    lv2 = np.asarray(levels[2]).copy()
    lv2[7] ^= 0xDEAD
    levels[2] = jnp.asarray(lv2)
    masks = hashk.verify(tuple(levels), width=W)
    # level-1 recompute-from-children mismatches at bucket 7's parent?
    # No: verify flags the STORED parent vs recomputed-from-children —
    # corrupting level 2 makes (a) level-1's stored value stale at
    # bucket 7//W and (b) level-2 recomputed-from-level-3 mismatch at
    # bucket 7.
    assert bool(np.asarray(masks[1])[7 // W]) or \
        bool(np.asarray(masks[2])[7])


def test_leaf_hash_version_sensitivity():
    h1 = hashk.leaf_hash(jnp.asarray([1]), jnp.asarray([1]))
    h2 = hashk.leaf_hash(jnp.asarray([1]), jnp.asarray([2]))
    h3 = hashk.leaf_hash(jnp.asarray([2]), jnp.asarray([1]))
    assert not np.array_equal(np.asarray(h1), np.asarray(h2))
    assert not np.array_equal(np.asarray(h1), np.asarray(h3))
    assert not np.array_equal(np.asarray(h2), np.asarray(h3))


def test_million_segment_build_compiles():
    """The production shape (1M segments, width 16 — synctree.erl
    :88-89) builds and updates under jit."""
    rng = np.random.default_rng(6)
    segs = 16 ** 5
    leaves = jnp.zeros((segs, hashk.LANES), jnp.uint32)
    levels = hashk.build(leaves, width=16)
    assert levels[0].shape == (1, hashk.LANES)
    ids = jnp.asarray(rng.integers(0, segs, 256))
    new = jnp.asarray(
        rng.integers(0, 2**32, (256, hashk.LANES), dtype=np.uint32))
    updated = hashk.update(levels, ids, new, width=16)
    leaf_mask = np.asarray(
        hashk.diff_levels(levels, updated)[-1])
    assert set(np.nonzero(leaf_mask)[0]) == set(np.asarray(ids).tolist())


def test_update_duplicate_seg_ids_last_write_wins():
    """A batch with duplicate segment ids is a sequence of inserts:
    the final occurrence must win deterministically (JAX scatter order
    with duplicates is otherwise unspecified)."""
    segs = 16 ** 2
    leaves = jnp.zeros((segs, hashk.LANES), jnp.uint32)
    levels = hashk.build(leaves, width=16)
    ids = jnp.asarray([7, 3, 7, 7, 3])
    rng = np.random.default_rng(5)
    new = jnp.asarray(rng.integers(0, 2 ** 32, (5, hashk.LANES),
                                   dtype=np.uint32))
    got = hashk.update(levels, ids, new, width=16)
    # sequential oracle
    want = levels
    for i in range(5):
        want = hashk.update(want, ids[i:i + 1], new[i:i + 1], width=16)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- fold quality: the detection properties the parallel-mix form
# -- claims (uniformity + avalanche + order sensitivity) -------------


def test_fold_avalanche():
    """A single flipped bit in any child flips ~half the parent bits
    (the corruption-detection property the round-4 parallel-mix fold
    must preserve from the chained form)."""
    rng = np.random.default_rng(0)
    children = np.asarray(rng.integers(0, 2**32, (16, hashk.LANES)),
                          dtype=np.uint32)
    base = np.asarray(hashk.fold(jnp.asarray(children)))
    fracs = []
    for trial in range(64):
        i = rng.integers(0, 16)
        lane = rng.integers(0, hashk.LANES)
        bit = rng.integers(0, 32)
        mut = children.copy()
        mut[i, lane] ^= np.uint32(1) << np.uint32(bit)
        out = np.asarray(hashk.fold(jnp.asarray(mut)))
        assert (out != base).any(), "flip went undetected"
        diff = np.bitwise_xor(out, base)
        nbits = sum(int(x).bit_count() for x in diff.ravel())
        fracs.append(nbits / (32 * hashk.LANES))
    mean = float(np.mean(fracs))
    assert 0.40 < mean < 0.60, f"avalanche degraded: {mean:.3f}"


def test_fold_order_and_position_sensitivity():
    """Swapping two distinct children, or moving a value to a
    different position among zeros, changes the parent (the position
    salt)."""
    rng = np.random.default_rng(1)
    children = np.asarray(rng.integers(0, 2**32, (16, hashk.LANES)),
                          dtype=np.uint32)
    base = np.asarray(hashk.fold(jnp.asarray(children)))
    swapped = children.copy()
    swapped[[2, 9]] = swapped[[9, 2]]
    assert (np.asarray(hashk.fold(jnp.asarray(swapped))) != base).any()

    for pos in range(1, 16):
        a = np.zeros((16, hashk.LANES), np.uint32)
        b = np.zeros((16, hashk.LANES), np.uint32)
        a[0] = 12345
        b[pos] = 12345
        assert (np.asarray(hashk.fold(jnp.asarray(a)))
                != np.asarray(hashk.fold(jnp.asarray(b)))).any(), pos


def test_fold_collision_smoke():
    """10k random child blocks -> 10k distinct parents (128-bit lanes
    make true collisions astronomically unlikely; a structural flaw in
    the mix would show up immediately)."""
    rng = np.random.default_rng(2)
    blocks = np.asarray(
        rng.integers(0, 2**32, (10_000, 16, hashk.LANES)),
        dtype=np.uint32)
    outs = np.asarray(hashk.fold(jnp.asarray(blocks)))
    view = {tuple(int(v) for v in row) for row in outs}
    assert len(view) == 10_000


def test_fold_compensated_swap_no_collision():
    """Regression (round-5 ADVICE): format 2's fold pre-mixed children
    LINEARLY (child*C1 + pos*C2 + lane), so replacing children (a, b)
    at positions (p, q) with (b+d, a-d), d = (q-p)*C2*C1^-1 mod 2^32,
    preserved the pre-mix multiset and collided deterministically.
    Format 3 xors an avalanched position salt and multiplies by a
    per-position odd constant, so neither additive nor xor shifts can
    compensate a swap."""
    # C1^-1 mod 2^32 (C1 is odd, hence invertible)
    c1, c2 = 0xCC9E2D51, 0x1B873593
    c1_inv = pow(c1, -1, 2**32)
    rng = np.random.default_rng(7)
    for trial in range(100):
        children = np.asarray(
            rng.integers(0, 2**32, (16, hashk.LANES)), dtype=np.uint32)
        base = np.asarray(hashk.fold(jnp.asarray(children)))
        p, q = sorted(rng.choice(16, size=2, replace=False))
        d = np.uint32((int(q - p) * c2 * c1_inv) % 2**32)
        # the exact format-2 attack: additive-compensated swap
        add = children.copy()
        add[p] = children[q] + d
        add[q] = children[p] - d
        assert (np.asarray(hashk.fold(jnp.asarray(add))) != base).any(), \
            f"additive compensated swap collided (trial {trial})"
        # the analogous xor-compensated swap (defeats a salt-only fix)
        for delta in (np.uint32(d), np.uint32(trial + 1)):
            xr = children.copy()
            xr[p] = children[q] ^ delta
            xr[q] = children[p] ^ delta
            assert (np.asarray(hashk.fold(jnp.asarray(xr)))
                    != base).any(), \
                f"xor compensated swap collided (trial {trial})"


def test_fold_plain_swap_with_shift_sweep():
    """Broader structured-collision sweep: swapping two children and
    shifting both by ANY small constant (add or xor, d in 1..64) never
    collides — simple arithmetic relationships between siblings must
    not cancel the position salts."""
    rng = np.random.default_rng(8)
    children = np.asarray(
        rng.integers(0, 2**32, (16, hashk.LANES)), dtype=np.uint32)
    base = np.asarray(hashk.fold(jnp.asarray(children)))
    for d in range(1, 65):
        du = np.uint32(d)
        add = children.copy()
        add[0], add[1] = children[1] + du, children[0] - du
        assert (np.asarray(hashk.fold(jnp.asarray(add))) != base).any()
        xr = children.copy()
        xr[0], xr[1] = children[1] ^ du, children[0] ^ du
        assert (np.asarray(hashk.fold(jnp.asarray(xr))) != base).any()
