"""Fleet-scope observability (docs/ARCHITECTURE.md §11, round 13).

Unit coverage for the fleet primitives (NTP-midpoint clock offsets
with their asymmetry-proof bound, the Prometheus multi-host merge,
the span store's structured misses, flight-dump rotation), a
deterministic TWO-PROCESS federation smoke — in-process leader plus
a SUBPROCESS replica host, so the span stores are genuinely separate
processes joined only by fids and offsets — and a ``slow``-marked
live 3-host merge under a 5 ms injected one-way RTT (the PR 9 fault
plane as the skew generator) asserting alignment stays within the
estimated offset bound."""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import faults, obs, wire  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.obs import fleet  # noqa: E402
from riak_ensemble_tpu.obs.flightrec import DUMP_SCHEMA  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- clock offsets -----------------------------------------------------------

def test_clock_offset_bound_holds_under_any_asymmetry():
    """The NTP-midpoint invariant: for ANY split of a round-trip
    into request/response delay, |estimate − truth| <= bound.  This
    is the property every alignment assertion downstream leans on."""
    true_offset = 37.5  # remote clock runs this far ahead
    for d_req, d_resp in ((0.001, 0.001), (0.005, 0.0005),
                          (0.0001, 0.008), (0.01, 0.0)):
        c = fleet.ClockOffset()
        t0 = 100.0
        t_remote = t0 + d_req + true_offset
        t1 = t0 + d_req + d_resp
        c.update(t0, t_remote, t1)
        off, bound = c.estimate(now=t1)
        assert abs(off - true_offset) <= bound + 1e-12, \
            (d_req, d_resp, off, bound)


def test_clock_offset_prefers_tight_samples_and_ages_bound():
    c = fleet.ClockOffset()
    c.update(0.0, 50.05, 0.1)    # sloppy: ±50 ms
    c.update(1.0, 51.001, 1.002)  # tight: ±1 ms
    off, bound = c.estimate(now=1.002)
    assert bound < 0.002 and abs(off - 50.0) < 0.001
    # the tight sample's bound widens with age (drift allowance);
    # the estimator must never claim yesterday's precision today
    _off2, bound2 = c.estimate(now=1000.0)
    assert bound2 > bound
    s = c.section()
    assert s["samples"] == 2 and "offset_ms" in s
    assert fleet.ClockOffset().section() == {"samples": 0}
    # a nonsensical window (t1 < t0) is dropped, not folded
    c.update(5.0, 55.0, 4.0)
    assert c.samples == 2


# -- prometheus merge --------------------------------------------------------

def test_merge_prometheus_groups_families_and_labels_hosts():
    r1, r2 = obs.MetricsRegistry(), obs.MetricsRegistry()
    r1.counter("retpu_x_total", "a counter").inc(3)
    r1.histogram("retpu_h_ms").record(2.0)
    r2.counter("retpu_x_total", "a counter").labels('we"ird').inc(5)
    r2.gauge("retpu_g", "a gauge").set(7)
    txt = fleet.merge_prometheus(
        {"a:1": r1.render_prometheus(),
         "b:2": r2.render_prometheus(),
         "dead:3": None})  # unreachable host: skipped, not crashed
    # ONE header block per family even though both hosts export it
    assert txt.count("# TYPE retpu_x_total counter") == 1
    assert 'retpu_x_total{host="a:1"} 3' in txt
    # host label composes with existing (hostile) labels
    assert 'retpu_x_total{host="b:2",tenant="we\\"ird"} 5' in txt
    assert 'retpu_h_ms_bucket{host="a:1",le="0.05"}' in txt
    assert 'retpu_h_ms_count{host="a:1"} 1' in txt
    assert 'retpu_g{host="b:2"} 7' in txt
    # samples of a family merge under one block: no second TYPE line
    # between the two hosts' retpu_x_total samples
    block = txt.split("# TYPE retpu_x_total counter")[1]
    block = block.split("# ")[0]
    assert 'host="a:1"' in block and 'host="b:2"' in block
    # idempotent injection: an already host-labeled sample (a
    # re-merged fleet section, or a family whose own label is host)
    # must NOT grow a duplicate host label — Prometheus rejects the
    # whole document on duplicate label names
    pre = 'retpu_y{host="x:9",peer="p"} 1'
    assert fleet.inject_host_label(pre, "z:1") == pre
    merged2 = fleet.merge_prometheus({"z:1": pre + "\n"})
    assert merged2.count('host="') == 1


def test_registry_render_prometheus_host_kwarg():
    r = obs.MetricsRegistry()
    r.counter("retpu_x_total").inc()
    txt = r.render_prometheus(host="h:9")
    assert 'retpu_x_total{host="h:9"} 1' in txt
    # header lines pass through unlabeled
    assert "# TYPE retpu_x_total counter" in txt


# -- span store structured misses -------------------------------------------

def test_span_store_structured_miss_and_counters():
    s = obs.SpanStore(max_flushes=2)
    s.record(1, "leader", [("a", 0.1)])
    s.record(2, "leader", [("a", 0.1)])
    s.record(3, "leader", [("a", 0.1)])  # evicts fid 1
    hit = s.timeline(2)
    assert "miss" not in hit and hit["leader"]
    assert s.timeline(1) == {"flush_id": 1, "miss": "evicted"}
    assert s.timeline(99) == {"flush_id": 99, "miss": "unknown"}
    assert s.misses == {"evicted": 1, "unknown": 1}
    # span_values: absent fids count a miss and contribute nothing
    vals = s.span_values([2, 1, 99], "leader", "a")
    assert vals == [0.1]
    assert s.misses == {"evicted": 2, "unknown": 2}
    # the service registry exports the process-global store's counts
    svc = BatchedEnsembleService(WallRuntime(), 2, 1, 4, tick=None,
                                 max_ops_per_tick=2)
    snap = svc.obs_registry.snapshot()
    assert set(snap["retpu_span_misses_total"]) == {"evicted",
                                                    "unknown"}
    svc.stop()


# -- flight-dump rotation ----------------------------------------------------

def test_flight_dump_rotation_bounds_dir(tmp_path, monkeypatch):
    """A long soak with a flapping trigger must not fill the disk:
    the dump dir retains at most RETPU_OBS_DUMP_KEEP files,
    oldest-first unlinked, newest (the live evidence) kept."""
    monkeypatch.setenv("RETPU_OBS_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("RETPU_OBS_DUMP_KEEP", "3")
    fr = obs.FlightRecorder(capacity=32, min_samples=8,
                            min_dump_interval_s=0.0, name="t",
                            max_dumps=64)
    for i in range(16):
        assert fr.record({"flush_id": i, "total": 0.01}) is None
    paths = []
    for i in range(8):
        snap = fr.record({"flush_id": 100 + i, "total": 1.0})
        assert snap is not None and "path" in snap
        paths.append(snap["path"])
        # distinct mtimes so oldest-first is deterministic on
        # coarse-mtime filesystems
        t = time.time() - (8 - i)
        os.utime(snap["path"], (t, t))
        fr._rotate(str(tmp_path))
    left = sorted(p for p in os.listdir(tmp_path)
                  if p.endswith(".json"))
    assert len(left) == 3, left
    # the newest dumps survived; the oldest were unlinked
    assert os.path.basename(paths[-1]) in left
    assert os.path.basename(paths[0]) not in left
    # keep<=0 disables rotation
    monkeypatch.setenv("RETPU_OBS_DUMP_KEEP", "0")
    snap = fr.record({"flush_id": 999, "total": 1.0})
    assert snap is not None
    assert len([p for p in os.listdir(tmp_path)
                if p.endswith(".json")]) == 4


# -- bench-trend box grouping ------------------------------------------------

def test_bench_trend_never_ratchets_across_fingerprints(tmp_path):
    """Two synthetic fingerprints: the newest round on a NEW box must
    never be ratcheted against the old box's best (cross-box
    absolute-ms comparisons are weather), while a same-box regression
    still trips — and the table draws the boundary explicitly."""
    from tools import bench_trend

    box_a = {"cpu_count": 2, "jax": "j", "jaxlib": "jl",
             "platform": "cpu"}
    box_b = {"cpu_count": 96, "jax": "j", "jaxlib": "jl",
             "platform": "tpu"}
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": {"value": 1000.0, "box": box_a}}))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": {"value": 900.0, "box": box_a}}))
    # a 100x "regression" on a DIFFERENT box: not comparable, passes
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"parsed": {"value": 10.0, "box": box_b}}))
    rep = bench_trend.check(str(tmp_path), tolerance=0.5)
    assert rep["comparable_rounds"] == 0
    assert rep["best_same_box_ops_per_sec"] is None
    # same box again, out-of-band: trips
    (tmp_path / "BENCH_r04.json").write_text(
        json.dumps({"parsed": {"value": 400.0, "box": box_a}}))
    with pytest.raises(bench_trend.TrendError):
        bench_trend.check(str(tmp_path), tolerance=0.5)
    # grouping + explicit boundary rendering
    rows = bench_trend.trajectory(
        bench_trend.load_rounds(str(tmp_path)))
    groups = bench_trend.box_groups(rows)
    assert [len(g) for _k, g in groups] == [2, 1, 1]
    table = bench_trend.render_table(rows)
    assert table.count("box change") == 2
    assert "cpu2 -> cpu96" in table


# -- watchdog pending-pull expiry -------------------------------------------

def test_watchdog_expires_orphaned_pulls():
    """A silent fault plan consumes obsq frames without ever firing
    their tickets; the watchdog must EXPIRE such orphans (counted as
    failures) instead of letting them hit the pending cap and wedge
    the standing pull forever — liveness past the heal."""
    import threading

    from riak_ensemble_tpu.obs.watchdog import AnomalyWatchdog

    class _Tk:
        def __init__(self):
            self.event = threading.Event()  # never fires

    class _Svc:
        pipeline_depth = 1
        _links = ()

    wd = AnomalyWatchdog(_Svc(), cadence=1)
    old = time.monotonic() - wd.PULL_EXPIRE_S - 1.0
    fresh = time.monotonic()
    wd._pending = [(None, [1], _Tk(), old),
                   (None, [2], _Tk(), fresh)]
    wd.evaluate()
    # the stale orphan dropped (a failure); the fresh one survives
    assert wd.pull_failures == 1
    assert len(wd._pending) == 1 and wd._pending[0][1] == [2]


# -- fleet trace export ------------------------------------------------------

def test_fleet_trace_export_per_host_tracks(tmp_path):
    """Aligned fleet timelines render as ONE merged Chrome trace with
    per-HOST tracks at their clock-aligned times (not the ordinal
    layout the single-store exporter uses), and the CLI round-trips
    a JSON file of them."""
    from tools import trace_export

    def tl(fid, base, lead_start, rep_start):
        return {
            "flush_id": fid, "schema": "retpu-fleet-timeline-v1",
            "base_s": base,
            "clock": {"h:1": {"offset_ms": 0.1, "bound_ms": 0.2,
                              "samples": 3}},
            "roles": {
                "leader": {"host": "me:0", "aligned": True,
                           "bound_ms": 0.0,
                           "spans": [["enqueue", lead_start, 0.001],
                                     ["repl_ack", lead_start + 0.001,
                                      0.002]]},
                "replica@h:1": {"host": "h:1", "aligned": True,
                                "bound_ms": 0.2,
                                "spans": [["apply", rep_start,
                                           0.0015]]},
            },
        }

    tls = [tl(7, 100.0, 0.0, 0.0005), tl(8, 100.01, 0.0, 0.0004)]
    events = trace_export.fleet_trace_events(tls)
    pids = {e["pid"] for e in events}
    assert pids == {"me:0", "h:1"}
    rep = [e for e in events if e["pid"] == "h:1"
           and e["args"]["flush_id"] == 7][0]
    # aligned placement: the replica span sits at its aligned start
    # (µs), inside the leader's flush window — not stacked ordinally
    assert abs(rep["ts"] - 500.0) < 1e-6
    assert rep["args"]["bound_ms"] == 0.2
    # the second flush's events shift by the base delta (10 ms)
    rep2 = [e for e in events if e["pid"] == "h:1"
            and e["args"]["flush_id"] == 8][0]
    assert abs(rep2["ts"] - (10_000.0 + 400.0)) < 1e-6
    # CLI round-trip
    src = tmp_path / "fleet.json"
    src.write_text(json.dumps(tls))
    out = tmp_path / "trace.json"
    assert trace_export.main(["--fleet-timelines", str(src),
                              "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert len(doc["traceEvents"]) == len(events)
    # empty/missing-role inputs degrade to an empty event list
    assert trace_export.fleet_trace_events([{}]) == []


# -- standalone fleet surfaces ----------------------------------------------

def test_fleet_verbs_standalone_service_and_svcnode():
    """On a linkless service the fleet IS this host: the verbs answer
    the same shapes (one host, trivial clock) so a dashboard works
    before the group does — and they ride the svcnode wire."""
    import asyncio

    from riak_ensemble_tpu import svcnode

    async def run():
        server = await svcnode.serve(4, 3, 8, port=0, tick=0.002,
                                     config=fast_test_config())
        client = svcnode.ServiceClient(server.host, server.port)
        await client.connect()
        try:
            r = await client.kput(0, "k", b"v")
            assert r[0] == "ok"
            fh = await client.fleet_health()
            assert fh["schema"] == "retpu-fleet-health-v1"
            (label,) = fh["hosts"]
            assert fh["hosts"][label]["schema"] == "retpu-health-v1"
            fm = await client.fleet_metrics()
            assert fm["schema"] == "retpu-fleet-metrics-v1"
            assert fm["hosts"][label]["retpu_flushes_total"] >= 1
            txt = await client.fleet_metrics("prometheus")
            assert f'host="{label}"' in txt
            assert txt.count("# TYPE retpu_flushes_total counter") == 1
            # a real fid aligns trivially; a bogus one is a
            # structured miss, and hostile fids are rejected
            st = await client.call("stats")
            assert st["flushes"] >= 1
            tl = await client.fleet_timeline(1)
            assert tl["schema"] == "retpu-fleet-timeline-v1"
            bad = await client.call("fleet", "timeline", "x")
            assert bad == ("error", "bad-request")
            bad2 = await client.call("fleet", "nope")
            assert bad2 == ("error", "bad-request")
        finally:
            await client.close()
            await server.stop()

    asyncio.run(run())


# -- two-process federation smoke -------------------------------------------

def _spawn_replica(n_ens, n_slots, tmp, procs):
    """One SUBPROCESS replica host (a genuinely separate span store —
    the federation smoke's whole point); registered in ``procs``
    before the ready-line parse so it can never leak."""
    child = textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {REPO!r})
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          {REPO!r} + "/.jax_cache")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
        from riak_ensemble_tpu.parallel import repgroup
        repgroup.main(["--n-ens", "{n_ens}", "--group-size", "2",
                       "--n-slots", "{n_slots}", "--fast",
                       "--data-dir", {tmp!r} + "/r1"])
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True,
                         env=env)
    procs.append(p)
    line = p.stdout.readline()
    assert line, "replica subprocess died before its ready line"
    parts = dict(kv.split("=") for kv in line.split()[2:])
    import threading
    threading.Thread(target=lambda f=p.stdout: [None for _ in f],
                     daemon=True).start()
    return int(parts["repl"])


def test_two_process_federation_smoke(tmp_path, monkeypatch):
    """Acceptance (deterministic tier-1 shape): in-process leader +
    subprocess replica.  Fleet metrics/health merge both hosts, the
    fleet timeline joins the subprocess's replica spans onto the
    leader's axis within the estimated offset bound, and a triggered
    slow flush writes ONE correlated dump (schema v4) carrying the
    replica's matching span records — round-tripped through JSON."""
    import signal

    monkeypatch.setenv("RETPU_OBS_DUMP_DIR", str(tmp_path / "dumps"))
    procs = []
    svc = None
    try:
        repl_port = _spawn_replica(4, 8, str(tmp_path), procs)
        svc = repgroup.ReplicatedService(
            WallRuntime(), 4, 1, 8, group_size=2,
            peers=[("127.0.0.1", repl_port)], ack_timeout=60.0,
            max_ops_per_tick=4, config=fast_test_config(),
            data_dir=str(tmp_path / "leader"))
        repgroup.warmup_kernels(svc)
        assert svc.takeover()
        futs = [svc.kput_many(e, ["a", "b"], [b"1", b"2"])
                for e in range(4)]
        while any(svc.queues):
            svc.flush()
        assert svc.heartbeat()
        svc._drain_pending(block_all=True)
        assert all(f.done for f in futs)

        # fleet metrics: BOTH processes under host labels, one scrape
        fm = svc.fleet_metrics()
        assert len(fm["hosts"]) == 2, sorted(fm["hosts"])
        (link,) = svc._links
        assert link.label in fm["hosts"]
        assert fm["hosts"][link.label]["retpu_flushes_total"] >= 1
        txt = svc.fleet_metrics("prometheus")
        assert txt.count("# TYPE retpu_flushes_total counter") == 1
        assert f'host="{link.label}"' in txt
        # a valid exposition document: no sample may carry two
        # host labels (the clock gauges label their dimension
        # `peer` for exactly this reason)
        for ln in txt.splitlines():
            assert ln.count('host="') <= 1, ln
        fh = svc.fleet_health()
        assert len(fh["hosts"]) == 2
        rep_health = fh["hosts"][link.label]
        assert rep_health["schema"] == "retpu-health-v1"
        assert rep_health["group"]["leader"] is False
        # every fleet answer rides the restricted wire codec
        wire.encode(fm)
        wire.encode(fh)

        # clock: same machine, so truth is ~0 — the estimate must
        # honor its own bound (the NTP invariant, live)
        est = link.clock.section()
        assert est["samples"] >= 1
        assert abs(est["offset_ms"]) <= est["bound_ms"] + 0.5, est

        # aligned cross-host timeline: the subprocess's replica side
        # joins the leader's on ONE axis
        joined = None
        for fid in reversed(obs.SPANS.flush_ids()):
            tl = svc.fleet_timeline(fid)
            reps = [r for r in tl.get("roles", ())
                    if str(r).startswith("replica")]
            if reps and "leader" in tl["roles"]:
                joined = (tl, reps)
                break
        assert joined, "no flush joined leader + subprocess spans"
        tl, reps = joined
        wire.encode(tl)
        lead = tl["roles"]["leader"]
        assert lead["aligned"] and lead["host"] == \
            svc._fleet_self_label()
        for r in reps:
            side = tl["roles"][r]
            assert side["aligned"], tl
            assert side["host"] == link.label
            assert side["bound_ms"] > 0.0
            names = [n for n, _s, _d in side["spans"]]
            assert "apply" in names
            # spans are laid out on the shared axis: start offsets
            # are non-negative and within the flush's neighborhood
            assert all(s >= 0.0 for _n, s, _d in side["spans"])

        # correlated flight dump: a >5x-p50 flush pulls the
        # replica's matching records into ONE schema-v4 file
        svc.flight = obs.FlightRecorder(min_samples=8,
                                        refresh_every=2,
                                        min_dump_interval_s=0.0,
                                        name="svc")
        for i in range(10):
            fut = svc.kput(i % 4, "w", b"v%d" % i)
            while not fut.done:
                svc.flush()
        stall = max(6.0 * svc.flight._p50, 0.05)
        orig = svc._fetch_packed

        def slow_fetch(fl):
            time.sleep(stall)
            return orig(fl)

        monkeypatch.setattr(svc, "_fetch_packed", slow_fetch)
        fut = svc.kput(0, "w", b"slow")
        while not fut.done:
            svc.flush()
        monkeypatch.setattr(svc, "_fetch_packed", orig)
        assert svc.flight.anomalies >= 1
        snap = svc.flight.dumps[-1]
        assert snap["schema"] == DUMP_SCHEMA == "retpu-flight-dump-v4"
        with open(snap["path"]) as f:
            data = json.load(f)
        assert link.label in data["hosts"], sorted(data["hosts"])
        spans = data["hosts"][link.label].get("spans") or {}
        real = {int(f): tl for f, tl in spans.items()
                if isinstance(tl, dict) and not tl.get("miss")}
        assert real, "correlated dump carries no replica records"
        some = next(iter(real.values()))
        assert any(str(r).startswith("replica") for r in some)
        assert data["clock_offsets"][link.label]["samples"] >= 1
        assert isinstance(data["watchdog_findings"], list)
        # the structured misses distinguish lag from loss: fids the
        # replica never saw answer "unknown", never bare None
        for f, tl_ in spans.items():
            if isinstance(tl_, dict) and tl_.get("miss"):
                assert tl_["miss"] in ("evicted", "unknown")
    finally:
        if svc is not None:
            svc.stop()
        for p in procs:
            try:
                p.send_signal(signal.SIGKILL)
            except ProcessLookupError:
                pass


# -- live 3-host merge under injected skew (slow lane) -----------------------

@pytest.mark.slow
def test_three_host_merge_under_injected_rtt(tmp_path, monkeypatch):
    """Acceptance (live): a 3-host group under a 5 ms injected
    ONE-WAY RTT (the PR 9 fault plane as the skew generator).  One
    ``fleet_timeline(fid)`` call returns leader and replica spans on
    a single aligned axis with skew within the estimated offset
    bound; one Prometheus scrape carries all three hosts; a
    triggered slow flush produces ONE correlated dump with all
    hosts' records for its fids."""
    monkeypatch.setenv("RETPU_OBS_DUMP_DIR", str(tmp_path / "dumps"))
    servers = [repgroup.ReplicaServer(4, 3, 8,
                                      data_dir=str(tmp_path / f"r{i}"),
                                      config=fast_test_config())
               for i in (1, 2)]
    svc = repgroup.ReplicatedService(
        WallRuntime(), 4, 1, 8, group_size=3,
        peers=[("127.0.0.1", s.repl_port) for s in servers],
        ack_timeout=60.0, max_ops_per_tick=4,
        config=fast_test_config(), data_dir=str(tmp_path / "leader"))
    repgroup.warmup_kernels(svc)
    try:
        assert svc.takeover()
        # the skew generator: 5 ms one-way on every replica→leader
        # RESPONSE — the PR 9 injected-ack-RTT scenario, and the
        # WORST case for a midpoint estimator (a fully asymmetric
        # window: error -> one-way/2, still inside the bound by
        # construction).  Leader→request injection would land before
        # the ticket's wire re-stamp (queue dwell, by design), so
        # the return path is where a slow wire is visible.
        plan = faults.install(faults.FaultPlan())
        plan.set_rtt("*", faults.LOCAL, 5.0)
        try:
            futs = [svc.kput_many(e, ["a", "b"], [b"1", b"2"])
                    for e in range(4)]
            while any(svc.queues):
                svc.flush()
            assert svc.heartbeat()
            svc._drain_pending(block_all=True)
            assert all(f.done for f in futs)

            # one scrape, three hosts
            txt = svc.fleet_metrics("prometheus")
            hosts = {ln.split('host="')[1].split('"')[0]
                     for ln in txt.splitlines()
                     if ln.startswith("retpu_flushes_total{")}
            assert len(hosts) == 3, hosts

            # alignment within the estimated bound: same box, so the
            # TRUE offset is ~0 — the estimator's claim must cover it
            # even under the asymmetric 5 ms injection
            for link in svc._links:
                est = link.clock.section()
                assert est["samples"] >= 1
                assert abs(est["offset_ms"]) <= est["bound_ms"], est
                # the injected asymmetry really stretched the bound
                assert est["bound_ms"] >= 2.0, est

            joined = None
            for fid in reversed(obs.SPANS.flush_ids()):
                tl = svc.fleet_timeline(fid)
                reps = [r for r in tl.get("roles", ())
                        if str(r).startswith("replica")]
                if len(reps) == 2 and "leader" in tl["roles"]:
                    joined = tl
                    break
            assert joined, "no flush joined all three hosts"
            assert all(i["aligned"] for i in joined["roles"].values())

            # correlated dump under skew
            svc.flight = obs.FlightRecorder(min_samples=8,
                                            refresh_every=2,
                                            min_dump_interval_s=0.0,
                                            name="svc")
            for i in range(10):
                fut = svc.kput(i % 4, "w", b"v%d" % i)
                while not fut.done:
                    svc.flush()
            stall = max(6.0 * svc.flight._p50, 0.05)
            orig = svc._fetch_packed
            monkeypatch.setattr(
                svc, "_fetch_packed",
                lambda fl: (time.sleep(stall), orig(fl))[1])
            fut = svc.kput(0, "w", b"slow")
            while not fut.done:
                svc.flush()
            monkeypatch.setattr(svc, "_fetch_packed", orig)
            assert svc.flight.anomalies >= 1
            snap = svc.flight.dumps[-1]
            assert snap["schema"] == "retpu-flight-dump-v4"
            assert len(snap["hosts"]) == 2  # + the leader's own ring
            for label, sect in snap["hosts"].items():
                assert sect.get("spans"), (label, sect)
        finally:
            faults.clear()
    finally:
        svc.stop()
        for s in servers:
            s.stop()
