"""Delta replication transport (ISSUE 5 tentpole).

The leader→replica apply stream ships changed-slot DELTA frames (wire
cost proportional to what committed, not to the [K, E] grid), coalesced
into one raw frame per flush per link, applied by the replica IN PLACE
through one scatter + mirror/WAL pass.  These tests pin the load-
bearing contract: a replica lane fed deltas must be BIT-EQUAL to the
full-plane re-execution reference — which is exactly the leader's own
lane — across every keyed storage class, across elections (the
full-plane fallback), across re-syncs and install barriers, and across
arbitrary coalescing boundaries.  Plus the raw-buffer wire section the
frames ride on (zero-copy scatter-gather encode, memoryview decode,
native/python parity, hostile-frame rejection).
"""

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

import conftest  # noqa: F401

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import funref, wire  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.ops import engine as eng  # noqa: E402
from riak_ensemble_tpu.parallel import repgroup  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService, WallRuntime)

N_ENS = 4
N_SLOTS = 8
GROUP = 3


# -- harness -----------------------------------------------------------------


def _group(tmp_path, n_ens=N_ENS, n_slots=N_SLOTS, **leader_kw):
    """In-process group: leader + 2 threaded ReplicaServer hosts (one
    jit cache, no subprocess compile) — the delta/full equivalence
    harness, where both replica lanes are directly inspectable."""
    srvs = [repgroup.ReplicaServer(
        n_ens, GROUP, n_slots, data_dir=str(tmp_path / f"r{i}"),
        config=fast_test_config()) for i in (1, 2)]
    svc = repgroup.ReplicatedService(
        WallRuntime(), n_ens, 1, n_slots, group_size=GROUP,
        peers=[("127.0.0.1", s.repl_port) for s in srvs],
        ack_timeout=15.0, config=fast_test_config(),
        data_dir=str(tmp_path / "leader"), **leader_kw)
    repgroup.warmup_kernels(svc)
    assert svc.takeover(), "takeover needs a replica majority"
    return svc, srvs


def _settle(svc, futs, budget=30.0):
    end = time.time() + budget
    while not all(f.done for f in futs) and time.time() < end:
        svc.flush()
    assert all(f.done for f in futs), "futures never settled"
    return [f.value for f in futs]


def _canon(svc):
    """Canonical lane state: engine arrays verbatim + order-insensitive
    keyed mirrors (dict/list iteration order is process history, not
    replicated state)."""
    fields, host = repgroup.dump_state(svc)
    (key_slot, slot_handle, values, _nh, leader_b, dyn, live_b,
     free_rows, ens_names, member_b, inline) = host
    return (fields,
            [sorted(p) for p in key_slot],
            [sorted(p) for p in slot_handle],
            sorted(values),
            leader_b, dyn, member_b,
            [sorted(s) for s in inline])


def _assert_lanes_equal(svc, srvs):
    """THE acceptance invariant: the leader executed every launch for
    real (the full-plane reference); a delta-fed replica must hold the
    bit-identical lane."""
    for _ in range(2):
        svc.heartbeat()
    svc._drain_pending(block_all=True)
    # the commit barrier settles at MAJORITY: a replica that just
    # consumed a catch-up install may still be grinding the batch
    # backlog its link queued behind it (correct, just behind) —
    # equivalence is defined at the leader's applied position, so
    # wait for every lane to reach it before comparing
    want_pos = (svc.core.applied_ge, svc.core.applied_seq)
    end = time.monotonic() + 60.0
    while time.monotonic() < end:
        with_pos = []
        for s in srvs:
            with s._lock:
                with_pos.append((s.core.applied_ge,
                                 s.core.applied_seq))
        if all(p >= want_pos for p in with_pos):
            break
        time.sleep(0.02)
    else:
        raise AssertionError(
            f"replicas never reached the leader's applied position "
            f"{want_pos}: {with_pos}")
    want = _canon(svc)
    for i, s in enumerate(srvs):
        with s._lock:
            got = _canon(s.svc)
        for j, (w, g) in enumerate(zip(want, got)):
            assert w == g, (
                f"replica {i} lane diverged from the leader "
                f"(component {j})")


def _stop(svc, srvs):
    svc.stop()
    for s in srvs:
        s.stop()


# -- wire: raw-buffer section ------------------------------------------------


def test_wire_raw_roundtrip_and_native_parity():
    arr = np.arange(37, dtype=np.int32)
    small = np.asarray([7], np.int16)
    v = ("d", 12, wire.Raw(arr), [wire.Raw(b"payload"),
                                  wire.Raw(small)],
         {"k": wire.Raw(b"")}, None, True)
    parts = wire.encode_parts(v)
    assert isinstance(parts, list) and len(parts) == 5  # header + 4
    payload = b"".join(bytes(p) for p in parts)
    for decoder in (wire.decode_py, wire.decode):
        out = decoder(payload)
        assert out[0] == "d" and out[1] == 12
        assert (np.frombuffer(out[2], np.int32) == arr).all()
        assert bytes(out[3][0]) == b"payload"
        assert (np.frombuffer(out[3][1], np.int16) == small).all()
        assert bytes(out[4]["k"]) == b""
        assert out[5] is None and out[6] is True
    # native and python decode agree value-for-value
    assert wire.decode(payload) == wire.decode_py(payload)


def test_wire_raw_bufferless_and_plain_frames_unchanged():
    v = ("x", [1, 2], {"a": b"b"})
    payload = b"".join(bytes(p) for p in wire.encode_parts(v))
    assert wire.decode(payload) == v
    assert wire.decode_py(payload) == v
    # plain encode is byte-stable and rejects Raw (parts-only type)
    assert wire.decode(wire.encode(v)) == v
    with pytest.raises(wire.WireError):
        wire.encode_py(wire.Raw(b"zz"))


def test_wire_raw_hostile_frames_rejected():
    cases = [
        b"B\x00r\x00",          # ref with empty table
        b"B\x01\x05N",          # table claims 5 bytes, none follow
        b"B\x02\x7f\x7fN",      # table exceeds frame
        b"B\x01\x01NNx",        # trailing bytes before the buffer
    ]
    good = b"".join(bytes(p) for p in
                    wire.encode_parts(("ok", wire.Raw(b"abc"))))
    # a ref index past the table
    bad_ref = bytearray(good)
    assert bad_ref.count(b"r"[0])  # tag present
    for payload in cases:
        for decoder in (wire.decode_py, wire.decode):
            with pytest.raises(wire.WireError):
                decoder(payload)


def test_recv_frame_rejects_oversized_header():
    a, b = socket.socketpair()
    try:
        too_big = repgroup._MAX_FRAME + 1
        a.sendall(struct.pack(">I", too_big))
        with pytest.raises(wire.WireError):
            repgroup.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_record_digest_numpy_int_stable():
    """The repr()-CRC replacement (satellite): numpy scalars and
    python ints digest identically — the wire contract, not repr."""
    plain = repgroup.record_digest([(1, 2, 3, 4), (5, 6, 7, 8)])
    mixed = repgroup.record_digest(
        [(np.int32(1), np.int64(2), 3, np.int32(4)),
         (5, np.int64(6), np.int32(7), 8)])
    assert plain == mixed


# -- delta entry unit behavior ----------------------------------------------


def _plain_core(tmp_path):
    svc = BatchedEnsembleService(WallRuntime(), N_ENS, 1, N_SLOTS,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "lane"),
                                 tick=None)
    return svc, repgroup.ReplicaCore(svc)


def test_delta_crc_violation_nacks(tmp_path):
    """A flipped byte in a delta section must nack (and leave the
    lane untouched) — the frame CRC is the integrity contract."""
    svc, core = _plain_core(tmp_path)
    committed = np.zeros((2, N_ENS), bool)
    committed[0, 1] = True
    value = np.zeros((2, N_ENS), np.int32)
    kind = np.zeros((2, N_ENS), np.int32)
    kind[0, 1] = eng.OP_PUT
    slot = np.zeros((2, N_ENS), np.int32)
    slot[0, 1] = 3
    val = np.full((2, N_ENS), 9, np.int32)
    q = np.ones((N_ENS,), bool)
    entry, crc, nbytes = repgroup.build_delta_entry(
        1, 2, committed, value, kind, slot, val, q, [])
    assert nbytes > 0 and entry[0] == "d"
    # corrupt the vals section (index 10) but keep the shipped crc
    bad_vals = np.frombuffer(entry[10].buf, np.int32).copy()
    bad_vals[0] ^= 0xFF
    bad = entry[:10] + (wire.Raw(bad_vals),) + entry[11:]
    r = core.handle_abatch(("abatch", 0, [bad]))
    assert r[0] == "nack" and r[1] == "crc"
    assert core.applied_seq == 0
    r = core.handle_abatch(("abatch", 0, [entry]))
    assert r == ("applied", 0, 1, repgroup._crc_chain(0, crc))
    assert int(np.asarray(svc.state.obj_val)[1, 0, 3]) == 9
    svc.stop()


def test_delta_seq_gap_nacks(tmp_path):
    svc, core = _plain_core(tmp_path)
    q = np.ones((N_ENS,), bool)
    e1, _, _ = repgroup.build_delta_entry(
        1, 0, None, None, np.zeros((0, N_ENS), np.int32),
        np.zeros((0, N_ENS), np.int32), np.zeros((0, N_ENS), np.int32),
        q, [])
    e3, _, _ = repgroup.build_delta_entry(
        3, 0, None, None, np.zeros((0, N_ENS), np.int32),
        np.zeros((0, N_ENS), np.int32), np.zeros((0, N_ENS), np.int32),
        q, [])
    r = core.handle_abatch(("abatch", 0, [e1, e3]))
    assert r[0] == "nack" and r[1] == "seq"
    assert core.applied_seq == 1  # the in-order prefix applied
    svc.stop()


def test_batch_ack_gathers_at_majority_not_slowest():
    """Satellite: the shared-condition ack gather settles at majority
    time — a dead-slow link no longer holds the batch to its
    deadline (nor does list-order waiting sum slow prefixes)."""

    class _L:
        def __init__(self, i):
            self.host, self.port = "h", i
            self.needs_sync = False

    entry = repgroup._PendingEntry(1, 111, ("d",))
    batch = repgroup._PendingShip([entry], time.monotonic() + 30.0)
    crc = batch.crc
    links = [_L(0), _L(1), _L(2)]
    tickets = []
    for link in links:
        t = repgroup._Ticket(on_done=batch._notify)
        tickets.append(t)
        batch.sends.append((link, t))
    # the SLOW link (index 0, FIRST in list order) never answers;
    # links 1 and 2 ack after 50 ms
    def ack_later():
        time.sleep(0.05)
        for t in tickets[1:]:
            t.result = ("applied", 0, 1, crc)
            t._fire()
    threading.Thread(target=ack_later, daemon=True).start()
    t0 = time.monotonic()
    batch.wait_quorum(lambda acked: len(acked) + 1 >= 2)
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, f"waited {elapsed:.1f}s — not majority-gated"
    assert len(batch._acked_now()) == 2


# -- delta vs full-plane replica equivalence ---------------------------------


def test_delta_equivalence_scalar_sweep(tmp_path):
    svc, srvs = _group(tmp_path)
    try:
        futs = []
        for e in range(N_ENS):
            futs += [svc.kput(e, f"k{e}", b"v%d" % e),
                     svc.kget(e, f"k{e}"),
                     svc.kput(e, f"j{e}", b"w")]
        _settle(svc, futs)
        r = _settle(svc, [svc.kupdate(0, "k0", (1, 1), b"v0b")])
        assert r[0][0] == "ok"
        _settle(svc, [svc.kdelete(1, "k1"),
                      svc.kput_once(2, "once", b"o")])
        g = svc.stats()["group"]
        assert g["repl_delta_entries"] > 0, g
        assert g["quorum_failures"] == 0, g
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_delta_equivalence_keyed_rmw_inline(tmp_path):
    """Device RMW through the delta path: inline slots (value lives
    in the engine arrays), including the computed-0 tombstone drop."""
    svc, srvs = _group(tmp_path)
    try:
        futs = [svc.kmodify(e, f"ctr{e}", funref.ref("rmw:add", 5), 0)
                for e in range(N_ENS)]
        _settle(svc, futs)
        futs = [svc.kmodify(e, f"ctr{e}", funref.ref("rmw:add", 3), 0)
                for e in range(N_ENS)]
        _settle(svc, futs)
        r = _settle(svc, [svc.kget(0, "ctr0")])
        assert r[0] == ("ok", 8)
        # computed 0 = tombstone: reads see NOTFOUND (the engine-wide
        # 0-is-notfound encoding, test_rmw convention) on every lane
        _settle(svc, [svc.kmodify(1, "ctr1", funref.ref("rmw:sub", 8),
                                  0)])
        r = _settle(svc, [svc.kget(1, "ctr1")])
        assert r[0] == ("ok", wire.NOTFOUND), r
        g = svc.stats()["group"]
        assert g["repl_delta_entries"] > 0
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_delta_equivalence_batched_wide_groups(tmp_path):
    svc, srvs = _group(tmp_path)
    try:
        keys = [f"key{j}" for j in range(6)]
        vals = [b"v%d" % j for j in range(6)]
        for _ in range(3):
            futs = []
            for e in range(N_ENS):
                futs.append(svc.kput_many(e, keys, vals))
                futs.append(svc.kget_many(e, keys[:3]))
            _settle(svc, futs)
        futs = [svc.kdelete_many(e, keys[::2]) for e in range(N_ENS)]
        _settle(svc, futs)
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_delta_across_elections_full_fallback(tmp_path):
    """An electing launch ships full-plane (the replica re-executes
    it — epoch bumps are kernel work); the delta stream resumes after
    and the post-election stale-epoch GET rewrites (commits on READ
    rounds) must replicate through deltas too."""
    svc, srvs = _group(tmp_path)
    try:
        futs = [svc.kput(e, f"k{e}", b"v") for e in range(N_ENS)]
        _settle(svc, futs)
        g0 = svc.stats()["group"]
        # depose the device-lane leaders: the next flush elects
        svc.leader_np[:] = -1
        svc._slot_vsn_ok[:] = False
        futs = [svc.kget(e, f"k{e}") for e in range(N_ENS)]
        _settle(svc, futs)
        g1 = svc.stats()["group"]
        assert g1["repl_full_entries"] > g0["repl_full_entries"], (
            "the electing launch must ship full-plane")
        # stale-epoch rewrites ride the delta stream on later reads
        futs = [svc.kget(e, f"k{e}") for e in range(N_ENS)]
        _settle(svc, futs)
        futs = [svc.kput(e, f"post{e}", b"p") for e in range(N_ENS)]
        _settle(svc, futs)
        assert svc.stats()["group"]["quorum_failures"] == 0
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_delta_across_resync_and_install_barrier(tmp_path):
    """A link marked stale mid-stream re-syncs via an install queued
    ahead of the batches (the install-barrier discipline) and lands
    bit-equal; the commit path never stalls on it."""
    svc, srvs = _group(tmp_path)
    try:
        _settle(svc, [svc.kput(0, "a", b"1")])
        # declare replica 0 diverged (as a CRC mismatch would)
        svc._links[0].needs_sync = True
        futs = [svc.kput(e, f"b{e}", b"2") for e in range(N_ENS)]
        _settle(svc, futs)
        end = time.monotonic() + 30.0
        while time.monotonic() < end:
            svc.heartbeat()
            if svc.stats()["group"]["peers_synced"] == 2:
                break
            time.sleep(0.05)
        g = svc.stats()["group"]
        assert g["peers_synced"] == 2, g
        assert g["resyncs"] + g["tree_resyncs"] >= 1, g
        futs = [svc.kput(e, f"c{e}", b"3") for e in range(N_ENS)]
        _settle(svc, futs)
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_delta_off_knob_full_plane_equivalence(tmp_path):
    """The RETPU_REPL_DELTA=0 arm: every entry ships full-plane and
    the lanes still converge (the A/B baseline the bench runs)."""
    svc, srvs = _group(tmp_path)
    try:
        svc._repl_delta = False  # what RETPU_REPL_DELTA=0 pins
        futs = []
        for e in range(N_ENS):
            futs += [svc.kput(e, f"k{e}", b"v"), svc.kget(e, f"k{e}")]
        _settle(svc, futs)
        g = svc.stats()["group"]
        assert g["repl_delta_entries"] == 0, g
        assert g["repl_full_entries"] > 0, g
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_coalesced_boundary_fuzz(tmp_path):
    """Randomized coalescing-boundary sweep: random op mixes, delta
    toggles and forced elections across many flushes, with the chain
    flush (host-path kmodify) producing multi-entry frames — every
    frame boundary must preserve the stream and the lanes must end
    bit-equal.  Seeded: failures reproduce."""
    rng = np.random.default_rng(7)
    svc, srvs = _group(tmp_path)
    try:
        if not hasattr(funref, "_delta_fuzz_reg"):
            funref._delta_fuzz_reg = True

            @funref.register("tests.delta_fuzz_incr")
            def _incr(cur, by):  # noqa: F811 — registry-addressed
                return (0 if cur in (None, repgroup.wire.NOTFOUND)
                        else int(cur)) + int(by)
        for rnd in range(12):
            futs = []
            for e in range(N_ENS):
                n = int(rng.integers(0, 4))
                for j in range(n):
                    which = int(rng.integers(0, 4))
                    key = f"f{e}_{int(rng.integers(0, 6))}"
                    if which == 0:
                        futs.append(svc.kput(e, key, b"x%d" % rnd))
                    elif which == 1:
                        futs.append(svc.kget(e, key))
                    elif which == 2:
                        futs.append(svc.kdelete(e, key))
                    else:
                        futs.append(svc.kmodify(
                            e, key,
                            funref.ref("rmw:add", int(
                                rng.integers(1, 9))), 0))
            if rnd == 4:
                svc._repl_delta = False
            if rnd == 6:
                svc._repl_delta = True
            if rnd == 8:
                svc.leader_np[:] = -1  # forced re-election
                svc._slot_vsn_ok[:] = False
            _settle(svc, futs)
        g = svc.stats()["group"]
        assert g["quorum_failures"] == 0, g
        assert g["repl_delta_entries"] > 0
        assert g["repl_full_entries"] > 0
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)


def test_multi_entry_frames_coalesce(tmp_path):
    """One flush settling several launches ships them as ONE frame
    (entries > frames), and the cumulative ack covers all of them."""
    svc, srvs = _group(tmp_path)
    try:
        _settle(svc, [svc.kput(0, "seed", b"s")])
        g0 = svc.stats()["group"]
        k = np.zeros((1, N_ENS), np.int32)
        s = np.zeros((1, N_ENS), np.int32)
        v = np.zeros((1, N_ENS), np.int32)
        k[0, :] = eng.OP_PUT
        s[0, :] = N_SLOTS - 1
        v[0, :] = 42
        f1 = svc.execute_async(k, s, v)
        v2 = v.copy()
        v2[0, :] = 43
        f2 = svc.execute_async(k, s, v2)
        _settle(svc, [f1, f2])
        svc._drain_pending(block_all=True)
        g1 = svc.stats()["group"]
        entries = (g1["repl_delta_entries"] + g1["repl_full_entries"]
                   - g0["repl_delta_entries"] - g0["repl_full_entries"])
        frames = g1["repl_frames"] - g0["repl_frames"]
        assert entries >= 2
        assert frames < entries, (
            f"{entries} entries rode {frames} frames — no coalescing")
        assert g1["quorum_failures"] == g0["quorum_failures"]
        _assert_lanes_equal(svc, srvs)
    finally:
        _stop(svc, srvs)
