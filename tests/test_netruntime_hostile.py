"""Adversarial transport input: a hostile peer that can reach the node
port must at worst inject a well-formed protocol message — malformed,
oversized, deep-nested, or unknown-record frames are dropped and the
node keeps serving (the codec's no-code-on-decode property end to
end).  The pickle transport this replaces failed this by design."""

import asyncio
import struct

import pytest

from riak_ensemble_tpu import wire
from riak_ensemble_tpu.netruntime import FRAME_HEADER, NetRuntime
from riak_ensemble_tpu.runtime import Actor


class _Sink(Actor):
    def __init__(self, runtime, name, node):
        super().__init__(runtime, name, node)
        self.got = []

    def handle(self, msg):
        self.got.append(msg)


def _frame(payload: bytes) -> bytes:
    return FRAME_HEADER.pack(len(payload)) + payload


HOSTILE = [
    b"",                                   # empty payload
    b"\x00" * 64,                          # zero garbage
    b"Q" + b"\xff" * 32,                   # unknown tag
    b"R\x7fNN",                            # unknown record code
    b"t\x01" * 64 + b"N",                  # nesting bomb
    b"t" + bytes([0x80] * 5 + [0x01]),     # huge claimed count
    b"s\x02\xff\xff",                      # invalid utf-8 str
    b"e\x01l\x00",                         # unhashable set member
    # pickle opcodes (what an old-style attacker would send): must be
    # rejected as an unknown tag, never evaluated
    b"\x80\x04\x95n.",
]


def test_hostile_frames_dropped_node_keeps_serving():
    async def scenario():
        runtime = NetRuntime("node0", {"node0": ("127.0.0.1", 0)})
        # Bind an ephemeral port directly (peers map has port 0).
        runtime.loop = asyncio.get_running_loop()
        server = await asyncio.start_server(
            runtime._on_client, "127.0.0.1", 0)
        runtime._server = server
        port = server.sockets[0].getsockname()[1]

        sink = _Sink(runtime, ("manager", "node0"), "node0")

        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        for payload in HOSTILE:
            writer.write(_frame(payload))
        # A valid frame after the garbage: the connection (and node)
        # must still deliver it.
        ok = wire.encode((("manager", "node0"), ("ping", 42)))
        writer.write(_frame(ok))
        await writer.drain()

        for _ in range(200):
            if sink.got:
                break
            await asyncio.sleep(0.01)
        assert sink.got == [("ping", 42)], sink.got

        # Oversized frame header: connection is closed defensively,
        # but a fresh connection still works.
        writer.write(FRAME_HEADER.pack(1 << 31))
        await writer.drain()
        writer.close()

        r2, w2 = await asyncio.open_connection("127.0.0.1", port)
        w2.write(_frame(wire.encode((("manager", "node0"),
                                     ("ping", 43)))))
        await w2.drain()
        for _ in range(200):
            if len(sink.got) >= 2:
                break
            await asyncio.sleep(0.01)
        assert sink.got[-1] == ("ping", 43), sink.got
        w2.close()
        await runtime.stop()

    asyncio.run(scenario())
