"""Storage fault plane (docs/ARCHITECTURE.md §15, ISSUE 15).

The reference's headline safety property is surviving a bad disk
(synctree.erl:21-73).  These tests pin the injection plane itself —
per-path-class EIO/ENOSPC on write/fsync, torn writes, bit-flip read
corruption, the env knobs — and the service-level contract built on
it: a detected corruption is evidence (counted, quarantined), never
served; a WAL EIO/ENOSPC storm degrades the service to read-only (or
steps a replicated leader down) instead of crashing the serving loop,
observable in health()/stats() and the retpu_fault_*/retpu_recovery_*
gauges.  Cheap, deterministic, tier-1; the randomized kill sweeps and
the live 3-host corruption-repair scenario ride the slow lane in
test_crashpoints.py.
"""

import errno
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from riak_ensemble_tpu import faults  # noqa: E402
from riak_ensemble_tpu import save as savelib  # noqa: E402
from riak_ensemble_tpu.config import fast_test_config  # noqa: E402
from riak_ensemble_tpu.parallel.batched_host import (  # noqa: E402
    BatchedEnsembleService,
)
from riak_ensemble_tpu.parallel.wal import (  # noqa: E402
    PyLogStore, ServiceWAL,
)
from riak_ensemble_tpu.runtime import Runtime  # noqa: E402
from riak_ensemble_tpu.synctree.backends import FileBackend  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    faults.clear()
    yield
    faults.clear()


def settle(runtime, fut, timeout=5.0):
    return runtime.await_future(fut, timeout)


# -- plan rule surface / env knobs -------------------------------------------


def test_storage_knobs_parse_from_env():
    p = faults.from_env({
        "RETPU_FAULT_STORAGE": "wal.fsync=ENOSPC,ckpt.write=EIO:2",
        "RETPU_FAULT_TORN": "wal:37",
        "RETPU_FAULT_CORRUPT": "tree:0.5",
    })
    assert p is not None and p.active()
    d = p.describe()
    assert d["storage"] == {"wal.fsync": ["ENOSPC", None],
                            "ckpt.write": ["EIO", 2]}
    assert d["torn"] == {"wal": 37}
    assert d["corrupt"] == {"tree": 0.5}
    # bounded rule: exactly two injections, then clean
    assert p.storage_error("ckpt", "write").errno == errno.EIO
    assert p.storage_error("ckpt", "write").errno == errno.EIO
    assert p.storage_error("ckpt", "write") is None
    # unbounded rule: keeps firing, counts the evidence
    assert p.storage_error("wal", "fsync").errno == errno.ENOSPC
    assert p.storage_error("wal", "write") is None
    # torn rule is one-shot
    assert p.torn_limit("wal") == 37
    assert p.torn_limit("wal") is None
    assert p.counters()["storage_errors_injected"] == 3
    assert p.counters()["torn_writes_injected"] == 1


def test_malformed_storage_knob_raises_loudly():
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_STORAGE": "wal.fsync=EPERM"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_STORAGE": "walfsync=EIO"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_TORN": "wal"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_CORRUPT": "tree:x"})
    # review r15: a typo'd class/op or a zero count would arm an
    # injecting-nothing nemesis — rejected at arm time instead
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_STORAGE": "wla.fsync=EIO"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_STORAGE": "wal.sync=EIO"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_STORAGE": "wal.fsync=EIO:0"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_TORN": "foo:5"})
    with pytest.raises(ValueError):
        faults.from_env({"RETPU_FAULT_CORRUPT": "blob:0.5"})


def test_heal_clears_storage_rules_keeps_evidence():
    p = faults.FaultPlan()
    p.set_storage_error("wal", "write", "EIO")
    p.set_torn_write("ckpt", 9)
    p.set_read_corruption("tree", 1.0)
    assert p.active()
    assert p.storage_error("wal", "write") is not None
    p.heal()
    assert not p.active()
    assert p.storage_error("wal", "write") is None
    assert p.counters()["storage_errors_injected"] == 1


# -- WAL store seams ----------------------------------------------------------


def test_pylogstore_injected_write_and_fsync_errors(tmp_path):
    st = PyLogStore(str(tmp_path / "log"))
    st.store("k0", "v0")
    faults.install(faults.FaultPlan()
                   .set_storage_error("wal", "write", "EIO"))
    with pytest.raises(OSError) as ei:
        st.store("k1", "v1")
    assert ei.value.errno == errno.EIO
    faults.install(faults.FaultPlan()
                   .set_storage_error("wal", "fsync", "ENOSPC"))
    with pytest.raises(OSError) as ei:
        st.sync()
    assert ei.value.errno == errno.ENOSPC
    faults.clear()
    st.sync()
    st.close()


def test_injected_torn_write_repaired_and_later_acks_survive(
        tmp_path):
    """A torn write (truncated mid-record) fails the writer, which
    REPAIRS the frame boundary before continuing (review r15) — so
    every preceding record replays, the torn record is never served,
    and crucially records appended+synced AFTER the failure survive
    the next replay instead of being truncated behind the tear."""
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store("k0", "v0")
    st.sync()
    faults.install(faults.FaultPlan().set_torn_write("wal", 6))
    with pytest.raises(OSError):
        st.store("k1", "v1")
    faults.clear()
    assert st.append_repairs == 1
    # the writer survived: later appends must be fully replayable
    st.store("k2", "v2")
    st.sync()
    st.close()
    st2 = PyLogStore(p)
    assert st2.fetch("k0") == "v0"
    assert st2.fetch("k1") is None, "torn record served"
    assert st2.fetch("k2") == "v2", \
        "record appended after the repaired tear was lost at replay"
    assert st2.truncations == 0, "repair left a tear for replay"
    st2.close()


def test_injected_read_corruption_detected_never_served(tmp_path):
    """Bit-flip corruption on WAL replay reads: the CRC gate must
    stop replay at the flipped record (detection), drop it (never
    serve it), and the injection must be counted."""
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store("k0", "v0" * 20)
    st.sync()
    st.close()
    plan = faults.install(faults.FaultPlan(seed=7)
                          .set_read_corruption("wal", 1.0))
    st2 = PyLogStore(p)
    faults.clear()
    assert plan.corrupt_reads_injected >= 1
    assert st2.fetch("k0") is None, \
        "corrupted record served instead of dropped"
    assert st2.truncations == 1
    st2.close()


# -- synctree/treestore seams -------------------------------------------------


def test_filebackend_tree_faults_and_corruption_detection(tmp_path):
    be = FileBackend(str(tmp_path / "t" / "tree"))
    be.store("a", 1)
    be.sync()
    faults.install(faults.FaultPlan()
                   .set_storage_error("tree", "write", "ENOSPC"))
    with pytest.raises(OSError) as ei:
        be.store("b", 2)
    assert ei.value.errno == errno.ENOSPC
    faults.install(faults.FaultPlan()
                   .set_storage_error("tree", "fsync", "EIO"))
    with pytest.raises(OSError):
        be.sync()
    faults.clear()
    be.sync()
    be.close()
    # corrupt replay read: CRC detects, drops, counts — never serves
    plan = faults.install(faults.FaultPlan(seed=3)
                          .set_read_corruption("tree", 1.0))
    be2 = FileBackend(str(tmp_path / "t" / "tree"))
    faults.clear()
    assert plan.corrupt_reads_injected >= 1
    assert be2.fetch("a") is None
    assert be2.truncations == 1
    be2.close()


# -- checkpoint seams ---------------------------------------------------------


def test_checkpoint_write_fault_keeps_prior_checkpoint_restorable(
        tmp_path):
    """An ENOSPC mid-save must fail the save() loudly while the
    previous checkpoint + WAL tail stay fully restorable (the CURRENT
    pointer never flipped to a half-written image)."""
    data = str(tmp_path / "data")
    rt = Runtime(seed=31)
    svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 data_dir=data)
    assert settle(rt, svc.kput(0, "a", b"1"))[0] == "ok"
    svc.save()
    assert settle(rt, svc.kput(0, "b", b"2"))[0] == "ok"
    faults.install(faults.FaultPlan()
                   .set_storage_error("ckpt", "write", "ENOSPC"))
    with pytest.raises(OSError):
        svc.save()
    faults.clear()
    svc.stop()
    svc._wal.close()

    rt2 = Runtime(seed=32)
    svc2 = BatchedEnsembleService.restore(
        rt2, data, tick=0.005, config=fast_test_config(),
        data_dir=data)
    assert settle(rt2, svc2.kget(0, "a")) == ("ok", b"1")
    assert settle(rt2, svc2.kget(0, "b")) == ("ok", b"2")
    svc2.stop()


def test_save_read_survives_injected_bitflip(tmp_path):
    """The 4-copy save format vs injected read corruption: a flipped
    bit in one copy must never surface — read() falls through to an
    intact copy (the save.erl paranoia, now actually exercised)."""
    path = str(tmp_path / "blob")
    savelib.write(path, b"payload-bytes" * 17)
    faults.install(faults.FaultPlan(seed=5)
                   .set_read_corruption("ckpt", 1.0))
    got = savelib.read(path)
    faults.clear()
    assert got == b"payload-bytes" * 17


# -- graceful degradation (tentpole c) ----------------------------------------


def test_wal_enospc_degrades_readonly_not_crash(tmp_path):
    """EIO/ENOSPC under the WAL: the serving loop must NOT crash —
    the affected writes fail (never acked), the service flips
    read-only (journaled decision), reads keep serving, later writes
    fail fast, and health()/stats()/gauges all carry the evidence."""
    events = []
    rt = Runtime(seed=21)
    svc = BatchedEnsembleService(rt, 2, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "data"))
    rt.trace = lambda kind, payload: events.append((kind, payload))
    assert settle(rt, svc.kput(0, "a", b"1"))[0] == "ok"

    faults.install(faults.FaultPlan()
                   .set_storage_error("wal", "fsync", "ENOSPC"))
    assert settle(rt, svc.kput(0, "b", b"2")) == "failed"
    h = svc.health()["storage"]
    assert h["degraded"] is True and h["mode"] == "read_only"
    assert h["reason"] == "ENOSPC" and h["wal_errors"] >= 1
    # the decision is journaled as a trace event
    assert any(k == "svc_storage_degraded" for k, _ in events), events
    # reads keep serving; the storm never crashed the flush loop
    assert settle(rt, svc.kget(0, "a")) == ("ok", b"1")
    # disk healed or not: the service stays read-only until restart
    faults.clear()
    assert settle(rt, svc.kput(1, "c", b"3")) == "failed"
    assert settle(rt, svc.kput_many(1, ["d", "e"],
                                    [b"4", b"5"])) == ["failed"] * 2
    # the acked pre-storm write is still served
    assert settle(rt, svc.kget(0, "a")) == ("ok", b"1")
    # gauges: the recovery plane is observable
    snap = svc.obs_registry.snapshot()
    assert snap["retpu_recovery_degraded"] == 1
    assert snap["retpu_recovery_wal_errors_total"] >= 1
    svc.stop()

    # restart-to-recover: restore on a healthy disk serves writes again
    rt2 = Runtime(seed=22)
    svc2 = BatchedEnsembleService.restore(
        rt2, str(tmp_path / "data"), tick=0.005,
        config=fast_test_config(), data_dir=str(tmp_path / "data"))
    assert svc2.health()["storage"]["degraded"] is False
    assert settle(rt2, svc2.kget(0, "a")) == ("ok", b"1")
    # the never-acked storm write must not have materialized
    from riak_ensemble_tpu.types import NOTFOUND
    assert settle(rt2, svc2.kget(0, "b")) in (("ok", NOTFOUND),
                                              ("ok", b"2"))
    assert settle(rt2, svc2.kput(0, "post", b"p"))[0] == "ok"
    svc2.stop()


def test_generic_wal_oserror_still_raises(tmp_path):
    """Only the real bad-disk errnos degrade; a generic OSError keeps
    the historical raise-to-driver contract (pinned by
    test_pipeline.test_wal_error_does_not_abandon_later_launches —
    this asserts the split directly)."""
    rt = Runtime(seed=23)
    svc = BatchedEnsembleService(rt, 1, 3, 4, tick=None,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "data"))
    svc.flush()
    real_log = svc._wal.log

    def flaky(recs):
        raise OSError("transient")
    svc._wal.log = flaky
    f = svc.kput(0, "k", b"v")
    with pytest.raises(OSError):
        for _ in range(6):
            svc.flush()
    assert f.done and f.value == "failed"
    assert svc._storage_degraded is None, \
        "generic OSError must not flip read-only"
    svc._wal.log = real_log
    f2 = svc.kput(0, "k", b"v2")
    for _ in range(6):
        if f2.done:
            break
        svc.flush()
    assert f2.value[0] == "ok"
    svc.stop()


def test_late_fatal_errno_wins_over_earlier_transient(tmp_path):
    """Review r15: a fatal EIO/ENOSPC observed on a LATER launch of
    the same drain must still win the degrade decision — the first
    (non-fatal) error of the drain must not mask it and crash the
    serving loop."""
    rt = Runtime(seed=27)
    svc = BatchedEnsembleService(rt, 1, 3, 8, tick=None,
                                 max_ops_per_tick=1,
                                 pipeline_depth=2,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "data"))
    svc.flush()
    svc.flush()
    errs = [OSError(errno.EBADF, "yanked fd"),
            OSError(errno.EIO, "dead disk")]

    def flaky(recs):
        raise errs.pop(0)
    svc._wal.log = flaky
    f1 = svc.kput(0, "a", b"1")
    f2 = svc.kput(0, "b", b"2")
    for _ in range(6):  # must NOT raise: the late EIO degrades
        svc.flush()
    assert f1.done and f1.value == "failed"
    assert f2.done and f2.value == "failed"
    assert svc._storage_degraded is not None
    assert svc.health()["storage"]["reason"] == "EIO"
    svc.stop()


def test_replicated_leader_steps_down_on_storage_degrade():
    """The repgroup hook: a replicated leader whose WAL disk dies
    demotes itself through the existing step-down machinery — no
    leadership, no host lease, the decision in group_stats and the
    storage record marked step_down."""
    from riak_ensemble_tpu.parallel.batched_host import WallRuntime
    from riak_ensemble_tpu.parallel.repgroup import (
        DeposedError, ReplicatedService)

    svc = ReplicatedService(WallRuntime(), 2, 1, 4, group_size=1)
    svc._is_leader = True
    svc._host_lease_until = 1e18
    svc._degrade_storage("wal", OSError(errno.ENOSPC, "disk full"))
    assert svc.is_leader is False and svc._deposed is True
    assert svc._host_lease_until == 0.0
    assert svc._storage_degraded["mode"] == "step_down"
    assert svc.group_stats["storage_step_downs"] == 1
    h = svc.health()
    assert h["storage"]["degraded"] is True
    assert h["storage"]["mode"] == "step_down"
    with pytest.raises(DeposedError):
        svc.update_members([("127.0.0.1", 1)])
    svc.stop()


def test_storage_health_section_constant_shape(tmp_path):
    """Healthy service: the storage section is present with the same
    keys a degraded one reports (dashboard-query stability — the §13
    gauge discipline applied to §15)."""
    rt = Runtime(seed=24)
    svc = BatchedEnsembleService(rt, 1, 3, 4, tick=None,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "d"))
    h = svc.health()["storage"]
    assert h["degraded"] is False and h["mode"] is None
    assert set(h) == {"degraded", "mode", "reason", "at_flush",
                      "wal_errors", "wal_quarantines",
                      "wal_truncations"}
    s = svc.stats()
    assert s["storage"] == h
    assert s["wal"]["records"] == 0
    snap = svc.obs_registry.snapshot()
    assert snap["retpu_recovery_degraded"] == 0
    assert snap["retpu_fault_storage_errors_total"] == 0
    assert snap["retpu_fault_torn_writes_total"] == 0
    assert snap["retpu_fault_corrupt_reads_total"] == 0
    svc.stop()


def test_degrade_fails_queued_writes_keeps_queued_reads(tmp_path):
    """Review r15: the read-only contract covers writes already
    QUEUED at degrade time — left queued they would flush later and
    could ack if the disk flickered back.  They fail at the degrade;
    queued reads survive and serve."""
    rt = Runtime(seed=26)
    svc = BatchedEnsembleService(rt, 2, 3, 8, tick=None,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "data"))
    svc.flush()  # elections
    f0 = svc.kput(0, "pre", b"p")
    for _ in range(4):
        if f0.done:
            break
        svc.flush()
    assert f0.value[0] == "ok"
    # queue a backlog WITHOUT flushing, then degrade; the read is
    # forced onto a device round (lease zeroed) so it really queues
    w1 = svc.kput(0, "q1", b"1")
    w2 = svc.kput_many(1, ["q2", "q3"], [b"2", b"3"])
    svc.lease_until[:] = 0.0
    g = svc.kget(0, "pre")
    svc._degrade_storage("wal", OSError(errno.ENOSPC, "disk full"))
    assert w1.done and w1.value == "failed"
    assert w2.done and w2.value == ["failed", "failed"]
    assert not g.done  # the queued read survives the purge
    for _ in range(4):
        if g.done:
            break
        svc.flush()  # must not raise, must serve the read
    assert g.value == ("ok", b"p")
    # bulk execute writes refuse loudly on the read-only service
    import numpy as np

    from riak_ensemble_tpu.ops import engine as eng
    with pytest.raises(OSError):
        svc.execute(np.full((1, 2), eng.OP_PUT, np.int32),
                    np.zeros((1, 2), np.int32),
                    np.ones((1, 2), np.int32))
    svc.stop()


def test_degraded_service_never_compacts_onto_dead_disk(tmp_path):
    """Review r15: a read-only (degraded) service must not run WAL
    compaction — save() would write the same dead disk and the
    OSError would crash the flush loop the degradation protects."""
    rt = Runtime(seed=25)
    svc = BatchedEnsembleService(rt, 1, 3, 4, tick=0.005,
                                 config=fast_test_config(),
                                 data_dir=str(tmp_path / "data"))
    assert settle(rt, svc.kput(0, "a", b"1"))[0] == "ok"
    faults.install(faults.FaultPlan()
                   .set_storage_error("wal", "fsync", "ENOSPC"))
    assert settle(rt, svc.kput(0, "b", b"2")) == "failed"
    assert svc._storage_degraded is not None
    # past the compaction bound with the disk still dead: idle
    # flushes must neither compact nor raise
    svc.wal_compact_records = 1
    for _ in range(4):
        svc.flush()
    assert svc.wal_compactions == 0
    assert settle(rt, svc.kget(0, "a")) == ("ok", b"1")
    faults.clear()
    svc.stop()


def test_double_torn_append_repairs_at_true_eof(tmp_path):
    """Review r15 (reproduced upstream): truncate() does not move
    the buffered stream position, so without re-anchoring at EOF a
    SECOND failed append would repair at a stale offset, punching a
    hole that destroys later fsync-acked records at replay."""
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store("k1", "v1")
    st.sync()
    for i in (2, 3):  # two consecutive torn appends, both repaired
        faults.install(faults.FaultPlan().set_torn_write("wal", 6))
        with pytest.raises(OSError):
            st.store(f"k{i}", f"v{i}")
        faults.clear()
    assert st.append_repairs == 2
    st.store("k4", "v4")  # fsync-acked after both repairs
    st.sync()
    st.close()
    st2 = PyLogStore(p)
    assert st2.fetch("k1") == "v1"
    assert st2.fetch("k4") == "v4", \
        "acked record after the second repair lost at replay"
    assert st2.truncations == 0
    st2.close()


def test_transient_read_corruption_heals_on_retry(tmp_path,
                                                  monkeypatch):
    """Review r15: a CRC mismatch from a TRANSIENT bad read (heals
    on re-read) must not be treated as a torn tail — truncating on
    it would destroy healthy fsync-acked frames behind it."""
    p = str(tmp_path / "log")
    st = PyLogStore(p)
    st.store("k0", "v0")
    st.store("k1", "v1")
    st.sync()
    st.close()
    calls = {"n": 0}

    def one_shot_flip(path_class, data):
        calls["n"] += 1
        if calls["n"] == 1 and data:
            out = bytearray(data)
            out[0] ^= 0x40
            return bytes(out)
        return data
    monkeypatch.setattr(faults, "read_filter", one_shot_flip)
    st2 = PyLogStore(p)
    assert st2.read_retries == 1
    assert st2.truncations == 0, \
        "transient read error truncated a healthy log"
    assert st2.fetch("k0") == "v0" and st2.fetch("k1") == "v1"
    st2.close()


# -- crash-point scheduler basics ---------------------------------------------


def test_crashpoint_unarmed_is_noop():
    faults.crashpoint("wal_fsync_pre")  # must not exit this process


def test_crashpoint_malformed_nth_disarms_loudly(monkeypatch,
                                                 capsys):
    """Review r15: a malformed :nth must not raise inside the
    durability barrier (WAL lock held, serving loop) — it shouts to
    stderr once and disarms, the plan()-knob discipline."""
    monkeypatch.setenv("RETPU_CRASHPOINT", "wal_append:2x")
    faults.crashpoint("wal_append")  # neither exits nor raises
    assert "RETPU_CRASHPOINT" not in os.environ
    assert "malformed" in capsys.readouterr().err
    faults.crashpoint("wal_append")  # disarmed: clean no-op


def test_crashpoint_kills_at_nth_hit():
    """RETPU_CRASHPOINT=<name>:<nth> terminates the process with
    CRASH_EXIT at exactly the nth barrier crossing (cheap: the child
    imports faults alone, no jax)."""
    child = (
        "import sys; sys.path.insert(0, %r)\n"
        "from riak_ensemble_tpu import faults\n"
        "faults.crashpoint('other')\n"
        "faults.crashpoint('b'); print('one', flush=True)\n"
        "faults.crashpoint('b'); print('two', flush=True)\n"
    ) % REPO
    env = dict(os.environ, RETPU_CRASHPOINT="b:2")
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == faults.CRASH_EXIT, (p.returncode, p.stderr)
    assert "one" in p.stdout and "two" not in p.stdout
